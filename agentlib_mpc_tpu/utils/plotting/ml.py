"""ML fit evaluation (reference ``utils/plotting/ml_model_test.py:56+``):
one-step prediction scatter + error metrics of a serialized model against
held-out data."""

from __future__ import annotations

import numpy as np

from agentlib_mpc_tpu.ml.predictors import make_predictor
from agentlib_mpc_tpu.ml.serialized import SerializedMLModel
from agentlib_mpc_tpu.utils.plotting.basic import COLORS, make_fig


def evaluate_ml_fit(serialized: SerializedMLModel, X, y,
                    ax=None, plot: bool = True) -> dict:
    """Returns {"rmse", "mae", "r2"} per output; optionally draws the
    predicted-vs-true scatter."""
    pred = make_predictor(serialized)
    X = np.asarray(X, dtype=float)
    y = np.asarray(y, dtype=float).reshape(len(X), -1)
    got = np.stack([np.asarray(pred.apply(pred.params, x)) for x in X])
    metrics = {}
    for j, name in enumerate(serialized.output):
        err = got[:, j] - y[:, j]
        ss_res = float(np.sum(err ** 2))
        ss_tot = float(np.sum((y[:, j] - y[:, j].mean()) ** 2)) or 1e-30
        metrics[name] = {
            "rmse": float(np.sqrt(np.mean(err ** 2))),
            "mae": float(np.mean(np.abs(err))),
            "r2": 1.0 - ss_res / ss_tot,
        }
    if plot:
        if ax is None:
            _, axes = make_fig()
            ax = axes[0, 0]
        for j, name in enumerate(serialized.output):
            ax.scatter(y[:, j], got[:, j], s=8, alpha=0.6,
                       label=f"{name} (r2={metrics[name]['r2']:.3f})")
        lims = [min(y.min(), got.min()), max(y.max(), got.max())]
        ax.plot(lims, lims, color=COLORS["grey"], linewidth=0.8)
        ax.set_xlabel("true")
        ax.set_ylabel("predicted")
        ax.legend()
    return metrics
