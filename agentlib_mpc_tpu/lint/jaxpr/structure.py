"""Stage-structure certification: dependence + Hessian-interaction pass.

PR 4's block-tridiagonal KKT sweep (``ops/stagewise.py``) silently drops
every matrix entry outside the tridiagonal band — correct ONLY if the
transcription really produces a banded system under the attached
:class:`~agentlib_mpc_tpu.ops.stagewise.StagePartition`. Until now that
was a *layout* argument (``build_stage_partition`` mirrors the
flattening order) plus numeric probes of sample matrices. This pass
proves it against the actual traced functions, the CasADi
``which_depends`` role done one level down:

* every ``w`` element is seeded with a one-bit *stage mask* (its stage
  under the partition); masks propagate through the jaxpr per element,
  giving the exact w→(g, h) dependence bipartite graph at stage
  granularity;
* every nonlinear combination records an *interaction* pair of masks —
  a sound over-approximation of Lagrangian-Hessian sparsity (mul gives
  ∂²/∂a∂b, a smooth unary gives ∂²/∂a∂a, …);
* :func:`certify_stage_structure` then checks the band conditions the
  sweep relies on:

  1. equality row ``r`` (KKT index ``n_w + r``, stage ``s_r``) may
     depend only on stages ``s_r − 1 … s_r + 1``  (the ``Jg``/``Jgᵀ``
     blocks);
  2. each inequality row's dependence stages span ≤ 1 (rows of ``Jh``
     enter ``W`` as ``Jhᵀ Σ Jh``, coupling all their stages pairwise);
  3. every recorded Hessian interaction rectangle lies in the band
     (the ``∇²f``, ``y∇²g``, ``z∇²h`` contributions to ``W``).

``stop_gradient`` kills dependence (the pass models what AD — and hence
the solver's KKT assembly — sees, not raw value flow). Opaque
primitives with tainted inputs smear to all stages, so they can only
ever *fail* certification, never fake a pass.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from agentlib_mpc_tpu.lint.jaxpr.interp import Domain, run_nlp_function
from agentlib_mpc_tpu.ops.stagewise import StagePartition, stage_of_index

__all__ = ["StructureCertificate", "DependenceDomain",
           "certify_stage_structure"]


class DependenceDomain(Domain):
    """Per-element dependence bitmask over stages (arbitrary-width
    Python ints in an object array — production partitions have more
    stages than an int64 holds), plus the global interaction-pair set."""

    dtype = object

    def __init__(self, stage_of_w: np.ndarray):
        super().__init__()
        self.stage_of_w = stage_of_w
        self.interactions: "set[tuple[int, int]]" = set()

    def zero(self):
        return 0

    def w_element(self, flat_index: int):
        return 1 << int(self.stage_of_w[flat_index])

    def join(self, args):
        out = np.asarray(args[0], dtype=object).copy()
        for a in args[1:]:
            # re-wrap every step: numpy collapses 0-d object results to
            # bare Python ints, which the interpreter cannot index
            out = np.asarray(np.bitwise_or(out, np.asarray(a, dtype=object)),
                             dtype=object)
        return out

    def _record(self, a, b):
        af = np.asarray(a, dtype=object).reshape(-1)
        bf = np.broadcast_to(np.asarray(b, dtype=object),
                             np.shape(a)).reshape(-1)
        for x, y in zip(af.tolist(), bf.tolist()):
            if x and y:
                self.interactions.add((x, y) if x <= y else (y, x))

    def mul(self, a, b):
        self._record(a, b)
        return self.join([a, b])

    def div(self, a, b):
        # ∂²(a/b) has a·b and b·b terms, no a·a term
        self._record(a, b)
        self._record(b, b)
        return self.join([a, b])

    def int_pow(self, a, y: int):
        if y == 0:
            return self.zeros(np.shape(a))
        if y not in (0, 1):
            self._record(a, a)
        return self.join([a])

    def nonlinear(self, args):
        j = self.join(args)
        self._record(j, j)
        return j

    def nonsmooth(self, args):
        # piecewise-LINEAR in its inputs: second derivatives vanish a.e.,
        # so the branch interactions (already recorded while computing
        # the branches) cover the Hessian the solver ever materializes
        return self.join(args)

    def select(self, pred, cases):
        # w-dependent predicate: value is piecewise in w; the KKT
        # derivatives a.e. are the branch derivatives — keep the union,
        # no extra interactions beyond the branches' own
        return self.join([pred] + list(cases))

    def top_like(self, shape, args):
        mask = 0
        for a in args:
            flat = np.asarray(a, dtype=object).reshape(-1)
            for m in flat.tolist():
                mask |= m
        # an opaque primitive could couple everything it saw
        if mask:
            self.interactions.add((mask, mask))
        out = np.empty(shape, dtype=object)
        out[...] = mask
        return out


def _mask_stages(mask: int):
    out = []
    s = 0
    while mask:
        if mask & 1:
            out.append(s)
        mask >>= 1
        s += 1
    return out


@dataclasses.dataclass(frozen=True)
class StructureCertificate:
    """``ok`` iff the traced w→(g, h) dependence graph and the Hessian
    interaction set are covered by the partition's block-tridiagonal
    band. ``violations`` name each out-of-band coupling.

    ``h_row_stages`` records, per inequality row, the SMALLEST stage the
    row's traced dependence reaches (rows with no ``w`` dependence get
    stage 0). Only meaningful when ``ok`` — condition 2 then bounds each
    row's column support to stages ``{s, s+1}``, which is exactly the
    static metadata the stage-sparse derivative pipeline
    (:mod:`agentlib_mpc_tpu.ops.stagejac`) needs to compress ``Jh``
    pullbacks; ``None`` when certification failed before reaching h."""

    ok: bool
    n_stages: int
    violations: tuple = ()
    notes: tuple = ()
    opaque: tuple = ()
    h_row_stages: "tuple | None" = None

    def describe(self) -> str:
        if self.ok:
            return f"banded over {self.n_stages} stages"
        head = "; ".join(self.violations[:3])
        more = f" (+{len(self.violations) - 3} more)" \
            if len(self.violations) > 3 else ""
        return f"NOT banded: {head}{more}"


def certify_stage_structure(nlp, theta, n_w: int,
                            partition: StagePartition
                            ) -> StructureCertificate:
    """Prove the KKT system of ``nlp`` block-tridiagonal under
    ``partition`` (for all theta). The backends and
    ``TranscribedOCP.certify_stage_structure`` route through here; the
    CLI runs it over every example OCP in CI."""
    import jax.numpy as jnp

    stage_of = stage_of_index(partition)
    if n_w != partition.n_w:
        # the band checks below index equality rows at stage_of[n_w + r]
        # — only meaningful when the partition's primal offset matches
        raise ValueError(
            f"partition covers n_w={partition.n_w} primal variables, "
            f"the NLP has {n_w}")
    w0 = jnp.zeros((n_w,))
    violations: list[str] = []
    notes: list[str] = []
    opaque: list[str] = []
    interactions: "set[tuple[int, int]]" = set()

    results = {}
    for name, fn in (("f", nlp.f), ("g", nlp.g), ("h", nlp.h)):
        dom = DependenceDomain(stage_of[:n_w])
        try:
            outs = run_nlp_function(fn, w0, theta, dom)
        except Exception as exc:  # noqa: BLE001 — report, don't crash
            return StructureCertificate(
                ok=False, n_stages=partition.n_stages,
                violations=(f"{name}: interpreter error: {exc!r}",),
                opaque=("interpreter-error",))
        results[name] = outs
        interactions |= dom.interactions
        notes.extend(dom.notes)
        opaque.extend(dom.opaque)

    # 1. equality rows: deps within one stage of the row's own stage
    g_payload = np.concatenate(
        [np.asarray(o.payload, dtype=object).reshape(-1)
         for o in results["g"]]) if results["g"] else np.zeros(0, object)
    for r, mask in enumerate(g_payload.tolist()):
        s_r = int(stage_of[n_w + r])
        bad = [s for s in _mask_stages(mask) if abs(s - s_r) > 1]
        if bad:
            violations.append(
                f"g[{r}] (stage {s_r}) depends on stage(s) {bad}")

    # 2. inequality rows: dependence stages must span ≤ 1 (Jhᵀ Σ Jh)
    h_payload = np.concatenate(
        [np.asarray(o.payload, dtype=object).reshape(-1)
         for o in results["h"]]) if results["h"] else np.zeros(0, object)
    h_row_stages = []
    for r, mask in enumerate(h_payload.tolist()):
        stages = _mask_stages(mask)
        h_row_stages.append(stages[0] if stages else 0)
        if stages and stages[-1] - stages[0] > 1:
            violations.append(
                f"h[{r}] couples stages {stages[0]}..{stages[-1]} "
                f"through Jhᵀ·Σ·Jh")

    # 3. Hessian interaction rectangles inside the band
    for ma, mb in sorted(interactions):
        sa, sb = _mask_stages(ma), _mask_stages(mb)
        if not sa or not sb:
            continue
        if max(sa[-1] - sb[0], sb[-1] - sa[0]) > 1:
            violations.append(
                f"Hessian interaction couples stages {sa} x {sb}")

    if opaque:
        notes.append(
            "opaque primitive(s) smeared dependence: "
            + ",".join(sorted(set(opaque))))
    return StructureCertificate(
        ok=not violations,
        n_stages=partition.n_stages,
        violations=tuple(violations),
        notes=tuple(notes),
        opaque=tuple(opaque),
        h_row_stages=tuple(h_row_stages),
    )
