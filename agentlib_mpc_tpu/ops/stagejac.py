"""Stage-sparse derivative pipeline: banded eval+jac for stage-banded OCPs.

The reference (AgentLib-MPC) gets exact sparse Jacobians for free from
CasADi's graph coloring; our solver's dense ``jax.jacrev`` over the whole
decision vector computes the full ``(1+m_e+m_h) × n_w`` matrix — and the
dense Lagrangian Hessian all ``n_w`` columns — even though the PR 4
:class:`~agentlib_mpc_tpu.ops.stagewise.StagePartition` and the PR 5
jaxpr certificate *prove* both block-banded. PERF.md round 5/7 attribute
65–75 % of a warm interior-point iteration to exactly this eval+jac
cost, and the round-6 1024-zone table shows the dense per-agent KKT
working set (O(N²) mostly-zero floats) as the LLC scaling ceiling.

This module is the CasADi-coloring role, done with stage structure
instead of generic graph coloring:

* **Row-compressed pullbacks.** Constraint rows anchored at stages
  ``s`` and ``s' ≥ s+3`` have disjoint column supports (each row reaches
  only stages within ±1 of its own), so one VJP cotangent can carry one
  row from every third stage. The full ``Jg``/``Jh`` falls out of
  ``1 + 3·e_s + 3·h_s`` pullbacks (``e_s``/``h_s`` = max constraint rows
  per stage — horizon-independent) instead of ``1 + m_e + m_h`` — O(N)
  total FLOPs instead of O(N²).
* **Column-compressed Hessian.** The Lagrangian Hessian couples stages
  within distance 1, so ``3·v_s`` forward-over-reverse seeds (``v_s`` =
  max variables per stage) recover every column — instead of ``n_w``.
* **Direct banded assembly.** The compressed results scatter straight
  into the block-tridiagonal ``(D, E)`` layout
  :func:`~agentlib_mpc_tpu.ops.stagewise.factor_kkt_stage_banded`
  consumes; the dense KKT matrix is never materialized on this path, so
  per-agent KKT storage is O(N·n_s²) instead of O(N²·n_s²).

Routing follows the PR 5 pattern: the jaxpr stage-structure certificate
is the authority. :func:`plan_from_certificate` builds a
:class:`StageJacobianPlan` only from a *proved* certificate (which also
supplies the per-row ``Jh`` stage windows); refuted/unknown structure
keeps the dense pipeline, loudly. The plan is static per problem
structure, hashable by its defining key, and rides inside
``SolverOptions`` the way the stage partition does.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.ops.stagewise import StagePartition, stage_of_index
from agentlib_mpc_tpu.telemetry.profiler import phase_scope

__all__ = [
    "StageJacobianPlan",
    "assemble_kkt_banded",
    "attach_plan_if_worthwhile",
    "band_matvec",
    "band_rmatvec",
    "band_row_absmax",
    "banded_fgh_jac",
    "banded_lagrangian_hessian",
    "build_stage_jacobian_plan",
    "hessian_rows",
    "plan_from_certificate",
    "stacked_fgh",
    "tree_assemble_kkt_banded",
    "tree_banded_fgh_jac",
    "tree_banded_lagrangian_hessian",
    "tree_plan_from_certificate",
]

logger = logging.getLogger(__name__)


class StageJacobianPlan:
    """Static metadata of the stage-sparse derivative pipeline for ONE
    problem structure: compressed-cotangent seed matrices, row-window
    gather indices, and banded-KKT scatter targets.

    Hashable/comparable by its *defining key* ``(partition,
    h_row_stages)`` only — the derived index arrays (tens of thousands
    of ints for long horizons) are deterministic functions of the key
    and are deliberately excluded, so jit static-argument hashing stays
    as cheap as the partition's. Build through
    :func:`build_stage_jacobian_plan` (memoized: equal keys return the
    identical object) or :func:`plan_from_certificate`."""

    def __init__(self, partition: StagePartition, h_row_stages: tuple):
        p = partition
        S, ns = p.n_stages, p.block
        n_w, n_total = p.n_w, p.n_total
        m_e = n_total - n_w
        m_h = len(h_row_stages)
        self.partition = p
        self.h_row_stages = tuple(int(s) for s in h_row_stages)
        self.n_w, self.m_e, self.m_h = n_w, m_e, m_h

        perm = np.asarray(p.perm, dtype=np.int64)
        stage_of = stage_of_index(p)
        pos_of = np.empty((n_total,), dtype=np.int64)
        valid = perm >= 0
        pos_of[perm[valid]] = np.nonzero(valid)[0]
        slot_of = pos_of % ns

        # per-stage variable / equality-row layout (rank = order within
        # the stage's padded block, so it is deterministic)
        var_count = np.zeros((S,), dtype=np.int64)
        eq_count = np.zeros((S,), dtype=np.int64)
        var_rank = np.zeros((n_w,), dtype=np.int64)
        eq_rank = np.zeros((max(m_e, 1),), dtype=np.int64)
        for pos in range(S * ns):
            orig = perm[pos]
            if orig < 0:
                continue
            s = pos // ns
            if orig < n_w:
                var_rank[orig] = var_count[s]
                var_count[s] += 1
            else:
                eq_rank[orig - n_w] = eq_count[s]
                eq_count[s] += 1
        v_s = int(var_count.max()) if n_w else 1
        e_s = int(eq_count.max()) if m_e else 0
        var_cols = np.full((S, v_s), -1, dtype=np.int64)
        fill = np.zeros((S,), dtype=np.int64)
        for pos in range(S * ns):
            orig = perm[pos]
            if 0 <= orig < n_w:
                s = pos // ns
                var_cols[s, fill[s]] = orig
                fill[s] += 1
        self.v_s, self.e_s = v_s, e_s

        eq_stage = stage_of[n_w:] if m_e else np.zeros((0,), np.int64)
        h_base = np.asarray(self.h_row_stages, dtype=np.int64)
        if m_h and (h_base.min() < 0 or h_base.max() >= S):
            raise ValueError(
                f"h_row_stages outside the partition's {S} stages")
        h_count = np.zeros((S,), dtype=np.int64)
        h_rank = np.zeros((max(m_h, 1),), dtype=np.int64)
        for r in range(m_h):
            h_rank[r] = h_count[h_base[r]]
            h_count[h_base[r]] += 1
        h_s = int(h_count.max()) if m_h else 0
        self.h_s = h_s

        # ---- compressed VJP cotangents over the stacked [f; g; h] ------
        # seed (c, k) sums row k of every stage ≡ c (mod 3): rows three
        # stages apart have disjoint column supports, so the compressed
        # pullback is loss-free
        n_ct = 1 + 3 * e_s + 3 * h_s
        ct = np.zeros((n_ct, 1 + m_e + m_h))
        ct[0, 0] = 1.0
        g_seed = np.zeros((max(m_e, 1),), dtype=np.int64)
        for r in range(m_e):
            g_seed[r] = 1 + (int(eq_stage[r]) % 3) * e_s + eq_rank[r]
            ct[g_seed[r], 1 + r] = 1.0
        h_seed = np.zeros((max(m_h, 1),), dtype=np.int64)
        for r in range(m_h):
            h_seed[r] = 1 + 3 * e_s + (int(h_base[r]) % 3) * h_s + h_rank[r]
            ct[h_seed[r], 1 + m_e + r] = 1.0
        self.n_ct = n_ct
        self.ct_matrix = ct

        # ---- Hessian forward seeds -------------------------------------
        # column compression: variables of stages ≡ c (mod 3) share one
        # seed per in-stage rank (Hessian rows of two such columns are
        # disjoint because interactions stay within stage distance 1)
        n_hs = 3 * v_s
        hess_seeds = np.zeros((n_hs, n_w))
        for s in range(S):
            for b in range(v_s):
                j = var_cols[s, b]
                if j >= 0:
                    hess_seeds[(s % 3) * v_s + b, j] = 1.0
        self.hess_seeds = hess_seeds

        def window_cols(stages):
            out = []
            for s in stages:
                if 0 <= s < S:
                    out.extend(var_cols[s].tolist())
                else:
                    out.extend([-1] * v_s)
            return out

        def hseed_of_col(j):
            return (int(stage_of[j]) % 3) * v_s + var_rank[j]

        # ---- Jg / Jh / H row windows (gathered from compressed results)
        W_g = 3 * v_s
        g_cols = np.full((max(m_e, 1), W_g), -1, dtype=np.int64)
        g_src = np.zeros((max(m_e, 1), W_g), dtype=np.int64)
        for r in range(m_e):
            sr = int(eq_stage[r])
            g_cols[r] = window_cols((sr - 1, sr, sr + 1))
            g_src[r] = g_seed[r] * n_w + np.maximum(g_cols[r], 0)
        self.W_g = W_g
        self.g_cols = g_cols[:m_e]
        self.g_cols_safe = np.maximum(self.g_cols, 0).astype(np.int32)
        self.g_src = g_src[:m_e].astype(np.int32)
        self.g_mask = self.g_cols >= 0

        W_h = 2 * v_s
        h_cols = np.full((max(m_h, 1), W_h), -1, dtype=np.int64)
        h_src = np.zeros((max(m_h, 1), W_h), dtype=np.int64)
        for r in range(m_h):
            s0 = int(h_base[r])
            h_cols[r] = window_cols((s0, s0 + 1))
            h_src[r] = h_seed[r] * n_w + np.maximum(h_cols[r], 0)
        self.W_h = W_h
        self.h_cols = h_cols[:m_h]
        self.h_cols_safe = np.maximum(self.h_cols, 0).astype(np.int32)
        self.h_src = h_src[:m_h].astype(np.int32)
        self.h_mask = self.h_cols >= 0

        W_H = 3 * v_s
        hrow_cols = np.full((n_w, W_H), -1, dtype=np.int64)
        hrow_src = np.zeros((n_w, W_H), dtype=np.int64)
        for i in range(n_w):
            si = int(stage_of[i])
            hrow_cols[i] = window_cols((si - 1, si, si + 1))
            for k, j in enumerate(hrow_cols[i]):
                if j >= 0:
                    hrow_src[i, k] = hseed_of_col(j) * n_w + i
        self.W_H = W_H
        self.hrow_cols = hrow_cols
        self.hrow_cols_safe = np.maximum(hrow_cols, 0).astype(np.int32)
        self.hrow_src = hrow_src.astype(np.int32)
        self.hrow_mask = hrow_cols >= 0

        # ---- banded-KKT scatter layout ---------------------------------
        # one flat buffer [D (S·ns²) | E ((S-1)·ns²) | garbage (1)];
        # entries that belong to an implicit-transpose block (the sweep
        # reads only D and the sub-diagonal E) scatter into the garbage
        # slot and are dropped
        n_D = S * ns * ns
        n_E = (S - 1) * ns * ns
        garbage = n_D + n_E
        self._n_D, self._n_E, self._S, self._ns = n_D, n_E, S, ns

        def dst_of(i_orig, j_orig):
            """Flat destination of entry (row i, col j) of the permuted
            KKT matrix, or the garbage slot when the entry lives in an
            implicit-transpose block (it is covered from (j, i))."""
            si, sj = int(stage_of[i_orig]), int(stage_of[j_orig])
            ai, aj = int(slot_of[i_orig]), int(slot_of[j_orig])
            if si == sj:
                return si * ns * ns + ai * ns + aj
            if si == sj + 1:                      # sub-diagonal block
                return n_D + sj * ns * ns + ai * ns + aj
            if si == sj - 1:                      # super-diagonal: E^T
                return garbage
            raise AssertionError(
                f"entry ({i_orig}, {j_orig}) couples stages {si} and "
                f"{sj} — outside the certified band")

        de_init = np.zeros((n_D + n_E + 1,))
        for pos in range(S * ns):
            if perm[pos] < 0:                     # decoupled unit pivot
                s, a = pos // ns, pos % ns
                de_init[s * ns * ns + a * ns + a] = 1.0
        self.de_init = de_init

        # Hessian: every (var row i, window col) entry of H_rows
        hasm = np.full((n_w, W_H), garbage, dtype=np.int64)
        for i in range(n_w):
            for k, j in enumerate(hrow_cols[i]):
                if j >= 0:
                    hasm[i, k] = dst_of(i, j)
        self.hasm_dst = hasm.reshape(-1).astype(np.int32)

        # Jg: orientation 1 = (equality row, variable column) placed
        # wherever it lands in {D, E-or-transpose-partner}; orientation 2
        # = the symmetric (variable, equality) entry, needed only for
        # same-stage pairs (cross-stage partners are the E entries
        # orientation 1 already wrote)
        g1 = np.full((max(m_e, 1), W_g), garbage, dtype=np.int64)
        g2 = np.full((max(m_e, 1), W_g), garbage, dtype=np.int64)
        for r in range(m_e):
            i = n_w + r
            for k, j in enumerate(g_cols[r]):
                if j < 0:
                    continue
                d1 = dst_of(i, j)
                if d1 == garbage:                 # super-diagonal: write
                    d1 = dst_of(j, i)             # the (var, eq) partner
                g1[r, k] = d1
                if int(stage_of[i]) == int(stage_of[j]):
                    g2[r, k] = dst_of(j, i)
        self.gasm_dst1 = g1[:m_e].reshape(-1).astype(np.int32)
        self.gasm_dst2 = g2[:m_e].reshape(-1).astype(np.int32)

        # Jhᵀ Σ Jh: per-row outer products over the row's window
        jh = np.full((max(m_h, 1), W_h, W_h), garbage, dtype=np.int64)
        for r in range(m_h):
            for k1, c1 in enumerate(h_cols[r]):
                if c1 < 0:
                    continue
                for k2, c2 in enumerate(h_cols[r]):
                    if c2 < 0:
                        continue
                    jh[r, k1, k2] = dst_of(c1, c2)
        self.jh_dst = jh[:m_h].reshape(-1).astype(np.int32)

        vd = np.zeros((n_w,), dtype=np.int64)
        for i in range(n_w):
            vd[i] = dst_of(i, i)
        self.var_diag_dst = vd.astype(np.int32)
        ed = np.zeros((max(m_e, 1),), dtype=np.int64)
        for r in range(m_e):
            ed[r] = dst_of(n_w + r, n_w + r)
        self.eq_diag_dst = ed[:m_e].astype(np.int32)

    # identity is defined by the key; derived arrays are deterministic
    def _key(self):
        return (self.partition, self.h_row_stages)

    def __eq__(self, other):
        return (isinstance(other, StageJacobianPlan)
                and self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return (f"StageJacobianPlan(stages={self.partition.n_stages}, "
                f"block={self.partition.block}, n_w={self.n_w}, "
                f"m_e={self.m_e}, m_h={self.m_h}, "
                f"seeds={self.n_ct}+{3 * self.v_s})")

    @property
    def kkt_band_entries(self) -> int:
        """Banded KKT storage (floats) the sparse path carries per agent:
        S + (S-1) blocks of n_s² — O(N) vs the dense O(N²) matrix."""
        return self._n_D + self._n_E


_PLAN_CACHE: dict = {}


def build_stage_jacobian_plan(partition: StagePartition,
                              h_row_stages=()) -> StageJacobianPlan:
    """Build (memoized) the stage-sparse derivative plan for a partition
    plus the per-row base stages of ``h`` (from the jaxpr certificate's
    ``h_row_stages``; each row's column support must lie in stages
    ``{s, s+1}`` — exactly certificate condition 2)."""
    key = (partition, tuple(int(s) for s in h_row_stages))
    plan = _PLAN_CACHE.get(key)
    if plan is None:
        plan = StageJacobianPlan(*key)
        _PLAN_CACHE[key] = plan
    return plan


def plan_from_certificate(nlp, theta, n_w: int, partition: StagePartition,
                          log=None, label: str = "problem"
                          ) -> "StageJacobianPlan | None":
    """Routing authority for the sparse derivative pipeline: run the
    jaxpr stage-structure certifier and build a plan ONLY from a proved
    certificate. Refuted or unknown structure (opaque primitives,
    interpreter errors) returns None — the dense pipeline stays, loudly —
    mirroring :func:`agentlib_mpc_tpu.ops.qp.resolve_qp_routing`."""
    log = log or logger
    from agentlib_mpc_tpu.lint.jaxpr import certify_stage_structure

    try:
        cert = certify_stage_structure(nlp, theta, n_w, partition)
    except Exception:  # noqa: BLE001 — certification must never block setup
        log.warning(
            "stage-structure certification raised for %s; keeping the "
            "dense derivative pipeline", label, exc_info=True)
        return None
    if not cert.ok or cert.h_row_stages is None:
        log.warning(
            "stage structure not proved for %s (%s): keeping the dense "
            "derivative pipeline (jacobian='sparse' would drop real "
            "out-of-band couplings)", label, cert.describe())
        return None
    log.info(
        "stage structure proved for %s (%s): stage-sparse derivative "
        "pipeline eligible", label, cert.describe())
    return build_stage_jacobian_plan(partition, cert.h_row_stages)


def attach_plan_if_worthwhile(options, partition, nlp, theta, n_w: int,
                              log=None, label: str = "problem"):
    """The ONE gate+certify+attach seam every caller routes through
    (module backends via ``mpc_backend.attach_derivative_plan``, the
    ADMM backend and the fused fleet with their augmented nlps): run
    the certifier only when ``plan_worthwhile`` says the solve could
    route sparse, attach the resulting plan (or nothing, loudly) to the
    options. Returns the (possibly updated) options."""
    from agentlib_mpc_tpu.ops.solver import (
        attach_jacobian_plan,
        plan_worthwhile,
    )

    if not plan_worthwhile(options, partition):
        return options
    plan = plan_from_certificate(nlp, theta, n_w, partition, log=log,
                                 label=label)
    return attach_jacobian_plan(options, plan)


# --------------------------------------------------------------------------
# traced building blocks (all index arrays are static numpy constants)
# --------------------------------------------------------------------------

def stacked_fgh(nlp, theta):
    """The stacked residual [f, g..., h...] as a function of ``w`` — the
    same single-primal-pass stacking the solver evaluates."""
    def fgh(w):
        return jnp.concatenate([nlp.f(w, theta)[None], nlp.g(w, theta),
                                nlp.h(w, theta)])

    return fgh


def band_matvec(rows: jnp.ndarray, cols_safe, x: jnp.ndarray) -> jnp.ndarray:
    """J @ x for a banded-rows matrix: ``rows`` (m, W) with padded
    entries exactly zero, ``cols_safe`` (m, W) static column indices
    (padding clamped to 0 — its coefficient is zero)."""
    return jnp.sum(rows * x[jnp.asarray(cols_safe)], axis=-1)


def band_rmatvec(rows: jnp.ndarray, cols_safe, y: jnp.ndarray,
                 n: int) -> jnp.ndarray:
    """Jᵀ @ y via scatter-add over the rows' column windows."""
    vals = (rows * y[:, None]).reshape(-1)
    return jnp.zeros((n,), rows.dtype).at[
        jnp.asarray(cols_safe).reshape(-1)].add(vals)


def band_row_absmax(rows: jnp.ndarray, cols_safe, d: jnp.ndarray
                    ) -> jnp.ndarray:
    """Per-row max |J[r, :] * d| (the gradient-based row scaling the
    solver computes from the dense Jacobian today), from banded rows."""
    return jnp.max(jnp.abs(rows * d[jnp.asarray(cols_safe)]), axis=-1)


def banded_fgh_jac(plan: StageJacobianPlan, fgh, w: jnp.ndarray):
    """Values + banded Jacobian rows of the stacked residual in ONE
    primal pass and ``1 + 3·e_s + 3·h_s`` compressed pullbacks (vs
    ``1 + m_e + m_h`` dense rows). Returns ``(vals, gf, Jg_rows,
    Jh_rows)`` with rows in the plan's per-row column windows."""
    vals, pullback = jax.vjp(fgh, w)
    ct = jnp.asarray(plan.ct_matrix, vals.dtype)
    comp = jax.vmap(lambda c: pullback(c)[0])(ct)       # (n_ct, n_w)
    flat = comp.reshape(-1)
    gf = comp[0]
    zero = jnp.zeros((), vals.dtype)
    if plan.m_e:
        Jg_rows = jnp.where(plan.g_mask, flat[jnp.asarray(plan.g_src)],
                            zero)
    else:
        Jg_rows = jnp.zeros((0, plan.W_g), vals.dtype)
    if plan.m_h:
        Jh_rows = jnp.where(plan.h_mask, flat[jnp.asarray(plan.h_src)],
                            zero)
    else:
        Jh_rows = jnp.zeros((0, plan.W_h), vals.dtype)
    return vals, gf, Jg_rows, Jh_rows


def banded_lagrangian_hessian(plan: StageJacobianPlan, grad_fn,
                              w: jnp.ndarray) -> jnp.ndarray:
    """Compressed Lagrangian-Hessian columns: ``3·v_s`` forward passes
    through one linearization of ``grad_fn`` (vs ``n_w`` for the dense
    ``jax.hessian``). ``CH[seed_of(col j), i] = H[i, j]``."""
    with phase_scope("eval_jac"):
        _, jvp_fn = jax.linearize(grad_fn, w)
        seeds = jnp.asarray(plan.hess_seeds, w.dtype)
        return jax.vmap(jvp_fn)(seeds)


def hessian_rows(plan: StageJacobianPlan, CH: jnp.ndarray) -> jnp.ndarray:
    """Banded H rows (n_w, W_H) gathered from compressed columns — the
    matvec form of the Hessian (QP fast path: ``H @ w`` per iteration)."""
    flat = CH.reshape(-1)
    return jnp.where(plan.hrow_mask, flat[jnp.asarray(plan.hrow_src)],
                     jnp.zeros((), CH.dtype))


def assemble_kkt_banded(plan: StageJacobianPlan, CH: jnp.ndarray,
                        Jg_rows: jnp.ndarray, Jh_rows: jnp.ndarray,
                        sigma_s: jnp.ndarray, w_diag: jnp.ndarray,
                        delta_c: float):
    """Assemble the reduced KKT system

        K = [[H + diag(w_diag) + Jhᵀ diag(σ_s) Jh, Jgᵀ],
             [Jg, -δ_c I]]

    directly as stage-permuted banded blocks ``(D, E)`` for
    :func:`~agentlib_mpc_tpu.ops.stagewise.factor_kkt_stage_banded` —
    the dense matrix is never materialized. All scatter targets are
    static; entries belonging to implicit-transpose blocks drop into a
    garbage slot."""
    with phase_scope("assemble"):
        dtype = w_diag.dtype
        de = jnp.asarray(plan.de_init, dtype)
        H_rows = hessian_rows(plan, CH)
        de = de.at[jnp.asarray(plan.hasm_dst)].add(H_rows.reshape(-1))
        if plan.m_e:
            gflat = Jg_rows.reshape(-1)
            de = de.at[jnp.asarray(plan.gasm_dst1)].add(gflat)
            de = de.at[jnp.asarray(plan.gasm_dst2)].add(gflat)
            de = de.at[jnp.asarray(plan.eq_diag_dst)].add(
                jnp.full((plan.m_e,), -delta_c, dtype))
        if plan.m_h:
            outer = (sigma_s[:, None, None]
                     * Jh_rows[:, :, None] * Jh_rows[:, None, :])
            de = de.at[jnp.asarray(plan.jh_dst)].add(outer.reshape(-1))
        de = de.at[jnp.asarray(plan.var_diag_dst)].add(w_diag)
        S, ns = plan._S, plan._ns
        D = de[:plan._n_D].reshape(S, ns, ns)
        E = de[plan._n_D:plan._n_D + plan._n_E].reshape(
            max(S - 1, 0), ns, ns)
        # the two H orientations are gathered from different compressed
        # columns (equal in exact arithmetic); symmetrize so the
        # pivot-free quasi-definite sweep sees an exactly symmetric block
        D = 0.5 * (D + jnp.swapaxes(D, 1, 2))
        return D, E


# --------------------------------------------------------------------------
# tree-banded seeds (ISSUE 12): the scenario axis of a tree-structured
# OCP. Every branch of a scenario tree evaluates the SAME traced
# residual structure (branches differ in disturbance VALUES, which are
# theta, not structure), so one proved flat certificate — hence one
# compressed seed set — serves the whole tree: the tree-banded
# VJP/forward seeds are the flat plan's seeds vmapped over the scenario
# axis. The degenerate single-scenario batch routes through the flat
# entry points unwrapped, so the tree path can never silently diverge
# from the proven flat pipeline.
# --------------------------------------------------------------------------

def _theta_row(theta_batch, s: int):
    import jax as _jax

    return _jax.tree.map(lambda leaf: leaf[s], theta_batch)


def tree_banded_fgh_jac(plan: StageJacobianPlan, fgh, w_batch: jnp.ndarray,
                        theta_batch):
    """Values + banded Jacobian rows for a scenario batch: ``fgh(w,
    theta)`` is the branch-shared stacked residual, ``w_batch`` (S, n_w)
    and ``theta_batch`` (scenario-stacked pytree) carry the per-branch
    data. One compressed-cotangent seed matrix, S pullback batches."""
    if w_batch.shape[0] == 1:
        th0 = _theta_row(theta_batch, 0)
        vals, gf, Jg, Jh = banded_fgh_jac(
            plan, lambda w: fgh(w, th0), w_batch[0])
        return vals[None], gf[None], Jg[None], Jh[None]
    return jax.vmap(
        lambda w, th: banded_fgh_jac(plan, lambda ww: fgh(ww, th), w)
    )(w_batch, theta_batch)


def tree_banded_lagrangian_hessian(plan: StageJacobianPlan, grad_fn,
                                   w_batch: jnp.ndarray, theta_batch
                                   ) -> jnp.ndarray:
    """Compressed Lagrangian-Hessian columns per scenario branch:
    ``grad_fn(w, theta)`` is the branch-shared Lagrangian gradient; the
    flat plan's ``3·v_s`` forward seeds serve every branch."""
    if w_batch.shape[0] == 1:
        th0 = _theta_row(theta_batch, 0)
        return banded_lagrangian_hessian(
            plan, lambda w: grad_fn(w, th0), w_batch[0])[None]
    return jax.vmap(
        lambda w, th: banded_lagrangian_hessian(
            plan, lambda ww: grad_fn(ww, th), w)
    )(w_batch, theta_batch)


def tree_assemble_kkt_banded(plan: StageJacobianPlan, CH_batch,
                             Jg_batch, Jh_batch, sigma_batch,
                             w_diag_batch, delta_c: float):
    """Scenario-batched banded KKT assembly: (D, E) stacks with a
    leading scenario axis, ready for
    :func:`~agentlib_mpc_tpu.ops.stagewise.factor_kkt_scenarios_banded`
    (single-scenario batches route through the flat assembly)."""
    if CH_batch.shape[0] == 1:
        D, E = assemble_kkt_banded(plan, CH_batch[0], Jg_batch[0],
                                   Jh_batch[0], sigma_batch[0],
                                   w_diag_batch[0], delta_c)
        return D[None], E[None]
    return jax.vmap(
        lambda CH, Jg, Jh, sg, wd: assemble_kkt_banded(
            plan, CH, Jg, Jh, sg, wd, delta_c)
    )(CH_batch, Jg_batch, Jh_batch, sigma_batch, w_diag_batch)


def tree_plan_from_certificate(nlp, theta, n_w: int, tree_partition,
                               log=None, label: str = "scenario tree"
                               ) -> "StageJacobianPlan | None":
    """Routing authority for the tree-banded derivative pipeline: the
    branches share one structure, so ONE flat certification answers for
    the whole tree — run it against the tree partition's per-branch
    :class:`~agentlib_mpc_tpu.ops.stagewise.StagePartition` and build
    the (shared) plan only from a proved certificate. Refuted or
    unknown structure returns None — every branch keeps the dense
    pipeline, loudly, per the PR 5 authority pattern."""
    base = getattr(tree_partition, "base", tree_partition)
    return plan_from_certificate(nlp, theta, n_w, base, log=log,
                                 label=label)
