"""Convex-QP fast path: structure certification + Mehrotra solver.

The reference routes LQ problems to dedicated QP codes
(qpoases/osqp/proxqp, ``data_structures/casadi_utils.py:52-61,127-161``);
here that role is ``ops/qp.py``. Evidence: the QP solver agrees exactly
with the general IPM and with SciPy on random convex programs, the
structure probe separates LQ from genuinely nonlinear transcriptions,
and the ``jax`` backend auto-routes an LQ model while leaving the
flagship (bilinear) model on the NLP path — with identical closed-loop
answers whichever solver runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import minimize

from agentlib_mpc_tpu.ops.qp import is_lq, solve_qp
from agentlib_mpc_tpu.ops.solver import (
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)

OPTS = SolverOptions(tol=1e-8, max_iter=60)


def _random_qp_nlp(rng, n, m_eq, m_in):
    A = rng.normal(size=(n, n))
    Q = A @ A.T + n * np.eye(n)
    c = rng.normal(size=n) * 2.0
    lb = -1.0 - rng.random(n)
    ub = 1.0 + rng.random(n)
    x_feas = lb + (ub - lb) * rng.random(n)
    Aeq = rng.normal(size=(m_eq, n)) if m_eq else np.zeros((0, n))
    beq = Aeq @ x_feas
    G = rng.normal(size=(m_in, n)) if m_in else np.zeros((0, n))
    hvec = G @ x_feas - rng.random(m_in) if m_in else np.zeros(0)
    Qj, cj = jnp.asarray(Q), jnp.asarray(c)
    Aj, bj = jnp.asarray(Aeq), jnp.asarray(beq)
    Gj, hj = jnp.asarray(G), jnp.asarray(hvec)
    nlp = NLPFunctions(
        f=lambda w, t: 0.5 * w @ Qj @ w + cj @ w,
        g=lambda w, t: Aj @ w - bj,
        h=lambda w, t: Gj @ w - hj,
    )
    return nlp, (Q, c, lb, ub, Aeq, beq, G, hvec), x_feas


def _scipy_solution(Q, c, lb, ub, Aeq, beq, G, hvec, x0):
    cons = []
    if Aeq.shape[0]:
        cons.append({"type": "eq", "fun": lambda x: Aeq @ x - beq,
                     "jac": lambda x: Aeq})
    if G.shape[0]:
        cons.append({"type": "ineq", "fun": lambda x: G @ x - hvec,
                     "jac": lambda x: G})
    res = minimize(lambda x: 0.5 * x @ Q @ x + c @ x,
                   jac=lambda x: Q @ x + c, x0=x0,
                   bounds=list(zip(lb, ub)), constraints=cons,
                   method="SLSQP", options={"maxiter": 500, "ftol": 1e-12})
    assert res.success, res.message
    return res.x


@pytest.mark.parametrize("n,m_eq,m_in", [
    (4, 0, 0), (8, 3, 0), (8, 0, 4),
    pytest.param(12, 4, 5, marks=pytest.mark.slow),
])
def test_qp_matches_ipm_and_scipy(n, m_eq, m_in):
    rng = np.random.default_rng(1000 * n + 10 * m_eq + m_in)
    for trial in range(3):
        nlp, data, x_feas = _random_qp_nlp(rng, n, m_eq, m_in)
        lb, ub = jnp.asarray(data[2]), jnp.asarray(data[3])
        w0 = jnp.asarray(x_feas)
        r_qp = solve_qp(nlp, w0, None, lb, ub, OPTS)
        assert bool(r_qp.stats.success), f"trial {trial}: QP not converged"
        r_ip = solve_nlp(nlp, w0, None, lb, ub, OPTS)
        np.testing.assert_allclose(np.asarray(r_qp.w), np.asarray(r_ip.w),
                                   atol=2e-6, err_msg=f"trial {trial}")
        x_ref = _scipy_solution(*data, x_feas)
        np.testing.assert_allclose(np.asarray(r_qp.w), x_ref, atol=2e-5,
                                   err_msg=f"trial {trial}")


def test_qp_vmaps():
    """Batched solves (the multi-agent substrate) equal per-item solves."""
    rng = np.random.default_rng(3)
    nlp, data, x_feas = _random_qp_nlp(rng, 6, 2, 0)
    lb, ub = jnp.asarray(data[2]), jnp.asarray(data[3])
    w0s = jnp.asarray(x_feas) + 0.1 * jnp.asarray(
        rng.normal(size=(4, 6)))
    batched = jax.vmap(
        lambda w0: solve_qp(nlp, w0, None, lb, ub, OPTS))(w0s)
    single0 = solve_qp(nlp, w0s[0], None, lb, ub, OPTS)
    assert bool(jnp.all(batched.stats.success))
    np.testing.assert_allclose(np.asarray(batched.w[0]),
                               np.asarray(single0.w), atol=1e-9)
    # all instances of the same strictly convex QP land on one optimum
    np.testing.assert_allclose(np.asarray(batched.w),
                               np.tile(np.asarray(single0.w), (4, 1)),
                               atol=1e-6)


def test_qp_warm_budget_traced():
    """`max_iter` as a traced value (the fused-ADMM warm-budget seam)."""
    rng = np.random.default_rng(5)
    nlp, data, x_feas = _random_qp_nlp(rng, 6, 0, 3)
    lb, ub = jnp.asarray(data[2]), jnp.asarray(data[3])
    full = solve_qp(nlp, jnp.asarray(x_feas), None, lb, ub, OPTS)
    budget2 = solve_qp(nlp, jnp.asarray(x_feas), None, lb, ub, OPTS,
                       max_iter=jnp.asarray(2))
    assert int(budget2.stats.iterations) <= 2 < int(full.stats.iterations)
    # resuming from the truncated point's primal-duals reaches the optimum
    resumed = solve_qp(nlp, budget2.w, None, lb, ub, OPTS,
                       y0=budget2.y, z0=budget2.z)
    np.testing.assert_allclose(np.asarray(resumed.w), np.asarray(full.w),
                               atol=2e-6)


class TestStructureProbe:
    def test_lq_transcription_certified(self):
        from agentlib_mpc_tpu.models.zoo import LinearRCZone
        from agentlib_mpc_tpu.ops.transcription import transcribe

        ocp = transcribe(LinearRCZone(), ["Q"], N=4, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params()
        n = int(ocp.initial_guess(theta).shape[0])
        assert is_lq(ocp.nlp, theta, n)

    def test_bilinear_transcription_rejected(self):
        from agentlib_mpc_tpu.models.zoo import OneRoom
        from agentlib_mpc_tpu.ops.transcription import transcribe

        ocp = transcribe(OneRoom(), ["mDot"], N=4, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params()
        n = int(ocp.initial_guess(theta).shape[0])
        assert not is_lq(ocp.nlp, theta, n)


class TestBackendRouting:
    def _backend(self, model_cls, controls, qp_fast_path=None):
        from agentlib_mpc_tpu.backends.backend import (
            VariableReference,
            create_backend,
        )

        solver = {"max_iter": 80, "tol": 1e-8}
        if qp_fast_path is not None:
            solver["qp_fast_path"] = qp_fast_path
        backend = create_backend({
            "type": "jax",
            "model": {"class": model_cls},
            "discretization_options": {"collocation_order": 2},
            "solver": solver,
        })
        if model_cls.__name__ == "LinearRCZone":
            var_ref = VariableReference(
                states=["T", "T_slack"], controls=controls,
                inputs=["load", "T_amb", "T_upper"],
                parameters=["C", "R", "s_T", "r_Q"])
        else:
            var_ref = VariableReference(
                states=["T", "T_slack"], controls=controls,
                inputs=["load", "T_in", "T_upper"],
                parameters=["cp", "C", "s_T", "r_mDot"])
        backend.setup_optimization(var_ref, time_step=300.0,
                                   prediction_horizon=6)
        return backend

    def test_auto_routes_linear_model_to_qp(self):
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        backend = self._backend(LinearRCZone, ["Q"])
        assert backend.uses_qp_fast_path

    def test_auto_keeps_bilinear_model_on_nlp(self):
        from agentlib_mpc_tpu.models.zoo import CooledRoom

        backend = self._backend(CooledRoom, ["mDot"])
        assert not backend.uses_qp_fast_path

    def test_invalid_mode_rejected(self):
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        with pytest.raises(ValueError, match="qp_fast_path"):
            self._backend(LinearRCZone, ["Q"], qp_fast_path="yes")

    def test_admm_backend_probes_augmented_problem(self):
        """The decentralized-ADMM backend routes on the AUGMENTED OCP:
        a linear model with quadratic coupling penalties certifies; the
        bilinear cooled room does not."""
        from conftest import make_tracker_model

        from agentlib_mpc_tpu.backends.admm_backend import (
            ADMMVariableReference,
        )
        from agentlib_mpc_tpu.backends.backend import create_backend
        from agentlib_mpc_tpu.models.zoo import CooledRoom

        def admm_backend(model_cls, var_ref):
            backend = create_backend({
                "type": "jax_admm",
                "model": {"class": model_cls},
                "discretization_options": {"collocation_order": 1},
                "solver": {"max_iter": 40},
            })
            backend.setup_optimization(var_ref, time_step=300.0,
                                       prediction_horizon=4)
            return backend

        linear = admm_backend(
            make_tracker_model(),
            ADMMVariableReference(parameters=["a"], couplings=["u"]))
        assert linear.uses_qp_fast_path
        bilinear = admm_backend(
            CooledRoom,
            ADMMVariableReference(
                states=["T", "T_slack"],
                inputs=["load", "T_in", "T_upper"],
                parameters=["cp", "C", "s_T"], couplings=["mDot"]))
        assert not bilinear.uses_qp_fast_path
        # the routed backend still solves the coupled problem
        res = linear.solve(0.0, {"a": 2.0})
        assert res["stats"]["success"]

    def test_mhe_backend_routes_linear_estimation(self):
        """Linear plant + quadratic tracking = LQ estimation program:
        the MHE backend certifies and both paths agree."""
        from agentlib_mpc_tpu.backends.backend import create_backend
        from agentlib_mpc_tpu.backends.mhe_backend import (
            MHEVariableReference,
        )

        def mhe_backend(qp):
            backend = create_backend({
                "type": "jax_mhe",
                "model": {"class": "LinearRCZone"},
                "discretization_options": {"collocation_order": 2},
                "solver": {"max_iter": 60, "tol": 1e-8,
                           "qp_fast_path": qp},
            })
            backend.setup_optimization(
                MHEVariableReference(
                    states=["T"], measured_states=["measured_T"],
                    weights_states=["weight_T"],
                    estimated_inputs=["Q"],
                    known_inputs=["load", "T_amb", "T_upper"]),
                time_step=300.0, prediction_horizon=4)
            return backend

        fast, slow = mhe_backend("auto"), mhe_backend("off")
        assert fast.uses_qp_fast_path and not slow.uses_qp_fast_path
        meas = (np.array([0.0, 300.0, 600.0, 900.0, 1200.0]),
                np.array([298.0, 297.4, 296.9, 296.5, 296.2]))
        variables = {"measured_T": meas, "weight_T": 10.0,
                     "load": 150.0, "T_amb": 303.15, "T_upper": 295.15}
        rf = fast.solve(1200.0, dict(variables))
        rs = slow.solve(1200.0, dict(variables))
        assert rf["stats"]["success"] and rs["stats"]["success"]
        # the estimation problem is near-degenerate (input + free
        # initial state anchored only by tracking) and heavily scaled,
        # so both solvers stop at honest near-optima in a flat valley
        # (measured: ~1e-3 relative objective gap persists even at
        # tol=1e-10 for either path) — equivalence is judged at that
        # resolution
        scale = max(1.0, abs(rs["stats"]["objective"]))
        assert abs(rf["stats"]["objective"]
                   - rs["stats"]["objective"]) < 2e-3 * scale
        np.testing.assert_allclose(rf["estimates"]["T"],
                                   rs["estimates"]["T"], atol=0.05)

    def test_qp_and_nlp_paths_agree_on_lq_mpc(self):
        """The A/B VERDICT r4 #3 asks for: same linearized one-room
        problem, both solver paths, identical trajectories."""
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        fast = self._backend(LinearRCZone, ["Q"])
        slow = self._backend(LinearRCZone, ["Q"], qp_fast_path="off")
        assert fast.uses_qp_fast_path and not slow.uses_qp_fast_path
        for t, temp in ((0.0, 297.15), (300.0, 296.6), (600.0, 296.1)):
            rf = fast.solve(t, {"T": temp})
            rs = slow.solve(t, {"T": temp})
            assert rf["stats"]["success"] and rs["stats"]["success"]
            np.testing.assert_allclose(
                np.asarray(rf["traj"]["u"]), np.asarray(rs["traj"]["u"]),
                atol=1e-3, err_msg=f"t={t}")   # 1 mW on a 500 W scale
            scale = max(1.0, abs(rs["stats"]["objective"]))
            assert abs(rf["stats"]["objective"]
                       - rs["stats"]["objective"]) < 1e-5 * scale


class TestForcedStageTinySizes:
    """The known pre-existing stall (CHANGES.md PR 6): ``solve_qp`` with
    FORCED ``kkt_method="stage"`` at tiny sizes (N=8 LinearRCZone, KKT
    dim 74 — far below every auto-routing floor) used to burn its whole
    budget with the iterate running away once the pivot-free stage LDLᵀ
    broke down at near-convergence conditioning. The direction-health
    guard + adaptive Levenberg delta + stall exit must make the forced
    path terminate quickly with an honest verdict and a solution that
    matches the LU path."""

    @pytest.mark.parametrize("N", [6, 8])
    def test_forced_stage_converges_and_matches_lu(self, N):
        from agentlib_mpc_tpu.models.zoo import LinearRCZone
        from agentlib_mpc_tpu.ops.transcription import transcribe

        ocp = transcribe(LinearRCZone(), ["Q"], N=N, dt=300.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params()
        lb, ub = ocp.bounds(theta)
        w0 = ocp.initial_guess(theta)
        results = {}
        for method in ("lu", "stage"):
            opts = SolverOptions(tol=1e-6, max_iter=60, kkt_method=method,
                                 stage_partition=ocp.stage_partition)
            res = solve_qp(ocp.nlp, w0, theta, lb, ub, opts)
            assert bool(res.stats.success), \
                f"{method} failed at N={N}: {res.stats}"
            # the stall exit bounds the burn: a wedged solve must stop
            # well before a large budget instead of running it out
            assert int(res.stats.iterations) < 50
            results[method] = res
        # same optimum (f64 suite precision: the factorizations agree)
        np.testing.assert_allclose(
            np.asarray(results["stage"].w), np.asarray(results["lu"].w),
            atol=1e-4)
        obj_lu = float(results["lu"].stats.objective)
        assert abs(float(results["stage"].stats.objective) - obj_lu) \
            <= 1e-6 * max(1.0, abs(obj_lu))
