"""Analytic fusion planner: rank boundary merges before touching silicon.

ROADMAP item 2's structural lever, made a *model* instead of a hunch:
the reference design dispatches the IPM iteration's stages — eval+jac,
banded assemble, stage factor, line search — as separate device
programs (CasADi/IPOPT goes further and pays a host round-trip per
callback). Each stage boundary costs a fixed dispatch overhead plus
the HBM round-trip of its intermediates; fusing stages buys both back
at the price of co-resident working sets. This planner joins the three
certified models the ``lint/jaxpr`` stack already carries —

* :func:`~agentlib_mpc_tpu.telemetry.calibration.phase_costs`
  (the :func:`~.cost.op_cost` charging rules accumulated per
  ``phase.*`` name-stack component) for per-phase FLOPs and bytes;
* :meth:`~.collectives.CollectiveCertificate.comm_bytes` for the
  round's cross-device traffic (a fused region must keep its psums —
  fusion may never reorder the collective schedule);
* the PR 13 live-range walk (:func:`~.memory.certify_memory`) for the
  projected peak-HBM bound — the walk runs on the *fused* trace, where
  every merged stage's buffers are already co-resident, so its peak
  bounds every partial merge from above;

— across every contiguous merge of the observed phase pipeline, ranks
candidates by modeled dispatch-overhead savings (per round: saved
boundaries × the while-trip budget × :data:`DISPATCH_OVERHEAD_US`)
against projected peak-HBM growth, **refuses** any candidate whose
projected peak the memory certifier proves over capacity, and emits
the :class:`FusionPlan` artifact ``bench.py --emit-metrics`` embeds.

The overhead constant is a MODEL (like
:data:`~agentlib_mpc_tpu.telemetry.calibration.PLATFORM_PEAKS`): its
value is *ranking* — which boundary to fuse first — not an absolute
latency claim; the plan records what it assumed.
"""

from __future__ import annotations

import dataclasses

__all__ = [
    "DISPATCH_OVERHEAD_US",
    "FusionCandidate",
    "FusionPlan",
    "IPM_PIPELINE",
    "plan_fusion",
]

#: modeled fixed cost of one device dispatch (host enqueue + launch),
#: microseconds. Order-of-magnitude of a jax.jit dispatch on current
#: runtimes; overridable per call. Ranking fuel, not a benchmark.
DISPATCH_OVERHEAD_US = 70.0

#: the IPM iteration's stage pipeline, in dataflow order — the phase
#: vocabulary subset a solver round actually stages through
#: (``telemetry.profiler.PHASES`` names; consensus/collectives phases
#: are excluded: fusing across a psum would reorder the certified
#: collective schedule)
IPM_PIPELINE = ("eval_jac", "assemble", "factor", "resolve",
                "line_search")


@dataclasses.dataclass(frozen=True)
class FusionCandidate:
    """One contiguous stage merge, scored.

    ``savings_bytes`` models the HBM boundary traffic the merge keeps
    on-chip per round: at each interior boundary the staged program
    writes the producer's intermediates and reads them back — charged
    as half the smaller neighbour's per-iteration byte volume (a
    phase's ``bytes`` counts reads *and* writes, so one direction is
    half), × the while-trip budget. ``projected_peak_bytes`` is the
    live-range peak of the fused trace — co-residency of the merged
    stages is exactly what that walk measures, so it bounds the merge
    from above. ``refused`` marks a plan the memory certifier proves
    over capacity."""

    name: str
    phases: tuple
    dispatches_saved_per_iteration: int
    dispatches_saved_per_round: int
    savings_us: float
    savings_bytes: int
    projected_peak_bytes: int
    refused: bool = False
    reason: str = ""

    def describe(self) -> str:
        verdict = f"REFUSED ({self.reason})" if self.refused else \
            (f"saves {self.dispatches_saved_per_round} dispatch(es) "
             f"~{self.savings_us:.0f}us + {self.savings_bytes} B "
             f"HBM round-trips per round")
        return (f"{self.name}: {verdict}; projected peak "
                f"{self.projected_peak_bytes} B")


@dataclasses.dataclass(frozen=True)
class FusionPlan:
    """Ranked fusion targets for one traced round.

    ``status``: ``"planned"`` (at least one admissible candidate),
    ``"refused"`` (every candidate over capacity), ``"empty"`` (the
    program carries no staged phase annotations to merge), or
    ``"unknown"`` (trace/cost failure — notes say why)."""

    status: str
    candidates: tuple = ()       # admissible first, ranked by savings
    phase_costs: "dict | None" = None    # per-iteration {phase: costs}
    certified_peak_bytes: int = 0
    hbm_bytes: "int | None" = None
    while_trips: int = 1
    overhead_us: float = DISPATCH_OVERHEAD_US
    notes: tuple = ()

    @property
    def top(self) -> "FusionCandidate | None":
        for c in self.candidates:
            if not c.refused:
                return c
        return None

    @property
    def savings_bytes(self) -> int:
        """The top-ranked plan's modeled HBM savings per round — the
        ``fusion_plan_savings_bytes`` gauge."""
        c = self.top
        return 0 if c is None else int(c.savings_bytes)

    @property
    def projected_peak_bytes(self) -> int:
        """The bound the fused engine's memory certificate must land
        within (acceptance seam: certificate peak ≤ plan projection)."""
        c = self.top
        return self.certified_peak_bytes if c is None \
            else int(c.projected_peak_bytes)

    def describe(self) -> str:
        if self.status != "planned":
            return f"{self.status}: {'; '.join(self.notes) or 'n/a'}"
        c = self.top
        return (f"planned: top merge {c.describe()} "
                f"({len(self.candidates)} candidate(s), trips="
                f"{self.while_trips}, overhead {self.overhead_us}us)")

    def as_dict(self) -> dict:
        return {
            "status": self.status,
            "candidates": [dataclasses.asdict(c)
                           for c in self.candidates],
            "top": None if self.top is None else self.top.name,
            "savings_bytes": self.savings_bytes,
            "projected_peak_bytes": self.projected_peak_bytes,
            "certified_peak_bytes": int(self.certified_peak_bytes),
            "hbm_bytes": self.hbm_bytes,
            "while_trips": int(self.while_trips),
            "overhead_us": float(self.overhead_us),
            "phase_costs": {k: dict(v) for k, v in
                            (self.phase_costs or {}).items()
                            if not k.startswith("_")},
            "notes": list(self.notes),
        }


def plan_fusion(fn_or_jaxpr, *args, while_trips: "int | None" = None,
                hbm_bytes: "int | None" = None,
                donated_invars=None,
                overhead_us: float = DISPATCH_OVERHEAD_US,
                pipeline: tuple = IPM_PIPELINE) -> FusionPlan:
    """Plan stage fusion for a traced round.

    ``while_trips`` charges loop-carried boundaries (the inner solver
    loop's iteration budget — the PR 11 plumbing); ``hbm_bytes``
    overrides the refusal capacity (defaults to the backend device's
    reported HBM; no capacity known → nothing can be refused, noted).
    ``donated_invars`` flows to the memory certifier so the projected
    peak is donation-aware like the build-time certificate."""
    import jax

    from agentlib_mpc_tpu.lint.jaxpr.cost import WHILE_TRIP_GUESS
    from agentlib_mpc_tpu.lint.jaxpr.memory import (
        certify_memory,
        device_hbm_bytes,
    )
    from agentlib_mpc_tpu.telemetry.calibration import phase_costs

    notes: list = []
    try:
        if hasattr(fn_or_jaxpr, "jaxpr") and not args:
            closed = fn_or_jaxpr
        else:
            closed = jax.make_jaxpr(fn_or_jaxpr)(*args)
        # per-ITERATION costs: charge while bodies once — the trip
        # budget multiplies boundary counts explicitly below
        costs = phase_costs(closed, while_trips=1)
        mem = certify_memory(closed, donated_invars=donated_invars)
    except Exception as exc:  # noqa: BLE001 — planning must not kill
        # a build; an unplannable program is an honest unknown
        return FusionPlan(status="unknown",
                          notes=(f"planner error: {exc!r}",))
    if while_trips is None:
        while_trips = WHILE_TRIP_GUESS
        notes.append(f'trips="unbounded" — charged the '
                     f"{WHILE_TRIP_GUESS}-trip guess; pass "
                     f"while_trips=<iteration budget>")
    trips = max(int(while_trips), 1)
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes()
        if hbm_bytes is None:
            notes.append("backend reports no memory capacity — no "
                         "candidate can be refused over capacity")
    peak = int(mem.peak_bytes)
    if mem.status != "proved":
        notes.append(f"memory model degraded: {mem.describe()}")

    present = [p for p in pipeline
               if costs.get(p, {}).get("flops", 0)
               or costs.get(p, {}).get("bytes", 0)]
    if len(present) < 2:
        return FusionPlan(
            status="empty", phase_costs=costs,
            certified_peak_bytes=peak, hbm_bytes=hbm_bytes,
            while_trips=trips, overhead_us=float(overhead_us),
            notes=tuple(notes + [
                f"{len(present)} staged phase(s) observed — nothing "
                f"to merge (annotate stages with phase_scope)"]))

    def boundary_bytes(a: str, b: str) -> int:
        # the staged program's HBM round-trip at the a->b boundary:
        # half the smaller neighbour's byte volume (bytes counts both
        # directions of every access)
        return int(min(costs[a]["bytes"], costs[b]["bytes"]) // 2)

    cands = []
    for i in range(len(present)):
        for j in range(i + 1, len(present)):
            run = tuple(present[i:j + 1])
            saved = len(run) - 1
            sav_bytes = sum(boundary_bytes(run[k], run[k + 1])
                            for k in range(saved)) * trips
            cand = FusionCandidate(
                name="+".join(run), phases=run,
                dispatches_saved_per_iteration=saved,
                dispatches_saved_per_round=saved * trips,
                savings_us=float(saved * trips * overhead_us),
                savings_bytes=sav_bytes,
                projected_peak_bytes=peak)
            if hbm_bytes is not None and peak > int(hbm_bytes):
                cand = dataclasses.replace(
                    cand, refused=True,
                    reason=f"memory certifier proves the merged "
                           f"region's projected peak {peak} B over "
                           f"the {int(hbm_bytes)} B capacity")
            cands.append(cand)
    admissible = sorted(
        [c for c in cands if not c.refused],
        key=lambda c: (-c.savings_us, -c.savings_bytes, c.name))
    refused = [c for c in cands if c.refused]
    status = "planned" if admissible else "refused"
    if status == "refused":
        notes.append("every candidate merge is over capacity — the "
                     "staged program is the only admissible schedule")
    return FusionPlan(
        status=status,
        candidates=tuple(admissible + refused),
        phase_costs=costs,
        certified_peak_bytes=peak,
        hbm_bytes=None if hbm_bytes is None else int(hbm_bytes),
        while_trips=trips,
        overhead_us=float(overhead_us),
        notes=tuple(notes),
    )
