"""physXAI bridge + GPR data reduction.

Mirrors the reference's physXAI plugin tests
(``tests/test_physXAI_plugin/``: config translation, model creation,
predictor equivalence) against synthetic artifacts, plus the Nystroem
reducer contract (``data_reduction.py:33-52``).
"""

import numpy as np
import pytest

from agentlib_mpc_tpu.ml.data_reduction import NystroemReducer
from agentlib_mpc_tpu.ml.physxai import (
    convert_physxai_model,
    parse_physxai_features,
)
from agentlib_mpc_tpu.ml.predictors import make_predictor


def _preprocessing():
    return {
        "time_step": 900,
        "shift": 1,
        "inputs": ["T_amb", "Q", "Q_lag1", "T", "T_lag1"],
        "output": ["Change(T)"],
    }


class TestConfigTranslation:
    def test_lags_and_output_type(self):
        dt, inputs, output = parse_physxai_features(_preprocessing())
        assert dt == 900.0
        assert inputs["T_amb"].lag == 1
        assert inputs["Q"].lag == 2
        assert "T" not in inputs  # recursive output, not a plain input
        feat = output["T"]
        assert feat.lag == 2
        assert feat.output_type == "difference"
        assert feat.recursive

    def test_absolute_output(self):
        cfg = {**_preprocessing(), "output": ["y"],
               "inputs": ["T_amb", "Q"]}
        _, inputs, output = parse_physxai_features(cfg)
        assert output["y"].output_type == "absolute"
        assert not output["y"].recursive

    def test_shift_must_be_one(self):
        with pytest.raises(ValueError, match="shift"):
            parse_physxai_features({**_preprocessing(), "shift": 2})

    def test_non_consecutive_lags_rejected(self):
        cfg = {**_preprocessing(), "inputs": ["Q", "Q_lag2"]}
        with pytest.raises(ValueError, match="consecutive"):
            parse_physxai_features(cfg)


class TestModelConversion:
    def test_linreg_artifact_roundtrip(self, tmp_path):
        from sklearn.linear_model import LinearRegression

        rng = np.random.default_rng(0)
        # feature layout follows our column_order: inputs (T_amb, Q x2),
        # then recursive output T x2
        X = rng.normal(size=(50, 5))
        y = X @ np.array([0.1, -0.4, -0.2, 0.9, 0.05]) + 0.3
        lr = LinearRegression().fit(X, y)
        import joblib

        path = tmp_path / "linreg.joblib"
        joblib.dump(lr, path)
        m = convert_physxai_model(_preprocessing(), path, "LinReg")
        assert m.dt == 900.0
        pred = make_predictor(m)
        for x in rng.normal(size=(5, 5)):
            np.testing.assert_allclose(
                float(pred.apply(pred.params, x)[0]),
                lr.predict(x[None, :])[0], rtol=1e-6)

    def test_ann_artifact(self):
        rng = np.random.default_rng(1)
        artifact = {
            "weights": [rng.normal(size=(5, 8)), rng.normal(size=(8, 1))],
            "biases": [rng.normal(size=8), rng.normal(size=1)],
            "activations": ["tanh", "linear"],
        }
        m = convert_physxai_model(_preprocessing(), artifact, "ANN")
        pred = make_predictor(m)
        out = pred.apply(pred.params, np.zeros(5))
        assert out.shape == (1,)

    def test_generate_requires_physxai(self):
        from agentlib_mpc_tpu.ml.physxai import generate_physxai_models

        with pytest.raises(ImportError, match="physXAI"):
            generate_physxai_models(["train.py"], ".", "data.csv", "run1")


class TestNystroem:
    def test_reduces_to_m_points(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(300, 2))
        y = X[:, 0] + X[:, 1]
        Xm, ym = NystroemReducer(n_components=40).reduce(X, y)
        assert len(Xm) <= 40
        assert len(Xm) == len(ym)
        # inducing points are actual samples with matching targets
        for xr, yr in zip(Xm[:5], ym[:5]):
            i = int(np.argmin(np.sum((X - xr) ** 2, axis=1)))
            assert yr[0] == pytest.approx(y[i])

    def test_small_set_passthrough(self):
        X = np.ones((5, 2))
        y = np.ones(5)
        Xm, ym = NystroemReducer(n_components=10).reduce(X, y)
        assert len(Xm) == 5

    def test_reduced_gpr_still_accurate(self):
        from agentlib_mpc_tpu.ml import Feature, OutputFeature
        from agentlib_mpc_tpu.ml.training import fit_gpr

        rng = np.random.default_rng(3)
        X = rng.uniform(-2, 2, size=(400, 1))
        y = np.sin(X[:, 0])
        Xm, ym = NystroemReducer(n_components=60, seed=0).reduce(X, y)
        m = fit_gpr(Xm, ym, dt=1.0,
                    inputs={"a": Feature(name="a")},
                    output={"y": OutputFeature(name="y",
                                               output_type="absolute",
                                               recursive=False)})
        pred = make_predictor(m)
        Xq = np.linspace(-1.5, 1.5, 20)[:, None]
        got = np.array([float(pred.apply(pred.params, x)[0]) for x in Xq])
        np.testing.assert_allclose(got, np.sin(Xq[:, 0]), atol=0.1)
