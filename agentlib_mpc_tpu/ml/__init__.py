"""ML surrogates: serialized exchange format, JAX predictors, NARX models.

TPU-native counterpart of the reference's data-driven MPC stack
(``agentlib_mpc/models/serialized_ml_model.py``, ``casadi_predictor.py``,
``casadi_ml_model.py``): trained ANN/GPR/linear-regression surrogates are
serialized to a JSON exchange format, evaluated as pure JAX functions (so
they sit *inside* the jitted OCP), and composed into hybrid NARX models
with white-box dynamics.
"""

from agentlib_mpc_tpu.ml.serialized import (
    Feature,
    OutputFeature,
    SerializedANN,
    SerializedGPR,
    SerializedLinReg,
    SerializedMLModel,
    SerializedWarmstart,
    column_order,
    load_serialized_model,
)
from agentlib_mpc_tpu.ml.predictors import make_predictor
