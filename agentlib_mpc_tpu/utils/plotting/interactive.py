"""Interactive dashboards (reference ``utils/plotting/interactive.py:300``,
``mpc_dashboard.py``, ``admm_dashboard.py``). Dash/plotly are optional
extras; without them a static matplotlib overview is produced instead so
the entry point always yields something useful."""

from __future__ import annotations

from typing import Optional


def show_dashboard(results: dict, stats=None, save_path: Optional[str] = None,
                   port: int = 8050, block: bool = True):
    """MPC/ADMM results overview. With dash+plotly installed, serves the
    interactive dashboard (agent/module browsing, prediction fades, ADMM
    iteration browser, residual/solver panels — the reference's
    ``mpc_dashboard``/``admm_dashboard`` capability); otherwise renders a
    static multi-panel matplotlib figure (returned; saved when
    ``save_path`` given). Never raises just because dash is present —
    any dashboard failure falls back to the static figure."""
    try:
        import dash  # noqa: F401
        import plotly  # noqa: F401
    except ImportError:
        return _static_dashboard(results, stats, save_path)
    try:
        from agentlib_mpc_tpu.utils.plotting.dashboard import (
            build_app,
            run_dashboard,
        )

        if not block:
            return build_app(results, stats)
        return run_dashboard(results, stats, port=port)
    except ValueError:
        raise  # empty/unshaped results: same error contract as static
    except Exception as exc:  # pragma: no cover - dash runtime issues
        import logging

        logging.getLogger(__name__).warning(
            "interactive dashboard failed (%s); falling back to static",
            exc)
        return _static_dashboard(results, stats, save_path)


def _static_dashboard(results, stats, save_path):
    from agentlib_mpc_tpu.utils.plotting.basic import make_fig
    from agentlib_mpc_tpu.utils.plotting.mpc import plot_mpc

    frames = {}
    for agent_id, modules in results.items():
        if not isinstance(modules, dict):
            continue
        for module_id, df in modules.items():
            if df is None:
                continue
            if hasattr(df, "index") and getattr(df.index, "nlevels", 1) == 2:
                frames[f"{agent_id}/{module_id}"] = df
    if not frames:
        raise ValueError("no MPC-shaped results to show")
    key, df = next(iter(frames.items()))
    variables = sorted({c[1] for c in df.columns
                        if isinstance(c, tuple)}) or list(df.columns)
    rows = len(variables)
    fig, axes = make_fig(rows=rows)
    for ax, var in zip(axes.ravel(), variables):
        plot_mpc(df, var, ax=ax)
        ax.set_title(f"{key}: {var}", fontsize=9)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path)
    return fig


