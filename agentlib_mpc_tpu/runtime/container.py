"""Container entry point: run one agent (or a local group) from a config
file, joined to the fleet over MQTT.

Counterpart of the reference's cloneMAP container entry
(``DockerfileMPC:25`` → agentlib's clonemap communicator): each container
hosts an agent process; inter-agent traffic rides an external broker.
Configuration via environment:

``AGENT_CONFIG``      path to a JSON agent config (reference shape:
                      ``{"id": ..., "modules": [...]}``) or a JSON list of
                      such configs (one container hosting a local group)
``MQTT_HOST``/``MQTT_PORT``  broker address (default localhost:1883);
                      set ``MQTT_HOST=none`` for an isolated container
                      (single-agent simulation, no fleet)
``MQTT_RECONNECT_MAX_DELAY``  cap (s) on the decorrelated-jitter
                      reconnect backoff (default 1.0; docs/robustness.md)
``RUN_UNTIL``         simulation/wall-clock horizon in seconds
                      (default: run forever in wall-clock mode)
``REALTIME``          "1" (default) wall-clock env; "0" fast simulation
``RESULTS_DIR``       when set, every module's results frame is written
                      to ``<dir>/<agent>__<module>.csv`` on shutdown
                      (the reference's results CSVs, written by the
                      container instead of the host)

Usage: ``python -m agentlib_mpc_tpu.runtime.container``
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys

logger = logging.getLogger(__name__)


def load_configs(path: str) -> list[dict]:
    with open(path) as fh:
        cfg = json.load(fh)
    return cfg if isinstance(cfg, list) else [cfg]


def build_mas(configs: list[dict], realtime: bool = True,
              mqtt_host: str | None = None, mqtt_port: int = 1883):
    """LocalMAS over the configs; optionally bridged onto an MQTT broker
    so other containers' agents appear as external peers."""
    import agentlib_mpc_tpu.modules  # noqa: F401 - register module types
    from agentlib_mpc_tpu.runtime.mas import LocalMAS

    mas = LocalMAS(configs, env={"rt": realtime, "factor": 1.0})
    buses = []
    if mqtt_host and mqtt_host.lower() != "none":
        from agentlib_mpc_tpu.runtime.mqtt import MqttBus

        reconnect_cap = float(
            os.environ.get("MQTT_RECONNECT_MAX_DELAY", "1.0"))
        for agent_id, agent in mas.agents.items():
            bus = MqttBus(agent_id, broker_host=mqtt_host,
                          broker_port=mqtt_port,
                          reconnect_max_delay=reconnect_cap)
            bus.attach(agent.data_broker)
            buses.append(bus)
    return mas, buses


def write_results(mas, results_dir: str) -> list[str]:
    """Persist every module's results frame as
    ``<dir>/<agent>__<module>.csv`` (reference results-CSV role)."""
    os.makedirs(results_dir, exist_ok=True)
    written = []
    for agent_id, modules in mas.get_results().items():
        for module_id, df in modules.items():
            path = os.path.join(results_dir,
                                f"{agent_id}__{module_id}.csv")
            try:
                df.to_csv(path)
                written.append(path)
            except Exception as exc:  # noqa: BLE001 - best-effort dump
                logger.warning("could not write %s: %s", path, exc)
    logger.info("wrote %d results CSVs to %s", len(written), results_dir)
    return written


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO"),
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    config_path = os.environ.get("AGENT_CONFIG")
    if not config_path:
        print("AGENT_CONFIG must point to a JSON agent config",
              file=sys.stderr)
        return 2
    configs = load_configs(config_path)
    realtime = os.environ.get("REALTIME", "1") != "0"
    until_env = os.environ.get("RUN_UNTIL")
    until = float(until_env) if until_env else (
        float("inf") if realtime else 24 * 3600.0)
    mas, buses = build_mas(
        configs, realtime=realtime,
        mqtt_host=os.environ.get("MQTT_HOST", "localhost"),
        mqtt_port=int(os.environ.get("MQTT_PORT", "1883")))

    stop = {"flag": False}

    def _sig(_signum, _frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        if realtime:
            # run in slices so SIGTERM can land between env.run calls —
            # a finite wall-clock horizon must be interruptible too, or
            # docker stop's grace period expires and SIGKILL skips the
            # clean terminate()/close() below
            t = 0.0
            while not stop["flag"] and t < until:
                t = min(t + 5.0, until)
                mas.run(until=t)
        else:
            mas.run(until=until)
    finally:
        mas.terminate()
        results_dir = os.environ.get("RESULTS_DIR")
        if results_dir:
            try:
                write_results(mas, results_dir)
            except Exception as exc:  # noqa: BLE001 - best-effort dump:
                # a read-only mount must not leak the buses below or
                # mask an original exception from the run
                logger.warning("results dump to %s failed: %s",
                               results_dir, exc)
        for bus in buses:
            bus.close()
    logger.info("container agent(s) %s shut down cleanly",
                [c.get("id") for c in configs])
    return 0


if __name__ == "__main__":
    sys.exit(main())
