"""Keras interop equivalence tests (reference pattern:
``tests/test_serialized_keras_ann.py:34-107`` — stored Keras artifacts must
predict identically through the in-OCP evaluator).

Each test builds a real Keras model, converts it with
``ml/keras_graph.from_keras`` and checks the pure-JAX evaluation against
``model.predict`` on random inputs.
"""

import json

import numpy as np
import pytest

keras = pytest.importorskip("keras")

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu.ml.keras_graph import (
    build_graph_apply,
    from_keras,
    spec_from_jsonable,
    spec_to_jsonable,
)
from agentlib_mpc_tpu.ml.predictors import make_predictor
from agentlib_mpc_tpu.ml.serialized import (
    Feature,
    OutputFeature,
    SerializedGraphANN,
    SerializedKerasANN,
    SerializedMLModel,
)

RNG = np.random.default_rng(42)


def _check_equiv(model, n_in, atol=1e-5, n_samples=5):
    spec, params = from_keras(model)
    apply = build_graph_apply(spec)
    x = RNG.normal(size=(n_samples, n_in)).astype(np.float32)
    y_keras = np.asarray(model.predict(x, verbose=0))
    y_jax = np.stack([np.asarray(apply(params, jnp.asarray(xi)))
                      for xi in x])
    np.testing.assert_allclose(y_jax, y_keras.reshape(n_samples, -1),
                               atol=atol, rtol=1e-4)
    return spec, params, apply


def test_sequential_dense_stack():
    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(6, activation="tanh"),
        keras.layers.Dense(5, activation="sigmoid"),
        keras.layers.Dense(2, activation="softplus"),
        keras.layers.Dense(1, activation="linear"),
    ])
    _check_equiv(model, 4)


def test_sequential_batchnorm_rescaling():
    model = keras.Sequential([
        keras.layers.Input(shape=(3,)),
        keras.layers.Rescaling(scale=2.5, offset=-1.0),
        keras.layers.Dense(6, activation="relu"),
        keras.layers.BatchNormalization(),
        keras.layers.Dense(1),
    ])
    # give batchnorm non-trivial moving statistics
    model(np.zeros((1, 3), np.float32))
    bn = model.layers[2]
    bn.set_weights([
        RNG.normal(size=6).astype(np.float32) + 1.0,   # gamma
        RNG.normal(size=6).astype(np.float32),         # beta
        RNG.normal(size=6).astype(np.float32),         # moving mean
        RNG.uniform(0.5, 2.0, size=6).astype(np.float32),  # moving var
    ])
    _check_equiv(model, 3)


def test_sequential_normalization_adapted():
    norm = keras.layers.Normalization(axis=-1)
    data = RNG.normal(size=(100, 4)).astype(np.float32) * 3.0 + 2.0
    norm.adapt(data)
    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        norm,
        keras.layers.Dense(1),
    ])
    _check_equiv(model, 4)


def test_functional_branches_and_merges():
    inp = keras.layers.Input(shape=(5,))
    a = keras.layers.Dense(7, activation="relu")(inp)
    b = keras.layers.Dense(7, activation="tanh")(inp)
    added = keras.layers.Add()([a, b])
    subbed = keras.layers.Subtract()([a, b])
    mult = keras.layers.Multiply()([added, subbed])
    avg = keras.layers.Average()([a, b])
    cat = keras.layers.Concatenate()([mult, avg])
    out = keras.layers.Dense(1)(cat)
    model = keras.Model(inputs=inp, outputs=out)
    _check_equiv(model, 5)


def test_functional_nested_submodel():
    inner = keras.Sequential(
        [keras.layers.Input(shape=(6,)),
         keras.layers.Dense(4, activation="relu"),
         keras.layers.Dense(3, activation="tanh")],
        name="inner_encoder")
    inp = keras.layers.Input(shape=(6,))
    enc = inner(inp)
    out = keras.layers.Dense(1)(enc)
    model = keras.Model(inputs=inp, outputs=out)
    _check_equiv(model, 6)


def test_flatten_reshape_cropping():
    model = keras.Sequential([
        keras.layers.Input(shape=(8,)),
        keras.layers.Reshape((4, 2)),
        keras.layers.Cropping1D(cropping=(1, 1)),
        keras.layers.Flatten(),
        keras.layers.Dense(1),
    ])
    _check_equiv(model, 8)


class _RBF(keras.layers.Layer):
    """Minimal RBF layer with the reference's attributes
    (``casadi_predictor.py:517-532``)."""

    def __init__(self, units, dim, **kw):
        super().__init__(**kw)
        self.units = units
        self.centers = self.add_weight(shape=(units, dim), name="centers")
        self.log_gamma = self.add_weight(shape=(units,), name="log_gamma")

    def call(self, x):
        diff = x[:, None, :] - self.centers[None, :, :]
        dist_sq = keras.ops.sum(diff ** 2, axis=2)
        return keras.ops.exp(-keras.ops.exp(self.log_gamma) * dist_sq)


def test_rbf_layer():
    inp = keras.layers.Input(shape=(3,))
    phi = _RBF(5, 3, name="rbf_basis")(inp)
    out = keras.layers.Dense(1)(phi)
    model = keras.Model(inputs=inp, outputs=out)
    _check_equiv(model, 3)


def test_exponential_and_gaussian_activations():
    model = keras.Sequential([
        keras.layers.Input(shape=(2,)),
        keras.layers.Dense(4, activation="exponential"),
        keras.layers.Dense(1),
    ])
    _check_equiv(model, 2)


def test_graph_document_roundtrip():
    model = keras.Sequential([
        keras.layers.Input(shape=(3,)),
        keras.layers.Dense(4, activation="relu"),
        keras.layers.Dense(1),
    ])
    spec, params, apply = _check_equiv(model, 3)
    doc = spec_to_jsonable(spec, params)
    doc2 = json.loads(json.dumps(doc))          # through-the-wire
    spec2, params2 = spec_from_jsonable(doc2)
    apply2 = build_graph_apply(spec2)
    x = jnp.asarray(RNG.normal(size=3))
    np.testing.assert_allclose(np.asarray(apply2(params2, x)),
                               np.asarray(apply(params, x)), atol=1e-6)


def test_serialized_keras_ann_artifact(tmp_path):
    """Reference flow: save .keras, reference by path, load, predict
    (``serialized_ml_model.py:662-709``)."""
    model = keras.Sequential([
        keras.layers.Input(shape=(2,)),
        keras.layers.Dense(5, activation="tanh"),
        keras.layers.Dense(1),
    ])
    feats = {"T": Feature(name="T", lag=1), "u": Feature(name="u", lag=1)}
    outs = {"T": OutputFeature(name="T", lag=1, output_type="absolute")}
    ser = SerializedKerasANN.serialize(
        model, dt=300.0, inputs=feats, output=outs,
        model_path=tmp_path / "m.keras")
    # JSON round trip of the document
    ser2 = SerializedMLModel.from_json(ser.to_json())
    pred = make_predictor(ser2)
    x = RNG.normal(size=(4, 2)).astype(np.float32)
    y_keras = np.asarray(model.predict(x, verbose=0)).reshape(-1)
    y_jax = np.asarray([float(pred.apply(pred.params, jnp.asarray(xi))[0])
                        for xi in x])
    np.testing.assert_allclose(y_jax, y_keras, atol=1e-5)
    # conversion to the self-contained document drops the keras dependency
    graph_doc = ser2.to_graph()
    pred3 = make_predictor(SerializedMLModel.from_json(graph_doc.to_json()))
    y3 = np.asarray([float(pred3.apply(pred3.params, jnp.asarray(xi))[0])
                     for xi in x])
    np.testing.assert_allclose(y3, y_keras, atol=1e-5)


def test_shared_layer_two_calls():
    """Weight sharing: one Dense applied to two tensors must yield two
    distinct graph nodes (not a silent overwrite)."""
    shared = keras.layers.Dense(4, activation="tanh", name="shared_dense")
    inp = keras.layers.Input(shape=(4,))
    a = shared(inp)
    b = shared(keras.layers.Rescaling(scale=2.0)(inp))
    out = keras.layers.Dense(1)(keras.layers.Concatenate()([a, b]))
    model = keras.Model(inputs=inp, outputs=out)
    spec, params, _ = _check_equiv(model, 4)
    dense_nodes = [n for n in spec["nodes"] if "shared_dense" in n["name"]]
    assert len(dense_nodes) == 2
    assert len({n["name"] for n in dense_nodes}) == 2


def test_unsupported_layer_raises():
    model = keras.Sequential([
        keras.layers.Input(shape=(4, 2)),
        keras.layers.GlobalAveragePooling1D(),
        keras.layers.Dense(1),
    ])
    with pytest.raises(NotImplementedError, match="not supported"):
        from_keras(model)


def test_rescaling_per_feature_arrays():
    model = keras.Sequential([
        keras.layers.Input(shape=(2,)),
        keras.layers.Rescaling(scale=[0.1, 10.0], offset=[0.0, -1.0]),
        keras.layers.Dense(1),
    ])
    _check_equiv(model, 2)


def test_converted_model_is_differentiable_and_vmappable():
    """The point of the exercise: the converted ANN sits inside the OCP."""
    inp = keras.layers.Input(shape=(3,))
    h = keras.layers.Dense(6, activation="tanh")(inp)
    out = keras.layers.Dense(1)(h)
    model = keras.Model(inputs=inp, outputs=out)
    spec, params = from_keras(model)
    apply = build_graph_apply(spec)
    g = jax.grad(lambda x: apply(params, x)[0])(jnp.ones(3))
    assert g.shape == (3,) and bool(jnp.all(jnp.isfinite(g)))
    ys = jax.vmap(lambda x: apply(params, x))(jnp.ones((7, 3)))
    assert ys.shape == (7, 1)
