"""SPMD collective certifier (ISSUE 11): the adversarial corpus.

The replication-lattice pass must prove the shard-uniformity of the
fused round's collective schedule, refute the divergence hazards a pod
cannot observe at runtime (shard-varying while-exits and branch
indices over a psum, dropped axis_names behind ``check_rep=False``
out-specs, collectives over the wrong mesh axis), stay honest about
callbacks (``unknown``, never executed), and pin the PR 9 "ONE psum
family per ADMM iteration" invariant against ``[jaxpr.collectives]``
— including the mutation direction: an injected second all-reduce
family must be refuted with the offending equation named (the
static-analysis analogue of PR 3's source-surgery test).

Small shard_map programs trace in milliseconds; the two engine-backed
classes (schedule pin, degraded-mesh identity) share module fixtures
the way every mesh test module does — engine builds dominate the cost.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from agentlib_mpc_tpu.lint.jaxpr.collectives import (
    CollectiveCertificate,
    certify_collectives,
    check_collective_budget,
)
from agentlib_mpc_tpu.lint.jaxpr.cost import op_cost
from agentlib_mpc_tpu.ops import admm as admm_ops
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel import fleet_mesh
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)
from agentlib_mpc_tpu.parallel.survival import FleetSupervisor

from conftest import make_tracker_model  # noqa: E402


def _mesh(n=4, axis="a"):
    return Mesh(np.array(jax.devices("cpu")[:n]), (axis,))


def _certify(body, mesh, in_specs, out_specs, x, **kw):
    sm = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)
    return certify_collectives(sm, x, **kw)


class TestReplicationLattice:
    """The corpus on hand-written shard_map programs."""

    def test_uniform_psum_schedule_proved(self):
        mesh = _mesh()

        def body(x):
            return lax.psum(jnp.sum(x), "a")

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 3)))
        assert cert.proved
        assert len(cert.schedule) == 1
        op = cert.schedule[0]
        assert op.primitive == "psum" and op.axes == ("a",)
        assert op.loop_path == ()
        assert cert.schedule_digest is not None
        assert cert.axis_sizes == {"a": 4}

    def test_divergent_while_exit_refuted_naming_eqn(self):
        """A while_loop whose exit predicate is shard-varying,
        dominating a psum: shards would disagree about entering the
        collective — the silent pod hang, refuted by name."""
        mesh = _mesh()

        def body(x):
            def cond(c):
                v, _ = c
                return jnp.sum(v) < 10.0        # shard-local: VARYING

            def step(c):
                v, acc = c
                return v + 1.0, acc + lax.psum(jnp.sum(v), "a")

            _, acc = lax.while_loop(cond, step, (x, 0.0))
            return acc

        cert = _certify(body, mesh, P("a"), P(), jnp.zeros((8, 2)))
        assert cert.status == "refuted"
        msg = " ".join(cert.refutations)
        assert "psum" in msg and "while" in msg.lower()
        assert "SHARD-VARYING" in msg
        # the offending eqn is named by source position
        assert "test_jaxpr_collectives" in msg

    def test_psum_then_branch_proved(self):
        """The predicate is re-replicated BY the collective before the
        loop consumes it — exactly the fused round's Boyd exit shape
        (psum'ed residuals feed the while predicate)."""
        mesh = _mesh()

        def body(x):
            r = lax.psum(jnp.sum(x), "a")       # rejoins REPLICATED

            def cond(c):
                v, _ = c
                return v < 10.0                  # replicated predicate

            def step(c):
                v, s = c
                return v + 1.0, s + lax.psum(v, "a")

            out = lax.while_loop(cond, step, (r, 0.0))
            return out[1]

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 2)))
        assert cert.proved, cert.refutations
        paths = [op.loop_path for op in cert.schedule]
        assert () in paths and ("while",) in paths

    def test_nested_single_axis_psums_close_on_2d_mesh(self):
        """The per-axis lattice (ISSUE 12): on a 2-D mesh the scenario
        fleet closes its residuals with one psum PER AXIS —
        psum@b(psum@a(x)) must prove re-replication (the scalar lattice
        could not represent "varies only over b" and refuted this
        shape), the two collectives landing in two distinct families.
        An in-spec sharded over ONE axis must also seed as replicated
        along the other: psum over just that axis then fully rejoins."""
        devs = np.array(jax.devices("cpu")[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("a", "b"))

        def body(x, y):
            # x sharded over both axes; y over "a" only
            r = lax.psum(lax.psum(jnp.sum(x), "a"), "b")
            ry = lax.psum(jnp.sum(y), "a")   # rejoins: y repl. over b

            def cond(c):
                return c[0] < 10.0           # provably replicated

            def step(c):
                v, s = c
                return v + 1.0, s + lax.psum(v, ("a", "b"))

            return lax.while_loop(cond, step, (r + ry, 0.0))[1]

        sm = shard_map(body, mesh=mesh, in_specs=(P("a", "b"), P("a")),
                       out_specs=P(), check_rep=False)
        cert = certify_collectives(sm, jnp.zeros((4, 4)),
                                   jnp.zeros((4, 2)))
        assert cert.proved, cert.refutations
        fams = cert.families()
        assert "0:psum@a" in fams and "0:psum@b" in fams

    def test_varying_cond_over_collective_refuted(self):
        mesh = _mesh()

        def body(x):
            pred = jnp.sum(x) > 0.0              # shard-varying index
            return lax.cond(pred,
                            lambda v: lax.psum(jnp.sum(v), "a"),
                            lambda v: jnp.sum(v), x)

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 2)))
        assert cert.status == "refuted"
        assert any("cond" in r for r in cert.refutations)

    def test_missing_axis_name_refuted(self):
        """A consensus mean whose axis_name was dropped: each shard
        computes a LOCAL mean but the out-spec claims it replicated —
        with check_rep=False only this pass catches it."""
        mesh = _mesh()

        def body(x):
            return jnp.mean(x, axis=0)           # no psum: shard-local

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 2)))
        assert cert.status == "refuted"
        msg = " ".join(cert.refutations)
        assert "REPLICATED" in msg and "out-spec" in msg

    def test_mismatched_axis_name_refuted(self):
        """On a 2-axis mesh, a psum over the wrong axis is refuted
        against the expected axis set."""
        devs = np.array(jax.devices("cpu")[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("a", "b"))

        def body(x):
            return lax.psum(jnp.sum(x), "b")

        sm = shard_map(body, mesh=mesh, in_specs=P("a", "b"),
                       out_specs=P(), check_rep=False)
        cert = certify_collectives(sm, jnp.ones((4, 4)),
                                   allowed_axes=("a",))
        assert cert.status == "refuted"
        assert any("unexpected axis" in r and "'b'" in r
                   for r in cert.refutations)

    def test_partial_axis_psum_on_2d_mesh_does_not_rejoin(self):
        """On a 2-axis mesh a psum over ONE axis re-replicates only
        along that axis — the result still varies over the other, so a
        while predicate derived from it is shard-varying (refuted).
        The same program with the psum over BOTH axes is proved: the
        coverage rule must not cost full-coverage precision."""
        devs = np.array(jax.devices("cpu")[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("a", "b"))

        def make(reduce_axes):
            def body(x):
                r = lax.psum(jnp.sum(x), reduce_axes)

                def cond(c):
                    return c[0] < 10.0

                def step(c):
                    v, s = c
                    return v + 1.0, s + lax.psum(v, ("a", "b"))

                return lax.while_loop(cond, step, (r, 0.0))[1]

            return shard_map(body, mesh=mesh, in_specs=P("a", "b"),
                             out_specs=P(), check_rep=False)

        partial = certify_collectives(make("a"), jnp.zeros((4, 4)))
        assert partial.status == "refuted"
        assert any("SHARD-VARYING" in r for r in partial.refutations)
        assert any("subset of the mesh axes" in n for n in partial.notes)

        full = certify_collectives(make(("a", "b")), jnp.zeros((4, 4)))
        assert full.proved

    def test_nested_shard_map_opaque_unknown(self):
        """A nested shard_map's in-spec seeding ignores the outer
        shard-local payloads, so walking it could launder VARYING back
        to REPLICATED — the region must be opaque: honest "unknown",
        never a clean certificate."""
        mesh = _mesh()

        def inner(v):
            return v * 2.0

        def body(x):
            y = shard_map(inner, mesh=mesh, in_specs=P(),
                          out_specs=P(), check_rep=False)(jnp.sum(x))

            def cond(c):
                return c[0] < 10.0

            def step(c):
                v, s = c
                return v + 1.0, s + lax.psum(v, "a")

            return lax.while_loop(cond, step, (y, 0.0))[1]

        cert = _certify(body, mesh, P("a"), P(), jnp.zeros((8, 2)))
        assert cert.status != "proved"
        assert "shard_map" in cert.opaque
        assert any("nested shard_map" in n for n in cert.notes)
        assert cert.schedule_digest is None

    def test_pure_callback_unknown_never_executed(self):
        """Callbacks degrade the verdict to an honest unknown; the host
        function is NEVER executed during certification."""
        mesh = _mesh()
        calls = []

        def hostile(x):
            calls.append(1)
            raise AssertionError("certification executed a callback")

        def body(x):
            y = jax.pure_callback(
                hostile, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return lax.psum(jnp.sum(y), "a")

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 2)))
        assert cert.status == "unknown"
        assert "pure_callback" in cert.opaque
        assert calls == []
        assert cert.schedule_digest is None  # an unproved schedule has
        # no identity to assert restores/rebuilds against

    def test_scan_multiplicity_recorded(self):
        mesh = _mesh()

        def body(x):
            def step(c, _):
                return c + lax.psum(jnp.sum(x), "a"), None

            out, _ = lax.scan(step, 0.0, None, length=5)
            return out

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 2)))
        assert cert.proved
        (op,) = cert.schedule
        assert op.loop_path == ("scan[5]",) and op.multiplicity == 5
        assert op.bounded

    def test_varying_predicate_through_long_carry_chain_refuted(self):
        """VARYING walks an iteration-to-iteration carry chain one
        link per fixpoint pass — a fixed small pass cap would converge
        early and PROVE this genuinely divergent loop (the exact
        silent-pod-hang class), so the fixpoint must be bounded by the
        carry count, not a constant."""
        mesh = _mesh()

        def body(x):
            def cond(c):
                return c[0] < 10.0           # reads the END of the chain

            def step(c):
                c0, c1, c2, c3, c4, c5, acc = c
                # 6-link shift chain: the shard-local seed reaches the
                # predicate's carry only on the 6th pass
                return (c1, c2, c3, c4, c5, jnp.sum(x),
                        acc + lax.psum(jnp.sum(x), "a"))

            out = lax.while_loop(cond, step, (0.0,) * 7)
            return out[-1]

        cert = _certify(body, mesh, P("a"), P(), jnp.zeros((8, 2)))
        assert cert.status == "refuted"
        assert any("SHARD-VARYING" in r for r in cert.refutations)

    def test_comm_bytes_scale_with_axis_and_trips(self):
        mesh = _mesh()

        def body(x):
            def cond(c):
                return c[0] < 10.0

            def step(c):
                v, s = c
                return v + 1.0, s + lax.psum(jnp.sum(x), "a")

            seed = lax.psum(0.0, "a")
            return lax.while_loop(cond, step, (seed, 0.0))[1]

        cert = _certify(body, mesh, P("a"), P(), jnp.ones((8, 2)))
        assert cert.proved
        # the loop-invariant seed psum folds at trace time; what
        # remains is the per-trip psum: payload x axis size x trips
        # (x64 follows the ambient flag — read the recorded payload)
        ops = [op for op in cert.schedule if not op.bounded]
        assert ops, "the in-loop psum must be on the schedule"
        per_trip = sum(op.bytes_payload for op in ops) * 4
        fixed = cert.comm_bytes(while_trips=1) - per_trip
        assert cert.comm_bytes(while_trips=10) == fixed + 10 * per_trip
        assert cert.comm_bytes(while_trips=10) > \
            cert.comm_bytes(while_trips=1)


class TestCostModelCommRows:
    """Satellites: collectives get a comm-cost column, while loops an
    explicit trips qualifier."""

    def test_collective_bytes_counted(self):
        mesh = _mesh()

        def body(x):
            return lax.psum(x, "a")              # (2,) f32 payload

        sm = shard_map(body, mesh=mesh, in_specs=P(None, "a"),
                       out_specs=P(None, "a"), check_rep=False)
        est = op_cost(sm, jnp.ones((2, 8)))
        # bytes moved x axis size: the shard-local (2,2) f32 payload...
        # shapes aside, the row must be non-zero and attributed to psum
        assert est.collective_bytes > 0
        assert "psum" in est.per_primitive_collective_bytes
        # ... and scaled by the 4-device axis read from the mesh eqn
        assert est.collective_bytes == \
            est.per_primitive_collective_bytes["psum"]
        base = op_cost(sm, jnp.ones((2, 8)),
                       axis_sizes={"a": 1}).collective_bytes
        assert est.collective_bytes == 4 * base

    def test_positional_axis_psum_not_charged_as_comm(self):
        """A vmapped psum over a positional batch axis is a
        shard-local reduction — zero cross-device traffic — so it must
        not inflate collective_bytes; it is charged as the reduction
        it lowers to."""
        fn = jax.vmap(lambda x: lax.psum(x, "b"), axis_name="b")
        est = op_cost(fn, jnp.arange(8.0))
        assert est.collective_bytes == 0
        assert est.per_primitive_collective_bytes == {}
        assert est.per_primitive_flops.get("psum", 0) > 0

    def test_while_unbounded_qualifier_and_budget(self):
        def fn(x):
            def cond(c):
                return c[0] < 10.0

            def step(c):
                return c[0] + 1.0, c[1] + jnp.sum(x)

            return lax.while_loop(cond, step, (0.0, 0.0))[1]

        est = op_cost(fn, jnp.ones((4,)))
        assert any('trips="unbounded"' in n for n in est.notes)
        budgeted = op_cost(fn, jnp.ones((4,)), while_trips=25)
        assert any("25-trip budget" in n for n in budgeted.notes)
        assert budgeted.flops > est.flops      # 25 > the 10-trip guess
        assert not any('unbounded' in n for n in budgeted.notes)


OPTS = FusedADMMOptions(max_iterations=8, rho=2.0)
SOLVER = SolverOptions(max_iter=25)

Tracker = make_tracker_model()


def _tracker_fleet(n_agents, mesh, **engine_kw):
    ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                     method="multiple_shooting")
    group = AgentGroup(name="fleet", ocp=ocp, n_agents=n_agents,
                       couplings={"shared_u": "u"},
                       solver_options=SOLVER,
                       # the solver-routing certification (LQ probe) is
                       # irrelevant to the collective schedule — skip it
                       # so these engine builds stay cheap
                       qp_fast_path="off")
    thetas = stack_params([
        ocp.default_params(p=jnp.array([float(i + 1)]))
        for i in range(n_agents)])
    engine = FusedADMM([group], OPTS, mesh=mesh, **engine_kw)
    return engine, thetas


class TestFusedRoundSchedule:
    """The engine seam: build-time certification, the budget pin, the
    mutation direction, and degraded-mesh schedule identity."""

    @pytest.fixture(scope="class")
    def fleet(self, eight_devices):
        mesh = fleet_mesh(devices=eight_devices)
        engine, thetas = _tracker_fleet(8, mesh)
        return engine, thetas

    def test_mesh_engine_certifies_at_build(self, fleet):
        engine, _thetas = fleet
        cert = engine.collective_certificate
        assert isinstance(cert, CollectiveCertificate)
        assert cert.proved, cert.refutations
        assert engine.collective_schedule_digest == cert.schedule_digest
        fams = cert.families()
        # PR 9's prose invariant, now a proof: ONE psum family, riding
        # the agents axis, inside the iteration while_loop — nothing
        # deeper (no all-reduce per interior-point iteration), nothing
        # else
        assert set(fams) == {"1:psum@agents"}
        assert all(op.loop_path == ("while",) for op in cert.schedule)

    def test_gate_matches_checked_in_budget(self, fleet, eight_devices):
        """The [jaxpr.collectives] pin holds for the real engine — the
        gate-as-test pattern (a budget drifting from the code fails
        here, not in a postponed CI surprise)."""
        from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

        engine, _ = fleet
        cfg = load_budgets().get("jaxpr", {}).get("collectives", {})
        assert cfg, "[jaxpr.collectives] missing from lint_budgets.toml"
        violations = check_collective_budget(
            engine.collective_certificate, cfg)
        assert violations == []

    def test_injected_second_family_refuted_by_budget(
            self, eight_devices, monkeypatch):
        """Mutation test (the static analogue of PR 3's source-surgery
        test): a second all-reduce family slipped into the consensus
        update must fail the [jaxpr.collectives] check with the
        offending equations named by source."""
        from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

        real = admm_ops.consensus_update

        def sabotaged(locals_, state, active=None, axis_name=None):
            new_state, res = real(locals_, state, active=active,
                                  axis_name=axis_name)
            # the regression: an extra all-reduce smuggled into the
            # round (folded into the residual so it cannot be DCE'd)
            extra = lax.psum(jnp.sum(locals_ ** 3), axis_name)
            return new_state, res._replace(
                primal=res.primal + 0.0 * extra)

        monkeypatch.setattr(admm_ops, "consensus_update", sabotaged)
        mesh = fleet_mesh(devices=eight_devices)
        engine, _ = _tracker_fleet(8, mesh)
        cert = engine.collective_certificate
        assert cert.proved          # uniform control flow — the hazard
        # here is the SCHEDULE drift, which the budget pin catches:
        cfg = load_budgets().get("jaxpr", {}).get("collectives", {})
        violations = check_collective_budget(cert, cfg)
        assert violations, "the injected psum family went unnoticed"
        msg = " ".join(violations)
        assert "psum family" in msg
        # ... naming the offending eqn: the injected psum's source is
        # THIS file (every family member is listed, the mutation among
        # them)
        assert "test_jaxpr_collectives" in msg

    def test_dropped_axis_name_refutes_engine_build(
            self, eight_devices, monkeypatch, caplog):
        """The engine-level missing-axis_name case: a consensus mean
        computed shard-locally (axis_name dropped) flows into a
        replicated out-spec — each shard would carry a DIFFERENT
        'consensus'. Single-host the build warns loudly and proceeds
        (the watchdog still bounds it); collective_certify='require'
        refuses outright — the policy a pod launch script should set."""
        import logging

        real = admm_ops._masked_mean

        def dropped(locals_, active, axis_name=None):
            return real(locals_, active, None)   # the regression

        monkeypatch.setattr(admm_ops, "_masked_mean", dropped)
        mesh = fleet_mesh(devices=eight_devices)
        # ONE transcription for both builds: the second hits the
        # certificate memo (same structural key), so the require-policy
        # check never pays a second trace
        ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        group = AgentGroup(name="fleet", ocp=ocp, n_agents=8,
                           couplings={"shared_u": "u"},
                           solver_options=SOLVER, qp_fast_path="off")
        with caplog.at_level(logging.WARNING,
                             logger="agentlib_mpc_tpu.parallel.fused_admm"):
            engine = FusedADMM([group], OPTS, mesh=mesh)
        cert = engine.collective_certificate
        assert cert.status == "refuted"
        assert any("shard-varying" in r for r in cert.refutations)
        assert engine.collective_schedule_digest is None
        assert any("REFUTED" in rec.message for rec in caplog.records)
        with pytest.raises(ValueError, match="REFUTED"):
            FusedADMM([group], OPTS, mesh=mesh,
                      collective_certify="require")

    def test_degraded_rebuild_schedule_identity_and_drift_refusal(
            self, eight_devices, monkeypatch):
        """The ISSUE acceptance row, both directions on ONE supervisor
        (engine builds dominate; a second supervisor would double the
        cost for no coverage): (a) the FleetSupervisor's degraded
        rebuild certifies the IDENTICAL schedule (modulo mesh size) as
        the full engine; (b) a rebuild that WOULD issue a different
        all-reduce sequence — consensus update sabotaged between the
        full build and a further degrade — is refused statically,
        before any round dispatches."""
        ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        group = AgentGroup(name="fleet", ocp=ocp, n_agents=8,
                           couplings={"shared_u": "u"},
                           solver_options=SOLVER, qp_fast_path="off")
        sup = FleetSupervisor(
            [group], OPTS, mesh=fleet_mesh(devices=eight_devices),
            watchdog_timeout_s=60.0)
        full_digest = sup.engine.collective_schedule_digest
        assert full_digest is not None
        sup.force_degrade([eight_devices[-1].id])
        degraded = sup.engine
        assert degraded is not sup._layouts[sup._full_ids].engine
        # _layout_for would have raised on a mismatch; the degraded
        # engine re-certified and agrees modulo mesh size
        assert degraded.collective_schedule_digest == full_digest
        assert sup.stats()["collective_schedule_digest"] == full_digest

        # (b) sabotage AFTER the engines above built: the next
        # degraded sibling traces an extra psum — schedule drift
        # between peers, exactly what a pod cannot survive
        real = admm_ops.consensus_update

        def drifted(locals_, state, active=None, axis_name=None):
            new_state, res = real(locals_, state, active=active,
                                  axis_name=axis_name)
            extra = lax.psum(jnp.sum(locals_ ** 3), axis_name)
            return new_state, res._replace(
                primal=res.primal + 0.0 * extra)

        monkeypatch.setattr(admm_ops, "consensus_update", drifted)
        with pytest.raises(RuntimeError, match="DIFFERENT collective"):
            sup.force_degrade([eight_devices[-2].id])


class TestScheduleStamps:
    """The digest rides the engine-store manifest and the plane
    checkpoint, and both restore paths verify it (the ISSUE acceptance
    row's carry/verify half). Export/revival mechanics are stubbed —
    they have their own coverage in test_serving_survivability; what
    is under test here is the digest plumbing."""

    @pytest.fixture(scope="class")
    def mesh_plane(self, eight_devices):
        from agentlib_mpc_tpu.lint.retrace_budget import (
            tracker_tenant_spec,
        )
        from agentlib_mpc_tpu.serving import ServingPlane

        mesh = fleet_mesh(devices=eight_devices)
        ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        plane = ServingPlane(admm_options=OPTS, mesh=mesh,
                             warm_on_build=False)
        spec = tracker_tenant_spec(ocp, "t0", 1.0)
        plane.join(spec)
        return plane, ocp

    def test_checkpoint_carries_and_verifies_digest(
            self, mesh_plane, tmp_path):
        import json

        from agentlib_mpc_tpu.lint.retrace_budget import (
            tracker_tenant_spec,
        )
        from agentlib_mpc_tpu.serving import ServingPlane
        from agentlib_mpc_tpu.serving.checkpoint import (
            restore_plane,
            save_plane,
        )

        plane, ocp = mesh_plane
        bucket = next(iter(plane._buckets.values()))
        digest = bucket.engine.collective_schedule_digest
        assert digest is not None
        path = str(tmp_path / "ckpt")
        save_plane(plane, path)
        with open(f"{path}/manifest.json") as fh:
            manifest = json.load(fh)
        assert manifest["buckets"][0]["collective_digest"] == digest

        # clean restore: rebuilt engine certifies the same schedule
        # (the saver's CompileCache is shared, so both restores are
        # cache hits — the digest check, not the build, is under test)
        spec = tracker_tenant_spec(ocp, "t0", 1.0)
        fresh = ServingPlane(admm_options=OPTS, mesh=plane.mesh,
                             warm_on_build=False, cache=plane.cache)
        report = restore_plane(fresh, path, [spec])
        assert report.tenants == ("t0",)

        # drifted stamp: the restore must refuse BEFORE splicing state
        manifest["buckets"][0]["collective_digest"] = "deadbeef0000"
        with open(f"{path}/manifest.json", "w") as fh:
            json.dump(manifest, fh)
        fresh2 = ServingPlane(admm_options=OPTS, mesh=plane.mesh,
                              warm_on_build=False, cache=plane.cache)
        with pytest.raises(ValueError, match="collective schedule"):
            restore_plane(fresh2, path, [spec])

    def test_engine_store_meta_carries_digest_and_revival_trusts_it(
            self, mesh_plane, tmp_path, monkeypatch):
        import json

        from agentlib_mpc_tpu.lint.retrace_budget import (
            tracker_tenant_spec,
        )
        from agentlib_mpc_tpu.parallel import export as export_mod
        from agentlib_mpc_tpu.serving import ServingPlane
        from agentlib_mpc_tpu.serving.cache import CompileCache

        plane, ocp = mesh_plane
        digest = next(iter(
            plane._buckets.values())).engine.collective_schedule_digest
        # stub the expensive export/prewarm/install mechanics: the
        # digest plumbing around them is what this test pins
        monkeypatch.setattr(export_mod, "export_fused_step",
                            lambda *a, **k: b"blob")
        monkeypatch.setattr(export_mod, "prewarm_exported",
                            lambda *a, **k: None)
        monkeypatch.setattr(export_mod, "install_exported_step",
                            lambda engine, blob, warm_args=None: None)
        # ... and the pre-export warmup step (a real compile this
        # plumbing test has no use for)
        monkeypatch.setattr(
            FusedADMM, "step",
            lambda self, state, thetas, active=None: (state, (), None))

        spec = tracker_tenant_spec(ocp, "t0", 1.0)
        store_root = str(tmp_path / "estore")
        saver = ServingPlane(admm_options=OPTS, mesh=plane.mesh,
                             warm_on_build=False,
                             engine_store=store_root)
        saver.join(spec)
        metas = [p for p in (tmp_path / "estore").iterdir()
                 if p.suffix == ".json"]
        assert len(metas) == 1
        meta = json.loads(metas[0].read_text())
        assert meta["collective_digest"] == digest

        # a FRESH process (empty CompileCache) revives: certification
        # is skipped (trace-free restore) and the engine carries the
        # artifact's recorded digest
        reviver = ServingPlane(admm_options=OPTS, mesh=plane.mesh,
                               warm_on_build=False,
                               engine_store=store_root,
                               cache=CompileCache())
        receipt = reviver.join(tracker_tenant_spec(ocp, "t1", 2.0))
        assert not receipt.engine_cached
        assert reviver.cache.persistent_restores == 1
        engine = next(iter(reviver._buckets.values())).engine
        assert engine.collective_certificate is None
        assert engine.collective_schedule_digest == digest
