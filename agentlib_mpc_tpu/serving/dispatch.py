"""Donated, pipelined dispatch: overlap control-plane work with compute.

JAX dispatch is asynchronous: ``engine.step`` returns device futures
long before the round finishes executing. The synchronous serving loop
wastes that — it materializes round k's ``u0`` rows (blocking
device→host transfer + Python result decoding + guard assessment)
before enqueuing round k+1, so the device idles through all of the
control-plane work.

:class:`PipelinedDispatcher` runs depth-1 software pipelining per
bucket: round k+1 is ENQUEUED first, then round k's results are
materialized while k+1 executes. Combined with the engine's donated
``FusedState`` carry (the previous state is dead the moment the next
round is enqueued, so XLA reuses its buffers instead of holding two
full copies), the per-round overhead seen by the caller drops to the
result decode alone — ``bench.py --serve`` A/Bs this against the
synchronous loop.

The price is one round of result latency: ``dispatch()`` returns the
PREVIOUS round's results. An MPC control loop absorbs this naturally
when the round period exceeds the compute time; latency-critical
tenants can run a sync plane instead (``ServingPlane(pipelined=False)``).
"""

from __future__ import annotations


class PipelinedDispatcher:
    """Per-bucket depth-1 pipeline over
    :class:`~agentlib_mpc_tpu.serving.slots.SlotPlane` rounds."""

    def __init__(self, pipelined: bool = True):
        self.pipelined = bool(pipelined)
        self._inflight: dict = {}

    def dispatch(self, key, slot_plane) -> "dict | None":
        """Enqueue one round for ``slot_plane``. Synchronous mode
        returns this round's decoded results; pipelined mode returns the
        previous round's (None on the bucket's first round)."""
        if not self.pipelined:
            return slot_plane.materialize(slot_plane.launch_round())
        handle = slot_plane.launch_round()       # k+1 in flight ...
        prev = self._inflight.get(key)
        self._inflight[key] = (slot_plane, handle)
        if prev is None:
            return None
        prev_plane, prev_handle = prev
        return prev_plane.materialize(prev_handle)   # ... while k reads back

    def flush(self, key=None) -> dict:
        """Materialize in-flight rounds (one bucket, or all): the
        drain-the-pipeline call for shutdown and for callers that need
        results-to-date. Returns ``{key: results}``."""
        keys = [key] if key is not None else list(self._inflight)
        out = {}
        for k in keys:
            entry = self._inflight.pop(k, None)
            if entry is not None:
                plane, handle = entry
                out[k] = plane.materialize(handle)
        return out
