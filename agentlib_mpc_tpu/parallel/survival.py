"""Elastic degraded-mesh execution: the fused fleet survives shard loss.

PR 9 moved the fused ADMM fleet onto a ``shard_map`` device mesh; that
made ONE sick or hung shard a fleet-wide outage — the ``lax.psum``
consensus collective blocks every agent behind the dead participant.
:class:`FleetSupervisor` is the recovery ladder above the engine,
mirroring the PR 8 serving-health ladder at DEVICE granularity:

1. **Detect** — every round runs under the engine's collective
   watchdog (``FusedADMM(watchdog_timeout_s=...)``). A blown budget
   condemns the mesh and surfaces a
   :class:`~agentlib_mpc_tpu.parallel.multihost.MeshRoundTimeout`
   carrying the bounded per-device probe.
2. **Degrade** — the supervisor re-probes through its own (chaos-
   injectable) seam, marks the dead shards' lanes, and rebuilds the
   fleet on the surviving-device mesh through the existing pad path:
   the warm ``FusedState``/theta/masks carry over shard-aligned
   (:meth:`FusedADMM.pad_state_rows` + ``shard_args`` placement), dead
   lanes are masked out (their last-known iterates ride as padding —
   dead weight, never wrong answers), and the carried consensus leaves
   are asserted BITWISE against the pre-failure iterate before any
   degraded round runs. The qp routing and derivative plans recorded by
   the full-mesh engine are forced onto the rebuild
   (:meth:`FusedADMM.routed_groups`), so a degrade never re-certifies
   LQ/stage structure — but its **collective schedule** IS re-certified
   and asserted identical (modulo mesh size) to the full engine's
   (:mod:`agentlib_mpc_tpu.lint.jaxpr.collectives`): a rebuild that
   would issue a different all-reduce sequence than the surviving
   peers is refused statically, before it can hang a pod.
3. **Serve degraded** — the round that timed out is RETRIED from the
   pre-failure state on the degraded mesh (which is why the supervisor
   rejects donated engines); surviving agents keep actuating.
4. **Re-admit** — after ``readmit_after`` consecutive healthy degraded
   rounds the supervisor probes the FULL mesh; when every device
   answers it reshards back: state sliced back to the base layout, the
   lost lanes re-spliced with FRESH warm starts (the recycled-slot
   contract — a lane that died mid-iterate must not resume from it),
   and the cached full-mesh engine reinstated (zero new compiles).
   Re-admission opens a **probation** window: a timeout inside it
   re-degrades immediately AND doubles the healthy-round requirement
   (hysteresis — a flapping device must prove itself, one lucky round
   must not bounce the fleet back onto it).

Engines are cached per surviving-device set, so a repeat degrade to the
same topology — and every re-admission — is executable reuse, never a
recompile (the ``[mesh.survive]`` retrace budget pins this: zero
traces/compiles beyond the one legitimate degraded-mesh rebuild).

The supervisor's API is layout-stable: :meth:`step` takes and returns
state/thetas/trajectories in the BASE (caller) layout regardless of the
mesh currently serving — padding and slicing are internal, so the
control loop upstairs never sees the degradation except through
``stats``/telemetry (``mesh_devices_active``, ``mesh_degrade_total``,
``mesh_readmit_total``, ``mesh_shard_loss_recovery_seconds``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.parallel import multihost
from agentlib_mpc_tpu.parallel.fused_admm import (
    FusedADMM,
    FusedADMMOptions,
)
from agentlib_mpc_tpu.parallel.multihost import MeshRoundTimeout

logger = logging.getLogger(__name__)

#: transient (all-shards-answer) retries per round before the
#: supervisor concludes the mesh is lying and escalates
MAX_TRANSIENT_RETRIES = 2


class _Layout(NamedTuple):
    """One mesh configuration's serving machinery."""

    device_ids: tuple        # surviving device ids, full-mesh order
    mesh: object             # the (possibly degraded) 1-D mesh
    engine: FusedADMM
    pads: dict               # group index -> rows added over BASE


class FleetSupervisor:
    """Run a fused fleet with shard-loss survival (module docstring).

    ``groups``/``options``/``active`` are the base fleet exactly as
    :class:`FusedADMM` takes them; ``mesh`` defaults to
    :func:`~agentlib_mpc_tpu.parallel.multihost.fleet_mesh`. Group
    sizes need NOT divide any mesh — every layout pads through
    :meth:`FusedADMM.pad_state_rows` (masked dead lanes).
    """

    def __init__(self, groups, options: FusedADMMOptions = FusedADMMOptions(),
                 mesh=None, active=None,
                 watchdog_timeout_s: float = 30.0,
                 probe_timeout_s: float = multihost.MESH_PROBE_TIMEOUT_S,
                 readmit_after: int = 2,
                 probation_rounds: int = 2,
                 warmup_budget_s: float = 600.0):
        self.full_mesh = multihost.fleet_mesh() if mesh is None else mesh
        self.options = options
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        #: extra watchdog allowance for a layout's FIRST round: a fresh
        #: (full or degraded) engine's trace+compile rides inside that
        #: round's bounded wait, and must not read as a collective
        #: stall — the steady-state budget applies from round two
        self.warmup_budget_s = float(warmup_budget_s)
        self.readmit_after = max(1, int(readmit_after))
        self.probation_rounds = max(0, int(probation_rounds))
        self.base_groups = tuple(groups)
        if active is None:
            active = [jnp.ones((g.n_agents,), bool)
                      for g in self.base_groups]
        self.base_active = tuple(jnp.asarray(a, bool) for a in active)
        #: chaos-injectable probe seam (the device-loss injector wraps
        #: this to keep a "dead" virtual device from answering)
        self._probe = lambda m: multihost.probe_mesh_devices(
            m, self.probe_timeout_s)
        self._layouts: dict = {}
        self._full_ids = tuple(d.id for d in self.full_mesh.devices.flat)
        #: base-layout lanes lost to dead shards, one bool array/group
        self.dead_lanes = tuple(
            np.zeros((g.n_agents,), bool) for g in self.base_groups)
        self.dead_devices: tuple = ()
        self._current = self._layout_for(self._full_ids)
        #: participation/structure reference (group layout identical in
        #: every padded variant)
        self._ref = self._current.engine
        # survivability bookkeeping
        self.degraded = False
        self._healthy_degraded_rounds = 0
        self._readmit_needed = self.readmit_after
        self._probation_left = 0
        self._reset_lanes_pending = False
        self.rounds = 0
        self.degraded_rounds = 0
        self.last_mttr_s: "float | None" = None
        self._consensus_snapshot = None
        self._verify_carry = False
        self._export_gauges()

    # -- layouts --------------------------------------------------------------

    def _layout_for(self, device_ids) -> _Layout:
        key = tuple(device_ids)
        layout = self._layouts.get(key)
        if layout is not None:
            return layout
        mesh = multihost.surviving_mesh(self.full_mesh, key)
        n_dev = len(key)
        pads = {gi: (-g.n_agents) % n_dev
                for gi, g in enumerate(self.base_groups)}
        if not self._layouts:
            groups = self.base_groups          # first build certifies
        else:
            # siblings inherit the full engine's resolved routing and
            # attached plans — a degrade must never re-certify
            groups = self._ref.routed_groups()
        groups = tuple(
            dataclasses.replace(g, n_agents=self.base_groups[gi].n_agents
                                + pads[gi])
            for gi, g in enumerate(groups))
        engine = FusedADMM(groups, self.options, mesh=mesh,
                           watchdog_timeout_s=self.watchdog_timeout_s)
        if self._layouts:
            # static schedule-identity gate (ISSUE 11): a degraded
            # rebuild that would issue a DIFFERENT collective sequence
            # than its surviving full-mesh peers is exactly the
            # cross-host hang a pod cannot observe — refuse it here,
            # before any round dispatches, not after a watchdog fires
            ref_digest = self._ref.collective_schedule_digest
            new_digest = engine.collective_schedule_digest
            if ref_digest is not None and new_digest is not None \
                    and new_digest != ref_digest:
                raise RuntimeError(
                    f"degraded-mesh rebuild on {len(key)} device(s) "
                    f"certifies a DIFFERENT collective schedule than "
                    f"the full engine (digest {new_digest} vs "
                    f"{ref_digest}) — its all-reduce sequence would "
                    f"diverge from the surviving peers'; refusing the "
                    f"rebuild (full schedule: "
                    f"{self._ref.collective_certificate.describe()}; "
                    f"rebuilt: {engine.collective_certificate.describe()})")
            if ref_digest is not None and new_digest is None:
                logger.warning(
                    "degraded-mesh rebuild carries no proved collective "
                    "schedule (%s) — identity vs the full engine cannot "
                    "be asserted statically",
                    engine.collective_certificate.describe()
                    if engine.collective_certificate else "not certified")
        layout = _Layout(device_ids=key, mesh=mesh, engine=engine,
                         pads=pads)
        self._layouts[key] = layout
        return layout

    @property
    def engine(self) -> FusedADMM:
        """The engine currently serving (full or degraded mesh)."""
        return self._current.engine

    @property
    def mesh_devices(self) -> int:
        return len(self._current.device_ids)

    # -- layout-stable state plumbing -----------------------------------------

    def init_state(self, theta_batches):
        """Fresh fleet state in the BASE layout. The full engine's lane
        count may exceed the base group sizes (non-divisible groups pad
        to the mesh), so the template is built at full-layout width and
        sliced back — a mixed-width state (theta-derived leaves at base
        width, zero-filled leaves at engine width) must never exist."""
        full = self._layouts[self._full_ids]
        _none, padded = self._ref.pad_state_rows(
            full.pads, None, tuple(theta_batches))
        state = full.engine.init_state(padded)
        if not any(full.pads.values()):
            return state
        return self._slice_state(state)

    def shift_state(self, state):
        return self._ref.shift_state(state)

    def _layout_masks(self, layout: _Layout, base_masks) -> tuple:
        out = []
        for gi, mask in enumerate(base_masks):
            alive = jnp.asarray(mask, bool) & jnp.asarray(
                ~self.dead_lanes[gi])
            if layout.pads.get(gi):
                alive = jnp.concatenate(
                    [alive, jnp.zeros((layout.pads[gi],), bool)])
            out.append(alive)
        return tuple(out)

    def _slice_state(self, state):
        """State back to the base layout: drop each group's padding
        rows."""
        counts = {gi: g.n_agents for gi, g in enumerate(self.base_groups)}

        def sl(leaf, gi):
            return leaf[:counts[gi]]

        lam = {a: tuple(
            sl(piece, gi) for (gi, _c, _s), piece in zip(
                self._ref._group_participations(a, "consensus"), pieces))
            for a, pieces in state.lam.items()}
        ex_diff = {a: tuple(
            sl(piece, gi) for (gi, _c, _s), piece in zip(
                self._ref._group_participations(a, "exchange"), pieces))
            for a, pieces in state.ex_diff.items()}
        return state._replace(
            w=tuple(sl(state.w[gi], gi) for gi in counts),
            y=tuple(sl(state.y[gi], gi) for gi in counts),
            z=tuple(sl(state.z[gi], gi) for gi in counts),
            lam=lam, ex_diff=ex_diff)

    def _slice_rows(self, state, trajs, stats):
        """Round outputs back to the base layout."""
        counts = {gi: g.n_agents for gi, g in enumerate(self.base_groups)}

        def sl(leaf, gi):
            return leaf[:counts[gi]]

        state = self._slice_state(state)
        trajs = tuple(
            jax.tree.map(lambda leaf, gi=gi: sl(leaf, gi), trajs[gi])
            for gi in counts)
        if stats.lane_quarantined is not None:
            stats = stats._replace(lane_quarantined=tuple(
                sl(stats.lane_quarantined[gi], gi) for gi in counts))
        return state, trajs, stats

    def _consensus_host(self, state) -> dict:
        out = {}
        for kind in ("zbar", "ex_mean", "ex_lam", "rho"):
            for alias, leaf in getattr(state, kind).items():
                out[(kind, alias)] = np.asarray(leaf)
        return out

    def _recenter_consensus_multipliers(self, state, masks):
        """Restore the sum-of-active-multipliers = 0 invariant.

        The consensus dual update CONSERVES the active multiplier sum
        (``zbar`` is the masked mean, so the per-round increments cancel
        across active lanes) — which means any change to the active set
        leaves a stale sum behind: masking lanes out strands their share
        of the balance with the survivors, and re-admitting a lane with
        a zeroed multiplier removes its share outright. Either way the
        fleet converges — confidently, with tiny residuals — to a
        consensus biased by exactly ``mean_active(lam)/rho``, forever
        (observed: a 6-tracker fleet re-admitting one lane settled
        1/(n·rho) off the true mean and called it converged).
        Re-centering at every membership transition keeps the degraded
        AND the recovered equilibrium unbiased."""
        lam = {a: list(p) for a, p in state.lam.items()}
        for a, pieces in lam.items():
            parts = self._ref._group_participations(a, "consensus")
            tot = 0.0
            cnt = 0.0
            for slot, (gj, _c, _s) in enumerate(parts):
                m = jnp.asarray(masks[gj], bool)
                tot = tot + jnp.sum(
                    jnp.where(m[:, None], pieces[slot], 0.0), axis=0)
                cnt = cnt + jnp.sum(m)
            mean = tot / jnp.maximum(cnt, 1)
            for slot, (gj, _c, _s) in enumerate(parts):
                m = jnp.asarray(masks[gj], bool)
                pieces[slot] = jnp.where(
                    m[:, None], pieces[slot] - mean[None, :],
                    pieces[slot])
        return state._replace(lam={a: tuple(p) for a, p in lam.items()})

    def _reset_dead_lane_starts(self, state, theta_batches):
        """Fresh warm starts for the lanes a dead shard carried — the
        recycled-slot contract at device granularity: a lane that died
        mid-iterate re-enters on the (sanitized) OCP initial guess and
        zeroed multipliers, never its stale pre-failure iterate."""
        w, y, z = list(state.w), list(state.y), list(state.z)
        lam = {a: list(p) for a, p in state.lam.items()}
        ex_diff = {a: list(p) for a, p in state.ex_diff.items()}
        for gi, g in enumerate(self.base_groups):
            dead = jnp.asarray(self.dead_lanes[gi])
            if not bool(np.any(self.dead_lanes[gi])):
                continue
            w_init = jax.vmap(g.ocp.initial_guess)(theta_batches[gi])
            w_init = jnp.where(jnp.isfinite(w_init), w_init, 0.0)
            w[gi] = jnp.where(dead[:, None], w_init, w[gi])
            y[gi] = jnp.where(dead[:, None], 0.0, y[gi])
            z[gi] = jnp.where(dead[:, None], 0.1, z[gi])
            for a, pieces in lam.items():
                for slot, (gj, _c, _s) in enumerate(
                        self._ref._group_participations(a, "consensus")):
                    if gj == gi:
                        pieces[slot] = jnp.where(dead[:, None], 0.0,
                                                 pieces[slot])
            for a, pieces in ex_diff.items():
                for slot, (gj, _c, _s) in enumerate(
                        self._ref._group_participations(a, "exchange")):
                    if gj == gi:
                        pieces[slot] = jnp.where(dead[:, None], 0.0,
                                                 pieces[slot])
        return state._replace(
            w=tuple(w), y=tuple(y), z=tuple(z),
            lam={a: tuple(p) for a, p in lam.items()},
            ex_diff={a: tuple(p) for a, p in ex_diff.items()})

    # -- the survivable round -------------------------------------------------

    def step(self, state, theta_batches: Sequence, active=None):
        """One fused round in the BASE layout, surviving shard loss.

        Same signature and return contract as :meth:`FusedADMM.step`;
        on a collective timeout the round is retried on the degraded
        mesh from this very ``state`` (the pre-failure iterate), so the
        caller's loop never sees the failure — only the stats and the
        telemetry do."""
        base_masks = (self.base_active if active is None
                      else tuple(jnp.asarray(a, bool) for a in active))
        theta_batches = tuple(theta_batches)
        self._maybe_readmit()
        if self._reset_lanes_pending:
            state = self._reset_dead_lane_starts(state, theta_batches)
            self.dead_lanes = tuple(
                np.zeros((g.n_agents,), bool) for g in self.base_groups)
            self._reset_lanes_pending = False
            # the zeroed multipliers changed the active sum the dual
            # update conserves — re-center or the recovered fleet
            # settles mean(lam)/rho off the true consensus, forever
            state = self._recenter_consensus_multipliers(state,
                                                         base_masks)
        # the pre-failure iterate's consensus fingerprint: what a
        # degraded-mesh carry-over must reproduce bitwise
        self._consensus_snapshot = self._consensus_host(state)
        transient = 0
        t_detect = None
        while True:
            layout = self._current
            try:
                out = self._run_layout(layout, state, theta_batches,
                                       base_masks)
                break
            except MeshRoundTimeout:
                if t_detect is None:
                    t_detect = time.perf_counter()
                report = self._probe(layout.mesh)
                if not report.answered:
                    raise RuntimeError(
                        "no mesh device answered the post-condemnation "
                        "probe — the whole mesh is unreachable; escalate "
                        "to checkpoint restore "
                        "(docs/robustness.md, 'Surviving shard loss')"
                    ) from None
                if report.dead:
                    self._degrade(report)
                    continue
                transient += 1
                if telemetry.enabled():
                    telemetry.counter(
                        "mesh_round_retries_total",
                        "condemned rounds retried on the same mesh "
                        "(every shard answered the probe)").inc(
                        reason="transient")
                if transient > MAX_TRANSIENT_RETRIES:
                    raise RuntimeError(
                        f"fused round timed out {transient} times while "
                        f"every shard answers the probe — the collective "
                        f"is wedged without an attributable dead device; "
                        f"raise watchdog_timeout_s or escalate to "
                        f"checkpoint restore") from None
                logger.warning(
                    "condemned round retried on the same %d-device mesh "
                    "(all shards answered the probe; attempt %d/%d)",
                    len(layout.device_ids), transient,
                    MAX_TRANSIENT_RETRIES)
                layout.engine.mesh_condemned = False
        if t_detect is not None:
            self.last_mttr_s = time.perf_counter() - t_detect
            if telemetry.enabled():
                telemetry.histogram(
                    "mesh_shard_loss_recovery_seconds",
                    "wall seconds from a condemned collective to the "
                    "first completed (possibly degraded) round"
                    ).observe(self.last_mttr_s)
        self.rounds += 1
        if self.degraded:
            self.degraded_rounds += 1
            self._healthy_degraded_rounds += 1
        if self._probation_left > 0:
            self._probation_left -= 1
            if self._probation_left == 0:
                # probation served: the full mesh proved itself
                self._readmit_needed = self.readmit_after
        state_out, trajs, stats = out
        self._consensus_snapshot = self._consensus_host(state_out)
        return state_out, trajs, stats

    def _run_layout(self, layout: _Layout, state, theta_batches,
                    base_masks):
        if any(layout.pads.values()):
            state, theta_batches = self._ref.pad_state_rows(
                layout.pads, state, theta_batches)
        # placement on the layout's mesh (shard_args with pre-padded
        # inputs is pure placement: pads resolve to zero)
        state, theta_batches = layout.engine.shard_args(
            layout.mesh, state, theta_batches)
        if self._verify_carry:
            # the degraded carry-over must reproduce the pre-failure
            # consensus iterate BITWISE after pad + placement — a carry
            # that cannot is corrupted and must not resume
            carried = self._consensus_host(state)
            for key, ref in (self._consensus_snapshot or {}).items():
                if not np.array_equal(carried[key], ref):
                    kind, alias = key
                    raise RuntimeError(
                        f"degraded-mesh carry-over drifted from the "
                        f"pre-failure iterate at {kind}[{alias}] — "
                        f"refusing to resume from a corrupted carry")
            self._verify_carry = False
            # the dead lanes just left the active set, stranding their
            # share of the conserved multiplier sum with the survivors
            # — re-center so the DEGRADED equilibrium is the survivors'
            # true consensus, not a biased one
            state = self._recenter_consensus_multipliers(
                state, self._layout_masks(layout, base_masks))
        masks = self._layout_masks(layout, base_masks)
        engine = layout.engine
        if not getattr(engine, "_supervisor_warmed", False):
            # first round of a fresh layout: trace+compile rides inside
            # the bounded wait — give it the warmup allowance so a
            # legitimate compile never reads as a collective stall
            budget = engine.watchdog_timeout_s
            engine.watchdog_timeout_s = budget + self.warmup_budget_s
            try:
                out = engine.step(state, theta_batches, active=masks)
            finally:
                engine.watchdog_timeout_s = budget
            engine._supervisor_warmed = True
        else:
            out = engine.step(state, theta_batches, active=masks)
        return self._slice_rows(*out)

    # -- degrade / re-admit ---------------------------------------------------

    def _mark_dead_lanes(self, dead_ids) -> None:
        """Base-layout lanes hosted by the dead shards, derived from
        the CURRENT layout's contiguous row assignment — on a cascading
        loss the failure happens on an already-degraded mesh whose
        rows-per-device and device positions differ from the full
        layout's, and the lanes to mask are the ones the dying shard
        actually hosted there (padding rows it hosted mask nothing)."""
        layout = self._current
        n_dev = len(layout.device_ids)
        positions = [i for i, did in enumerate(layout.device_ids)
                     if did in set(dead_ids)]
        for gi, g in enumerate(self.base_groups):
            n_rows = g.n_agents + layout.pads.get(gi, 0)
            rpd = n_rows // n_dev
            for p in positions:
                lo, hi = p * rpd, (p + 1) * rpd
                self.dead_lanes[gi][lo:min(hi, g.n_agents)] = True

    def _degrade(self, report) -> None:
        """Shard loss: rebuild on the surviving mesh, carry the warm
        state over shard-aligned, mask the dead lanes."""
        dead = tuple(report.dead)
        alive = tuple(did for did in self._current.device_ids
                      if did not in set(dead))
        if not alive:
            raise RuntimeError("every device of the current mesh is "
                               "dead — escalate to checkpoint restore")
        self._mark_dead_lanes(dead)
        self.dead_devices = tuple(dict.fromkeys(
            (*self.dead_devices, *dead)))
        # consensus identity against the pre-failure iterate: the
        # replicated leaves are host-snapshotted at round start; a
        # carry that cannot reproduce them bitwise must not resume
        snap = self._consensus_snapshot
        if snap is not None:
            for (kind, alias), ref in snap.items():
                if not np.all(np.isfinite(ref)):
                    raise RuntimeError(
                        f"pre-failure consensus iterate {kind}[{alias}] "
                        f"is non-finite — refusing to carry a corrupted "
                        f"state onto the degraded mesh")
        was = len(self._current.device_ids)
        t0 = time.perf_counter()
        self._current = self._layout_for(alive)
        build_s = time.perf_counter() - t0
        self.degraded = True
        self._verify_carry = True
        self._healthy_degraded_rounds = 0
        if self._probation_left > 0:
            # relapse during probation: hysteresis — the next
            # re-admission needs twice the proof
            self._readmit_needed = max(
                self._readmit_needed * 2, self.readmit_after)
            self._probation_left = 0
        if telemetry.enabled():
            telemetry.counter(
                "mesh_degrade_total",
                "degraded-mesh fallbacks (shard loss absorbed)").inc()
        self._export_gauges()
        logger.warning(
            "fleet degraded %d -> %d devices (dead: %s; engine %s in "
            "%.2fs); %d lane(s) masked until re-admission",
            was, len(alive), list(dead),
            "reused" if build_s < 0.05 else "built", build_s,
            int(sum(int(d.sum()) for d in self.dead_lanes)))

    def _maybe_readmit(self) -> None:
        if not self.degraded:
            return
        if self._healthy_degraded_rounds < self._readmit_needed:
            return
        report = self._probe(self.full_mesh)
        if not report.all_answered:
            # restart the hysteresis clock: probing a still-dead device
            # costs the probe deadline AND leaks one wedged probe
            # thread per dead device on real hardware (the block is
            # uncancellable) — once per readmit window is the bounded
            # rate, once per round would not be
            self._healthy_degraded_rounds = 0
            logger.info(
                "re-admission probe: %d device(s) still dead (%s) — "
                "staying on the degraded mesh; next probe after %d "
                "more healthy rounds", len(report.dead),
                list(report.dead), self._readmit_needed)
            return
        full = self._layouts[self._full_ids]
        full.engine.mesh_condemned = False
        self._current = full
        self.degraded = False
        self._healthy_degraded_rounds = 0
        self._reset_lanes_pending = True
        self._probation_left = self.probation_rounds
        self.dead_devices = ()
        if telemetry.enabled():
            telemetry.counter(
                "mesh_readmit_total",
                "full-mesh re-admissions after degraded service").inc()
        self._export_gauges()
        logger.warning(
            "full %d-device mesh re-admitted on probation (%d rounds); "
            "lost lanes re-enter with fresh warm starts",
            len(self._full_ids), self.probation_rounds)

    # -- operator / gate hooks ------------------------------------------------

    def force_degrade(self, dead_device_ids) -> None:
        """Operator/gate entry: degrade as if ``dead_device_ids`` had
        failed a probe (no round needs to time out first)."""
        self._degrade(multihost.ShardProbeReport(
            answered=tuple(d for d in self._current.device_ids
                           if d not in set(dead_device_ids)),
            dead=tuple(dead_device_ids), latency_s={}))

    def force_readmit(self) -> None:
        """Operator/gate entry: reshard back to the full mesh now,
        bypassing the hysteresis clock (the probe still runs via
        :meth:`_maybe_readmit` on the next step for the honest path;
        this one trusts the operator)."""
        self._healthy_degraded_rounds = self._readmit_needed
        probe, self._probe = self._probe, lambda m: \
            multihost.ShardProbeReport(
                answered=tuple(d.id for d in m.devices.flat),
                dead=(), latency_s={})
        try:
            self._maybe_readmit()
        finally:
            self._probe = probe

    def _export_gauges(self) -> None:
        if telemetry.enabled():
            telemetry.gauge(
                "mesh_devices_active",
                "devices in the mesh currently serving the fleet").set(
                float(len(self._current.device_ids)))

    def stats(self) -> dict:
        return {
            "devices_full": len(self._full_ids),
            "devices_active": len(self._current.device_ids),
            "degraded": self.degraded,
            "dead_devices": list(self.dead_devices),
            "dead_lanes": int(sum(int(d.sum()) for d in self.dead_lanes)),
            "rounds": self.rounds,
            "degraded_rounds": self.degraded_rounds,
            "layouts_built": len(self._layouts),
            "last_mttr_s": self.last_mttr_s,
            "probation_left": self._probation_left,
            "collective_schedule_digest":
                self._current.engine.collective_schedule_digest,
        }
