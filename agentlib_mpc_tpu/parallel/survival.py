"""Elastic degraded-mesh execution: the fused fleet survives shard loss.

PR 9 moved the fused ADMM fleet onto a ``shard_map`` device mesh; that
made ONE sick or hung shard a fleet-wide outage — the ``lax.psum``
consensus collective blocks every agent behind the dead participant.
:class:`FleetSupervisor` is the recovery ladder above the engine,
mirroring the PR 8 serving-health ladder at DEVICE granularity:

1. **Detect** — every round runs under the engine's collective
   watchdog (``FusedADMM(watchdog_timeout_s=...)``). A blown budget
   condemns the mesh and surfaces a
   :class:`~agentlib_mpc_tpu.parallel.multihost.MeshRoundTimeout`
   carrying the bounded per-device probe.
2. **Degrade** — the supervisor re-probes through its own (chaos-
   injectable) seam, marks the dead shards' lanes, and rebuilds the
   fleet on the surviving-device mesh through the existing pad path:
   the warm ``FusedState``/theta/masks carry over shard-aligned
   (:meth:`FusedADMM.pad_state_rows` + ``shard_args`` placement), dead
   lanes are masked out (their last-known iterates ride as padding —
   dead weight, never wrong answers), and the carried consensus leaves
   are asserted BITWISE against the pre-failure iterate before any
   degraded round runs. The qp routing and derivative plans recorded by
   the full-mesh engine are forced onto the rebuild
   (:meth:`FusedADMM.routed_groups`), so a degrade never re-certifies
   LQ/stage structure — but its **collective schedule** IS re-certified
   and asserted identical (modulo mesh size) to the full engine's
   (:mod:`agentlib_mpc_tpu.lint.jaxpr.collectives`): a rebuild that
   would issue a different all-reduce sequence than the surviving
   peers is refused statically, before it can hang a pod.
3. **Serve degraded** — the round that timed out is RETRIED from the
   pre-failure state on the degraded mesh (which is why the supervisor
   rejects donated engines); surviving agents keep actuating.
4. **Re-admit** — after ``readmit_after`` consecutive healthy degraded
   rounds the supervisor probes the FULL mesh; when every device
   answers it reshards back: state sliced back to the base layout, the
   lost lanes re-spliced with FRESH warm starts (the recycled-slot
   contract — a lane that died mid-iterate must not resume from it),
   and the cached full-mesh engine reinstated (zero new compiles).
   Re-admission opens a **probation** window: a timeout inside it
   re-degrades immediately AND doubles the healthy-round requirement
   (hysteresis — a flapping device must prove itself, one lucky round
   must not bounce the fleet back onto it).

Engines are cached per surviving-device set, so a repeat degrade to the
same topology — and every re-admission — is executable reuse, never a
recompile (the ``[mesh.survive]`` retrace budget pins this: zero
traces/compiles beyond the one legitimate degraded-mesh rebuild).

The supervisor's API is layout-stable: :meth:`step` takes and returns
state/thetas/trajectories in the BASE (caller) layout regardless of the
mesh currently serving — padding and slicing are internal, so the
control loop upstairs never sees the degradation except through
``stats``/telemetry (``mesh_devices_active``, ``mesh_degrade_total``,
``mesh_readmit_total``, ``mesh_shard_loss_recovery_seconds``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.parallel import multihost
from agentlib_mpc_tpu.parallel.fused_admm import (
    FusedADMM,
    FusedADMMOptions,
)
from agentlib_mpc_tpu.parallel.multihost import MeshRoundTimeout

logger = logging.getLogger(__name__)

#: transient (all-shards-answer) retries per round before the
#: supervisor concludes the mesh is lying and escalates
MAX_TRANSIENT_RETRIES = 2


def _quarantine_count(stats) -> int:
    """Total quarantined (lane, iteration) attributions of one round —
    the flight recorder's symptom field for a contained NaN storm (a
    storm the quarantine absorbs is invisible in every OTHER signal)."""
    for field in ("lane_quarantined", "quarantined"):
        q = getattr(stats, field, None)
        if q is None:
            continue
        try:
            if isinstance(q, (tuple, list)):
                return int(sum(int(np.asarray(g).sum()) for g in q))
            return int(np.asarray(q).sum())
        except (TypeError, ValueError):
            continue
    return 0


def assert_schedule_identity(ref_engine, new_engine, what: str) -> None:
    """The ISSUE 11 static gate both supervisors share: a degraded
    rebuild that would issue a DIFFERENT collective sequence than its
    surviving peers is exactly the cross-host hang a pod cannot
    observe — refuse it here, before any round dispatches, not after a
    watchdog fires. (The ``collective_schedule_digest`` is mesh-size-
    independent, so a smaller mesh of the same program matches.)"""
    ref_digest = ref_engine.collective_schedule_digest
    new_digest = new_engine.collective_schedule_digest
    if ref_digest is not None and new_digest is not None \
            and new_digest != ref_digest:
        # payload shapes ride the full digest, and a rebuild that
        # re-pads its lane rows legitimately changes shard-local
        # payload shapes (the 2-D fleet's non-anticipativity psum
        # carries local agent rows) — the SEQUENCE identity is what a
        # pod's peers must agree on, so fall back to the lane-count-
        # independent family digest before refusing
        ref_cert = getattr(ref_engine, "collective_certificate", None)
        new_cert = getattr(new_engine, "collective_certificate", None)
        fam_ref = ref_cert.family_digest if ref_cert is not None \
            else None
        fam_new = new_cert.family_digest if new_cert is not None \
            else None
        if fam_ref is not None and fam_ref == fam_new:
            logger.info(
                "%s re-certified with lane-count-shifted payload "
                "shapes; the all-reduce sequence is identical "
                "(family digest %s)", what, fam_ref)
            return
        telemetry.journal_event(
            "certifier.refused", kind="collective_schedule",
            what=what, collective_digest=ref_digest,
            rebuilt_digest=new_digest)
        raise RuntimeError(
            f"{what} certifies a DIFFERENT collective schedule than "
            f"the full engine (digest {new_digest} vs {ref_digest}) — "
            f"its all-reduce sequence would diverge from the surviving "
            f"peers'; refusing the rebuild (full schedule: "
            f"{ref_engine.collective_certificate.describe()}; rebuilt: "
            f"{new_engine.collective_certificate.describe()})")
    if ref_digest is not None and new_digest is None:
        logger.warning(
            "%s carries no proved collective schedule (%s) — identity "
            "vs the full engine cannot be asserted statically", what,
            new_engine.collective_certificate.describe()
            if new_engine.collective_certificate else "not certified")


class _Layout(NamedTuple):
    """One mesh configuration's serving machinery."""

    device_ids: tuple        # surviving device ids, full-mesh order
    mesh: object             # the (possibly degraded) 1-D mesh
    engine: FusedADMM
    pads: dict               # group index -> rows added over BASE


class FleetSupervisor:
    """Run a fused fleet with shard-loss survival (module docstring).

    ``groups``/``options``/``active`` are the base fleet exactly as
    :class:`FusedADMM` takes them; ``mesh`` defaults to
    :func:`~agentlib_mpc_tpu.parallel.multihost.fleet_mesh`. Group
    sizes need NOT divide any mesh — every layout pads through
    :meth:`FusedADMM.pad_state_rows` (masked dead lanes).
    """

    def __init__(self, groups, options: FusedADMMOptions = FusedADMMOptions(),
                 mesh=None, active=None,
                 watchdog_timeout_s: float = 30.0,
                 probe_timeout_s: float = multihost.MESH_PROBE_TIMEOUT_S,
                 readmit_after: int = 2,
                 probation_rounds: int = 2,
                 warmup_budget_s: float = 600.0):
        self.full_mesh = multihost.fleet_mesh() if mesh is None else mesh
        self.options = options
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        #: extra watchdog allowance for a layout's FIRST round: a fresh
        #: (full or degraded) engine's trace+compile rides inside that
        #: round's bounded wait, and must not read as a collective
        #: stall — the steady-state budget applies from round two
        self.warmup_budget_s = float(warmup_budget_s)
        self.readmit_after = max(1, int(readmit_after))
        self.probation_rounds = max(0, int(probation_rounds))
        self.base_groups = tuple(groups)
        if active is None:
            active = [jnp.ones((g.n_agents,), bool)
                      for g in self.base_groups]
        self.base_active = tuple(jnp.asarray(a, bool) for a in active)
        #: chaos-injectable probe seam (the device-loss injector wraps
        #: this to keep a "dead" virtual device from answering)
        self._probe = lambda m: multihost.probe_mesh_devices(
            m, self.probe_timeout_s)
        self._layouts: dict = {}
        self._full_ids = tuple(d.id for d in self.full_mesh.devices.flat)
        #: base-layout lanes lost to dead shards, one bool array/group
        self.dead_lanes = tuple(
            np.zeros((g.n_agents,), bool) for g in self.base_groups)
        self.dead_devices: tuple = ()
        self._current = self._layout_for(self._full_ids)
        #: participation/structure reference (group layout identical in
        #: every padded variant)
        self._ref = self._current.engine
        # survivability bookkeeping
        self.degraded = False
        self._healthy_degraded_rounds = 0
        self._readmit_needed = self.readmit_after
        self._probation_left = 0
        self._reset_lanes_pending = False
        self.rounds = 0
        self.degraded_rounds = 0
        self.last_mttr_s: "float | None" = None
        self._consensus_snapshot = None
        self._verify_carry = False
        self._export_gauges()

    # -- layouts --------------------------------------------------------------

    def _layout_for(self, device_ids) -> _Layout:
        key = tuple(device_ids)
        layout = self._layouts.get(key)
        if layout is not None:
            return layout
        mesh = multihost.surviving_mesh(self.full_mesh, key)
        n_dev = len(key)
        pads = {gi: (-g.n_agents) % n_dev
                for gi, g in enumerate(self.base_groups)}
        if not self._layouts:
            groups = self.base_groups          # first build certifies
        else:
            # siblings inherit the full engine's resolved routing and
            # attached plans — a degrade must never re-certify
            groups = self._ref.routed_groups()
        groups = tuple(
            dataclasses.replace(g, n_agents=self.base_groups[gi].n_agents
                                + pads[gi])
            for gi, g in enumerate(groups))
        engine = FusedADMM(groups, self.options, mesh=mesh,
                           watchdog_timeout_s=self.watchdog_timeout_s)
        if self._layouts:
            assert_schedule_identity(
                self._ref, engine,
                f"degraded-mesh rebuild on {len(key)} device(s)")
        layout = _Layout(device_ids=key, mesh=mesh, engine=engine,
                         pads=pads)
        self._layouts[key] = layout
        return layout

    @property
    def engine(self) -> FusedADMM:
        """The engine currently serving (full or degraded mesh)."""
        return self._current.engine

    @property
    def mesh_devices(self) -> int:
        return len(self._current.device_ids)

    # -- layout-stable state plumbing -----------------------------------------

    def init_state(self, theta_batches):
        """Fresh fleet state in the BASE layout. The full engine's lane
        count may exceed the base group sizes (non-divisible groups pad
        to the mesh), so the template is built at full-layout width and
        sliced back — a mixed-width state (theta-derived leaves at base
        width, zero-filled leaves at engine width) must never exist."""
        full = self._layouts[self._full_ids]
        _none, padded = self._ref.pad_state_rows(
            full.pads, None, tuple(theta_batches))
        state = full.engine.init_state(padded)
        if not any(full.pads.values()):
            return state
        return self._slice_state(state)

    def shift_state(self, state):
        return self._ref.shift_state(state)

    def _layout_masks(self, layout: _Layout, base_masks) -> tuple:
        out = []
        for gi, mask in enumerate(base_masks):
            alive = jnp.asarray(mask, bool) & jnp.asarray(
                ~self.dead_lanes[gi])
            if layout.pads.get(gi):
                alive = jnp.concatenate(
                    [alive, jnp.zeros((layout.pads[gi],), bool)])
            out.append(alive)
        return tuple(out)

    def _slice_state(self, state):
        """State back to the base layout: drop each group's padding
        rows."""
        counts = {gi: g.n_agents for gi, g in enumerate(self.base_groups)}

        def sl(leaf, gi):
            return leaf[:counts[gi]]

        lam = {a: tuple(
            sl(piece, gi) for (gi, _c, _s), piece in zip(
                self._ref._group_participations(a, "consensus"), pieces))
            for a, pieces in state.lam.items()}
        ex_diff = {a: tuple(
            sl(piece, gi) for (gi, _c, _s), piece in zip(
                self._ref._group_participations(a, "exchange"), pieces))
            for a, pieces in state.ex_diff.items()}
        return state._replace(
            w=tuple(sl(state.w[gi], gi) for gi in counts),
            y=tuple(sl(state.y[gi], gi) for gi in counts),
            z=tuple(sl(state.z[gi], gi) for gi in counts),
            lam=lam, ex_diff=ex_diff)

    def _slice_rows(self, state, trajs, stats):
        """Round outputs back to the base layout."""
        counts = {gi: g.n_agents for gi, g in enumerate(self.base_groups)}

        def sl(leaf, gi):
            return leaf[:counts[gi]]

        state = self._slice_state(state)
        trajs = tuple(
            jax.tree.map(lambda leaf, gi=gi: sl(leaf, gi), trajs[gi])
            for gi in counts)
        if stats.lane_quarantined is not None:
            stats = stats._replace(lane_quarantined=tuple(
                sl(stats.lane_quarantined[gi], gi) for gi in counts))
        return state, trajs, stats

    def _consensus_host(self, state) -> dict:
        out = {}
        for kind in ("zbar", "ex_mean", "ex_lam", "rho"):
            for alias, leaf in getattr(state, kind).items():
                out[(kind, alias)] = np.asarray(leaf)
        return out

    def _recenter_consensus_multipliers(self, state, masks):
        """Restore the sum-of-active-multipliers = 0 invariant.

        The consensus dual update CONSERVES the active multiplier sum
        (``zbar`` is the masked mean, so the per-round increments cancel
        across active lanes) — which means any change to the active set
        leaves a stale sum behind: masking lanes out strands their share
        of the balance with the survivors, and re-admitting a lane with
        a zeroed multiplier removes its share outright. Either way the
        fleet converges — confidently, with tiny residuals — to a
        consensus biased by exactly ``mean_active(lam)/rho``, forever
        (observed: a 6-tracker fleet re-admitting one lane settled
        1/(n·rho) off the true mean and called it converged).
        Re-centering at every membership transition keeps the degraded
        AND the recovered equilibrium unbiased."""
        lam = {a: list(p) for a, p in state.lam.items()}
        for a, pieces in lam.items():
            parts = self._ref._group_participations(a, "consensus")
            tot = 0.0
            cnt = 0.0
            for slot, (gj, _c, _s) in enumerate(parts):
                m = jnp.asarray(masks[gj], bool)
                tot = tot + jnp.sum(
                    jnp.where(m[:, None], pieces[slot], 0.0), axis=0)
                cnt = cnt + jnp.sum(m)
            mean = tot / jnp.maximum(cnt, 1)
            for slot, (gj, _c, _s) in enumerate(parts):
                m = jnp.asarray(masks[gj], bool)
                pieces[slot] = jnp.where(
                    m[:, None], pieces[slot] - mean[None, :],
                    pieces[slot])
        return state._replace(lam={a: tuple(p) for a, p in lam.items()})

    def _reset_dead_lane_starts(self, state, theta_batches):
        """Fresh warm starts for the lanes a dead shard carried — the
        recycled-slot contract at device granularity: a lane that died
        mid-iterate re-enters on the (sanitized) OCP initial guess and
        zeroed multipliers, never its stale pre-failure iterate."""
        w, y, z = list(state.w), list(state.y), list(state.z)
        lam = {a: list(p) for a, p in state.lam.items()}
        ex_diff = {a: list(p) for a, p in state.ex_diff.items()}
        for gi, g in enumerate(self.base_groups):
            dead = jnp.asarray(self.dead_lanes[gi])
            if not bool(np.any(self.dead_lanes[gi])):
                continue
            w_init = jax.vmap(g.ocp.initial_guess)(theta_batches[gi])
            w_init = jnp.where(jnp.isfinite(w_init), w_init, 0.0)
            w[gi] = jnp.where(dead[:, None], w_init, w[gi])
            y[gi] = jnp.where(dead[:, None], 0.0, y[gi])
            z[gi] = jnp.where(dead[:, None], 0.1, z[gi])
            for a, pieces in lam.items():
                for slot, (gj, _c, _s) in enumerate(
                        self._ref._group_participations(a, "consensus")):
                    if gj == gi:
                        pieces[slot] = jnp.where(dead[:, None], 0.0,
                                                 pieces[slot])
            for a, pieces in ex_diff.items():
                for slot, (gj, _c, _s) in enumerate(
                        self._ref._group_participations(a, "exchange")):
                    if gj == gi:
                        pieces[slot] = jnp.where(dead[:, None], 0.0,
                                                 pieces[slot])
        return state._replace(
            w=tuple(w), y=tuple(y), z=tuple(z),
            lam={a: tuple(p) for a, p in lam.items()},
            ex_diff={a: tuple(p) for a, p in ex_diff.items()})

    # -- the survivable round -------------------------------------------------

    def step(self, state, theta_batches: Sequence, active=None):
        """One fused round in the BASE layout, surviving shard loss.

        Same signature and return contract as :meth:`FusedADMM.step`;
        on a collective timeout the round is retried on the degraded
        mesh from this very ``state`` (the pre-failure iterate), so the
        caller's loop never sees the failure — only the stats and the
        telemetry do."""
        base_masks = (self.base_active if active is None
                      else tuple(jnp.asarray(a, bool) for a in active))
        theta_batches = tuple(theta_batches)
        telemetry.journal_set_round(self.rounds)
        self._maybe_readmit()
        if self._reset_lanes_pending:
            state = self._reset_dead_lane_starts(state, theta_batches)
            self.dead_lanes = tuple(
                np.zeros((g.n_agents,), bool) for g in self.base_groups)
            self._reset_lanes_pending = False
            # the zeroed multipliers changed the active sum the dual
            # update conserves — re-center or the recovered fleet
            # settles mean(lam)/rho off the true consensus, forever
            state = self._recenter_consensus_multipliers(state,
                                                         base_masks)
        # the pre-failure iterate's consensus fingerprint: what a
        # degraded-mesh carry-over must reproduce bitwise
        self._consensus_snapshot = self._consensus_host(state)
        transient = 0
        t_detect = None
        while True:
            layout = self._current
            try:
                out = self._run_layout(layout, state, theta_batches,
                                       base_masks)
                break
            except MeshRoundTimeout:
                if t_detect is None:
                    t_detect = time.perf_counter()
                report = self._probe(layout.mesh)
                if not report.answered:
                    raise RuntimeError(
                        "no mesh device answered the post-condemnation "
                        "probe — the whole mesh is unreachable; escalate "
                        "to checkpoint restore "
                        "(docs/robustness.md, 'Surviving shard loss')"
                    ) from None
                if report.dead:
                    self._degrade(report)
                    continue
                transient += 1
                if telemetry.enabled():
                    telemetry.counter(
                        "mesh_round_retries_total",
                        "condemned rounds retried on the same mesh "
                        "(every shard answered the probe)").inc(
                        reason="transient")
                if transient > MAX_TRANSIENT_RETRIES:
                    raise RuntimeError(
                        f"fused round timed out {transient} times while "
                        f"every shard answers the probe — the collective "
                        f"is wedged without an attributable dead device; "
                        f"raise watchdog_timeout_s or escalate to "
                        f"checkpoint restore") from None
                logger.warning(
                    "condemned round retried on the same %d-device mesh "
                    "(all shards answered the probe; attempt %d/%d)",
                    len(layout.device_ids), transient,
                    MAX_TRANSIENT_RETRIES)
                layout.engine.mesh_condemned = False
        if t_detect is not None:
            self.last_mttr_s = time.perf_counter() - t_detect
            if telemetry.enabled():
                telemetry.histogram(
                    "mesh_shard_loss_recovery_seconds",
                    "wall seconds from a condemned collective to the "
                    "first completed (possibly degraded) round"
                    ).observe(self.last_mttr_s)
        self.rounds += 1
        if self.degraded:
            self.degraded_rounds += 1
            self._healthy_degraded_rounds += 1
        if self._probation_left > 0:
            self._probation_left -= 1
            if self._probation_left == 0:
                # probation served: the full mesh proved itself
                self._readmit_needed = self.readmit_after
        state_out, trajs, stats = out
        if telemetry.journal_active() is not None:
            # guarded: _quarantine_count is a device->host readback —
            # a journal-off fleet must not pay it per round
            telemetry.journal_event(
                "fleet.round", round=self.rounds - 1,
                degraded=self.degraded,
                devices=len(self._current.device_ids),
                dead_devices=list(self.dead_devices),
                quarantined=_quarantine_count(stats))
        self._consensus_snapshot = self._consensus_host(state_out)
        return state_out, trajs, stats

    def _run_layout(self, layout: _Layout, state, theta_batches,
                    base_masks):
        if any(layout.pads.values()):
            state, theta_batches = self._ref.pad_state_rows(
                layout.pads, state, theta_batches)
        # placement on the layout's mesh (shard_args with pre-padded
        # inputs is pure placement: pads resolve to zero)
        state, theta_batches = layout.engine.shard_args(
            layout.mesh, state, theta_batches)
        if self._verify_carry:
            # the degraded carry-over must reproduce the pre-failure
            # consensus iterate BITWISE after pad + placement — a carry
            # that cannot is corrupted and must not resume
            carried = self._consensus_host(state)
            for key, ref in (self._consensus_snapshot or {}).items():
                if not np.array_equal(carried[key], ref):
                    kind, alias = key
                    raise RuntimeError(
                        f"degraded-mesh carry-over drifted from the "
                        f"pre-failure iterate at {kind}[{alias}] — "
                        f"refusing to resume from a corrupted carry")
            self._verify_carry = False
            # the dead lanes just left the active set, stranding their
            # share of the conserved multiplier sum with the survivors
            # — re-center so the DEGRADED equilibrium is the survivors'
            # true consensus, not a biased one
            state = self._recenter_consensus_multipliers(
                state, self._layout_masks(layout, base_masks))
        masks = self._layout_masks(layout, base_masks)
        engine = layout.engine
        if not getattr(engine, "_supervisor_warmed", False):
            # first round of a fresh layout: trace+compile rides inside
            # the bounded wait — give it the warmup allowance so a
            # legitimate compile never reads as a collective stall
            budget = engine.watchdog_timeout_s
            engine.watchdog_timeout_s = budget + self.warmup_budget_s
            try:
                out = engine.step(state, theta_batches, active=masks)
            finally:
                engine.watchdog_timeout_s = budget
            engine._supervisor_warmed = True
        else:
            out = engine.step(state, theta_batches, active=masks)
        return self._slice_rows(*out)

    # -- degrade / re-admit ---------------------------------------------------

    def _mark_dead_lanes(self, dead_ids) -> None:
        """Base-layout lanes hosted by the dead shards, derived from
        the CURRENT layout's contiguous row assignment — on a cascading
        loss the failure happens on an already-degraded mesh whose
        rows-per-device and device positions differ from the full
        layout's, and the lanes to mask are the ones the dying shard
        actually hosted there (padding rows it hosted mask nothing)."""
        layout = self._current
        n_dev = len(layout.device_ids)
        positions = [i for i, did in enumerate(layout.device_ids)
                     if did in set(dead_ids)]
        for gi, g in enumerate(self.base_groups):
            n_rows = g.n_agents + layout.pads.get(gi, 0)
            rpd = n_rows // n_dev
            for p in positions:
                lo, hi = p * rpd, (p + 1) * rpd
                self.dead_lanes[gi][lo:min(hi, g.n_agents)] = True

    def _degrade(self, report) -> None:
        """Shard loss: rebuild on the surviving mesh, carry the warm
        state over shard-aligned, mask the dead lanes."""
        dead = tuple(report.dead)
        alive = tuple(did for did in self._current.device_ids
                      if did not in set(dead))
        if not alive:
            raise RuntimeError("every device of the current mesh is "
                               "dead — escalate to checkpoint restore")
        self._mark_dead_lanes(dead)
        self.dead_devices = tuple(dict.fromkeys(
            (*self.dead_devices, *dead)))
        # consensus identity against the pre-failure iterate: the
        # replicated leaves are host-snapshotted at round start; a
        # carry that cannot reproduce them bitwise must not resume
        snap = self._consensus_snapshot
        if snap is not None:
            for (kind, alias), ref in snap.items():
                if not np.all(np.isfinite(ref)):
                    raise RuntimeError(
                        f"pre-failure consensus iterate {kind}[{alias}] "
                        f"is non-finite — refusing to carry a corrupted "
                        f"state onto the degraded mesh")
        was = len(self._current.device_ids)
        t0 = time.perf_counter()
        self._current = self._layout_for(alive)
        build_s = time.perf_counter() - t0
        self.degraded = True
        self._verify_carry = True
        self._healthy_degraded_rounds = 0
        if self._probation_left > 0:
            # relapse during probation: hysteresis — the next
            # re-admission needs twice the proof
            self._readmit_needed = max(
                self._readmit_needed * 2, self.readmit_after)
            self._probation_left = 0
        if telemetry.enabled():
            telemetry.counter(
                "mesh_degrade_total",
                "degraded-mesh fallbacks (shard loss absorbed)").inc()
        telemetry.journal_event(
            "mesh.degrade", axis="agents", dead=list(dead),
            devices_from=was, devices_to=len(alive),
            dead_lanes=int(sum(int(d.sum())
                               for d in self.dead_lanes)),
            engine_reused=build_s < 0.05,
            collective_digest=self._current.engine
            .collective_schedule_digest)
        self._export_gauges()
        logger.warning(
            "fleet degraded %d -> %d devices (dead: %s; engine %s in "
            "%.2fs); %d lane(s) masked until re-admission",
            was, len(alive), list(dead),
            "reused" if build_s < 0.05 else "built", build_s,
            int(sum(int(d.sum()) for d in self.dead_lanes)))

    def _maybe_readmit(self) -> None:
        if not self.degraded:
            return
        if self._healthy_degraded_rounds < self._readmit_needed:
            return
        report = self._probe(self.full_mesh)
        if not report.all_answered:
            # restart the hysteresis clock: probing a still-dead device
            # costs the probe deadline AND leaks one wedged probe
            # thread per dead device on real hardware (the block is
            # uncancellable) — once per readmit window is the bounded
            # rate, once per round would not be
            self._healthy_degraded_rounds = 0
            logger.info(
                "re-admission probe: %d device(s) still dead (%s) — "
                "staying on the degraded mesh; next probe after %d "
                "more healthy rounds", len(report.dead),
                list(report.dead), self._readmit_needed)
            return
        full = self._layouts[self._full_ids]
        full.engine.mesh_condemned = False
        self._current = full
        self.degraded = False
        self._healthy_degraded_rounds = 0
        self._reset_lanes_pending = True
        self._probation_left = self.probation_rounds
        self.dead_devices = ()
        if telemetry.enabled():
            telemetry.counter(
                "mesh_readmit_total",
                "full-mesh re-admissions after degraded service").inc()
        telemetry.journal_event(
            "mesh.readmit", devices=len(self._full_ids),
            probation_rounds=self.probation_rounds)
        self._export_gauges()
        logger.warning(
            "full %d-device mesh re-admitted on probation (%d rounds); "
            "lost lanes re-enter with fresh warm starts",
            len(self._full_ids), self.probation_rounds)

    # -- operator / gate hooks ------------------------------------------------

    def force_degrade(self, dead_device_ids) -> None:
        """Operator/gate entry: degrade as if ``dead_device_ids`` had
        failed a probe (no round needs to time out first)."""
        self._degrade(multihost.ShardProbeReport(
            answered=tuple(d for d in self._current.device_ids
                           if d not in set(dead_device_ids)),
            dead=tuple(dead_device_ids), latency_s={}))

    def force_readmit(self) -> None:
        """Operator/gate entry: reshard back to the full mesh now,
        bypassing the hysteresis clock (the probe still runs via
        :meth:`_maybe_readmit` on the next step for the honest path;
        this one trusts the operator)."""
        self._healthy_degraded_rounds = self._readmit_needed
        probe, self._probe = self._probe, lambda m: \
            multihost.ShardProbeReport(
                answered=tuple(d.id for d in m.devices.flat),
                dead=(), latency_s={})
        try:
            self._maybe_readmit()
        finally:
            self._probe = probe

    def _export_gauges(self) -> None:
        if telemetry.enabled():
            telemetry.gauge(
                "mesh_devices_active",
                "devices in the mesh currently serving the fleet").set(
                float(len(self._current.device_ids)))

    def stats(self) -> dict:
        return {
            "devices_full": len(self._full_ids),
            "devices_active": len(self._current.device_ids),
            "degraded": self.degraded,
            "dead_devices": list(self.dead_devices),
            "dead_lanes": int(sum(int(d.sum()) for d in self.dead_lanes)),
            "rounds": self.rounds,
            "degraded_rounds": self.degraded_rounds,
            "layouts_built": len(self._layouts),
            "last_mttr_s": self.last_mttr_s,
            "probation_left": self._probation_left,
            "collective_schedule_digest":
                self._current.engine.collective_schedule_digest,
        }


# --------------------------------------------------------------------------
# survivability on the 2-D (agents × scenarios) mesh (ISSUE 14)
# --------------------------------------------------------------------------


class _ScenLayout(NamedTuple):
    """One 2-D mesh configuration's serving machinery."""

    rows: tuple          # surviving agent-axis row indices, FULL grid
    cols: tuple          # surviving scenario-axis column indices
    mesh: object         # the (possibly degraded) 2-D mesh
    fleet: object        # ScenarioFleet
    tree: object         # the layout's (reduced, RE-NORMALIZED) tree
    scen_keep: tuple     # surviving BASE scenario indices, ascending
    pad: int             # agent rows added over the base group size


class ScenarioFleetSupervisor:
    """Run a :class:`~agentlib_mpc_tpu.scenario.fleet.ScenarioFleet`
    with shard-loss survival on BOTH mesh axes — the
    :class:`FleetSupervisor` ladder lifted to the 2-D
    (agents × scenarios) grid (ISSUE 14):

    1. **Detect** — every robust round runs under the fleet's
       collective watchdog (``ScenarioFleet(watchdog_timeout_s=...)``);
       a blown budget condemns the mesh and surfaces the bounded
       per-device probe.
    2. **Classify** — a 2-D mesh must stay rectangular, so a dead
       device costs its whole grid ROW or COLUMN. ``degrade_axis``
       decides: ``"auto"`` prefers the **scenarios** axis whenever it
       can shrink (dropping a column costs robustness *breadth* —
       recoverable statistically through probability renormalization —
       while dropping a row takes real agents' plants offline);
       ``"agents"``/``"scenarios"`` force the call.
    3. **Degrade** —
       * **agents-axis loss** rides the flat pad path: the lanes the
         dead rows hosted are masked at base granularity, the warm
         state carries over row-aligned, and the agent-consensus
         multipliers are re-centered over the survivors (the PR 10
         conserved-λ-sum fix, per scenario column).
       * **scenarios-axis loss** rebuilds on the reduced scenario mesh:
         the lost branches leave their non-anticipativity node groups
         and the surviving group probabilities are **re-normalized**
         (:meth:`~agentlib_mpc_tpu.scenario.tree.ScenarioTree.subtree`)
         so the projection stays a true probability-weighted mean — and
         the non-anticipativity multipliers ``nu`` are re-centered per
         surviving node group: the dual update conserves each group's
         ``nu`` sum (the projection is the group mean), so dropping
         members strands a stale sum with the survivors and the fleet
         would converge — confidently, with tiny residuals — to an
         actuated u0 biased by exactly ``mean_group(nu)/rho_na``,
         forever. The 2-D analogue of the PR 10 fix.
       Every degraded rebuild must certify the IDENTICAL per-axis
       collective schedule as the full engine
       (:func:`assert_schedule_identity` — the PR 11 gate) and carry a
       memory certificate within capacity (the PR 13 gate fires inside
       the ``ScenarioFleet`` build via ``memory_certify``).
    4. **Serve degraded** — the condemned round retries from its input
       state on the reduced grid; surviving agents (and branches) keep
       actuating, with the lost branches' trajectory rows NaN-filled
       (no data is honest; fabricated data is not).
    5. **Re-admit** — hysteretic and PER AXIS: after enough healthy
       degraded rounds the full grid is probed; when every device
       answers, the full layout is reinstated (cached engine — zero new
       compiles), lost lanes AND branches re-enter with fresh warm
       starts, multipliers re-center, and a probation window opens —
       a relapse inside it doubles the *failing axis's* healthy-round
       requirement.

    Degenerate contract: a single-scenario tree delegates UNWRAPPED to
    a flat :class:`FleetSupervisor` (flat state/theta types, the flat
    mesh) — the S=1 supervisor IS the flat supervisor, bitwise, the
    same way the S=1 solver stack routes through the flat sweep.

    API is layout-stable at BASE shapes: ``step`` takes and returns
    state/theta/trajectories at (n_agents, S_base) regardless of the
    grid currently serving — selection, padding and scatter-back are
    internal."""

    def __init__(self, group, tree,
                 options=None, mesh=None, active=None,
                 watchdog_timeout_s: float = 30.0,
                 probe_timeout_s: float = multihost.MESH_PROBE_TIMEOUT_S,
                 readmit_after: int = 2,
                 probation_rounds: int = 2,
                 warmup_budget_s: float = 600.0,
                 degrade_axis: str = "auto",
                 collective_certify: str = "auto",
                 memory_certify: str = "auto"):
        import numpy as _np

        from agentlib_mpc_tpu.scenario.fleet import (
            ScenarioFleet,
            ScenarioFleetOptions,
        )

        if options is None:
            options = ScenarioFleetOptions()
        if degrade_axis not in ("auto", "agents", "scenarios"):
            raise ValueError(
                f"degrade_axis must be 'auto', 'agents' or "
                f"'scenarios', got {degrade_axis!r}")
        self._fleet_cls = ScenarioFleet
        self.base_group = group
        self.tree = tree.validate(group.ocp.N)
        self.options = options
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.warmup_budget_s = float(warmup_budget_s)
        self.readmit_after = max(1, int(readmit_after))
        self.probation_rounds = max(0, int(probation_rounds))
        self.degrade_axis = degrade_axis
        self.collective_certify = collective_certify
        self.memory_certify = memory_certify

        # -- degenerate contract: S=1 routes UNWRAPPED through the flat
        # supervisor (state types, mesh and all) — pinned bitwise in
        # tests/test_scenario_fleet.py
        self._flat: "FleetSupervisor | None" = None
        if self.tree.n_scenarios == 1:
            self.flat_options = FusedADMMOptions(
                max_iterations=options.max_iterations,
                rho=options.rho, abs_tol=options.abs_tol,
                rel_tol=options.rel_tol,
                use_relative_tolerances=options.use_relative_tolerances,
                primal_tol=options.primal_tol,
                dual_tol=options.dual_tol,
                quarantine=options.quarantine,
                quarantine_reset_after=options.quarantine_reset_after)
            flat_mesh = self._flatten_degenerate_mesh(mesh)
            self._flat = FleetSupervisor(
                [group], self.flat_options, mesh=flat_mesh,
                active=None if active is None else [active],
                watchdog_timeout_s=watchdog_timeout_s,
                probe_timeout_s=probe_timeout_s,
                readmit_after=readmit_after,
                probation_rounds=probation_rounds,
                warmup_budget_s=warmup_budget_s)
            return

        if mesh is None:
            mesh = multihost.scenario_mesh(1)
        names = tuple(mesh.axis_names)
        if names != ("agents", "scenarios"):
            raise ValueError(
                f"ScenarioFleetSupervisor needs a 2-D ('agents', "
                f"'scenarios') mesh (multihost.scenario_mesh); got "
                f"axes {names}")
        self.full_mesh = mesh
        self.grid = _np.asarray(mesh.devices)        # (A_sh, S_sh)
        self.grid_ids = _np.vectorize(lambda d: d.id)(self.grid)
        self._full_ids = tuple(d.id for d in self.grid.flat)
        self.S = self.tree.n_scenarios
        n_cols = self.grid.shape[1]
        if self.S % n_cols:
            raise ValueError(
                f"{self.S} scenarios do not divide the {n_cols}-shard "
                f"scenario axis — pad the tree first "
                f"(scenario.fleet.pad_scenarios)")
        #: scenarios hosted per grid column on the FULL mesh
        self.spd = self.S // n_cols
        if active is None:
            active = jnp.ones((group.n_agents,), bool)
        self.base_active = jnp.asarray(active, bool)
        self._probe = lambda m: multihost.probe_mesh_devices(
            m, self.probe_timeout_s)
        self._layouts: dict = {}
        #: base-layout agent lanes lost to dead rows
        self.dead_lanes = np.zeros((group.n_agents,), bool)
        #: base scenario indices lost to dead columns
        self.dead_branches: set = set()
        self.dead_devices: tuple = ()
        self._full_key = (tuple(range(self.grid.shape[0])),
                          tuple(range(self.grid.shape[1])))
        self._current = self._layout_for(*self._full_key)
        self._ref = self._current.fleet
        # survivability bookkeeping (per-axis hysteresis)
        self.degraded = False
        self.degraded_axes: set = set()
        self._healthy_degraded_rounds = 0
        self._readmit_needed = {"agents": self.readmit_after,
                                "scenarios": self.readmit_after}
        self._probation_left = 0
        self._reset_pending = False
        #: axes whose membership changed at the LAST transition — the
        #: re-centering debt consumed by the next _run_layout (a later
        #: cascading loss on the other axis must not re-touch this one)
        self._recenter_pending: set = set()
        self.rounds = 0
        self.degraded_rounds = 0
        self.last_mttr_s: "float | None" = None
        self.mttr_by_axis: dict = {"agents": None, "scenarios": None}
        self._consensus_snapshot = None
        self._verify_carry = False
        self._export_gauges()

    @staticmethod
    def _flatten_degenerate_mesh(mesh):
        """The 1-D agents mesh the S=1 delegate runs on: a 2-D mesh
        whose scenario axis is width 1 flattens to its agent column; a
        wider scenario axis has no single-scenario layout at all."""
        if mesh is None:
            return None
        names = tuple(mesh.axis_names)
        if names == ("agents",):
            return mesh
        if names == ("agents", "scenarios"):
            import numpy as _np

            grid = _np.asarray(mesh.devices)
            if grid.shape[1] != 1:
                raise ValueError(
                    f"a single-scenario tree cannot shard over the "
                    f"{grid.shape[1]}-column scenario axis — use "
                    f"scenario_mesh(1) or a 1-D agents mesh")
            return multihost.fleet_mesh(devices=list(grid[:, 0]))
        raise ValueError(f"unsupported mesh axes {names}")

    # -- layouts --------------------------------------------------------------

    def _layout_for(self, rows, cols) -> _ScenLayout:
        key = (tuple(rows), tuple(cols))
        layout = self._layouts.get(key)
        if layout is not None:
            return layout
        full = key == self._full_key
        mesh = self.full_mesh if full else multihost.surviving_mesh_2d(
            self.full_mesh, key[0], key[1])
        scen_keep = tuple(s for c in key[1]
                          for s in range(c * self.spd,
                                         (c + 1) * self.spd))
        # the reduced tree drops the lost branches from their node
        # groups and RE-NORMALIZES the group probabilities — without
        # this the projection is a sub-distribution-weighted mean and
        # the actuated u0 carries a permanent stale-probability bias
        tree = self.tree if full else self.tree.subtree(scen_keep)
        n_rows = len(key[0])
        pad = (-self.base_group.n_agents) % n_rows
        group = self.base_group
        if pad:
            group = dataclasses.replace(
                group, n_agents=group.n_agents + pad)
        fleet = self._fleet_cls(
            group, tree, self.options, mesh=mesh,
            watchdog_timeout_s=self.watchdog_timeout_s,
            collective_certify=self.collective_certify,
            memory_certify=self.memory_certify)
        if self._layouts:
            # PR 11 + PR 13 wired into every degraded rebuild: the
            # schedule must be IDENTICAL per axis (below), and the
            # memory certificate was already enforced within capacity
            # by the ScenarioFleet build we just paid (memory_certify)
            assert_schedule_identity(
                self._ref, fleet,
                f"degraded 2-D rebuild on {len(key[0])}x{len(key[1])} "
                f"devices")
        layout = _ScenLayout(rows=key[0], cols=key[1], mesh=mesh,
                             fleet=fleet, tree=tree,
                             scen_keep=scen_keep, pad=pad)
        self._layouts[key] = layout
        return layout

    @property
    def engine(self):
        """The fleet currently serving (full or degraded grid)."""
        if self._flat is not None:
            return self._flat.engine
        return self._current.fleet

    @property
    def mesh_shape(self) -> tuple:
        """(agent shards, scenario shards) currently serving."""
        if self._flat is not None:
            return (self._flat.mesh_devices, 1)
        return (len(self._current.rows), len(self._current.cols))

    @property
    def scenarios_active(self) -> int:
        if self._flat is not None:
            return 1
        return len(self._current.scen_keep)

    # -- layout-stable state plumbing -----------------------------------------

    def init_state(self, theta_batch):
        """Fresh robust state in the BASE (n_agents, S) layout (the S=1
        delegate takes the flat supervisor's per-group theta list)."""
        if self._flat is not None:
            return self._flat.init_state(theta_batch)
        full = self._layouts[self._full_key]
        if full.pad:
            theta_batch = self._pad_theta(theta_batch, full.pad)
        state = full.fleet.init_state(theta_batch)
        if full.pad:
            state = self._slice_agents(state, self.base_group.n_agents)
        return state

    def shift_state(self, state):
        if self._flat is not None:
            return self._flat.shift_state(state)
        return self._ref.shift_state(state)

    @staticmethod
    def _pad_theta(theta_batch, pad: int):
        return jax.tree.map(
            lambda leaf: jnp.concatenate(
                [leaf, jnp.repeat(leaf[-1:], pad, axis=0)]), theta_batch)

    @staticmethod
    def _pad_state_rows(state, pad: int):
        """Grow the agent axis by ``pad`` repeated last rows (masked
        dead weight — the ``pad_group_to_devices`` semantics on the
        scenario state; ``zbar`` has no agent axis)."""
        grow = lambda leaf: jnp.concatenate(
            [leaf, jnp.repeat(leaf[-1:], pad, axis=0)])
        return state._replace(
            lam={a: grow(v) for a, v in state.lam.items()},
            nu=grow(state.nu), na_target=grow(state.na_target),
            w=grow(state.w), y=grow(state.y), z=grow(state.z))

    @staticmethod
    def _slice_agents(state, n: int):
        sl = lambda leaf: leaf[:n]
        return state._replace(
            lam={a: sl(v) for a, v in state.lam.items()},
            nu=sl(state.nu), na_target=sl(state.na_target),
            w=sl(state.w), y=sl(state.y), z=sl(state.z))

    @staticmethod
    def _select_scenarios(state, scen_keep):
        """Restrict the scenario axis to the surviving base indices —
        the lost branches' columns stay behind in the caller's
        base-layout state as dead weight."""
        idx = jnp.asarray(scen_keep)
        return state._replace(
            zbar={a: v[idx] for a, v in state.zbar.items()},
            lam={a: v[:, idx] for a, v in state.lam.items()},
            nu=state.nu[:, idx], na_target=state.na_target[:, idx],
            w=state.w[:, idx], y=state.y[:, idx], z=state.z[:, idx])

    def _merge_state(self, base_state, lstate, layout) -> object:
        """Scatter a layout's round output back into the BASE layout:
        agent pads sliced off, surviving scenario columns updated, lost
        columns left at their pre-loss values (dead weight until the
        re-admission resets them)."""
        n = self.base_group.n_agents
        lstate = self._slice_agents(lstate, n)
        if layout.scen_keep == tuple(range(self.S)):
            return lstate
        idx = jnp.asarray(layout.scen_keep)
        put = lambda base, part: base.at[:, idx].set(part)
        return base_state._replace(
            zbar={a: base_state.zbar[a].at[idx].set(v)
                  for a, v in lstate.zbar.items()},
            lam={a: put(base_state.lam[a], v)
                 for a, v in lstate.lam.items()},
            nu=put(base_state.nu, lstate.nu),
            na_target=put(base_state.na_target, lstate.na_target),
            w=put(base_state.w, lstate.w),
            y=put(base_state.y, lstate.y),
            z=put(base_state.z, lstate.z))

    @staticmethod
    def _unplace(tree_):
        """Pull a pytree off its mesh placement: the degraded layout's
        outputs live on the reduced device set, the base-layout state
        on the full one — a scatter across the two placements is
        rejected by the runtime, so the merge happens unplaced (the
        next round's ``shard_args`` re-places everything anyway)."""
        return jax.tree.map(
            lambda leaf: jnp.asarray(np.asarray(leaf)), tree_)

    def _merge_outputs(self, base_state, out, layout):
        lstate, ltrajs, lstats = out
        n = self.base_group.n_agents
        reduced = layout.scen_keep != tuple(range(self.S))
        if reduced:
            base_state = self._unplace(base_state)
            lstate = self._unplace(lstate)
            ltrajs = self._unplace(ltrajs)
            lstats = self._unplace(lstats)
        state = self._merge_state(base_state, lstate, layout)
        if not reduced:
            trajs = jax.tree.map(lambda leaf: leaf[:n], ltrajs) \
                if layout.pad else ltrajs
            stats = lstats
            if layout.pad and lstats.lane_quarantined is not None:
                stats = lstats._replace(
                    lane_quarantined=lstats.lane_quarantined[:n])
            return state, trajs, stats
        idx = jnp.asarray(layout.scen_keep)

        def scatter_traj(leaf):
            leaf = leaf[:n]
            base = jnp.full((n, self.S) + leaf.shape[2:], jnp.nan,
                            leaf.dtype)
            return base.at[:, idx].set(leaf)

        trajs = jax.tree.map(scatter_traj, ltrajs)
        stats = lstats
        if lstats.lane_quarantined is not None:
            q = jnp.zeros((n, self.S), jnp.int32).at[:, idx].set(
                lstats.lane_quarantined[:n])
            stats = lstats._replace(lane_quarantined=q)
        return state, trajs, stats

    def _consensus_host(self, state) -> dict:
        return {alias: np.asarray(leaf)
                for alias, leaf in state.zbar.items()}

    # -- multiplier re-centering (the conserved-sum fixes) --------------------

    def _recenter_consensus_multipliers(self, state, mask):
        """PR 10's conserved-λ-sum fix per scenario column: the agent-
        consensus dual update conserves the active lanes' multiplier
        sum, so any agent-membership change strands a stale sum and
        biases that scenario's consensus by mean(λ)/ρ forever."""
        m = jnp.asarray(mask, bool)[:, None, None]
        cnt = jnp.maximum(jnp.sum(jnp.asarray(mask, bool)), 1)
        lam = {}
        for a, leaf in state.lam.items():
            mean = jnp.sum(jnp.where(m, leaf, 0.0), axis=0) / cnt
            lam[a] = jnp.where(m, leaf - mean[None], leaf)
        return state._replace(lam=lam)

    def _recenter_na_multipliers(self, state, tree, scen_positions):
        """The 2-D analogue of the conserved-sum fix: the NA dual
        update ``nu -= rho_na * (target - u)`` sums to zero across a
        node group (the target is the group mean), so each group's
        ``nu`` sum is conserved — branch loss (or a re-admitted branch
        with zeroed ``nu``) strands a stale sum and the converged
        projection lands exactly ``mean_group(nu)/rho_na`` off the
        survivors' true probability-weighted mean. Re-center per
        (agent, group, stage)."""
        nu = state.nu
        for t in range(tree.robust_horizon):
            for grp in tree.groups_at(t):
                cols = jnp.asarray(
                    [scen_positions[s] for s in grp])
                mean = jnp.mean(nu[:, cols, t, :], axis=1,
                                keepdims=True)
                nu = nu.at[:, cols, t, :].add(-mean)
        return state._replace(nu=nu)

    def _reset_dead_starts(self, state, theta_batch):
        """Fresh warm starts for everything a dead shard carried —
        the recycled-slot contract on both axes: lost agent LANES and
        lost scenario BRANCHES re-enter on the sanitized OCP initial
        guess with zeroed multipliers, never their stale pre-failure
        iterates."""
        w_init = jax.vmap(jax.vmap(
            self.base_group.ocp.initial_guess))(theta_batch)
        w_init = jnp.where(jnp.isfinite(w_init), w_init, 0.0)
        lanes = jnp.asarray(self.dead_lanes)
        branches = jnp.zeros((self.S,), bool)
        if self.dead_branches:
            branches = branches.at[
                jnp.asarray(sorted(self.dead_branches))].set(True)
        fresh = lanes[:, None] | branches[None, :]       # (n, S)
        if not bool(jnp.any(fresh)):
            return state
        f2 = fresh[:, :, None]
        state = state._replace(
            w=jnp.where(f2, w_init, state.w),
            y=jnp.where(f2, 0.0, state.y),
            z=jnp.where(f2, 0.1, state.z),
            nu=jnp.where(fresh[:, :, None, None], 0.0, state.nu),
            lam={a: jnp.where(f2, 0.0, v)
                 for a, v in state.lam.items()},
            zbar={a: jnp.where(branches[:, None], 0.0, v)
                  for a, v in state.zbar.items()})
        return state

    # -- the survivable round -------------------------------------------------

    def step(self, state, theta_batch, active=None):
        """One fused robust round in the BASE layout, surviving shard
        loss on either axis. Same signature and return contract as
        :meth:`ScenarioFleet.step` (the S=1 delegate follows
        :meth:`FleetSupervisor.step`'s flat contract instead)."""
        if self._flat is not None:
            # the 2-D contract hands ONE (n_agents,) mask; the flat
            # supervisor takes a per-group sequence — wrap a bare mask
            # so both conventions work on the degenerate supervisor
            if active is not None and not isinstance(active,
                                                     (list, tuple)):
                active = [active]
            return self._flat.step(state, theta_batch, active=active)
        mask = (self.base_active if active is None
                else jnp.asarray(active, bool))
        telemetry.journal_set_round(self.rounds)
        self._maybe_readmit()
        if self._reset_pending:
            state = self._reset_dead_starts(state, theta_batch)
            had_lanes = bool(np.any(self.dead_lanes))
            had_branches = bool(self.dead_branches)
            self.dead_lanes = np.zeros(
                (self.base_group.n_agents,), bool)
            self.dead_branches = set()
            self._reset_pending = False
            # the zeroed multipliers changed the conserved sums the
            # dual updates preserve — re-center both families or the
            # recovered fleet settles off the true consensus, forever
            if had_lanes:
                state = self._recenter_consensus_multipliers(state, mask)
            if had_branches:
                state = self._recenter_na_multipliers(
                    state, self.tree, tuple(range(self.S)))
        self._consensus_snapshot = self._consensus_host(state)
        transient = 0
        t_detect = None
        detect_axis = None
        while True:
            layout = self._current
            try:
                out = self._run_layout(layout, state, theta_batch, mask)
                break
            except MeshRoundTimeout:
                if t_detect is None:
                    t_detect = time.perf_counter()
                report = self._probe(layout.mesh)
                if not report.answered:
                    raise RuntimeError(
                        "no device of the 2-D mesh answered the post-"
                        "condemnation probe — the whole grid is "
                        "unreachable; escalate to checkpoint restore "
                        "(docs/robustness.md, 'Surviving loss on "
                        "either axis')") from None
                if set(report.dead) & set(self._current_ids()):
                    detect_axis = self._degrade(report)
                    continue
                transient += 1
                if telemetry.enabled():
                    telemetry.counter(
                        "mesh_round_retries_total",
                        "condemned rounds retried on the same mesh "
                        "(every shard answered the probe)").inc(
                        reason="transient")
                telemetry.journal_event(
                    "mesh.retry", attempt=transient,
                    mesh_shape=[len(layout.rows), len(layout.cols)],
                    answered=list(report.answered))
                if transient > MAX_TRANSIENT_RETRIES:
                    raise RuntimeError(
                        f"scenario round timed out {transient} times "
                        f"while every shard answers the probe — the "
                        f"collective is wedged without an attributable "
                        f"dead device; raise watchdog_timeout_s or "
                        f"escalate to checkpoint restore") from None
                logger.warning(
                    "condemned round retried on the same %dx%d grid "
                    "(all shards answered the probe; attempt %d/%d)",
                    len(layout.rows), len(layout.cols), transient,
                    MAX_TRANSIENT_RETRIES)
                layout.fleet.mesh_condemned = False
        if t_detect is not None:
            self.last_mttr_s = time.perf_counter() - t_detect
            if detect_axis is not None:
                self.mttr_by_axis[detect_axis] = self.last_mttr_s
            if telemetry.enabled():
                telemetry.histogram(
                    "mesh_shard_loss_recovery_seconds",
                    "wall seconds from a condemned collective to the "
                    "first completed (possibly degraded) round"
                    ).observe(self.last_mttr_s,
                              axis=detect_axis or "transient")
        self.rounds += 1
        if self.degraded:
            self.degraded_rounds += 1
            self._healthy_degraded_rounds += 1
        if self._probation_left > 0:
            self._probation_left -= 1
            if self._probation_left == 0:
                self._readmit_needed = {
                    "agents": self.readmit_after,
                    "scenarios": self.readmit_after}
        state_out, trajs, stats = out
        if telemetry.journal_active() is not None:
            # guarded: _quarantine_count is a device->host readback —
            # a journal-off fleet must not pay it per round
            telemetry.journal_event(
                "fleet.round", round=self.rounds - 1,
                degraded=self.degraded,
                mesh_shape=[len(self._current.rows),
                            len(self._current.cols)],
                dead_devices=list(self.dead_devices),
                dead_branches=sorted(self.dead_branches),
                quarantined=_quarantine_count(stats))
        self._consensus_snapshot = self._consensus_host(state_out)
        return state_out, trajs, stats

    def _run_layout(self, layout: _ScenLayout, state, theta_batch,
                    base_mask):
        reduced = layout.scen_keep != tuple(range(self.S))
        lstate = self._select_scenarios(state, layout.scen_keep) \
            if reduced else state
        ltheta = jax.tree.map(
            lambda leaf: leaf[:, jnp.asarray(layout.scen_keep)],
            theta_batch) if reduced else theta_batch
        if self._verify_carry:
            # the degraded carry-over must reproduce the pre-failure
            # consensus iterate BITWISE on the surviving branches — a
            # carry that cannot is corrupted and must not resume
            for alias, ref in (self._consensus_snapshot or {}).items():
                carried = np.asarray(lstate.zbar[alias])
                expect = ref[np.asarray(layout.scen_keep)]
                if not np.array_equal(carried, expect):
                    raise RuntimeError(
                        f"degraded-mesh carry-over drifted from the "
                        f"pre-failure iterate at zbar[{alias}] — "
                        f"refusing to resume from a corrupted carry")
            self._verify_carry = False
            # the just-departed members stranded their share of the
            # conserved multiplier sums with the survivors — re-center
            # exactly the family the failing axis disturbed, once
            if "scenarios" in self._recenter_pending:
                lstate = self._recenter_na_multipliers(
                    lstate, layout.tree,
                    tuple(range(len(layout.scen_keep))))
            if "agents" in self._recenter_pending:
                lstate = self._recenter_consensus_multipliers(
                    lstate, np.asarray(base_mask)
                    & ~np.asarray(self.dead_lanes))
            self._recenter_pending = set()
        mask = jnp.asarray(base_mask, bool) & jnp.asarray(
            ~self.dead_lanes)
        if layout.pad:
            lstate = self._pad_state_rows(lstate, layout.pad)
            ltheta = self._pad_theta(ltheta, layout.pad)
            mask = jnp.concatenate(
                [mask, jnp.zeros((layout.pad,), bool)])
        lstate, ltheta = layout.fleet.shard_args(layout.mesh, lstate,
                                                 ltheta)
        fleet = layout.fleet
        if not getattr(fleet, "_supervisor_warmed", False):
            # first round of a fresh layout: trace+compile rides inside
            # the bounded wait — the warmup allowance keeps a
            # legitimate compile from reading as a collective stall
            budget = fleet.watchdog_timeout_s
            fleet.watchdog_timeout_s = budget + self.warmup_budget_s
            try:
                out = fleet.step(lstate, ltheta, active=mask)
            finally:
                fleet.watchdog_timeout_s = budget
            fleet._supervisor_warmed = True
        else:
            out = fleet.step(lstate, ltheta, active=mask)
        return self._merge_outputs(state, out, layout)

    # -- degrade / re-admit ---------------------------------------------------

    def _dead_positions(self, dead_ids) -> tuple:
        """(row positions, col positions) of the dead devices within
        the CURRENT layout's grid."""
        layout = self._current
        dead = set(dead_ids)
        rows_hit, cols_hit = set(), set()
        for i, r in enumerate(layout.rows):
            for j, c in enumerate(layout.cols):
                if self.grid_ids[r, c] in dead:
                    rows_hit.add(i)
                    cols_hit.add(j)
        return tuple(sorted(rows_hit)), tuple(sorted(cols_hit))

    def _classify_axis(self, rows_hit, cols_hit,
                       forced: "str | None" = None) -> str:
        """Which axis pays for the loss. ``"auto"`` prefers scenarios
        whenever that axis can shrink: a dropped column costs
        robustness breadth (recoverable — the surviving branches'
        probabilities renormalize into an honest reduced-tree problem),
        a dropped row takes real plants offline. A scenario degrade
        that would leave a SINGLE surviving branch is off the table
        either way: the degenerate tree traces no non-anticipativity
        collectives at all — a different program class the
        schedule-identity gate refuses — so "auto" falls back to the
        agents axis there."""
        layout = self._current
        spd = len(layout.scen_keep) // len(layout.cols)
        surviving_branches = (len(layout.cols) - len(cols_hit)) * spd
        axis = forced or self.degrade_axis
        if axis == "auto":
            axis = ("scenarios"
                    if len(layout.cols) - len(cols_hit) >= 1
                    and len(layout.cols) > 1
                    and surviving_branches > 1 else "agents")
        if axis == "scenarios":
            if len(layout.cols) - len(cols_hit) < 1:
                raise RuntimeError(
                    "every scenario column hosts a dead device — no "
                    "reduced scenario mesh exists; escalate to "
                    "checkpoint restore")
            if surviving_branches <= 1:
                raise RuntimeError(
                    "a scenarios-axis degrade here would leave a "
                    "single surviving branch — the degenerate tree "
                    "traces no non-anticipativity collectives (a "
                    "different program class the schedule-identity "
                    "gate refuses); degrade the agents axis instead")
        elif len(layout.rows) - len(rows_hit) < 1:
            raise RuntimeError(
                "every agent row hosts a dead device — no reduced "
                "agent mesh exists; escalate to checkpoint restore")
        return axis

    def _mark_dead_lanes(self, rows_hit) -> None:
        """Base agent lanes hosted by the dead rows, derived from the
        CURRENT layout's contiguous row assignment (the cascading-loss
        rule of the flat supervisor: padding rows mask nothing)."""
        layout = self._current
        n_rows = len(layout.rows)
        n_base = self.base_group.n_agents
        rpd = (n_base + layout.pad) // n_rows
        for p in rows_hit:
            lo, hi = p * rpd, (p + 1) * rpd
            self.dead_lanes[lo:min(hi, n_base)] = True

    def _mark_dead_branches(self, cols_hit) -> None:
        """Base scenario branches hosted by the dead columns, via the
        CURRENT layout's contiguous column assignment."""
        layout = self._current
        n_cols = len(layout.cols)
        spd = len(layout.scen_keep) // n_cols
        for p in cols_hit:
            for s in layout.scen_keep[p * spd:(p + 1) * spd]:
                self.dead_branches.add(int(s))

    def _degrade(self, report, forced_axis: "str | None" = None) -> str:
        """Shard loss: classify by axis, rebuild on the surviving
        rectangle, carry the warm state over aligned."""
        layout = self._current
        dead_here = tuple(d for d in report.dead
                          if d in set(self._current_ids()))
        if not dead_here:
            raise ValueError(
                f"none of the dead devices {list(report.dead)} sit on "
                f"the current {len(layout.rows)}x{len(layout.cols)} "
                f"grid — nothing to degrade")
        rows_hit, cols_hit = self._dead_positions(dead_here)
        axis = self._classify_axis(rows_hit, cols_hit, forced_axis)
        snap = self._consensus_snapshot
        if snap is not None:
            for alias, ref in snap.items():
                if not np.all(np.isfinite(ref)):
                    raise RuntimeError(
                        f"pre-failure consensus iterate zbar[{alias}] "
                        f"is non-finite — refusing to carry a "
                        f"corrupted state onto the degraded mesh")
        self.dead_devices = tuple(dict.fromkeys(
            (*self.dead_devices, *dead_here)))
        was = (len(layout.rows), len(layout.cols))
        if axis == "scenarios":
            self._mark_dead_branches(cols_hit)
            new_rows = layout.rows
            new_cols = tuple(c for j, c in enumerate(layout.cols)
                             if j not in set(cols_hit))
        else:
            self._mark_dead_lanes(rows_hit)
            new_rows = tuple(r for i, r in enumerate(layout.rows)
                             if i not in set(rows_hit))
            new_cols = layout.cols
        t0 = time.perf_counter()
        self._current = self._layout_for(new_rows, new_cols)
        build_s = time.perf_counter() - t0
        self.degraded = True
        self.degraded_axes.add(axis)
        self._verify_carry = True
        self._recenter_pending.add(axis)
        self._healthy_degraded_rounds = 0
        if self._probation_left > 0:
            # relapse during probation: hysteresis PER AXIS — the
            # failing axis's next re-admission needs twice the proof
            self._readmit_needed[axis] = max(
                self._readmit_needed[axis] * 2, self.readmit_after)
            self._probation_left = 0
        if telemetry.enabled():
            telemetry.counter(
                "mesh_degrade_total",
                "degraded-mesh fallbacks (shard loss absorbed)").inc(
                axis=axis)
        telemetry.journal_event(
            "mesh.degrade", axis=axis, dead=list(dead_here),
            shape_from=list(was),
            shape_to=[len(new_rows), len(new_cols)],
            dead_lanes=int(self.dead_lanes.sum()),
            dead_branches=sorted(self.dead_branches),
            engine_reused=build_s < 0.05,
            collective_digest=self._current.fleet
            .collective_schedule_digest)
        self._export_gauges()
        logger.warning(
            "scenario fleet degraded %dx%d -> %dx%d devices on the %s "
            "axis (dead: %s; engine %s in %.2fs); %d lane(s) and %d "
            "branch(es) masked until re-admission",
            was[0], was[1], len(new_rows), len(new_cols), axis,
            list(dead_here),
            "reused" if build_s < 0.05 else "built", build_s,
            int(self.dead_lanes.sum()), len(self.dead_branches))
        return axis

    def _maybe_readmit(self) -> None:
        if not self.degraded:
            return
        needed = max(self._readmit_needed[ax]
                     for ax in self.degraded_axes) \
            if self.degraded_axes else self.readmit_after
        if self._healthy_degraded_rounds < needed:
            return
        report = self._probe(self.full_mesh)
        if not report.all_answered:
            self._healthy_degraded_rounds = 0
            logger.info(
                "re-admission probe: %d device(s) still dead (%s) — "
                "staying on the %dx%d grid; next probe after %d more "
                "healthy rounds", len(report.dead), list(report.dead),
                len(self._current.rows), len(self._current.cols),
                needed)
            return
        full = self._layouts[self._full_key]
        full.fleet.mesh_condemned = False
        self._current = full
        self.degraded = False
        self.degraded_axes = set()
        self._healthy_degraded_rounds = 0
        self._reset_pending = True
        self._probation_left = self.probation_rounds
        self.dead_devices = ()
        if telemetry.enabled():
            telemetry.counter(
                "mesh_readmit_total",
                "full-mesh re-admissions after degraded service").inc()
        telemetry.journal_event(
            "mesh.readmit",
            mesh_shape=[int(self.grid.shape[0]),
                        int(self.grid.shape[1])],
            probation_rounds=self.probation_rounds)
        self._export_gauges()
        logger.warning(
            "full %dx%d grid re-admitted on probation (%d rounds); "
            "lost lanes and branches re-enter with fresh warm starts",
            self.grid.shape[0], self.grid.shape[1],
            self.probation_rounds)

    # -- actuation ------------------------------------------------------------

    def actuated_u0(self, state) -> jnp.ndarray:
        """The robust controls to actuate, BASE layout (n_agents, S,
        n_u): the non-anticipativity projection's first-interval rows.
        Lost branches report their stage-0 node group's surviving
        projection (group-identical by construction extends to the
        members that are not being solved); a dead branch whose ENTIRE
        stage-0 group was lost has no surviving projection and reports
        NaN — no data is honest data, a stale pre-loss iterate is not
        (the caller's guard ladder owns a NaN command)."""
        if self._flat is not None:
            raise NotImplementedError(
                "the S=1 delegate has no non-anticipativity "
                "projection — read u0 from the flat round's "
                "trajectories, like FleetSupervisor")
        if not self.tree.robust_horizon:
            u = jax.vmap(jax.vmap(
                lambda w: self.base_group.ocp.unflatten(w)["u"]))(
                state.w)
            return u[:, :, 0, :]
        u0 = state.na_target[:, :, 0, :]
        if not self.dead_branches:
            return u0
        u0 = np.asarray(u0).copy()
        alive = [s for s in range(self.S)
                 if s not in self.dead_branches]
        groups0 = self.tree.groups_at(0)
        for s in sorted(self.dead_branches):
            grp = next((g for g in groups0 if s in g), None)
            donor = next((m for m in (grp or ()) if m in alive), None)
            u0[:, s] = u0[:, donor] if donor is not None else np.nan
        return jnp.asarray(u0)

    # -- operator / gate hooks ------------------------------------------------

    def force_degrade(self, dead_device_ids,
                      axis: "str | None" = None) -> str:
        """Operator/gate entry: degrade as if ``dead_device_ids`` had
        failed a probe. ``axis`` overrides the classification policy
        for this call. Returns the degraded axis."""
        if self._flat is not None:
            self._flat.force_degrade(dead_device_ids)
            return "agents"
        alive = tuple(d for d in self._current_ids()
                      if d not in set(dead_device_ids))
        return self._degrade(multihost.ShardProbeReport(
            answered=alive, dead=tuple(dead_device_ids),
            latency_s={}), forced_axis=axis)

    def _current_ids(self) -> tuple:
        layout = self._current
        return tuple(self.grid_ids[np.ix_(layout.rows,
                                          layout.cols)].flat)

    def force_readmit(self) -> None:
        """Operator/gate entry: reshard back to the full grid now,
        bypassing the hysteresis clock."""
        if self._flat is not None:
            self._flat.force_readmit()
            return
        needed = max(self._readmit_needed[ax]
                     for ax in self.degraded_axes) \
            if self.degraded_axes else self.readmit_after
        self._healthy_degraded_rounds = needed
        probe, self._probe = self._probe, lambda m: \
            multihost.ShardProbeReport(
                answered=tuple(d.id for d in m.devices.flat),
                dead=(), latency_s={})
        try:
            self._maybe_readmit()
        finally:
            self._probe = probe

    def _export_gauges(self) -> None:
        if telemetry.enabled():
            telemetry.gauge(
                "mesh_devices_active",
                "devices in the mesh currently serving the fleet").set(
                float(len(self._current.rows)
                      * len(self._current.cols)))
            telemetry.gauge(
                "scenario_branches_active",
                "disturbance branches currently solved by the "
                "scenario supervisor (base count minus dead "
                "branches)").set(
                float(self.S - len(self.dead_branches)))

    def stats(self) -> dict:
        if self._flat is not None:
            out = self._flat.stats()
            out["degraded_axes"] = []
            out["scenarios_active"] = 1
            return out
        return {
            "devices_full": len(self._full_ids),
            "devices_active": len(self._current.rows)
            * len(self._current.cols),
            "mesh_shape": self.mesh_shape,
            "degraded": self.degraded,
            "degraded_axes": sorted(self.degraded_axes),
            "dead_devices": list(self.dead_devices),
            "dead_lanes": int(self.dead_lanes.sum()),
            "dead_branches": sorted(self.dead_branches),
            "scenarios_active": self.S - len(self.dead_branches),
            "rounds": self.rounds,
            "degraded_rounds": self.degraded_rounds,
            "layouts_built": len(self._layouts),
            "last_mttr_s": self.last_mttr_s,
            "mttr_by_axis": dict(self.mttr_by_axis),
            "probation_left": self._probation_left,
            "collective_schedule_digest":
                self._current.fleet.collective_schedule_digest,
        }
