"""Tests for the batched LDLᵀ KKT solver (``ops/kkt.py``).

Covers: the pure-JAX recursion, the vmap-transparent custom_vmap wrappers,
the Pallas kernels in interpreter mode (the TPU path, executed on CPU), and
end-to-end agreement of the interior-point solver between the pivoted-LU
and pivot-free-LDLᵀ KKT backends.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.ops import kkt


def _quasi_definite_batch(B, n, m, seed=0, dtype=jnp.float32):
    """Random interior-point-shaped KKT matrices [[W, Jgᵀ], [Jg, -δI]]."""
    rng = np.random.default_rng(seed)
    Ks, rhss = [], []
    for _ in range(B):
        A = rng.normal(size=(n, n))
        W = A @ A.T + 3 * np.eye(n)
        Jg = rng.normal(size=(m, n))
        K = np.block([[W, Jg.T], [Jg, -1e-6 * np.eye(m)]])
        Ks.append(K)
        rhss.append(rng.normal(size=n + m))
    return (jnp.asarray(np.stack(Ks), dtype=dtype),
            jnp.asarray(np.stack(rhss), dtype=dtype))


def _residual(K, x, rhs):
    return float(jnp.max(jnp.abs(jnp.einsum("...ij,...j->...i", K, x) - rhs)))


def test_ldl_ref_single():
    K, rhs = _quasi_definite_batch(1, 13, 5)
    LD = kkt.ldl_factor_ref(K[0])
    x = kkt.ldl_solve_ref(LD, rhs[0])
    assert _residual(K[0], x, rhs[0]) < 1e-3


def test_ldl_custom_vmap_batched():
    K, rhs = _quasi_definite_batch(6, 11, 4, seed=1)
    xs = jax.vmap(lambda k, b: kkt.ldl_solve(kkt.ldl_factor(k), b))(K, rhs)
    assert _residual(K, xs, rhs) < 1e-3


def test_solve_kkt_ldl_refinement_accuracy():
    K, rhs = _quasi_definite_batch(4, 17, 6, seed=2)
    xs = jax.vmap(kkt.solve_kkt_ldl)(K, rhs)
    assert _residual(K, xs, rhs) < 1e-4


def test_pallas_interpret_matches_ref():
    """The exact TPU kernel code path, run through the Pallas interpreter."""
    K, rhs = _quasi_definite_batch(5, 13, 5, seed=3)
    LD = kkt._ldl_factor_batched(K, interpret=True)
    x = kkt._ldl_solve_batched(LD, rhs, interpret=True)
    assert _residual(K, x, rhs) < 1e-3
    LD_ref = jax.vmap(kkt.ldl_factor_ref)(K)
    np.testing.assert_allclose(np.asarray(LD), np.asarray(LD_ref),
                               rtol=1e-4, atol=1e-5)


def test_pallas_interpret_padding_lanes_and_rows():
    """Batch not a multiple of 128 and M not a multiple of 8 both pad."""
    K, rhs = _quasi_definite_batch(3, 7, 3, seed=4)   # M = 10
    LD = kkt._ldl_factor_batched(K, interpret=True)
    x = kkt._ldl_solve_batched(LD, rhs, interpret=True)
    assert _residual(K, x, rhs) < 1e-3


def test_indefinite_matrix_yields_finite_or_rejectable():
    """A genuinely indefinite (not quasi-definite) matrix may produce a bad
    factor — but never silently: the solve either stays finite or returns
    non-finite values the solver's finite-merit check rejects."""
    K = jnp.asarray(np.diag([1.0, -1.0, 0.0, 2.0]), dtype=jnp.float32)
    rhs = jnp.ones((4,), jnp.float32)
    x = kkt.ldl_solve_ref(kkt.ldl_factor_ref(K), rhs)
    assert x.shape == (4,)  # no crash; NaN/Inf acceptable here


@pytest.mark.parametrize("method", ["lu", "ldl"])
def test_solver_end_to_end_kkt_methods_agree(method):
    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
    from agentlib_mpc_tpu.ops.transcription import transcribe

    model = OneRoom(overrides={"s_T": 0.001, "r_mDot": 0.01})
    ocp = transcribe(model, ["mDot"], N=5, dt=300.0,
                     method="collocation", collocation_degree=2)
    theta = ocp.default_params(x0=jnp.array([297.5]))
    lb, ub = ocp.bounds(theta)
    res = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                    SolverOptions(tol=1e-6, max_iter=60, kkt_method=method))
    assert bool(res.stats.success)
    test_solver_end_to_end_kkt_methods_agree.obj = getattr(
        test_solver_end_to_end_kkt_methods_agree, "obj", {})
    test_solver_end_to_end_kkt_methods_agree.obj[method] = float(
        res.stats.objective)
    objs = test_solver_end_to_end_kkt_methods_agree.obj
    if len(objs) == 2:
        assert abs(objs["lu"] - objs["ldl"]) <= 1e-4 * (
            1.0 + abs(objs["lu"]))


def test_kkt_method_probe_cpu_falls_back():
    """On non-TPU backends the auto path must select LU (probe False),
    and the probe result is cached."""
    assert kkt.kkt_method_available() is False
    assert kkt._PROBE_RESULT.get(("cpu", 8)) is False
    # cached second call, and a size-specific probe caches its own key
    assert kkt.kkt_method_available() is False
    assert kkt.kkt_method_available(92) is False
    assert kkt._PROBE_RESULT.get(("cpu", 96)) is False


def test_pallas_interpret_production_size():
    """The exact TPU kernel at the PRODUCTION tile shape: the 256-zone
    benchmark factors 92-dim KKT systems, padding to (96, 96, 128) — the
    same padded shape the size-aware availability probe compiles on real
    hardware. Raw-kernel residual (no equilibration/refinement) must
    already be small."""
    K, rhs = _quasi_definite_batch(2, 61, 31, seed=9)
    LD = kkt._ldl_factor_batched(K, interpret=True)
    x = kkt._ldl_solve_batched(LD, rhs, interpret=True)
    assert _residual(K, x, rhs) < 1e-2
    # and through the full solve path (equilibration + refinement)
    x2 = jax.vmap(kkt.solve_kkt_ldl)(K, rhs)
    assert _residual(K, x2, rhs) < 1e-3
