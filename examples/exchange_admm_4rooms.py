"""4-zone decentralized exchange-ADMM: rooms and a supplier balance air flow.

Native re-design of the reference's exchange-ADMM benchmark
(``examples/exchange_admm/admm_4rooms_main.py``): four zones each request
air (``mDot_out = +mDot``) and one supplier produces it
(``mDot_net = -mDot``); all five agents exchange on one shared alias, and
the exchange-ADMM mean-zero condition enforces supply = total consumption
without any coordinator (fully decentralized, peer-to-peer broadcasts).

This is one of the four BASELINE.md benchmark configs. Run directly for a
report, or call ``run_example`` (examples-as-tests, SURVEY.md §4).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types
from agentlib_mpc_tpu.models.zoo import AirSupplier, ExchangeRoom
from agentlib_mpc_tpu.runtime.mas import LocalMAS

N_ROOMS = 4
TIME_STEP = 300.0
HORIZON = 8
UB = 295.15
START_TEMP = 298.16
LOADS = (80.0, 110.0, 140.0, 170.0)
EXCHANGE_ALIAS = "air_balance"


def _backend(model_cls):
    return {
        "type": "jax_admm",
        "model": {"class": model_cls},
        "discretization_options": {"collocation_order": 2,
                                   "collocation_method": "legendre"},
        "solver": {"max_iter": 60},
    }


def agent_configs(max_iterations: int = 12, penalty_factor: float = 50.0):
    rooms = []
    sims = []
    for i in range(1, N_ROOMS + 1):
        rooms.append({
            "id": f"Room_{i}",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "admm", "type": "admm_local",
                 "optimization_backend": _backend(ExchangeRoom),
                 "time_step": TIME_STEP,
                 "prediction_horizon": HORIZON,
                 "max_iterations": max_iterations,
                 "penalty_factor": penalty_factor,
                 "parameters": [{"name": "s_T", "value": 1.0}],
                 "inputs": [
                     {"name": "load", "value": LOADS[i - 1]},
                     {"name": "T_in", "value": 290.15},
                     {"name": "T_upper", "value": UB},
                 ],
                 "states": [
                     {"name": "T", "value": START_TEMP, "ub": 303.15,
                      "lb": 288.15, "alias": f"T_{i}",
                      "source": f"Simulation_{i}"},
                 ],
                 "controls": [
                     {"name": "mDot", "value": 0.02, "ub": 0.05,
                      "lb": 0.0, "alias": f"mDot_{i}"},
                 ],
                 "exchange": [
                     {"name": "mDot_out", "alias": EXCHANGE_ALIAS,
                      "value": 0.02, "ub": 0.05, "lb": 0.0},
                 ]},
            ],
        })
        sims.append({
            "id": f"Simulation_{i}",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "simulator", "type": "simulator",
                 "model": {"class": ExchangeRoom,
                           "states": [{"name": "T", "value": START_TEMP}],
                           "inputs": [{"name": "load",
                                       "value": LOADS[i - 1]}]},
                 "t_sample": 60,
                 "outputs": [{"name": "T_out", "value": START_TEMP,
                              "alias": f"T_{i}"}],
                 "inputs": [{"name": "mDot", "value": 0.02,
                             "alias": f"mDot_{i}"}]},
            ],
        })

    supplier = {
        "id": "Supplier",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "admm", "type": "admm_local",
             "optimization_backend": _backend(AirSupplier),
             "time_step": TIME_STEP,
             "prediction_horizon": HORIZON,
             "max_iterations": max_iterations,
             "penalty_factor": penalty_factor,
             "parameters": [{"name": "r_mDot", "value": 1.0}],
             "controls": [
                 {"name": "mDot", "value": 0.08, "ub": 0.2, "lb": 0.0,
                  "alias": "mDot_supply"},
             ],
             "exchange": [
                 {"name": "mDot_net", "alias": EXCHANGE_ALIAS,
                  "value": -0.08, "ub": 0.0, "lb": -0.2},
             ]},
        ],
    }
    return [*rooms, supplier, *sims]


def run_example(until: float = 3600.0, testing: bool = False,
                verbose: bool = True) -> dict:
    mas = LocalMAS(agent_configs(), env={"rt": False})
    mas.run(until=until)
    results = mas.get_results()

    temps = {}
    flows = {}
    for i in range(1, N_ROOMS + 1):
        sim_df = results[f"Simulation_{i}"]["simulator"]
        temps[i] = np.asarray(sim_df["T_out"], dtype=float)
        flows[i] = np.asarray(sim_df["mDot"], dtype=float)
    total_consumption = sum(flows.values())

    supplier_mod = mas.agents["Supplier"].get_module("admm")
    supply = float(supplier_mod.vars["mDot"].value)

    if verbose:
        for i in range(1, N_ROOMS + 1):
            print(f"room {i}: {temps[i][0]:.2f} K -> {temps[i][-1]:.2f} K "
                  f"(load {LOADS[i - 1]:.0f} W, "
                  f"mean flow {np.mean(flows[i]):.4f})")
        print(f"final supplier flow {supply:.4f}, "
              f"final total consumption {total_consumption[-1]:.4f}")

    if testing:
        mean_start = np.mean([temps[i][0] for i in range(1, N_ROOMS + 1)])
        mean_end = np.mean([temps[i][-1] for i in range(1, N_ROOMS + 1)])
        assert mean_end < mean_start, "building must cool on average"
        # exchange balance: supplier production tracks total consumption
        assert abs(supply - total_consumption[-1]) < 0.02, (
            f"supply {supply:.4f} vs consumption "
            f"{total_consumption[-1]:.4f}")
        # higher-load rooms draw more air
        assert np.mean(flows[N_ROOMS]) > np.mean(flows[1])
    return results


if __name__ == "__main__":
    run_example(until=3600.0, testing=True)
