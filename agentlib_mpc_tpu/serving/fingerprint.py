"""Tenant specs and the structural-fingerprint bucket key.

A tenant is one MPC problem instance: a transcribed OCP plus its
parameter values, coupling layout and solver configuration. Two tenants
belong to the same *bucket* — and may share one compiled fused engine —
exactly when everything that shapes the executable is equal:

* the :class:`~agentlib_mpc_tpu.lint.jaxpr.StructuralFingerprint` of the
  OCP's NLP (jaxpr digests: same computation graph up to parameter
  values; certificates: same proved routing facts),
* the horizon / shape bucket (``bucket_agents`` groups by shape today;
  the fingerprint subsumes its ``id(ocp)`` key with a *structural* one,
  so a separately re-transcribed but identical OCP still buckets),
* the coupling/exchange alias layout,
* the (cold and warm) solver options and QP-fast-path mode.

Parameter VALUES (theta) never enter the key — they are the vmapped
axis.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

from agentlib_mpc_tpu.ops.solver import SolverOptions

#: per-OCP-object memo of the (expensive: certifier passes + traces)
#: structural fingerprint — keyed by object identity like
#: ``bucket_agents``, holding ``id(ocp) -> (ocp, fingerprint)`` (the
#: ocp reference keeps the id stable for the cache's lifetime). The
#: VALUE is structural, so two distinct OCP objects with identical
#: structure produce EQUAL fingerprints — but each distinct OBJECT pays
#: the certifier once; transcribe once per model class (the
#: ``bucket_agents`` contract) to keep this cache one entry per
#: structure instead of one per tenant
_FP_MEMO: dict = {}


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's problem definition, as handed to
    :meth:`~agentlib_mpc_tpu.serving.plane.ServingPlane.join`.

    ``theta`` is the tenant's parameter pytree (one agent row, NOT
    batched); ``couplings``/``exchanges`` map global aliases to this
    model's control names exactly like
    :class:`~agentlib_mpc_tpu.parallel.fused_admm.AgentGroup`.
    ``deadline_s`` is the tenant's per-request service deadline for the
    admission queue (None: the plane default applies).
    """

    tenant_id: str
    ocp: object                  # TranscribedOCP
    theta: object                # OCPParams
    couplings: dict = dataclasses.field(default_factory=dict)
    exchanges: dict = dataclasses.field(default_factory=dict)
    solver_options: SolverOptions = SolverOptions()
    warm_solver_options: "SolverOptions | None" = None
    qp_fast_path: str = "auto"
    deadline_s: "float | None" = None
    #: robust tenant (ISSUE 14): a hashable
    #: :class:`~agentlib_mpc_tpu.scenario.tree.ScenarioTree` lifts this
    #: tenant into a SCENARIO bucket — its lane solves S disturbance
    #: branches per round on a :class:`~agentlib_mpc_tpu.scenario.
    #: fleet.ScenarioFleet` engine, and ``theta`` must carry the
    #: (S, ...)-leading per-branch parameter stack
    #: (``scenario.generate`` builds it). Tree identity enters the
    #: bucket key: different trees are different compiled programs.
    #: The degenerate single-scenario tree normalizes into the FLAT
    #: bucket (theta's branch axis squeezed at join) — the S=1 path
    #: must never fork a second program for the same problem.
    scenario_tree: "object | None" = None
    #: robust-round knobs (a hashable ``ScenarioFleetOptions``); None =
    #: the fleet defaults. Ignored without ``scenario_tree``.
    scenario_options: "object | None" = None


class BucketKey(NamedTuple):
    """Hashable engine-bucket identity (everything but capacity — the
    :class:`~agentlib_mpc_tpu.serving.cache.CompileCache` key adds the
    padded slot count and the engine options on top)."""

    structure_digest: str
    horizon: int
    couplings: tuple         # sorted (alias, control) pairs
    exchanges: tuple
    solver_options: SolverOptions
    warm_solver_options: "SolverOptions | None"
    qp_fast_path: str
    #: scenario-tree identity (ISSUE 14): a robust bucket's engine is
    #: a ScenarioFleet compiled FOR this tree — branch count, node
    #: groups and probabilities are all baked into the traced round,
    #: so tenants bucket together exactly when their trees are equal.
    #: None = flat bucket (including the normalized S=1 degenerate)
    scenario_tree: "object | None" = None
    scenario_options: "object | None" = None

    @property
    def digest(self) -> str:
        import hashlib

        return hashlib.sha256(repr(self).encode()).hexdigest()[:12]


def tenant_fingerprint(ocp):
    """The memoized structural fingerprint of one transcribed OCP.

    First call per OCP *object* pays the certifier (seconds); every
    later call on the same object is a lookup. A DIFFERENT object of
    identical structure pays the certifier once too (equality of
    structure cannot be known without computing its fingerprint) and
    then fingerprints EQUAL — so it still lands in the same serving
    bucket; transcribe once per model class to avoid the repeated
    certification cost. Returns a
    :class:`~agentlib_mpc_tpu.lint.jaxpr.StructuralFingerprint`.
    """
    entry = _FP_MEMO.get(id(ocp))
    if entry is None:
        from agentlib_mpc_tpu.lint.jaxpr import structural_fingerprint

        fp = structural_fingerprint(
            ocp.nlp, ocp.default_params(), ocp.n_w,
            getattr(ocp, "stage_partition", None))
        # hold the ocp alongside its fingerprint: the id() key is only
        # collision-free while the object lives
        entry = _FP_MEMO[id(ocp)] = (ocp, fp)
    return entry[1]


def bucket_key(spec: TenantSpec) -> BucketKey:
    """Bucket identity of one tenant spec (see module docstring)."""
    fp = tenant_fingerprint(spec.ocp)
    tree = spec.scenario_tree
    if tree is not None and tree.n_scenarios == 1:
        # degenerate contract: the single-scenario tree IS the flat
        # problem — it must land in the flat bucket, not fork a
        # second compiled program for the same structure
        tree = None
    return BucketKey(
        structure_digest=fp.digest,
        horizon=int(spec.ocp.N),
        couplings=tuple(sorted(spec.couplings.items())),
        exchanges=tuple(sorted(spec.exchanges.items())),
        solver_options=spec.solver_options,
        warm_solver_options=spec.warm_solver_options,
        qp_fast_path=spec.qp_fast_path,
        scenario_tree=tree,
        scenario_options=(spec.scenario_options if tree is not None
                          else None),
    )
