from agentlib_mpc_tpu.ops.collocation import collocation_matrices
from agentlib_mpc_tpu.ops.transcription import (
    OCPParams,
    TranscribedOCP,
    transcribe,
)
from agentlib_mpc_tpu.ops.solver import NLPFunctions, SolverOptions, solve_nlp
