"""The SLO autopilot: a feedback controller that SPENDS the error budget.

Everything below this module *measures* or *reacts*: the SLO tracker
(ISSUE 15) knows each tenant's multi-window burn rate, the health
ladder evicts sick tenants, the watchdog condemns hung rounds — but
nothing trades quality for survival on purpose. Under a demand spike a
tenant burns to SLO breach at full solution quality because no
component is allowed to decide "a cheaper round that actuates beats a
perfect round that misses its deadline". This controller is that
component: wired as ``ServingPlane(autopilot=AutopilotPolicy(...))``,
it reads the tracker's fast-window burn rate every ``serve_round`` and
walks each tenant up and down a **quality ladder**:

====  ==================  =================================================
level lever               mechanism
====  ==================  =================================================
L1    ``warm_iters``      cap the warm interior-point iteration budget
                          (``warm_solver_options`` — a bucket-key field,
                          so the move re-buckets through the compile
                          cache: a cache hit after first use, never a
                          cold build per move)
L2    ``deadline``        relax the tenant's admission deadline by
                          ``l2_deadline_factor`` (host-side: deadlines
                          never enter the bucket key) — wider coalescing,
                          fewer deadline sheds
L3    ``scenario_subtree``shrink a robust tenant's scenario tree to its
                          highest-probability branches
                          (``ScenarioTree.subtree`` + probability
                          renormalization, the ISSUE 14 degrade applied
                          by *choice*), theta rows sliced to match —
                          again a re-bucket through the cache
L4    ``mesh_predegrade`` pre-emptively degrade the device mesh to a
                          smaller cached layout (``mesh_degrade_hook``,
                          e.g. ``FleetSupervisor.force_degrade``) before
                          the watchdog condemns it; latched fleet-wide
====  ==================  =================================================

and spends budget *back* — restores iteration budgets, deadlines,
trees, the mesh — when burn recedes.

Hysteresis is the health ladder's discipline (PR 8), not a new one:
``degrade_after`` consecutive hot rounds (fast-window burn above
``burn_threshold``) per down-move, ``restore_after`` consecutive cool
rounds (burn at or below ``restore_threshold``) per up-move, a dead
band between the two thresholds in which streaks reset, and
``probation_rounds`` after every up-move during which ONE hot round
re-degrades immediately — the controller can never flap a tenant
between quality levels on alternating rounds.

Every move journals as a typed ``autopilot.move`` event (level from/to,
direction, lever, the trigger burn + window) so ``--incident`` reports
render *policy* actions beside *fault* reactions; ladder positions and
hysteresis counters ride the plane checkpoint (a crash restart resumes
mid-incident at the same quality level, with the same effective specs,
asserted by the restore digest check). Gauges/counters:
``autopilot_level{tenant}``, ``autopilot_moves_total{direction,lever}``
and ``error_budget_spent_by_policy`` (unavailable results delivered
while the controller held the tenant at reduced quality — the budget it
chose to spend).
"""

from __future__ import annotations

import dataclasses
import logging
import math

from agentlib_mpc_tpu import telemetry

logger = logging.getLogger(__name__)

__all__ = ["AutopilotPolicy", "SLOAutopilot", "LEVERS"]

#: lever per ladder level — the journal/metric label vocabulary; a move
#: between N-1 and N (either direction) is labelled with level N's lever
LEVERS = {1: "warm_iters", 2: "deadline", 3: "scenario_subtree",
          4: "mesh_predegrade"}


@dataclasses.dataclass(frozen=True)
class AutopilotPolicy:
    """Knobs of the quality ladder (plane config key ``autopilot``)."""

    #: fast-window burn rate above which a round counts HOT (1.0 =
    #: consuming exactly the budgeted miss rate)
    burn_threshold: float = 1.0
    #: fast-window burn rate at or below which a round counts COOL;
    #: the gap to ``burn_threshold`` is the hysteresis dead band
    restore_threshold: float = 0.25
    #: consecutive hot rounds per down-move
    degrade_after: int = 2
    #: consecutive cool rounds per up-move
    restore_after: int = 4
    #: rounds after an up-move during which ONE hot round re-degrades
    #: immediately (the health ladder's probation discipline)
    probation_rounds: int = 4
    #: deepest ladder level the controller may reach (L4 additionally
    #: requires a ``mesh_degrade_hook``)
    max_level: int = 4
    #: L1: warm interior-point iteration cap
    l1_warm_max_iter: int = 2
    #: L2: admission-deadline relaxation factor
    l2_deadline_factor: float = 4.0
    #: L3: fraction of scenario branches kept (highest-probability
    #: first; at least one always survives)
    l3_keep_fraction: float = 0.5

    def __post_init__(self):
        if self.burn_threshold <= 0:
            raise ValueError(f"burn_threshold must be > 0, got "
                             f"{self.burn_threshold}")
        if not (0.0 <= self.restore_threshold < self.burn_threshold):
            raise ValueError(
                f"need 0 <= restore_threshold < burn_threshold "
                f"(hysteresis dead band), got {self.restore_threshold} "
                f"/ {self.burn_threshold}")
        if min(self.degrade_after, self.restore_after,
               self.probation_rounds) < 1:
            raise ValueError("degrade_after, restore_after and "
                             "probation_rounds must all be >= 1")
        if not (1 <= int(self.max_level) <= 4):
            raise ValueError(f"max_level must sit in [1, 4], got "
                             f"{self.max_level}")
        if self.l1_warm_max_iter < 1:
            raise ValueError("l1_warm_max_iter must be >= 1")
        if self.l2_deadline_factor < 1.0:
            raise ValueError("l2_deadline_factor must be >= 1 (an "
                             "autopilot that TIGHTENS deadlines under "
                             "overload is an amplifier)")
        if not (0.0 < self.l3_keep_fraction <= 1.0):
            raise ValueError(f"l3_keep_fraction must sit in (0, 1], "
                             f"got {self.l3_keep_fraction}")

    @classmethod
    def from_config(cls, cfg: dict) -> "AutopilotPolicy":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown autopilot option(s) {sorted(unknown)}; "
                f"known: {sorted(known)}")
        return cls(**cfg)


@dataclasses.dataclass
class TenantLadder:
    """One tenant's ladder row (checkpointed verbatim)."""

    level: int = 0
    hot_streak: int = 0
    cool_streak: int = 0
    #: probation rounds remaining after the latest up-move
    probation: int = 0
    moves: int = 0


class SLOAutopilot:
    """The per-plane controller; owns the decisions, the plane executes
    them (``_rebucket_tenant``) — the health-ledger split, applied to
    quality instead of sickness."""

    def __init__(self, policy: AutopilotPolicy = AutopilotPolicy(),
                 mesh_degrade_hook=None, mesh_restore_hook=None):
        self.policy = policy
        #: L4 levers: zero-arg callables (e.g. bound
        #: ``FleetSupervisor.force_degrade(dead)`` / ``force_readmit``
        #: partials). Without a degrade hook the effective ladder tops
        #: out at L3 — the controller never pretends to pull a lever it
        #: does not hold.
        self.mesh_degrade_hook = mesh_degrade_hook
        self.mesh_restore_hook = mesh_restore_hook
        self._rows: "dict[str, TenantLadder]" = {}
        #: join-normalized ORIGINAL specs of tenants at level > 0 —
        #: every effective spec is derived from the original, never
        #: incrementally, so level k's spec (and bucket digest) is
        #: deterministic across live moves and checkpoint restores
        self._originals: dict = {}
        #: L4 is a fleet-wide latch: fired when the first tenant enters
        #: L4, released when the last one leaves it
        self._mesh_degraded = False

    # -- introspection --------------------------------------------------------

    def row(self, tenant_id: str) -> TenantLadder:
        return self._rows.setdefault(tenant_id, TenantLadder())

    def level(self, tenant_id: str) -> int:
        row = self._rows.get(tenant_id)
        return 0 if row is None else row.level

    @property
    def effective_max_level(self) -> int:
        if self.mesh_degrade_hook is None:
            return min(int(self.policy.max_level), 3)
        return int(self.policy.max_level)

    @property
    def mesh_degraded(self) -> bool:
        return self._mesh_degraded

    def report(self) -> dict:
        return {tid: dataclasses.asdict(row)
                for tid, row in sorted(self._rows.items())}

    # -- levers ---------------------------------------------------------------

    def relaxed_deadline(self, tenant_id: str,
                         deadline_s: "float | None") -> "float | None":
        """The L2 lever, applied by ``ServingPlane.submit`` to BOTH
        spec-default and explicitly supplied deadlines (an overload
        storm forcing tight deadlines must be counterable)."""
        if deadline_s is None or self.level(tenant_id) < 2:
            return deadline_s
        return float(deadline_s) * self.policy.l2_deadline_factor

    def effective_spec(self, spec, level: int):
        """The tenant spec at ladder ``level``, derived from the
        ORIGINAL (join-normalized) ``spec``. L1+ caps the warm solver
        budget; L3+ shrinks a robust tenant's tree to its
        highest-probability branches and slices theta rows to match.
        L2/L4 are host-side levers — no spec change. The caller
        re-normalizes (``_normalize_robust_spec``) so an L3 subtree
        that degenerates to one scenario squeezes into the flat
        bucket exactly like a join would."""
        if level <= 0:
            return spec
        changes: dict = {}
        base_warm = spec.warm_solver_options
        if base_warm is None:
            # the engine's own warm default (fused_admm: warm budget =
            # min(cold, 6)) — cap RELATIVE to what actually runs warm
            base_warm = spec.solver_options._replace(
                max_iter=min(spec.solver_options.max_iter, 6))
        changes["warm_solver_options"] = base_warm._replace(
            max_iter=min(base_warm.max_iter,
                         int(self.policy.l1_warm_max_iter)))
        tree = spec.scenario_tree
        if level >= 3 and tree is not None and tree.n_scenarios > 1:
            import jax
            import jax.numpy as jnp
            import numpy as np

            s = tree.n_scenarios
            n_keep = max(1, int(math.floor(
                s * self.policy.l3_keep_fraction)))
            if n_keep < s:
                order = sorted(range(s),
                               key=lambda i: (-tree.probabilities[i], i))
                keep = tuple(sorted(order[:n_keep]))
                idx = np.asarray(keep)
                changes["scenario_tree"] = tree.subtree(keep)
                changes["theta"] = jax.tree.map(
                    lambda leaf: jnp.asarray(leaf)[idx], spec.theta)
        return dataclasses.replace(spec, **changes)

    # -- the control loop -----------------------------------------------------

    def tick(self, plane, tally: "dict | None" = None) -> None:
        """One controller step, called by ``serve_round`` right after
        the SLO windows advance. Reads the FAST window's burn per
        tenant; no-traffic rounds (burn None) are neutral — they move
        neither streak."""
        pol = self.policy
        fast = min(int(w) for w in plane.slo.policy.windows)
        burns = plane.slo.burn_rates()
        for tid in list(plane._tenant_bucket):
            row = self.row(tid)
            burn = (burns.get(tid) or {}).get(fast)
            if burn is None:
                continue
            if burn > pol.burn_threshold:
                row.cool_streak = 0
                row.hot_streak += 1
                forced = row.probation > 0
                if (forced or row.hot_streak >= pol.degrade_after) \
                        and row.level < self.effective_max_level:
                    if self._move(plane, tid, row, row.level + 1,
                                  window=fast, burn=burn,
                                  threshold=pol.burn_threshold,
                                  probation_strike=forced):
                        row.hot_streak = 0
                        row.probation = 0
            elif burn <= pol.restore_threshold:
                row.hot_streak = 0
                if row.probation > 0:
                    row.probation -= 1
                if row.level > 0:
                    row.cool_streak += 1
                    if row.cool_streak >= pol.restore_after:
                        if self._move(plane, tid, row, row.level - 1,
                                      window=fast, burn=burn,
                                      threshold=pol.restore_threshold):
                            row.cool_streak = 0
                            row.probation = pol.probation_rounds
            else:
                # the dead band: neither hot nor cool — both streaks
                # reset, which is exactly what forbids flapping on a
                # burn rate oscillating around one threshold
                row.hot_streak = 0
                row.cool_streak = 0
        if tally:
            self._account_spend(tally)

    def force_level(self, plane, tenant_id: str, level: int) -> bool:
        """Walk a tenant to ``level`` one rung at a time, journaling
        each move with ``trigger="forced"`` — operator intervention and
        the ``[serving.autopilot]`` retrace gate."""
        row = self.row(tenant_id)
        level = max(0, min(int(level), self.effective_max_level))
        while row.level != level:
            step = row.level + (1 if level > row.level else -1)
            if not self._move(plane, tenant_id, row, step, forced=True):
                return False
        return True

    def _move(self, plane, tenant_id: str, row: TenantLadder,
              new_level: int, window: "int | None" = None,
              burn: "float | None" = None,
              threshold: "float | None" = None, forced: bool = False,
              probation_strike: bool = False) -> bool:
        new_level = max(0, min(int(new_level), self.effective_max_level))
        old_level = row.level
        if new_level == old_level:
            return True
        direction = "down" if new_level > old_level else "up"
        lever = LEVERS[max(new_level, old_level)]
        orig = self._originals.get(tenant_id)
        if orig is None:
            orig = self._originals[tenant_id] = \
                plane._specs[tenant_id]
        if not plane._rebucket_tenant(
                tenant_id, self.effective_spec(orig, new_level)):
            # the memory certificate refused the target bucket — hold
            # the current level (a quality move must never OOM a round)
            logger.warning(
                "autopilot: %s move for tenant %s (L%d -> L%d) refused "
                "by the memory certificate — holding L%d", direction,
                tenant_id, old_level, new_level, old_level)
            return False
        if new_level >= 4 and not self._mesh_degraded:
            self._fire_mesh_hook(self.mesh_degrade_hook, "degrade")
            self._mesh_degraded = True
        elif old_level >= 4 > new_level and self._mesh_degraded \
                and not any(r.level >= 4
                            for t, r in self._rows.items()
                            if t != tenant_id):
            self._fire_mesh_hook(self.mesh_restore_hook, "restore")
            self._mesh_degraded = False
        row.level = new_level
        row.moves += 1
        if new_level == 0:
            # back at full quality: the live spec IS the original again
            self._originals.pop(tenant_id, None)
        key = plane._tenant_bucket.get(tenant_id)
        telemetry.journal_event(
            "autopilot.move", tenant=tenant_id, level_from=old_level,
            level_to=new_level, direction=direction, lever=lever,
            trigger="forced" if forced else "burn",
            window=window, burn=None if burn is None else round(burn, 3),
            threshold=threshold, probation_strike=bool(probation_strike),
            bucket=key.digest if key is not None else None)
        if telemetry.enabled():
            telemetry.counter(
                "autopilot_moves_total",
                "quality-ladder moves executed by the SLO autopilot"
                ).inc(direction=direction, lever=lever)
            telemetry.gauge(
                "autopilot_level",
                "per-tenant quality-ladder position (0 = full quality, "
                "4 = mesh pre-degraded)").set(float(new_level),
                                              tenant=tenant_id)
        logger.log(
            logging.WARNING if direction == "down" else logging.INFO,
            "autopilot: tenant %s L%d -> L%d (%s, lever=%s%s)",
            tenant_id, old_level, new_level, direction, lever,
            "" if burn is None
            else f", burn={burn:.2f} over {window}-round window")
        return True

    def _fire_mesh_hook(self, hook, kind: str) -> None:
        if hook is None:
            return
        try:
            hook()
        except Exception:  # noqa: BLE001 — a failed lever must not
            # fail the round; the watchdog path still backstops it
            logger.warning("autopilot: mesh %s hook failed", kind,
                           exc_info=True)

    def _account_spend(self, tally: dict) -> None:
        """Budget spent BY POLICY this round: unavailable results
        delivered while the controller held the tenant below full
        quality — the deliberate part of the burn."""
        spent = 0
        for tid, counts in tally.items():
            row = self._rows.get(tid)
            if row is None or row.level <= 0:
                continue
            spent += max(0, int(counts[0]) - int(counts[1]))
        if spent and telemetry.enabled():
            telemetry.counter(
                "error_budget_spent_by_policy",
                "unavailable results delivered while the autopilot "
                "held the tenant at reduced quality (error budget "
                "spent deliberately)").inc(float(spent))

    # -- checkpoint seam ------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able controller state for the plane checkpoint: ladder
        positions AND hysteresis counters — a restore that forgot the
        streaks would up-move (re-grow trees, re-trace nothing but
        re-warm everything) on the first cool round mid-incident."""
        return {
            "mesh_degraded": bool(self._mesh_degraded),
            "tenants": {tid: dataclasses.asdict(row)
                        for tid, row in self._rows.items()},
        }

    def restore(self, snap: "dict | None") -> None:
        """Counters only — spec transforms are
        :meth:`transform_specs`'s job (restore_plane calls both). The
        mesh latch restores as a FLAG: the hook is not re-fired (the
        supervisor owns its own checkpoint; firing a degrade against
        an already-degraded mesh would double-count)."""
        if not snap:
            return
        self._mesh_degraded = bool(snap.get("mesh_degraded"))
        for tid, row in (snap.get("tenants") or {}).items():
            self._rows[tid] = TenantLadder(**row)
            if telemetry.enabled():
                telemetry.gauge(
                    "autopilot_level",
                    "per-tenant quality-ladder position (0 = full "
                    "quality, 4 = mesh pre-degraded)").set(
                    float(self._rows[tid].level), tenant=tid)

    def transform_specs(self, plane, specs: dict) -> dict:
        """Apply restored ladder levels to the caller's (normalized,
        ORIGINAL) specs so the restore's digest matching sees the same
        effective buckets the checkpoint recorded. Registers the
        originals for later up-moves."""
        out = dict(specs)
        for tid, row in self._rows.items():
            if row.level <= 0 or tid not in out:
                continue
            orig = out[tid]
            self._originals[tid] = orig
            out[tid] = plane._normalize_robust_spec(
                self.effective_spec(orig, row.level))
        return out
