"""Serving survivability: durable plane state, fault isolation, watchdog.

The PR 8 contracts (ISSUE 8 / docs/serving.md "Surviving failures"):

* **crash/restart** — a multi-bucket plane checkpoints, tears down and
  restores into a fresh plane with every tenant's restore a
  compile-cache hit (0 cold builds) and the warm-start state restored
  bitwise; a corrupted checkpoint is rejected loudly, never restored;
* **fault isolation** — a persistently NaN-ing tenant walks
  quarantine → eviction within the configured window, its bucket's
  other tenants' solutions stay bitwise-unaffected vs a no-chaos run,
  and it re-admits cleanly on probation after the fault window (zero
  retraces: the ``[serving.health]`` budget gate);
* **watchdog** — a chaos-stalled in-flight round times out, affected
  tenants shed into their guard ladders (no exception escapes
  ``serve_round``), and the dispatcher serves subsequent rounds in
  sync mode.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
from agentlib_mpc_tpu.resilience.chaos import (
    ServeChaosConfig,
    ServeNaNStormRule,
    ServeStallRule,
    corrupt_checkpoint,
    install_serving_chaos,
)
from agentlib_mpc_tpu.serving import (
    HealthPolicy,
    ServingPlane,
    TenantSpec,
    has_plane_checkpoint,
)
from agentlib_mpc_tpu.serving.health import (
    EVICTED,
    HEALTHY,
    PROBATION,
    QUARANTINED,
    HealthLedger,
)

ADMM_OPTS = FusedADMMOptions(max_iterations=6, rho=2.0)

#: module-shared engine cache: every test plane draws from it, so each
#: unique bucket structure pays its cold build once per test module
#: (sharing a cache across planes is exactly the supervisor-restart
#: model the crash tests exercise)
_CACHE = None


@pytest.fixture(scope="module")
def ocp():
    return tracker_ocp()


def make_spec(ocp, tid, a, max_iter=30, couplings=None):
    return TenantSpec(
        tenant_id=tid, ocp=ocp,
        theta=ocp.default_params(p=jnp.array([float(a)])),
        couplings={"shared_u": "u"} if couplings is None else couplings,
        solver_options=SolverOptions(max_iter=max_iter))


def make_plane(**kw):
    global _CACHE
    from agentlib_mpc_tpu.serving import CompileCache

    if _CACHE is None:
        _CACHE = CompileCache()
    kw.setdefault("cache", _CACHE)
    kw.setdefault("slot_multiple", 1)
    kw.setdefault("initial_capacity", 4)
    kw.setdefault("pipelined", False)
    kw.setdefault("donate", False)
    return ServingPlane(ADMM_OPTS, **kw)


def state_arrays(plane):
    return {
        key.digest: jax.tree.map(np.asarray, bucket.state)
        for key, bucket in plane._buckets.items()
    }


class TestCrashRestart:
    """Acceptance: >=4 tenants across >=2 buckets round-trip through a
    checkpoint with zero cold builds and bitwise warm starts."""

    @pytest.fixture(scope="class")
    def saved(self, ocp, tmp_path_factory):
        plane = make_plane()
        # two structure buckets: max_iter 30 vs 31 shape two distinct
        # executables over the same OCP
        specs = {tid: make_spec(ocp, tid, a, max_iter=mi)
                 for tid, a, mi in [("a", 1.0, 30), ("b", 3.0, 30),
                                    ("c", 2.0, 31), ("d", -1.0, 31)]}
        for spec in specs.values():
            plane.join(spec)
        for _ in range(2):
            for tid in plane.tenants:
                plane.submit(tid)
            plane.serve_round()
        plane.submit("a")             # queue carryover
        path = str(tmp_path_factory.mktemp("ckpt") / "plane")
        plane.save_checkpoint(path)
        return {"plane": plane, "specs": specs, "path": path,
                "states": state_arrays(plane),
                "slots": {k.digest: list(b.slots)
                          for k, b in plane._buckets.items()}}

    def test_restore_is_all_cache_hits_with_bitwise_state(self, saved):
        assert has_plane_checkpoint(saved["path"])
        # "torn down": the fresh plane only shares the compile cache
        # (the supervisor-restart model; cross-process the persistent
        # XLA cache plays this role)
        fresh = make_plane(cache=saved["plane"].cache)
        report = fresh.restore_checkpoint(saved["path"], saved["specs"])
        assert sorted(report.tenants) == ["a", "b", "c", "d"]
        assert report.buckets == 2
        assert report.cold_builds == 0          # the acceptance bar
        assert report.cache_hits == 4           # one reuse per tenant
        assert report.requeued == 1
        assert report.total_s > 0
        assert set(report.per_tenant_s) == {"a", "b", "c", "d"}
        for key, bucket in fresh._buckets.items():
            assert list(bucket.slots) == saved["slots"][key.digest]
            before = saved["states"][key.digest]
            for x, y in zip(jax.tree.leaves(before),
                            jax.tree.leaves(bucket.state)):
                np.testing.assert_array_equal(np.asarray(x),
                                              np.asarray(y))
        # the carryover request serves immediately and actuates
        res = fresh.serve_round()
        assert res["a"].action == "actuate"

    def test_restore_requires_empty_plane(self, saved, ocp):
        fresh = make_plane(cache=saved["plane"].cache)
        fresh.join(make_spec(ocp, "squatter", 0.5))
        with pytest.raises(ValueError, match="EMPTY"):
            fresh.restore_checkpoint(saved["path"], saved["specs"])

    def test_restore_rejects_spec_drift(self, saved, ocp):
        drifted = dict(saved["specs"])
        drifted["a"] = make_spec(ocp, "a", 1.0, max_iter=77)
        fresh = make_plane(cache=saved["plane"].cache)
        with pytest.raises(ValueError, match="fingerprints into"):
            fresh.restore_checkpoint(saved["path"], drifted)

    def test_missing_spec_rejected(self, saved):
        partial = {t: s for t, s in saved["specs"].items() if t != "c"}
        fresh = make_plane(cache=saved["plane"].cache)
        with pytest.raises(KeyError, match="'c'"):
            fresh.restore_checkpoint(saved["path"], partial)

    def test_corrupt_arrays_rejected_not_restored(self, saved,
                                                  tmp_path):
        import shutil

        copy = str(tmp_path / "plane")
        shutil.copytree(saved["path"], copy)
        corrupt_checkpoint(copy, mode="truncate")
        fresh = make_plane(cache=saved["plane"].cache)
        with pytest.raises((ValueError, RuntimeError)):
            fresh.restore_checkpoint(copy, saved["specs"])

    def test_dropped_manifest_means_no_checkpoint(self, saved,
                                                  tmp_path):
        import shutil

        copy = str(tmp_path / "plane")
        shutil.copytree(saved["path"], copy)
        corrupt_checkpoint(copy, mode="drop-manifest")
        assert not has_plane_checkpoint(copy)
        fresh = make_plane(cache=saved["plane"].cache)
        with pytest.raises(RuntimeError, match="manifest"):
            fresh.restore_checkpoint(copy, saved["specs"])

    def test_absent_path_is_file_not_found(self, saved, tmp_path):
        fresh = make_plane(cache=saved["plane"].cache)
        with pytest.raises(FileNotFoundError):
            fresh.restore_checkpoint(str(tmp_path / "nope"),
                                     saved["specs"])


class TestHealthLedgerUnit:
    def test_quarantine_evict_probation_cycle(self):
        ledger = HealthLedger(HealthPolicy(
            quarantine_after=2, evict_after=3, readmit_after=2,
            probation_rounds=2))
        assert ledger.observe("t", True) is None
        assert ledger.state("t") == HEALTHY        # 1 strike
        assert ledger.observe("t", True) is None
        assert ledger.state("t") == QUARANTINED    # 2 strikes
        assert ledger.observe("t", True) == "evict"
        assert ledger.state("t") == EVICTED
        assert ledger.tick_evicted() == []         # 1 round evicted
        assert ledger.tick_evicted() == ["t"]      # window open
        ledger.readmitted("t")
        assert ledger.state("t") == PROBATION
        assert ledger.observe("t", False) is None
        assert ledger.observe("t", False) == "clear"
        assert ledger.state("t") == HEALTHY

    def test_one_sick_probation_round_reevicts(self):
        ledger = HealthLedger(HealthPolicy(
            quarantine_after=1, evict_after=2, readmit_after=1,
            probation_rounds=3))
        ledger.force_evict("t")
        ledger.readmitted("t")
        assert ledger.observe("t", True) == "evict"
        assert ledger.state("t") == EVICTED

    def test_healthy_round_resets_strikes(self):
        ledger = HealthLedger(HealthPolicy(quarantine_after=2,
                                           evict_after=3))
        ledger.observe("t", True)
        ledger.observe("t", False)
        ledger.observe("t", True)
        ledger.observe("t", True)
        assert ledger.state("t") == QUARANTINED    # never reached 3
        ledger.observe("t", False)
        assert ledger.state("t") == HEALTHY

    def test_quarantine_carried_lane_is_sick(self):
        """The engine quarantine substitutes a NaN lane, so its decoded
        result is finite+healthy — the per-lane attribution must flag
        it anyway."""
        ledger = HealthLedger(HealthPolicy())
        healthy_stats = {"iterations": 6, "quarantined_iters": 0}
        carried_stats = {"iterations": 6, "quarantined_iters": 6}
        assert not ledger.is_sick_result(True, healthy_stats)
        assert ledger.is_sick_result(True, carried_stats)
        assert ledger.is_sick_result(False, healthy_stats)

    def test_snapshot_roundtrip(self):
        ledger = HealthLedger(HealthPolicy(quarantine_after=1,
                                           evict_after=2))
        ledger.observe("t", True)
        ledger.force_evict("u")
        clone = HealthLedger(ledger.policy)
        clone.restore(ledger.snapshot())
        assert clone.state("t") == QUARANTINED
        assert clone.state("u") == EVICTED
        assert clone.row("t").sick_streak == 1


class TestFaultIsolation:
    """Acceptance: NaN-storm tenant evicted in-window; bucket peers
    bitwise-unaffected; clean probation re-admission."""

    @pytest.mark.chaos
    def test_nan_tenant_evicted_peers_bitwise_unaffected(self, ocp):
        policy = HealthPolicy(quarantine_after=1, evict_after=2,
                              readmit_after=2, probation_rounds=1)
        tenants = [("sick", 0.0), ("h1", 1.0), ("h2", -2.0)]

        def run(with_chaos):
            plane = make_plane(health_policy=policy)
            for tid, a in tenants:
                plane.join(make_spec(ocp, tid, a, couplings={}))
            ctl = None
            if with_chaos:
                ctl = install_serving_chaos(plane, ServeChaosConfig(
                    nan_storm=(ServeNaNStormRule(
                        tenant="sick", start_round=0, n_rounds=4),)))
            history = []
            evicted_at = None
            for r in range(10):
                for tid, a in tenants:
                    if tid in plane.evicted_tenants:
                        continue
                    plane.submit(tid, theta=ocp.default_params(
                        p=jnp.array([a + 0.01 * r])))
                res = plane.serve_round()
                history.append({t: np.asarray(v.controls["u"])
                                if v.action == "actuate"
                                and v.controls else None
                                for t, v in res.items()})
                if evicted_at is None and "sick" in \
                        plane.evicted_tenants:
                    evicted_at = r
            if ctl is not None:
                ctl.uninstall()
            return plane, history, evicted_at

        clean_plane, clean_hist, _ = run(with_chaos=False)
        chaos_plane, chaos_hist, evicted_at = run(with_chaos=True)

        # evicted within the window: 2 sick rounds at evict_after=2
        assert evicted_at is not None and evicted_at <= 2
        # ... and re-admitted cleanly after the storm: by the end the
        # tenant is healthy again and actuating
        assert "sick" not in chaos_plane.evicted_tenants
        assert chaos_plane.health_state("sick") in (HEALTHY, PROBATION)
        assert chaos_hist[-1]["sick"] is not None
        # bucket peers: bitwise-identical controls in EVERY round
        for r, (clean, chaos) in enumerate(zip(clean_hist,
                                               chaos_hist)):
            for tid in ("h1", "h2"):
                assert clean[tid] is not None and chaos[tid] is not None
                assert (clean[tid] == chaos[tid]).all(), (
                    f"round {r}: {tid} diverged under chaos")

    @pytest.mark.chaos
    def test_result_mode_storm_walks_guard_verdicts(self, ocp):
        """The decode-level storm drives eviction through the guard
        path (NaN u0 + success=False) instead of door rejection."""
        plane = make_plane(health_policy=HealthPolicy(
            quarantine_after=1, evict_after=2, readmit_after=8,
            probation_rounds=1))
        plane.join(make_spec(ocp, "v", 1.0, couplings={}))
        plane.join(make_spec(ocp, "w", 2.0, couplings={}))
        ctl = install_serving_chaos(plane, ServeChaosConfig(
            nan_storm=(ServeNaNStormRule(tenant="v", mode="result",
                                         start_round=0, n_rounds=6),)))
        actions = []
        for _ in range(4):
            for tid in ("v", "w"):
                if tid not in plane.evicted_tenants:
                    plane.submit(tid)
            res = plane.serve_round()
            actions.append({t: r.action for t, r in res.items()})
        ctl.uninstall()
        assert "v" in plane.evicted_tenants
        assert plane.health_state("v") == EVICTED
        # the victim's unhealthy rounds walked its ladder, peers kept on
        assert any(a.get("v") in ("replay", "hold", "fallback")
                   for a in actions)
        assert all(a.get("w") == "actuate" for a in actions
                   if "w" in a)


class TestWatchdog:
    @pytest.mark.chaos
    def test_stalled_round_sheds_and_falls_back_to_sync(self, ocp):
        plane = make_plane(pipelined=True, donate=True,
                           watchdog_timeout_s=0.5)
        plane.join(make_spec(ocp, "a", 1.0))
        plane.join(make_spec(ocp, "b", 3.0))
        # materialize call 0 is round 0's readback at round 1
        ctl = install_serving_chaos(plane, ServeChaosConfig(
            stall=(ServeStallRule(call=1, duration_s=3.0),)))
        for t in ("a", "b"):
            plane.submit(t)
        plane.serve_round()                 # round 0 in flight
        for t in ("a", "b"):
            plane.submit(t)
        res = plane.serve_round()           # delivers round 0: healthy
        assert all(r.action == "actuate" for r in res.values())
        for t in ("a", "b"):
            plane.submit(t)
        res = plane.serve_round()           # watchdog fires — NO raise
        assert set(res) == {"a", "b"}
        for r in res.values():
            assert not r.healthy
            assert r.action in ("replay", "hold", "fallback")
        assert plane.dispatcher.stalls == 1
        assert plane.dispatcher.sync_fallback
        assert plane.dispatcher.pipelined is False
        # subsequent rounds serve synchronously and recover
        for t in ("a", "b"):
            plane.submit(t)
        res = plane.serve_round()
        assert all(r.action == "actuate" for r in res.values())
        assert plane.dispatcher.stalls == 1
        ctl.uninstall()

    def test_stall_condemns_other_buckets_inflight_rounds(self):
        """A stall in bucket A must not strand bucket B's in-flight
        round: it is condemned (RoundTimeout via drain_failed), never
        surfaced later as a stale out-of-order result."""
        import time as _time

        from agentlib_mpc_tpu.serving.dispatch import (
            PipelinedDispatcher,
            RoundTimeout,
        )

        class FakeHandle:
            def __init__(self, served):
                self.served = served

        class FakePlane:
            def __init__(self, name, hang=False):
                self.name = name
                self.hang = hang
                self.launched = 0

            def launch_round(self):
                self.launched += 1
                return FakeHandle(((f"{self.name}{self.launched}", 0),))

            def materialize(self, handle):
                if self.hang:
                    _time.sleep(5.0)
                return {t: {"u0": {}} for t, _ in handle.served}

        d = PipelinedDispatcher(pipelined=True, timeout_s=0.2)
        a, b = FakePlane("a", hang=True), FakePlane("b")
        assert d.dispatch("A", a) is None       # A round 1 in flight
        assert d.dispatch("B", b) is None       # B round 1 in flight
        res = d.dispatch("A", a)                # A's readback stalls
        assert isinstance(res, RoundTimeout)
        # A's tenants from BOTH the stalled and the just-launched round
        assert {t for t, _ in res.served} == {"a1", "a2"}
        # B's stranded round is condemned, not forgotten
        failed = d.drain_failed()
        assert set(failed) == {"B"}
        assert isinstance(failed["B"], RoundTimeout)
        assert {t for t, _ in failed["B"].served} == {"b1"}
        assert d.flush() == {}                  # nothing left behind
        assert d.pipelined is False and d.sync_fallback

    def test_flush_condemns_rest_after_first_stall(self):
        """One stall inside a multi-bucket flush: the remaining handles
        are condemned without paying a timeout each."""
        import time as _time

        from agentlib_mpc_tpu.serving.dispatch import (
            PipelinedDispatcher,
            RoundTimeout,
        )

        class FakeHandle:
            def __init__(self, served):
                self.served = served

        class FakePlane:
            def __init__(self, hang):
                self.hang = hang

            def launch_round(self):
                return FakeHandle((("t", 0),))

            def materialize(self, handle):
                if self.hang:
                    _time.sleep(5.0)
                return {"t": {"u0": {}}}

        d = PipelinedDispatcher(pipelined=True, timeout_s=0.2)
        for k, hang in (("A", True), ("B", True), ("C", True)):
            plane = FakePlane(hang)
            d.dispatch(k, plane)
        t0 = _time.perf_counter()
        out = d.flush()
        elapsed = _time.perf_counter() - t0
        assert set(out) == {"A", "B", "C"}
        assert all(isinstance(v, RoundTimeout) for v in out.values())
        # one timeout paid, not three
        assert elapsed < 2.0
        assert d.stalls == 1

    def test_leave_of_restored_evicted_tenant_without_bucket(self,
                                                             ocp):
        """A checkpoint-restored evicted tenant whose bucket was not
        persisted (all members evicted at save time) must still leave
        cleanly."""
        from agentlib_mpc_tpu.serving import bucket_key

        plane = make_plane(health_policy=HealthPolicy())
        spec = make_spec(ocp, "ghost", 1.0)
        key = bucket_key(spec)
        plane._register_tenant("ghost", key, spec)
        plane._evicted["ghost"] = key            # no bucket exists
        plane.leave("ghost")
        assert "ghost" not in plane.tenants
        assert "ghost" not in plane.evicted_tenants
        assert plane._guards == {} and plane._specs == {}

    def test_probe_device_bounded_answers_on_live_backend(self):
        from agentlib_mpc_tpu.serving.dispatch import (
            probe_device_bounded,
        )

        assert probe_device_bounded(timeout_s=30.0) == \
            jax.default_backend()


class TestServeChaosConfig:
    def test_unknown_option_rejected(self):
        with pytest.raises(ValueError, match="unknown serve-chaos"):
            ServeChaosConfig.from_dict({"nan_storms": []})

    def test_from_dict_builds_rules(self):
        cfg = ServeChaosConfig.from_dict({
            "seed": 3,
            "nan_storm": [{"tenant": "x", "start_round": 2,
                           "n_rounds": 4}],
            "stall": [{"call": 5, "duration_s": 1.0}],
            "build_fail": [{"build": 0}],
        })
        assert cfg.nan_storm[0].matches("x")
        assert cfg.nan_storm[0].triggered(2)
        assert not cfg.nan_storm[0].triggered(6)
        assert cfg.build_fail[0].triggered(0)
        assert not cfg.build_fail[0].triggered(1)

    def test_build_fail_propagates_from_join(self, ocp):
        from agentlib_mpc_tpu.resilience.chaos import ChaosBuildError

        plane = make_plane()
        from agentlib_mpc_tpu.resilience.chaos import ServeBuildFailRule

        ctl = install_serving_chaos(plane, ServeChaosConfig(
            build_fail=(ServeBuildFailRule(build=0, n_builds=1),)))
        with pytest.raises(ChaosBuildError):
            plane.join(make_spec(ocp, "x", 1.0, max_iter=40))
        ctl.uninstall()
        # the failed build left no cache entry: the next join pays a
        # real build and succeeds
        rec = plane.join(make_spec(ocp, "x", 1.0, max_iter=40))
        assert not rec.engine_cached
        plane.leave("x")


class TestGuardSnapshot:
    def test_roundtrip_preserves_ladder_and_plan(self):
        from agentlib_mpc_tpu.resilience.guard import (
            ActuationGuard,
            DegradationPolicy,
        )

        guard = ActuationGuard(DegradationPolicy(replay_steps=2))
        guard.assess({"u0": {"u": 1.5},
                      "traj": {"u": np.array([[1.5], [1.6], [1.7]])},
                      "stats": {"success": True}})
        guard.assess({"u0": {"u": float("nan")},
                      "stats": {"success": False}})
        clone = ActuationGuard(guard.policy)
        clone.restore(guard.snapshot())
        assert clone.level == guard.level
        assert clone._unhealthy_streak == guard._unhealthy_streak
        assert clone._last_controls == guard._last_controls
        # the restored plan replays the same step next failure
        d1 = guard.assess({"u0": {"u": 0.0},
                           "stats": {"success": False}})
        d2 = clone.assess({"u0": {"u": 0.0},
                           "stats": {"success": False}})
        assert d1.action == d2.action == "replay"
        assert d1.controls == d2.controls


@pytest.mark.chaos
class TestChaosServeBench:
    def test_chaos_serve_smoke(self):
        """Fast ``--chaos-serve`` smoke: 2 tenants, reduced rounds —
        the fault schedule runs, availability is measured, the crash
        restore is all cache hits."""
        import bench

        out = bench.run_chaos_serve(seed=1, n_tenants=2, rounds=12)
        assert out["metric"].startswith("serve_availability_pct")
        assert 0 < out["value"] <= 100.0
        assert out["mttr_ms"] is not None and out["mttr_ms"] > 0
        assert out["restore_cold_builds"] == 0
        assert out["evictions"] >= 1
        assert out["chaos_events"]["serve_nan_theta"] >= 1

    @pytest.mark.slow
    def test_chaos_serve_full(self):
        """Full-scale run: the stall fires inside the schedule too."""
        import bench

        out = bench.run_chaos_serve(seed=0, n_tenants=6, rounds=24)
        assert out["restore_cold_builds"] == 0
        assert out["watchdog_stalls"] >= 1
        assert out["readmissions"] >= 1
        assert out["value"] > 50.0


class TestCrossProcessRestore:
    """ISSUE 10: topology-stamped checkpoints + the on-disk engine
    store — crash recovery must survive REAL process death, and a
    restore onto a different device topology must fail loudly with a
    reshard recipe instead of splicing misaligned slots."""

    def test_topology_drift_rejected_with_reshard_recipe(
            self, ocp, tmp_path):
        from agentlib_mpc_tpu.serving import plane_checkpoint_topology

        plane = make_plane()
        spec = make_spec(ocp, "topo", 1.0)
        plane.join(spec)
        path = str(tmp_path / "plane")
        plane.save_checkpoint(path)
        assert has_plane_checkpoint(path)
        topo = plane_checkpoint_topology(path)
        assert topo["slot_multiple"] == 1
        assert topo["mesh_devices"] is None
        # a plane padded for a different slot multiple must NOT splice
        drifted = make_plane(slot_multiple=2)
        with pytest.raises(ValueError, match="RESHARD"):
            drifted.restore_checkpoint(path, {"topo": spec})
        assert not drifted.tenants          # nothing was spliced
        # the checkpoint itself is intact: a matching plane restores
        ok = make_plane()
        report = ok.restore_checkpoint(path, {"topo": spec})
        assert report.tenants == ("topo",)

    def test_engine_store_revival_survives_process_death(
            self, ocp, tmp_path):
        """The cross-process acceptance row, emulated in-process by
        dropping the ENTIRE in-memory compile cache: the fresh plane's
        restore revives its bucket engine from the on-disk export
        store (certify/trace never re-run — 0 cold builds, >=1
        persistent restore), warm starts come back bitwise, and the
        revived engine serves. The true two-process variant is
        ``bench.py --chaos-mesh``'s --restore-mttr child."""
        from agentlib_mpc_tpu.serving import CompileCache, EngineStore

        store = EngineStore(str(tmp_path / "store"))
        # max_iter=37: a structure no other test builds, so THIS join
        # is the cold build that exports into the store
        spec = make_spec(ocp, "phoenix", 2.0, max_iter=37)
        plane = ServingPlane(ADMM_OPTS, slot_multiple=1,
                             initial_capacity=2, pipelined=False,
                             donate=False, cache=CompileCache(),
                             engine_store=store)
        plane.join(spec)
        assert store.saves == 1
        for _ in range(2):
            plane.submit("phoenix")
            plane.serve_round()
        path = str(tmp_path / "plane")
        plane.save_checkpoint(path)
        saved_states = state_arrays(plane)

        fresh = ServingPlane(ADMM_OPTS, slot_multiple=1,
                             initial_capacity=2, pipelined=False,
                             donate=False, cache=CompileCache(),
                             engine_store=store)
        report = fresh.restore_checkpoint(path, {"phoenix": spec})
        assert report.cold_builds == 0
        assert report.persistent_restores == 1
        assert report.cache_hits == 0
        # warm starts bitwise through process death
        for digest, saved in saved_states.items():
            restored = state_arrays(fresh)[digest]
            for a, b in zip(jax.tree.leaves(saved),
                            jax.tree.leaves(restored)):
                np.testing.assert_array_equal(a, b)
        engine = next(iter(fresh._buckets.values())).engine
        assert getattr(engine, "step_restored_from_export", False)
        fresh.submit("phoenix")
        res = fresh.serve_round()
        assert res["phoenix"].action == "actuate"
