"""Precision certifier: prove which subgraphs survive bf16/f32.

The ninth pass on the shared :mod:`.interp` stack. Every precision
decision in this codebase used to be folklore patched after the fact —
the f32 feasibility floor (PR 7), the pivot-free LDLᵀ conditioning
ceiling (PR 4), the ~1e9-magnitude baked standardization weights
cancelling catastrophically in f32 (PR 19). The ``check_dtypes`` pass
sees dtype *leaks*; none of those were leaks — they were *error
growth*. This pass propagates a forward error lattice over the traced
program and emits a :class:`PrecisionCertificate`: per **phase** (the
PR 16 ``phase_scope`` vocabulary, read straight from each equation's
``name_stack``) the maximum certified-safe dtype, with the dominating
hazard named by eqn source when a phase refutes bf16/f32.

The lattice. Each value is summarized as ``(lo, hi, rel)``: a signed
magnitude interval over all its elements plus an accumulated
relative-error bound, evaluated once per candidate dtype (bf16 / f32 /
f64, unit roundoffs 2⁻⁸ / 2⁻²⁴ / 2⁻⁵³). The per-primitive rules:

* **add/sub** — interval arithmetic plus the *provable* condition
  bound ``κ_min = (|a|+|b|) / max|out|``: when the intervals prove the
  result small against its operands (the mutation test's
  ``(x+1e8)−1e8``, a near-constant column minus its mean), every point
  of the interval cancels and ``rel`` is amplified by ``κ_min``;
  same-scale operands of unknown sign get ``κ_min ≈ 1`` — the
  backward-error reading (error small relative to the *data*), which
  is the model under which bf16 Jacobians + iterative refinement are
  certified at all (Carson–Higham style);
* **mul/div** — well-conditioned (``rel_a + rel_b + u``); a divisor
  interval containing zero refutes outright, and a divisor provably
  reaching below ``100·u`` of a *narrower-than-traced* candidate is
  noise-dominated at that candidate (the barrier-parameter division
  near the μ-floor: the floor constants were chosen for the traced
  dtype, PR 7 — re-running them at bf16 is exactly where they break);
* **matmul / reductions** — pairwise accumulation charged at the
  *accumulate* dtype, which the mixed routing pins at ≥ f32
  (``default_matmul_precision('bfloat16')`` = bf16 operands, f32
  accumulation on the MXU) — the reason the MXU-dominant phases can
  certify narrow at all;
* **scan/while** — carry fixpoints with honest widening: a carry that
  does not stabilize is widened to an unbounded interval and its
  carried error reset to one fresh roundoff, under an explicit note —
  per-iteration error compounding is the *compensator's* certified
  contract (the 2-step iterative refinement in ops/stagewise), not the
  lattice's;
* **opaque primitives** (``lu``, ``triangular_solve``, callbacks, …) —
  unknown, like every other pass: their outputs are fresh unbounded
  values and the binding phase's verdict is ``"unknown"`` — which is
  why ``factor``/``resolve`` stay at the traced (full) precision under
  every routing.

``status`` judges the **mixed routing** the certificate is cashed
behind (``SolverOptions.precision``): ``"proved"`` iff every phase the
mixed program would run narrow (:data:`MIXED_NARROW_PHASES` —
eval_jac, assemble: the MXU-dominant work) certifies bf16;
``"refuted"`` names the dominating hazard by source; ``"unknown"``
when an opaque primitive contaminates a required phase. For a plain
(un-phased) function the single ``unphased`` phase must certify at
least f32 — the standardization-fold regression class (PR 19).

``precision_digest`` is the identity of the verdict table (phase →
certified dtype, never magnitudes): it rides the engine-store meta and
the plane-checkpoint stamps beside the collective/memory/dispatch
digests, so a restore whose fresh build would certify *differently* is
refused. CLI: the ``--jaxpr`` precision leg
(:func:`precision_gate_summary`) holds the example menu's solver
traces to the ``[jaxpr.precision]`` pins. See
``docs/static_analysis.md`` "Precision certificates" (incl. the
soundness-boundary table: affine-fold correlations, control-flow
predicates and host callbacks are *outside* the lattice — the
``--precision-ab`` identity gate is the dynamic check for the model's
residual risk).
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import re

from agentlib_mpc_tpu.lint.jaxpr.interp import (
    CALLBACK_PRIMS,
    COLLECTIVE_PRIMS,
    LINEAR_REDUCE,
    NONLINEAR_EW,
    NONSMOOTH_EW,
    NONSMOOTH_REDUCE,
    STRUCTURAL,
)

__all__ = [
    "CANDIDATE_DTYPES",
    "MIXED_FULL_PHASES",
    "MIXED_NARROW_PHASES",
    "PHASE_TOLS",
    "PhaseVerdict",
    "PrecisionCertificate",
    "certify_precision",
    "certify_solver_precision",
    "check_precision_budget",
    "precision_gate_summary",
]

#: candidate evaluation dtypes, narrowest first, with unit roundoffs
CANDIDATE_DTYPES = ("bf16", "f32", "f64")
_UNIT_ROUNDOFF = {"bf16": 2.0 ** -8, "f32": 2.0 ** -24, "f64": 2.0 ** -53}

#: per-phase relative-error budgets. The narrow phases (eval_jac,
#: assemble) run against the COMPENSATED budget: the 2-step iterative
#: refinement in the resolve path contracts an O(1%) Jacobian/assembly
#: error back to the f32 residual class (the certified compensator), so
#: a phase is bf16-safe when its worst value stays within ~13 bf16
#: roundoffs. The full-precision phases carry the solver's own
#: f32-noise-floor budget (~1e3·eps_f32, the PR 7 feasibility floor).
PHASE_TOLS: "dict[str, float]" = {
    "eval_jac": 5e-2,
    "assemble": 5e-2,
    "factor": 1e-4,
    "resolve": 1e-4,
    "line_search": 1e-4,
    "step_update": 1e-3,
    "consensus": 1e-3,
    "non_anticipativity": 1e-3,
    "collectives": 1e-3,
    "unphased": 1e-3,
}

#: phases the certificate-gated mixed routing runs at bf16 input /
#: f32 accumulation — the MXU-dominant work of the IPM iteration
MIXED_NARROW_PHASES = ("eval_jac", "assemble")
#: phases the mixed routing keeps at the traced (full) precision, with
#: the iterative refinement in ``resolve`` as the certified compensator
MIXED_FULL_PHASES = ("factor", "resolve", "line_search")

#: default seeded magnitude for invars without bounds, and the sentinel
#: for provably-unbounded values (inf survives interval arithmetic)
_DEFAULT_MAG = 1e4
_INF = math.inf
_TINY = 1e-300

#: axis size charged for a collective whose mesh is not in the params
_DEFAULT_AXIS_SIZE = 8

#: fixpoint budget before a scan/while carry is widened
_FIXPOINT_ITERS = 12
_WIDEN_AFTER = 8

_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
    "remat2": "jaxpr",
}

_PHASE_RE = re.compile(r"phase\.([A-Za-z0-9_]+)")


def _source_of(eqn) -> str:
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:  # noqa: BLE001 — diagnostics only
        return "<unknown>"


def _phase_of(eqn, default: str) -> str:
    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:  # noqa: BLE001 — no name stack, keep enclosing
        return default
    hits = _PHASE_RE.findall(stack)
    return hits[-1] if hits else default


def _as_jaxpr(obj):
    if hasattr(obj, "jaxpr"):            # ClosedJaxpr
        return obj.jaxpr, list(obj.consts)
    return obj, []


@dataclasses.dataclass(frozen=True)
class _Val:
    """One value's lattice summary: signed magnitude interval over all
    elements plus the accumulated relative-error bound at the walker's
    candidate dtype."""

    lo: float
    hi: float
    rel: float

    @property
    def mag(self) -> float:
        return max(abs(self.lo), abs(self.hi))

    @property
    def minmag(self) -> float:
        if self.lo <= 0.0 <= self.hi:
            return 0.0
        return min(abs(self.lo), abs(self.hi))


_BOOL = _Val(0.0, 1.0, 0.0)
_TOP = _Val(-_INF, _INF, 0.0)


def _mul_bound(a: float, b: float) -> float:
    # inf-safe product: 0 * inf is 0 here (an exactly-zero bound
    # annihilates), never NaN
    if a == 0.0 or b == 0.0:
        return 0.0
    return a * b


def _interval_mul(a: _Val, b: _Val) -> "tuple[float, float]":
    prods = [_mul_bound(x, y) for x in (a.lo, a.hi)
             for y in (b.lo, b.hi)]
    return min(prods), max(prods)


def _hull(vals: "list[_Val]") -> _Val:
    vals = [v for v in vals if v is not None]
    if not vals:
        return _TOP
    return _Val(min(v.lo for v in vals), max(v.hi for v in vals),
                max(v.rel for v in vals))


def _log2(k: int) -> float:
    return math.log2(max(int(k), 2))


@dataclasses.dataclass(frozen=True)
class PhaseVerdict:
    """One phase's row of the certificate table.

    ``certified_dtype`` is the narrowest candidate whose error bounds
    stay within the phase budget — ``"none"`` when even f64 refutes,
    ``"unknown"`` when an opaque primitive sits inside the phase.
    ``hazard`` names the dominating hazard (by eqn source) of the
    narrowest *refuted* candidate; ``hazards`` carries one line per
    refuted candidate."""

    phase: str
    certified_dtype: str
    hazard: "str | None" = None
    hazards: tuple = ()
    eqns: int = 0

    def describe(self) -> str:
        extra = f" — {self.hazard}" if self.hazard else ""
        return f"{self.phase}: {self.certified_dtype}{extra}"


@dataclasses.dataclass(frozen=True)
class PrecisionCertificate:
    """Outcome of :func:`certify_precision`.

    ``status`` judges the mixed routing (module doc): ``"proved"`` —
    every :data:`MIXED_NARROW_PHASES` member present certifies bf16
    (for an un-phased program: ``unphased`` certifies ≥ f32);
    ``"refuted"`` — a required phase refutes, ``refutations`` name the
    dominating hazards by source; ``"unknown"`` — an opaque primitive
    contaminates a required phase. The per-phase table stands either
    way."""

    status: str
    phases: "tuple[PhaseVerdict, ...]" = ()
    refutations: tuple = ()
    opaque: tuple = ()
    notes: tuple = ()

    @property
    def proved(self) -> bool:
        return self.status == "proved"

    def verdict(self, phase: str) -> "PhaseVerdict | None":
        for v in self.phases:
            if v.phase == phase:
                return v
        return None

    def certified_dtype(self, phase: str) -> str:
        v = self.verdict(phase)
        return v.certified_dtype if v is not None else "unknown"

    @property
    def precision_digest(self) -> "str | None":
        """Identity of the verdict table (phase → certified dtype, in
        program order — never magnitudes or error bounds, which move
        with seeds and lane counts). None unless proved."""
        if self.status != "proved":
            return None
        ident = "|".join(f"{v.phase}:{v.certified_dtype}"
                         for v in self.phases)
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def describe(self) -> str:
        table = ", ".join(f"{v.phase}={v.certified_dtype}"
                          for v in self.phases)
        if self.status == "proved":
            return f"proved: {table}"
        if self.status == "refuted":
            head = "; ".join(self.refutations[:2])
            more = (f" (+{len(self.refutations) - 2} more)"
                    if len(self.refutations) > 2 else "")
            return f"REFUTED: {head}{more} [{table}]"
        return (f"unknown: "
                f"{'; '.join(self.notes[:2]) or 'uninterpretable'}"
                f" [{table}]")

    def as_dict(self) -> dict:
        return {
            "status": self.status,
            "phases": {v.phase: v.certified_dtype for v in self.phases},
            "hazards": {v.phase: list(v.hazards)
                        for v in self.phases if v.hazards},
            "digest": self.precision_digest,
            "refutations": list(self.refutations),
            "opaque": sorted(set(self.opaque)),
            "notes": list(self.notes),
        }


class _DtypeWalker:
    """One candidate dtype's forward error propagation.

    The candidate models the regime the routing can actually PRODUCE,
    not a wholesale recast: the ``bf16`` candidate is the MXU mixed
    regime — contraction operands (and the stored Hessian) rounded to
    bf16, f32 accumulation, elementwise arithmetic still at the traced
    dtype (``default_matmul_precision("bfloat16")`` changes nothing
    else). So ``u_ew`` charges elementwise ops, ``u_op`` charges each
    contraction operand's storage rounding, ``u_acc`` the pairwise
    accumulation. ``narrow_ew`` marks a candidate whose ELEMENTWISE
    roundoff is coarser than the traced program's (f32 on an
    x64-traced program): only there does the noise-floor division
    hazard apply — the traced constants' floors (μ-floor = 100·eps,
    clamp guards) were chosen for the traced dtype."""

    def __init__(self, name: str, u_ew: float, u_op: float,
                 u_acc: float, narrow_ew: bool,
                 phase_tols: "dict[str, float]"):
        self.name = name
        self.u_ew = u_ew
        self.u_op = u_op
        self.u_acc = u_acc
        self.narrow_ew = narrow_ew
        self.tols = phase_tols
        #: >0 while re-walking a loop body whose carries have not
        #: settled — hazards there would blame unsettled intermediate
        #: bounds; the fixpoint runs muted and one reporting pass runs
        #: at the settled carries
        self.mute = 0
        self.env: "dict[int, _Val]" = {}
        # phase -> (severity, detail) dominating hazard
        self.hazards: "dict[str, tuple[float, str]]" = {}
        self.phase_eqns: "dict[str, int]" = {}
        self.opaque_phases: "dict[str, set]" = {}
        self.notes: "list[str]" = []
        self._seen_hazards: set = set()

    # ---- environment -----------------------------------------------------
    def read(self, v) -> _Val:
        val = getattr(v, "val", None)
        if val is not None:                     # Literal
            return self._const(val)
        return self.env.get(id(v), _TOP)

    def write(self, v, val: _Val) -> None:
        if type(v).__name__ == "DropVar":
            return
        self.env[id(v)] = val

    def _const(self, arr) -> _Val:
        import numpy as np

        try:
            a = np.asarray(arr)
            if a.size == 0:
                return _Val(0.0, 0.0, 0.0)
            if not np.issubdtype(a.dtype, np.floating) and \
                    not np.issubdtype(a.dtype, np.integer):
                return _BOOL
            lo = float(np.min(a))
            hi = float(np.max(a))
            if not (math.isfinite(lo) and math.isfinite(hi)):
                return _TOP
            # stored constants are exact at trace time; they pay one
            # rounding when materialized at the candidate dtype
            return _Val(lo, hi, self.u_ew)
        except Exception:  # noqa: BLE001 — unreadable const
            return _TOP

    # ---- bookkeeping -----------------------------------------------------
    def _note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)

    def _count(self, phase: str) -> None:
        self.phase_eqns[phase] = self.phase_eqns.get(phase, 0) + 1

    def _opaque(self, phase: str, prim: str) -> None:
        self.opaque_phases.setdefault(phase, set()).add(prim)

    def _hazard(self, phase: str, severity: float, detail: str,
                source: str) -> None:
        if self.mute:
            return
        key = (phase, detail.split(" at ")[0], source)
        if key in self._seen_hazards:
            return
        self._seen_hazards.add(key)
        msg = f"{detail} at {source}"
        prev = self.hazards.get(phase)
        if prev is None or severity > prev[0]:
            self.hazards[phase] = (severity, msg)

    def _check(self, phase: str, out: _Val, eqn, what: str) -> None:
        tol = self.tols.get(phase, self.tols["unphased"])
        if out.rel > tol and math.isfinite(out.mag):
            self._hazard(
                phase, out.rel,
                f"{what}: relative error bound {out.rel:.2e} exceeds "
                f"the {phase} budget {tol:.0e} at {self.name}",
                _source_of(eqn))

    # ---- per-primitive rules --------------------------------------------
    def _add_sub(self, a: _Val, b: _Val, sub: bool, eqn,
                 phase: str) -> _Val:
        if sub:
            b = _Val(-b.hi, -b.lo, b.rel)
        lo, hi = a.lo + b.lo, a.hi + b.hi
        if math.isnan(lo) or math.isnan(hi):    # inf - inf
            lo, hi = -_INF, _INF
        out_mag = max(abs(lo), abs(hi))
        in_mag = a.mag + b.mag
        if in_mag == 0.0:
            return _Val(lo, hi, self.u_ew)
        if not math.isfinite(in_mag) or out_mag == 0.0:
            kappa = 1.0      # nothing provable
        else:
            kappa = max(in_mag / max(out_mag, _TINY), 1.0)
        # PROPAGATION is backward-sense (additive): the accumulated
        # bound stays relative to the operand scale. κ-compounding a
        # forward bound across chains of interval-CORRELATED
        # subtractions (a collocation defect x_next − x_k − dt·f is
        # small BECAUSE its operands nearly cancel by construction)
        # would be vacuously refuting — interval arithmetic cannot see
        # the correlation. κ instead drives the LOCAL catastrophic-
        # cancellation check: one operation whose provable condition
        # amplifies the accumulated bound past the phase budget is a
        # hazard (the mutation test's (x+1e8)−1e8, a near-constant
        # column minus its mean). This is the model's stated soundness
        # boundary (docs/static_analysis.md).
        rel = max(a.rel, b.rel) + self.u_ew
        amplified = kappa * rel
        tol = self.tols.get(phase, self.tols["unphased"])
        if amplified > tol:
            self._hazard(
                phase, amplified,
                f"ill-conditioned {'subtraction' if sub else 'sum'} "
                f"(provable condition ≥ {kappa:.1e}) amplifies the "
                f"accumulated error to {amplified:.2e} (> {tol:.0e}) "
                f"at {self.name}",
                _source_of(eqn))
        return _Val(lo, hi, rel)

    def _mul(self, a: _Val, b: _Val) -> _Val:
        lo, hi = _interval_mul(a, b)
        return _Val(lo, hi, a.rel + b.rel + self.u_ew)

    def _div(self, a: _Val, b: _Val, eqn, phase: str) -> _Val:
        if b.minmag == 0.0:
            # an unguardable-looking division is almost always guarded
            # by a predicate the lattice cannot see (fraction-to-
            # boundary where-selects, sign-gated steps): unbounded
            # output, finite error, soundness-boundary note — the
            # dynamic --precision-ab identity gate covers the residual
            # risk
            self._note(
                "division by a sign-indefinite interval treated as "
                "predicate-guarded (unbounded value, finite error) — "
                "outside the lattice's soundness boundary")
            return _Val(-_INF, _INF, a.rel + b.rel + self.u_ew)
        if self.narrow_ew and b.minmag < 100.0 * self.u_ew and \
                math.isfinite(b.mag):
            # the μ-floor class: the divisor's floor constant was
            # chosen for the TRACED dtype (100·eps there); at this
            # narrower candidate the same floor sits below the noise
            self._hazard(
                phase, 1.0 / max(b.minmag, _TINY),
                f"division by values reaching {b.minmag:.1e} — below "
                f"100·u({self.name}) = {100.0 * self.u_ew:.1e}, the "
                f"candidate's noise floor (barrier-parameter / "
                f"μ-floor class)", _source_of(eqn))
        inv = _Val(1.0 / b.hi if b.hi > 0 else 1.0 / b.hi,
                   1.0 / b.lo if b.lo != 0 else _INF, 0.0)
        if b.lo > 0:
            inv = _Val(1.0 / b.hi, 1.0 / b.lo, 0.0)
        elif b.hi < 0:
            inv = _Val(1.0 / b.hi, 1.0 / b.lo, 0.0)
        lo, hi = _interval_mul(a, inv)
        return _Val(lo, hi, a.rel + b.rel + self.u_ew)

    _NL_UNIT = frozenset({"sin", "cos", "tanh", "erf", "logistic"})
    _NL_POS = frozenset({"exp", "exp2", "expm1", "cosh"})

    def _nonlinear(self, prim: str, args: "list[_Val]", eqn,
                   phase: str) -> _Val:
        a = args[0]
        rel_in = max(v.rel for v in args)
        if prim in self._NL_UNIT:
            # bounded range, condition ≤ ~1 in the backward sense
            return _Val(-1.0 if prim != "logistic" else 0.0, 1.0,
                        rel_in + self.u_ew)
        if prim in self._NL_POS:
            hi = math.exp(min(a.hi, 700.0)) if math.isfinite(a.hi) \
                else _INF
            cond = min(a.mag, 1e12) if math.isfinite(a.mag) else 1.0
            out = _Val(0.0 if prim != "expm1" else -1.0, hi,
                       cond * rel_in + self.u_ew)
            self._check(phase, out, eqn, f"exp-class growth ({prim})")
            return out
        if prim in ("sqrt", "cbrt"):
            hi = math.sqrt(a.hi) if a.hi > 0 and math.isfinite(a.hi) \
                else (a.hi if a.hi <= 0 else _INF)
            return _Val(0.0, max(hi, 0.0), 0.5 * rel_in + self.u_ew)
        if prim == "rsqrt":
            if a.minmag == 0.0:
                self._hazard(
                    phase, _INF,
                    f"rsqrt over an interval touching zero at "
                    f"{self.name}", _source_of(eqn))
                return _Val(0.0, _INF, rel_in + self.u_ew)
            return _Val(0.0, 1.0 / math.sqrt(a.minmag),
                        0.5 * rel_in + self.u_ew)
        if prim in ("log", "log1p", "log2"):
            # |log| is backward stable (log(x(1+δ)) = log x + O(δ)):
            # the absolute error is one δ; judged backward like a
            # same-scale subtraction
            self._note(
                f"{prim} judged in the backward-error sense (its "
                f"relative condition is unbounded near roots)")
            return _Val(-_INF, _INF, rel_in + self.u_ew)
        # no condition rule: honest backward reading over an unbounded
        # range (still a KNOWN elementwise primitive — not opaque)
        self._note(
            f"no condition rule for elementwise {prim}: judged in the "
            f"backward-error sense over an unbounded range")
        return _Val(-_INF, _INF, rel_in + self.u_ew)

    def _nonsmooth(self, prim: str, args: "list[_Val]") -> _Val:
        rel = max((v.rel for v in args), default=0.0)
        if prim == "abs":
            a = args[0]
            return _Val(a.minmag, a.mag, a.rel)
        if prim == "max":
            a, b = args[0], args[-1]
            return _Val(max(a.lo, b.lo), max(a.hi, b.hi), rel)
        if prim == "min":
            a, b = args[0], args[-1]
            return _Val(min(a.lo, b.lo), min(a.hi, b.hi), rel)
        if prim == "clamp":
            lo_b, x, hi_b = args
            return _Val(max(x.lo, lo_b.lo), min(x.hi, hi_b.hi), x.rel)
        if prim in ("sign", "floor", "ceil", "round", "is_finite") or \
                prim.startswith(("eq", "ne", "lt", "le", "gt", "ge",
                                 "and", "or", "not", "xor")):
            return _BOOL if prim not in ("floor", "ceil", "round") \
                else _Val(args[0].lo - 1.0, args[0].hi + 1.0, 0.0)
        return _Val(_hull(args).lo, _hull(args).hi, rel)

    def _reduce_size(self, eqn) -> int:
        try:
            in_sz = 1
            for d in eqn.invars[0].aval.shape:
                in_sz *= int(d)
            out_sz = 1
            for d in eqn.outvars[0].aval.shape:
                out_sz *= int(d)
            return max(in_sz // max(out_sz, 1), 1)
        except Exception:  # noqa: BLE001
            return _DEFAULT_AXIS_SIZE

    def _sum_like(self, a: _Val, k: int) -> _Val:
        lo = _mul_bound(float(k), a.lo) if a.lo < 0 else a.lo
        hi = _mul_bound(float(k), a.hi) if a.hi > 0 else a.hi
        return _Val(lo, hi,
                    a.rel + (_log2(k) + 1.0) * self.u_acc + self.u_ew)

    def _dot(self, a: _Val, b: _Val, eqn) -> _Val:
        try:
            (lhs_c, _rhs_c), _ = eqn.params["dimension_numbers"]
            k = 1
            for d in lhs_c:
                k *= int(eqn.invars[0].aval.shape[d])
            k = max(k, 1)
        except Exception:  # noqa: BLE001
            k = _DEFAULT_AXIS_SIZE
        lo, hi = _interval_mul(a, b)
        mag = _mul_bound(float(k), max(abs(lo), abs(hi)))
        if a.lo >= 0.0 and b.lo >= 0.0:
            lo2, hi2 = _mul_bound(float(k), lo), mag
        else:
            lo2, hi2 = -mag, mag
        return _Val(lo2, hi2, a.rel + b.rel + 2.0 * self.u_op
                    + (_log2(k) + 1.0) * self.u_acc + self.u_ew)

    # ---- the walk --------------------------------------------------------
    def walk(self, obj, phase: str) -> None:
        jaxpr, consts = _as_jaxpr(obj)
        for cv, cval in zip(jaxpr.constvars, consts):
            self.write(cv, self._const(cval))
        for eqn in jaxpr.eqns:
            self.eqn(eqn, _phase_of(eqn, phase))

    def _inline(self, eqn, sub, phase: str) -> None:
        sub_jaxpr, consts = _as_jaxpr(sub)
        for iv, ov in zip(sub_jaxpr.invars, eqn.invars):
            self.write(iv, self.read(ov))
        self.walk(sub, phase)
        for ov, sv in zip(eqn.outvars, sub_jaxpr.outvars):
            self.write(ov, self.read(sv))

    def _loop_body(self, eqn, body, carries_in, n_consts: int,
                   phase: str, label: str) -> "list[_Val]":
        """Carry fixpoint with honest widening (module doc). The
        fixpoint iterations run MUTED — hazards blamed on unsettled
        intermediate carries would be noise — then ONE reporting pass
        at the settled carries records the real ones."""
        body_jaxpr, _ = _as_jaxpr(body)
        carry_vals = [self.read(v) for v in carries_in]
        n_carry = len(carry_vals)
        widened = False
        self.mute += 1
        try:
            for it in range(_FIXPOINT_ITERS):
                for iv, cval in zip(body_jaxpr.invars[n_consts:],
                                    carry_vals):
                    self.write(iv, cval)
                self.walk(body, phase)
                new_vals = [
                    _hull([old, self.read(ov)])
                    for old, ov in zip(
                        carry_vals, body_jaxpr.outvars[:n_carry])]
                if new_vals == carry_vals:
                    break
                carry_vals = new_vals
                if it >= _WIDEN_AFTER:
                    # the widened carry is PINNED: [-inf, inf] interval
                    # with one fresh roundoff. Re-iterating would only
                    # compound the per-iteration error budget — which
                    # is exactly what the lattice does NOT certify for
                    # a non-settling loop (an IPM iteration recomputes
                    # its residuals from state each round; the
                    # compensator, not accumulation, owns that error)
                    carry_vals = [
                        _Val(-_INF, _INF, self.u_ew)
                        for _ in carry_vals]
                    widened = True
                    self._note(
                        f"{label} fixpoint widened: carried intervals "
                        f"unbounded, carried error reset to one fresh "
                        f"roundoff — per-iteration compounding is the "
                        f"compensator's contract, not the lattice's")
                    break
        finally:
            self.mute -= 1
        # one reporting pass at the settled (or pinned-widened)
        # carries records the real hazards
        for iv, cval in zip(body_jaxpr.invars[n_consts:], carry_vals):
            self.write(iv, cval)
        self.walk(body, phase)
        if widened:
            return carry_vals
        return [_hull([old, self.read(ov)])
                for old, ov in zip(carry_vals,
                                   body_jaxpr.outvars[:n_carry])]

    def eqn(self, eqn, phase: str) -> None:  # noqa: PLR0911,PLR0912
        name = eqn.primitive.name
        args = [self.read(v) for v in eqn.invars]

        # -- control flow / calls (not counted as phase arithmetic) --
        if name in _CALL_PRIMS:
            sub = eqn.params.get(_CALL_PRIMS[name])
            if sub is not None:
                self._inline(eqn, sub, phase)
                return
        if name == "shard_map":
            self._inline(eqn, eqn.params["jaxpr"], phase)
            return
        if name == "cond":
            branch_outs = []
            for br in eqn.params["branches"]:
                br_jaxpr, _ = _as_jaxpr(br)
                for iv, ov in zip(br_jaxpr.invars, eqn.invars[1:]):
                    self.write(iv, self.read(ov))
                self.walk(br, phase)
                branch_outs.append([self.read(v)
                                    for v in br_jaxpr.outvars])
            for i, ov in enumerate(eqn.outvars):
                self.write(ov, _hull([outs[i] for outs in branch_outs]))
            return
        if name == "scan":
            n_consts = int(eqn.params["num_consts"])
            n_carry = int(eqn.params["num_carry"])
            body = eqn.params["jaxpr"]
            body_jaxpr, _ = _as_jaxpr(body)
            for iv, ov in zip(body_jaxpr.invars[:n_consts],
                              eqn.invars[:n_consts]):
                self.write(iv, self.read(ov))
            for iv, ov in zip(body_jaxpr.invars[n_consts + n_carry:],
                              eqn.invars[n_consts + n_carry:]):
                self.write(iv, self.read(ov))
            carry = self._loop_body(
                eqn, body, eqn.invars[n_consts:n_consts + n_carry],
                n_consts, phase, "scan")
            for i, ov in enumerate(eqn.outvars):
                if i < n_carry:
                    self.write(ov, carry[i])
                else:
                    self.write(ov, self.read(
                        body_jaxpr.outvars[i]))
            return
        if name == "while":
            cn = int(eqn.params["cond_nconsts"])
            bn = int(eqn.params["body_nconsts"])
            body = eqn.params["body_jaxpr"]
            body_jaxpr, _ = _as_jaxpr(body)
            for iv, ov in zip(body_jaxpr.invars[:bn],
                              eqn.invars[cn:cn + bn]):
                self.write(iv, self.read(ov))
            carry = self._loop_body(
                eqn, body, eqn.invars[cn + bn:], bn, phase, "while")
            for ov, cval in zip(eqn.outvars, carry):
                self.write(ov, cval)
            return

        # -- data primitives -----------------------------------------
        self._count(phase)
        if name in CALLBACK_PRIMS:
            self._opaque(phase, name)
            for ov in eqn.outvars:
                self.write(ov, _TOP)
            return
        if name in COLLECTIVE_PRIMS:
            out = self._sum_like(_hull(args), _DEFAULT_AXIS_SIZE) \
                if name in ("psum", "psum2") \
                else _hull(args)
            for ov in eqn.outvars:
                self.write(ov, out)
            return
        if name in STRUCTURAL or name in (
                "stop_gradient", "copy", "broadcast_in_dim", "squeeze",
                "reshape", "transpose", "slice", "dynamic_slice",
                "dynamic_update_slice", "concatenate", "pad", "gather",
                "scatter", "scatter-add", "rev", "select_n",
                "convert_element_type", "reduce_precision", "iota",
                "real", "imag"):
            if name == "iota":
                try:
                    n = int(eqn.outvars[0].aval.shape[
                        int(eqn.params.get("dimension", 0))])
                except Exception:  # noqa: BLE001
                    n = _DEFAULT_AXIS_SIZE
                self.write(eqn.outvars[0], _Val(0.0, float(n - 1), 0.0))
                return
            if name in ("convert_element_type", "reduce_precision"):
                a = args[0]
                self.write(eqn.outvars[0],
                           _Val(a.lo, a.hi, a.rel + self.u_ew))
                return
            if name == "select_n":
                out = _hull(args[1:])
            elif name == "scatter-add":
                out = self._sum_like(_hull(args), 2)
            else:
                data = args
                spec = STRUCTURAL.get(name)
                if isinstance(spec, tuple):
                    data = [args[i] for i in spec if i < len(args)]
                out = _hull(data)
            for ov in eqn.outvars:
                self.write(ov, out)
            return
        if name in ("add", "add_any", "sub"):
            out = self._add_sub(args[0], args[1], name == "sub", eqn,
                                phase)
        elif name == "neg":
            a = args[0]
            out = _Val(-a.hi, -a.lo, a.rel)
        elif name == "mul":
            out = self._mul(args[0], args[1])
            self._check(phase, out, eqn, "product")
        elif name == "div":
            out = self._div(args[0], args[1], eqn, phase)
        elif name in ("integer_pow", "square"):
            a = args[0]
            y = abs(int(eqn.params.get("y", 2)))
            lo, hi = a.lo, a.hi
            mag = min(a.mag ** y, _INF) if math.isfinite(a.mag) \
                else _INF
            if y % 2 == 0:
                lo2, hi2 = (0.0 if a.minmag == 0.0
                            else min(a.minmag ** y, _INF)), mag
            else:
                lo2, hi2 = (-mag if lo < 0 else
                            min(max(lo, 0.0) ** y, _INF)), mag
            out = _Val(lo2, hi2, y * a.rel + self.u_ew)
            self._check(phase, out, eqn, "power")
        elif name == "dot_general":
            out = self._dot(args[0], args[1], eqn)
            self._check(phase, out, eqn, "contraction")
        elif name in LINEAR_REDUCE:
            out = self._sum_like(args[0], self._reduce_size(eqn))
            self._check(phase, out, eqn, "reduction")
        elif name in NONSMOOTH_REDUCE:
            a = args[0]
            out = _Val(a.lo, a.hi, a.rel)
        elif name == "reduce_prod":
            k = self._reduce_size(eqn)
            a = args[0]
            mag = min(a.mag ** k, _INF) if math.isfinite(a.mag) and \
                a.mag > 1.0 else a.mag
            out = _Val(-mag, mag, k * a.rel + _log2(k) * self.u_ew)
            self._check(phase, out, eqn, "product reduction")
        elif name in NONSMOOTH_EW:
            out = self._nonsmooth(name, args)
        elif name in NONLINEAR_EW or name in (
                "pow", "atan2", "rem", "logistic", "erf", "erf_inv",
                "erfc"):
            out = self._nonlinear(name, args, eqn, phase)
        else:
            # opaque primitive: unknown, like every other pass — its
            # outputs are fresh unbounded values and the phase cannot
            # be certified at any dtype
            self._opaque(phase, name)
            for ov in eqn.outvars:
                self.write(ov, _TOP)
            return
        for ov in eqn.outvars:
            self.write(ov, out)


def _seed_vals(jaxpr, seeds, u: float) -> "list[_Val]":
    out = []
    for i, _v in enumerate(jaxpr.invars):
        lo, hi = -_DEFAULT_MAG, _DEFAULT_MAG
        if seeds is not None and i in seeds:
            lo, hi = seeds[i]
            lo = float(lo) if math.isfinite(lo) else -_DEFAULT_MAG
            hi = float(hi) if math.isfinite(hi) else _DEFAULT_MAG
        out.append(_Val(float(lo), float(hi), u))
    return out


def _program_roundoff(jaxpr) -> float:
    """The traced program's own unit roundoff: the widest float dtype
    among its invars (f32 unless the program was traced under x64)."""
    import numpy as np

    u = _UNIT_ROUNDOFF["f32"]
    for v in list(jaxpr.invars) + list(jaxpr.outvars):
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None and np.issubdtype(dt, np.float64):
            return _UNIT_ROUNDOFF["f64"]
    return u


def certify_precision(fn_or_jaxpr, *args, seeds=None,
                      phase_tols=None) -> PrecisionCertificate:
    """Certify the per-phase precision safety of a traced program.

    ``fn_or_jaxpr``: a ``ClosedJaxpr`` (pass no ``args``) or a callable
    traced as ``jax.make_jaxpr(fn)(*args)``. ``seeds``: optional
    ``{flat_invar_index: (lo, hi)}`` magnitude intervals (variable
    bounds, typically); unseeded invars get ±1e4. ``phase_tols``
    overrides :data:`PHASE_TOLS` per phase.

    Runs the error lattice once per candidate dtype (module doc) and
    assembles the per-phase verdict table. Never executes user code."""
    if hasattr(fn_or_jaxpr, "jaxpr") and not args:
        closed = fn_or_jaxpr
    else:
        import jax

        closed = jax.make_jaxpr(fn_or_jaxpr)(*args)
    tols = dict(PHASE_TOLS)
    if phase_tols:
        tols.update(phase_tols)
    try:
        u_prog = _program_roundoff(closed.jaxpr)
        walkers = []
        for cand in CANDIDATE_DTYPES:
            u_c = _UNIT_ROUNDOFF[cand]
            if cand == "bf16":
                # the MXU mixed regime the routing actually produces:
                # elementwise stays at the traced dtype, contraction
                # operands round to bf16, accumulation at f32
                u_ew = u_prog
                u_op = u_c
                u_acc = _UNIT_ROUNDOFF["f32"]
            else:
                u_ew = u_op = u_acc = u_c
            w = _DtypeWalker(cand, u_ew, u_op, u_acc,
                             narrow_ew=u_ew > u_prog, phase_tols=tols)
            for iv, val in zip(closed.jaxpr.invars,
                               _seed_vals(closed.jaxpr, seeds, u_ew)):
                w.write(iv, val)
            w.walk(closed, "unphased")
            walkers.append(w)
    except Exception as exc:  # noqa: BLE001 — certification must not
        # kill a build; an uninterpretable program is "unknown"
        return PrecisionCertificate(
            status="unknown",
            notes=(f"interpreter error: {exc!r}",))

    phase_order: "list[str]" = []
    for w in walkers:
        for p in w.phase_eqns:
            if p not in phase_order:
                phase_order.append(p)
    opaque: "set[str]" = set()
    notes: "list[str]" = []
    for w in walkers:
        for prims in w.opaque_phases.values():
            opaque.update(prims)
    for n in walkers[0].notes:
        notes.append(n)

    verdicts = []
    for p in phase_order:
        cand_hazards = []
        certified = "none"
        dominating = None
        if any(p in w.opaque_phases for w in walkers):
            prims = sorted(set().union(
                *(w.opaque_phases.get(p, set()) for w in walkers)))
            verdicts.append(PhaseVerdict(
                phase=p, certified_dtype="unknown",
                hazard=f"opaque primitive(s) {', '.join(prims)} — "
                       f"outside the lattice",
                eqns=walkers[0].phase_eqns.get(p, 0)))
            continue
        for w in walkers:
            hz = w.hazards.get(p)
            if hz is None:
                certified = w.name
                break
            cand_hazards.append(f"{w.name}: {hz[1]}")
            dominating = hz[1]
        verdicts.append(PhaseVerdict(
            phase=p, certified_dtype=certified,
            hazard=(cand_hazards[0].split(": ", 1)[1]
                    if cand_hazards else None)
            if certified != "none" else dominating,
            hazards=tuple(cand_hazards),
            eqns=walkers[0].phase_eqns.get(p, 0)))

    by_phase = {v.phase: v for v in verdicts}
    refutations: "list[str]" = []
    unknown = False
    if set(by_phase) <= {"unphased"}:
        # a plain function: must survive its own (f32-class) budget
        v = by_phase.get("unphased")
        if v is not None:
            if v.certified_dtype == "unknown":
                unknown = True
            elif v.certified_dtype not in ("bf16", "f32"):
                f32_haz = next(
                    (h for h in v.hazards if h.startswith("f32:")),
                    v.hazard)
                refutations.append(
                    f"program refutes f32: {f32_haz}")
    else:
        for p in MIXED_NARROW_PHASES:
            v = by_phase.get(p)
            if v is None:
                continue
            if v.certified_dtype == "unknown":
                unknown = True
            elif v.certified_dtype != "bf16":
                refutations.append(
                    f"mixed routing needs {p} at bf16, certified "
                    f"{v.certified_dtype}: {v.hazards[0] if v.hazards else v.hazard}")
        for p in MIXED_FULL_PHASES:
            v = by_phase.get(p)
            if v is not None and v.certified_dtype == "none":
                refutations.append(
                    f"{p} refutes every candidate dtype: {v.hazard}")
    if refutations:
        status = "refuted"
    elif unknown:
        status = "unknown"
        notes.append(
            "an opaque primitive contaminates a phase the mixed "
            "routing would run narrow")
    else:
        status = "proved"
    return PrecisionCertificate(
        status=status,
        phases=tuple(verdicts),
        refutations=tuple(refutations),
        opaque=tuple(sorted(opaque)),
        notes=tuple(notes),
    )


def certify_solver_precision(nlp, theta, n_w: int, w_lb=None, w_ub=None,
                             options=None,
                             solver: str = "ipm") -> PrecisionCertificate:
    """Certify the traced interior-point solve of one NLP.

    Traces ``solve_nlp`` (or ``solve_qp`` for ``solver="qp"``) on shape
    templates — the phases come from the solver's own ``phase_scope``
    annotations — and seeds the primal invar from the variable bounds.
    ``theta`` is closed over, so its concrete values become exact
    lattice constants. Never executes the solve."""
    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp

    opts = options or SolverOptions()
    # certify the FULL-precision program: the certificate decides
    # whether the mixed routing may be applied to it
    if getattr(opts, "precision", "auto") != "f64":
        opts = opts._replace(precision="f64")
    lb = jnp.full((n_w,), -_DEFAULT_MAG) if w_lb is None \
        else jnp.asarray(w_lb)
    ub = jnp.full((n_w,), _DEFAULT_MAG) if w_ub is None \
        else jnp.asarray(w_ub)
    if solver == "qp":
        from agentlib_mpc_tpu.ops.qp import solve_qp as _solve
    else:
        _solve = solve_nlp

    def run(w0):
        return _solve(nlp, w0, theta, lb, ub, opts)

    import numpy as np

    lo = float(np.nanmax([-_DEFAULT_MAG,
                          float(np.min(np.asarray(lb)))]))
    hi = float(np.nanmin([_DEFAULT_MAG,
                          float(np.max(np.asarray(ub)))]))
    if not math.isfinite(lo):
        lo = -_DEFAULT_MAG
    if not math.isfinite(hi):
        hi = _DEFAULT_MAG
    closed = jax.make_jaxpr(run)(jnp.zeros((n_w,)))
    return certify_precision(closed, seeds={0: (lo, hi)})


def check_precision_budget(cert: PrecisionCertificate,
                           expect: str) -> "list[str]":
    """Compare a certificate against one ``[jaxpr.precision.expect]``
    pin: ``expect`` is ``"phase=dtype,phase=dtype,..."`` (a flat string
    so the minimal built-in TOML parser can read it). A drift in EITHER
    direction fails — a phase suddenly refusing bf16 is a lost
    optimization, a phase suddenly certifying narrower than pinned is a
    certifier regression about to mis-route production solves.

    Returns violation strings (empty = within budget)."""
    out = []
    for part in expect.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            out.append(f"unparseable precision pin {part!r} "
                       f"(want phase=dtype)")
            continue
        phase, want = (s.strip() for s in part.split("=", 1))
        got = cert.certified_dtype(phase)
        if got != want:
            v = cert.verdict(phase)
            detail = f" ({v.hazard})" if v is not None and v.hazard \
                else ""
            out.append(
                f"phase {phase} certifies {got!r}, budget pins "
                f"{want!r}{detail} — the certified routing table "
                f"drifted")
    return out


def precision_gate_summary(budgets: "dict | None" = None) -> dict:
    """The ``--jaxpr`` CLI's precision leg: certify the traced solve of
    every example-menu entry and hold the per-phase certified-dtype
    table to the ``[jaxpr.precision]`` pins. Also the
    ``precision_certificates`` section of ``bench.py
    --emit-metrics``."""
    from agentlib_mpc_tpu.lint.jaxpr.examples import EXAMPLE_OCPS
    from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

    cfg = (budgets if budgets is not None else load_budgets()).get(
        "jaxpr", {}).get("precision", {})
    expects = cfg.get("expect", {})
    rows = []
    failures = 0
    for ex in EXAMPLE_OCPS:
        try:
            ocp = ex.build()
            theta = ocp.default_params()
            w_lb, w_ub = ocp.bounds(theta)
            cert = certify_solver_precision(
                ocp.nlp, theta, ocp.n_w, w_lb, w_ub)
            violations = []
            pin = expects.get(ex.name)
            if pin:
                violations = check_precision_budget(cert, pin)
        except Exception as exc:  # noqa: BLE001 — report, don't crash CI
            rows.append({"name": ex.name, "error": repr(exc)})
            failures += 1
            continue
        if violations:
            failures += len(violations)
        rows.append({
            "name": ex.name,
            "certificate": cert.as_dict(),
            "digest": cert.precision_digest,
            "violations": violations,
        })
    return {"examples": rows, "failures": failures,
            "budget": dict(cfg)}
