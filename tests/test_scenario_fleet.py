"""ScenarioFleet (ISSUE 12): the fused robust round over the 2-D
(agents × scenarios) axis pair — correctness against serial branches,
non-anticipativity, and the two-psum-family collective certification.

Engine builds dominate the cost; everything reusable is module-scoped.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from agentlib_mpc_tpu.lint.jaxpr.collectives import (
    check_collective_budget,
)
from agentlib_mpc_tpu.lint.retrace_budget import load_budgets, tracker_ocp
from agentlib_mpc_tpu.ops import admm as admm_ops
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
from agentlib_mpc_tpu.parallel.multihost import fleet_mesh, scenario_mesh
from agentlib_mpc_tpu.scenario import (
    ScenarioFleet,
    ScenarioFleetOptions,
    fan_tree,
    single_scenario,
)

N_AGENTS = 4
N_SCEN = 4


@pytest.fixture(scope="module")
def ocp():
    return tracker_ocp()


@pytest.fixture(scope="module")
def group(ocp):
    return AgentGroup(name="scenario-test", ocp=ocp, n_agents=N_AGENTS,
                      couplings={"shared_u": "u"},
                      solver_options=SolverOptions(max_iter=30))


def _thetas(ocp, n_agents=N_AGENTS, n_scen=N_SCEN, spread=0.5):
    """(n_agents, S) tracker targets: agent base a_i = i+1, scenario s
    offset by s*spread — genuinely different branch problems."""
    rows = []
    for i in range(n_agents):
        rows.append(jax.tree.map(lambda *xs: jnp.stack(xs), *[
            ocp.default_params(
                p=jnp.array([float(i + 1) + spread * s]))
            for s in range(n_scen)]))
    return jax.tree.map(lambda *xs: jnp.stack(xs), *rows)


OPTS = ScenarioFleetOptions(max_iterations=12, rho=2.0, rho_na=4.0)


@pytest.fixture(scope="module")
def coupled_fleet(group):
    return ScenarioFleet(group, fan_tree(N_SCEN, robust_horizon=1), OPTS)


class TestBatchedVsSerial:
    def test_uncoupled_batch_matches_serial_branches(self, group, ocp):
        """Acceptance: the S-scenario batched round equals S serial
        single-scenario rounds of the per-branch problems (no
        non-anticipativity — independent branches). Tolerances are
        pinned to ZERO so both runs execute the identical fixed
        iteration count — the batched round's residual exit aggregates
        over all branches and would otherwise stop at a different
        iteration than a lone branch."""
        opts = OPTS._replace(abs_tol=0.0, rel_tol=0.0, primal_tol=0.0,
                             dual_tol=0.0)
        thetas = _thetas(ocp)
        free = ScenarioFleet(group, fan_tree(N_SCEN, robust_horizon=0),
                             opts)
        st = free.init_state(thetas)
        st, trajs, stats = free.step(st, thetas)
        serial = ScenarioFleet(group, single_scenario(), opts)
        for s in range(N_SCEN):
            th_s = jax.tree.map(lambda l, s=s: l[:, s:s + 1], thetas)
            st_s = serial.init_state(th_s)
            st_s, trajs_s, _ = serial.step(st_s, th_s)
            np.testing.assert_allclose(
                np.asarray(st.zbar["shared_u"][s]),
                np.asarray(st_s.zbar["shared_u"][0]),
                rtol=1e-6, atol=1e-6)
            np.testing.assert_allclose(
                np.asarray(st.w[:, s]), np.asarray(st_s.w[:, 0]),
                rtol=1e-5, atol=1e-6)

    def test_non_anticipativity_holds(self, ocp):
        """Acceptance: the actuated u0 is identical across every
        scenario of a group — exactly for the projection, and the raw
        branch controls agree to ADMM tolerance."""
        # no agent coupling: isolate the scenario coupling's physics
        group = AgentGroup(name="na-test", ocp=ocp, n_agents=2,
                           solver_options=SolverOptions(max_iter=30))
        fleet = ScenarioFleet(
            group, fan_tree(N_SCEN, robust_horizon=1),
            ScenarioFleetOptions(max_iterations=25, rho_na=4.0,
                                 abs_tol=1e-6, rel_tol=1e-5))
        thetas = _thetas(ocp, n_agents=2)
        st = fleet.init_state(thetas)
        st, trajs, stats = fleet.step(st, thetas)
        u0 = np.asarray(fleet.actuated_u0(st))    # (n_agents, S, n_u)
        # the projection is group-identical BY CONSTRUCTION
        np.testing.assert_array_equal(u0, np.broadcast_to(
            u0[:, :1], u0.shape))
        # ... and the raw branch controls actually converged onto it
        u_raw = np.asarray(jax.vmap(jax.vmap(
            lambda w: fleet.group.ocp.unflatten(w)["u"]))(st.w))
        spread = np.max(np.abs(u_raw[:, :, 0, :] - u0))
        assert spread < 1e-3
        rel = spread / max(np.max(np.abs(u0)), 1e-12)
        assert rel < 1e-3
        # tracker analytics: every scenario wants u == a_s; the shared
        # first interval lands on the scenario mean, later intervals
        # recourse to their own target
        a = np.asarray(thetas.p)[:, :, 0]
        np.testing.assert_allclose(u0[:, 0, 0], a.mean(axis=1),
                                   atol=1e-3)
        np.testing.assert_allclose(u_raw[:, :, -1, 0], a, atol=1e-3)
        assert float(stats.na_spread) < 1e-3

    def test_spread_zero_for_identical_branches(self, coupled_fleet,
                                                ocp):
        thetas = _thetas(ocp, spread=0.0)
        st = coupled_fleet.init_state(thetas)
        st, _trajs, stats = coupled_fleet.step(st, thetas)
        assert float(stats.na_spread) < 1e-9


class TestMeshAndCertification:
    @pytest.fixture(scope="class")
    def mesh2d(self, eight_devices):
        return scenario_mesh(2, devices=eight_devices)

    @pytest.fixture(scope="class")
    def mesh_fleet(self, group, mesh2d):
        return ScenarioFleet(group, fan_tree(N_SCEN, robust_horizon=1),
                             OPTS, mesh=mesh2d)

    def test_two_psum_families_proved(self, mesh_fleet):
        """Acceptance: the 2-D round's certificate proves EXACTLY two
        per-iteration psum families — agents + scenarios."""
        cert = mesh_fleet.collective_certificate
        assert cert is not None and cert.proved
        fams = cert.families()
        assert sorted(fams) == ["1:psum@agents", "1:psum@scenarios"]
        assert mesh_fleet.collective_schedule_digest \
            == cert.schedule_digest is not None

    def test_budget_pin_matches_checked_in_toml(self, mesh_fleet):
        """Gate-as-test: the [jaxpr.collectives.scenario] pin holds for
        the real engine (a budget drifting from the code fails here)."""
        cfg = load_budgets().get("jaxpr", {}).get(
            "collectives", {}).get("scenario", {})
        assert cfg, "[jaxpr.collectives.scenario] missing from " \
                    "lint_budgets.toml"
        assert check_collective_budget(
            mesh_fleet.collective_certificate, cfg) == []

    def test_degenerate_engine_certifies_one_family(self, group,
                                                    eight_devices):
        """Acceptance: the single-scenario engine's schedule is the
        one-family shape of today's agent fleet — no scenario
        collectives are traced at all."""
        fleet = ScenarioFleet(
            group, single_scenario(), OPTS,
            mesh=fleet_mesh(devices=eight_devices[:4]))
        cert = fleet.collective_certificate
        assert cert.proved
        assert sorted(cert.families()) == ["1:psum@agents"]

    def test_mesh_matches_single_device(self, mesh_fleet, coupled_fleet,
                                        mesh2d, ocp):
        thetas = _thetas(ocp)
        st1 = coupled_fleet.init_state(thetas)
        st1, _t, _s = coupled_fleet.step(st1, thetas)
        stm = mesh_fleet.init_state(thetas)
        stm, th_m = mesh_fleet.shard_args(mesh2d, stm, thetas)
        stm, _tm, _sm = mesh_fleet.step(stm, th_m)
        np.testing.assert_allclose(
            np.asarray(stm.zbar["shared_u"]),
            np.asarray(st1.zbar["shared_u"]), rtol=1e-6, atol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(mesh_fleet.actuated_u0(stm)),
            np.broadcast_to(np.asarray(
                mesh_fleet.actuated_u0(stm))[:, :1],
                (N_AGENTS, N_SCEN, 1)))

    def test_injected_third_family_fails_budget(self, group, mesh2d,
                                                monkeypatch):
        """Mutation gate over the new axis: a collective family slipped
        into the round under a NEW axes combination must fail the
        [jaxpr.collectives.scenario] check as an UNBUDGETED family,
        naming the offending equation."""
        real = admm_ops.consensus_update

        def sabotaged(locals_, state, active=None, axis_name=None):
            new_state, res = real(locals_, state, active=active,
                                  axis_name=axis_name)
            extra = lax.psum(jnp.sum(locals_ ** 3),
                             ("agents", "scenarios"))
            return new_state, res._replace(primal=res.primal
                                           + 0.0 * extra)

        monkeypatch.setattr(admm_ops, "consensus_update", sabotaged)
        fleet = ScenarioFleet(group,
                              fan_tree(N_SCEN, robust_horizon=1),
                              OPTS, mesh=mesh2d)
        cert = fleet.collective_certificate
        assert cert.proved      # uniform control flow — the hazard is
        # the schedule drift, which the per-family budget pin catches:
        cfg = load_budgets().get("jaxpr", {}).get(
            "collectives", {}).get("scenario", {})
        violations = check_collective_budget(cert, cfg)
        assert violations, "the injected psum family went unnoticed"
        msg = " ".join(violations)
        assert "UNBUDGETED" in msg and "agents,scenarios" in msg
        assert "test_scenario_fleet" in msg


class TestPadScenarios:
    def test_pads_to_shard_multiple(self, ocp):
        from agentlib_mpc_tpu.scenario.fleet import pad_scenarios

        tree = fan_tree(3, robust_horizon=1)
        thetas = _thetas(ocp, n_scen=3)
        padded_tree, padded = pad_scenarios(tree, thetas, 2)
        assert padded_tree.n_scenarios == 4
        # pad branches weigh nothing and join no real group
        assert padded_tree.probabilities[-1] == 0.0
        assert padded_tree.groups_at(0)[:1] == ((0, 1, 2),)
        np.testing.assert_array_equal(np.asarray(padded.p[:, 3]),
                                      np.asarray(padded.p[:, 2]))
        # already divisible: identity
        same_tree, same = pad_scenarios(padded_tree, padded, 2)
        assert same_tree is padded_tree and same is padded


class TestBranchQuarantine:
    """ISSUE 14 satellite: per-(agent, scenario) quarantine
    attribution. The substitution keeps a diverged branch's decoded
    trajectory finite, so ``lane_quarantined`` is the only signal the
    serving health ledger gets on a persistently sick branch."""

    def test_poisoned_branch_is_quarantined_and_attributed(
            self, coupled_fleet, ocp):
        thetas = _thetas(ocp)
        st = coupled_fleet.init_state(thetas)
        # poison ONE branch's primal iterate: the warm start a crashed
        # process / corrupted splice would hand the round
        st = st._replace(w=st.w.at[1, 2].set(jnp.nan))
        st, trajs, stats = coupled_fleet.step(st, thetas)
        q = np.asarray(stats.lane_quarantined).copy()
        assert q.shape == (N_AGENTS, N_SCEN)
        assert q[1, 2] >= 1
        # attribution is per branch: nobody else was quarantined
        q[1, 2] = 0
        assert (q == 0).all()
        # ... and the substitution contained it: everything decoded
        # finite, including the poisoned lane
        assert np.isfinite(np.asarray(trajs["u"])).all()
        assert np.isfinite(np.asarray(st.w)).all()

    def test_quarantine_counter_recorded(self, coupled_fleet, ocp):
        from agentlib_mpc_tpu import telemetry

        was = telemetry.enabled()
        telemetry.configure(enabled=True)
        try:
            thetas = _thetas(ocp)
            st = coupled_fleet.init_state(thetas)
            st = st._replace(w=st.w.at[0, 1].set(jnp.nan))
            coupled_fleet.step(st, thetas)
            count = telemetry.metrics().get(
                "scenario_quarantined_iters", group="scenario-test")
            assert count and count >= 1
        finally:
            telemetry.configure(enabled=was)


class TestDegenerateSupervisor:
    """ISSUE 14 satellite: the degenerate-contract EXTENSION — an S=1
    ScenarioFleetSupervisor run (degrade → serve → readmit) is BITWISE
    identical to the flat FleetSupervisor on the same group, because
    the S=1 supervisor routes UNWRAPPED through the flat machinery
    (state types, mesh and engines included)."""

    def test_s1_supervisor_is_flat_supervisor_bitwise(
            self, group, ocp, eight_devices):
        from agentlib_mpc_tpu.parallel.fused_admm import stack_params
        from agentlib_mpc_tpu.parallel.survival import (
            FleetSupervisor,
            ScenarioFleetSupervisor,
        )

        sup = ScenarioFleetSupervisor(
            group, single_scenario(), OPTS, mesh=fleet_mesh(),
            watchdog_timeout_s=60.0, readmit_after=1,
            probation_rounds=1)
        assert sup._flat is not None
        ref = FleetSupervisor(
            [group], sup.flat_options, mesh=fleet_mesh(),
            watchdog_timeout_s=60.0, readmit_after=1,
            probation_rounds=1)
        thetas = [stack_params([
            ocp.default_params(p=jnp.array([float(i + 1)]))
            for i in range(N_AGENTS)])]
        ss, rs = sup.init_state(thetas), ref.init_state(thetas)
        dead = sup._flat.full_mesh.devices.flat[-1].id
        ss, _t, _s = sup.step(ss, thetas)
        rs, _t, _s = ref.step(rs, thetas)
        sup.force_degrade([dead])
        ref.force_degrade([dead])
        assert sup.stats()["degraded"] and sup.scenarios_active == 1
        ss, _t, _s = sup.step(ss, thetas)
        rs, _t, _s = ref.step(rs, thetas)
        sup.force_readmit()
        ref.force_readmit()
        ss, _t, _s = sup.step(ss, thetas)
        rs, _t, _s = ref.step(rs, thetas)
        # BITWISE: the degenerate supervisor IS the flat one
        np.testing.assert_array_equal(
            np.asarray(ss.zbar["shared_u"]),
            np.asarray(rs.zbar["shared_u"]))
        np.testing.assert_array_equal(np.asarray(ss.w[0]),
                                      np.asarray(rs.w[0]))
        for a in ss.lam:
            np.testing.assert_array_equal(np.asarray(ss.lam[a][0]),
                                          np.asarray(rs.lam[a][0]))


class TestTelemetry:
    def test_scenario_metrics_recorded(self, coupled_fleet, ocp):
        from agentlib_mpc_tpu import telemetry

        was = telemetry.enabled()
        telemetry.configure(enabled=True)
        try:
            thetas = _thetas(ocp)
            st = coupled_fleet.init_state(thetas)
            coupled_fleet.step(st, thetas)
            reg = telemetry.metrics()
            (count_sample,) = [
                s for s in reg.gauge("scenario_count").samples()]
            assert count_sample["value"] == N_SCEN
            spread_samples = reg.histogram(
                "scenario_spread").samples()
            assert spread_samples
        finally:
            telemetry.configure(enabled=was)
