"""Fixed-step ODE integrators as jit-friendly scans.

TPU-native replacement for the CVODES/IDAS integrators the reference drives
through ``ca.integrator`` (``agentlib_mpc/models/casadi_model.py:402-447``;
multiple-shooting integrator choice euler/rk/cvodes at
``optimization_backends/casadi_/basic.py:450-476``). Explicit euler and RK4
cover the reference's fast paths; an implicit-midpoint method with a fixed
Newton iteration covers moderately stiff plants while staying
shape-static and differentiable (no adaptive step control inside jit).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

ODE = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]  # f(x, t) -> dx/dt


def euler_step(f: ODE, x, t, h):
    return x + h * f(x, t)


def rk4_step(f: ODE, x, t, h):
    k1 = f(x, t)
    k2 = f(x + 0.5 * h * k1, t + 0.5 * h)
    k3 = f(x + 0.5 * h * k2, t + 0.5 * h)
    k4 = f(x + h * k3, t + h)
    return x + (h / 6.0) * (k1 + 2.0 * k2 + 2.0 * k3 + k4)


def implicit_midpoint_step(f: ODE, x, t, h, newton_iters: int = 5):
    """Implicit midpoint rule, solved with a fixed number of Newton steps.

    A-stable: suitable for the stiff building-physics plants the reference
    hands to CVODES. The Newton loop is a lax.fori_loop with a dense linear
    solve on the (small) state dimension.
    """
    n = x.shape[0]
    eye = jnp.eye(n, dtype=x.dtype)

    def residual(x_next):
        xm = 0.5 * (x + x_next)
        return x_next - x - h * f(xm, t + 0.5 * h)

    jac = jax.jacfwd(residual)

    def body(_, x_next):
        r = residual(x_next)
        J = jac(x_next)
        dx = jnp.linalg.solve(J + 1e-10 * eye, -r)
        return x_next + dx

    x0 = x + h * f(x, t)  # explicit predictor
    return jax.lax.fori_loop(0, newton_iters, body, x0)


_STEPPERS = {
    "euler": euler_step,
    "rk4": rk4_step,
    "implicit_midpoint": implicit_midpoint_step,
}


def integrate(f: ODE, x0, t0, dt, substeps: int = 1, method: str = "rk4"):
    """Integrate x' = f(x, t) from t0 over dt with `substeps` fixed steps."""
    stepper = _STEPPERS[method]
    h = dt / substeps

    def body(x, i):
        return stepper(f, x, t0 + i * h, h), None

    x_final, _ = jax.lax.scan(body, x0, jnp.arange(substeps))
    return x_final
