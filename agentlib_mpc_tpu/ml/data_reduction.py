"""GPR training-data reduction via Nystroem inducing points.

Counterpart of the reference's ``NystroemReducer``
(``modules/ml_model_training/data_reduction.py:33-52``): exact GPR
prediction costs O(n) per query in the training-set size, which lands in
the jitted OCP; reducing to m inducing points caps the on-device
``k(x, X_train) @ alpha`` matvec at m rows.
"""

from __future__ import annotations

import numpy as np


class NystroemReducer:
    """Select m inducing points and re-fit targets on them.

    ``reduce(X, y)`` returns (X_m, y_m) where X_m are m rows chosen by
    k-means (cluster centers mapped to nearest samples) and y_m the
    corresponding targets — a drop-in smaller training set for `fit_gpr`.
    """

    def __init__(self, n_components: int = 100, seed: int = 0):
        self.n_components = int(n_components)
        self.seed = int(seed)

    def reduce(self, X, y) -> tuple[np.ndarray, np.ndarray]:
        X = np.asarray(X, dtype=float)
        y = np.asarray(y, dtype=float).reshape(len(X), -1)
        m = min(self.n_components, len(X))
        if m >= len(X):
            return X, y
        from sklearn.cluster import KMeans

        km = KMeans(n_clusters=m, random_state=self.seed, n_init=3).fit(X)
        idx = []
        for center in km.cluster_centers_:
            idx.append(int(np.argmin(np.sum((X - center) ** 2, axis=1))))
        idx = sorted(set(idx))
        return X[idx], y[idx]
