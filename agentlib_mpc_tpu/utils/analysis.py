"""Results persistence, loading and slicing.

Counterpart of the reference's ``utils/analysis.py`` (load_mpc :21-25,
load_sim :41-46, mpc_at_time_step :108-163, admm_at_time_step :166-241,
iteration counts :244-255, index conversion :49-76). The on-disk layout is
the reference's: MPC results are MultiIndex (time, grid) CSVs with
two-level columns, ADMM results (time, iteration, grid), simulator and
stats tables flat time-indexed CSVs — so analyses written against the
reference port mechanically.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union

import numpy as np

from agentlib_mpc_tpu.utils.time_utils import TIME_CONVERSION


# -- saving -------------------------------------------------------------------

def save_mpc(df, path) -> None:
    df.to_csv(path)


def save_results(results: dict, directory: Union[str, Path]) -> dict:
    """Write a LocalMAS ``get_results()`` tree to ``directory`` as
    ``<agent>_<module>[ _<part>].csv``. Returns {key: path}."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    written = {}
    for agent_id, modules in results.items():
        if not isinstance(modules, dict):
            continue
        for module_id, res in modules.items():
            parts = res.items() if isinstance(res, dict) else [("", res)]
            for part, df in parts:
                if df is None or not hasattr(df, "to_csv"):
                    continue
                name = f"{agent_id}_{module_id}" + (f"_{part}" if part
                                                    else "")
                path = directory / f"{name}.csv"
                df.to_csv(path)
                written[name] = path
    return written


# -- loading ------------------------------------------------------------------

def load_mpc(path) -> "pd.DataFrame":
    """(time, grid)-indexed MPC results with ('variable', name) columns
    (reference ``load_mpc``, ``analysis.py:21-25``)."""
    import pandas as pd

    return pd.read_csv(path, index_col=[0, 1], header=[0, 1])


def load_admm(path) -> "pd.DataFrame":
    """(time, iteration, grid)-indexed ADMM results with the two-level
    ('variable', name) column header (reference ``load_admm`` delegates
    to ``load_mpc`` with ``header=[0, 1]``, ``utils/analysis.py:17-25``;
    layout from ``casadi_/admm.py:364-424``)."""
    import pandas as pd

    return pd.read_csv(path, index_col=[0, 1, 2], header=[0, 1])


def load_sim(path, causality=None) -> "pd.DataFrame":
    """Flat time-indexed simulator results (reference ``load_sim``,
    ``analysis.py:41-46``)."""
    import pandas as pd

    return pd.read_csv(path, index_col=0)


def load_mpc_stats(path) -> "pd.DataFrame":
    import pandas as pd

    return pd.read_csv(path, index_col=0)


# -- index handling -----------------------------------------------------------

def convert_index(df, to_unit: str = "hours", from_unit: str = "seconds",
                  level: Union[int, str] = 0):
    """Convert one level of a (Multi)Index between time units (reference
    ``convert_multi_index``/``convert_index``, ``analysis.py:49-76``)."""
    import pandas as pd

    factor = TIME_CONVERSION[from_unit] / TIME_CONVERSION[to_unit]
    if isinstance(df.index, pd.MultiIndex):
        values = [np.asarray(df.index.get_level_values(i), dtype=float)
                  for i in range(df.index.nlevels)]
        pos = level if isinstance(level, int) \
            else df.index.names.index(level)
        values[pos] = values[pos] * factor
        df = df.copy()
        df.index = pd.MultiIndex.from_arrays(values, names=df.index.names)
        return df
    df = df.copy()
    df.index = np.asarray(df.index, dtype=float) * factor
    return df


# -- slicing ------------------------------------------------------------------

def _nearest_time(times: np.ndarray, time_step: Optional[float]):
    times = np.unique(np.asarray(times, dtype=float))
    if time_step is None:
        return times[-1]
    idx = int(np.argmin(np.abs(times - float(time_step))))
    return times[idx]


def mpc_at_time_step(data, time_step: Optional[float] = None,
                     variable: Optional[str] = None,
                     index_offset: bool = True):
    """One solve's predicted trajectory, grid offsets made absolute
    (reference ``mpc_at_time_step``, ``analysis.py:108-163``): pass the
    closed-loop time of the solve (nearest match; None = last)."""
    t = _nearest_time(data.index.get_level_values(0), time_step)
    sl = data.loc[t]
    if index_offset:
        sl = sl.copy()
        sl.index = np.asarray(sl.index, dtype=float) + float(t)
    if variable is not None:
        cols = sl.columns
        if hasattr(cols, "nlevels") and cols.nlevels == 2:
            return sl[("variable", variable)]
        return sl[variable]
    return sl


def admm_at_time_step(data, time_step: Optional[float] = None,
                      variable: Optional[str] = None,
                      iteration: Optional[float] = None,
                      index_offset: bool = True):
    """Slice ADMM results at a control step; ``iteration=None`` → all
    iterations of that step (reference ``admm_at_time_step``,
    ``analysis.py:166-241``)."""
    t = _nearest_time(data.index.get_level_values(0), time_step)
    sl = data.loc[t]
    if iteration is not None:
        iters = np.unique(np.asarray(
            sl.index.get_level_values(0), dtype=float))
        it = iters[int(np.argmin(np.abs(iters - float(iteration))))]
        sl = sl.loc[it]
        if index_offset:
            sl = sl.copy()
            sl.index = np.asarray(sl.index, dtype=float) + float(t)
    if variable is not None:
        cols = sl.columns
        if hasattr(cols, "nlevels") and cols.nlevels == 2:
            return sl[("variable", variable)]
        return sl[variable]
    return sl


def get_number_of_iterations(data) -> dict:
    """time → ADMM iteration count (reference ``analysis.py:244-255``)."""
    out = {}
    for t in np.unique(np.asarray(data.index.get_level_values(0),
                                  dtype=float)):
        out[t] = len(np.unique(np.asarray(
            data.loc[t].index.get_level_values(0), dtype=float)))
    return out


def first_vals_at_trajectory_index(data):
    """First value of each solve's trajectory — the closed-loop signal
    (reference ``analysis.py:263-278``)."""
    import pandas as pd

    times = np.unique(np.asarray(data.index.get_level_values(0),
                                 dtype=float))
    return pd.Series({t: data.loc[t].iloc[0] for t in times})


def last_vals_at_trajectory_index(data):
    import pandas as pd

    times = np.unique(np.asarray(data.index.get_level_values(0),
                                 dtype=float))
    return pd.Series({t: data.loc[t].iloc[-1] for t in times})
