"""MPC result plots with prediction fade (reference
``utils/plotting/mpc.py:48+``): every solve's predicted trajectory is
drawn with opacity growing toward the most recent solve, the realized
closed-loop signal on top."""

from __future__ import annotations

from typing import Optional

import numpy as np

from agentlib_mpc_tpu.utils.analysis import (
    first_vals_at_trajectory_index,
    mpc_at_time_step,
)
from agentlib_mpc_tpu.utils.plotting.basic import COLORS, Style, make_fig


def plot_mpc(data, variable: str, ax=None, plot_actual_values: bool = True,
             plot_predictions: bool = True, color: Optional[str] = None,
             style: Optional[Style] = None):
    """data: (time, grid)-MultiIndex results (module ``results()`` or
    ``analysis.load_mpc``). Returns the axis."""
    if ax is None:
        _, axes = make_fig(style)
        ax = axes[0, 0]
    color = color or COLORS["blue"]
    times = np.unique(np.asarray(data.index.get_level_values(0),
                                 dtype=float))
    if plot_predictions:
        n = len(times)
        for i, t in enumerate(times):
            series = mpc_at_time_step(data, t, variable)
            alpha = 0.1 + 0.5 * (i + 1) / n
            ax.plot(series.index, series.to_numpy(dtype=float),
                    color=color, alpha=alpha, linewidth=0.8)
    if plot_actual_values:
        cols = data.columns
        key = ("variable", variable) if getattr(cols, "nlevels", 1) == 2 \
            else variable
        actual = first_vals_at_trajectory_index(data[key])
        ax.plot(actual.index, actual.to_numpy(dtype=float), color=color,
                linewidth=1.8, label=variable)
    ax.set_xlabel("time / s")
    ax.set_ylabel(variable)
    return ax


def plot_mpc_plan(data, variable: str, time_step: Optional[float] = None,
                  ax=None, color: Optional[str] = None):
    """A single solve's plan (reference per-step plan plot)."""
    if ax is None:
        _, axes = make_fig()
        ax = axes[0, 0]
    series = mpc_at_time_step(data, time_step, variable)
    ax.step(series.index, series.to_numpy(dtype=float), where="post",
            color=color or COLORS["red"], label=f"{variable} plan")
    ax.set_xlabel("time / s")
    ax.set_ylabel(variable)
    return ax
