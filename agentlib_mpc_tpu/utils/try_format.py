"""German Test-Reference-Year (TRY) weather file parsing.

Counterpart of the reference's TRY support: its ``TRYPredictor`` subclasses
agentlib's TRYSensor and publishes eleven weather quantities parsed from
DWD TRY datasets (``modules/InputPrediction/try_predictor.py:7-90``; the
reference ships ``examples/three_zone_datadriven_admm/TRY2015_Aachen_Jahr.dat``).

File layout (DWD TRY 2015): a free-text header terminated by a ``***``
line, then hourly rows of whitespace-separated columns

    RW HW MM DD HH  t  p  WR WG N  x  RF B  D  A  E  IL

This parser maps them to the reference's published variable names, converts
air temperature to Kelvin (the reference publishes ``T_oda`` in K), and
indexes rows in seconds from the file start (hourly grid) so the result
plugs straight into :class:`~agentlib_mpc_tpu.modules.data_source.DataSource`
/ :class:`~agentlib_mpc_tpu.modules.input_prediction.InputPredictor`.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

#: data-row columns of a TRY 2015 file, in file order
_RAW_COLUMNS = ("RW", "HW", "MM", "DD", "HH", "t", "p", "WR", "WG", "N",
                "x", "RF", "B", "D", "A", "E", "IL")

#: raw column → published quantity name (reference predictor's variables,
#: ``try_predictor.py:13-68``); RW/HW/date columns and the quality bit are
#: metadata, not measurements
TRY_QUANTITIES = {
    "t": "T_oda",                 # air temperature 2 m [K] (converted)
    "p": "pressure",              # air pressure [hPa]
    "WR": "wind_direction",       # [deg] {0..360; 999}
    "WG": "wind_speed",           # [m/s]
    "N": "coverage",              # cloud coverage [eighth] {0..8; 9}
    "x": "absolute_humidity",     # mixing ratio [g/kg]
    "RF": "relative_humidity",    # [%] {1..100}
    "B": "beam_direct",           # direct solar beam, horizontal [W/m2]
    "D": "beam_diffuse",          # diffuse solar beam, horizontal [W/m2]
    "A": "beam_atm",              # atmospheric counter-radiation [W/m2]
    "E": "beam_terr",             # terrestrial radiation [W/m2]
}

_HEADER_END = "***"
_HOUR = 3600.0


def read_try_file(path: str | Path):
    """Parse a TRY ``.dat`` file → DataFrame of the eleven published
    quantities on an hourly seconds index (0, 3600, 7200, ...).

    Air temperature is converted °C → K under the reference's ``T_oda``
    name; all other columns keep the file's units.
    """
    import pandas as pd

    lines = Path(path).read_text().splitlines()
    data_start = None
    for i, line in enumerate(lines):
        if line.strip().startswith(_HEADER_END):
            data_start = i + 1
            break
    if data_start is None:
        raise ValueError(
            f"{path}: not a TRY file (no '{_HEADER_END}' header terminator)")

    rows = []
    for line in lines[data_start:]:
        parts = line.split()
        if len(parts) != len(_RAW_COLUMNS):
            if parts:  # tolerate blank lines, reject malformed data
                raise ValueError(
                    f"{path}: malformed TRY data row (expected "
                    f"{len(_RAW_COLUMNS)} columns, got {len(parts)}): "
                    f"{line!r}")
            continue
        rows.append([float(p) for p in parts])
    if not rows:
        raise ValueError(f"{path}: TRY file contains no data rows")

    raw = np.asarray(rows)
    out = {}
    for col, name in TRY_QUANTITIES.items():
        vals = raw[:, _RAW_COLUMNS.index(col)]
        if col == "t":
            vals = vals + 273.15
        out[name] = vals
    index = np.arange(len(rows)) * _HOUR
    return pd.DataFrame(out, index=index)


def try_forecast_ensemble(df, column: str, t0: float, horizon_steps: int,
                          n_scenarios: int, seed: int = 0,
                          spread: "float | None" = None,
                          dt: float = _HOUR) -> np.ndarray:
    """Batched forecast ensemble from a parsed TRY table: ``(S,
    horizon_steps)`` trajectories of ``column`` starting at ``t0``
    (seconds on the table's index) on a ``dt`` grid — row 0 the nominal
    interpolated series, rows 1.. seeded random-walk perturbations from
    the chaos harness's :func:`~agentlib_mpc_tpu.resilience.chaos.
    disturbance_model` (one deterministic source for scenario
    generation AND chaos replays; equal arguments reproduce the
    identical ensemble). ``spread`` is the per-step walk sigma; None
    defaults to 5% of the window's peak-to-peak range.

    The rows plug straight into
    :func:`agentlib_mpc_tpu.scenario.generate.scenario_thetas` as one
    exogenous channel's per-scenario ``d_traj`` column."""
    from agentlib_mpc_tpu.resilience.chaos import disturbance_model

    if column not in df.columns:
        raise KeyError(
            f"column {column!r} not in the TRY table "
            f"({sorted(df.columns)})")
    grid = float(t0) + np.arange(int(horizon_steps)) * float(dt)
    base = np.interp(grid, np.asarray(df.index, dtype=float),
                     np.asarray(df[column], dtype=float))
    sigma = float(spread) if spread is not None else \
        0.05 * float(np.ptp(base)) if base.size else 0.0
    draws = disturbance_model(
        seed=seed + int(t0), horizon=base.shape[0],
        n_scenarios=int(n_scenarios), scale=sigma, kind="walk")
    return base[None, :] + draws[:, :, 0]


def is_try_file(path) -> bool:
    """Cheap sniff: TRY files are ``.dat`` with a ``***`` header separator
    in their first ~60 lines."""
    p = Path(path)
    if p.suffix.lower() != ".dat":
        return False
    try:
        with open(p, "r", errors="replace") as fh:
            for _ in range(60):
                line = fh.readline()
                if not line:
                    return False
                if line.strip().startswith(_HEADER_END):
                    return True
    except OSError:
        return False
    return False
