"""Single-zone cooling MPC — the minimum end-to-end slice.

Native re-build of the reference's flagship example
(``examples/one_room_mpc/physical/simple_mpc.py``): a one-state zone model
with soft comfort constraint, collocation transcription, and a closed loop
of plant simulation + MPC solve every 300 s. The reference runs CasADi +
IPOPT per step; here the whole controller step (warm-started interior-point
solve) is one jitted XLA computation and the plant integrator another.

Run:  python examples/one_room_mpc.py
Prints the same closed-loop metrics as the reference example
(``simple_mpc.py:254-264``): absolute integral comfort error (K·h) and
cooling energy (kWh).
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from agentlib_mpc_tpu.models.zoo import OneRoom
from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
from agentlib_mpc_tpu.ops.transcription import transcribe

UB_COMFORT = 295.15  # K, soft upper comfort bound


def run_example(until: float = 7200.0, time_step: float = 300.0,
                prediction_horizon: int = 15, t_sample: float = 10.0,
                verbose: bool = True):
    """Closed loop: plant at `t_sample` resolution, MPC every `time_step`."""
    model = OneRoom(overrides={"s_T": 0.001, "r_mDot": 0.01})
    ocp = transcribe(model, ["mDot"], N=prediction_horizon, dt=time_step,
                     method="collocation", collocation_degree=2,
                     collocation_method="legendre")
    # tol reachable in f64; the stall-acceptance criteria cover the f32
    # (TPU) precision floor
    opts = SolverOptions(tol=1e-6, max_iter=60)

    @jax.jit
    def mpc_step(x0, u_prev, w_guess, y_guess, z_guess, mu0):
        theta = ocp.default_params(
            x0=x0, u_prev=u_prev,
            d_traj=jnp.broadcast_to(
                jnp.array([150.0, 290.15, UB_COMFORT]),
                (prediction_horizon, 3)),
        )
        lb, ub = ocp.bounds(theta)
        res = solve_nlp(ocp.nlp, w_guess, theta, lb, ub, opts,
                        y0=y_guess, z0=z_guess, mu0=mu0)
        traj = ocp.trajectories(res.w, theta)
        u0 = jnp.clip(traj["u"][0], theta.u_lb[0], theta.u_ub[0])
        next_guess = ocp.shift_guess(res.w, theta)
        return u0, next_guess, res.y, res.z, res.stats, traj

    plant_substeps = round(time_step / t_sample)
    if abs(plant_substeps * t_sample - time_step) > 1e-9:
        raise ValueError(
            f"t_sample={t_sample} must divide time_step={time_step}")

    @jax.jit
    def plant_roll(x, u_ctrl):
        u_full = model.default_vector("inputs")
        u_full = u_full.at[model.input_index("mDot")].set(u_ctrl[0])

        def sub(xx, _):
            xn, y = model.simulate_step(xx, u_full,
                                        model.default_vector("parameters"),
                                        dt=t_sample, substeps=2)
            return xn, y[0]

        x_next, temps = jax.lax.scan(sub, x, jnp.arange(plant_substeps))
        return x_next, temps

    n_steps = int(until / time_step)
    x = jnp.array([298.16])
    u_prev = jnp.array([0.02])
    theta0 = ocp.default_params(x0=x, u_prev=u_prev)
    w_guess = ocp.initial_guess(theta0)
    # cold duals for the first solve; thereafter warm-start primal AND dual
    # with a small barrier (the payoff of a persistent jitted solver state)
    y_guess = jnp.zeros((ocp.n_g,))
    # strong-typed like the solver's returned duals, so feeding results back
    # in at step 1 doesn't retrace (weak→strong aval mismatch)
    z_guess = jnp.full((ocp.n_h,), 0.1).astype(y_guess.dtype)

    temps_all, mdot_all, solve_times, stats_rows = [], [], [], []
    for k in range(n_steps):
        t0 = time.perf_counter()
        mu0 = jnp.asarray(0.1 if k == 0 else 1e-2)
        u0, w_guess, y_guess, z_guess, stats, traj = mpc_step(
            x, u_prev, w_guess, y_guess, z_guess, mu0)
        u0.block_until_ready()
        solve_times.append(time.perf_counter() - t0)
        x, temps = plant_roll(x, u0)
        temps_all.append(temps)
        mdot_all.append(jnp.full((plant_substeps,), u0[0]))
        u_prev = u0
        stats_rows.append(stats)
        if verbose and k % 4 == 0:
            print(f"t={k*time_step:6.0f}s  T={float(x[0]):.2f}K  "
                  f"mDot={float(u0[0]):.4f}  iters={int(stats.iterations)}  "
                  f"ok={bool(stats.success)}  "
                  f"solve={solve_times[-1]*1e3:.1f}ms")

    temps = jnp.concatenate(temps_all)
    mdots = jnp.concatenate(mdot_all)
    # closed-loop metrics as printed by the reference (simple_mpc.py:254-264)
    aie_kh = float(jnp.sum(jnp.abs(temps - UB_COMFORT)) * t_sample / 3600.0)
    energy_kwh = float(jnp.sum(mdots * (temps - 290.15)) * t_sample / 3600.0)
    meta = {
        "aie_kh": aie_kh,
        "energy_kwh": energy_kwh,
        "mean_solve_ms": 1e3 * sum(solve_times[1:]) / max(len(solve_times) - 1, 1),
        "first_solve_ms": 1e3 * solve_times[0],
        "all_success": all(bool(s.success) for s in stats_rows),
        "final_T": float(x[0]),
        "temps": temps,
        "mdots": mdots,
    }
    if verbose:
        print(f"Absolute integral error: {aie_kh:.3f} Kh.")
        print(f"Cooling energy used: {energy_kwh:.3f} kWh.")
        print(f"Mean solve time (warm): {meta['mean_solve_ms']:.1f} ms "
              f"(first incl. compile: {meta['first_solve_ms']:.0f} ms)")
    return meta


if __name__ == "__main__":
    run_example()
