"""Fleet survivability on the sharded mesh (ISSUE 10).

Pins the recovery ladder of :class:`FleetSupervisor` on the 8-virtual-
device CPU mesh: the collective watchdog condemning a hung round, the
elastic degraded-mesh fallback (shard loss -> masked lanes -> re-pad on
the survivors), the consensus carry-over guard, hysteretic re-admission
restoring the full-mesh computation BITWISE, and the bounded watchdog
reader (the PR 8 leaked-daemon-thread fix).

Engine builds dominate the cost (the IPM's Python trace is outside the
persistent XLA cache), so the supervisor + its single-device reference
are ONE module fixture; the chaos acceptance test drives the same
supervisor through loss AND revival so the degraded layout compiles
once for the whole module.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel import fleet_mesh
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)
from agentlib_mpc_tpu.parallel.multihost import (
    MeshRoundTimeout,
    probe_mesh_devices,
    surviving_mesh,
)
from agentlib_mpc_tpu.parallel.survival import FleetSupervisor
from agentlib_mpc_tpu.utils.watchdog import BoundedReader

from conftest import make_tracker_model  # noqa: E402

SOLVER = SolverOptions(tol=1e-8, max_iter=30)
# 25 iterations: a degrade genuinely moves the consensus to the
# survivors' equilibrium (the multiplier re-centering at membership
# transitions), and that re-convergence takes ~15 halving steps at
# abs_tol=1e-6 — a budget of 8 would report honest non-convergence
OPTS = FusedADMMOptions(max_iterations=25, rho=2.0, abs_tol=1e-6,
                        rel_tol=1e-5)
UB = 10.0

Tracker = make_tracker_model(lb=-UB, ub=UB)


@pytest.fixture(scope="module")
def rig(eight_devices):
    """(supervisor, reference single-device engine, thetas): built once
    — every survivability test drives the same warm machinery."""
    ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                     method="multiple_shooting")
    group = AgentGroup(name="surv", ocp=ocp, n_agents=8,
                       couplings={"c": "u"}, solver_options=SOLVER)
    thetas = [stack_params([
        ocp.default_params(p=jnp.array([float(t)])) for t in range(8)])]
    ref = FusedADMM([group], OPTS)
    sup = FleetSupervisor([group], OPTS, mesh=fleet_mesh(),
                          watchdog_timeout_s=60.0, readmit_after=1,
                          probation_rounds=1)
    return sup, ref, thetas


class TestCollectiveWatchdog:
    def test_probe_reports_all_virtual_devices(self, eight_devices):
        report = probe_mesh_devices(fleet_mesh(), timeout_s=30.0)
        assert len(report.answered) == len(jax.devices())
        assert report.all_answered and not report.dead
        small = surviving_mesh(fleet_mesh(), report.answered[:4])
        assert int(small.devices.size) == 4

    def test_hung_round_condemns_mesh_and_probes(self, rig):
        """The PR 8 materialize-watchdog pattern one layer down: a
        dispatch that outlives the budget raises MeshRoundTimeout
        carrying the per-device probe, and condemns the engine."""
        sup, _ref, thetas = rig
        engine = sup.engine
        state = sup.init_state(thetas)
        orig_step, orig_budget = engine._step, engine.watchdog_timeout_s
        engine.watchdog_timeout_s = 0.2

        def hung(*args):
            time.sleep(3.0)
            return orig_step(*args)

        engine._step = hung
        try:
            with pytest.raises(MeshRoundTimeout) as exc:
                engine.step(state, thetas)
        finally:
            engine._step = orig_step
            engine.watchdog_timeout_s = orig_budget
        assert engine.mesh_condemned
        # every virtual device answers: the probe exonerates the shards
        assert exc.value.probe is not None
        assert exc.value.probe.all_answered
        assert engine.shard_report is exc.value.probe
        engine.mesh_condemned = False

    def test_watchdog_rejects_donated_engine(self, rig):
        sup, _ref, _ = rig
        group = sup.base_groups[0]
        with pytest.raises(ValueError, match="donate_state"):
            FusedADMM([group], OPTS, donate_state=True,
                      watchdog_timeout_s=1.0)


class TestShardLossAcceptance:
    def test_kill_one_shard_mid_run(self, rig, tmp_path):
        """The ISSUE 10 acceptance row: kill one shard of the
        8-virtual-device fused fleet mid-run. Surviving agents' controls
        stay finite and bounded, the fleet completes the round on the
        degraded mesh, and re-admission restores full-mesh consensus
        BITWISE vs an uninterrupted engine stepping the same state.

        ISSUE 15 rides the same run: the flight recorder is on, and the
        injection → condemnation/degrade → readmit chain is asserted
        afterwards FROM THE JOURNAL ALONE (the chaos object is used
        only to install the fault, never to assert)."""
        from agentlib_mpc_tpu import telemetry
        from agentlib_mpc_tpu.resilience.chaos import (
            MeshChaosConfig,
            MeshDeviceLossRule,
            install_mesh_chaos,
        )

        sup, _ref, thetas = rig
        victim = 6
        journal_path = str(tmp_path / "mesh.jsonl")
        telemetry.enable_journal(journal_path)
        chaos = install_mesh_chaos(sup, MeshChaosConfig(
            device_loss=(MeshDeviceLossRule(
                device_index=victim, die_at_round=1, revive_at_round=3),),
        ), seed=0)
        # a short budget so the hang is condemned fast; the supervisor
        # gives a fresh layout's first round its own compile allowance
        for layout in sup._layouts.values():
            layout.engine.watchdog_timeout_s = 2.0
        sup.watchdog_timeout_s = 2.0
        try:
            state = sup.init_state(thetas)
            state, _t, _s = sup.step(state, thetas)          # round 0
            state, trajs, stats = sup.step(state, thetas)    # loss hits
            assert sup.degraded and sup.mesh_devices == 7
            assert list(sup.dead_lanes[0]).count(True) == 1
            u = np.asarray(trajs[0]["u"])
            survivors = [i for i in range(8) if i != victim]
            assert np.isfinite(u[survivors]).all()
            assert (np.abs(u[survivors]) <= UB + 1e-9).all()
            assert bool(stats.converged)
            # base-layout shapes even while a 14-lane padded batch
            # serves underneath
            assert u.shape[0] == 8
            state, _t, _s = sup.step(state, thetas)          # round 2
            # device revives at round 3; hysteresis re-admits
            state, _t, _s = sup.step(state, thetas)
            assert not sup.degraded and sup.mesh_devices == 8
        finally:
            for layout in sup._layouts.values():
                layout.engine.watchdog_timeout_s = 60.0
            sup.watchdog_timeout_s = 60.0
            chaos.uninstall()
            telemetry.disable_journal()
        # -- flight-recorder leg: the journal ALONE ----------------------
        from agentlib_mpc_tpu.telemetry import journal as journal_mod
        from agentlib_mpc_tpu.telemetry.incident import build_incident

        events = journal_mod.read_events(journal_path)
        injected = [e for e in events
                    if e["etype"] == "chaos.injected"]
        assert injected, "chaos did not self-record into the journal"
        assert all(e.get("rule") and e.get("target") is not None
                   and e.get("round") is not None for e in injected)
        assert {"watchdog.condemned", "mesh.degrade",
                "mesh.readmit", "fleet.round"} <= \
            {e["etype"] for e in events}
        rep = build_incident(events)
        loss_chains = [
            c for c in rep["chains"]
            if c["injection"]["rule"] in ("mesh_device_hang",
                                          "mesh_probe_dead")
            and c["status"] == "complete"]
        assert loss_chains, rep["chains"]
        assert loss_chains[0]["symptom"]["etype"] in (
            "watchdog.condemned", "mesh.degrade")
        assert loss_chains[0]["recovery"]["etype"] == "mesh.readmit"
        # bitwise: an INDEPENDENT, never-interrupted full-mesh engine
        # (same structure, same mesh => same deterministic executable)
        # stepping the same post-recovery state reproduces the
        # recovered fleet's consensus exactly — re-admission restored
        # the full-mesh computation, not an approximation of it
        uninterrupted = FusedADMM([sup.base_groups[0]], OPTS,
                                  mesh=fleet_mesh())
        rs, _rt, _ = uninterrupted.step(
            *uninterrupted.shard_args(sup.full_mesh, state, thetas))
        ss, _st, _ = sup.step(state, thetas)
        assert np.array_equal(np.asarray(ss.zbar["c"]),
                              np.asarray(rs.zbar["c"]))
        assert sup.stats()["layouts_built"] == 2

    def test_cascading_loss_marks_current_layout_lanes(
            self, eight_devices):
        """A SECOND device loss happens on the already-degraded mesh,
        whose rows-per-device and device positions differ from the full
        layout's — dead-lane attribution must follow the CURRENT
        layout's row assignment (a dying shard that hosts only padding
        rows masks nothing). Construction-only: no engine ever steps,
        so this costs no compile."""
        ocp = transcribe(Tracker(), ["u"], N=4, dt=300.0,
                         method="multiple_shooting")
        group = AgentGroup(name="casc", ocp=ocp, n_agents=8,
                          couplings={"c": "u"}, solver_options=SOLVER)
        sup = FleetSupervisor([group], OPTS, mesh=fleet_mesh(),
                              watchdog_timeout_s=60.0)
        ids = list(sup._full_ids)
        sup.force_degrade([ids[3]])
        assert list(np.where(sup.dead_lanes[0])[0]) == [3]
        # degraded layout: 7 devices x 2 rows (8 agents padded to 14);
        # the device at CURRENT position 6 (full position 7) hosts rows
        # 12/13 — both padding — so losing it kills NO further lane ...
        current = list(sup._current.device_ids)
        sup.force_degrade([current[6]])
        assert list(np.where(sup.dead_lanes[0])[0]) == [3]
        # ... while CURRENT position 2 hosts base rows 4/5
        current = list(sup._current.device_ids)
        sup.force_degrade([current[2]])
        assert list(np.where(sup.dead_lanes[0])[0]) == [3, 4, 5]

    def test_degraded_carry_must_match_pre_failure_iterate(self, rig):
        """The consensus carry-over guard: a degraded-mesh resume whose
        replicated leaves drift from the pre-failure iterate is refused
        (corrupted carry, not a recovery)."""
        sup, _ref, thetas = rig
        state = sup.init_state(thetas)
        state, _t, _s = sup.step(state, thetas)
        # same victim as the acceptance test: the degraded layout is
        # already cached, so this unit costs no engine build
        dead = sup.full_mesh.devices.flat[6].id
        sup.force_degrade([dead])
        sup._consensus_snapshot = {
            ("zbar", "c"): np.asarray(state.zbar["c"]) + 1.0}
        try:
            with pytest.raises(RuntimeError, match="pre-failure"):
                sup._run_layout(sup._current, state, tuple(thetas),
                                sup.base_active)
        finally:
            sup.force_readmit()
            sup.step(state, thetas)        # consume the lane resets


class TestBoundedReader:
    """Satellite 1: the watchdog's leaked daemon threads are bounded,
    reused, and exported as a gauge."""

    def test_healthy_reads_reuse_one_worker(self):
        reader = BoundedReader(name="t-reuse", max_leaked=2)
        assert reader.run(lambda: 41 + 1, 5.0) == ("ok", 42)
        worker = reader._worker
        assert reader.run(lambda: "again", 5.0) == ("ok", "again")
        assert reader._worker is worker          # no thread churn
        assert reader.leaked == 0

    def test_errors_are_forwarded(self):
        reader = BoundedReader(name="t-err")

        def boom():
            raise RuntimeError("decode exploded")

        kind, exc = reader.run(boom, 5.0)
        assert kind == "err" and "decode exploded" in str(exc)
        assert reader.leaked == 0

    def test_leak_cap_saturates_without_waiting(self):
        reader = BoundedReader(name="t-cap", max_leaked=2)
        release = threading.Event()

        def wedge():
            release.wait(30.0)
            return "late"

        assert reader.run(wedge, 0.05)[0] == "timeout"
        assert reader.run(wedge, 0.05)[0] == "timeout"
        assert reader.leaked == 2
        t0 = time.perf_counter()
        kind, _ = reader.run(wedge, 10.0)
        assert kind == "saturated"
        # the refusal is immediate — no third timeout is burned
        assert time.perf_counter() - t0 < 1.0
        assert reader.saturations == 1
        release.set()

    def test_wedged_worker_is_recovered_after_unblocking(self):
        reader = BoundedReader(name="t-recover", max_leaked=4)
        release = threading.Event()
        assert reader.run(lambda: release.wait(30.0), 0.05)[0] == \
            "timeout"
        assert reader.leaked == 1
        wedged = reader._wedged[0]
        release.set()
        deadline = time.monotonic() + 5.0
        while reader.leaked and time.monotonic() < deadline:
            time.sleep(0.01)
        assert reader.leaked == 0
        # ... and it is the SAME worker that serves again — recovered
        # and reused, not dropped to idle forever while a fresh thread
        # answers (the silent-leak regression this pins)
        assert reader.run(lambda: "alive", 5.0) == ("ok", "alive")
        assert reader._worker is wedged

    def test_leak_gauge_exported(self, compile_profiler):
        from agentlib_mpc_tpu import telemetry

        reader = BoundedReader(name="t-gauge", max_leaked=3)
        release = threading.Event()
        reader.run(lambda: release.wait(30.0), 0.05)
        assert telemetry.metrics().get(
            "dispatch_watchdog_threads_leaked", reader="t-gauge") == 1.0
        release.set()
