"""Interactive dashboard internals: pure data layer + dash/plotly layer.

Capability port of the reference's interactive tooling
(``utils/plotting/mpc_dashboard.py`` — agent/module browsing, per-variable
prediction plots with fade, solver-stats and objective panels;
``utils/plotting/admm_dashboard.py`` — time-step/iteration sliders over
coupling variables plus Boyd-residual views; ``interactive.py:300``).

Design: everything the dashboard *computes* lives in pure functions over
the results dict / stats DataFrames so it is unit-testable without dash
installed (this environment has no dash); the dash/plotly app is a thin
layer over those functions, imported lazily and exercised by a stub-based
smoke test.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


# ---------------------------------------------------------------------------
# pure data layer
# ---------------------------------------------------------------------------

def discover_frames(results: dict) -> dict:
    """(agent_id, module_id) → DataFrame for every MultiIndex results frame
    in a ``mas.get_results()`` dict. 2-level = MPC/MHE, 3-level = ADMM."""
    frames = {}
    for agent_id, modules in (results or {}).items():
        if not isinstance(modules, dict):
            continue
        for module_id, df in modules.items():
            nlevels = getattr(getattr(df, "index", None), "nlevels", 1)
            if df is not None and nlevels in (2, 3):
                frames[(agent_id, module_id)] = df
    return frames


def frame_kind(df) -> str:
    """"admm" for (time, iter, grid) frames; 2-level frames split into
    "mhe" (backward horizon: negative grid offsets — the estimation
    module's `estimation_frame`) vs "mpc" (forward predictions)."""
    if df.index.nlevels == 3:
        return "admm"
    grid = df.index.get_level_values(-1)
    return "mhe" if len(grid) and float(np.min(grid)) < 0 else "mpc"


def variables_of(df) -> list:
    """Plottable variable names (('variable', name) columns, else flat)."""
    names = []
    for c in df.columns:
        if isinstance(c, tuple):
            if c[0] == "variable":
                names.append(c[1])
        else:
            names.append(c)
    return sorted(dict.fromkeys(names))


def time_steps_of(df) -> np.ndarray:
    """Sorted unique solve times (level 0 of the MultiIndex)."""
    return np.asarray(sorted(df.index.get_level_values(0).unique()))


def iterations_at(df, time) -> np.ndarray:
    """ADMM frames: sorted iteration numbers stored for one solve time."""
    sub = df.xs(time, level=0)
    return np.asarray(sorted(sub.index.get_level_values(0).unique()))


def _col(df, variable):
    return ("variable", variable) if ("variable", variable) in df.columns \
        else variable


def prediction_traces(df, variable: str, max_steps: Optional[int] = None):
    """[(t_solve, abs_times, values)] — one predicted trajectory per solve
    (the reference's fade plot, ``plot_mpc_plotly``). For ADMM frames the
    last stored iteration per step is used."""
    col = _col(df, variable)
    if col not in df.columns:
        return []
    times = time_steps_of(df)
    if max_steps is not None and len(times) > max_steps:
        idx = np.linspace(0, len(times) - 1, max_steps).astype(int)
        times = times[np.unique(idx)]
    out = []
    for t in times:
        sub = df.xs(t, level=0)
        if sub.index.nlevels == 2:  # admm: (iter, grid) → last iteration
            last_iter = sub.index.get_level_values(0).max()
            sub = sub.xs(last_iter, level=0)
        series = sub[col].dropna()
        grid = np.asarray(series.index, dtype=float)
        out.append((float(t), float(t) + grid,
                    np.asarray(series, dtype=float)))
    return out


def actual_series(df, variable: str):
    """(times, values): the realized closed-loop series — first value of
    each prediction (reference ``first_vals_at_trajectory_index``)."""
    traces = prediction_traces(df, variable)
    ts, vs = [], []
    for t, _, vals in traces:
        if len(vals):
            ts.append(t)
            vs.append(vals[0])
    return np.asarray(ts), np.asarray(vs)


def estimate_series(df, variable: str):
    """(times, values): the published estimate over time — the LAST node
    of each backward trajectory (grid offset 0 = "estimate at now")."""
    ts, vs = [], []
    for t, _, vals in prediction_traces(df, variable):
        if len(vals):
            ts.append(t)
            vs.append(vals[-1])
    return np.asarray(ts), np.asarray(vs)


def measurement_points(measurements, variable: str):
    """(times, values) scatter data for one variable from an
    MHE ``measurements_frame`` (columns may carry a ``measured_``
    prefix) — empty arrays when the variable has no measurements."""
    if measurements is None:
        return np.asarray([]), np.asarray([])
    for col in (variable, f"measured_{variable}"):
        if col in getattr(measurements, "columns", ()):
            series = measurements[col].dropna()
            return (np.asarray(series.index, dtype=float),
                    np.asarray(series, dtype=float))
    return np.asarray([]), np.asarray([])


def admm_iteration_traces(df, variable: str, time) -> list:
    """[(iteration, grid, values)] for one solve time — the iteration
    browser of the reference ADMM dashboard (``create_coupling_var_plot``)."""
    col = _col(df, variable)
    if col not in df.columns:
        return []
    sub = df.xs(time, level=0)
    out = []
    for it in sorted(sub.index.get_level_values(0).unique()):
        series = sub.xs(it, level=0)[col].dropna()
        out.append((int(it), np.asarray(series.index, dtype=float),
                    np.asarray(series, dtype=float)))
    return out


def residual_table(stats):
    """Tidy per-(time, iteration) residual frame from coordinator or
    fused-fleet stats (columns: primal_residual, dual_residual, and the
    penalty under any of its historical names)."""
    if stats is None or len(stats) == 0:
        return None
    cols = [c for c in ("primal_residual", "dual_residual",
                        "penalty_parameter", "penalty", "rho")
            if c in stats.columns]
    if not cols or stats.index.nlevels != 2:
        return None
    return stats[cols]


def solver_table(stats):
    """Per-solve stats columns for the solver panel (iterations, success,
    solve_wall_time, kkt_error, objective where available)."""
    if stats is None or len(stats) == 0:
        return None
    cols = [c for c in ("iterations", "success", "solve_wall_time",
                        "kkt_error", "objective") if c in stats.columns]
    return stats[cols] if cols else None


# ---------------------------------------------------------------------------
# plotly figure builders (lazy imports; pure functions of the data layer)
# ---------------------------------------------------------------------------

def prediction_figure(df, variable: str, max_steps: int = 40):
    """Prediction-fade figure: one fading line per solve + the realized
    series on top (reference ``plot_mpc_plotly``)."""
    import plotly.graph_objects as go

    traces = prediction_traces(df, variable, max_steps=max_steps)
    fig = go.Figure()
    n = max(len(traces), 1)
    for i, (t, abs_t, vals) in enumerate(traces):
        alpha = 0.15 + 0.55 * (i + 1) / n
        fig.add_trace(go.Scatter(
            x=abs_t, y=vals, mode="lines",
            line={"color": f"rgba(0, 84, 159, {alpha:.3f})", "width": 1},
            name=f"t={t:g}", showlegend=False,
            hovertemplate=f"pred@t={t:g}<br>%{{x}}: %{{y:.4g}}"))
    ts, vs = actual_series(df, variable)
    if len(ts):
        fig.add_trace(go.Scatter(
            x=ts, y=vs, mode="lines+markers",
            line={"color": "rgb(204, 7, 30)", "width": 2},
            name="closed loop"))
    fig.update_layout(title=variable, margin=dict(l=40, r=10, t=40, b=30),
                      height=320)
    return fig


def admm_iteration_figure(df, variable: str, time, iteration=None):
    """Coupling-variable trajectories across ADMM iterations at one step;
    iterations up to ``iteration`` fade in (reference
    ``create_coupling_var_plot``)."""
    import plotly.graph_objects as go

    traces = admm_iteration_traces(df, variable, time)
    if iteration is not None:
        traces = [tr for tr in traces if tr[0] <= iteration]
    fig = go.Figure()
    n = max(len(traces), 1)
    for i, (it, grid, vals) in enumerate(traces):
        alpha = 0.2 + 0.6 * (i + 1) / n
        fig.add_trace(go.Scatter(
            x=grid, y=vals, mode="lines",
            line={"color": f"rgba(0, 84, 159, {alpha:.3f})", "width": 1.5},
            name=f"iter {it}"))
    fig.update_layout(title=f"{variable} @ t={time:g}",
                      xaxis_title="horizon [s]",
                      margin=dict(l=40, r=10, t=40, b=30), height=320)
    return fig


def mhe_figure(df, variable: str, measurements=None, max_steps: int = 40):
    """Estimation view (the reference's MHE half of its unified
    MPC/MHE dashboard, ``utils/plotting/mpc_dashboard.py``): per-solve
    backward estimate trajectories fading in, the published
    estimate-at-now series on top, and the raw measurement scatter as
    the truth overlay."""
    import plotly.graph_objects as go

    traces = prediction_traces(df, variable, max_steps=max_steps)
    fig = go.Figure()
    n = max(len(traces), 1)
    for i, (t, abs_t, vals) in enumerate(traces):
        alpha = 0.15 + 0.55 * (i + 1) / n
        fig.add_trace(go.Scatter(
            x=abs_t, y=vals, mode="lines",
            line={"color": f"rgba(87, 171, 39, {alpha:.3f})", "width": 1},
            name=f"t={t:g}", showlegend=False,
            hovertemplate=f"estimate@t={t:g}<br>%{{x}}: %{{y:.4g}}"))
    ts, vs = estimate_series(df, variable)
    if len(ts):
        fig.add_trace(go.Scatter(
            x=ts, y=vs, mode="lines+markers",
            line={"color": "rgb(204, 7, 30)", "width": 2},
            name="estimate"))
    mt, mv = measurement_points(measurements, variable)
    if len(mt):
        fig.add_trace(go.Scatter(
            x=mt, y=mv, mode="markers",
            marker={"color": "rgba(0, 0, 0, 0.55)", "size": 5,
                    "symbol": "x"},
            name="measured"))
    fig.update_layout(title=f"{variable} (estimation)",
                      margin=dict(l=40, r=10, t=40, b=30), height=320)
    return fig


def residual_figure(stats, time=None):
    """Primal/dual residual (log scale) per iteration — one solve time or
    all (reference ``create_residuals_plot``)."""
    import plotly.graph_objects as go

    table = residual_table(stats)
    fig = go.Figure()
    if table is None:
        return fig
    if time is not None:
        try:
            sub = table.xs(time, level=0)
        except KeyError:
            return fig
        x = np.asarray(sub.index, dtype=float)
        for col in ("primal_residual", "dual_residual"):
            if col in sub.columns:
                fig.add_trace(go.Scatter(
                    x=x, y=np.asarray(sub[col], dtype=float),
                    mode="lines+markers", name=col))
        fig.update_layout(title=f"residuals @ t={time:g}",
                          xaxis_title="iteration")
    else:
        x = np.arange(len(table))
        for col in ("primal_residual", "dual_residual"):
            if col in table.columns:
                fig.add_trace(go.Scatter(
                    x=x, y=np.asarray(table[col], dtype=float),
                    mode="lines", name=col))
        fig.update_layout(title="residuals (all iterations)",
                          xaxis_title="cumulative iteration")
    fig.update_yaxes(type="log")
    fig.update_layout(margin=dict(l=40, r=10, t=40, b=30), height=320)
    return fig


# ---------------------------------------------------------------------------
# telemetry section (pure data layer over a MetricsRegistry snapshot)
# ---------------------------------------------------------------------------

def _snapshot_of(telemetry_src) -> list:
    """Normalize a telemetry source: a MetricsRegistry (snapshot() called),
    an already-made snapshot list, or None (the process default registry)."""
    if telemetry_src is None:
        from agentlib_mpc_tpu import telemetry as _t

        return _t.metrics().snapshot()
    if hasattr(telemetry_src, "snapshot"):
        return telemetry_src.snapshot()
    return list(telemetry_src)


def _labels_str(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def scalar_rows(snapshot, prefix: str = "") -> list:
    """[(name, labels-string, value)] for every counter/gauge sample whose
    family name starts with ``prefix`` — the generic metrics table."""
    rows = []
    for fam in snapshot:
        if fam["kind"] not in ("counter", "gauge"):
            continue
        if not fam["name"].startswith(prefix):
            continue
        for s in fam["samples"]:
            rows.append((fam["name"], _labels_str(s["labels"]), s["value"]))
    return rows


def compile_table(snapshot) -> list:
    """Per-entry-point compile economics: [{'entry_point', 'traces',
    'retraces', 'compiles', 'compile_seconds'}] from the ``jax_*``
    families (rows sorted by compile seconds, heaviest first)."""
    per: dict = {}

    def acc(fam_name, field):
        for fam in snapshot:
            if fam["name"] != fam_name:
                continue
            for s in fam["samples"]:
                ep = s["labels"].get("entry_point", "(unscoped)")
                per.setdefault(ep, {"entry_point": ep, "traces": 0,
                                    "retraces": 0, "compiles": 0,
                                    "compile_seconds": 0.0})[field] \
                    += s["value"]

    acc("jax_traces_total", "traces")
    acc("jax_retraces_total", "retraces")
    acc("jax_compiles_total", "compiles")
    acc("jax_compile_seconds_total", "compile_seconds")
    return sorted(per.values(), key=lambda r: -r["compile_seconds"])


def residual_gauge_table(snapshot) -> list:
    """[(iteration, primal, dual, extra-labels-str)] from the per-iteration
    ``admm_*_residual`` gauges — the fused/coordinator residual view when
    no results DataFrame is around (e.g. reading a bench metrics file)."""
    per: dict = {}
    for fam in snapshot:
        if fam["name"] not in ("admm_primal_residual",
                               "admm_dual_residual"):
            continue
        which = "primal" if "primal" in fam["name"] else "dual"
        for s in fam["samples"]:
            labels = dict(s["labels"])
            it = labels.pop("iteration", None)
            if it is None:
                continue
            key = (int(it), _labels_str(labels))
            per.setdefault(key, {})[which] = s["value"]
    return [(it, vals.get("primal"), vals.get("dual"), rest)
            for (it, rest), vals in sorted(per.items())]


def span_summary(recorder=None) -> list:
    """[(name, count, total_s, max_s)] sorted by total time, heaviest
    first — where the wall-clock of the retained spans went."""
    if recorder is None:
        from agentlib_mpc_tpu import telemetry as _t

        recorder = _t.recorder()
    agg = recorder.aggregate() if hasattr(recorder, "aggregate") \
        else dict(recorder)
    return sorted(
        ((name, a["count"], a["total_s"], a["max_s"])
         for name, a in agg.items()),
        key=lambda r: -r[2])


def telemetry_figure(telemetry_src=None):
    """Compile-cost panel: per-entry-point compile seconds (bars) with the
    retrace count as hover detail — the "which call site paid XLA and did
    it recompile" view."""
    import plotly.graph_objects as go

    table = compile_table(_snapshot_of(telemetry_src))
    fig = go.Figure()
    if table:
        fig.add_trace(go.Bar(
            x=[r["entry_point"] for r in table],
            y=[r["compile_seconds"] for r in table],
            customdata=[(r["compiles"], r["retraces"]) for r in table],
            hovertemplate=("%{x}<br>compile %{y:.2f}s"
                           "<br>%{customdata[0]} compiles, "
                           "%{customdata[1]} retraces<extra></extra>"),
            marker_color="rgb(0, 84, 159)"))
    fig.update_layout(title="XLA compile cost per entry point",
                      yaxis_title="compile seconds",
                      margin=dict(l=40, r=10, t=40, b=30), height=320)
    return fig


def admm_residual_gauge_figure(telemetry_src=None):
    """Primal/dual residuals per ADMM iteration from the telemetry gauges
    (log scale — the same view ``residual_figure`` builds from stats
    DataFrames, sourced from the registry instead). One trace pair per
    residual source (the non-iteration labels, e.g. ``fleet=bench`` vs
    ``agent=coordinator``) — mixing sources into one line would zig-zag
    over repeated iteration values and misrepresent both curves."""
    import plotly.graph_objects as go

    rows = residual_gauge_table(_snapshot_of(telemetry_src))
    fig = go.Figure()
    by_source: dict = {}
    for it, prim, dual, rest in rows:
        by_source.setdefault(rest, []).append((it, prim, dual))
    for rest, series in sorted(by_source.items()):
        suffix = f" [{rest}]" if rest and len(by_source) > 1 else ""
        its = [s[0] for s in series]
        fig.add_trace(go.Scatter(
            x=its, y=[s[1] for s in series], mode="lines+markers",
            name=f"primal_residual{suffix}"))
        fig.add_trace(go.Scatter(
            x=its, y=[s[2] for s in series], mode="lines+markers",
            name=f"dual_residual{suffix}"))
    if rows:
        fig.update_yaxes(type="log")
    fig.update_layout(title="ADMM residuals (telemetry gauges)",
                      xaxis_title="iteration",
                      margin=dict(l=40, r=10, t=40, b=30), height=320)
    return fig


def solver_figure(stats):
    """Solver panel: iterations + wall time per solve (reference
    ``solver_return``/``solver plot``)."""
    import plotly.graph_objects as go

    table = solver_table(stats)
    fig = go.Figure()
    if table is None:
        return fig
    x = np.asarray(table.index.get_level_values(0) if
                   table.index.nlevels > 1 else table.index, dtype=float)
    if "iterations" in table.columns:
        fig.add_trace(go.Scatter(
            x=x, y=np.asarray(table["iterations"], dtype=float),
            mode="lines+markers", name="iterations", yaxis="y"))
    if "solve_wall_time" in table.columns:
        fig.add_trace(go.Scatter(
            x=x, y=1e3 * np.asarray(table["solve_wall_time"], dtype=float),
            mode="lines+markers", name="wall [ms]", yaxis="y2"))
    fig.update_layout(
        title="solver", xaxis_title="time [s]",
        yaxis=dict(title="iterations"),
        yaxis2=dict(title="wall [ms]", overlaying="y", side="right"),
        margin=dict(l=40, r=40, t=40, b=30), height=320)
    return fig


# ---------------------------------------------------------------------------
# dash app layer
# ---------------------------------------------------------------------------

def build_app(results: dict, stats=None, measurements=None, telemetry=None,
              spans=None):
    """Construct (but do not run) the dash app: agent/module dropdowns,
    variable checklist, per-step slider for ADMM frames, estimation
    views for MHE frames (``measurements``: optional truth-overlay frame,
    see :func:`measurement_points`), residual/solver panels, and — when
    ``telemetry`` is given (a MetricsRegistry, a snapshot list, or
    ``True`` for the process default registry) — a telemetry section with
    the compile-cost panel, residual gauges, span summary and the raw
    counter/gauge table. ``spans``: span source for the summary table — an
    aggregate dict (e.g. the ``"spans"`` key of an ``--emit-metrics``
    artifact) or a SpanRecorder; defaults to the live process recorder for
    live telemetry sources, and is omitted for a plain snapshot list
    (whose spans this process does not know). Requires dash + plotly."""
    import dash
    from dash import dcc, html
    from dash.dependencies import Input, Output

    frames = discover_frames(results)
    if not frames:
        raise ValueError("no MPC/ADMM-shaped results to show")
    keys = [f"{a}/{m}" for a, m in frames]
    by_key = {f"{a}/{m}": df for (a, m), df in frames.items()}

    telemetry_children = []
    if telemetry is not None:
        snapshot = _snapshot_of(None if telemetry is True else telemetry)
        rows = scalar_rows(snapshot)
        if spans is None and not isinstance(telemetry, (list, tuple)):
            # live source (registry / True): the process recorder is the
            # matching span source; a plain snapshot list carries no spans
            span_rows = span_summary()
        elif spans is not None:
            span_rows = span_summary(spans)
        else:
            span_rows = []
        telemetry_children = [
            html.H3("telemetry"),
            dcc.Graph(figure=telemetry_figure(snapshot)),
            dcc.Graph(figure=admm_residual_gauge_figure(snapshot)),
            html.Details([
                html.Summary("span summary / raw metrics"),
                html.Table(
                    [html.Tr([html.Th(h) for h in
                              ("span", "count", "total [s]", "max [s]")])]
                    + [html.Tr([html.Td(n), html.Td(c),
                                html.Td(f"{t:.4f}"), html.Td(f"{m:.4f}")])
                       for n, c, t, m in span_rows]),
                html.Table(
                    [html.Tr([html.Th(h) for h in
                              ("metric", "labels", "value")])]
                    + [html.Tr([html.Td(n), html.Td(l), html.Td(v)])
                       for n, l, v in rows]),
            ]),
        ]

    app = dash.Dash("agentlib_mpc_tpu")
    app.layout = html.Div([
        html.H2("agentlib-mpc-tpu results"),
        html.Div([
            html.Label("module"),
            dcc.Dropdown(id="module", options=[{"label": k, "value": k}
                                               for k in keys],
                         value=keys[0], clearable=False),
        ]),
        html.Div(id="step-controls"),
        html.Div(id="graphs"),
        html.Div(telemetry_children),
        dcc.Store(id="placeholder"),
    ])

    @app.callback(Output("step-controls", "children"),
                  Input("module", "value"))
    def _step_controls(key):
        df = by_key[key]
        if frame_kind(df) != "admm":
            return html.Div()
        times = time_steps_of(df)
        return html.Div([
            html.Label("solve time"),
            dcc.Slider(id="step-slider", min=0, max=len(times) - 1, step=1,
                       value=len(times) - 1,
                       marks={i: f"{t:g}" for i, t in
                              enumerate(times) if i % max(1, len(times) // 10)
                              == 0}),
        ])

    @app.callback(Output("graphs", "children"), Input("module", "value"))
    def _graphs(key):
        df = by_key[key]
        children = []
        if frame_kind(df) == "admm":
            times = time_steps_of(df)
            t_last = times[-1]
            for var in variables_of(df):
                children.append(dcc.Graph(
                    figure=admm_iteration_figure(df, var, t_last)))
            if stats is not None:
                children.append(dcc.Graph(
                    figure=residual_figure(stats, t_last)))
        elif frame_kind(df) == "mhe":
            for var in variables_of(df):
                children.append(dcc.Graph(
                    figure=mhe_figure(df, var, measurements=measurements)))
            if stats is not None:
                children.append(dcc.Graph(figure=solver_figure(stats)))
        else:
            for var in variables_of(df):
                children.append(dcc.Graph(
                    figure=prediction_figure(df, var)))
            if stats is not None:
                children.append(dcc.Graph(figure=solver_figure(stats)))
        return html.Div(children)

    return app


def run_dashboard(results: dict, stats=None, port: int = 8050,
                  debug: bool = False, measurements=None,
                  telemetry=None):  # pragma: no cover - needs dash
    """Build and serve the dash app (blocks)."""
    app = build_app(results, stats, measurements=measurements,
                    telemetry=telemetry)
    run = getattr(app, "run", None) or getattr(app, "run_server")
    run(port=port, debug=debug)
    return app


# ---------------------------------------------------------------------------
# unified entry point (interactive when dash+plotly exist, static otherwise)
# ---------------------------------------------------------------------------

def show_dashboard(results: dict, stats=None, save_path: Optional[str] = None,
                   port: int = 8050, block: bool = True, mode: str = "auto",
                   measurements=None, telemetry=None):
    """MPC/MHE/ADMM results overview — the reference's dashboard entry
    point (``utils/plotting/interactive.py:300``, ``mpc_dashboard.py``,
    ``admm_dashboard.py``) unified into one call. ``telemetry``: optional
    registry/snapshot (or ``True`` for the process default) adding the
    compile/residual/span telemetry section in interactive mode. ``mode``:

    - ``"auto"`` (default): serve the interactive dash app when
      dash+plotly are importable, else render the static matplotlib
      overview (returned; saved when ``save_path`` given);
    - ``"interactive"``: require dash (ImportError propagates);
    - ``"static"``: always the matplotlib overview — the export path.

    Never half-fails: any dash *runtime* problem in auto mode falls back
    to the static figure."""
    if mode not in ("auto", "interactive", "static"):
        raise ValueError(
            f"mode must be 'auto', 'interactive' or 'static', got {mode!r}")
    if mode != "static":
        try:
            import dash  # noqa: F401
            import plotly  # noqa: F401
        except ImportError:
            if mode == "interactive":
                raise
            return static_dashboard(results, stats, save_path,
                                    measurements=measurements)
        try:
            if not block:
                return build_app(results, stats, measurements=measurements,
                                 telemetry=telemetry)
            return run_dashboard(results, stats, port=port,
                                 measurements=measurements,
                                 telemetry=telemetry)
        except ValueError:
            raise  # empty/unshaped results: same error contract as static
        except Exception as exc:  # pragma: no cover - dash runtime issues
            import logging

            logging.getLogger(__name__).warning(
                "interactive dashboard failed (%s); falling back to "
                "static", exc)
    return static_dashboard(results, stats, save_path,
                            measurements=measurements)


def static_dashboard(results, stats=None, save_path=None, measurements=None):
    """Static matplotlib overview of the first results frame — one panel
    per variable; MHE frames get estimate-vs-measurement panels."""
    from agentlib_mpc_tpu.utils.plotting.basic import make_fig
    from agentlib_mpc_tpu.utils.plotting.mpc import plot_mpc

    frames = {f"{a}/{m}": df for (a, m), df in
              discover_frames(results).items()}
    if not frames:
        raise ValueError("no MPC-shaped results to show")
    key, df = next(iter(frames.items()))
    variables = variables_of(df)
    rows = max(len(variables), 1)
    fig, axes = make_fig(rows=rows)
    kind = frame_kind(df)
    for ax, var in zip(np.atleast_1d(axes).ravel(), variables):
        if kind == "mhe":
            ts, vs = estimate_series(df, var)
            ax.plot(ts, vs, color="tab:red", lw=1.5, label="estimate")
            mt, mv = measurement_points(measurements, var)
            if len(mt):
                ax.plot(mt, mv, "x", color="0.3", ms=4, label="measured")
            ax.legend(fontsize=7)
        elif kind == "admm":
            # last-iteration prediction fades (prediction_traces already
            # selects the final ADMM iteration per step) + realized line
            traces = prediction_traces(df, var, max_steps=40)
            n = max(len(traces), 1)
            for i, (_t, abs_t, vals) in enumerate(traces):
                ax.plot(abs_t, vals, color="tab:blue", lw=0.8,
                        alpha=0.15 + 0.55 * (i + 1) / n)
            ts, vs = actual_series(df, var)
            if len(ts):
                ax.plot(ts, vs, color="tab:red", lw=1.5)
        else:
            plot_mpc(df, var, ax=ax)
        ax.set_title(f"{key}: {var}", fontsize=9)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path)
    return fig
