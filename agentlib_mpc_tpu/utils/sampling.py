"""Trajectory sampling: interpolate live variable values onto OCP grids.

Re-implements the semantics of the reference's ``utils/sampling.py``
(``sample`` :45-164, ``interpolate_to_previous`` :183-202; enum
``data_structures/interpolation.py:6-24``): a variable arriving over the
broker may be a scalar (hold constant), a list (already on the grid), or a
(times, values) trajectory to interpolate at the solve's current time with
linear or previous-value (zero-order hold) interpolation, extrapolating
edges with the boundary value.

Host-side numpy: this runs in the control loop *before* device dispatch and
produces the fixed-shape arrays the jitted solve consumes.
"""

from __future__ import annotations

from enum import Enum
from typing import Sequence

import numpy as np


class InterpolationMethods(str, Enum):
    linear = "linear"
    previous = "previous"
    mean_over_interval = "mean_over_interval"


def sample(
    value,
    grid: Sequence[float],
    current: float = 0.0,
    method: InterpolationMethods = InterpolationMethods.linear,
) -> np.ndarray:
    """Sample `value` onto `current + grid`.

    value: scalar | sequence of len(grid) | (times, values) pair |
           dict {time: value} | pandas Series.
    """
    grid = np.asarray(grid, dtype=float)
    # pandas Series → (times, values) without importing pandas here
    if hasattr(value, "index") and hasattr(value, "values"):
        value = (np.asarray(value.index, dtype=float),
                 np.asarray(value.values, dtype=float))
    if isinstance(value, dict):
        # keys may be strings (JSON round-trip of a pandas Series): sort
        # numerically, not lexicographically
        keys = sorted(value, key=float)
        value = (np.array([float(k) for k in keys]),
                 np.array([value[k] for k in keys], dtype=float))
    if np.isscalar(value) or (isinstance(value, np.ndarray) and value.ndim == 0):
        return np.full(grid.shape, float(value))
    if isinstance(value, (list, np.ndarray)):
        arr = np.asarray(value, dtype=float)
        if arr.shape == grid.shape:
            return arr
        if arr.size == 1:
            return np.full(grid.shape, float(arr.reshape(())))
        raise ValueError(
            f"list value of length {arr.size} does not match grid of "
            f"length {grid.size}; pass a (times, values) pair to interpolate")
    times, vals = value
    times = np.asarray(times, dtype=float)
    vals = np.asarray(vals, dtype=float)
    target = current + grid
    if method == InterpolationMethods.previous:
        return interpolate_to_previous(target, times, vals)
    if method == InterpolationMethods.mean_over_interval:
        out = np.empty(target.shape)
        for i, t0 in enumerate(target):
            t1 = target[i + 1] if i + 1 < len(target) else t0
            mask = (times >= t0) & (times < t1) if t1 > t0 else np.array([])
            if np.any(mask):
                out[i] = float(np.mean(vals[mask]))
            else:
                out[i] = float(np.interp(t0, times, vals))
        return out
    # linear with edge extrapolation by boundary value (np.interp semantics)
    return np.interp(target, times, vals)


def shift_time_series(arr: np.ndarray, horizon: int) -> np.ndarray:
    """Shift a trajectory one control interval forward, repeating the tail —
    the between-steps warm start both ADMM modes use (reference
    ``shift_values_by_one``, ``admm_datatypes.py:275-282``; jit twin:
    ``ops/admm.shift_one``). ``arr`` has ``k·horizon`` samples."""
    arr = np.asarray(arr)
    k = max(len(arr) // max(horizon, 1), 1)
    return np.concatenate([arr[k:], arr[-k:]])


def interpolate_to_previous(target, times, vals) -> np.ndarray:
    """Zero-order hold (reference ``interpolate_to_previous``,
    ``utils/sampling.py:183-202``)."""
    idx = np.searchsorted(times, np.asarray(target, dtype=float), side="right") - 1
    idx = np.clip(idx, 0, len(vals) - 1)
    return np.asarray(vals, dtype=float)[idx]
