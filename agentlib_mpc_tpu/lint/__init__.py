"""jaxlint: project-specific JIT-hygiene & thread-discipline analyzer.

The framework's performance story is "compile once, dispatch forever"
(PERF.md: one stray dispatch costs ~64 ms against 1-9 ms kernels), and its
runtime story is "callback threads + locks around every shared structure".
Both disciplines were tribal knowledge enforced by review; the two worst
regressions so far (the weak-typed ``init_state`` z/rho that silently
recompiled the fused-ADMM engine every round, and scattered host syncs
turning jitted paths into per-step tunnels) were compile-cache bugs found
by accident. This package machine-checks them:

* :mod:`.jit_hygiene` — AST passes over the jit-reachable call graph:
  host syncs (``float``/``int``/``.item()``/``.tolist()``/``np.*``/
  ``print``), Python ``if``/``while`` on tracer-typed values, wall-clock
  reads inside traced code, weak-typed scalar literals stored into carried
  state pytrees, non-hashable static args.
* :mod:`.thread_discipline` — every mutation of a field annotated
  ``# guarded-by: <lock>`` must sit inside a ``with <lock>`` block; and
  callback (de)registration must never run under a lock annotated
  ``# lint: dispatch-lock`` (the classic dispatch-reentry deadlock).
* :mod:`.retrace_budget` — a runtime gate: run the 4-agent fused-ADMM
  bench step for N rounds after warmup and fail when any entry point
  compiles more often than ``lint_budgets.toml`` allows.

Findings carry stable fingerprints; pre-existing debt lives in a
checked-in ``lint_baseline.json`` (with justifications) so only NEW
violations fail CI. See ``docs/static_analysis.md``.

The static passes are stdlib-only (``ast`` + ``tokenize``) — no jax
import, so the linter runs in tooling contexts (CI collect phase, editor
hooks) without touching an accelerator.
"""

from __future__ import annotations

from agentlib_mpc_tpu.lint.findings import (  # noqa: F401
    Baseline,
    Finding,
    fingerprint,
)
from agentlib_mpc_tpu.lint.runner import (  # noqa: F401
    collect_findings,
    collect_stats,
    package_root,
    repo_root,
)
