"""Analysis utils + plotting: CSV round trips, slicing, index conversion,
figure rendering (Agg), NLP sparsity, ML fit metrics.

Mirrors the reference's analysis surface (``utils/analysis.py``) against
synthetic results in the exact on-disk layout, so both stacks' analyses
interoperate.
"""

import numpy as np
import pandas as pd
import pytest

import matplotlib

matplotlib.use("Agg")

from agentlib_mpc_tpu.utils import analysis
from agentlib_mpc_tpu.utils.plotting import (
    evaluate_ml_fit,
    plot_admm_residuals,
    plot_mpc,
    plot_mpc_plan,
    show_dashboard,
    spy_nlp,
)


def _mpc_frame():
    """Two solves at t=0 and t=300, horizon grid 0/100/200."""
    frames = []
    for t in (0.0, 300.0):
        df = pd.DataFrame({
            ("variable", "T"): [295.0 + t / 100, 294.0, 293.0],
            ("variable", "mDot"): [0.01, 0.02, np.nan],
        })
        df.index = pd.MultiIndex.from_product(
            [[t], [0.0, 100.0, 200.0]], names=["time", "grid"])
        frames.append(df)
    out = pd.concat(frames)
    out.columns = pd.MultiIndex.from_tuples(out.columns)
    return out


def _admm_frame():
    frames = []
    for t in (0.0, 300.0):
        for it in (0, 1, 2):
            df = pd.DataFrame({"mDot": [0.01 * (it + 1)] * 3})
            df.index = pd.MultiIndex.from_product(
                [[t], [it], [0.0, 100.0, 200.0]],
                names=["time", "iteration", "grid"])
            frames.append(df)
    return pd.concat(frames)


class TestAnalysis:
    def test_mpc_roundtrip(self, tmp_path):
        df = _mpc_frame()
        path = tmp_path / "mpc.csv"
        analysis.save_mpc(df, path)
        back = analysis.load_mpc(path)
        assert back.index.names == ["time", "grid"]
        np.testing.assert_allclose(
            back[("variable", "T")].to_numpy(dtype=float),
            df[("variable", "T")].to_numpy(dtype=float))

    def test_at_time_step_offsets(self):
        df = _mpc_frame()
        series = analysis.mpc_at_time_step(df, 300.0, "T")
        np.testing.assert_allclose(series.index, [300.0, 400.0, 500.0])
        assert series.iloc[0] == pytest.approx(298.0)
        # nearest-match semantics
        series2 = analysis.mpc_at_time_step(df, 290.0, "T")
        np.testing.assert_allclose(series2.index, [300.0, 400.0, 500.0])

    def test_admm_slicing(self):
        df = _admm_frame()
        final = analysis.admm_at_time_step(df, 0.0, "mDot", iteration=2)
        np.testing.assert_allclose(final.to_numpy(dtype=float), 0.03)
        assert analysis.get_number_of_iterations(df) == {0.0: 3, 300.0: 3}

    def test_convert_index(self):
        df = _mpc_frame()
        hours = analysis.convert_index(df, to_unit="hours", level="time")
        times = np.unique(hours.index.get_level_values(0))
        np.testing.assert_allclose(times, [0.0, 300.0 / 3600.0])

    def test_first_vals(self):
        df = _mpc_frame()
        closed_loop = analysis.first_vals_at_trajectory_index(
            df[("variable", "T")])
        np.testing.assert_allclose(closed_loop.to_numpy(dtype=float),
                                   [295.0, 298.0])

    def test_save_results_tree(self, tmp_path):
        results = {"agentA": {"mpc": _mpc_frame(),
                              "sim": pd.DataFrame({"T": [1.0, 2.0]},
                                                  index=[0.0, 60.0])}}
        written = analysis.save_results(results, tmp_path)
        assert set(written) == {"agentA_mpc", "agentA_sim"}
        assert analysis.load_sim(written["agentA_sim"])["T"].iloc[1] == 2.0


class TestPlotting:
    def test_plot_mpc_renders(self):
        ax = plot_mpc(_mpc_frame(), "T")
        assert len(ax.lines) >= 3  # 2 faded predictions + actual

    def test_plot_plan(self):
        ax = plot_mpc_plan(_mpc_frame(), "mDot", 0.0)
        assert ax.get_ylabel() == "mDot"

    def test_residual_plot(self):
        stats = pd.DataFrame({
            "primal_residual": [1.0, 0.1, 0.01],
            "dual_residual": [0.5, 0.2, 0.05],
            "penalty": [10.0, 10.0, 20.0]})
        ax = plot_admm_residuals(stats)
        assert len(ax.lines) == 3

    def test_static_dashboard(self, tmp_path):
        fig = show_dashboard({"agentA": {"mpc": _mpc_frame()}},
                             save_path=str(tmp_path / "dash.png"))
        assert (tmp_path / "dash.png").exists()
        import matplotlib.pyplot as plt

        plt.close(fig)

    def test_spy_nlp_banded(self):
        from agentlib_mpc_tpu.models.zoo import OneRoom
        from agentlib_mpc_tpu.ops.transcription import transcribe
        from agentlib_mpc_tpu.utils.plotting.structure import \
            nlp_jacobian_pattern

        ocp = transcribe(OneRoom(), ["mDot"], N=4, dt=300.0,
                         method="multiple_shooting")
        pattern = nlp_jacobian_pattern(ocp)
        assert pattern.shape == (ocp.n_g + ocp.n_h, ocp.n_w)
        # shooting structure is sparse: well under half the entries active
        assert 0 < pattern.mean() < 0.5
        ax = spy_nlp(ocp)
        assert ax.get_xlabel().startswith("decision")

    def test_ml_fit_metrics(self):
        from agentlib_mpc_tpu.ml import Feature, OutputFeature, \
            SerializedLinReg

        m = SerializedLinReg(
            dt=1.0, inputs={"a": Feature(name="a")},
            output={"y": OutputFeature(name="y", output_type="absolute",
                                       recursive=False)},
            coef=[[2.0]], intercept=[1.0])
        X = np.linspace(0, 1, 20)[:, None]
        y = 2.0 * X[:, 0] + 1.0
        metrics = evaluate_ml_fit(m, X, y, plot=False)
        assert metrics["y"]["rmse"] == pytest.approx(0.0, abs=1e-9)
        assert metrics["y"]["r2"] == pytest.approx(1.0)


class TestAdmmAnimation:
    """Counterparts of the reference's admm_animation / consensus shades."""

    def _two_agent_data(self):
        return {"room": _admm_frame(), "cooler": _admm_frame()}

    def test_make_image_renders_chosen_iteration(self, tmp_path):
        from agentlib_mpc_tpu.utils.plotting.admm_animation import (
            make_image,
        )

        out = tmp_path / "frame.png"
        fig, ax = make_image(self._two_agent_data(), time_step=0.0,
                             variable="mDot", file_name=str(out),
                             iteration=-1)
        assert out.exists() and out.stat().st_size > 0
        # two agents -> two lines, last iteration values 0.03
        lines = [ln for ln in ax.get_lines() if len(ln.get_ydata())]
        assert len(lines) == 2
        assert np.allclose(lines[0].get_ydata(), 0.03)

    def test_make_animation_writes_gif(self, tmp_path):
        from agentlib_mpc_tpu.utils.plotting.admm_animation import (
            make_animation,
        )

        out = tmp_path / "conv.gif"
        name = make_animation(self._two_agent_data(), time_step=0.0,
                              variable="mDot", file_name=str(out),
                              interval=50)
        assert name == str(out)
        assert out.exists() and out.stat().st_size > 0

    def test_animation_rejects_non_gif(self, tmp_path):
        from agentlib_mpc_tpu.utils.plotting.admm_animation import (
            make_animation,
        )

        with pytest.raises(ValueError, match="gif"):
            make_animation(self._two_agent_data(), time_step=0.0,
                           file_name=str(tmp_path / "anim.mp4"))

    def test_consensus_shades_renders(self):
        from agentlib_mpc_tpu.utils.plotting.admm import (
            plot_consensus_shades,
        )

        ax = plot_consensus_shades({"room": _admm_frame()}, "mDot")
        # 2 control steps (final iteration each) + 1 actual-values line
        assert len(ax.get_lines()) == 3
        matplotlib.pyplot.close("all")

    def test_consensus_shades_all_iterations(self):
        from agentlib_mpc_tpu.utils.plotting.admm import (
            plot_consensus_shades,
        )

        ax = plot_consensus_shades({"room": _admm_frame()}, "mDot",
                                   final_iteration_only=False)
        # 2 steps x 3 iterations + actual line
        assert len(ax.get_lines()) == 7
        matplotlib.pyplot.close("all")

    def test_interpolate_colors_endpoints(self):
        from agentlib_mpc_tpu.utils.plotting.admm import (
            SHADE_RAMP,
            interpolate_colors,
        )

        assert interpolate_colors(0.0, SHADE_RAMP) == tuple(SHADE_RAMP[0])
        assert interpolate_colors(1.0, SHADE_RAMP) == tuple(SHADE_RAMP[-1])
        mid = interpolate_colors(0.5, SHADE_RAMP)
        assert mid == tuple(SHADE_RAMP[1])

    def test_make_image_accepts_preselected_series(self, tmp_path):
        """Reference calling convention: per-label Series (covers agents
        whose coupling columns have different local names)."""
        from agentlib_mpc_tpu.utils.plotting.admm_animation import (
            make_image,
        )

        frame = _admm_frame()
        data = {"room": frame["mDot"], "cooler": frame["mDot"] * 2.0}
        out = tmp_path / "series_frame.png"
        fig, ax = make_image(data, time_step=0.0, file_name=str(out))
        assert out.exists() and out.stat().st_size > 0
        lines = [ln for ln in ax.get_lines() if len(ln.get_ydata())]
        assert len(lines) == 2
