"""ML training pipeline: pure-stage unit tests + the full §3.5 loop
(excite → record → retrain → broadcast → hot-swap) as a MAS run.

The reference covers its trainer only through examples; the pipeline
stages here are tested directly (SURVEY.md §4 lesson).
"""

import numpy as np
import pandas as pd
import pytest

import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types
from agentlib_mpc_tpu.ml import Feature, OutputFeature
from agentlib_mpc_tpu.ml.serialized import SerializedLinReg
from agentlib_mpc_tpu.ml.training import (
    ANNTrainerCore,
    create_lagged_features,
    fit_ann,
    fit_gpr,
    fit_linreg,
    resample,
    train_val_test_split,
)
from agentlib_mpc_tpu.ml.predictors import make_predictor
from agentlib_mpc_tpu.models.ml_model import MLModel
from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.variables import (
    control_input,
    output,
    parameter,
    state,
)
from agentlib_mpc_tpu.runtime.mas import LocalMAS

DT = 60.0
C = 50000.0
LOAD = 200.0


class TestPipeline:
    def test_resample_uniform(self):
        df = pd.DataFrame({"a": [0.0, 2.0, 4.0]}, index=[0.0, 2.0, 4.0])
        out = resample(df, 1.0)
        np.testing.assert_allclose(out.index, [0, 1, 2, 3, 4])
        np.testing.assert_allclose(out["a"], [0, 1, 2, 3, 4])

    def test_lagged_features_layout(self):
        df = pd.DataFrame({"u": [10.0, 11, 12, 13],
                           "x": [0.0, 1, 2, 3]}, index=[0.0, 1, 2, 3])
        X, y = create_lagged_features(
            df, {"u": Feature(name="u", lag=2)},
            {"x": OutputFeature(name="x", output_type="difference",
                                recursive=True)})
        assert list(X.columns) == ["u", "u_1", "x"]
        # first valid row: t=1 (needs u at t and t−1); target x(2)−x(1)
        np.testing.assert_allclose(X.iloc[0], [11, 10, 1])
        np.testing.assert_allclose(y.iloc[0], [1.0])
        assert len(X) == 2

    def test_split_shares(self):
        X = pd.DataFrame({"a": np.arange(100.0)})
        y = pd.DataFrame({"b": np.arange(100.0)})
        data = train_val_test_split(X, y, (0.6, 0.2, 0.2), seed=1)
        assert len(data.training_inputs) == 60
        assert len(data.validation_inputs) == 20
        assert len(data.test_inputs) == 20
        # disjoint cover
        all_idx = np.concatenate([data.training_inputs.index,
                                  data.validation_inputs.index,
                                  data.test_inputs.index])
        assert len(np.unique(all_idx)) == 100

    def test_bad_shares_rejected(self):
        X = pd.DataFrame({"a": [1.0]})
        with pytest.raises(ValueError, match="sum to 1"):
            train_val_test_split(X, X, (0.5, 0.2, 0.2))


class TestFitters:
    def test_linreg_recovers_exact_law(self):
        rng = np.random.default_rng(0)
        Q = rng.uniform(0, 500, 50)
        X = pd.DataFrame({"Q": Q, "x": rng.uniform(290, 300, 50)})
        y = pd.DataFrame({"x": DT / C * (LOAD - Q)})
        m = fit_linreg(X, y, dt=DT,
                       inputs={"Q": Feature(name="Q")},
                       output={"x": OutputFeature(
                           name="x", output_type="difference")})
        coef = np.asarray(m.coef)[0]
        assert coef[0] == pytest.approx(-DT / C, rel=1e-6)
        assert coef[1] == pytest.approx(0.0, abs=1e-9)
        assert np.asarray(m.intercept)[0] == pytest.approx(DT / C * LOAD,
                                                           rel=1e-6)

    def test_ann_learns_nonlinear_map(self):
        rng = np.random.default_rng(1)
        X = rng.uniform(-1, 1, size=(400, 2))
        y = np.sin(2 * X[:, 0]) * X[:, 1]
        core = ANNTrainerCore(hidden=(24, 24), epochs=300,
                              learning_rate=3e-3, seed=0)
        m = fit_ann(X, y, dt=1.0,
                    inputs={"a": Feature(name="a"), "b": Feature(name="b")},
                    output={"y": OutputFeature(name="y",
                                               output_type="absolute",
                                               recursive=False)},
                    trainer=core)
        pred = make_predictor(m)
        Xq = rng.uniform(-1, 1, size=(50, 2))
        got = np.array([float(pred.apply(pred.params, x)[0]) for x in Xq])
        want = np.sin(2 * Xq[:, 0]) * Xq[:, 1]
        assert np.sqrt(np.mean((got - want) ** 2)) < 0.1

    def test_gpr_learns_smooth_map(self):
        rng = np.random.default_rng(2)
        X = rng.uniform(-2, 2, size=(60, 1))
        y = np.sin(X[:, 0])
        m = fit_gpr(X, y, dt=1.0,
                    inputs={"a": Feature(name="a")},
                    output={"y": OutputFeature(name="y",
                                               output_type="absolute",
                                               recursive=False)},
                    n_restarts_optimizer=1)
        pred = make_predictor(m)
        Xq = np.linspace(-1.5, 1.5, 20)[:, None]
        got = np.array([float(pred.apply(pred.params, x)[0]) for x in Xq])
        np.testing.assert_allclose(got, np.sin(Xq[:, 0]), atol=0.05)


# -- the full train→broadcast→hot-swap loop (§3.5) ---------------------------

class LinearPlant(Model):
    inputs = [control_input("Q", 0.0, lb=0.0, ub=500.0)]
    states = [state("T", 295.15, lb=280.0, ub=320.0)]
    parameters = [parameter("C", C), parameter("load", LOAD)]
    outputs = [output("T_out")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", (v.load - v.Q) / v.C)
        eq.alg("T_out", v.T)
        return eq


def _seed_surrogate():
    """Deliberately wrong initial surrogate (to be hot-swapped)."""
    return SerializedLinReg(
        dt=DT,
        inputs={"Q": Feature(name="Q", lag=1)},
        output={"T": OutputFeature(name="T", output_type="difference",
                                   recursive=True)},
        coef=[[0.0, 0.0]], intercept=[0.0])


class NarxPlant(MLModel):
    inputs = [control_input("Q", 0.0, lb=0.0, ub=500.0)]
    states = [state("T", 295.15)]
    parameters = []
    dt = DT
    ml_model_sources = [_seed_surrogate()]


@pytest.fixture(scope="module")
def training_loop_results():
    prbs_times = np.arange(0, 7200, 300.0)
    rng = np.random.default_rng(3)
    prbs = rng.uniform(0.0, 500.0, size=len(prbs_times))

    mas = LocalMAS([
        {
            "id": "Source",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "excite", "type": "data_source",
                 "t_sample": 300,
                 "data": {"Q": dict(zip(prbs_times, prbs))}},
            ],
        },
        {
            "id": "Plant",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "room", "type": "simulator",
                 "model": {"class": LinearPlant},
                 "t_sample": DT,
                 "inputs": [{"name": "Q", "alias": "Q"}],
                 "states": [],
                 "outputs": [{"name": "T_out", "alias": "T"}]},
            ],
        },
        {
            "id": "Trainer",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "learn", "type": "linreg_trainer",
                 "step_size": DT,
                 "retrain_delay": 3600,
                 "inputs": [{"name": "Q", "alias": "Q"}],
                 "outputs": [{"name": "T", "alias": "T"}]},
            ],
        },
        {
            "id": "Twin",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "twin", "type": "ml_simulator",
                 "model": {"class": NarxPlant},
                 "t_sample": DT,
                 "inputs": [{"name": "Q", "alias": "Q"}],
                 "states": [{"name": "T", "value": 295.15, "shared": False}],
                 "outputs": []},
            ],
        },
    ], env={"rt": False})
    # plant must publish its state so trainer can record it: wire T_out
    plant = mas.agents["Plant"].get_module("room")
    twin = mas.agents["Twin"].get_module("twin")
    mas.run(until=7200)
    return mas, plant, twin


class TestTrainingLoop:
    def test_trainer_recovers_dynamics(self, training_loop_results):
        mas, plant, twin = training_loop_results
        trainer = mas.agents["Trainer"].get_module("learn")
        assert trainer._retrains >= 1
        # the hot-swapped twin surrogate must match the true discrete law
        key = "T"
        params = twin.model.ml_params[twin.model._model_of_output[key]]
        coef = np.asarray(params["coef"])[0]
        assert coef[0] == pytest.approx(-DT / C, rel=0.05)

    def test_twin_received_hot_swap(self, training_loop_results):
        mas, plant, twin = training_loop_results
        m = twin.model.serialized[twin.model._model_of_output["T"]]
        assert m.trainer_config is not None  # came from the trainer
        assert m.trainer_config["type"] == "linreg_trainer"


def test_keras_ann_trainer_roundtrip():
    """Train with keras, predict with the pure-JAX graph evaluator
    (the reference's trainer stack end-to-end, ml_model_trainer.py:617-667)."""
    pytest.importorskip("keras")
    import numpy as np

    from agentlib_mpc_tpu.ml.predictors import make_predictor
    from agentlib_mpc_tpu.ml.serialized import (
        Feature,
        OutputFeature,
        SerializedMLModel,
    )
    from agentlib_mpc_tpu.ml.training import fit_keras_ann

    rng = np.random.default_rng(1)
    X = rng.uniform(-1, 1, size=(400, 2)).astype(np.float32)
    y = (0.5 * X[:, :1] - 0.25 * X[:, 1:]).astype(np.float32)
    ser = fit_keras_ann(
        X[:320], y[:320], X[320:], y[320:], dt=60.0,
        inputs={"a": Feature(name="a", lag=1),
                "b": Feature(name="b", lag=1)},
        output={"o": OutputFeature(name="o", lag=1,
                                   output_type="absolute",
                                   recursive=False)},
        layers=(16,), epochs=120, learning_rate=1e-2)
    # wire round-trip, then evaluate without keras in the loop
    ser2 = SerializedMLModel.from_json(ser.to_json())
    pred = make_predictor(ser2)
    import jax.numpy as jnp

    err = 0.0
    for xi, yi in zip(X[:50], y[:50]):
        err = max(err, abs(float(pred.apply(pred.params,
                                            jnp.asarray(xi))[0])
                           - float(yi[0])))
    assert err < 0.1, f"keras-trained surrogate off by {err}"
