"""Module base class and registry.

Replaces agentlib's BaseModule/BaseModuleConfig contract that every
reference module builds on (``modules/mpc/mpc.py:9-14``): a module is
instantiated from a JSON-shaped config dict, owns a typed variable store,
receives variable updates through broker callbacks, and contributes a
``process()`` generator to the environment.

Config shape (compatible with the reference's agent configs):
    {"module_id": "myMPC", "type": "mpc", <scalar options...>,
     "inputs": [{...var...}], "outputs": [...], ...}

Module classes declare which config keys are variable groups
(``variable_groups``) and which groups are broadcast by default
(``shared_groups``). String type keys resolve through MODULE_TYPES —
the reference's registry pattern (``modules/__init__.py:21-79``) without
the import indirection.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Iterable, Optional, Type

from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source

logger = logging.getLogger(__name__)

MODULE_TYPES: dict[str, Type["BaseModule"]] = {}


def register_module(*names: str):
    def deco(cls):
        for n in names:
            MODULE_TYPES[n] = cls
        cls.type_names = names
        return cls
    return deco


def create_module(config: dict, agent) -> "BaseModule":
    type_key = config.get("type")
    if isinstance(type_key, dict):
        # custom injection: {"file": path, "class_name": X} — the reference's
        # custom_injection hook (modules/mpc/mpc.py:120-122)
        from agentlib_mpc_tpu.backends.backend import load_custom_class

        cls = load_custom_class(type_key["file"], type_key["class_name"])
    else:
        if type_key not in MODULE_TYPES:
            raise KeyError(
                f"unknown module type {type_key!r}; known: "
                f"{sorted(MODULE_TYPES)}")
        cls = MODULE_TYPES[type_key]
    return cls(config, agent)


class BaseModule:
    """Base for all agent modules."""

    #: config keys parsed as lists of AgentVariables
    variable_groups: tuple[str, ...] = ("inputs", "outputs", "states",
                                        "parameters")
    #: groups whose variables default to shared=True (broadcast)
    shared_groups: tuple[str, ...] = ("outputs",)
    type_names: tuple[str, ...] = ()

    def __init__(self, config: dict, agent):
        self.config = dict(config)
        self.agent = agent
        self.id = config.get("module_id", type(self).__name__)
        self.env = agent.env
        self.logger = logging.getLogger(
            f"{type(self).__name__}[{agent.id}/{self.id}]")
        #: shutdown signal for modules running background workers; checked
        #: by abortable loops (e.g. ADMM round termination) and set by
        #: :meth:`terminate`. Part of the module contract, not ad-hoc.
        self._stop = threading.Event()
        self.vars: dict[str, AgentVariable] = {}
        self._groups: dict[str, list[str]] = {}
        for group in self.variable_groups:
            names = []
            for cfg in config.get(group, []):
                var = AgentVariable.from_config(cfg)
                # group default shared=True applies only when the config
                # did not set the flag explicitly (dict without "shared");
                # an AgentVariable instance always carries its own choice
                explicit = isinstance(cfg, AgentVariable) or (
                    isinstance(cfg, dict) and "shared" in cfg)
                if group in self.shared_groups and not explicit:
                    var.shared = True
                self._declare(var, group)
                names.append(var.name)
            self._groups[group] = names

    # -- variable store -------------------------------------------------------

    def _declare(self, var: AgentVariable, group: str) -> None:
        if var.name in self.vars:
            raise ValueError(
                f"duplicate variable {var.name!r} in module {self.id}")
        self.vars[var.name] = var

    def variables_in_group(self, group: str) -> list[AgentVariable]:
        return [self.vars[n] for n in self._groups.get(group, [])]

    def get(self, name: str) -> AgentVariable:
        return self.vars[name]

    def get_value(self, name: str):
        return self.vars[name].value

    def set(self, name: str, value) -> None:
        """Update a variable and publish it to the broker (the reference's
        ``self.set(...)`` → data_broker.send_variable path)."""
        var = self.vars[name]
        var.value = value
        var.timestamp = self.env.now
        out = var.copy(source=Source(agent_id=self.agent.id,
                                     module_id=self.id))
        self.agent.data_broker.send_variable(out)

    def send(self, var: AgentVariable) -> None:
        """Publish an ad-hoc variable (not necessarily declared)."""
        out = var.copy(source=Source(agent_id=self.agent.id,
                                     module_id=self.id))
        out.timestamp = self.env.now
        self.agent.data_broker.send_variable(out)

    # -- lifecycle ------------------------------------------------------------

    def register_callbacks(self) -> None:
        """Subscribe to updates for declared variables that reference an
        external source or alias. Default: every variable whose config gave
        an explicit source, or whose alias differs from its name, is
        listened for; received values update the local store."""
        for var in self.vars.values():
            explicit_source = var.source.agent_id is not None \
                or var.source.module_id is not None
            if explicit_source or var.alias != var.name or not var.shared:
                self.agent.data_broker.register_callback(
                    var.alias, var.source, self._make_update_callback(var.name))

    def _make_update_callback(self, name: str):
        def _cb(incoming: AgentVariable):
            local = self.vars[name]
            local.value = incoming.value
            local.timestamp = incoming.timestamp
        return _cb

    def process(self):
        """Override: generator yielding delays (seconds). Default: inert."""
        return None

    def terminate(self) -> None:
        """Release background resources (worker threads, sockets). Called
        by :meth:`Agent.terminate` at MAS shutdown; the default sets the
        ``_stop`` event. Must be idempotent and must not raise."""
        self._stop.set()

    def _join_worker(self, thread, wake_events=(), timeout: float = 10.0):
        """Shared worker-shutdown sequence: signal stop, wake the thread
        out of any event wait, join with a budget, report a stuck worker.
        Returns None (the caller clears its thread reference)."""
        self._stop.set()
        for event in wake_events:
            event.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
            if thread.is_alive():  # pragma: no cover - diagnostic path
                self.logger.error(
                    "worker thread %s did not stop within %.1fs",
                    thread.name, timeout)
        return None

    def cleanup_results(self) -> None:
        pass

    def results(self):
        """Override: return a pandas DataFrame of recorded results."""
        return None
