"""Aux control modules: PID, fallback hand-over, MPC deactivation,
set-point generator, input prediction, time utils.

Covers the reference's deactivation suite (``deactivate_mpc.py``,
``fallback_pid.py``, ``skippable_mixin.py``) and excitation/prediction
modules with direct unit tests plus a MAS hand-over scenario.
"""

import numpy as np
import pytest

import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types
from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.variables import control_input, output, parameter, state
from agentlib_mpc_tpu.modules.deactivate_mpc import MPC_FLAG_ACTIVE
from agentlib_mpc_tpu.runtime.mas import LocalMAS
from agentlib_mpc_tpu.utils.sampling import sample
from agentlib_mpc_tpu.utils.time_utils import (
    convert_time,
    is_time_in_intervals,
)


class TestTimeUtils:
    def test_convert(self):
        assert convert_time(2, "hours", "seconds") == 7200
        assert convert_time(86400, "seconds", "days") == 1

    def test_intervals(self):
        assert is_time_in_intervals(5, [(0, 10)])
        assert not is_time_in_intervals(11, [(0, 10)])
        assert is_time_in_intervals(15, [(0, 10), (12, 20)])


class _Host:
    """Minimal agent stand-in for module unit tests."""

    class _Env:
        now = 0.0

    class _Broker:
        def register_callback(self, *a, **k):
            pass

        def send_variable(self, v):
            pass

    def __init__(self):
        self.id = "host"
        self.env = self._Env()
        self.data_broker = self._Broker()


class TestPIDUnit:
    def _pid(self, **cfg):
        from agentlib_mpc_tpu.modules.pid import PID

        base = {"module_id": "pid",
                "input": {"name": "y"},
                "output": {"name": "u"},
                "setpoint": 10.0, "Kp": 2.0}
        base.update(cfg)
        return PID(base, _Host())

    def test_proportional(self):
        pid = self._pid()
        assert pid.do_step(8.0, 0.0) is None  # first sample arms timing
        assert pid.do_step(8.0, 1.0) == pytest.approx(4.0)  # Kp*e = 2*2

    def test_integral_accumulates(self):
        pid = self._pid(Ti=10.0)
        pid.do_step(8.0, 0.0)
        u1 = pid.do_step(8.0, 1.0)
        u2 = pid.do_step(8.0, 2.0)
        assert u2 > u1  # integral grows with persistent error

    def test_saturation_and_antiwindup(self):
        pid = self._pid(Ti=1.0, ub=1.0)
        pid.do_step(0.0, 0.0)
        for k in range(1, 20):
            u = pid.do_step(0.0, float(k))
        assert u == 1.0
        windup = pid.integral
        # error flips sign: output must unwind immediately, not after
        # discharging a huge integral
        assert windup < 50.0
        u = pid.do_step(20.0, 21.0)
        assert u < 1.0

    def test_reverse_acting(self):
        pid = self._pid(reverse_acting=True)
        pid.do_step(12.0, 0.0)
        assert pid.do_step(12.0, 1.0) == pytest.approx(4.0)  # −(10−12)·2


class TestSetPointGenerator:
    def test_bands(self):
        from agentlib_mpc_tpu.modules.setpoint_generator import \
            SetPointGenerator

        gen = SetPointGenerator({"module_id": "sp", "interval": 3600,
                                 "day_start": 8, "day_end": 16}, _Host())
        assert gen.band_at(10 * 3600.0) == (gen.day_lb, gen.day_ub)
        assert gen.band_at(20 * 3600.0) == (gen.night_lb, gen.night_ub)
        # day 5 = weekend → night band even at noon
        assert gen.band_at((5 * 24 + 12) * 3600.0) == (gen.night_lb,
                                                       gen.night_ub)


class TestInputPredictor:
    def test_prediction_series_sampleable(self):
        from agentlib_mpc_tpu.modules.input_prediction import InputPredictor

        table = {"T_amb": {float(t): 280.0 + t / 100.0
                           for t in range(0, 7200, 600)}}
        mod = InputPredictor({"module_id": "weather", "data": table,
                              "t_sample": 600, "prediction_horizon": 1800,
                              "prediction_sample": 600}, _Host())
        preds = mod.get_prediction_at_time(1200.0)
        times, vals = preds["T_amb"]
        assert len(times) == 4
        assert vals[0] == pytest.approx(292.0)
        # an MPC backend samples the forecast onto its own grid
        onto = sample((times, vals), [0.0, 600.0], current=1200.0)
        np.testing.assert_allclose(onto, [292.0, 298.0])


# -- MAS hand-over scenario ---------------------------------------------------

class OneRoomFast(Model):
    inputs = [
        control_input("mDot", 0.02, lb=0.0, ub=0.05),
        control_input("load", 150.0),
        control_input("T_in", 290.15),
        control_input("T_upper", 295.15),
    ]
    states = [state("T", 295.15, lb=288.15, ub=303.15),
              state("T_slack", 0.0)]
    parameters = [parameter("cp", 1000.0), parameter("C", 100000.0),
                  parameter("s_T", 0.01), parameter("r_mDot", 0.1)]
    outputs = [output("T_out")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", v.cp * v.mDot / v.C * (v.T_in - v.T) + v.load / v.C)
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        from agentlib_mpc_tpu.models.objective import SubObjective

        eq.objective = (SubObjective(v.mDot, weight=v.r_mDot, name="c")
                        + SubObjective(v.T_slack ** 2, weight=v.s_T,
                                       name="s"))
        return eq


@pytest.fixture(scope="module")
def handover_results():
    mpc_agent = {
        "id": "Controller",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "mpc", "type": "mpc",
             "enable_deactivation": True,
             "optimization_backend": {
                 "type": "jax",
                 "model": {"class": OneRoomFast},
                 "discretization_options": {"method": "multiple_shooting"},
                 "solver": {"max_iter": 40}},
             "time_step": 300, "prediction_horizon": 6,
             "inputs": [{"name": "T_in"}, {"name": "load"},
                        {"name": "T_upper"}],
             "controls": [{"name": "mDot", "value": 0.02,
                           "lb": 0, "ub": 0.05}],
             "states": [{"name": "T", "value": 297.15, "alias": "T",
                         "source": "Plant"}],
             "outputs": [{"name": "T_out", "shared": False}],
             "parameters": []},
            # deactivate the MPC between 1500 s and 3000 s
            {"module_id": "onoff", "type": "skip_mpc_intervals",
             "t_sample": 300, "intervals": [[1500, 3000]]},
            {"module_id": "fallback", "type": "fallback_pid",
             "input": {"name": "T", "alias": "T", "source": "Plant"},
             "output": {"name": "mDot", "alias": "mDot"},
             "setpoint": 295.15, "Kp": 0.01, "Ti": 600.0,
             "lb": 0.0, "ub": 0.05, "reverse_acting": True},
        ],
    }
    plant_agent = {
        "id": "Plant",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "room", "type": "simulator",
             "model": {"class": OneRoomFast,
                       "states": [{"name": "T", "value": 297.15}]},
             "t_sample": 60,
             "inputs": [{"name": "mDot", "alias": "mDot"}],
             "outputs": [{"name": "T_out", "alias": "T"}]},
        ],
    }
    mas = LocalMAS([mpc_agent, plant_agent], env={"rt": False})
    mas.run(until=4500)
    return mas


class TestHandover:
    def test_mpc_skips_in_interval(self, handover_results):
        mpc = handover_results.agents["Controller"].get_module("mpc")
        stats = mpc.solver_stats()
        times = stats.index.to_numpy()
        assert not np.any((times >= 1800) & (times < 3000)), \
            "MPC must not solve while deactivated"
        assert np.any(times >= 3000), "MPC must resume after the interval"
        assert np.any(times < 1500)

    def test_flag_broadcast(self, handover_results):
        onoff = handover_results.agents["Controller"].get_module("onoff")
        assert MPC_FLAG_ACTIVE in onoff.vars

    def test_plant_controlled_throughout(self, handover_results):
        sim = handover_results.agents["Plant"].get_module("room")
        df = sim.results()
        # fallback PID keeps cooling during the MPC outage
        outage = df[(df.index > 2000) & (df.index < 3000)]
        assert outage["mDot"].max() > 0.0
        assert df["T_out"].iloc[-1] < 296.5