"""Exported fused steps: trace-free engine revival across processes.

The compile-latency ladder for a fused engine has three rungs: jaxpr
certification (~0.3–2.4 s), Python tracing of the interior-point solver
(seconds — NOT covered by any XLA cache), and XLA compilation (seconds
to tens of seconds — covered by the persistent compilation cache,
``utils/jax_setup.enable_persistent_cache``). The in-process
:class:`~agentlib_mpc_tpu.serving.cache.CompileCache` skips all three
while the process lives; across real process death the persistent XLA
cache used to kill only the third rung, leaving 2× seconds of
certify + trace on every crash restart.

This module kills the other two: a built engine's compiled step is
exported to portable StableHLO (``jax.export``) once, at build time; a
fresh process deserializes the artifact and installs it as the engine's
step WITHOUT ever tracing the solver — certification is skipped by
forcing the recorded qp-routing decisions
(:meth:`FusedADMM.routed_groups` semantics), and the only remaining
cost is one XLA compile of the deserialized module, which the
persistent cache turns into a disk hit. Measured on the 2-core CPU VM:
deserialize ~50 ms + lower ~140 ms + (cache-hit) compile ~0.8 s vs a
13–26 s cold build.

Sharded engines export too: a ``shard_map``-over-mesh step serializes
with its sharding annotations and must be revived in a process with the
SAME device count (``Exported.nr_devices``); the engine store keys
artifacts by mesh identity so a different-size mesh can never splice a
mismatched module. The store's metadata additionally records the
engine's certified **collective schedule digest**
(:mod:`agentlib_mpc_tpu.lint.jaxpr.collectives`): revival constructs
the engine with ``collective_certify="off"`` — the exported program IS
the certified one, so restores stay trace-free — and stamps the
recorded digest onto it, keeping the checkpoint/supervisor schedule-
identity checks working across process boundaries.

Two sharp edges this module owns so callers cannot hit them:

* **PyTree registration** — ``jax.export`` serializes pytree
  structure; the repo's NamedTuple carriers must be registered once
  per process (:func:`register_export_types`, idempotent).
* **Custom-call registration** — executing a deserialized module that
  contains LAPACK custom calls (every KKT factor does) SEGFAULTS in a
  process that never lowered a linalg op, because XLA:CPU registers
  those call targets lazily at lowering time. :func:`warm_linalg_calls`
  lowers (never executes) a tiny op set first — milliseconds, and
  mandatory before any ``install_exported_step``.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

_types_registered = False
_linalg_warmed = False


def register_export_types() -> None:
    """Register the repo's NamedTuple pytree carriers for
    ``jax.export`` serialization (idempotent, once per process)."""
    global _types_registered
    if _types_registered:
        return
    from jax import export as jexport

    from agentlib_mpc_tpu.ops.transcription import OCPParams
    from agentlib_mpc_tpu.parallel.fused_admm import (
        FusedState,
        IterationStats,
    )

    for cls in (FusedState, IterationStats, OCPParams):
        try:
            jexport.register_namedtuple_serialization(
                cls, serialized_name=f"agentlib_mpc_tpu.{cls.__name__}")
        except ValueError:
            pass    # already registered (e.g. by a parallel import path)
    _types_registered = True


def warm_linalg_calls() -> None:
    """Register XLA:CPU's LAPACK/BLAS custom-call targets by LOWERING
    (never executing) a tiny linalg op set. Executing a deserialized
    exported module whose body contains those custom calls in a process
    that never lowered one crashes the process — registration happens
    lazily inside the lowering rules, which export-based revival
    bypasses by design. Idempotent, milliseconds."""
    global _linalg_warmed
    if _linalg_warmed:
        return
    import jax.scipy.linalg as jsl

    for dt in (jnp.float32, jnp.float64):
        x = jax.ShapeDtypeStruct((2, 2), dt)
        jax.jit(lambda m: jsl.lu_factor(m)[0]).lower(x)
        jax.jit(lambda m: jsl.cho_factor(m)[0]).lower(x)
        jax.jit(lambda m: jsl.solve_triangular(m, m)).lower(x)
        jax.jit(lambda m: jnp.linalg.solve(m, m)).lower(x)
    _linalg_warmed = True


def export_fused_step(engine, state, theta_batches, active=None) -> bytes:
    """Serialize an engine's compiled step to portable bytes.

    ``state``/``theta_batches`` supply the input avals AND shardings
    (pass exactly what :meth:`FusedADMM.step` is called with — for mesh
    engines that means ``shard_args``-placed inputs, so the artifact
    records the production sharding). The engine must already be
    warm (stepped once): exporting re-lowers from the traced step, so
    an unwarmed engine would pay its trace here instead.
    """
    from jax import export as jexport

    register_export_types()
    masks = engine.active if active is None \
        else tuple(jnp.asarray(a, bool) for a in active)
    exported = jexport.export(engine._step)(
        state, tuple(theta_batches), masks)
    return exported.serialize()


def prewarm_exported(blob: bytes, state, theta_batches, active) -> None:
    """Compile the DESERIALIZED module once in this process, seeding
    the persistent XLA cache with the exact program a fresh process
    compiles at restore — the original traced step and its exported
    twin lower to different cache fingerprints, so without this the
    first crash restart after every cold build pays a real compile.
    One extra (cache-stored) compile at save time buys every future
    restart a disk hit."""
    from jax import export as jexport

    register_export_types()
    warm_linalg_calls()
    exported = jexport.deserialize(blob)
    masks = tuple(jnp.asarray(a, bool) for a in active)
    jax.jit(exported.call).lower(state, tuple(theta_batches),
                                 masks).compile()


def install_exported_step(engine, blob: bytes, warm_args=None) -> None:
    """Revive an engine's step from exported bytes: ``engine._step``
    becomes the deserialized module under ``jax.jit`` — the solver is
    never traced in this process. The engine must have been constructed
    with the SAME structure/capacity/mesh the artifact was exported
    from (the engine store's key discipline); a mesh mismatch fails
    loudly at deserialization (``Exported.nr_devices``).

    ``warm_args``: optional ``(state, theta_batches, active)`` to run
    one throwaway call NOW, so the single XLA compile of the
    deserialized module (persistent-cache-covered) lands inside the
    restore measurement instead of ambushing the first served round.
    """
    from jax import export as jexport

    register_export_types()
    warm_linalg_calls()
    exported = jexport.deserialize(blob)
    n_here = 1 if engine.mesh is None else int(engine.mesh.devices.size)
    if int(exported.nr_devices) != n_here:
        raise ValueError(
            f"exported step spans {exported.nr_devices} device(s) but "
            f"the engine's mesh has {n_here} — a different-size mesh "
            f"cannot splice this artifact (rebuild cold, or restore on "
            f"the recorded topology)")
    donate = (0,) if engine.donate_state else ()
    engine._step = jax.jit(exported.call, donate_argnums=donate)
    engine.step_restored_from_export = True
    if warm_args is not None:
        state, thetas, masks = warm_args
        out = engine._step(state, tuple(thetas),
                           tuple(jnp.asarray(a, bool) for a in masks))
        jax.block_until_ready(out)
