"""Retrace-budget gate + the PR 2 weak-type regression, live.

The static analyzer (``test_lint.py``) proves the weak-typed
``init_state`` literal is caught at the AST layer; this file proves the
*runtime* layer: the fused-ADMM engine must run warm rounds with ZERO
additional traces/compiles (the "compile once, dispatch forever"
contract), and a weak-typed carry — the exact PR 2 bug — must trip the
retrace counters the gate watches.

Uses the ``compile_profiler`` conftest fixture (telemetry +
``jax.monitoring`` hooks) and the same 4-agent tracker fleet
``python -m agentlib_mpc_tpu.lint --retrace-budget`` runs in CI.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax.numpy as jnp

from agentlib_mpc_tpu.lint.retrace_budget import (
    build_bench_engine,
    run_gate,
)


class TestRetraceBudgetGate:
    def test_zero_recompiles_across_three_warm_rounds(self):
        """The CI gate, in-process: 2 warmup rounds then 3 measured
        rounds with shift_state between steps (values change, avals must
        not) — every entry point's trace+compile delta must be zero."""
        report = run_gate(budgets={"retrace": {
            "warmup_rounds": 2, "rounds": 3, "n_agents": 4,
            "budgets": {"default": 0}}}, verbose=False)
        assert report["violations"] == [], report
        assert all(delta == 0 for delta in report["deltas"].values()), \
            report["deltas"]

    def test_zero_recompiles_with_stage_factorization(self):
        """The checked-in lint_budgets.toml pins kkt_method="stage": the
        stage-structured KKT sweep (ops/stagewise.py) inside the fused
        fleet must hold the same zero-recompile steady state as the
        dense paths it replaces — its scan/permutation plumbing is all
        static, so one warm trace serves every round."""
        report = run_gate(budgets={"retrace": {
            "warmup_rounds": 2, "rounds": 3, "n_agents": 4,
            "kkt_method": "stage", "budgets": {"default": 0}}},
            verbose=False)
        assert report["kkt_method"] == "stage"
        assert report["violations"] == [], report
        assert all(delta == 0 for delta in report["deltas"].values()), \
            report["deltas"]

    def test_zero_recompiles_with_sparse_jacobian_pipeline(self):
        """The checked-in lint_budgets.toml now ALSO pins
        jacobian="sparse": the stage-sparse derivative pipeline
        (ops/stagejac.py — compressed pullbacks, banded assembly,
        banded stage factor) must hold the same zero-recompile steady
        state as the dense jacrev path it replaces; every seed matrix
        and scatter index is a static constant, so one warm trace
        serves every round."""
        report = run_gate(budgets={"retrace": {
            "warmup_rounds": 2, "rounds": 3, "n_agents": 4,
            "kkt_method": "stage", "jacobian": "sparse",
            "budgets": {"default": 0}}},
            verbose=False)
        assert report["jacobian"] == "sparse"
        assert report["violations"] == [], report
        assert all(delta == 0 for delta in report["deltas"].values()), \
            report["deltas"]

    def test_weak_typed_init_state_is_caught_by_the_gate(
            self, compile_profiler):
        """Re-introduce the PR 2 bug at runtime: replace the strong-typed
        z warm-start fill with a weak-typed one (``jnp.full(...)`` without
        dtype). Round 1 traces with weak avals; the engine returns
        strong-typed arrays, so round 2's carry differs and the whole
        fused program retraces — which the gate's counters must see."""
        from agentlib_mpc_tpu.telemetry import jax_events

        engine, state, thetas = build_bench_engine(4)
        state = state._replace(
            z=tuple(jnp.full(z.shape, 0.1) for z in state.z))
        assert all(z.weak_type for z in state.z)

        jax_events.reset_scopes()
        state, _trajs, _stats = engine.step(state, thetas)
        after_round1 = compile_profiler.counter(
            "jax_retraces_total").total()
        assert not any(getattr(z, "weak_type", False) for z in state.z), \
            "engine output z should be strong-typed"
        state, _trajs, _stats = engine.step(state, thetas)
        after_round2 = compile_profiler.counter(
            "jax_retraces_total").total()
        assert after_round2 > after_round1, (
            "weak-typed carry did not retrace — either jax now "
            "auto-strengthens (great: delete this engine rebuild cost) "
            "or the profiling hooks lost the event")
