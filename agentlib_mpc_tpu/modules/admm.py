"""Decentralized ADMM modules (peer-to-peer distributed MPC).

Re-design of the reference's fully decentralized consensus/exchange-ADMM
(``modules/dmpc/admm/admm.py``): each agent owns an augmented local OCP
(`ADMMBackend`), broadcasts its coupling trajectories over the broker,
registers whoever else broadcasts on the same coupling alias, averages the
received trajectories, and updates its multipliers — iterating until a
wall-clock/iteration budget is exhausted. Two execution modes, mirroring the
reference:

- ``admm_local`` (`LocalADMM`): the whole algorithm as one cooperative
  generator with tiny sync yields — deterministic fast simulation, the mode
  most reference examples/tests use (``admm.py:873-937``).
- ``admm`` (`RealtimeADMM`): wall-clock mode — a daemon thread performs the
  ADMM round each time a periodic event fires, with a real registration
  window and blocking receive timeouts (``admm.py:143-321``).

Protocol compatibility: coupling trajectories travel under the reference's
wire aliases (``admm_coupling_<alias>`` / ``admm_exchange_<alias>``,
``data_structures/admm_datatypes.py:16-23,112-120``), so a mixed deployment
against reference agents speaks the same naming scheme.

The numerics (mean, multiplier update, penalties) are the tested pure
functions in ``ops/admm.py``; this module is only host-side protocol. The
per-iteration local solve is the jitted augmented OCP — it never recompiles
across iterations because means/multipliers are traced arguments.
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from collections import deque
from enum import Enum, auto
from typing import Dict, Iterable, List, Optional

import numpy as np

from agentlib_mpc_tpu.backends.admm_backend import (
    ADMMVariableReference,
    EXCHANGE_LOCAL_PREFIX,
    EXCHANGE_MEAN_PREFIX,
    EXCHANGE_MULTIPLIER_PREFIX,
    ADMM_PREFIX,
    LOCAL_PREFIX,
    MEAN_PREFIX,
    MULTIPLIER_PREFIX,
)
from agentlib_mpc_tpu.modules.mpc import BaseMPC
from agentlib_mpc_tpu.runtime.module import register_module
from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source


@dataclasses.dataclass(frozen=True)
class CouplingEntry:
    """Naming conventions for the aux quantities of one consensus coupling
    (reference ``admm_datatypes.py:26-50``)."""

    name: str

    @property
    def local(self) -> str:
        return f"{LOCAL_PREFIX}_{self.name}"

    @property
    def mean(self) -> str:
        return f"{MEAN_PREFIX}_{self.name}"

    @property
    def multiplier(self) -> str:
        return f"{MULTIPLIER_PREFIX}_{self.name}"


@dataclasses.dataclass(frozen=True)
class ExchangeEntry:
    """Naming conventions for one exchange coupling
    (reference ``admm_datatypes.py:53-77``)."""

    name: str

    @property
    def local(self) -> str:
        return f"{EXCHANGE_LOCAL_PREFIX}_{self.name}"

    @property
    def mean_diff(self) -> str:
        return f"{EXCHANGE_MEAN_PREFIX}_{self.name}"

    @property
    def multiplier(self) -> str:
        return f"{EXCHANGE_MULTIPLIER_PREFIX}_{self.name}"


def coupling_alias(alias: str) -> str:
    """Wire alias for consensus coupling broadcasts
    (``admm_datatypes.py:112-115``)."""
    return f"{LOCAL_PREFIX}_{alias}"


def exchange_alias(alias: str) -> str:
    """Wire alias for exchange coupling broadcasts
    (``admm_datatypes.py:118-120``)."""
    return f"{EXCHANGE_LOCAL_PREFIX}_{alias}"


class ParticipantStatus(Enum):
    not_participating = auto()
    available = auto()
    confirmed = auto()
    not_available = auto()


class ModuleStatus(Enum):
    syncing = auto()
    at_registration = auto()
    optimizing = auto()
    waiting_for_other_agents = auto()
    updating = auto()
    sleeping = auto()


_ITERATING = (ModuleStatus.optimizing, ModuleStatus.waiting_for_other_agents,
              ModuleStatus.updating)


_INBOX_DEPTH = 5


@dataclasses.dataclass
class NeighborLink:
    """Registration status + bounded trajectory inbox for one neighbor on
    one coupling wire (role of the participation record in reference
    ``admm.py:47-65``, re-done as a condition-guarded ring: broker callback
    threads deposit with :meth:`push`, the ADMM round takes with
    :meth:`pop`). Consumption is FIFO — the ADMM round processes a
    neighbor's iterates in order, one per iteration, keeping rounds
    aligned when a neighbor runs ahead. Only the bound is newest-biased:
    under flood the *stalest* queued trajectory is evicted (retention of
    the newest ``_INBOX_DEPTH``), since once entries must be dropped the
    oldest iterates are the least useful to the consensus update."""

    variable: AgentVariable  # guarded-by: self._cv
    status: ParticipantStatus = ParticipantStatus.not_participating  # guarded-by: self._cv
    _inbox: deque = dataclasses.field(  # guarded-by: self._cv
        default_factory=lambda: deque(maxlen=_INBOX_DEPTH))
    _cv: threading.Condition = dataclasses.field(
        default_factory=threading.Condition)

    def push(self, variable: AgentVariable) -> bool:
        """Deposit a broadcast and wake any blocked :meth:`pop`. Returns
        ``False`` when the bounded inbox evicted its oldest entry (the
        sender is flooding faster than this agent iterates)."""
        with self._cv:
            evicted = len(self._inbox) == self._inbox.maxlen
            self._inbox.append(variable)
            self.variable = variable
            self.status = ParticipantStatus.available
            self._cv.notify_all()
        return not evicted

    def pop(self, timeout: Optional[float] = None) -> Optional[AgentVariable]:
        """Take the oldest pending trajectory, waiting up to ``timeout``
        seconds for one to arrive (no wait when ``timeout`` is ``None``).
        Returns ``None`` if nothing arrived in time."""
        with self._cv:
            if timeout is not None and not self._inbox:
                self._cv.wait_for(lambda: bool(self._inbox), timeout)
            return self._inbox.popleft() if self._inbox else None

    @property
    def pending(self) -> int:
        with self._cv:
            return len(self._inbox)

    def set_status(self, status: ParticipantStatus) -> None:
        """Status transition from outside the link (the ADMM round
        thread); broker callback threads transition via :meth:`push`."""
        with self._cv:
            self.status = status

    def confirm(self, variable: AgentVariable) -> None:
        """Accept a popped trajectory as this iteration's contribution."""
        with self._cv:
            self.variable = variable
            self.status = ParticipantStatus.confirmed

    def reset(self, status: ParticipantStatus
              = ParticipantStatus.not_participating,
              variable: "AgentVariable | None" = None) -> None:
        """Drop all queued trajectories and move to ``status``
        (optionally refreshing the registration variable in the same
        critical section)."""
        with self._cv:
            self._inbox.clear()
            self.status = status
            if variable is not None:
                self.variable = variable


class ADMMModule(BaseMPC):
    """Shared machinery of both decentralized ADMM variants."""

    variable_groups = ("inputs", "outputs", "states", "parameters",
                       "controls", "couplings", "exchange")
    shared_groups = ("outputs", "controls", "couplings", "exchange")

    def __init__(self, config: dict, agent):
        self.penalty_factor = float(config.get("penalty_factor", 10.0))
        self.max_iterations = int(config.get("max_iterations", 20))
        self.iteration_timeout = float(config.get("iteration_timeout", 20.0))
        self.registration_period = float(
            config.get("registration_period", 2.0))
        self._status = ModuleStatus.syncing
        self._registered_participants: Dict[
            str, Dict[Source, NeighborLink]] = {}
        self._admm_values: Dict[str, np.ndarray] = {}
        self._iter_rows: List[dict] = []
        super().__init__(config, agent)

    # -- setup ---------------------------------------------------------------

    def _declare(self, var: AgentVariable, group: str) -> None:
        if var.name.startswith(ADMM_PREFIX):
            # reserved namespace (reference config guard, admm.py:95-108)
            raise ValueError(
                f"variable {var.name!r}: names starting with "
                f"{ADMM_PREFIX!r} are reserved for the ADMM protocol")
        super()._declare(var, group)

    def _setup_backend(self) -> None:
        from agentlib_mpc_tpu.backends.backend import load_model_for_backend

        self.couplings = [CouplingEntry(n)
                          for n in self._groups.get("couplings", [])]
        self.exchange = [ExchangeEntry(n)
                         for n in self._groups.get("exchange", [])]
        if not (self.couplings or self.exchange):
            raise ValueError(
                "ADMM module needs at least one coupling or exchange "
                "variable")
        self.var_ref = ADMMVariableReference(
            states=self._groups.get("states", []),
            controls=self._groups.get("controls", []),
            inputs=self._groups.get("inputs", []),
            parameters=self._groups.get("parameters", []),
            outputs=self._groups.get("outputs", []),
            couplings=[c.name for c in self.couplings],
            exchange=[e.name for e in self.exchange],
        )
        model = load_model_for_backend(self.backend.config["model"],
                                       dt=self.time_step)
        self.backend.config["model"] = model
        self.backend.setup_optimization(
            self.var_ref, self.time_step, self.prediction_horizon)
        self._init_admm_state()

    def _init_admm_state(self) -> None:
        """Create the aux trajectories and subscribe to the coupling wire
        aliases (reference ``_create_couplings``, ``admm.py:683-814``)."""
        n = len(self.backend.coupling_grid)
        for entry in self.cons_and_exchange:
            var = self.vars[entry.name]
            init = var.value if var.value is not None else 0.0
            self._admm_values[entry.local] = np.full(n, float(init))
            self._admm_values[entry.multiplier] = np.zeros(n)
            mean_key = entry.mean if isinstance(entry, CouplingEntry) \
                else entry.mean_diff
            self._admm_values[mean_key] = np.full(n, float(init)) \
                if isinstance(entry, CouplingEntry) else np.zeros(n)
            wire = self._wire_alias(entry)
            self._registered_participants.setdefault(wire, {})
            self.agent.data_broker.register_callback(
                wire, None, self.participant_callback)

    def _wire_alias(self, entry) -> str:
        var = self.vars[entry.name]
        if isinstance(entry, CouplingEntry):
            return coupling_alias(var.alias)
        return exchange_alias(var.alias)

    @property
    def cons_and_exchange(self):
        return [*self.couplings, *self.exchange]

    # -- participant bookkeeping ---------------------------------------------

    def participant_callback(self, variable: AgentVariable) -> None:
        """Route a received coupling broadcast into the sender's inbox
        (reference ``participant_callback``/``receive_participant``,
        ``admm.py:440-501``)."""
        if variable.source.agent_id == self.agent.id:
            return
        inboxes = self._registered_participants[variable.alias]
        if variable.source not in inboxes:
            self.logger.info("initially registered %s from %s",
                             variable.alias, variable.source)
            inboxes[variable.source] = NeighborLink(variable)
        neighbor = inboxes[variable.source]
        if self._status == ModuleStatus.at_registration:
            neighbor.reset(ParticipantStatus.not_available,
                           variable=variable)
        elif self._status in _ITERATING:
            if not neighbor.push(variable):
                self.logger.error(
                    "participant %s floods coupling %s; evicted its "
                    "stalest queued trajectory", variable.source,
                    variable.alias)

    def all_participations(self) -> Iterable[NeighborLink]:
        for per_coupling in self._registered_participants.values():
            yield from per_coupling.values()

    def reset_participants_ready(self) -> None:
        for p in self.all_participations():
            p.set_status(ParticipantStatus.available if p.pending
                         else ParticipantStatus.not_available)

    def deregister_all_participants(self) -> None:
        for p in self.all_participations():
            p.reset()

    def _receive_variables(self, start_wall: float, block: bool) -> None:
        """Collect one fresh trajectory per registered participant; slow
        ones are de-registered for the rest of the round
        (reference ``_receive_variables``, ``admm.py:298-321``)."""
        for participant in self.all_participations():
            if participant.status == ParticipantStatus.not_participating:
                continue
            remaining = max(
                self.iteration_timeout - (_time.time() - start_wall), 0.0)
            var = participant.pop(timeout=remaining if block else None)
            if var is not None:
                participant.confirm(var)
            else:
                participant.reset()
                self.logger.info(
                    "de-registered %s from %s (too slow)",
                    participant.variable.source, participant.variable.alias)

    def participant_values(self, wire: str) -> List[np.ndarray]:
        values = []
        for p in self._registered_participants[wire].values():
            if p.status == ParticipantStatus.confirmed:
                values.append(np.asarray(p.variable.value, dtype=float))
        return values

    # -- ADMM updates (host-side protocol around ops/admm math) ---------------

    def _shift(self, arr: np.ndarray) -> np.ndarray:
        """Shift one control interval forward, repeating the tail
        (reference ``_shift``, ``admm.py:328-342``)."""
        from agentlib_mpc_tpu.utils.sampling import shift_time_series

        return shift_time_series(arr, self.prediction_horizon)

    def _shift_and_send_couplings(self) -> None:
        """Warm-start broadcast that doubles as registration
        (``_shift_and_send_coupling_outputs``, ``admm.py:356-375``)."""
        for entry in self.cons_and_exchange:
            local = self._shift(self._admm_values[entry.local])
            self._admm_values[entry.local] = local
            self.send_coupling_variable(entry, local)

    def _shift_multipliers(self) -> None:
        for entry in self.cons_and_exchange:
            self._admm_values[entry.multiplier] = self._shift(
                self._admm_values[entry.multiplier])

    def send_coupling_variable(self, entry, value: np.ndarray) -> None:
        self.send(AgentVariable(
            name=entry.local, value=list(np.asarray(value, dtype=float)),
            alias=self._wire_alias(entry), shared=True, type="list"))

    def send_coupling_values(self, result: dict) -> None:
        """Broadcast the freshly optimized local coupling trajectories
        (``send_coupling_values``, ``admm.py:513-526``)."""
        for entry in self.cons_and_exchange:
            traj = np.asarray(result["couplings"][entry.name], dtype=float)
            self._admm_values[entry.local] = traj
            self.send_coupling_variable(entry, traj)

    def _set_mean_coupling_values(self) -> None:
        """Average own + received trajectories; exchange couplings store
        the deviation x − mean (``_set_mean_coupling_values``,
        ``admm.py:528-570``)."""
        for entry in self.couplings:
            own = self._admm_values[entry.local]
            values = self.participant_values(self._wire_alias(entry))
            values.append(own)
            self._admm_values[entry.mean] = np.mean(
                np.stack(values), axis=0)
        for entry in self.exchange:
            own = self._admm_values[entry.local]
            values = self.participant_values(self._wire_alias(entry))
            values.append(own)
            mean = np.mean(np.stack(values), axis=0)
            self._admm_values[entry.mean_diff] = own - mean

    def update_lambda(self) -> None:
        """Scaled-dual update λ ← λ − ρ(z̄ − x) / λ ← λ − ρ(diff − x)
        (``update_lambda``, ``admm.py:612-655``)."""
        rho = self.penalty_factor
        for entry in self.couplings:
            lam = self._admm_values[entry.multiplier]
            x = self._admm_values[entry.local]
            zbar = self._admm_values[entry.mean]
            self._admm_values[entry.multiplier] = lam - rho * (zbar - x)
        for entry in self.exchange:
            lam = self._admm_values[entry.multiplier]
            x = self._admm_values[entry.local]
            diff = self._admm_values[entry.mean_diff]
            self._admm_values[entry.multiplier] = lam - rho * (diff - x)

    # -- optimization ---------------------------------------------------------

    def collect_variables_for_optimization(self) -> dict:
        out = super().collect_variables_for_optimization()
        out["penalty_factor"] = self.penalty_factor
        return out

    def _solve_local(self, opt_inputs: dict, start_time: float,
                     admm_iter: int = 0) -> dict:
        opt_inputs = dict(opt_inputs)
        opt_inputs["admm_iteration"] = admm_iter
        for entry in self.cons_and_exchange:
            opt_inputs[entry.multiplier] = self._admm_values[entry.multiplier]
            if isinstance(entry, CouplingEntry):
                opt_inputs[entry.mean] = self._admm_values[entry.mean]
            else:
                opt_inputs[entry.mean_diff] = self._admm_values[entry.mean_diff]
        return self.backend.solve(start_time, opt_inputs)

    def _check_termination(self, admm_iter: int, start_time: float,
                           start_wall: float) -> bool:
        """Wall-clock budget ∨ iteration cap (``_check_termination``,
        ``admm.py:263-296``). In fast simulation the clock does not advance
        inside a round, so the iteration cap governs."""
        if self._stop.is_set():
            return True     # MAS shutdown: abandon the round cleanly
        budget = self.time_step - self.registration_period
        elapsed = (_time.time() - start_wall) if self.env.rt \
            else (self.env.now - start_time)
        if elapsed > budget:
            self.logger.warning(
                "ADMM exceeded the sampling-time budget of %ss; "
                "terminating control step", budget)
            return True
        if admm_iter >= self.max_iterations:
            self.logger.info("ADMM reached max_iterations=%s",
                             self.max_iterations)
            return True
        return False

    # -- the shared iteration body (VERDICT r5 weak #6) -----------------------

    def _run_admm_iterations(self, opt_inputs: dict, *, block: bool):
        """The solve → send → receive → update iteration loop shared by
        :class:`LocalADMM` and :class:`RealtimeADMM` (the two copies had
        already drifted once, per git history). A generator: it yields at
        every synchronization point — the fast-simulation variant re-emits
        each yield as an env delay to keep the lock-step fleet aligned,
        the realtime variant just drains them (:meth:`_drain`). ``block``
        is the receive semantics (realtime blocks with timeouts against a
        per-iteration wall clock; local polls against the round start).
        Returns (via ``StopIteration.value``) the last local result."""
        start_iterations = self.env.now
        start_wall = _time.time()
        admm_iter = 0
        result = None
        while True:
            recv_start = _time.time() if block else start_wall
            self._status = ModuleStatus.optimizing
            result = self._solve_local(opt_inputs, start_iterations,
                                       admm_iter)
            yield
            self.send_coupling_values(result)
            yield
            self._status = ModuleStatus.waiting_for_other_agents
            self._receive_variables(recv_start, block=block)
            yield
            self._status = ModuleStatus.updating
            self._set_mean_coupling_values()
            self.update_lambda()
            self.reset_participants_ready()
            self._record_iteration(result, admm_iter)
            yield
            admm_iter += 1
            if self._check_termination(admm_iter, start_iterations,
                                       start_wall):
                return result

    @staticmethod
    def _drain(gen):
        """Run a sync-point generator to completion, returning its result
        (the realtime variant has no scheduler to hand the yields to)."""
        while True:
            try:
                next(gen)
            except StopIteration as stop:
                return stop.value

    def _finish_round(self, result: "dict | None") -> None:
        """Common round epilogue: release neighbors, then actuate only
        what the resilience guard clears."""
        self.deregister_all_participants()
        decision = self.guarded_actuation(result)
        if decision.action == "actuate":
            self._record(result)

    # -- results --------------------------------------------------------------

    def _record_iteration(self, result: dict, admm_iter: int) -> None:
        self._iter_rows.append({
            "time": float(self.env.now),
            "iteration": admm_iter,
            "couplings": {k: np.asarray(v)
                          for k, v in result["couplings"].items()},
            "stats": result["stats"],
        })

    def admm_results(self):
        """(time, iteration, grid) MultiIndex coupling trajectories — the
        reference's iteration-buffered ADMM results layout
        (``casadi_/admm.py:364-424``; shared frame builder in
        utils/results.py, also used by the fused fleet)."""
        from agentlib_mpc_tpu.utils.results import (
            admm_iteration_frame,
            concat_admm_frames,
        )

        if not self._iter_rows:
            return None
        grid = np.asarray(self.backend.coupling_grid, dtype=float)
        frames = [
            admm_iteration_frame(row["time"], [row["iteration"]], grid,
                                 row["couplings"])
            for row in self._iter_rows]
        return concat_admm_frames(frames)

    def results(self):
        """dict with 'admm' (per-iteration couplings) and 'mpc' (per-step
        trajectories) DataFrames."""
        out = {}
        admm = self.admm_results()
        if admm is not None:
            out["admm"] = admm
        mpc = super().results()
        if mpc is not None:
            out["mpc"] = mpc
        return out or None

    def cleanup_results(self) -> None:
        super().cleanup_results()
        self._iter_rows.clear()


@register_module("admm_local", "local_admm")
class LocalADMM(ADMMModule):
    """Cooperative fast-simulation variant: the whole ADMM round is one
    generator; sync yields keep all agents in lock-step
    (reference ``LocalADMM.process``, ``admm.py:873-937``)."""

    def __init__(self, config: dict, agent):
        self.sync_delay = float(config.get("sync_delay", 1e-3))
        super().__init__(config, agent)

    def process(self):
        while True:
            start_round = self.env.now
            self._status = ModuleStatus.at_registration
            yield self.sync_delay
            self._shift_and_send_couplings()
            self._shift_multipliers()
            yield self.sync_delay
            self._status = ModuleStatus.optimizing
            yield self.sync_delay

            self._set_mean_coupling_values()
            opt_inputs = self.collect_variables_for_optimization()
            iterations = self._run_admm_iterations(opt_inputs, block=False)
            while True:
                try:
                    next(iterations)
                except StopIteration as stop:
                    result = stop.value
                    break
                yield self.sync_delay

            self._finish_round(result)
            self._status = ModuleStatus.sleeping
            spent = self.env.now - start_round
            yield max(self.time_step - spent, 0.0)


@register_module("admm")
class RealtimeADMM(ADMMModule):
    """Wall-clock variant: a daemon thread runs the ADMM round whenever the
    periodic event fires; registration is a real time window and receives
    block with timeouts (reference ``ADMM``, ``admm.py:143-321``)."""

    def __init__(self, config: dict, agent):
        self.start_step = threading.Event()
        self._thread: Optional[threading.Thread] = None
        super().__init__(config, agent)   # provides self._stop

    def process(self):
        self._thread = threading.Thread(
            target=self._admm_loop, daemon=True,
            name=f"admm_loop_{self.agent.id}")
        self._thread.start()
        self._status = ModuleStatus.syncing
        # sync to a multiple of the time step (reference ``_sync_start``)
        if self.env.rt:
            yield self.time_step - (_time.time() % self.time_step)
        while True:
            self._fire_trigger()
            yield self.time_step

    def _fire_trigger(self) -> None:
        """Kick the worker for the next round — unless the previous round
        is still in flight, which is reported, not queued
        (reference overrun detection, ``admm.py:277-286``)."""
        if self.start_step.is_set():
            self.logger.error(
                "previous ADMM round still running; skipping trigger")
        else:
            self.start_step.set()

    def _admm_loop(self) -> None:
        while not self._stop.is_set():
            # bounded wait so the worker notices a stop request promptly
            if not self.start_step.wait(timeout=0.2):
                continue
            self.start_step.clear()
            if self._stop.is_set():
                break
            try:
                self.admm_step()
            except Exception:  # pragma: no cover - diagnostic path
                if not self._stop.is_set():
                    self.logger.exception("ADMM round failed")
            self._status = ModuleStatus.sleeping

    def terminate(self) -> None:
        """Join the worker thread (clean interpreter shutdown: a daemon
        thread killed while blocked inside a C frame dies with 'FATAL:
        exception not rethrown'). An in-flight round exits at its next
        iteration boundary via the ``_stop``-aware termination check."""
        self._thread = self._join_worker(
            self._thread, wake_events=(self.start_step,),
            timeout=self.registration_period + self.iteration_timeout + 5.0)

    def admm_step(self) -> None:
        self._status = ModuleStatus.at_registration
        self._shift_and_send_couplings()
        self._shift_multipliers()
        _time.sleep(self.registration_period)
        self._status = ModuleStatus.updating

        self._set_mean_coupling_values()
        opt_inputs = self.collect_variables_for_optimization()
        result = self._drain(
            self._run_admm_iterations(opt_inputs, block=True))
        self._finish_round(result)
