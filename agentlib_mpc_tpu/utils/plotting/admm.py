"""ADMM diagnostics plots (reference ``utils/plotting/admm_residuals.py``
and ``admm_consensus_shades.py``)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from agentlib_mpc_tpu.utils.analysis import admm_at_time_step
from agentlib_mpc_tpu.utils.plotting.basic import COLORS, Style, make_fig


def plot_admm_residuals(stats, ax=None, rho: bool = True,
                        style: Optional[Style] = None):
    """stats: coordinator per-iteration DataFrame with columns
    primal_residual / dual_residual (and penalty) — semilog residual decay
    (reference ``admm_residuals.py:11-60``). Accepts a flat frame (one
    step) or one indexed (time, iteration)."""
    if ax is None:
        _, axes = make_fig(style)
        ax = axes[0, 0]
    idx = np.arange(len(stats))
    ax.semilogy(idx, np.abs(stats["primal_residual"].to_numpy(dtype=float)),
                color=COLORS["blue"], label="primal residual")
    ax.semilogy(idx, np.abs(stats["dual_residual"].to_numpy(dtype=float)),
                color=COLORS["red"], label="dual residual")
    if rho and "penalty" in stats:
        ax.semilogy(idx, stats["penalty"].to_numpy(dtype=float),
                    color=COLORS["grey"], linestyle="--", label="rho")
    ax.set_xlabel("ADMM iteration")
    ax.set_ylabel("residual")
    ax.legend()
    return ax


def plot_admm_consensus(data, variable: str, time_step: float, ax=None,
                        color: Optional[str] = None):
    """Iteration shades of one coupling trajectory converging at one
    control step (reference ``admm_consensus_shades.py``)."""
    if ax is None:
        _, axes = make_fig()
        ax = axes[0, 0]
    color = color or COLORS["green"]
    sl = admm_at_time_step(data, time_step)
    iters = np.unique(np.asarray(sl.index.get_level_values(0), dtype=float))
    for i, it in enumerate(iters):
        series = admm_at_time_step(data, time_step, variable, iteration=it)
        alpha = 0.15 + 0.85 * (i + 1) / len(iters)
        ax.plot(series.index, series.to_numpy(dtype=float), color=color,
                alpha=alpha,
                label=f"iter {int(it)}" if it == iters[-1] else None)
    ax.set_xlabel("time / s")
    ax.set_ylabel(variable)
    return ax
