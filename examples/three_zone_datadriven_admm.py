"""3-zone data-driven ADMM: ANN-NARX zone surrogates negotiate shared air.

Native re-design of the reference's three-zone data-driven benchmark
(``examples/three_zone_datadriven_admm/admm_3zone_sim.py``): each zone's
thermal dynamics are *learned* (ANN NARX surrogate trained on excitation
data from the physical plant), the learned models sit inside the local
OCPs (``jax_admm_ml`` backend), and the zones negotiate their shared
air-supply capacity with a physical AHU agent via consensus-ADMM — the
combination of the ML-surrogate stack (SURVEY.md §2.5/§2.6) with the
distributed-MPC stack (§2.2). Simulators run the *true* physical zones,
so the closed loop also tests surrogate fidelity.

This is one of the four BASELINE.md benchmark configs. Run directly for a
report, or call ``run_example`` (examples-as-tests, SURVEY.md §4).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

import agentlib_mpc_tpu.modules  # noqa: F401 - registers module types
from agentlib_mpc_tpu.ml import Feature, OutputFeature
from agentlib_mpc_tpu.ml.training import (
    ANNTrainerCore,
    create_lagged_features,
    fit_ann,
    resample,
    train_val_test_split,
)
from agentlib_mpc_tpu.models.ml_model import MLModel
from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.objective import SubObjective
from agentlib_mpc_tpu.models.variables import (
    control_input,
    output,
    parameter,
    state,
)
from agentlib_mpc_tpu.models.zoo import CooledRoom
from agentlib_mpc_tpu.runtime.mas import LocalMAS

N_ZONES = 3
DT = 300.0
HORIZON = 8
UB = 295.15
START_TEMP = 298.16
T_IN = 290.15
CP = 1000.0
C_CAP = 100000.0
LOADS = (90.0, 130.0, 170.0)
MDOT_MAX = 0.075  # shared AHU capacity; holding all 3 at the band needs ~0.08


def plant_step(T: float, mDot: float, load: float) -> float:
    """The 'true' zone (1R1C air-volume energy balance, explicit Euler on
    the control grid — the same physics the surrogate must learn)."""
    return float(T + DT * (CP * mDot / C_CAP * (T_IN - T) + load / C_CAP))


def train_zone_surrogate(load: float, epochs: int = 300, seed: int = 0):
    """Excite the true zone with random flows, fit an ANN NARX on
    (mDot, T) -> dT (difference mode, recursive) — the reference's
    ``training_direct.py`` pipeline in native form."""
    import pandas as pd

    rng = np.random.default_rng(seed)
    T, rows = 296.0, []
    for k in range(400):
        mDot = float(rng.uniform(0.0, 0.05))
        rows.append((k * DT, mDot, T))
        T = plant_step(T, mDot, load)
    df = pd.DataFrame(rows, columns=["t", "mDot", "T"]).set_index("t")

    inputs = {"mDot": Feature(name="mDot", lag=1)}
    outputs = {"T": OutputFeature(name="T", output_type="difference",
                                  recursive=True)}
    X, y = create_lagged_features(resample(df, DT, method="previous"),
                                  inputs, outputs)
    data = train_val_test_split(X, y, (0.7, 0.15, 0.15), seed=seed)
    return fit_ann(data.training_inputs, data.training_outputs,
                   data.validation_inputs, data.validation_outputs,
                   dt=DT, inputs=inputs, output=outputs,
                   trainer=ANNTrainerCore(hidden=(16, 16), epochs=epochs,
                                          learning_rate=3e-3))


class ZoneSurrogate(MLModel):
    """Zone with learned dynamics: ``T`` comes from the ANN surrogate; the
    comfort constraint and objective stay declarative white-box parts
    (hybrid model, reference ``models/casadi_ml_model.py``)."""

    inputs = [
        control_input("mDot", 0.02, lb=0.0, ub=0.05, unit="m^3/s"),
        control_input("T_upper", UB),
    ]
    states = [
        state("T", 296.0, lb=285.15, ub=310.15),
        state("T_slack", 0.0),
    ]
    parameters = [parameter("s_T", 1.0)]
    dt = DT

    def setup(self, v):
        eq = ModelEquations()
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = SubObjective(v.T_slack ** 2, weight=v.s_T,
                                    name="comfort")
        return eq


class ThreePortAHU(Model):
    """Physical AHU with three outlets and one shared capacity constraint
    (the example-local model, like the reference's ``models/rlt_model.py``)."""

    inputs = [
        control_input(f"mDot_{i}", 0.02, lb=0.0, ub=0.05, unit="m^3/s")
        for i in range(1, N_ZONES + 1)
    ]
    parameters = [
        parameter("mDot_max", MDOT_MAX),
        parameter("r_mDot", 1.0),
    ]
    outputs = [output(f"mDot_out_{i}", 0.02, unit="m^3/s")
               for i in range(1, N_ZONES + 1)]

    def setup(self, v):
        eq = ModelEquations()
        total = v.mDot_1 + v.mDot_2 + v.mDot_3
        for i in range(1, N_ZONES + 1):
            eq.alg(f"mDot_out_{i}", getattr(v, f"mDot_{i}"))
        eq.constraint(0.0, total, v.mDot_max)
        eq.objective = SubObjective(total, weight=v.r_mDot, name="flow_costs")
        return eq


def agent_configs(surrogates, max_iterations: int = 10,
                  penalty_factor: float = 20.0):
    zones = []
    sims = []
    for i in range(1, N_ZONES + 1):
        zones.append({
            "id": f"Zone_{i}",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "admm", "type": "admm_local",
                 "optimization_backend": {
                     "type": "jax_admm_ml",
                     "model": {"class": ZoneSurrogate,
                               "ml_model_sources": [surrogates[i - 1]]},
                     "solver": {"max_iter": 60},
                 },
                 "time_step": DT,
                 "prediction_horizon": HORIZON,
                 "max_iterations": max_iterations,
                 "penalty_factor": penalty_factor,
                 "parameters": [{"name": "s_T", "value": 1.0}],
                 "inputs": [{"name": "T_upper", "value": UB}],
                 "states": [
                     {"name": "T", "value": START_TEMP, "ub": 310.15,
                      "lb": 285.15, "alias": f"T_{i}",
                      "source": f"Simulation_{i}"},
                 ],
                 "controls": [],
                 "couplings": [
                     {"name": "mDot", "alias": f"air_{i}", "value": 0.02,
                      "ub": 0.05, "lb": 0.0},
                 ]},
            ],
        })
        sims.append({
            "id": f"Simulation_{i}",
            "modules": [
                {"module_id": "com", "type": "local_broadcast"},
                {"module_id": "simulator", "type": "simulator",
                 "model": {"class": CooledRoom,
                           "states": [{"name": "T", "value": START_TEMP}],
                           "inputs": [{"name": "load",
                                       "value": LOADS[i - 1]}]},
                 "t_sample": 60,
                 "outputs": [{"name": "T_out", "value": START_TEMP,
                              "alias": f"T_{i}"}],
                 "inputs": [{"name": "mDot", "value": 0.02,
                             "alias": f"mDot_{i}"}]},
            ],
        })

    ahu = {
        "id": "AHU",
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "admm", "type": "admm_local",
             "optimization_backend": {
                 "type": "jax_admm",
                 "model": {"class": ThreePortAHU},
                 "discretization_options": {"collocation_order": 1},
                 "solver": {"max_iter": 60},
             },
             "time_step": DT,
             "prediction_horizon": HORIZON,
             "max_iterations": max_iterations,
             "penalty_factor": penalty_factor,
             "parameters": [{"name": "r_mDot", "value": 1.0},
                            {"name": "mDot_max", "value": MDOT_MAX}],
             "controls": [
                 {"name": f"mDot_{i}", "value": 0.02, "ub": 0.05,
                  "lb": 0.0, "alias": f"mDot_{i}"}
                 for i in range(1, N_ZONES + 1)
             ],
             "couplings": [
                 {"name": f"mDot_out_{i}", "alias": f"air_{i}",
                  "value": 0.02}
                 for i in range(1, N_ZONES + 1)
             ]},
        ],
    }
    return [*zones, ahu, *sims]


def run_example(until: float = 3600.0, testing: bool = False,
                verbose: bool = True, epochs: int = 300) -> dict:
    surrogates = [train_zone_surrogate(LOADS[i], epochs=epochs, seed=i)
                  for i in range(N_ZONES)]
    mas = LocalMAS(agent_configs(surrogates), env={"rt": False})
    mas.run(until=until)
    results = mas.get_results()

    temps, flows = {}, {}
    for i in range(1, N_ZONES + 1):
        sim_df = results[f"Simulation_{i}"]["simulator"]
        temps[i] = np.asarray(sim_df["T_out"], dtype=float)
        flows[i] = np.asarray(sim_df["mDot"], dtype=float)
    total_flow = sum(flows.values())

    if verbose:
        for i in range(1, N_ZONES + 1):
            print(f"zone {i}: {temps[i][0]:.2f} K -> {temps[i][-1]:.2f} K "
                  f"(load {LOADS[i - 1]:.0f} W)")
        print(f"peak total flow {total_flow.max():.4f} "
              f"(capacity {MDOT_MAX})")

    if testing:
        mean_start = np.mean([temps[i][0] for i in range(1, N_ZONES + 1)])
        mean_end = np.mean([temps[i][-1] for i in range(1, N_ZONES + 1)])
        assert mean_end < mean_start, (
            "building must cool on average under surrogate control")
        assert float(total_flow.max()) <= MDOT_MAX * 1.10 + 1e-9
        # scarce air: the low-load zone backs off first; high-load zones may
        # tie when both saturate their share (the AHU is indifferent to the
        # split, so ties are a valid ADMM fixed point)
        assert np.mean(flows[N_ZONES]) >= np.mean(flows[1]) - 1e-6
    return results


if __name__ == "__main__":
    run_example(testing=True)
