"""Cross-process MAS: wire protocol, localhost relay, process-per-agent run.

The reference's "multi-node" test is its multiprocessing ADMM example with
real spawned processes (``tests/test_examples.py:170-186``); here the
equivalent is a two-process MAS — a data-source exciter and a simulator
plant — linked through the TCP relay, plus direct unit tests of the frame
protocol and relay.
"""

import socket
import threading

import numpy as np
import pytest

from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.variables import control_input, output, parameter, state
from agentlib_mpc_tpu.runtime.multiprocessing_mas import (
    MultiProcessingBroker,
    MultiProcessingMAS,
)
from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source
from agentlib_mpc_tpu.runtime.wire import (
    recv_frame,
    send_frame,
    var_from_wire,
    var_to_wire,
)


class TestWire:
    def test_scalar_roundtrip(self):
        var = AgentVariable(name="T", value=295.15, alias="temp",
                            shared=True,
                            source=Source(agent_id="a", module_id="m"))
        var.timestamp = 42.0
        back = var_from_wire(var_to_wire(var))
        assert back.name == "T" and back.alias == "temp"
        assert back.value == pytest.approx(295.15)
        assert back.timestamp == 42.0
        assert back.source.agent_id == "a"

    def test_numpy_payload(self):
        var = AgentVariable(name="traj", value=np.arange(3.0), shared=True)
        back = var_from_wire(var_to_wire(var))
        assert back.value == [0.0, 1.0, 2.0]

    def test_nested_dict_payload(self):
        var = AgentVariable(name="MLModel",
                            value={"coef": np.ones((1, 2)), "dt": 60.0},
                            shared=True)
        back = var_from_wire(var_to_wire(var))
        assert back.value == {"coef": [[1.0, 1.0]], "dt": 60.0}


class TestRelay:
    def test_broadcasts_to_others_not_sender(self):
        broker = MultiProcessingBroker()
        try:
            c1 = socket.create_connection((broker.host, broker.port))
            c2 = socket.create_connection((broker.host, broker.port))
            c3 = socket.create_connection((broker.host, broker.port))
            import time

            time.sleep(0.2)  # let accepts land
            send_frame(c1, b"hello")
            got2 = recv_frame(c2)
            got3 = recv_frame(c3)
            assert got2 == b"hello" and got3 == b"hello"
            c1.settimeout(0.3)
            with pytest.raises(socket.timeout):
                c1.recv(1)  # sender must not receive its own frame
        finally:
            broker.close()


# -- process-per-agent run ----------------------------------------------------

class MPPlant(Model):
    inputs = [control_input("Q", 0.0, lb=0.0, ub=500.0)]
    states = [state("T", 295.15)]
    parameters = [parameter("C", 50000.0), parameter("load", 200.0)]
    outputs = [output("T_out")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", (v.load - v.Q) / v.C)
        eq.alg("T_out", v.T)
        return eq


def force_cpu():
    """Per-process bootstrap: pin JAX to host CPU before any op (children
    of a spawn context do not inherit the parent's jax config)."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def test_two_process_mas():
    # hang protection is the run's own join_timeout below (pytest-timeout
    # is not installed, so a mark would be a silent no-op)
    source_agent = {
        "id": "Source",
        "modules": [
            {"module_id": "com", "type": "multiprocessing_broadcast"},
            {"module_id": "excite", "type": "data_source",
             "t_sample": 10,
             "data": {"Q": {0.0: 100.0, 30.0: 400.0, 60.0: 250.0}},
             "interpolation_method": "previous"},
        ],
    }
    plant_agent = {
        "id": "Plant",
        "modules": [
            {"module_id": "com", "type": "multiprocessing_broadcast"},
            {"module_id": "room", "type": "simulator",
             "model": {"class": MPPlant},
             "t_sample": 10,
             "inputs": [{"name": "Q", "alias": "Q"}],
             "outputs": [{"name": "T_out", "alias": "T"}]},
        ],
    }
    mas = MultiProcessingMAS([source_agent, plant_agent],
                             env={"rt": True, "factor": 0.02},
                             bootstrap=force_cpu)
    mas.run(until=60, join_timeout=120.0)
    results = mas.get_results()
    assert set(results) == {"Source", "Plant"}
    df = results["Plant"]["room"]
    # the plant must have integrated the excitation it received over TCP
    # (one-sample transport delay: inputs are snapshot before the yield)
    assert df["Q"].max() == pytest.approx(400.0)
    assert df["Q"][df.index >= 20.0].min() == pytest.approx(100.0)
    assert df["T_out"].std() > 0.0

def test_requires_rt():
    with pytest.raises(ValueError, match="real-time"):
        MultiProcessingMAS([], env={"rt": False})
