"""Integrator tests, incl. the adaptive stiff TR-BDF2 path.

Fidelity bar: the reference hands stiff plants to CVODES
(``agentlib_mpc/models/casadi_model.py:402-447``). The stiff test below is
one where fixed-step RK4 at the same budget visibly blows up while the
embedded-error TR-BDF2 controller matches a tight-tolerance solution.
"""

import jax
import jax.numpy as jnp
import pytest

from agentlib_mpc_tpu.ops.integrators import (
    integrate,
    integrate_adaptive,
    trbdf2_step,
)

LAM = 1.0e5


def stiff_f(x, t):
    """Prothero–Robinson: x' = λ(cos t − x) − sin t, exact x = cos t."""
    return LAM * (jnp.cos(t) - x) - jnp.sin(t)


def test_rk4_blows_up_on_stiff_problem():
    """At λh ≈ 4000 ≫ stability bound (~2.8), fixed-step RK4 diverges."""
    x0 = jnp.array([1.0])
    x_rk4 = integrate(stiff_f, x0, 0.0, 2.0, substeps=50, method="rk4")
    assert (not bool(jnp.all(jnp.isfinite(x_rk4)))
            or float(jnp.abs(x_rk4[0] - jnp.cos(2.0))) > 1.0)


def test_trbdf2_adaptive_matches_exact_on_stiff_problem():
    x0 = jnp.array([1.0])
    x_f, (acc, rej) = integrate_adaptive(stiff_f, x0, 0.0, 2.0,
                                         rtol=1e-6, atol=1e-9)
    err = float(jnp.abs(x_f[0] - jnp.cos(2.0)))
    assert err < 1e-4, f"stiff error {err}, acc={int(acc)} rej={int(rej)}"
    assert int(acc) > 0


def test_trbdf2_adaptive_is_cheap_when_smooth():
    """Step control must stretch steps on a non-stiff smooth problem."""
    f = lambda x, t: -x
    x0 = jnp.array([1.0])
    x_f, (acc, rej) = integrate_adaptive(f, x0, 0.0, 5.0,
                                         rtol=1e-6, atol=1e-9)
    assert float(jnp.abs(x_f[0] - jnp.exp(-5.0))) < 1e-4
    assert int(acc) + int(rej) < 200


def test_trbdf2_step_second_order_accuracy():
    """Single-step convergence: local error drops ~h^3 (2nd-order method)."""
    f = lambda x, t: jnp.array([x[1], -x[0]])  # harmonic oscillator
    x0 = jnp.array([1.0, 0.0])

    def one_step_err(h):
        x1, _ = trbdf2_step(f, x0, 0.0, h)
        exact = jnp.array([jnp.cos(h), -jnp.sin(h)])
        return float(jnp.linalg.norm(x1 - exact))

    e1, e2 = one_step_err(0.1), one_step_err(0.05)
    ratio = e1 / max(e2, 1e-300)
    assert 6.0 < ratio < 10.0, f"expected ~8x (h^3 local), got {ratio}"


def test_trbdf2_embedded_estimate_tracks_true_error():
    f = lambda x, t: jnp.array([x[1], -x[0]])
    x0 = jnp.array([1.0, 0.0])
    h = 0.1
    x1, est = trbdf2_step(f, x0, 0.0, h)
    true_err = jnp.linalg.norm(x1 - jnp.array([jnp.cos(h), -jnp.sin(h)]))
    est_norm = float(jnp.linalg.norm(est))
    assert 0.1 * float(true_err) < est_norm < 50.0 * float(true_err)


def test_adaptive_jit_and_vmap():
    """Shape-static: works under jit and vmap (fleet plant simulation)."""

    @jax.jit
    def roll(x0):
        return integrate_adaptive(stiff_f, x0, 0.0, 1.0,
                                  rtol=1e-5, atol=1e-8)[0]

    x0s = jnp.linspace(0.5, 1.5, 4).reshape(4, 1)
    outs = jax.vmap(roll)(x0s)
    assert outs.shape == (4, 1)
    # all trajectories collapse onto cos(t) regardless of x0 (λ huge)
    assert bool(jnp.all(jnp.abs(outs - jnp.cos(1.0)) < 1e-3))


@pytest.mark.parametrize("method", ["euler", "rk4", "implicit_midpoint",
                                    "trbdf2"])
def test_fixed_step_methods_on_linear_decay(method):
    f = lambda x, t: -x
    x0 = jnp.array([1.0])
    x_f = integrate(f, x0, 0.0, 1.0, substeps=64, method=method)
    tol = 5e-3 if method == "euler" else 1e-3   # euler is first order
    assert float(jnp.abs(x_f[0] - jnp.exp(-1.0))) < tol
