"""Stage-structured KKT factorization tests (``ops/stagewise.py``).

The fatrop-role coverage (VERDICT r5 task #2): the block-tridiagonal
stage sweep must (a) describe the transcribed KKT structure EXACTLY —
zero coupling outside the tridiagonal band for every transcription
variant, (b) reproduce the dense paths' solutions to corpus tolerances —
SciPy-certified random programs in the ``test_solver_random.py`` style
and degenerate programs in the ``test_solver_robustness.py`` style, both
through the forced ``kkt_method="stage"`` route, (c) ride the auto
routing behind the same size-aware probe pattern as the Pallas LDLᵀ, and
(d) actually deliver the sub-cubic factor cost the round-5 components
table (dense 2.0/33.4/236 ms at N=32/128/256) called the missing lever.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import minimize

from agentlib_mpc_tpu.ops import stagewise as sw
from agentlib_mpc_tpu.ops.solver import (
    KKT_PATHS,
    NLPFunctions,
    SolverOptions,
    solve_nlp,
)

OPTS = SolverOptions(tol=1e-8, max_iter=120)


def _transcribed(model_cls, controls, N=6, **kw):
    from agentlib_mpc_tpu.ops.transcription import transcribe

    return transcribe(model_cls(), controls, N=N, dt=60.0, **kw)


# --------------------------------------------------------------------------
# structure: the partition describes the real transcribed KKT exactly
# --------------------------------------------------------------------------

@pytest.mark.parametrize("method,d,fix", [
    ("collocation", 2, True),
    ("collocation", 3, False),          # the MHE configuration
    ("multiple_shooting", 1, True),
])
def test_transcribed_kkt_is_block_tridiagonal(method, d, fix):
    """Assemble the solver's exact reduced KKT matrix (Lagrangian
    Hessian + bound/slack sigmas + JhᵀΣJh, equality Jacobian border) at
    a random point with random multipliers and check that the stage
    permutation leaves NOTHING outside the tridiagonal band — the
    structural guarantee the sweep's dropped-blocks design rests on."""
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], method=method,
                       collocation_degree=d, fix_initial_state=fix)
    p = ocp.stage_partition
    theta = ocp.default_params()
    n, m_e = ocp.n_w, ocp.n_g
    assert p is not None and p.n_total == n + m_e and p.n_w == n

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=n))
    y = jnp.asarray(rng.normal(size=m_e))
    z = jnp.asarray(np.abs(rng.normal(size=ocp.n_h)))

    def lagrangian(w):
        val = ocp.nlp.f(w, theta) + y @ ocp.nlp.g(w, theta)
        if ocp.n_h:
            val = val - z @ ocp.nlp.h(w, theta)
        return val

    H = jax.hessian(lagrangian)(w)
    Jg = jax.jacrev(lambda w: ocp.nlp.g(w, theta))(w)
    W = H + jnp.diag(jnp.asarray(np.abs(rng.normal(size=n)) + 1.0))
    if ocp.n_h:
        Jh = jax.jacrev(lambda w: ocp.nlp.h(w, theta))(w)
        sigma = jnp.asarray(np.abs(rng.normal(size=ocp.n_h)) + 0.1)
        W = W + Jh.T @ (sigma[:, None] * Jh)
    K = np.asarray(jnp.block([[W, Jg.T], [Jg, -1e-8 * jnp.eye(m_e)]]))

    perm = np.asarray(p.perm)
    valid = perm >= 0
    Kp = K[np.where(valid, perm, 0)][:, np.where(valid, perm, 0)]
    Kp = Kp * (valid[:, None] & valid[None, :])
    S, ns = p.n_stages, p.block
    for i in range(S):
        for j in range(S):
            if abs(i - j) > 1:
                blk = Kp[i * ns:(i + 1) * ns, j * ns:(j + 1) * ns]
                assert np.max(np.abs(blk)) == 0.0, (i, j)

    # and the structured solve reproduces the dense one on this matrix
    rhs = jnp.asarray(rng.normal(size=p.n_total))
    x_stage = sw.solve_kkt_stage(jnp.asarray(K), rhs, p)
    x_dense = np.linalg.solve(K, np.asarray(rhs))
    np.testing.assert_allclose(np.asarray(x_stage), x_dense,
                               rtol=1e-8, atol=1e-8)


def test_synthetic_factor_solve_matches_dense_and_vmaps():
    p = sw.build_stage_partition(N=7, n_x=2, n_u=1, n_z=1, d=2,
                                 method="collocation")
    Ks, rs = zip(*(sw.synthetic_stage_kkt(p, seed=s) for s in range(4)))
    Kb, rb = jnp.asarray(np.stack(Ks)), jnp.asarray(np.stack(rs))
    xb = jax.vmap(lambda K, r: sw.solve_kkt_stage(K, r, p))(Kb, rb)
    for i in range(4):
        np.testing.assert_allclose(
            np.asarray(xb[i]), np.linalg.solve(Ks[i], rs[i]),
            rtol=1e-9, atol=1e-9)


def test_probe_certifies_and_memoizes():
    p = sw.build_stage_partition(N=3, n_x=1, n_u=1, n_z=0, d=2,
                                 method="collocation")
    assert sw.stage_method_available(p) is True
    assert sw._STAGE_PROBE[(jax.default_backend(), p)] is True
    assert sw.stage_method_available(p) is True   # cached


def test_forced_stage_without_partition_raises():
    nlp = NLPFunctions(f=lambda w, t: jnp.sum(w ** 2),
                       g=lambda w, t: jnp.zeros((0,)),
                       h=lambda w, t: jnp.zeros((0,)))
    with pytest.raises(ValueError, match="stage_partition"):
        solve_nlp(nlp, jnp.zeros(4), None, jnp.full(4, -1.0),
                  jnp.full(4, 1.0),
                  SolverOptions(kkt_method="stage"))


# --------------------------------------------------------------------------
# end-to-end: structured and dense paths produce identical solutions
# --------------------------------------------------------------------------

def test_solver_stage_vs_dense_identical_ocp():
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5,
                       method="collocation", collocation_degree=2)
    theta = ocp.default_params(x0=jnp.array([297.5]))
    lb, ub = ocp.bounds(theta)
    out = {}
    for method in ("lu", "stage"):
        opts = SolverOptions(tol=1e-6, max_iter=60, kkt_method=method,
                             stage_partition=ocp.stage_partition)
        res = solve_nlp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                        opts)
        assert bool(res.stats.success)
        assert KKT_PATHS[int(res.stats.kkt_path)] == method
        out[method] = res
    np.testing.assert_allclose(np.asarray(out["stage"].w),
                               np.asarray(out["lu"].w), atol=1e-8)
    assert abs(float(out["stage"].stats.objective)
               - float(out["lu"].stats.objective)) < 1e-8


def test_qp_fast_path_stage_vs_dense():
    """ops/qp.py first (ISSUE): the Mehrotra QP IPM factors the same
    stage-banded KKT form, so the sweep drops in unchanged."""
    from agentlib_mpc_tpu.models.zoo import LinearRCZone
    from agentlib_mpc_tpu.ops.qp import is_lq, solve_qp

    ocp = _transcribed(LinearRCZone, ["Q"], N=6,
                       method="collocation", collocation_degree=2)
    theta = ocp.default_params()
    lb, ub = ocp.bounds(theta)
    assert is_lq(ocp.nlp, theta, ocp.n_w)
    out = {}
    for method in ("lu", "stage"):
        opts = SolverOptions(tol=1e-8, max_iter=60, kkt_method=method,
                             stage_partition=ocp.stage_partition)
        res = solve_qp(ocp.nlp, ocp.initial_guess(theta), theta, lb, ub,
                       opts)
        assert bool(res.stats.success)
        assert KKT_PATHS[int(res.stats.kkt_path)] == method
        out[method] = res
    np.testing.assert_allclose(np.asarray(out["stage"].w),
                               np.asarray(out["lu"].w), atol=1e-6)


def test_auto_routing_is_size_aware():
    """Small systems stay on the dense paths (below the measured
    crossover the sweep's sequential scan loses); lowering the floor
    routes the same problem through the sweep — the same size-aware
    probe seam that picks LU/Pallas today."""
    from agentlib_mpc_tpu.models.zoo import OneRoom

    ocp = _transcribed(OneRoom, ["mDot"], N=5,
                       method="collocation", collocation_degree=2)
    theta = ocp.default_params()
    lb, ub = ocp.bounds(theta)
    w0 = ocp.initial_guess(theta)
    res = solve_nlp(ocp.nlp, w0, theta, lb, ub,
                    SolverOptions(max_iter=40, kkt_method="auto",
                                  stage_partition=ocp.stage_partition))
    assert KKT_PATHS[int(res.stats.kkt_path)] == "lu"   # 56-dim: dense
    res = solve_nlp(ocp.nlp, w0, theta, lb, ub,
                    SolverOptions(max_iter=40, kkt_method="auto",
                                  stage_partition=ocp.stage_partition,
                                  stage_min_size=0))
    assert KKT_PATHS[int(res.stats.kkt_path)] == "stage"


# --------------------------------------------------------------------------
# random stage-structured corpus, SciPy-certified (test_solver_random style)
# --------------------------------------------------------------------------

def _stage_partition_qp(S, nv, me):
    """Hand-built partition for a generic stage-structured QP: stage k
    holds vars [k·nv, (k+1)·nv) and equality rows [k·me, (k+1)·me)."""
    n = S * nv
    perm = []
    for k in range(S):
        perm += list(range(k * nv, (k + 1) * nv))
        perm += list(range(n + k * me, n + (k + 1) * me))
    return sw.StagePartition(n_stages=S, block=nv + me, n_w=n,
                             n_total=n + S * me, perm=tuple(perm))


def _random_stage_qp(rng, S, nv, me):
    """Strictly convex QP whose KKT matrix is block tridiagonal under
    ``_stage_partition_qp``: Q couples adjacent var stages, each stage's
    equality rows touch its own and the next stage's variables."""
    n = S * nv
    Q = np.zeros((n, n))
    for k in range(S):
        blk = rng.normal(size=(nv, nv))
        Q[k * nv:(k + 1) * nv, k * nv:(k + 1) * nv] = blk @ blk.T
        if k:
            off = 0.3 * rng.normal(size=(nv, nv))
            Q[k * nv:(k + 1) * nv, (k - 1) * nv:k * nv] = off
            Q[(k - 1) * nv:k * nv, k * nv:(k + 1) * nv] = off.T
    Q += n * np.eye(n)
    c = rng.normal(size=n) * 2.0
    lb = -1.0 - rng.random(n)
    ub = 1.0 + rng.random(n)
    A = np.zeros((S * me, n))
    for k in range(S):
        hi = min(k + 2, S)
        A[k * me:(k + 1) * me, k * nv:hi * nv] = rng.normal(
            size=(me, (hi - k) * nv))
    x_feas = lb + (ub - lb) * rng.random(n)
    return Q, c, lb, ub, A, A @ x_feas


def _scipy_solution(Q, c, lb, ub, Aeq, beq):
    cons = []
    if Aeq.shape[0]:
        cons.append({"type": "eq", "fun": lambda x: Aeq @ x - beq,
                     "jac": lambda x: Aeq})
    res = minimize(
        lambda x: 0.5 * x @ Q @ x + c @ x,
        jac=lambda x: Q @ x + c,
        x0=np.clip(np.zeros_like(c), lb, ub),
        bounds=list(zip(lb, ub)), constraints=cons, method="SLSQP",
        options={"maxiter": 500, "ftol": 1e-12})
    assert res.success, res.message
    return res.x


@pytest.mark.parametrize("S,nv,me", [(4, 3, 1), (6, 2, 1)])
def test_random_stage_qps_match_scipy(S, nv, me):
    rng = np.random.default_rng(S * 10 + nv)
    p = _stage_partition_qp(S, nv, me)
    for trial in range(5):
        Q, c, lb, ub, A, b = _random_stage_qp(rng, S, nv, me)
        Qj, cj = jnp.asarray(Q), jnp.asarray(c)
        Aj, bj = jnp.asarray(A), jnp.asarray(b)
        nlp = NLPFunctions(
            f=lambda w, t: 0.5 * w @ Qj @ w + cj @ w,
            g=lambda w, t: Aj @ w - bj,
            h=lambda w, t: jnp.zeros((0,)),
        )
        res = solve_nlp(nlp, jnp.zeros(S * nv), None, jnp.asarray(lb),
                        jnp.asarray(ub),
                        OPTS._replace(kkt_method="stage",
                                      stage_partition=p))
        assert bool(res.stats.success), f"trial {trial}"
        assert KKT_PATHS[int(res.stats.kkt_path)] == "stage"
        x_ref = _scipy_solution(Q, c, lb, ub, A, b)
        np.testing.assert_allclose(np.asarray(res.w), x_ref, atol=2e-5,
                                   err_msg=f"trial {trial}")


# --------------------------------------------------------------------------
# degenerate corpus (test_solver_robustness style) through the sweep
# --------------------------------------------------------------------------

def test_stage_licq_failure_duplicated_constraints():
    """The same equality row three times inside one stage: rank-deficient
    Jacobian everywhere, feasible set unchanged — the quasi-definite
    regularization must survive the BLOCK elimination exactly as it does
    the dense factorization."""
    S, nv, me = 4, 3, 3
    rng = np.random.default_rng(0)
    p = _stage_partition_qp(S, nv, me)
    n = S * nv
    Q, c, lb, ub, _A, _b = _random_stage_qp(rng, S, nv, 1)
    A = np.zeros((S * me, n))
    b = np.zeros(S * me)
    x_feas = lb + (ub - lb) * rng.random(n)
    for k in range(S):
        a = rng.normal(size=(1, nv))
        A[k * me:(k + 1) * me, k * nv:(k + 1) * nv] = np.vstack([a, a, a])
        b[k * me:(k + 1) * me] = (a @ x_feas[k * nv:(k + 1) * nv])[0]
    Qj, cj = jnp.asarray(Q), jnp.asarray(c)
    Aj, bj = jnp.asarray(A), jnp.asarray(b)
    nlp = NLPFunctions(f=lambda w, t: 0.5 * w @ Qj @ w + cj @ w,
                       g=lambda w, t: Aj @ w - bj,
                       h=lambda w, t: jnp.zeros((0,)))
    res = solve_nlp(nlp, jnp.zeros(n), None, jnp.asarray(lb),
                    jnp.asarray(ub),
                    OPTS._replace(kkt_method="stage", stage_partition=p))
    assert bool(res.stats.success)
    w = np.asarray(res.w)
    assert np.max(np.abs(A @ w - b)) < 1e-5
    grad = Q @ w + c + A.T @ np.asarray(res.y)
    assert np.max(np.abs(grad)) < 1e-4


def test_stage_weakly_active_bound():
    """Optimum exactly ON a bound with a vanishing multiplier, m_e = 0:
    exercises the K = W (no equality border) branch of the sweep."""
    S, nv = 3, 2
    n = S * nv
    p = _stage_partition_qp(S, nv, 0)
    nlp = NLPFunctions(f=lambda w, t: 0.5 * jnp.sum(w ** 2),
                       g=lambda w, t: jnp.zeros((0,)),
                       h=lambda w, t: jnp.zeros((0,)))
    lb = jnp.asarray([0.0] + [-1.0] * (n - 1))
    ub = jnp.full(n, 1.0)
    res = solve_nlp(nlp, jnp.full(n, 0.5), None, lb, ub,
                    OPTS._replace(kkt_method="stage", stage_partition=p))
    assert bool(res.stats.success)
    assert KKT_PATHS[int(res.stats.kkt_path)] == "stage"
    assert np.all(np.abs(np.asarray(res.w)) < 1e-4)


# --------------------------------------------------------------------------
# telemetry: which factor path ran, per solve
# --------------------------------------------------------------------------

def test_record_solver_stats_labels_kkt_path():
    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.ops.solver import record_solver_stats

    nlp = NLPFunctions(f=lambda w, t: jnp.sum((w - 0.3) ** 2),
                       g=lambda w, t: jnp.zeros((0,)),
                       h=lambda w, t: jnp.zeros((0,)))
    res = solve_nlp(nlp, jnp.zeros(3), None, jnp.full(3, -1.0),
                    jnp.full(3, 1.0), SolverOptions(max_iter=30))
    was = telemetry.enabled()
    telemetry.configure(enabled=True)
    try:
        telemetry.reset()
        record_solver_stats(res.stats, origin="test")
        count = telemetry.metrics().get(
            "solver_kkt_path_solves_total",
            kkt_path=KKT_PATHS[int(res.stats.kkt_path)], origin="test")
        assert count == 1.0
    finally:
        telemetry.reset()
        telemetry.configure(enabled=was)


# --------------------------------------------------------------------------
# slow tier: the measured story — sub-cubic factor growth + bench smoke
# --------------------------------------------------------------------------

def _timed_ms(fn, *args, reps=3):
    # the bench harness's shared best-of-N methodology, so this A/B
    # stays comparable with the PERF.md --ocp-ab columns
    import bench

    return bench.timed_best_ms(fn, *args, reps=reps)[0]


@pytest.mark.slow
def test_stage_factor_cost_grows_subcubically():
    """The acceptance A/B: at N=32/128/256 (the dense factor's own
    2.0/33.4/236 ms components table) the structured factor+resolve must
    grow FAR slower than the dense path's cubic blow-up, and beat it
    outright at N=256. Cubic scaling 32→256 is 512×; the sweep is
    ~linear — 60× is a generous noise margin that still rejects any
    quadratic-or-worse regression."""
    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops.solver import _factor_kkt, _resolve_kkt

    times = {}
    dense_256 = None
    for N in (32, 128, 256):
        ocp = _transcribed(OneRoom, ["mDot"], N=N,
                           method="collocation", collocation_degree=2)
        p = ocp.stage_partition
        K, rhs = sw.synthetic_stage_kkt(p, seed=0, dtype=np.float32)
        Kj, rj = jnp.asarray(K), jnp.asarray(rhs)
        stage = jax.jit(
            lambda K, r, p=p: _resolve_kkt(_factor_kkt(K, "stage", p), r))
        times[N] = _timed_ms(stage, Kj, rj)
        if N == 256:
            dense = jax.jit(
                lambda K, r: _resolve_kkt(_factor_kkt(K, "lu"), r))
            dense_256 = _timed_ms(dense, Kj, rj)
            np.testing.assert_allclose(np.asarray(stage(Kj, rj)),
                                       np.asarray(dense(Kj, rj)),
                                       rtol=1e-3, atol=1e-4)
    assert times[256] < 60.0 * times[32], times
    assert times[256] < dense_256, (times, dense_256)


@pytest.mark.slow
def test_bench_ocp_ab_smoke():
    """`bench.py --ocp-ab 32` through the fail-soft harness emits one
    well-formed row with agreeing solutions."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(Path(__file__).resolve().parents[1]
                             / "bench.py"), "--ocp-ab", "32"],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-800:]
    rows = [json.loads(ln) for ln in proc.stdout.splitlines()
            if ln.strip().startswith("{")]
    row = next(r for r in rows if r.get("metric") == "ocp_ab[N=32]")
    assert row["kkt_size"] == 290
    assert row["dense_factor_solve_ms"] > 0
    assert row["stage_factor_solve_ms"] > 0
    assert row["max_abs_diff"] < 1e-4
