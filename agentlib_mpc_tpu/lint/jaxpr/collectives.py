"""SPMD collective certification: prove the mesh program's schedule.

On a single host, a shard-varying branch around a ``lax.psum`` is a
wedged round the collective watchdog condemns in-process (PR 10). On a
multi-process pod the same bug changes failure class: shards that
disagree about whether — or how often — to enter a collective leave
every process blocked inside a different all-reduce, and **no single
process can observe the hang**. The only safe place to catch it is
before dispatch, statically, in the jaxpr.

This is the fourth certifier pass on the PR 5 interpreter stack: a
**replication lattice** over the ``shard_map`` body —

* every value's payload is the SET of mesh axes it may vary over:
  ``REPLICATED`` (the empty set) means provably identical on every
  shard of the mesh; a non-empty set names the axes along which shards
  may disagree (``VARYING`` is the conservative top). Per-axis
  precision is what makes a TWO-axis mesh provable: a ``psum`` over
  ``"agents"`` re-replicates along agents while the value still varies
  over ``"scenarios"``, and the follow-up ``psum`` over
  ``"scenarios"`` closes the set — the scenario fleet's nested
  residual reduction (ISSUE 12) proves instead of refuting;
* seeded by the ``shard_map`` in-specs (an input starts varying over
  exactly the axes its spec shards it over — sharded over a subset of
  a 2-D mesh means replicated along the rest);
* every non-collective primitive is a *pure shard-local function of its
  inputs* (the jaxpr has no other communication channel), so one
  generic join rule is sound for all of them: any ``VARYING`` input
  taints the output;
* collective outputs **rejoin**: a ``psum``/``pmean``/``all_gather``
  result is by construction identical on every shard of the reduced
  axes, so those axes leave the varying set — the re-replication that
  makes "psum then branch on the residual" provable;
* ``scan``/``while`` run their bodies to a payload fixpoint, ``cond``
  joins branches (the shared-interpreter recursion pattern,
  :mod:`.interp`).

The walk produces a :class:`CollectiveCertificate`: the **ordered
schedule** of collectives (primitive, axis names, payload
shape/dtype/bytes, loop position) plus a proof that every collective
sits on **shard-uniform control flow** — every ``while_loop`` predicate
and ``cond`` index dominating a collective derives from ``REPLICATED``
values. A shard-varying predicate over a collective is a *refutation*
naming the offending equation (the PR 5 loud-refutation pattern); a
replicated out-spec claimed over a shard-varying value (the
``check_rep=False`` blind spot — e.g. a consensus mean whose
``axis_name`` was dropped) refutes too. ``pure_callback`` and friends
are never executed and degrade the verdict to an honest ``"unknown"``.

Consumers (the mesh seams):

* :meth:`FusedADMM._compile_step` certifies the fused round at build
  time — a refuted schedule refuses to dispatch on a multi-process
  mesh and warns loudly on a single host;
* the schedule digest (mesh-size independent for the fused round: the
  psum payloads are post-reduction shapes) joins the engine-store
  manifest and the plane-checkpoint topology stamp, and
  :class:`~agentlib_mpc_tpu.parallel.survival.FleetSupervisor` asserts
  degraded-mesh rebuilds issue the **identical** schedule — a rebuild
  that would issue a different all-reduce sequence than its surviving
  peers is exactly the pod-hang refused here;
* ``python -m agentlib_mpc_tpu.lint --jaxpr`` pins the fused round's
  schedule against ``[jaxpr.collectives]`` in ``lint_budgets.toml``
  (one psum family per ADMM iteration, nothing deeper).
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging

import numpy as np

from agentlib_mpc_tpu.lint.jaxpr.interp import (
    CALLBACK_PRIMS,
    COLLECTIVE_PRIMS,
    collective_axes,
)

logger = logging.getLogger(__name__)

__all__ = [
    "CollectiveCertificate",
    "CollectiveOp",
    "REPLICATED",
    "VARYING",
    "certify_collectives",
    "check_collective_budget",
    "collectives_gate_summary",
]

#: the replication lattice: a payload is the frozenset of mesh axis
#: names a value may VARY over. ``REPLICATED`` (empty) = provably
#: identical on every shard; ``VARYING`` is the conservative top — the
#: ``"*"`` sentinel ("varies over axes the walker cannot name") that
#: only a full-mesh-coverage collective can clear. Joins are unions;
#: ordering is set inclusion.
REPLICATED = frozenset()
VARYING = frozenset({"*"})


def _join(args) -> frozenset:
    out = REPLICATED
    for a in args:
        out = out | a
    return out

#: call-like primitives whose single sub-jaxpr is inlined transparently
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
    "remat2": "jaxpr",
}


def _source_of(eqn) -> str:
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:  # noqa: BLE001 — diagnostics only
        return "<unknown>"


def _as_jaxpr(obj):
    """(jaxpr, consts) from a ClosedJaxpr or an open Jaxpr param."""
    if hasattr(obj, "jaxpr"):          # ClosedJaxpr
        return obj.jaxpr, list(obj.consts)
    return obj, []


def _contains_collective(obj, _seen=None) -> bool:
    """Syntactic scan: does this (Closed)Jaxpr bind any collective or
    callback primitive anywhere? Used to decide whether an unknown
    primitive's sub-jaxprs can be skipped with the pure-join rule."""
    jaxpr, _ = _as_jaxpr(obj)
    _seen = set() if _seen is None else _seen
    if id(jaxpr) in _seen:
        return False
    _seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS or name in CALLBACK_PRIMS \
                or name == "axis_index":
            return True
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    if _contains_collective(sub, _seen):
                        return True
    return False


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One scheduled collective: what crosses the mesh, where, how often.

    ``loop_path`` is the nesting position, outermost first — e.g.
    ``("while",)`` for the fused round's per-iteration consensus psums,
    ``("while", "while")`` for a (forbidden) collective inside the inner
    solver loop, ``("scan[8]",)`` under a static-length scan.
    ``multiplicity`` multiplies the static scan lengths on the path;
    ``bounded`` is False when a ``while`` frame makes the trip count
    data-dependent (``trips="unbounded"`` — budget it at the caller,
    e.g. with the ADMM ``max_iterations``)."""

    primitive: str
    axes: tuple
    shapes: tuple            # one entry per operand, shard-local
    dtypes: tuple
    bytes_payload: int       # sum over operands, one issue
    loop_path: tuple
    multiplicity: int        # product of static scan lengths on the path
    bounded: bool            # False when a while frame is on the path
    source: str = ""

    @property
    def family(self) -> str:
        """The schedule-identity family key: loop depth + primitive +
        axis names (the grouping XLA can fuse into one all-reduce
        phase; payload shapes ride in the digest, not the family)."""
        return f"{len(self.loop_path)}:{self.primitive}@" \
               f"{','.join(self.axes)}"

    def describe(self) -> str:
        loop = "/".join(self.loop_path) or "top"
        return (f"{self.primitive}@{','.join(self.axes)} "
                f"{'x'.join(str(s) for s in self.shapes) or '()'} "
                f"[{loop}] ({self.source})")


@dataclasses.dataclass(frozen=True)
class CollectiveCertificate:
    """Outcome of :func:`certify_collectives`.

    ``status``:

    * ``"proved"`` — every collective sits on shard-uniform control
      flow and every replicated out-spec covers a provably replicated
      value; the ``schedule`` is the program's collective schedule;
    * ``"refuted"`` — a divergence hazard exists; ``refutations`` name
      each offending equation (dispatching this program on a
      multi-process mesh risks a silent cross-host hang);
    * ``"unknown"`` — an opaque primitive (``pure_callback`` & friends,
      never executed) blocks the proof.
    """

    status: str
    schedule: tuple = ()            # ordered CollectiveOp entries
    refutations: tuple = ()
    opaque: tuple = ()
    notes: tuple = ()
    axis_sizes: "dict | None" = None   # axis name -> mesh size

    @property
    def proved(self) -> bool:
        return self.status == "proved"

    @property
    def schedule_digest(self) -> "str | None":
        """Mesh-size-independent identity of the collective schedule:
        primitive, axes (names, not sizes), operand shapes/dtypes and
        loop position per entry, in program order. Two engines with
        equal digests issue the same collective sequence — the
        degraded-rebuild / cross-process-restore compatibility check.
        None unless proved (an unproved schedule is not an identity)."""
        if self.status != "proved":
            return None
        ident = "|".join(
            f"{op.loop_path}:{op.primitive}@{op.axes}"
            f":{op.shapes}:{op.dtypes}:x{op.multiplicity}"
            f":{'b' if op.bounded else 'u'}"
            for op in self.schedule)
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    @property
    def family_digest(self) -> "str | None":
        """Lane-count-independent identity of the collective SEQUENCE:
        primitive, axis names, loop position and multiplicity per
        entry, in program order — operand shapes and dtypes excluded.
        A degraded-mesh rebuild that re-pads its lane rows legitimately
        changes shard-local payload shapes (the ISSUE 14 agents-axis
        case: the non-anticipativity psum carries local agent rows)
        while issuing the exact same all-reduce sequence; this digest
        is the identity that survives that, and it still changes the
        moment a collective is added, dropped, reordered or moved to a
        different axis or loop depth. None unless proved."""
        if self.status != "proved":
            return None
        ident = "|".join(
            f"{op.loop_path}:{op.primitive}@{op.axes}"
            f":x{op.multiplicity}:{'b' if op.bounded else 'u'}"
            for op in self.schedule)
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def families(self) -> "dict[str, list]":
        """Schedule grouped by :attr:`CollectiveOp.family`, order kept."""
        out: "dict[str, list]" = {}
        for op in self.schedule:
            out.setdefault(op.family, []).append(op)
        return out

    def comm_bytes(self, while_trips: int = 1) -> int:
        """Modeled bytes moved across the mesh per execution: payload ×
        axis size × loop trips, with every unbounded ``while`` frame on
        a path charged ``while_trips`` (pass the loop's real budget,
        e.g. the ADMM ``max_iterations`` — the cost model's
        ``trips="unbounded"`` contract)."""
        sizes = self.axis_sizes or {}
        total = 0
        for op in self.schedule:
            axis_factor = 1
            for a in op.axes:
                axis_factor *= int(sizes.get(a, 1))
            trips = op.multiplicity
            if not op.bounded:
                n_while = sum(1 for f in op.loop_path if f == "while")
                trips *= max(int(while_trips), 1) ** max(n_while, 1)
            total += op.bytes_payload * axis_factor * trips
        return int(total)

    def describe(self) -> str:
        if self.status == "proved":
            fams = self.families()
            return (f"proved: {len(self.schedule)} collective(s) in "
                    f"{len(fams)} family(ies) "
                    f"[{'; '.join(sorted(fams))}]")
        if self.status == "refuted":
            head = "; ".join(self.refutations[:2])
            more = (f" (+{len(self.refutations) - 2} more)"
                    if len(self.refutations) > 2 else "")
            return f"REFUTED: {head}{more}"
        return ("unknown: opaque primitive(s) "
                f"{','.join(sorted(set(self.opaque)))} block the proof")

    def as_dict(self) -> dict:
        return {
            "status": self.status,
            "schedule": [op.describe() for op in self.schedule],
            "families": {k: len(v) for k, v in self.families().items()},
            "digest": self.schedule_digest,
            "refutations": list(self.refutations),
            "opaque": sorted(set(self.opaque)),
            "notes": list(self.notes),
            "axis_sizes": dict(self.axis_sizes or {}),
        }


class _Frame:
    """One enclosing control-flow construct on the walker's stack."""

    __slots__ = ("kind", "varying_pred", "trips", "source")

    def __init__(self, kind, varying_pred, trips, source):
        self.kind = kind                  # "while" | "scan" | "cond"
        self.varying_pred = varying_pred  # predicate shard-varying?
        self.trips = trips                # static length, or None (while)
        self.source = source


class _Walker:
    """Per-axis replication lattice over a (Closed)Jaxpr.

    One frozenset payload per value — the mesh axes it may vary over —
    because replication is a whole-value property here: the fused
    round's predicates are scalars and its collectives reduce whole
    arrays. (Element-level precision, the shared interpreter's
    strength, buys nothing on this lattice and would cost the walk its
    speed — the fused round is ~2k equations walked multiple times per
    fixpoint.) Axis granularity, by contrast, is load-bearing: the 2-D
    (agents × scenarios) fused round closes its residuals with one
    psum per axis, and only a lattice that can say "still varies over
    scenarios" can follow the first psum without giving up.
    """

    def __init__(self, allowed_axes=None):
        self.schedule: list = []
        self.refutations: list = []
        self.opaque: list = []
        self.notes: list = []
        self.axis_sizes: dict = {}
        self.allowed_axes = (None if allowed_axes is None
                             else tuple(allowed_axes))
        self.frames: "list[_Frame]" = []
        self.recording = True
        self._inside_shard_map = False
        #: axis names of the ENCLOSING shard_map's mesh — a collective
        #: rejoins REPLICATED only when its named axes cover ALL of
        #: them (a psum over a subset of a 2-D mesh's axes still
        #: varies over the remaining axes)
        self._mesh_axes: "tuple | None" = None
        #: per-walk memo for the syntactic sub-jaxpr collective scan
        #: (fixpoint passes revisit the same equations several times)
        self._contains_memo: "dict[int, bool]" = {}

    # -- helpers --------------------------------------------------------------

    def _note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)

    def _loop_path(self) -> tuple:
        out = []
        for f in self.frames:
            out.append(f.kind if f.trips is None
                       else f"{f.kind}[{f.trips}]")
        return tuple(out)

    def _varying_all(self) -> frozenset:
        """The local top: varies over every axis of the enclosing mesh
        (plus the ``"*"`` sentinel outside any shard_map, where the
        axes are unknowable)."""
        if self._mesh_axes:
            return frozenset(self._mesh_axes)
        return VARYING

    def _record_collective(self, eqn, in_join: frozenset) -> frozenset:
        """Handle one collective eqn: uniformity check, schedule entry,
        output payload. ``in_join`` is the join of the operand payloads
        — the output when the collective does NOT re-replicate (a
        collective of provably replicated operands stays replicated
        even without rejoining)."""
        name = eqn.primitive.name
        axes = collective_axes(eqn)
        src = _source_of(eqn)
        if self.recording:
            for f in self.frames:
                if f.varying_pred:
                    self.refutations.append(
                        f"collective {name}@{','.join(axes)} at {src} is "
                        f"dominated by a SHARD-VARYING {f.kind} "
                        f"predicate ({f.source}): shards would disagree "
                        f"about entering the collective — a silent "
                        f"cross-host hang on a multi-process mesh")
                    break
            if self.allowed_axes is not None:
                bad = [a for a in axes if a not in self.allowed_axes]
                if bad:
                    self.refutations.append(
                        f"collective {name} at {src} communicates over "
                        f"unexpected axis(es) {bad} (mesh axes: "
                        f"{list(self.allowed_axes)})")
            if axes:            # positional-axis psums are shard-local
                shapes, dtypes, nbytes = [], [], 0
                for v in eqn.invars:
                    aval = getattr(v, "aval", None)
                    if aval is None or not hasattr(aval, "shape"):
                        continue
                    shapes.append(tuple(aval.shape))
                    dtypes.append(str(aval.dtype))
                    nbytes += int(np.prod(aval.shape, dtype=np.int64)
                                  ) * aval.dtype.itemsize
                mult = 1
                bounded = True
                for f in self.frames:
                    if f.trips is None:
                        bounded = False
                    else:
                        mult *= int(f.trips)
                self.schedule.append(CollectiveOp(
                    primitive=name, axes=axes, shapes=tuple(shapes),
                    dtypes=tuple(dtypes), bytes_payload=nbytes,
                    loop_path=self._loop_path(), multiplicity=mult,
                    bounded=bounded, source=src))
        if not COLLECTIVE_PRIMS[name][1]:
            # non-rejoining collective (ppermute/all_to_all/…): even a
            # replicated operand can come out shard-varying (all_to_all
            # hands each shard a DIFFERENT slice) — stay conservative
            return self._varying_all()
        if eqn.params.get("axis_index_groups") is not None:
            # a grouped all-reduce replicates only WITHIN each group —
            # across the reduced axes the result still varies by group
            if self.recording:
                self._note(f"{name} with axis_index_groups at {src}: "
                           f"replicated only within each group")
            return in_join | frozenset(axes) if in_join else REPLICATED
        mesh_axes = self._mesh_axes or ()
        if mesh_axes and set(axes) >= set(mesh_axes):
            # full mesh coverage re-replicates unconditionally — even a
            # payload carrying the "*" sentinel is summed across every
            # shard there is
            return REPLICATED
        out = in_join - frozenset(axes)
        if out and self.recording:
            # re-replicated along the reduced axes only; the per-axis
            # lattice carries the remainder exactly (the 2-D fused
            # round's first residual psum lands here, and the second —
            # over the remaining axis — closes the set)
            self._note(
                f"{name}@{','.join(axes)} at {src} reduces over a "
                f"subset of the mesh axes {list(mesh_axes)}: the "
                f"result still varies over {sorted(out)}")
        return out

    # -- the walk -------------------------------------------------------------

    def run(self, obj, in_payloads: "list[frozenset]") -> "list[frozenset]":
        jaxpr, consts = _as_jaxpr(obj)
        env: dict = {}
        for var, _c in zip(jaxpr.constvars, consts):
            env[var] = REPLICATED
        if len(jaxpr.invars) != len(in_payloads):
            raise ValueError(
                f"jaxpr expects {len(jaxpr.invars)} inputs, got "
                f"{len(in_payloads)}")
        for var, p in zip(jaxpr.invars, in_payloads):
            env[var] = p

        def read(v) -> frozenset:
            if type(v).__name__ == "Literal":
                return REPLICATED
            return env.get(v, REPLICATED)

        for eqn in jaxpr.eqns:
            args = [read(v) for v in eqn.invars]
            outs = self.eqn(eqn, args)
            for var, p in zip(eqn.outvars, outs):
                env[var] = p
        return [read(v) for v in jaxpr.outvars]

    def eqn(self, eqn, args: "list[frozenset]") -> "list[frozenset]":
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name == "shard_map":
            return self._shard_map(eqn, args)
        if name in COLLECTIVE_PRIMS:
            if not collective_axes(eqn):
                # purely positional axes (a vmapped reduction): no
                # cross-shard traffic — an ordinary pure reduction
                p = _join(args)
            else:
                p = self._record_collective(eqn, _join(args))
            return [p] * n_out
        if name == "axis_index":
            # each shard sees its own index along the named axis:
            # varying there by definition, but no data crosses the
            # mesh — not a schedule entry
            ax = eqn.params.get("axis_name", ())
            if not isinstance(ax, (tuple, list)):
                ax = (ax,)
            named = frozenset(a for a in ax if isinstance(a, str))
            return [named or self._varying_all()] * n_out
        if name in CALLBACK_PRIMS:
            # never executed; the host function is outside the proof
            if self.recording:
                self.opaque.append(name)
            return [self._varying_all()] * n_out
        if name in _CALL_PRIMS:
            sub = eqn.params.get(_CALL_PRIMS[name])
            sub_jaxpr, _ = _as_jaxpr(sub)
            if sub is not None and len(sub_jaxpr.invars) == len(args):
                return self.run(sub, args)
            # arity mismatch (wrapper consts): conservative fallthrough
        if name == "scan":
            return self._scan(eqn, args)
        if name == "while":
            return self._while(eqn, args)
        if name == "cond":
            return self._cond(eqn, args)

        # generic rule: every remaining primitive is a pure shard-local
        # function of its inputs — join. Sub-jaxprs (custom_linear_solve
        # etc.) are covered by the same argument UNLESS they hide a
        # collective, which the syntactic scan rules out.
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                if not (hasattr(sub, "eqns") or hasattr(sub, "jaxpr")):
                    continue
                hides = self._contains_memo.get(id(sub))
                if hides is None:
                    hides = _contains_collective(sub)
                    self._contains_memo[id(sub)] = hides
                if hides:
                    if self.recording:
                        self.opaque.append(name)
                        self._note(
                            f"opaque primitive {name} at "
                            f"{_source_of(eqn)} carries a sub-jaxpr "
                            f"with collectives — schedule not provable "
                            f"through it")
                    return [self._varying_all()] * n_out
        p = _join(args)
        return [p] * n_out

    # -- composite rules ------------------------------------------------------

    def _shard_map(self, eqn, args: "list[frozenset]") -> "list[frozenset]":
        if self._inside_shard_map:
            # a nested shard_map invalidates the outer shard-local
            # view: its in-spec seeding ignores the outer payloads, so
            # walking it could launder shard-VARYING values back to
            # REPLICATED. Honest "unknown" — the region is opaque to
            # the lattice and is not walked (its collectives cannot be
            # soundly scheduled either)
            if self.recording:
                self.opaque.append("shard_map")
                self._note(
                    f"nested shard_map at {_source_of(eqn)}: inner "
                    f"region is opaque to the replication lattice — "
                    f"schedule not provable through it")
            return [self._varying_all()] * len(eqn.outvars)
        mesh = eqn.params["mesh"]
        try:
            self.axis_sizes.update(
                {str(k): int(v) for k, v in dict(mesh.shape).items()})
        except Exception:  # noqa: BLE001 — AbstractMesh variants
            pass
        if self.allowed_axes is None:
            self.allowed_axes = tuple(
                str(a) for a in getattr(mesh, "axis_names", ()))
        in_names = eqn.params["in_names"]

        def spec_axes(names) -> frozenset:
            # an in-spec shards its input over exactly the axes it
            # names; along every other mesh axis the input is
            # replicated — the per-axis seeding a 2-D mesh needs
            out: set = set()
            vals = names.values() if hasattr(names, "values") else names
            for v in vals:
                if isinstance(v, (tuple, list)):
                    out.update(str(a) for a in v)
                else:
                    out.add(str(v))
            return frozenset(out)

        seeds = [spec_axes(names) if names else REPLICATED
                 for names in in_names]
        self._inside_shard_map = True
        self._mesh_axes = tuple(
            str(a) for a in getattr(mesh, "axis_names", ()))
        try:
            outs = self.run(eqn.params["jaxpr"], seeds)
        finally:
            self._inside_shard_map = False
            self._mesh_axes = None
        out_names = eqn.params["out_names"]
        if self.recording and not eqn.params.get("check_rep", False):
            for i, (p, names) in enumerate(zip(outs, out_names)):
                if not names and p:
                    self.refutations.append(
                        f"shard_map output {i} has a REPLICATED "
                        f"out-spec but its value is shard-varying "
                        f"over {sorted(p)} ({_source_of(eqn)}) — with "
                        f"check_rep=False each shard would return a "
                        f"DIFFERENT value as 'the' result (e.g. a "
                        f"consensus mean whose axis_name was dropped)")
        # outside the shard_map the results are global values again
        return [REPLICATED] * len(eqn.outvars)

    def _fixpoint_passes(self, n_carry: int) -> int:
        """Upper bound on fixpoint passes: every non-final pass grows at
        least one carry's varying set by one axis, and each carry can
        grow at most (mesh axes + the "*" sentinel) times."""
        height = len(self._mesh_axes or ()) + 2
        return n_carry * height + 1

    def _scan(self, eqn, args: "list[frozenset]") -> "list[frozenset]":
        n_const = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        body = eqn.params["jaxpr"]
        length = int(eqn.params["length"])
        consts = args[:n_const]
        carry = list(args[n_const:n_const + n_carry])
        xs = args[n_const + n_carry:]

        was = self.recording
        self.recording = False
        try:
            # a varying axis can walk a cross-iteration carry CHAIN
            # (c[i] fed from c[i-1]) one link per pass — bound the
            # product-lattice fixpoint by carries x lattice height
            for _ in range(self._fixpoint_passes(len(carry))):
                outs = self.run(body, consts + carry + xs)
                new_carry = [c | o for c, o in
                             zip(carry, outs[:n_carry])]
                if new_carry == carry:
                    break
                carry = new_carry
        finally:
            self.recording = was
        if self.recording:
            self.frames.append(_Frame("scan", False, length,
                                      _source_of(eqn)))
            try:
                outs = self.run(body, consts + carry + xs)
            finally:
                self.frames.pop()
        return carry + list(outs[n_carry:])

    def _while(self, eqn, args: "list[frozenset]") -> "list[frozenset]":
        cn, bn = eqn.params["cond_nconsts"], eqn.params["body_nconsts"]
        cond_consts = args[:cn]
        body_consts = args[cn:cn + bn]
        carry = list(args[cn + bn:])

        was = self.recording
        self.recording = False
        try:
            # see _scan: a carry chain propagates a varying axis one
            # link per pass — same product-lattice pass bound
            for _ in range(self._fixpoint_passes(len(carry))):
                outs = self.run(eqn.params["body_jaxpr"],
                                body_consts + carry)
                new_carry = [c | o for c, o in zip(carry, outs)]
                if new_carry == carry:
                    break
                carry = new_carry
            pred = _join(self.run(eqn.params["cond_jaxpr"],
                                  cond_consts + carry))
        finally:
            self.recording = was
        varying_pred = bool(pred)
        if self.recording:
            frame = _Frame("while", varying_pred, None, _source_of(eqn))
            self.frames.append(frame)
            try:
                # the predicate runs once per trip too — its collectives
                # (if any) are part of the per-iteration schedule
                self.run(eqn.params["cond_jaxpr"], cond_consts + carry)
                self.run(eqn.params["body_jaxpr"], body_consts + carry)
            finally:
                self.frames.pop()
        if varying_pred:
            # shards along the predicate's varying axes exit at
            # different trip counts: every carried value picks those
            # axes up after the loop
            carry = [c | pred for c in carry]
        return carry

    def _cond(self, eqn, args: "list[frozenset]") -> "list[frozenset]":
        pred, ops = args[0], args[1:]
        branches = eqn.params["branches"]
        varying_pred = bool(pred)
        if self.recording:
            frame = _Frame("cond", varying_pred, 1, _source_of(eqn))
            self.frames.append(frame)
            try:
                branch_outs = [self.run(br, list(ops)) for br in branches]
            finally:
                self.frames.pop()
        else:
            branch_outs = [self.run(br, list(ops)) for br in branches]
        outs = [_join(vals) for vals in zip(*branch_outs)] \
            if branch_outs and branch_outs[0] else []
        if varying_pred:
            outs = [o | pred for o in outs]
        return outs


def certify_collectives(fn_or_jaxpr, *args,
                        allowed_axes=None) -> CollectiveCertificate:
    """Certify the collective schedule of a traced mesh program.

    ``fn_or_jaxpr``: a ``ClosedJaxpr`` (pass no ``args``) or a callable
    traced as ``jax.make_jaxpr(fn)(*args)`` — typically the
    jit-of-``shard_map`` step of a fused engine, traced on shape
    templates. ``allowed_axes`` restricts the axis names collectives may
    communicate over (defaults to the mesh axes of the first
    ``shard_map`` encountered); a collective over any other axis
    refutes.

    Never executes user code: callbacks degrade the verdict to
    ``"unknown"``, exactly like the LQ pass (``ops/qp.py`` routing
    falls back to the probe there; here the caller falls back to the
    watchdog as the only line of defense, loudly)."""
    if hasattr(fn_or_jaxpr, "jaxpr") and not args:
        closed = fn_or_jaxpr
    else:
        import jax

        closed = jax.make_jaxpr(fn_or_jaxpr)(*args)
    walker = _Walker(allowed_axes=allowed_axes)
    try:
        walker.run(closed, [REPLICATED] * len(closed.jaxpr.invars))
    except Exception as exc:  # noqa: BLE001 — certification must not
        # kill an engine build; an uninterpretable program is "unknown"
        return CollectiveCertificate(
            status="unknown",
            opaque=("interpreter-error",),
            notes=(f"interpreter error: {exc!r}",))
    if walker.refutations:
        status = "refuted"
    elif walker.opaque:
        status = "unknown"
    else:
        status = "proved"
    return CollectiveCertificate(
        status=status,
        schedule=tuple(walker.schedule),
        refutations=tuple(walker.refutations),
        opaque=tuple(walker.opaque),
        notes=tuple(walker.notes),
        axis_sizes=dict(walker.axis_sizes),
    )


def check_collective_budget(cert: CollectiveCertificate,
                            cfg: dict) -> "list[str]":
    """Compare a certificate against the ``[jaxpr.collectives]`` budget.

    Keys (all optional):

    * ``axes`` — list of axis names every collective must ride;
    * ``max_loop_depth`` — deepest loop nesting a collective may sit at
      (1 = the ADMM iteration ``while``; a psum inside the inner solver
      loop would be an all-reduce per interior-point iteration);
    * ``iteration_psums`` — exact number of ``psum`` issues inside the
      depth-1 loop: the ONE consensus family, pinned. A regression that
      slips a second all-reduce family in changes this count and fails
      the lint job naming every member of the family (the injected eqn
      among them), not a future pod run.
    * ``iteration_psum_families`` — per-axes pins for multi-family
      rounds (the 2-D scenario fleet): a dict mapping an axes key
      (axis names joined by ``","``) to that family's exact depth-1
      psum issue count. Every depth-1 psum family must be named —
      an UNBUDGETED family (an injected third axes combination) is a
      violation naming its members, exactly like a count drift.

    Returns violation strings (empty = within budget)."""
    out = []
    if not cert.proved:
        out.append(f"schedule not proved: {cert.describe()}")
        return out
    axes = cfg.get("axes")
    if axes is not None:
        allowed = set(axes if isinstance(axes, (list, tuple)) else [axes])
        for op in cert.schedule:
            bad = [a for a in op.axes if a not in allowed]
            if bad:
                out.append(f"collective over unexpected axis(es) {bad}: "
                           f"{op.describe()}")
    max_depth = cfg.get("max_loop_depth")
    if max_depth is not None:
        for op in cert.schedule:
            if len(op.loop_path) > int(max_depth):
                out.append(
                    f"collective at loop depth {len(op.loop_path)} "
                    f"(budget {max_depth}) — an all-reduce inside the "
                    f"inner loop: {op.describe()}")
    want = cfg.get("iteration_psums")
    if want is not None:
        fam = [op for op in cert.schedule
               if op.primitive == "psum" and len(op.loop_path) == 1]
        if len(fam) != int(want):
            members = "\n  ".join(op.describe() for op in fam)
            out.append(
                f"the iteration-loop psum family has {len(fam)} "
                f"issue(s), budget pins {want} — a collective was "
                f"added to (or dropped from) the fused round's "
                f"per-iteration schedule. Family members:\n  {members}")
    fams_cfg = cfg.get("iteration_psum_families")
    if fams_cfg is not None:
        by_axes: "dict[str, list]" = {}
        for op in cert.schedule:
            if op.primitive == "psum" and len(op.loop_path) == 1:
                by_axes.setdefault(",".join(op.axes), []).append(op)
        for axes_key, want_n in sorted(dict(fams_cfg).items()):
            have = by_axes.pop(axes_key, [])
            if len(have) != int(want_n):
                members = "\n  ".join(op.describe() for op in have)
                out.append(
                    f"the iteration-loop psum family over axes "
                    f"[{axes_key}] has {len(have)} issue(s), budget "
                    f"pins {want_n}. Family members:\n  {members}")
        for axes_key, ops in sorted(by_axes.items()):
            members = "\n  ".join(op.describe() for op in ops)
            out.append(
                f"UNBUDGETED iteration-loop psum family over axes "
                f"[{axes_key}] ({len(ops)} issue(s)) — a collective "
                f"family was injected into the fused round's "
                f"per-iteration schedule. Family members:\n  {members}")
    return out


def collectives_gate_summary(budgets: "dict | None" = None) -> dict:
    """The ``--jaxpr`` CLI's collectives leg: build the gate's mesh
    fleets (the tracker consensus fleet the retrace gate uses, plus one
    LQ menu fleet so the QP-routed solve body is covered), certify each
    fused round, and hold the tracker schedule to
    ``[jaxpr.collectives]``. Runs on however many devices the process
    has (a 1-device mesh still traces the full psum schedule); CI pins
    8 virtual devices. Also the ``collective_certificates`` section of
    ``bench.py --emit-metrics``."""
    import jax

    from agentlib_mpc_tpu.lint.retrace_budget import load_budgets

    cfg = (budgets if budgets is not None else load_budgets()).get(
        "jaxpr", {}).get("collectives", {})
    n_dev = len(jax.devices())
    rows = []
    failures = 0

    def one_fleet(name, build_engine, pin: bool, budget_cfg=None):
        nonlocal failures
        try:
            engine = build_engine()
            cert = engine.collective_certificate
            if cert is None:
                raise RuntimeError("engine carries no certificate")
            pin_cfg = cfg if budget_cfg is None else budget_cfg
            violations = check_collective_budget(cert, pin_cfg) if pin \
                else ([] if cert.proved else [cert.describe()])
            comm = cert.comm_bytes(
                while_trips=engine.options.max_iterations)
        except Exception as exc:  # noqa: BLE001 — report, don't crash CI
            rows.append({"name": name, "error": repr(exc)})
            failures += 1
            return
        if violations:
            failures += len(violations)
        rows.append({
            "name": name,
            "certificate": cert.as_dict(),
            "digest": cert.schedule_digest,
            "collective_bytes_per_round": comm,
            "violations": violations,
        })

    def tracker_fleet():
        from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
        from agentlib_mpc_tpu.ops.solver import SolverOptions
        from agentlib_mpc_tpu.parallel import multihost
        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
        )

        ocp = tracker_ocp()
        group = AgentGroup(
            name="collectives-gate", ocp=ocp, n_agents=max(n_dev, 2),
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=30))
        return FusedADMM([group],
                         FusedADMMOptions(max_iterations=8, rho=2.0),
                         mesh=multihost.fleet_mesh())

    def menu_fleet():
        from agentlib_mpc_tpu.lint.jaxpr.examples import build_example
        from agentlib_mpc_tpu.parallel import multihost
        from agentlib_mpc_tpu.parallel.fused_admm import (
            AgentGroup,
            FusedADMM,
            FusedADMMOptions,
        )

        ocp = build_example("LinearRCZone/colloc-d1")
        group = AgentGroup(
            name="menu-lq-fleet", ocp=ocp, n_agents=max(n_dev, 2),
            couplings={"Q_shared": "Q"})
        return FusedADMM([group],
                         FusedADMMOptions(max_iterations=8, rho=2.0),
                         mesh=multihost.fleet_mesh())

    def scenario_fleet():
        from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
        from agentlib_mpc_tpu.ops.solver import SolverOptions
        from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
        from agentlib_mpc_tpu.parallel.multihost import scenario_mesh
        from agentlib_mpc_tpu.scenario import (
            ScenarioFleet,
            ScenarioFleetOptions,
            fan_tree,
        )

        ocp = tracker_ocp()
        group = AgentGroup(
            name="scenario-gate", ocp=ocp, n_agents=max(n_dev // 2, 2),
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=30))
        return ScenarioFleet(
            group, fan_tree(4, robust_horizon=1),
            ScenarioFleetOptions(max_iterations=8, rho=2.0, rho_na=2.0),
            mesh=scenario_mesh(2))

    one_fleet("tracker-consensus-fleet", tracker_fleet, pin=True)
    one_fleet("LinearRCZone-consensus-fleet", menu_fleet, pin=False)
    # the 2-D (agents x scenarios) robust round: the second psum family
    # (ISSUE 12), pinned per axes against [jaxpr.collectives.scenario].
    # Needs a 2-D mesh — on a host without enough devices the leg is
    # SKIPPED with a note, not failed: the 1-D gates above still prove
    # their full schedules (CI pins 8 virtual devices, so the leg
    # always runs there)
    scen_cfg = dict(cfg.get("scenario", {}) or {})
    if n_dev >= 4 and n_dev % 2 == 0:
        one_fleet("tracker-scenario-fleet", scenario_fleet,
                  pin=bool(scen_cfg), budget_cfg=scen_cfg)
    else:
        rows.append({
            "name": "tracker-scenario-fleet",
            "skipped": f"needs a 2-D (agents x scenarios) mesh; "
                       f"{n_dev} device(s) visible — set XLA_FLAGS="
                       f"--xla_force_host_platform_device_count=8 "
                       f"like CI does"})
    return {"fleets": rows, "failures": failures, "devices": n_dev,
            "budget": dict(cfg)}
