"""Optimization backends (L3): transcribe + solve OCPs for the modules.

Registry pattern mirroring the reference's
``optimization_backends/__init__.py:23-64`` string→class table, minus the
import indirection. The reference ships casadi/casadi_admm/casadi_ml/...;
the JAX backend family covers the same matrix (aliases for the reference's
type strings are registered so its configs keep working).
"""

from agentlib_mpc_tpu.backends.backend import (
    OptimizationBackend,
    VariableReference,
    backend_types,
    create_backend,
    load_model,
    register_backend,
)
from agentlib_mpc_tpu.backends.mpc_backend import JAXBackend
from agentlib_mpc_tpu.backends.admm_backend import ADMMBackend
from agentlib_mpc_tpu.backends.mhe_backend import MHEBackend
from agentlib_mpc_tpu.backends.minlp_backend import CIABackend, MINLPBackend
from agentlib_mpc_tpu.backends.ml_backend import MLADMMBackend, MLBackend
