"""Backend ABC, variable reference, registry, and model loading.

Counterpart of the reference's ``optimization_backends/backend.py``
(BackendConfig :26-79, OptimizationBackend :82-218): a backend is
constructed from the module's ``optimization_backend`` config dict, is
handed a `VariableReference` describing which module variables play which
OCP role, compiles the problem once (``setup_optimization``), and then
serves repeated ``solve(now, variables)`` calls.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import logging
from typing import Any, Optional, Type

from agentlib_mpc_tpu import telemetry
from agentlib_mpc_tpu.models.model import Model
from agentlib_mpc_tpu.ops.solver import (
    init_point_source_name,
    jac_path_name,
    kkt_path_name,
)

logger = logging.getLogger(__name__)

# the shared solver metric families (declared once in telemetry)
_SOLVER_METRICS = telemetry.solver_metrics()

backend_types: dict[str, Type["OptimizationBackend"]] = {}


def load_custom_class(file: str, class_name: str):
    """Load a class from a file path — the reference's ``custom_injection``
    hook (``modules/mpc/mpc.py:120-122``). Shared by module, backend and
    model loading."""
    spec = importlib.util.spec_from_file_location(
        f"_custom_{class_name}", file)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {class_name!r} from {file!r}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return getattr(mod, class_name)


def register_backend(*names: str):
    def deco(cls):
        for n in names:
            backend_types[n] = cls
        return cls
    return deco


def create_backend(config: dict) -> "OptimizationBackend":
    type_key = config.get("type", "jax")
    if isinstance(type_key, dict):
        cls = load_custom_class(type_key["file"], type_key["class_name"])
    else:
        if type_key not in backend_types:
            raise KeyError(f"unknown backend type {type_key!r}; known: "
                           f"{sorted(backend_types)}")
        cls = backend_types[type_key]
    return cls(config)


@dataclasses.dataclass
class VariableReference:
    """Names of the module variables in each OCP role (reference
    ``data_structures/mpc_datamodels.py`` VariableReference)."""

    states: list[str] = dataclasses.field(default_factory=list)
    controls: list[str] = dataclasses.field(default_factory=list)
    inputs: list[str] = dataclasses.field(default_factory=list)
    parameters: list[str] = dataclasses.field(default_factory=list)
    outputs: list[str] = dataclasses.field(default_factory=list)
    binary_controls: list[str] = dataclasses.field(default_factory=list)

    def all_names(self) -> list[str]:
        return [*self.states, *self.controls, *self.inputs,
                *self.parameters, *self.outputs, *self.binary_controls]


def load_model(model_cfg: dict | Model, dt: float | None = None) -> Model:
    """Instantiate the model named by a config dict.

    Accepts: a Model instance; {"class": ModelClass, ...}; {"class":
    "<zoo name>"} (pure-JSON configs, e.g. container deployments, name a
    built-in model from :mod:`agentlib_mpc_tpu.models.zoo` by string); or
    the reference-style custom injection {"type": {"file": ...,
    "class_name": ...}, <group overrides>} (``casadi_backend.py`` model
    loading via agentlib custom_injection).
    Overrides: any "states"/"inputs"/"parameters"/"outputs" lists of
    {"name", "value"} entries set initial/default values.
    """
    if isinstance(model_cfg, Model):
        return model_cfg
    model_cfg = dict(model_cfg)
    cls = model_cfg.get("class")
    if isinstance(cls, str):
        from agentlib_mpc_tpu.models import zoo

        candidate = getattr(zoo, cls, None)
        if not (isinstance(candidate, type) and candidate is not Model
                and issubclass(candidate, Model)):
            raise KeyError(
                f"model class {cls!r} is not a built-in zoo model; "
                f"for custom models use {{'type': {{'file', "
                f"'class_name'}}}} injection")
        cls = candidate
    if cls is None:
        type_key = model_cfg.get("type")
        if isinstance(type_key, dict):
            cls = load_custom_class(type_key["file"], type_key["class_name"])
        else:
            raise KeyError(
                "model config needs 'class' or {'type': {'file', "
                "'class_name'}}")
    overrides: dict[str, float] = {}
    for group in ("states", "inputs", "parameters", "outputs"):
        for entry in model_cfg.get(group, []):
            if "value" in entry:
                overrides[entry["name"]] = entry["value"]
    return cls(overrides=overrides or None, dt=dt)


def load_model_for_backend(model_cfg: dict | Model,
                           dt: float | None = None) -> Model:
    """Backend-aware model loading for the owning *module*: ML model
    configs carry ``ml_model_sources`` that plain :func:`load_model` would
    silently drop (the surrogates would never register and the NARX
    transcription would see no learned states). Dispatches to the ML
    loader when the config asks for it."""
    if isinstance(model_cfg, dict) and model_cfg.get("ml_model_sources"):
        from agentlib_mpc_tpu.backends.ml_backend import load_ml_model

        return load_ml_model(model_cfg, dt=dt)
    return load_model(model_cfg, dt=dt)


class OptimizationBackend:
    """Abstract backend. Subclasses implement setup_optimization/solve."""

    def __init__(self, config: dict):
        self.config = dict(config)
        self.var_ref: Optional[VariableReference] = None
        self.model: Optional[Model] = None
        self._stats_history: list[dict] = []
        self.logger = logger

    @property
    def stats_history(self) -> list[dict]:
        """Back-compat view of the per-solve stats rows.

        Telemetry is the first-class record now (``solver_*`` metric
        families in :mod:`agentlib_mpc_tpu.telemetry`); this property keeps
        the pre-telemetry contract — a mutable list of per-solve dicts with
        the historical key schema (time, iterations, success, kkt_error,
        objective, constraint_violation, solve_wall_time) — for the module
        results writers and existing user code. ``append``/``clear`` on the
        returned list behave exactly as before.
        """
        return self._stats_history

    @staticmethod
    def solver_stats_row(stats, now, wall: float, **extra) -> dict:
        """One solve's ``stats_history`` row from a ``SolverStats`` — the
        single place the key schema lives (time, iterations, success,
        kkt_error, objective, constraint_violation, solve_wall_time,
        kkt_path, jac_path, init_point_source), so the five backends
        cannot drift. ``extra`` appends or overrides (e.g. the MINLP
        two-phase iteration sum)."""
        return {
            "time": float(now),
            "iterations": int(stats.iterations),
            "success": bool(stats.success),
            "kkt_error": float(stats.kkt_error),
            "objective": float(stats.objective),
            "constraint_violation": float(stats.constraint_violation),
            "solve_wall_time": wall,
            "kkt_path": kkt_path_name(getattr(stats, "kkt_path", -1)),
            "jac_path": jac_path_name(getattr(stats, "jac_path", -1)),
            # initial-point provenance (ISSUE 19): legacy/unlabeled
            # stats read as the plain start they are
            "init_point_source": init_point_source_name(
                getattr(stats, "init_point_source", -1)) or "plain",
            **extra,
        }

    def _record_solve(self, stats_row: dict) -> None:
        """Record one solve: stats row (back-compat history), telemetry
        metrics, and — on failure — ONE warning carrying the full stats row
        (iterations / objective / constraint violation included, not just
        the kkt error) plus a ``solver_failures_total{backend=...}``
        increment. All five backends route their ``solve()`` through here.
        """
        if getattr(self, "_suppress_record", False):
            # throwaway solves (precompile warm-up) must not pollute the
            # solver_* families: a 10+ s compile-inclusive sample would
            # dominate solver_solve_seconds and read as a runtime solve.
            # The backend.solve span still records — compile attribution
            # is exactly what a precompile solve is for.
            return
        self._stats_history.append(stats_row)
        backend = type(self).__name__
        m = _SOLVER_METRICS
        if telemetry.enabled():
            m["solves"].inc(backend=backend)
            if "iterations" in stats_row:
                m["iterations"].observe(float(stats_row["iterations"]),
                                        backend=backend)
            if "solve_wall_time" in stats_row:
                m["solve_seconds"].observe(
                    float(stats_row["solve_wall_time"]), backend=backend)
            if "kkt_error" in stats_row:
                m["kkt_error"].set(float(stats_row["kkt_error"]),
                                   backend=backend)
        if not stats_row.get("success", True):
            if telemetry.enabled():
                m["failures"].inc(backend=backend)
            self.logger.warning(
                "%s solve at t=%s did not converge; stats: %s",
                backend, stats_row.get("time"), stats_row)

    def register_logger(self, lg: logging.Logger) -> None:
        """Reference contract: the owning module injects its logger
        (``optimization_backends/backend.py:102-104``)."""
        self.logger = lg

    def health_check(self, result: dict) -> tuple[bool, tuple[str, ...]]:
        """Backend-specific validity hook for one ``solve`` result,
        merged into the actuation guard's assessment (``BaseMPC.do_step``
        passes it as ``ActuationGuard.assess(..., precheck=...)``).

        The generic checks — solver success, finite ``u0``/trajectories,
        control bounds — already run in
        :func:`agentlib_mpc_tpu.resilience.guard.check_result`; the base
        hook therefore reports healthy and subclasses override to ADD
        checks only they can make (e.g. a surrogate's trust region, an
        integer schedule's feasibility). Returns ``(healthy, reasons)``;
        every reason becomes a ``mpc_unhealthy_solves_total{reason=...}``
        label."""
        return True, ()

    def problem_fingerprint(self):
        """Structural fingerprint of the transcribed problem this
        backend solves — the admission key of the serving dispatch plane
        (``agentlib_mpc_tpu/serving/``): an agent process asks its
        backend for this and hands it to
        :meth:`~agentlib_mpc_tpu.serving.plane.ServingPlane.join`
        bucketing. Available once ``setup_optimization`` has transcribed
        the OCP (the JAX backends set ``self.ocp``); raises otherwise.
        Memoized per OCP object via the serving layer's cache."""
        ocp = getattr(self, "ocp", None)
        if ocp is None:
            raise RuntimeError(
                "problem_fingerprint() needs a transcribed OCP — call "
                "setup_optimization first (or this backend type does "
                "not expose one)")
        from agentlib_mpc_tpu.serving.fingerprint import tenant_fingerprint

        return tenant_fingerprint(ocp)

    # -- durable warm-start state (beyond reference: its warm starts die
    #    with the process, ``casadi_utils.py:94-101``) ------------------------

    def warm_state(self) -> dict:
        """Pytree snapshot of the warm-start memory every JAX backend
        keeps (primal ``w``, duals ``y``/``z``, cold flag). Save with
        :func:`agentlib_mpc_tpu.utils.checkpoint.save_pytree`; a
        restarted controller restores it via :meth:`set_warm_state` and
        its first solve runs warm instead of paying cold-start
        iterations under a real-time deadline."""
        self._require_warm_state()
        return {"w": self._w_guess, "y": self._y_guess,
                "z": self._z_guess, "cold": bool(self._cold)}

    def _require_warm_state(self) -> None:
        """Distinguish the two no-warm-state conditions: lifecycle error
        (setup_optimization not called yet) vs a backend that genuinely
        keeps no warm-start memory."""
        if hasattr(self, "_w_guess"):
            return
        if self.var_ref is None:
            raise RuntimeError(
                f"{type(self).__name__}: call setup_optimization before "
                f"using warm_state/set_warm_state")
        raise NotImplementedError(
            f"{type(self).__name__} keeps no warm-start state")

    def _carry_warm_start(self, w_next, y_next, z_next, now=None) -> None:
        """Adopt a solve's final iterate as the next warm start — unless
        it is non-finite: carrying a NaN-diverged iterate would make
        EVERY subsequent solve non-finite, so the actuation guard's
        probe mode could never observe a recovery (and a restart would
        re-checkpoint the poison). Resets to the cold start instead,
        like the fused engine's quarantine."""
        import jax.numpy as jnp

        if bool(jnp.all(jnp.isfinite(w_next))
                & jnp.all(jnp.isfinite(y_next))
                & jnp.all(jnp.isfinite(z_next))):
            self._w_guess, self._y_guess, self._z_guess = \
                w_next, y_next, z_next
            self._cold = False
        else:
            self.logger.warning(
                "solve at t=%s produced non-finite iterates; resetting "
                "warm start", now)
            self._reset_warm_start()

    def set_warm_state(self, tree: dict) -> None:
        """Restore a :meth:`warm_state` snapshot (same problem shapes)."""
        self._require_warm_state()
        for key, current in (("w", self._w_guess), ("y", self._y_guess),
                             ("z", self._z_guess)):
            new = tree[key]
            if current.shape != new.shape or current.dtype != new.dtype:
                raise ValueError(
                    f"warm state {key!r} is {new.shape}/{new.dtype}, "
                    f"this backend's problem needs "
                    f"{current.shape}/{current.dtype} — restore into a "
                    f"backend built from the same config")
        self._w_guess = tree["w"]
        self._y_guess = tree["y"]
        self._z_guess = tree["z"]
        self._cold = bool(tree["cold"])

    def setup_optimization(self, var_ref: VariableReference,
                           time_step: float, prediction_horizon: int) -> None:
        raise NotImplementedError

    def solve(self, now: float, variables: dict[str, Any]) -> dict:
        """variables: name → current value (scalar or trajectory).
        Returns a result dict with at least 'u0' (first controls, by name),
        'traj' (full trajectories), 'stats'."""
        raise NotImplementedError

    def trajectory_layout(self) -> dict[str, list[str]]:
        """Column names of the trajectories this backend's ``solve`` returns
        in ``result["traj"]`` — the contract the module's results writer
        iterates (reference result-format bookkeeping,
        ``discretization.py:398-484``). Keys: "x" (node states), "u"
        (optimized inputs incl. merged couplings), "y" (outputs), "z"
        (algebraic/slack states)."""
        from agentlib_mpc_tpu.utils.results import trajectory_layout

        ocp = getattr(self, "ocp", None)
        u = list(ocp.control_names) if ocp is not None \
            else list(self.var_ref.controls)
        return trajectory_layout(self.model, u)

    def get_lags_per_variable(self) -> dict[str, int]:
        """name → number of past samples the backend needs (NARX models;
        reference ``casadi_ml.py:388-397``). Default: none."""
        return {}
