"""telemetry-overhead tier-1 gate (ISSUE 1 satellite).

Instrumenting the 4-agent fused ADMM bench step — span + per-iteration
residual gauges + solver-iterations histogram, exactly what
``bench.py --emit-metrics`` records per step — must add <5% wall-clock
over the same compiled step with telemetry disabled (the no-op registry
fast path), and the disabled fast path itself must be structurally
zero-cost (shared no-op span, no samples written).
"""

import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu import telemetry  # noqa: E402

N_AGENTS = 4
#: the telemetry budget: all host-side instrumentation work per step must
#: stay below this fraction of the step's own wall-clock
REL_BUDGET = 0.05


@pytest.fixture(autouse=True)
def _restore_telemetry():
    yield
    telemetry.configure(enabled=True)
    telemetry.reset()


def _record_step_telemetry(stats):
    """The full --emit-metrics per-step recording load: per-iteration
    residual gauges + real per-lane solver stats."""
    from agentlib_mpc_tpu.ops.admm import record_residuals
    from agentlib_mpc_tpu.ops.solver import SolverStats, record_solver_stats

    prim, dual, iters, ok, kkt = (np.asarray(s) for s in stats)
    for k in range(prim.shape[0]):
        record_residuals(prim[k], dual[k], iteration=k, fleet="overhead")
    record_solver_stats(
        SolverStats(iterations=iters.reshape(-1),
                    kkt_error=kkt.reshape(-1),
                    success=ok.reshape(-1),
                    objective=np.zeros(iters.size),
                    mu=np.zeros(iters.size),
                    constraint_violation=np.zeros(iters.size)),
        backend="overhead")


@pytest.fixture(scope="module")
def bench_step():
    """The compiled 4-agent bench step + one measured warm wall-clock —
    shared by the instrumented leg and the journal-enabled leg (the
    compile is the expensive part)."""
    import bench

    telemetry.install_jax_hooks()
    step, args = bench.build_step(N_AGENTS, record_stats=True)
    telemetry.configure(enabled=False)
    carry, stats = step(*args)                   # compile once
    jax.block_until_ready(carry)

    # the step's own wall-clock, no-op registry (min-of-5 warm)
    step_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        carry, stats = step(args[0], args[1], *carry[:5], args[7])
        jax.block_until_ready(carry)
        step_times.append(time.perf_counter() - t0)
    telemetry.configure(enabled=True)
    return {"stats": stats, "t_step": min(step_times), "step": step,
            "args": args, "carry": carry}


def test_instrumented_bench_step_overhead_under_5_percent(bench_step):
    """The instrumentation around one warm fused step is purely additive
    host-side work (a span, the stats device→host read, ~50 registry
    writes), so the honest measurement is its standalone cost against the
    step's own wall-clock — differencing two ~250 ms step timings would
    drown the ~1 ms telemetry cost in this VM's ±8% scheduler noise and
    flake either way."""
    stats, t_step = bench_step["stats"], bench_step["t_step"]

    # worst-of-5 cost of EVERYTHING telemetry adds per instrumented step
    telemetry.configure(enabled=True)
    telemetry_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        with telemetry.span("overhead.warm_step"):
            _record_step_telemetry(stats)
        telemetry_times.append(time.perf_counter() - t0)
    t_telemetry = max(telemetry_times)

    assert t_telemetry <= REL_BUDGET * t_step, (
        f"per-step telemetry work {1e3 * t_telemetry:.2f} ms exceeds 5% of "
        f"the {1e3 * t_step:.1f} ms fused step")
    # the instrumented runs really recorded (not a no-op A/A)
    assert telemetry.metrics().get("solver_solves_total",
                                   backend="overhead") > 0
    assert telemetry.metrics().get("admm_primal_residual",
                                   fleet="overhead", iteration="0") \
        is not None


def test_journal_enabled_leg_holds_the_same_budget(bench_step, tmp_path):
    """ISSUE 15 CI satellite: the journal-ENABLED overhead leg. One
    production round's worth of flight-recorder work — the round stamp,
    a fleet.round record and a handful of fault-seam events — plus the
    full metric/span load must still fit the same <5% budget. Journal
    writes are a json.dumps + one buffered write + flush each; if this
    leg ever breaches, an emit site started doing real work per round."""
    stats, t_step = bench_step["stats"], bench_step["t_step"]

    telemetry.configure(enabled=True)
    journal = telemetry.enable_journal(str(tmp_path / "overhead.jsonl"))
    try:
        times = []
        for r in range(5):
            t0 = time.perf_counter()
            with telemetry.span("overhead.journal_step"):
                _record_step_telemetry(stats)
                telemetry.journal_set_round(r)
                telemetry.journal_event("fleet.round", degraded=False,
                                        devices=1, quarantined=0)
                telemetry.journal_event("serve.round", tally={
                    "t000": [1, 1, 0], "t001": [1, 1, 0]})
                telemetry.journal_event("health.transition",
                                        tenant="t000", state="healthy",
                                        state_from="probation")
            times.append(time.perf_counter() - t0)
        t_journal = max(times)
        assert journal.stats()["events"] == 15   # really recorded
    finally:
        telemetry.disable_journal()

    assert t_journal <= REL_BUDGET * t_step, (
        f"journal-enabled per-step telemetry work "
        f"{1e3 * t_journal:.2f} ms exceeds 5% of the "
        f"{1e3 * t_step:.1f} ms fused step")


def test_periodic_profile_capture_overhead_under_budget(bench_step):
    """ISSUE 16 CI satellite: ``ServingPlane(profile_every=K)`` budget.
    A capture round runs the SAME warm step inside ``jax.profiler.
    trace`` plus host-side trace parsing and the phase join; amortized
    over the K-1 plain rounds between captures, that excess must stay
    under the 5% budget. A capture round is genuinely expensive —
    profiler session start/stop, the xplane write-out and the event
    parse are each host-side seconds — so the budget pins the CADENCE
    at which continuous profiling is honest (K in the thousands; at
    K=25 no implementation could amortize a multi-second capture under
    5% of a ~100 ms step, and a budget that pretended otherwise would
    just be untested). As with the other legs, the honest measurement
    is the capture round's standalone excess over the step's own
    wall-clock — a 2K-round A/B difference would drown it in scheduler
    noise."""
    from agentlib_mpc_tpu.telemetry import profiler as profiler_mod

    step, args = bench_step["step"], bench_step["args"]
    t_step = bench_step["t_step"]
    state = {"carry": bench_step["carry"]}

    def run_round():
        c, _s = step(args[0], args[1], *state["carry"][:5], args[7])
        jax.block_until_ready(c)
        state["carry"] = c

    every = 2000
    cap = profiler_mod.PeriodicCapture(every, rounds=1)
    # setup, outside the measured budget: the one-time .lower() retrace
    # for the phase join, and one throwaway capture to burn jax's
    # once-per-process first-trace-session python-tracer flood
    hlo = cap.hlo_for(
        "bench", step, args[0], args[1], *state["carry"][:5], args[7])
    assert hlo is not None
    profiler_mod.capture_phase_profile(
        run_round, rounds=1, hlo_text=hlo, journal=False)

    # the non-capture path is one integer modulo, nothing else
    t0 = time.perf_counter()
    for _ in range(10_000):
        cap.due()
    assert time.perf_counter() - t0 < 0.05

    # capture-round excess over a plain warm round, amortized over K
    times = []
    for _ in range(2):
        cap._calls = 0                      # force a due round
        t0 = time.perf_counter()
        prof = cap.tick(run_round, hlo_text=hlo, label="overhead",
                        platform=jax.default_backend())
        times.append(time.perf_counter() - t0)
    excess = max(min(times) - t_step, 0.0)

    assert excess <= REL_BUDGET * every * t_step, (
        f"capture-round excess {1e3 * excess:.1f} ms exceeds the "
        f"amortized 5% budget over profile_every={every} rounds of the "
        f"{1e3 * t_step:.1f} ms fused step")
    # the captures really recorded (not a no-op A/A)
    assert cap.captures == 2
    assert prof is not None and sum(prof.op_events.values()) > 0
    assert telemetry.metrics().get(
        "phase_device_ms", phase="resolve", bucket="overhead") is not None


def test_disabled_periodic_capture_is_a_true_noop():
    """``profile_every=None`` (the default) must degrade the hook to a
    call-through: no due rounds, no profiler session, no histogram —
    the serving fast path stays byte-identical to the uninstrumented
    one."""
    from agentlib_mpc_tpu.telemetry.profiler import PeriodicCapture

    cap = PeriodicCapture(None)
    assert not cap.due()
    calls = []
    out = cap.tick(lambda: calls.append(1) or "result")
    assert out == "result" and calls == [1]
    assert cap.captures == 0 and cap.last_profile is None
    assert cap._calls == 0          # not even the modulo counter moves
    assert telemetry.metrics().get(
        "phase_device_ms", phase="resolve", bucket="-") is None


def test_disabled_fast_path_is_structurally_free():
    telemetry.configure(enabled=False)
    # spans: one shared no-op object, no allocation, no recording
    assert telemetry.span("a") is telemetry.span("b") is telemetry.NOOP_SPAN
    before = telemetry.recorder().total_recorded
    with telemetry.span("x"):
        pass
    assert telemetry.recorder().total_recorded == before
    # metrics: writes vanish
    telemetry.counter("off_total").inc()
    telemetry.gauge("off_gauge").set(1.0)
    telemetry.histogram("off_hist").observe(1.0)
    telemetry.configure(enabled=True)
    assert telemetry.metrics().get("off_total") is None
    assert telemetry.metrics().get("off_gauge") is None
    assert telemetry.metrics().get("off_hist") is None
