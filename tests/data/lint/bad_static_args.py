"""Golden-file fixture: non-hashable default on a jit static arg —
raises TypeError at dispatch, and every distinct value recompiles."""

import functools

import jax


@functools.partial(jax.jit, static_argnums=(1,))
def bad_static(x, opts=[1, 2, 3]):
    return x * len(opts)


@functools.partial(jax.jit, static_argnames=("names",))
def bad_static_names(x, names={"a": 1}):
    return x + len(names)
