"""agentlib_mpc_tpu — a TPU-native multi-agent MPC framework.

A from-scratch JAX/XLA re-design of the capabilities of RWTH-EBC/AgentLib-MPC
(reference mounted at /root/reference): declarative dynamic models with
constraints and composable objectives, OCP transcription (direct collocation
and multiple shooting), a jit-compiled interior-point NLP solver, central /
MINLP / MHE controllers, distributed MPC via consensus- and exchange-ADMM
(fused on-device collectives and broker-based), ML-surrogate (ANN/GPR/linreg
NARX) dynamics inside the optimizer, and an agent runtime with simulated and
real-time clocks.

Design principles (TPU-first, not a port):
- models are pure jax-traceable functions, not symbolic graphs
  (reference: CasADi MX, agentlib_mpc/models/casadi_model.py)
- the NLP is solved by a jit-compiled primal-dual interior-point loop
  (reference: IPOPT via casadi nlpsol, data_structures/casadi_utils.py:117-300)
- N structure-identical agents are one vmapped batch; ADMM consensus is a
  mesh collective (reference: per-agent threads + message broker,
  modules/dmpc/admm/admm.py)
- all shapes static; control flow is lax.while_loop / lax.scan.
"""

__version__ = "0.1.0"

from agentlib_mpc_tpu.models.variables import (
    Var,
    state,
    control_input,
    parameter,
    output,
)
from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu import telemetry
