"""Lightweight span tracing: ``with span("solver.solve_nlp", backend=...)``.

Answers "where did this ADMM round's 400 ms go": every instrumented region
records a :class:`SpanRecord` (name, labels, wall-clock start/duration,
nesting depth, parent) into a process-global ring-buffer
:class:`SpanRecorder`.  The ring buffer bounds memory for long-lived
controllers — old spans are evicted, aggregates survive via
:meth:`SpanRecorder.aggregate`.

Spans also carry the *compile attribution scope* for the JAX profiling
hooks (:mod:`agentlib_mpc_tpu.telemetry.jax_events`): a compile/trace event
fired while a span is active is attributed to that span's name, which is
how ``jax_compile_seconds_total{entry_point="backend.solve"}`` knows its
entry point.

Disabled mode (``telemetry.configure(enabled=False)``) makes ``span(...)``
return a shared no-op context manager — no allocation beyond the call's own
kwargs, no contextvar writes, no recording.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextvars import ContextVar

from agentlib_mpc_tpu.telemetry import registry as _registry_mod

_seq = itertools.count(1)

#: innermost active span of the current thread/context (None at top level)
_current: ContextVar["SpanRecord | None"] = ContextVar(
    "agentlib_mpc_tpu_current_span", default=None)


class SpanRecord:
    """One timed region. ``duration`` is None while the span is open."""

    __slots__ = ("name", "labels", "start", "duration", "depth", "parent",
                 "seq", "_token")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self.start = 0.0
        self.duration: "float | None" = None
        self.depth = 0
        self.parent: "str | None" = None
        self.seq = next(_seq)
        self._token = None

    # -- context-manager protocol ---------------------------------------------

    def __enter__(self) -> "SpanRecord":
        outer = _current.get()
        if outer is not None:
            self.depth = outer.depth + 1
            self.parent = outer.name
        self._token = _current.set(self)
        self.start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration = time.perf_counter() - self.start
        _current.reset(self._token)
        RECORDER.record(self)

    def as_dict(self) -> dict:
        return {"name": self.name, "labels": dict(self.labels),
                "start": self.start, "duration_s": self.duration,
                "depth": self.depth, "parent": self.parent}


class _NoopSpan:
    """Shared do-nothing span — what ``span()`` returns when telemetry is
    disabled. Identity-stable so tests can assert zero allocation."""

    __slots__ = ()
    name = None
    duration = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return None


NOOP_SPAN = _NoopSpan()


def span(name: str, **labels) -> "SpanRecord | _NoopSpan":
    """Open a timed region::

        with span("admm.fused_step", fleet="rooms") as sp:
            ...
        # sp.duration holds the wall-clock seconds after exit

    Nesting is tracked per thread/context; the record lands in the global
    ring buffer at exit. Returns a shared no-op when telemetry is disabled.
    """
    if not _registry_mod.DEFAULT._enabled:
        return NOOP_SPAN
    return SpanRecord(name, labels)


def current_span() -> "SpanRecord | None":
    """Innermost active span of this thread/context (compile attribution
    scope for the JAX hooks)."""
    return _current.get()


class SpanRecorder:
    """Fixed-capacity ring buffer of completed spans, plus running
    per-name aggregates that are NOT subject to eviction — long-lived
    controllers keep exact count/total/max per span name even after the
    individual records have been overwritten."""

    #: per-name duration samples retained for percentile estimation —
    #: bounded so long-lived controllers don't grow without limit;
    #: p50/p99 are over the most recent SAMPLE_WINDOW records per name.
    SAMPLE_WINDOW = 1024

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError("SpanRecorder capacity must be >= 1")
        self._capacity = capacity
        self._buf: list = [None] * capacity
        self._write = 0      # next slot
        self._count = 0      # total ever recorded
        self._dropped = 0    # records evicted by the ring (ISSUE 15:
        #                      the observability layer reports its own
        #                      loss instead of overflowing silently)
        self._agg: dict[str, dict] = {}
        self._samples: dict[str, list] = {}
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def total_recorded(self) -> int:
        return self._count

    @property
    def dropped(self) -> int:
        """Individual span records lost to ring-buffer eviction (the
        per-name aggregates are never dropped)."""
        return self._dropped

    def record(self, rec: SpanRecord) -> None:
        with self._lock:
            evicting = self._buf[self._write] is not None
            self._buf[self._write] = rec
            self._write = (self._write + 1) % self._capacity
            self._count += 1
            if evicting:
                self._dropped += 1
            agg = self._agg.setdefault(
                rec.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            d = rec.duration or 0.0
            agg["total_s"] += d
            agg["max_s"] = max(agg["max_s"], d)
            samples = self._samples.setdefault(rec.name, [])
            samples.append(d)
            if len(samples) > self.SAMPLE_WINDOW:
                del samples[: len(samples) - self.SAMPLE_WINDOW]
        if evicting and _registry_mod.DEFAULT._enabled:
            # outside the recorder lock (the registry has its own)
            _registry_mod.DEFAULT.counter(
                "telemetry_spans_dropped_total",
                "span records evicted from the ring buffer before "
                "export (aggregates survive; raise SpanRecorder "
                "capacity if individual records matter)").inc()

    def spans(self) -> list[SpanRecord]:
        """Retained spans, oldest first (at most ``capacity``)."""
        with self._lock:
            if self._count < self._capacity:
                return [s for s in self._buf[:self._write]]
            return (self._buf[self._write:] + self._buf[:self._write])[:]

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self._capacity
            self._write = 0
            self._count = 0
            self._dropped = 0
            self._agg = {}
            self._samples = {}

    @staticmethod
    def _quantile(sorted_samples: list, q: float) -> float:
        """Nearest-rank quantile over a pre-sorted sample list."""
        if not sorted_samples:
            return 0.0
        idx = min(len(sorted_samples) - 1,
                  max(0, int(round(q * (len(sorted_samples) - 1)))))
        return sorted_samples[idx]

    def aggregate(self) -> dict:
        """name -> {count, total_s, max_s, p50_s, p99_s} over EVERY span
        ever recorded (running totals maintained at record time, immune
        to ring-buffer eviction) — the per-phase wall-clock breakdown
        ``bench.py --emit-metrics`` emits. count/total_s/max_s cover the
        full history; p50_s/p99_s are nearest-rank estimates over the
        most recent ``SAMPLE_WINDOW`` durations per name. When ring
        eviction has dropped individual records, a reserved
        ``"_dropped_spans"`` row (same shape) reports the loss — the
        observability layer accounts for its own blind spots."""
        with self._lock:
            out = {}
            for name, agg in self._agg.items():
                row = dict(agg)
                srt = sorted(self._samples.get(name, ()))
                row["p50_s"] = self._quantile(srt, 0.50)
                row["p99_s"] = self._quantile(srt, 0.99)
                out[name] = row
            if self._dropped:
                out["_dropped_spans"] = {"count": self._dropped,
                                         "total_s": 0.0, "max_s": 0.0,
                                         "p50_s": 0.0, "p99_s": 0.0}
            return out


#: the process-global recorder `span()` writes into
RECORDER = SpanRecorder()
