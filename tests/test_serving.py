"""Serving dispatch plane: fingerprints, cache, slots, admission, churn.

The tentpole contracts (ISSUE 7 / docs/serving.md):

* structural fingerprints are deterministic, equal across separately
  transcribed identical OCPs, distinct across different models;
* tenant join/leave flips traced masks inside padded slots — results
  match an unpadded fleet, and membership churn never retraces
  (the ``[serving]`` budget gate, run here as a test);
* a structurally-identical rejoining tenant is a compile-cache hit;
* the admission queue sheds on overload/deadline into the PR 2
  degradation ladder (replay → hold → fallback);
* pipelined dispatch delivers the same results as the synchronous loop,
  one round later.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
from agentlib_mpc_tpu.ops.solver import SolverOptions
from agentlib_mpc_tpu.ops.transcription import transcribe
from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
    stack_params,
)
from agentlib_mpc_tpu.resilience.guard import DegradationPolicy
from agentlib_mpc_tpu.serving import ServingPlane, TenantSpec

ADMM_OPTS = FusedADMMOptions(max_iterations=6, rho=2.0)
SOLVER_OPTS = SolverOptions(max_iter=30)


@pytest.fixture(scope="module")
def ocp():
    return tracker_ocp()


def make_spec(ocp, tid, a, **kw):
    return TenantSpec(
        tenant_id=tid, ocp=ocp,
        theta=ocp.default_params(p=jnp.array([float(a)])),
        couplings={"shared_u": "u"},
        solver_options=SOLVER_OPTS, **kw)


@pytest.fixture(scope="module")
def plane(ocp):
    """One shared pipelined+donated plane (module-scoped: the cold
    engine build is the expensive part; tests restore membership)."""
    return ServingPlane(ADMM_OPTS, slot_multiple=1, initial_capacity=4,
                        pipelined=True, donate=True)


class TestFingerprint:
    def test_deterministic_and_structural(self, ocp):
        from agentlib_mpc_tpu.lint.jaxpr import structural_fingerprint

        fp1 = structural_fingerprint(ocp.nlp, ocp.default_params(),
                                     ocp.n_w, ocp.stage_partition)
        fp2 = structural_fingerprint(ocp.nlp, ocp.default_params(),
                                     ocp.n_w, ocp.stage_partition)
        assert fp1 == fp2 and fp1.digest == fp2.digest
        # a separately transcribed, structurally identical OCP
        # fingerprints EQUAL — the rejoin-across-retranscription case
        ocp_b = tracker_ocp()
        assert ocp_b is not ocp
        fp3 = structural_fingerprint(ocp_b.nlp, ocp_b.default_params(),
                                     ocp_b.n_w, ocp_b.stage_partition)
        assert fp3 == fp1
        # a different structure (longer horizon) fingerprints apart
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        other = transcribe(LinearRCZone(), ["Q"], N=4, dt=300.0,
                           method="multiple_shooting")
        fp4 = structural_fingerprint(other.nlp, other.default_params(),
                                     other.n_w, other.stage_partition)
        assert fp4 != fp1

    def test_bucket_key_separates_solver_config(self, ocp):
        from agentlib_mpc_tpu.serving import bucket_key

        a = bucket_key(make_spec(ocp, "x", 1.0))
        b = bucket_key(make_spec(ocp, "y", 2.0))
        assert a == b          # theta differs, structure doesn't
        c = bucket_key(TenantSpec(
            tenant_id="z", ocp=ocp,
            theta=ocp.default_params(),
            couplings={"shared_u": "u"},
            solver_options=SolverOptions(max_iter=50)))
        assert c != a          # solver options shape the executable


class TestJoinServeLeave:
    def test_lifecycle_and_cache(self, plane, ocp):
        r1 = plane.join(make_spec(ocp, "a1", 1.0))
        assert not r1.engine_cached          # first build is cold
        r2 = plane.join(make_spec(ocp, "a2", 3.0))
        assert r2.engine_cached
        assert r2.latency_s < r1.latency_s / 10
        # serve until both tenants' results arrive (pipelined: round 1
        # delivers round 0)
        for t in ("a1", "a2"):
            plane.submit(t)
        plane.serve_round()
        res = plane.flush()
        assert set(res) == {"a1", "a2"}
        for r in res.values():
            assert r.action == "actuate" and r.healthy
            assert np.isfinite(r.controls["u"])
        # consensus across the two active lanes: tracker targets 1 and 3
        # coupled on one alias pull the shared control toward 2
        us = [res[t].controls["u"] for t in ("a1", "a2")]
        assert all(1.0 < u < 3.0 for u in us)
        plane.leave("a1")
        plane.leave("a2")
        assert plane.tenants == ()

    def test_rejoin_after_retirement_is_cache_hit(self, plane, ocp):
        hits0 = plane.cache.hits
        rec = plane.join(make_spec(ocp, "a1", 2.0))
        assert rec.engine_cached and plane.cache.hits > hits0
        assert rec.latency_s < 5.0           # splice, not compile
        plane.submit("a1")
        plane.serve_round()
        res = plane.flush()
        assert res["a1"].action == "actuate"
        # an isolated tenant's consensus tracks its own target (solo
        # consensus converges linearly in lam; 6 ADMM iterations leave
        # a ~1.5% bias — the gate here is "right target", not tol)
        assert abs(res["a1"].controls["u"] - 2.0) < 0.1
        plane.leave("a1")

    def test_recycled_slot_gets_fresh_warm_start(self, plane, ocp):
        """A new tenant taking a previously-used slot must not inherit
        the old tenant's iterate: its solve converges to ITS target."""
        plane.join(make_spec(ocp, "old", -4.0))
        plane.submit("old")
        plane.serve_round()
        plane.flush()
        plane.leave("old")
        rec = plane.join(make_spec(ocp, "new", 4.0))
        assert rec.slot == 0                 # same recycled slot
        plane.submit("new")
        plane.serve_round()
        res = plane.flush()
        # a leaked warm start from the old tenant (target -4) would land
        # far below; a fresh lane tracks the new target
        assert abs(res["new"].controls["u"] - 4.0) < 0.1
        plane.leave("new")


class TestMaskedEquivalence:
    def test_padded_plus_mask_equals_unpadded_fleet(self, ocp):
        """The dynamic-mask contract: a 4-slot engine with 2 active
        lanes must reproduce the 2-agent engine's consensus results
        (same semantics pad_group_to_devices promises statically)."""
        thetas2 = stack_params([
            ocp.default_params(p=jnp.array([1.0])),
            ocp.default_params(p=jnp.array([3.0]))])
        g2 = AgentGroup(name="ref", ocp=ocp, n_agents=2,
                        couplings={"shared_u": "u"},
                        solver_options=SOLVER_OPTS)
        ref = FusedADMM([g2], ADMM_OPTS)
        sref = ref.init_state([thetas2])
        sref, trajs_ref, _ = ref.step(sref, [thetas2])

        thetas4 = stack_params([
            ocp.default_params(p=jnp.array([a]))
            for a in (1.0, 3.0, 7.0, -7.0)])   # lanes 2/3 are padding
        g4 = AgentGroup(name="padded", ocp=ocp, n_agents=4,
                        couplings={"shared_u": "u"},
                        solver_options=SOLVER_OPTS)
        padded = FusedADMM([g4], ADMM_OPTS)
        mask = jnp.asarray([True, True, False, False])
        sp = padded.init_state([thetas4])
        sp, trajs_pad, _ = padded.step(sp, [thetas4], active=[mask])
        np.testing.assert_allclose(
            np.asarray(trajs_pad[0]["u"][:2]),
            np.asarray(trajs_ref[0]["u"]), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(sp.zbar["shared_u"]),
            np.asarray(sref.zbar["shared_u"]), atol=1e-5)

    def test_mask_flip_changes_consensus_without_retrace(self, ocp):
        """Flipping a lane between rounds is data: the consensus mean
        moves, the trace count does not."""
        from agentlib_mpc_tpu import telemetry
        from agentlib_mpc_tpu.utils.jax_setup import (
            enable_compile_profiling,
        )

        telemetry.configure(enabled=True)
        reg = enable_compile_profiling()
        thetas = stack_params([
            ocp.default_params(p=jnp.array([a])) for a in (0.0, 4.0)])
        g = AgentGroup(name="flip", ocp=ocp, n_agents=2,
                       couplings={"shared_u": "u"},
                       solver_options=SOLVER_OPTS)
        eng = FusedADMM([g], ADMM_OPTS)
        st = eng.init_state([thetas])
        st, _, _ = eng.step(st, [thetas],
                            active=[jnp.asarray([True, True])])
        zb_both = float(st.zbar["shared_u"][0])
        traces0 = reg.counter("jax_traces_total").total()
        # fresh state, lane 1 masked off: the consensus mean is lane 0's
        # own trajectory (target 0), nowhere near the two-lane mean
        st2 = eng.init_state([thetas])
        st2, _, _ = eng.step(st2, [thetas],
                             active=[jnp.asarray([True, False])])
        zb_solo = float(st2.zbar["shared_u"][0])
        assert reg.counter("jax_traces_total").total() == traces0
        assert abs(zb_solo) < 0.2            # solo lane tracks target 0
        assert zb_both > 1.5                 # both lanes: mean of 0 and 4


class TestAdmission:
    def test_overload_shed_walks_guard_ladder(self, ocp):
        sp = ServingPlane(ADMM_OPTS, slot_multiple=1, initial_capacity=2,
                          pipelined=False, donate=False, queue_limit=1,
                          guard_policy=DegradationPolicy(
                              replay_steps=1, hold_steps=1))
        sp.join(make_spec(ocp, "t1", 1.0))
        sp.join(make_spec(ocp, "t2", 2.0))
        # serve one healthy round so t2 has a stored plan to replay
        sp.submit("t1")
        sp.submit("t2")          # queue_limit=1: second submission shed
        # a never-served tenant has nothing to replay/hold -> fallback
        first = sp.submit("t2")
        assert first is not None and first.action == "fallback"
        res = sp.serve_round()
        assert res["t1"].action == "actuate"

    def test_deadline_expiry_sheds_at_drain(self, ocp):
        sp = ServingPlane(ADMM_OPTS, slot_multiple=1, initial_capacity=1,
                          pipelined=False, donate=False)
        sp.join(make_spec(ocp, "t1", 1.0))
        sp.submit("t1", deadline_s=0.5, now=0.0)
        res = sp.serve_round(now=10.0)       # way past the deadline
        assert sp.queue.shed_deadline == 1
        assert res["t1"].action in ("replay", "hold", "fallback")
        assert not res["t1"].healthy

    def test_replay_then_recovery_after_shed(self, ocp):
        """The full PR 2 wiring: healthy round stores a plan, a shed
        request replays it, the next healthy round re-engages."""
        sp = ServingPlane(ADMM_OPTS, slot_multiple=1, initial_capacity=1,
                          pipelined=False, donate=False, queue_limit=4)
        sp.join(make_spec(ocp, "t1", 2.0))
        sp.submit("t1")
        res = sp.serve_round()
        assert res["t1"].action == "actuate"
        sp.submit("t1", deadline_s=0.1, now=0.0)
        res = sp.serve_round(now=5.0)        # expired -> ladder: replay
        assert res["t1"].action == "replay"
        assert res["t1"].controls is not None
        sp.submit("t1")
        res = sp.serve_round()
        assert res["t1"].action == "actuate" and res["t1"].healthy


class TestInFlightChurn:
    def test_tenant_left_while_in_flight_is_dropped(self, plane, ocp):
        """The bare-continue branch in ``_assess_bucket``: a tenant that
        leaves between launch and materialize simply vanishes from the
        results — no KeyError, no ghost verdict — while its bucket
        peers still deliver."""
        plane.join(make_spec(ocp, "stay", 1.0))
        plane.join(make_spec(ocp, "goer", 3.0))
        plane.submit("stay")
        plane.submit("goer")
        plane.serve_round()              # pipelined: round in flight
        plane.leave("goer")              # leaves while in flight
        res = plane.flush()
        assert "goer" not in res
        assert res["stay"].action == "actuate"
        plane.leave("stay")

    def test_dispatcher_flush_with_dead_bucket_key(self):
        from agentlib_mpc_tpu.serving.dispatch import PipelinedDispatcher

        d = PipelinedDispatcher(pipelined=True)
        assert d.flush("no-such-bucket") == {}
        assert d.flush() == {}           # nothing in flight at all


class TestCacheLRU:
    def test_bounded_cache_evicts_lru_and_rejoin_is_miss(self):
        from agentlib_mpc_tpu.serving import CompileCache

        built = []

        def builder(tag):
            def build():
                built.append(tag)
                return f"engine-{tag}"
            return build

        cache = CompileCache(max_engines=2)
        cache.get_or_build("A", builder("A"), label="A")
        cache.get_or_build("B", builder("B"), label="B")
        # touch A so B is the least recently used
        _, hit, _ = cache.get_or_build("A", builder("A"), label="A")
        assert hit
        cache.get_or_build("C", builder("C"), label="C")   # evicts B
        assert cache.evictions == 1
        assert "B" not in cache and "A" in cache and "C" in cache
        # the eviction -> rejoin-is-miss contract
        _, hit, _ = cache.get_or_build("B", builder("B"), label="B")
        assert not hit
        assert built == ["A", "B", "C", "B"]
        assert len(cache) == 2            # A was evicted by B's return

    def test_unbounded_cache_never_evicts(self):
        from agentlib_mpc_tpu.serving import CompileCache

        cache = CompileCache()
        for i in range(64):
            cache.get_or_build(i, lambda i=i: i)
        assert len(cache) == 64 and cache.evictions == 0

    def test_bad_bound_rejected(self):
        from agentlib_mpc_tpu.serving import CompileCache

        with pytest.raises(ValueError, match="max_engines"):
            CompileCache(max_engines=0)

    def test_eviction_metric_counted(self):
        from agentlib_mpc_tpu import telemetry
        from agentlib_mpc_tpu.serving import CompileCache

        telemetry.configure(enabled=True)
        try:
            before = telemetry.metrics().counter(
                "serving_cache_evictions_total").total()
            cache = CompileCache(max_engines=1)
            cache.get_or_build("A", lambda: "a", label="bucketA")
            cache.get_or_build("B", lambda: "b", label="bucketB")
            after = telemetry.metrics().counter(
                "serving_cache_evictions_total").total()
            assert after - before == 1
        finally:
            telemetry.configure(enabled=False)


class TestSolvesByAction:
    def test_solves_counter_labelled_by_guard_action(self, ocp):
        """Satellite: ``serving_solves_total`` must attribute each
        delivered result to its guard action — a replayed/held round is
        not an availability, and telemetry alone must show that."""
        from agentlib_mpc_tpu import telemetry

        telemetry.configure(enabled=True)
        try:
            reg = telemetry.metrics()

            def count(action):
                return reg.get("serving_solves_total",
                               action=action) or 0.0

            sp = ServingPlane(ADMM_OPTS, slot_multiple=1,
                              initial_capacity=1, pipelined=False,
                              donate=False)
            sp.join(make_spec(ocp, "t1", 2.0))
            a0, r0 = count("actuate"), count("replay")
            sp.submit("t1")
            sp.serve_round()                  # healthy -> actuate
            sp.submit("t1", deadline_s=0.1, now=0.0)
            sp.serve_round(now=5.0)           # expired -> ladder
            assert count("actuate") == a0 + 1
            # the deadline shed never reaches the solves counter (no
            # result was delivered), so replay stays flat ...
            assert count("replay") == r0
        finally:
            telemetry.configure(enabled=False)


class TestChurnGate:
    def test_serving_budget_gate_is_green(self):
        """The CI gate as a test: zero warm traces/compiles across the
        scripted join→serve→leave→rejoin churn, rejoin a cache hit."""
        from agentlib_mpc_tpu.lint.retrace_budget import run_serving_gate

        report = run_serving_gate(verbose=False)
        assert report["violations"] == [], report
        assert report["failures"] == [], report
        assert report["cache"]["hits"] >= 1


class TestChurnSchedule:
    def test_deterministic_with_rejoins(self):
        from agentlib_mpc_tpu.resilience.chaos import churn_schedule

        s1 = churn_schedule(7, 6, 30)
        assert s1 == churn_schedule(7, 6, 30)
        assert s1 != churn_schedule(8, 6, 30)
        joins = [t for r in s1 for kind, t in r if kind == "join"]
        assert len(joins) > len(set(joins)), "no rejoin events in 30 rounds"
        # membership consistency: never leave an absent tenant, never
        # join a present one
        active = set()
        for r in s1:
            for kind, t in r:
                if kind == "join":
                    assert t not in active
                    active.add(t)
                else:
                    assert t in active
                    active.discard(t)


@pytest.mark.slow
class TestServeBenchSmoke:
    def test_bench_serve_smoke(self):
        """``bench.py --serve`` end to end at reduced scale: the metric
        row exists, platform-qualified, with the A/B and join columns."""
        import bench

        out = bench.run_serve(seed=1, n_tenants=2, rounds=6)
        assert out["metric"].startswith("serve_solves_per_sec")
        assert out["value"] > 0
        assert out["warm_retraces"] == 0
        assert out["join_cold_ms"] is not None
        assert out["cache"]["misses"] >= 1
        assert out["round_ms_p99"] >= out["round_ms_p50"]


class TestBackendSeam:
    def test_backend_exposes_problem_fingerprint(self):
        """The backend-side half of the admission handshake: an agent
        asks its backend for the structural fingerprint the serving
        plane buckets by."""
        from agentlib_mpc_tpu.backends.backend import (
            VariableReference,
            create_backend,
        )
        from agentlib_mpc_tpu.models.zoo import LinearRCZone

        backend = create_backend({
            "type": "jax",
            "model": {"class": LinearRCZone},
            "discretization_options": {"collocation_order": 2},
        })
        with pytest.raises(RuntimeError):
            backend.problem_fingerprint()    # no OCP yet
        backend.setup_optimization(
            VariableReference(
                states=["T", "T_slack"], controls=["Q"],
                inputs=["load", "T_amb", "T_upper"],
                parameters=["C", "R", "s_T", "r_Q"]),
            time_step=300.0, prediction_horizon=4)
        fp = backend.problem_fingerprint()
        assert fp.digest
        # memoized: the same backend returns the identical object
        assert backend.problem_fingerprint() is fp


class TestAutoDispatchDefaults:
    def test_auto_resolves_sync_on_cpu(self, ocp):
        """pipelined/donate "auto" resolve by backend (the
        fused_ls_jacobian pattern): sync + undonated on CPU, where the
        measured A/B is parity-to-negative (PERF.md round 9)."""
        sp = ServingPlane(ADMM_OPTS, slot_multiple=1)
        assert sp.dispatcher.pipelined is False
        assert sp.donate is False
        sp2 = ServingPlane(ADMM_OPTS, slot_multiple=1, pipelined=True,
                           donate=True)
        assert sp2.dispatcher.pipelined is True and sp2.donate is True
