"""Container entry point tests (deploy/Dockerfile CMD surface)."""

import json

import pytest

from agentlib_mpc_tpu.runtime.container import build_mas, load_configs, main
from test_mqtt import _FakeBrokerHub, _install_fake_paho

AGENT = {
    "id": "weather",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {"module_id": "src", "type": "data_source",
         "data": {"T_amb": {0.0: 280.0, 3600.0: 290.0}},
         "t_sample": 600.0},
    ],
}


def test_load_configs_single_and_list(tmp_path):
    p1 = tmp_path / "one.json"
    p1.write_text(json.dumps(AGENT))
    assert [c["id"] for c in load_configs(p1)] == ["weather"]
    p2 = tmp_path / "two.json"
    p2.write_text(json.dumps([AGENT, {**AGENT, "id": "weather2"}]))
    assert [c["id"] for c in load_configs(p2)] == ["weather", "weather2"]


def test_build_and_run_isolated():
    mas, buses = build_mas([AGENT], realtime=False, mqtt_host="none")
    assert buses == []
    mas.run(until=1800.0)
    mod = mas.agents["weather"].get_module("src")
    # last replay tick at t=1800 -> linear interpolation of the table
    assert abs(mod.get_value("T_amb") - (280.0 + 10.0 * 1800 / 3600)) < 1e-6
    mas.terminate()


def test_build_with_mqtt_bridge(monkeypatch):
    hub = _FakeBrokerHub()
    _install_fake_paho(monkeypatch, hub)
    mas, buses = build_mas([AGENT], realtime=False,
                           mqtt_host="broker.local", mqtt_port=1884)
    assert len(buses) == 1
    assert buses[0]._client.connected == ("broker.local", 1884)
    mas.run(until=600.0)
    mas.terminate()
    for bus in buses:
        bus.close()
    assert buses[0]._client.loop_running is False


def test_main_end_to_end(tmp_path, monkeypatch):
    cfg = tmp_path / "agent.json"
    cfg.write_text(json.dumps(AGENT))
    monkeypatch.setenv("AGENT_CONFIG", str(cfg))
    monkeypatch.setenv("MQTT_HOST", "none")
    monkeypatch.setenv("REALTIME", "0")
    monkeypatch.setenv("RUN_UNTIL", "1200")
    assert main([]) == 0


def test_main_requires_config(monkeypatch):
    monkeypatch.delenv("AGENT_CONFIG", raising=False)
    assert main([]) == 2
