"""Bounded-wait readers for watchdogged device operations.

The PR 8 dispatch watchdog survives a wedged device read by running the
blocking call on a daemon thread and abandoning it on timeout — the
thread cannot be cancelled (the block is inside XLA) and is leaked until
the device answers or the process exits. That design had two costs this
module bounds:

* **Unbounded leakage.** Every timed-out read leaked one fresh thread;
  a persistently dead device under a periodic serving loop would leak a
  thread per round, forever. :class:`BoundedReader` caps the number of
  concurrently-wedged reader threads (``max_leaked``); at the cap a new
  read is refused IMMEDIATELY (outcome ``"saturated"``) instead of
  waiting a full timeout against a device that is already known-dead —
  the caller sheds exactly as it would for a timeout, but without the
  extra blocking time or the extra thread.
* **One thread per healthy read.** The old path spawned (and exited) a
  thread per materialize even when the device always answered.
  :class:`BoundedReader` keeps ONE persistent worker and reuses it for
  every read that completes in time; a new worker is spawned only when
  the previous one is still wedged. A wedged worker that eventually
  unblocks parks back on its queue and is *recovered* (reused) instead
  of left idling.

The number of currently-wedged readers is exported as the
``dispatch_watchdog_threads_leaked`` gauge (labelled by reader name) —
the "how close to the cap are we" dashboard number.
"""

from __future__ import annotations

import queue
import threading

from agentlib_mpc_tpu import telemetry

#: default cap on concurrently-wedged reader threads per BoundedReader
MAX_LEAKED_READERS = 4


class _Worker:
    """One persistent daemon worker: a job queue in, a per-job result
    queue out. ``busy`` is True from submission until the result is
    posted — a worker stuck inside a wedged device call stays busy."""

    def __init__(self, name: str):
        self._jobs: "queue.Queue" = queue.Queue()
        self.busy = False
        self.thread = threading.Thread(target=self._loop, daemon=True,
                                       name=name)
        self.thread.start()

    def _loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:              # poison pill: retire the thread
                return
            fn, resq = job
            try:
                res = ("ok", fn())
            except BaseException as exc:  # noqa: BLE001 - forwarded
                res = ("err", exc)
            resq.put(res)
            self.busy = False

    def retire(self) -> None:
        """Ask the (idle) worker thread to exit — an idle worker the
        reader will never reuse must not linger as a silent leak."""
        self._jobs.put(None)

    def submit(self, fn, timeout_s: float):
        """Run ``fn`` on the worker; returns ("ok", value)/("err", exc)
        or None when the bound expired first (the worker stays busy
        until the call unblocks)."""
        resq: "queue.Queue" = queue.Queue()
        self.busy = True
        self._jobs.put((fn, resq))
        try:
            out = resq.get(timeout=timeout_s)
        except queue.Empty:
            return None
        self.busy = False
        return out


class BoundedReader:
    """Reusable bounded-wait runner with a leak cap.

    ``run(fn, timeout_s)`` returns one of::

        ("ok", value)        # fn completed in time
        ("err", exception)   # fn raised (caller re-raises)
        ("timeout", None)    # bound expired; the worker is leaked
        ("saturated", None)  # max_leaked workers already wedged — the
                             # read was refused WITHOUT waiting

    Treat ``timeout`` and ``saturated`` identically at the policy layer
    (the round is dead); ``saturated`` just costs zero extra seconds and
    zero extra threads.
    """

    def __init__(self, name: str = "watchdog-reader",
                 max_leaked: int = MAX_LEAKED_READERS):
        self.name = name
        self.max_leaked = max(1, int(max_leaked))
        self._worker: "_Worker | None" = None
        self._wedged: list = []
        #: previously-wedged workers that unblocked: reusable, never
        #: silently dropped (a dropped worker's thread would idle on
        #: its queue forever — the exact leak this class bounds)
        self._idle: list = []
        #: reads refused at the leak cap (observability)
        self.saturations = 0
        self._lock = threading.Lock()

    def _sweep_locked(self) -> None:
        """Drop dead threads from the wedged set and move workers that
        have since unblocked into the idle (reusable) pool — retiring
        any beyond one spare, so recoveries can never accumulate
        untracked idle threads."""
        recovered = [w for w in self._wedged
                     if not w.busy and w.thread.is_alive()]
        self._wedged = [w for w in self._wedged
                        if w.busy and w.thread.is_alive()]
        self._idle = [w for w in self._idle if w.thread.is_alive()]
        self._idle.extend(recovered)
        while len(self._idle) > 1:
            self._idle.pop().retire()

    def _export_gauge(self) -> None:
        if telemetry.enabled():
            telemetry.gauge(
                "dispatch_watchdog_threads_leaked",
                "watchdog reader threads currently wedged inside an "
                "unanswered device call (capped at max_leaked)").set(
                float(len(self._wedged)), reader=self.name)

    @property
    def leaked(self) -> int:
        with self._lock:
            self._sweep_locked()
            n = len(self._wedged)
        self._export_gauge()
        return n

    def run(self, fn, timeout_s: float):
        with self._lock:
            self._sweep_locked()
            w = self._worker
            if w is not None and (w.busy or not w.thread.is_alive()):
                # the previous read is still blocked (or its thread
                # died): account it wedged and find a replacement
                if w.busy and w.thread.is_alive() and w not in self._wedged:
                    self._wedged.append(w)
                w = None
            if w is None and self._idle:
                # a previously-wedged worker unblocked: reuse it instead
                # of spawning (the single-use-executor reuse)
                w = self._idle.pop(0)
            if w is None:
                if len(self._wedged) >= self.max_leaked:
                    self.saturations += 1
                    self._export_gauge()
                    return ("saturated", None)
                w = _Worker(self.name)
            self._worker = w
        out = w.submit(fn, float(timeout_s))
        if out is None:
            with self._lock:
                if w not in self._wedged:
                    self._wedged.append(w)
                if self._worker is w:
                    self._worker = None
            self._export_gauge()
            return ("timeout", None)
        self._export_gauge()
        return out
