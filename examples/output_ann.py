"""Output-ANN training: learn *absolute, non-recursive* output maps.

Native re-design of the reference's output-ANN example
(``examples/output_ann/generate_training_data.py``): an ANN with multiple
non-recursive ("output") targets — static maps rather than NARX dynamics —
is trained from generated data, serialized to the exchange format,
round-tripped, and verified against the ground-truth functions. This is
the trainer-side counterpart of the ``ml_output_names`` path in the hybrid
model (algebraic ML outputs, reference ``casadi_ml_model.py:401-416``).

Run directly for a report, or call ``run_example`` (examples-as-tests,
SURVEY.md §4).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

from agentlib_mpc_tpu.ml import Feature, OutputFeature
from agentlib_mpc_tpu.ml.predictors import make_predictor
from agentlib_mpc_tpu.ml.serialized import load_serialized_model
from agentlib_mpc_tpu.ml.training import ANNTrainerCore, fit_ann


def generate_training_data(n: int = 4000, seed: int = 0):
    """Two static maps of one input (the reference's y = 2x, y2 = x + 10)."""
    rng = np.random.default_rng(seed)
    x = rng.uniform(-50.0, 50.0, size=(n, 1))
    y = np.column_stack([2.0 * x[:, 0], x[:, 0] + 10.0])
    return x, y


def run_example(testing: bool = False, verbose: bool = True,
                epochs: int = 400) -> dict:
    X, Y = generate_training_data()
    model = fit_ann(
        X, Y, dt=1.0,
        inputs={"x": Feature(name="x")},
        output={
            "y": OutputFeature(name="y", output_type="absolute",
                               recursive=False),
            "y2": OutputFeature(name="y2", output_type="absolute",
                                recursive=False),
        },
        trainer=ANNTrainerCore(hidden=(32, 32), epochs=epochs,
                               learning_rate=3e-3, seed=0))

    # serialize → JSON → deserialize round trip (the exchange format the
    # trainer broadcasts and the controller hot-swaps, SURVEY.md §3.5)
    payload = model.to_json()
    restored = load_serialized_model(payload)
    pred = make_predictor(restored)

    xq = np.linspace(-40.0, 40.0, 41)
    got = np.stack([np.asarray(pred.apply(pred.params, np.array([v])))
                    for v in xq])
    want = np.column_stack([2.0 * xq, xq + 10.0])
    rmse = np.sqrt(np.mean((got - want) ** 2, axis=0))

    if verbose:
        print(f"output-ANN fit: rmse(y)={rmse[0]:.3f}, "
              f"rmse(y2)={rmse[1]:.3f} over x in [-40, 40]")

    if testing:
        assert rmse[0] < 1.5 and rmse[1] < 1.5, (
            f"learned static maps too inaccurate: {rmse}")
        assert restored.output["y"].recursive is False
        assert restored.output["y2"].output_type == "absolute"
    return {"model": restored, "rmse": rmse}


if __name__ == "__main__":
    run_example(testing=True)
