"""MHE: state + parameter + unknown-input estimation on the one-room model.

Mirrors the reference's MHE capability (``modules/estimation/mhe.py`` +
``casadi_/mhe.py``): a simulator plant publishes noisy temperature
measurements; the MHE module reconstructs the state and an unknown constant
heat load over a backwards horizon.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.backends.mhe_backend import make_mhe_model
from agentlib_mpc_tpu.models.variables import Var
from agentlib_mpc_tpu.models.zoo import OneRoom
from agentlib_mpc_tpu.runtime.mas import LocalMAS
import agentlib_mpc_tpu.modules  # noqa: F401


class RoomWithLoadParam(OneRoom):
    """OneRoom variant with the heat load as a *parameter* so the MHE can
    estimate it (the reference estimates parameters the same way,
    ``mhe.py:70-79``)."""

    inputs = [v for v in OneRoom.inputs if v.name != "load"]
    parameters = list(OneRoom.parameters) + [
        Var(name="load", value=150.0, lb=0.0, ub=500.0, role="parameter"),
    ]


def test_make_mhe_model_structure():
    base = RoomWithLoadParam()
    mhe_model = make_mhe_model(base, ["load"], ["T"])
    # estimated parameter became a zero-dynamics state
    assert "load" in mhe_model.diff_state_names
    assert "load" not in mhe_model.parameter_names
    # measurement/weight aux inputs exist
    assert "measured_T" in mhe_model.input_names
    assert "weight_T" in mhe_model.input_names
    # tracking objective only
    assert mhe_model.objective_term_names == ["mhe_tracking"]


TRUE_LOAD = 260.0
DT = 60.0

MHE_AGENT = {
    "id": "Estimator",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "mhe",
            "type": "mhe",
            "optimization_backend": {
                "type": "jax_mhe",
                "model": {"class": RoomWithLoadParam},
                "discretization_options": {"collocation_order": 2},
                "solver": {"max_iter": 50},
            },
            "time_step": DT,
            "horizon": 8,
            "state_weights": {"T": 1.0},
            "states": [
                {"name": "T", "value": 298.16, "alias": "T",
                 "source": "Plant"},
            ],
            "known_inputs": [
                {"name": "mDot", "value": 0.02, "alias": "mDot",
                 "source": "Plant"},
                {"name": "T_in", "value": 290.15},
                {"name": "T_upper", "value": 295.15},
            ],
            "estimated_parameters": [
                {"name": "load", "value": 100.0, "lb": 0.0, "ub": 500.0},
            ],
        },
    ],
}

PLANT = {
    "id": "Plant",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "room",
            "type": "simulator",
            "model": {"class": RoomWithLoadParam,
                      "states": [{"name": "T", "value": 298.16}],
                      "parameters": [{"name": "load", "value": TRUE_LOAD}]},
            "t_sample": DT,
            "outputs": [{"name": "T_out", "value": 298.16, "alias": "T"}],
            "inputs": [{"name": "mDot", "value": 0.02, "alias": "mDot",
                        "shared": True}],
        },
    ],
}


@pytest.fixture(scope="module")
def mas():
    mas = LocalMAS([MHE_AGENT, PLANT], env={"rt": False})
    mas.run(until=1500)
    return mas


def test_load_estimated(mas):
    mhe = mas.agents["Estimator"].get_module("mhe")
    est_load = mhe.get_value("load")
    assert abs(est_load - TRUE_LOAD) < 30.0, (
        f"estimated load {est_load} far from true {TRUE_LOAD}")


def test_state_estimate_tracks_measurement(mas):
    mhe = mas.agents["Estimator"].get_module("mhe")
    plant = mas.agents["Plant"].get_module("room")
    t_est = mhe.get_value("T")
    t_true = float(np.asarray(plant.get_value("T_out")))
    assert abs(t_est - t_true) < 0.5


def test_solver_stats_recorded(mas):
    mhe = mas.agents["Estimator"].get_module("mhe")
    stats = mhe.results()
    assert stats is not None and len(stats) >= 10
    assert stats["success"].mean() > 0.8
