"""Keras ANN interop: layer-graph IR + pure-JAX evaluation + converter.

Counterpart of the reference's symbolic Keras re-implementation
(``agentlib_mpc/models/casadi_predictor.py``: layer classes :197-536,
Sequential chain :599-616, Functional-API DAG walk :618-719, supported
``ANNLayerTypes`` :197-215). There every trained Keras model is rebuilt as
a CasADi expression so it can sit inside an NLP; here it is converted
**once** into

* a JSON-able *graph spec* — a topologically-ordered list of nodes
  (layer type + static config + input edges), and
* a *params* pytree of numpy/jnp weight arrays keyed by node name,

which :func:`build_graph_apply` turns into one pure function
``apply(params, x) -> y`` — jit / grad / vmap safe, so the same artifact
serves the plant simulator, the NARX transcription inside the OCP (where
``jax.grad`` differentiates through it for the KKT system) and training
sweeps. Hot-swapping retrained weights replaces pytree leaves without
recompiling.

Supported layer types (the reference's 17, ``casadi_predictor.py:197-215``):
dense (with the activation set incl. exponential/gaussian), flatten,
batch_normalization, normalization, cropping1d, concatenate, reshape,
input_slice, constant, add, subtract, multiply, divide, power, average,
rescaling, rbf. Nested Functional / Sequential submodels are inlined
recursively (the reference wraps them, :536-556).

Internal array convention: like the reference's CasADi layers, every value
is a 2-D ``(rows, features)`` array without the batch dimension
(``Layer.update_dimensions``, :239-252); the public ``apply`` takes the
flat input vector and returns the flat output.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

# activations the reference evaluates symbolically
# (``casadi_predictor.py:254-296``): the shared trainer/predictor table
# plus the two keras-only names it supports on top
from agentlib_mpc_tpu.ml.predictors import _ACT as _BASE_ACT  # noqa: E402

GRAPH_ACTIVATIONS = {
    **_BASE_ACT,
    "exponential": jnp.exp,
    "gaussian": lambda x: jnp.exp(-(x ** 2)),
}


def _act(name) -> Callable:
    if callable(name):
        return name
    if isinstance(name, dict):
        # keras custom-activation config dicts (reference :283-296):
        # concave(f)(x) = -f(-x); saturated(relu) = clip to [-1, 1]
        reg = name.get("registered_name", "")
        inner = name.get("config", {}).get("activation", "linear")
        if reg.endswith("ConcaveActivation"):
            base = _act(inner)
            return lambda x: -base(-x)
        if reg.endswith("SaturatedActivation"):
            if inner == "relu":
                return lambda x: jnp.clip(x, -1.0, 1.0)
            if inner == "softplus":
                e = float(np.e)
                return lambda x: jnp.where(
                    x >= 0,
                    jnp.log((1 + e) / (1 + jnp.exp(1 - x))),
                    jnp.log((1 + jnp.exp(1 + x)) / (1 + e)))
        raise ValueError(f"unsupported custom activation {name!r}")
    try:
        return GRAPH_ACTIVATIONS[str(name)]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}") from None


# --------------------------------------------------------------------------
# node forward functions: (params_of_node, [inputs]) -> output (2-D arrays)
# --------------------------------------------------------------------------

def _f_dense(cfg, p, xs):
    act = _act(cfg.get("activation", "linear"))
    return act(xs[0] @ p["kernel"] + p["bias"][None, :])


def _f_flatten(cfg, p, xs):
    return xs[0].reshape(1, -1)       # row-major == horzcat of rows


def _f_batch_normalization(cfg, p, xs):
    eps = float(cfg.get("epsilon", 1e-3))
    return ((xs[0] - p["mean"][None, :])
            / jnp.sqrt(p["var"][None, :] + eps)
            * p["gamma"][None, :] + p["beta"][None, :])


def _f_normalization(cfg, p, xs):
    return (xs[0] - p["mean"]) / jnp.sqrt(p["var"])


def _f_cropping1d(cfg, p, xs):
    lo, hi = cfg.get("cropping", (1, 1))
    x = xs[0]
    return x[int(lo): x.shape[0] - int(hi), :]


def _f_concatenate(cfg, p, xs):
    axis = int(cfg.get("axis", -1))
    # reference semantics (:410-418): feature axis → horzcat, time → vertcat
    return jnp.concatenate(xs, axis=1 if axis in (-1, 2) else 0)


def _f_reshape(cfg, p, xs):
    r, c = cfg["target_shape"]
    return xs[0].reshape(int(r), int(c))   # keras C-order (NOT CasADi's F)


def _f_input_slice(cfg, p, xs):
    idx = jnp.asarray(cfg["feature_indices"], dtype=jnp.int32)
    return xs[0][:, idx]


def _f_constant(cfg, p, xs):
    return p["constant"]


def _f_add(cfg, p, xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def _f_subtract(cfg, p, xs):
    return xs[0] - xs[1]


def _f_multiply(cfg, p, xs):
    out = xs[0]
    for x in xs[1:]:
        out = out * x
    return out


def _f_divide(cfg, p, xs):
    return xs[0] / xs[1]


def _f_power(cfg, p, xs):
    return xs[0] ** xs[1]


def _f_average(cfg, p, xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out / len(xs)


def _f_rescaling(cfg, p, xs):
    # scale/offset may be scalars or per-feature arrays (keras broadcasts)
    scale = jnp.asarray(cfg.get("scale", 1.0))
    offset = jnp.asarray(cfg.get("offset", 0.0))
    return xs[0] * scale + offset


def _f_rbf(cfg, p, xs):
    # phi_j = exp(-gamma_j ||x - c_j||^2), gamma = exp(log_gamma)
    # (reference RBF layer, ``casadi_predictor.py:517-532``)
    diff = xs[0] - p["centers"]                     # (units, d)
    dist_sq = jnp.sum(diff * diff, axis=1)          # (units,)
    gamma = jnp.exp(p["log_gamma"]).reshape(-1)
    return jnp.exp(-gamma * dist_sq)[None, :]       # (1, units)


NODE_FORWARDS = {
    "dense": _f_dense,
    "flatten": _f_flatten,
    "batch_normalization": _f_batch_normalization,
    "normalization": _f_normalization,
    "cropping1d": _f_cropping1d,
    "concatenate": _f_concatenate,
    "reshape": _f_reshape,
    "input_slice": _f_input_slice,
    "constant": _f_constant,
    "add": _f_add,
    "subtract": _f_subtract,
    "multiply": _f_multiply,
    "divide": _f_divide,
    "power": _f_power,
    "average": _f_average,
    "rescaling": _f_rescaling,
    "rbf": _f_rbf,
}


# --------------------------------------------------------------------------
# graph spec evaluation
# --------------------------------------------------------------------------

def build_graph_apply(spec: dict) -> Callable[[Any, jnp.ndarray], jnp.ndarray]:
    """Compile a graph spec into ``apply(params, x)``.

    Spec schema::

        {"input": {"name": str, "shape": [rows, features]},
         "nodes": [{"name": str, "type": str, "config": {...},
                    "inputs": [str, ...]}, ...],   # topological order
         "output": str}

    ``params`` maps node name → dict of weight arrays. ``x`` is the flat
    input vector; the output is flattened back to 1-D.
    """
    in_name = spec["input"]["name"]
    in_shape = tuple(int(s) for s in spec["input"]["shape"])
    nodes = spec["nodes"]
    known = {in_name}
    for node in nodes:
        if node["type"] not in NODE_FORWARDS:
            raise ValueError(
                f"unsupported layer type {node['type']!r} "
                f"(node {node['name']!r}); supported: "
                f"{sorted(NODE_FORWARDS)}")
        for src in node["inputs"]:
            if src not in known:
                raise ValueError(
                    f"node {node['name']!r} consumes {src!r} before its "
                    f"definition — spec must be topologically ordered")
        known.add(node["name"])
    if spec["output"] not in known:
        raise ValueError(f"output node {spec['output']!r} not in graph")

    def apply(params, x):
        values = {in_name: jnp.reshape(x, in_shape)}
        for node in nodes:
            fwd = NODE_FORWARDS[node["type"]]
            xs = [values[src] for src in node["inputs"]]
            values[node["name"]] = fwd(node.get("config", {}),
                                       params.get(node["name"], {}), xs)
        return jnp.reshape(values[spec["output"]], (-1,))

    return apply


# --------------------------------------------------------------------------
# Keras → (spec, params) converter
# --------------------------------------------------------------------------

_KERAS_CLASS_MAP = {
    "Dense": "dense",
    "Flatten": "flatten",
    "BatchNormalization": "batch_normalization",
    "Normalization": "normalization",
    "Cropping1D": "cropping1d",
    "Concatenate": "concatenate",
    "Reshape": "reshape",
    "Add": "add",
    "Subtract": "subtract",
    "Multiply": "multiply",
    "TrueDivide": "divide",
    "Divide": "divide",
    "Power": "power",
    "Average": "average",
    "Rescaling": "rescaling",
}


def _classify_layer(layer) -> str:
    """Keras layer → node type: exact class match, then duck-typing for the
    custom physXAI layers (rbf / input_slice / constant, reference
    :497-532). No name-substring matching — the reference's substring rule
    (:603-608) silently misclassifies e.g. GlobalAveragePooling as the
    'average' merge; unsupported layers must raise instead."""
    cls = type(layer).__name__
    if cls in _KERAS_CLASS_MAP:
        return _KERAS_CLASS_MAP[cls]
    if hasattr(layer, "centers") and hasattr(layer, "log_gamma"):
        return "rbf"
    if hasattr(layer, "feature_indices"):
        return "input_slice"
    if hasattr(layer, "constant"):
        return "constant"
    name = layer.get_config().get("name", "")
    raise NotImplementedError(
        f"Keras layer {cls!r} (name={name!r}) is not supported; "
        f"supported types: {sorted(set(_KERAS_CLASS_MAP))} + "
        f"rbf/input_slice/constant (by attributes)")


def _np(x):
    return np.asarray(x, dtype=np.float64)


def _extract(layer, node_type: str, cfg_out: dict, params_out: dict):
    """Pull static config + weights out of one keras layer."""
    cfg = layer.get_config()
    if node_type == "dense":
        w = layer.get_weights()
        params_out["kernel"] = _np(w[0])
        params_out["bias"] = (_np(w[1]) if len(w) > 1
                              else np.zeros(w[0].shape[1]))
        cfg_out["activation"] = cfg.get("activation", "linear")
    elif node_type == "batch_normalization":
        w = layer.get_weights()
        params_out["gamma"], params_out["beta"] = _np(w[0]), _np(w[1])
        params_out["mean"], params_out["var"] = _np(w[2]), _np(w[3])
        cfg_out["epsilon"] = float(cfg.get("epsilon", 1e-3))
    elif node_type == "normalization":
        mean, var = _np(layer.mean), _np(layer.variance)
        if mean.ndim == 3:      # (reference :382-390)
            mean, var = mean[-1], var[-1]
        params_out["mean"], params_out["var"] = mean, var
    elif node_type == "cropping1d":
        crop = layer.cropping
        cfg_out["cropping"] = [int(crop[0]), int(crop[1])] \
            if not np.isscalar(crop) else [int(crop), int(crop)]
    elif node_type == "concatenate":
        cfg_out["axis"] = int(layer.axis)
    elif node_type == "reshape":
        shape = tuple(int(s) for s in layer.target_shape)
        if len(shape) == 1:
            shape = (1, shape[0])
        cfg_out["target_shape"] = list(shape)
    elif node_type == "rescaling":
        # keep per-feature arrays intact (JSON-able nested lists)
        cfg_out["scale"] = np.asarray(layer.scale, dtype=float).tolist()
        cfg_out["offset"] = np.asarray(layer.offset, dtype=float).tolist()
    elif node_type == "rbf":
        params_out["centers"] = _np(layer.centers)
        params_out["log_gamma"] = _np(layer.log_gamma)
    elif node_type == "input_slice":
        cfg_out["feature_indices"] = [
            int(i) for i in np.asarray(layer.feature_indices).reshape(-1)]
    elif node_type == "constant":
        params_out["constant"] = _np(layer.constant)
    # pure-arithmetic merge layers carry no state


def _iter_history(tensor):
    """(producing layer, node_index, tensor_index) of a keras tensor."""
    h = tensor._keras_history
    return h.operation, h.node_index, h.tensor_index


def from_keras(model) -> tuple[dict, dict]:
    """Convert a Keras ``Sequential`` or ``Functional`` model (single input,
    single output — the reference's supported envelope, :579-587) into
    ``(spec, params)`` for :func:`build_graph_apply`.

    Nested Functional/Sequential submodels are inlined with name prefixes
    (the reference wraps them in ``FunctionalWrapper``/``SequentialWrapper``,
    :536-556)."""
    spec_nodes: list[dict] = []
    params: dict[str, dict] = {}
    used_names: set[str] = {"input"}

    def add_layer(layer, input_names: list[str], prefix: str) -> str:
        cls = type(layer).__name__
        if cls in ("Functional", "Sequential") or hasattr(layer, "layers"):
            return inline_submodel(layer, input_names, prefix)
        node_type = _classify_layer(layer)
        name = prefix + layer.name
        # weight-sharing: a layer called at several graph nodes yields one
        # spec node per CALL — unique names keep the calls' outputs apart
        # (weights are duplicated per call; acceptable for inference)
        k = 1
        while name in used_names:
            k += 1
            name = f"{prefix}{layer.name}__call{k}"
        used_names.add(name)
        cfg: dict = {}
        p: dict = {}
        _extract(layer, node_type, cfg, p)
        spec_nodes.append({"name": name, "type": node_type,
                           "config": cfg, "inputs": list(input_names)})
        if p:
            params[name] = p
        return name

    def inline_submodel(model_, input_names: list[str], prefix: str) -> str:
        sub_prefix = prefix + model_.name + "/"
        if _is_sequential(model_):
            cur = input_names
            last = input_names[0]
            for layer in model_.layers:
                if type(layer).__name__ == "InputLayer":
                    continue
                last = add_layer(layer, cur, sub_prefix)
                cur = [last]
            return last
        return walk_functional(model_, input_names, sub_prefix)

    def _is_sequential(m) -> bool:
        return type(m).__name__ == "Sequential" or not hasattr(m, "inputs")

    def walk_functional(model_, outer_inputs: list[str], prefix: str) -> str:
        if len(model_.inputs) != len(outer_inputs):
            raise NotImplementedError(
                f"model {model_.name!r} has {len(model_.inputs)} inputs; "
                f"{len(outer_inputs)} were wired")
        produced: dict[tuple, str] = {}
        for t, outer in zip(model_.inputs, outer_inputs):
            op, ni, ti = _iter_history(t)
            produced[(op.name, ni, ti)] = outer

        def resolve(tensor) -> str:
            op, ni, ti = _iter_history(tensor)
            key = (op.name, ni, ti)
            if key in produced:
                return produced[key]
            # evaluate the producing layer at this call node
            node = op._inbound_nodes[ni]
            srcs = [resolve(t) for t in node.input_tensors]
            out_name = add_layer(op, srcs, prefix)
            # register all output tensors of this call (single-output
            # layers: tensor_index 0)
            produced[(op.name, ni, 0)] = out_name
            return produced[key]

        outs = model_.outputs
        if len(outs) != 1:
            raise NotImplementedError(
                "only single-output Keras models are supported "
                "(reference envelope, casadi_predictor.py:676)")
        return resolve(outs[0])

    # top level
    if _is_sequential(model):
        in_shape = model.layers[0].input.shape \
            if model.layers else (None, 1)
        in_feat = tuple(int(s) for s in in_shape[1:]) or (1,)
        input_name = "input"
        cur = [input_name]
        last = input_name
        for layer in model.layers:
            if type(layer).__name__ == "InputLayer":
                continue
            last = add_layer(layer, cur, "")
            cur = [last]
        out_name = last
    else:
        if len(model.inputs) != 1:
            raise NotImplementedError(
                "only single-input Keras models are supported "
                "(reference envelope, casadi_predictor.py:579-587)")
        shape = model.inputs[0].shape
        in_feat = tuple(int(s) for s in shape[1:] if s is not None) or (1,)
        input_name = "input"
        out_name = walk_functional(model, [input_name], "")

    rows, feats = (1, in_feat[0]) if len(in_feat) == 1 else in_feat[:2]
    spec = {
        "input": {"name": input_name, "shape": [int(rows), int(feats)]},
        "nodes": spec_nodes,
        "output": out_name,
    }
    # validate + return jnp params
    build_graph_apply(spec)
    jparams = jax.tree.map(jnp.asarray, params)
    return spec, jparams


def spec_to_jsonable(spec: dict, params: dict) -> dict:
    """Self-contained JSON document (spec + weights as nested lists)."""
    return {
        "spec": spec,
        "params": {node: {k: np.asarray(v).tolist() for k, v in d.items()}
                   for node, d in params.items()},
    }


def spec_from_jsonable(doc: dict) -> tuple[dict, dict]:
    params = {
        node: {k: jnp.asarray(np.asarray(v, dtype=np.float64))
               for k, v in d.items()}
        for node, d in doc["params"].items()}
    return doc["spec"], params
