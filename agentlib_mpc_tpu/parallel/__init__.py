from agentlib_mpc_tpu.parallel.fused_admm import (
    AgentGroup,
    FusedADMM,
    FusedADMMOptions,
)
from agentlib_mpc_tpu.parallel.multihost import (
    fleet_mesh,
    host_local_batch,
    initialize_multihost,
)
