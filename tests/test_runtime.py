"""Unit tests for the agent runtime (environment, broker, modules)."""

import numpy as np
import pytest

from agentlib_mpc_tpu.runtime.broker import BroadcastBus, DataBroker
from agentlib_mpc_tpu.runtime.environment import Environment
from agentlib_mpc_tpu.runtime.module import BaseModule, register_module
from agentlib_mpc_tpu.runtime.mas import LocalMAS
from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source


def test_environment_runs_processes_in_time_order():
    env = Environment()
    log = []

    def proc(name, dt):
        while True:
            log.append((env.now, name))
            yield dt

    env.process(proc("a", 10.0))
    env.process(proc("b", 15.0))
    env.run(until=30.0)
    # ties resolve FIFO by scheduling order: b's t=30 event was enqueued at
    # t=15, before a's (enqueued at t=20)
    assert log == [(0.0, "a"), (0.0, "b"), (10.0, "a"), (15.0, "b"),
                   (20.0, "a"), (30.0, "b"), (30.0, "a")]


def test_environment_call_at():
    env = Environment()
    hits = []
    env.call_at(5.0, lambda: hits.append(env.now))
    env.call_in(7.0, lambda: hits.append(env.now))
    env.run(until=10.0)
    assert hits == [5.0, 7.0]


def test_broker_alias_and_source_matching():
    broker = DataBroker("agent1")
    got = []
    broker.register_callback("T", Source(agent_id="sim"), got.append)
    # wrong alias: ignored
    broker.send_variable(AgentVariable(name="x", alias="other",
                                       source=Source("sim")))
    # wrong source: ignored
    broker.send_variable(AgentVariable(name="T", alias="T",
                                       source=Source("other")))
    # match
    broker.send_variable(AgentVariable(name="T", alias="T", value=5.0,
                                       source=Source("sim")))
    assert len(got) == 1 and got[0].value == 5.0


def test_bus_broadcast_crosses_agents_only_when_shared():
    bus = BroadcastBus()
    b1, b2 = DataBroker("a1"), DataBroker("a2")
    bus.join(b1)
    bus.join(b2)
    got = []
    b2.register_callback("T", None, got.append)
    b1.send_variable(AgentVariable(name="T", value=1.0, shared=False,
                                   source=Source("a1")))
    assert got == []
    b1.send_variable(AgentVariable(name="T", value=2.0, shared=True,
                                   source=Source("a1")))
    assert len(got) == 1 and got[0].value == 2.0


@register_module("_test_counter")
class CounterModule(BaseModule):
    def __init__(self, config, agent):
        super().__init__(config, agent)
        self.count = 0

    def process(self):
        while True:
            self.count += 1
            yield self.config.get("dt", 1.0)


def test_local_mas_runs_modules():
    mas = LocalMAS([
        {"id": "a1", "modules": [
            {"module_id": "c1", "type": "_test_counter", "dt": 10.0}]},
    ])
    mas.run(until=100.0)
    assert mas.agents["a1"].get_module("c1").count == 11  # t=0..100


def test_module_variable_store_and_sharing():
    @register_module("_test_sender")
    class Sender(BaseModule):
        variable_groups = ("outputs",)
        shared_groups = ("outputs",)

        def process(self):
            self.set("y", 42.0)
            return
            yield

    @register_module("_test_receiver")
    class Receiver(BaseModule):
        variable_groups = ("inputs",)

    mas = LocalMAS([
        {"id": "s", "modules": [
            {"module_id": "m", "type": "_test_sender",
             "outputs": [{"name": "y", "alias": "meas"}]}]},
        {"id": "r", "modules": [
            {"module_id": "m", "type": "_test_receiver",
             "inputs": [{"name": "y_in", "alias": "meas", "source": "s"}]}]},
    ])
    mas.run(until=1.0)
    assert mas.agents["r"].get_module("m").get_value("y_in") == 42.0


def test_communicator_entries_are_accepted_and_skipped():
    mas = LocalMAS([
        {"id": "a", "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {"module_id": "c", "type": "_test_counter"}]},
    ])
    assert list(mas.agents["a"].modules) == ["c"]


def test_duplicate_agent_ids_rejected():
    with pytest.raises(ValueError, match="duplicate agent"):
        LocalMAS([{"id": "a", "modules": []}, {"id": "a", "modules": []}])


def test_environment_stop_freezes_clock():
    env = Environment()

    def stopper():
        yield 10.0
        env.stop()

    env.process(stopper())
    env.run(until=3600.0)
    assert env.now == 10.0  # not forced to `until`


def test_local_mas_second_run_continues_without_restart():
    mas = LocalMAS([
        {"id": "a1", "modules": [
            {"module_id": "c1", "type": "_test_counter", "dt": 10.0}]},
    ])
    mas.run(until=50.0)
    counter = mas.agents["a1"].get_module("c1")
    assert counter.count == 6
    mas.run(until=100.0)
    assert counter.count == 11  # continuation, no double-registration


def test_explicit_shared_false_instance_respected():
    from agentlib_mpc_tpu.runtime.module import BaseModule, register_module

    @register_module("_test_shared_probe")
    class Probe(BaseModule):
        variable_groups = ("outputs",)
        shared_groups = ("outputs",)

    mas = LocalMAS([{"id": "a", "modules": [
        {"module_id": "m", "type": "_test_shared_probe",
         "outputs": [AgentVariable(name="private_y", shared=False)]}]}])
    assert not mas.agents["a"].get_module("m").vars["private_y"].shared
