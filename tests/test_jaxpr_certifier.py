"""jaxpr-level structural certifier (ISSUE 5): sound LQ proofs, the
adversarial corpus the sampled probe gets wrong, stage-structure
certification against real transcriptions, dtype propagation, and the
cost model.

The headline case is the round-5 VERDICT medium: a theta that gates a
nonlinearity. ``is_lq`` probes only at the default theta, sees the
quadratic branch, and certifies — the auto-routed QP solver would then
silently converge to a wrong point for every theta on the other side of
the gate. ``certify_lq`` walks the jaxpr with theta symbolic, sees both
branches, and refutes.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.lint.jaxpr import (
    LQCertificate,
    certify_lq,
    certify_stage_structure,
    check_dtypes,
    op_cost,
)
from agentlib_mpc_tpu.ops.qp import is_lq, resolve_qp_routing
from agentlib_mpc_tpu.ops.solver import NLPFunctions

_N = 3  # primal dimension of the handcrafted corpus


def _nlp(f=None, g=None, h=None):
    zero_f = lambda w, th: jnp.sum(w) * 0.0
    empty = lambda w, th: jnp.zeros((0,))
    return NLPFunctions(f=f or zero_f, g=g or empty, h=h or empty)


# --------------------------------------------------------------------------
# the adversarial LQ corpus
# --------------------------------------------------------------------------


class TestCertifyLQ:
    def test_verdict_theta_gated_nonlinearity(self):
        """The exact VERDICT hazard: at the default theta=0 the gate
        picks the quadratic branch, so the sampled probe certifies LQ —
        while any theta > 0 activates sin(w) and the QP fast path would
        silently mis-solve. The jaxpr pass keeps theta symbolic and
        refutes for ALL theta."""

        def f(w, theta):
            return jnp.where(theta > 0.0,
                             jnp.sum(jnp.sin(w)),      # gated nonlinearity
                             jnp.sum(w * w))           # default-theta branch
        nlp = _nlp(f=f)
        theta0 = jnp.asarray(0.0)

        assert is_lq(nlp, theta0, _N), \
            "precondition: the sampled probe must falsely certify at " \
            "the default theta for this corpus entry to mean anything"
        cert = certify_lq(nlp, theta0, _N)
        assert cert.status == "not_lq"
        assert not cert.proved_lq

    def test_theta_gated_branches_both_lq_is_proved(self):
        """The converse precision check: a theta gate between two
        quadratics is LQ for every fixed theta — the lattice must not
        smear it to non-LQ just because the predicate is symbolic."""

        def f(w, theta):
            return jnp.where(theta > 0.0, jnp.sum(w * w),
                             2.0 * jnp.sum(w * w) + jnp.sum(w))
        cert = certify_lq(_nlp(f=f), jnp.asarray(0.0), _N)
        assert cert.status == "lq"
        assert cert.objective_degree == 2

    def test_proper_lq_program(self):
        def f(w, theta):
            return 0.5 * jnp.dot(w, w) + jnp.dot(theta, w)

        def g(w, theta):
            return jnp.asarray([w[0] + 2.0 * w[1] - theta[0]])

        def h(w, theta):
            return w - 1.0
        cert = certify_lq(_nlp(f=f, g=g, h=h), jnp.zeros((_N,)), _N)
        assert cert.status == "lq"
        assert (cert.objective_degree, cert.eq_degree,
                cert.ineq_degree) == (2, 1, 1)

    def test_cubic_objective_refuted(self):
        cert = certify_lq(_nlp(f=lambda w, th: jnp.sum(w ** 3)),
                          jnp.asarray(0.0), _N)
        assert cert.status == "not_lq"
        assert cert.objective_degree == 3

    def test_quadratic_constraint_refuted(self):
        cert = certify_lq(
            _nlp(g=lambda w, th: jnp.asarray([jnp.dot(w, w) - 1.0])),
            jnp.asarray(0.0), _N)
        assert cert.status == "not_lq"
        assert cert.eq_degree >= 2

    def test_theta_nonlinearity_stays_lq(self):
        """Arbitrary nonlinearity in THETA alone is fine — degree is
        measured in w, theta is a per-solve constant."""

        def f(w, theta):
            return jnp.exp(theta) * jnp.sum(w * w) + jnp.sin(theta)
        cert = certify_lq(_nlp(f=f), jnp.asarray(0.3), _N)
        assert cert.status == "lq"

    def test_pure_callback_is_unknown_not_executed(self):
        """Opaque primitive with w-tainted inputs: the certificate must
        be 'unknown' (route on the probe), and the certifier must never
        execute the host callback."""
        calls = []

        def cb(x):
            calls.append(1)
            return np.asarray(np.sum(x ** 2), dtype=np.float32)

        def f(w, theta):
            return jax.pure_callback(
                cb, jax.ShapeDtypeStruct((), jnp.float32), w)
        cert = certify_lq(_nlp(f=f), jnp.asarray(0.0), _N)
        assert cert.status == "unknown"
        assert cert.opaque
        assert calls == [], "certification executed user host code"

    def test_untainted_callback_keeps_precision(self):
        """An opaque primitive fed only theta/constants cannot carry w
        dependence (purity of jaxpr evaluation) — the proof survives."""

        def f(w, theta):
            c = jax.pure_callback(
                lambda t: np.asarray(t, dtype=np.float32),
                jax.ShapeDtypeStruct((), jnp.float32), theta)
            return c * jnp.sum(w * w)
        cert = certify_lq(_nlp(f=f), jnp.asarray(2.0), _N)
        assert cert.status == "lq"

    def test_jnp_square_is_degree_two(self):
        """jnp.square lowers to its own `square` primitive — it must
        count as integer_pow(2), not a transcendental, or every
        quadratic written idiomatically loses the fast path."""
        cert = certify_lq(_nlp(f=lambda w, th: jnp.sum(jnp.square(w))),
                          jnp.asarray(0.0), _N)
        assert cert.status == "lq"
        assert cert.objective_degree == 2

    def test_scan_accumulated_quadratic(self):
        """Control flow: a scan accumulating stage costs is the shape
        every transcription objective takes."""

        def f(w, theta):
            def body(c, wi):
                return c + wi * wi, None
            out, _ = jax.lax.scan(body, 0.0 * w[0], w)
            return out
        cert = certify_lq(_nlp(f=f), jnp.asarray(0.0), _N)
        assert cert.status == "lq"
        assert cert.objective_degree == 2


# --------------------------------------------------------------------------
# the routing seam: certificate is the authority, probe demoted
# --------------------------------------------------------------------------


def _cert(status):
    return LQCertificate(status=status, objective_degree=2, eq_degree=1,
                         ineq_degree=1)


class TestResolveQpRouting:
    def test_certified_lq_routes_with_probe_crosscheck(self):
        probed = []

        def probe():
            probed.append(1)
            return True
        assert resolve_qp_routing("auto", probe,
                                  certifier=lambda: _cert("lq")) is True
        assert probed == [1], "probe must run exactly once as cross-check"

    def test_refuted_skips_probe(self):
        """not_lq: the probe can only produce the false positive the
        certificate just ruled out — it must not run at all."""
        probed = []

        def probe():
            probed.append(1)
            return True
        assert resolve_qp_routing("auto", probe,
                                  certifier=lambda: _cert("not_lq")) is False
        assert probed == []

    def test_probe_disagreement_blocks_routing(self, caplog):
        with caplog.at_level(logging.WARNING):
            routed = resolve_qp_routing(
                "auto", lambda: False, certifier=lambda: _cert("lq"),
                logger=logging.getLogger("test.qp"), label="the corpus")
        assert routed is False
        assert "DISAGREE" in caplog.text

    def test_unknown_falls_back_to_probe(self, caplog):
        with caplog.at_level(logging.WARNING):
            routed = resolve_qp_routing(
                "auto", lambda: True, certifier=lambda: _cert("unknown"),
                logger=logging.getLogger("test.qp"), label="the corpus")
        assert routed is True
        assert "inconclusive" in caplog.text

    def test_crashing_certifier_falls_back_to_probe(self):
        def certifier():
            raise RuntimeError("interpreter exploded")
        assert resolve_qp_routing("auto", lambda: True,
                                  certifier=certifier) is True

    def test_on_off_skip_both(self):
        boom = lambda: (_ for _ in ()).throw(AssertionError("ran"))
        assert resolve_qp_routing("on", boom, certifier=boom) is True
        assert resolve_qp_routing("off", boom, certifier=boom) is False

    def test_end_to_end_verdict_case_not_routed(self):
        """The acceptance demo, end to end at the seam: the probe alone
        would route the theta-gated corpus entry to the QP fast path;
        with the certifier attached, auto-routing refuses."""

        def f(w, theta):
            return jnp.where(theta > 0.0, jnp.sum(jnp.sin(w)),
                             jnp.sum(w * w))
        nlp = _nlp(f=f)
        theta0 = jnp.asarray(0.0)
        probe = lambda: is_lq(nlp, theta0, _N)
        assert resolve_qp_routing("auto", probe) is True   # the old hazard
        assert resolve_qp_routing(
            "auto", probe,
            certifier=lambda: certify_lq(nlp, theta0, _N)) is False


# --------------------------------------------------------------------------
# stage-structure certification
# --------------------------------------------------------------------------


def _example(name):
    from agentlib_mpc_tpu.lint.jaxpr.examples import EXAMPLE_OCPS

    return next(ex for ex in EXAMPLE_OCPS if ex.name == name)


class TestStageStructure:
    def test_real_transcription_certifies(self):
        ocp = _example("LinearRCZone/colloc-d1").build()
        cert = ocp.certify_stage_structure()
        assert cert.ok, cert.describe()
        assert cert.n_stages == ocp.stage_partition.n_stages

    def test_mispermuted_partition_rejected(self):
        """Swap two primal slots from distant stages: the dependence
        graph no longer fits the band and certification must refuse —
        this is the partition corruption the sweep would silently
        mis-solve under."""
        ocp = _example("LinearRCZone/colloc-d1").build()
        p = ocp.stage_partition
        perm = list(p.perm)
        # first primal slot of stage 0 <-> first primal slot of stage 3
        a, b = 0 * p.block, 3 * p.block
        perm[a], perm[b] = perm[b], perm[a]
        bad = p._replace(perm=tuple(perm))
        cert = certify_stage_structure(
            ocp.nlp, ocp.default_params(), ocp.n_w, bad)
        assert not cert.ok
        assert cert.violations

    def test_out_of_band_coupling_rejected(self):
        """A handcrafted long-range constraint (w[0] with the last
        stage's variable) must be named as a violation."""
        ocp = _example("LinearRCZone/colloc-d1").build()

        def g(w, theta):
            return jnp.asarray([w[0] * w[ocp.n_w - 1]])
        nlp = NLPFunctions(f=ocp.nlp.f, g=g, h=ocp.nlp.h)
        cert = certify_stage_structure(
            nlp, ocp.default_params(), ocp.n_w, ocp.stage_partition)
        assert not cert.ok

    def test_partition_nw_mismatch_raises(self):
        """Either direction of an n_w mismatch silently shifts the
        equality-row offset the band checks index at — both refuse."""
        ocp = _example("LinearRCZone/colloc-d1").build()
        for bad_nw in (2, ocp.n_w + 1):
            small = ocp.stage_partition._replace(n_w=bad_nw)
            with pytest.raises(ValueError, match="partition covers"):
                certify_stage_structure(ocp.nlp, ocp.default_params(),
                                        ocp.n_w, small)

    def test_noncovering_perm_rejected(self):
        """A perm that duplicates one index (shadowing another) is not a
        partition: stage_of_index must refuse, not read garbage."""
        from agentlib_mpc_tpu.ops.stagewise import stage_of_index

        ocp = _example("LinearRCZone/colloc-d1").build()
        p = ocp.stage_partition
        perm = list(p.perm)
        dup = next(i for i, v in enumerate(perm) if v >= 0)
        other = next(i for i, v in enumerate(perm)
                     if v >= 0 and i != dup)
        perm[other] = perm[dup]
        with pytest.raises(ValueError, match="does not cover"):
            stage_of_index(p._replace(perm=tuple(perm)))

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "name", [ex.name for ex in __import__(
            "agentlib_mpc_tpu.lint.jaxpr.examples",
            fromlist=["EXAMPLE_OCPS"]).EXAMPLE_OCPS])
    def test_full_example_menu(self, name):
        """Every example OCP (colloc d=1/2, shooting, ± fix_initial_state,
        all three models) passes all four passes — the same sweep the CI
        lint job runs via ``--jaxpr``."""
        from agentlib_mpc_tpu.lint.jaxpr.examples import certify_example

        result = certify_example(_example(name))
        assert result["failures"] == []
        assert result["stage_ok"]
        assert result["lq_status"] == result["expected_lq"]


# --------------------------------------------------------------------------
# dtype propagation + cost model
# --------------------------------------------------------------------------


class TestDtypesAndCost:
    def test_weak_scan_carry_flagged(self):
        def fn(x):
            def body(c, _):
                return c + 1.0, None
            out, _ = jax.lax.scan(body, 0.0, None, length=3)
            return x + out
        rules = {f["rule"] for f in check_dtypes(fn, jnp.zeros((2,)))}
        assert "jaxpr-weak-leak" in rules

    def test_strongly_typed_function_clean(self):
        def fn(x):
            def body(c, _):
                return c + jnp.float32(1.0), None
            out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=3)
            return x + out
        assert [f for f in check_dtypes(fn, jnp.zeros((2,)),
                                        x64_check=False)] == []

    def test_dot_general_flops(self):
        a = jnp.zeros((8, 16))
        b = jnp.zeros((16, 4))
        est = op_cost(lambda a, b: a @ b, a, b)
        assert est.per_primitive_flops["dot_general"] == 2 * 8 * 4 * 16

    def test_scan_multiplies_body_cost(self):
        def fn(x):
            def body(c, _):
                return c * x, None
            out, _ = jax.lax.scan(body, jnp.ones_like(x), None, length=7)
            return out
        est = op_cost(fn, jnp.zeros((5,)))
        assert est.per_primitive_flops.get("mul", 0) == 7 * 5

    def test_example_cost_attribution_nonempty(self):
        ocp = _example("LinearRCZone/colloc-d1").build()
        theta = ocp.default_params()
        est = op_cost(ocp.nlp.f, jnp.zeros((ocp.n_w,)), theta)
        assert est.flops > 0
        assert est.bytes_accessed > 0
        assert est.top(1)
