"""Real-time threaded ADMM: wall-clock rounds, registration windows,
degradation paths and clean shutdown.

Reference behaviors mirrored: threaded two-agent exchange
(``tests/test_admm.py:26-80``), slow-participant de-registration and
receive timeouts (``modules/dmpc/admm/admm.py:298-321``), wall-clock budget
(``admm.py:263-296``), double-start detection (``admm.py:277-286``).
The shutdown tests are the regression suite for the round-2 teardown crash
('FATAL: exception not rethrown' from a worker killed mid-C-frame)."""

import logging
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.models.zoo import CooledRoom, Cooler
from agentlib_mpc_tpu.modules.admm import (
    ModuleStatus,
    NeighborLink,
    ParticipantStatus,
)
from agentlib_mpc_tpu.runtime.mas import LocalMAS
from agentlib_mpc_tpu.runtime.variables import AgentVariable, Source
import agentlib_mpc_tpu.modules  # noqa: F401


def _agent(aid, model_cls, couplings, controls, extra):
    return {
        "id": aid,
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {
                "module_id": "admm",
                "type": "admm",
                "optimization_backend": {
                    "type": "jax_admm",
                    "model": {"class": model_cls},
                    "discretization_options": {"collocation_order": 2},
                    "solver": {"max_iter": 25},
                    "precompile": True,
                },
                "time_step": 8.0,
                "prediction_horizon": 4,
                "max_iterations": 3,
                "iteration_timeout": 5.0,
                "registration_period": 0.3,
                "penalty_factor": 10.0,
                "couplings": couplings,
                "controls": controls,
                **extra,
            },
        ],
    }


ROOM = _agent(
    "Room", CooledRoom,
    couplings=[{"name": "mDot", "alias": "air", "value": 0.02,
                "ub": 0.05, "lb": 0.0}],
    controls=[],
    extra={
        "inputs": [
            {"name": "load", "value": 150},
            {"name": "T_in", "value": 290.15},
            {"name": "T_upper", "value": 295.15},
        ],
        "states": [{"name": "T", "value": 298.16}],
    },
)

COOLER = _agent(
    "Cooler", Cooler,
    couplings=[{"name": "mDot_out", "alias": "air", "value": 0.02}],
    controls=[{"name": "mDot", "value": 0.02, "ub": 0.05, "lb": 0.0}],
    extra={"parameters": [{"name": "r_mDot", "value": 1.0}]},
)


@pytest.fixture(scope="module")
def rt_mas():
    """One short wall-clock run shared by the realtime tests; torn down
    through the public terminate() path."""
    mas = LocalMAS([ROOM, COOLER], env={"rt": True, "factor": 1.0})
    mas.run(until=10.0)
    # let the daemon threads finish the round the last trigger started
    time.sleep(1.0)
    yield mas
    mas.terminate()


@pytest.mark.slow
def test_realtime_admm_round(rt_mas):
    room = rt_mas.agents["Room"].get_module("admm")
    cooler = rt_mas.agents["Cooler"].get_module("admm")

    # both saw each other on the shared wire alias
    assert any(p for p in room._registered_participants["admm_coupling_air"])
    assert any(
        p for p in cooler._registered_participants["admm_coupling_air"])

    # at least one full iteration with mean computation ran on each side
    assert room._iter_rows, "room completed no ADMM iteration"
    assert cooler._iter_rows, "cooler completed no ADMM iteration"
    mean_room = room._admm_values["admm_coupling_mean_mDot"]
    assert np.all(np.isfinite(mean_room))
    assert mean_room.shape == (4,)


@pytest.mark.slow
def test_midrun_join_registers_participant(rt_mas):
    """A participant broadcasting on the wire alias mid-run is registered
    on first contact (reference initial registration, ``admm.py:440-470``)."""
    room = rt_mas.agents["Room"].get_module("admm")
    newcomer = AgentVariable(
        name="admm_coupling_air", alias="admm_coupling_air",
        value=[0.01, 0.01, 0.01, 0.01],
        source=Source(agent_id="LateJoiner", module_id="admm"))
    room.participant_callback(newcomer)
    inboxes = room._registered_participants["admm_coupling_air"]
    assert Source(agent_id="LateJoiner", module_id="admm") in inboxes


@pytest.mark.slow
def test_iterating_broadcast_lands_in_inbox(rt_mas):
    """While iterating, fresh trajectories go into the bounded inbox and
    flip the sender to available (``admm.py:471-501``)."""
    room = rt_mas.agents["Room"].get_module("admm")
    src = Source(agent_id="LateJoiner", module_id="admm")
    var = AgentVariable(name="admm_coupling_air", alias="admm_coupling_air",
                        value=[0.02] * 4, source=src)
    room.participant_callback(var)              # ensure registered
    old_status = room._status
    room._status = ModuleStatus.optimizing
    try:
        room.participant_callback(var)
        p = room._registered_participants["admm_coupling_air"][src]
        assert p.status is ParticipantStatus.available
        assert p.pending >= 1
        p.reset()
    finally:
        room._status = old_status


@pytest.mark.slow
def test_slow_participant_deregistered_mid_iteration(rt_mas, caplog):
    """An empty inbox after the receive timeout de-registers the sender for
    the rest of the round (``admm.py:298-321``)."""
    room = rt_mas.agents["Room"].get_module("admm")
    src = Source(agent_id="Sluggish", module_id="admm")
    var = AgentVariable(name="admm_coupling_air", alias="admm_coupling_air",
                        value=[0.02] * 4, source=src)
    participation = NeighborLink(var)
    participation.status = ParticipantStatus.available
    # the sweep hits every participation: snapshot the fixture's state so
    # later fixture-sharing tests see it unchanged
    snapshot = [(p, p.status) for p in room.all_participations()]
    room._registered_participants["admm_coupling_air"][src] = participation
    try:
        with caplog.at_level(logging.INFO):
            # start_wall far in the past => remaining timeout clamps to 0
            room._receive_variables(start_wall=time.time() - 999.0,
                                    block=True)
        assert participation.status is ParticipantStatus.not_participating
        assert any("de-registered" in r.message and "Sluggish" in r.message
                   for r in caplog.records)
    finally:
        del room._registered_participants["admm_coupling_air"][src]
        for p, status in snapshot:
            p.status = status


@pytest.mark.slow
def test_wall_clock_budget_exhaustion(rt_mas, caplog):
    """Round must terminate once wall time exceeds
    time_step - registration_period (``admm.py:263-296``)."""
    room = rt_mas.agents["Room"].get_module("admm")
    with caplog.at_level(logging.WARNING):
        hit = room._check_termination(
            admm_iter=1, start_time=room.env.now,
            start_wall=time.time() - 2 * room.time_step)
    assert hit
    assert any("budget" in r.message for r in caplog.records)


@pytest.mark.slow
def test_iteration_cap_terminates(rt_mas):
    room = rt_mas.agents["Room"].get_module("admm")
    assert room._check_termination(
        admm_iter=room.max_iterations, start_time=room.env.now,
        start_wall=time.time())
    assert not room._check_termination(
        admm_iter=0, start_time=room.env.now, start_wall=time.time())


@pytest.mark.slow
def test_stop_request_aborts_round(rt_mas):
    """A shutdown request ends an in-flight round at the next iteration
    boundary (the terminate() contract)."""
    room = rt_mas.agents["Room"].get_module("admm")
    room._stop.set()
    try:
        assert room._check_termination(admm_iter=0, start_time=room.env.now,
                                       start_wall=time.time())
    finally:
        room._stop.clear()


def test_double_start_detection(caplog):
    """A trigger firing while the previous round still runs is reported,
    not queued (reference ``admm.py:277-286``). Tested on a detached stub
    so no live worker can race the event between set and check."""
    import threading
    import types

    from agentlib_mpc_tpu.modules.admm import RealtimeADMM

    stub = types.SimpleNamespace(
        start_step=threading.Event(),
        logger=logging.getLogger("test_double_start"))
    with caplog.at_level(logging.ERROR, logger="test_double_start"):
        RealtimeADMM._fire_trigger(stub)        # idle -> sets the event
        assert stub.start_step.is_set()
        RealtimeADMM._fire_trigger(stub)        # in flight -> reported
    assert any("still running" in r.message for r in caplog.records)


@pytest.mark.slow
def test_terminate_joins_workers_and_is_idempotent():
    """After terminate(): this MAS's worker threads are dead; second call
    no-op. Regression for the round-2 teardown crash. Collects the exact
    thread objects (a concurrently-running fixture MAS uses the same
    thread names)."""
    mas2 = LocalMAS([ROOM, COOLER], env={"rt": True, "factor": 1.0})
    mas2.run(until=2.0)
    workers = [mas2.agents[aid].get_module("admm")._thread
               for aid in ("Room", "Cooler")]
    assert all(t is not None and t.is_alive() for t in workers), \
        "workers should be running"
    mas2.terminate()
    deadline = time.time() + 5.0
    while time.time() < deadline and any(t.is_alive() for t in workers):
        time.sleep(0.05)
    assert not any(t.is_alive() for t in workers)
    for aid in ("Room", "Cooler"):
        assert mas2.agents[aid].get_module("admm")._thread is None
    mas2.terminate()    # idempotent


def test_neighbor_inbox_bounded_evicts_stalest():
    """Flooding sender cannot exhaust memory: the bounded inbox evicts
    its stalest entry (push reports the eviction) and keeps the newest."""
    src = Source(agent_id="a", module_id="m")
    mk = lambda i: AgentVariable(name="x", alias="x", value=[float(i)],
                                 source=src)
    p = NeighborLink(mk(-1))
    for i in range(5):
        assert p.push(mk(i))
    assert not p.push(mk(99))        # full -> evicts oldest, reports it
    assert p.pending == 5
    assert p.pop().value == [1.0]    # entry 0 was evicted
    p.reset()
    assert p.pending == 0
    assert p.pop() is None           # non-blocking pop on empty inbox
    assert p.pop(timeout=0.01) is None
