"""Scenario-tree metadata and the tree-structured KKT solve.

A scenario tree for robust MPC (Lucia et al., multi-stage NMPC; the
reference can only walk it branch by branch through serial CasADi
solves) is, per agent, S copies of the same transcribed OCP — one per
disturbance realization — coupled ONLY by non-anticipativity: scenarios
that share a tree node up to stage ``t`` must apply the same control at
``t`` (the controller cannot act on information it does not have yet).

That coupling pattern is block-sparse in exactly the way the PR 4
machinery exploits:

* the scenario-separable part of the tree KKT matrix is block-diagonal
  over branches, each block block-tridiagonal under the branch's
  :class:`~agentlib_mpc_tpu.ops.stagewise.StagePartition` — it factors
  as S independent stage sweeps, one ``vmap`` over the scenario axis
  (:func:`~agentlib_mpc_tpu.ops.stagewise.factor_kkt_scenarios`);
* the non-anticipativity rows are a THIN equality coupling (pairwise
  control pins within each node group, ``(|group|-1) · n_u`` rows per
  robust stage) whose Schur complement onto the coupling multipliers is
  a small dense SPD system — factored once per tree factorization,
  reused by every resolve.

:class:`TreePartition` extends the stage partition with the tree
metadata and the static coupling layout; the degenerate single-scenario
partition routes through the flat sweep UNWRAPPED (bitwise identity
with the proven flat path — the acceptance contract of ISSUE 12).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from agentlib_mpc_tpu.ops import kkt as kkt_ops
from agentlib_mpc_tpu.ops.stagewise import (
    StagePartition,
    factor_kkt_scenarios,
    resolve_kkt_scenarios,
    synthetic_stage_kkt,
)

_HI = jax.lax.Precision.HIGHEST

__all__ = [
    "ScenarioTree",
    "TreePartition",
    "TreeStructureCertificate",
    "branching_tree",
    "build_tree_partition",
    "certify_tree_structure",
    "factor_kkt_tree",
    "fan_tree",
    "resolve_kkt_tree",
    "single_scenario",
    "solve_kkt_tree",
    "synthetic_tree_kkt",
    "tree_method_available",
    "tree_partition_for_ocp",
]


class ScenarioTree(NamedTuple):
    """Static scenario-tree metadata. Hashable (plain ints + nested int
    tuples) so it can ride inside static jit arguments and engine
    bucket keys exactly like the stage partition does.

    ``node_of`` lists, per non-anticipative control interval ``t``
    (outermost tuple, length = robust horizon), the tree-node id of
    every scenario: scenarios sharing the node at ``t`` must apply the
    same control ``u_t`` — the non-anticipativity groups. An empty
    ``node_of`` means no coupling (independent scenarios).
    ``probabilities`` weight each branch's objective (uniform by
    default); they are data for the expectation, not structure."""

    n_scenarios: int
    node_of: tuple          # per robust stage: tuple(scenario -> node id)
    probabilities: tuple

    @property
    def robust_horizon(self) -> int:
        """Control intervals under non-anticipativity coupling."""
        return len(self.node_of)

    def groups_at(self, t: int) -> tuple:
        """Non-anticipativity groups at robust stage ``t``: tuple of
        scenario-index tuples, one per tree node, singletons included."""
        nodes: dict = {}
        for s, node in enumerate(self.node_of[t]):
            nodes.setdefault(node, []).append(s)
        return tuple(tuple(v) for _k, v in sorted(nodes.items()))

    def validate(self, N: "int | None" = None) -> "ScenarioTree":
        if self.n_scenarios < 1:
            raise ValueError("a scenario tree needs >= 1 scenario")
        if len(self.probabilities) != self.n_scenarios:
            raise ValueError(
                f"{len(self.probabilities)} probabilities for "
                f"{self.n_scenarios} scenarios")
        if abs(sum(self.probabilities) - 1.0) > 1e-9:
            raise ValueError("scenario probabilities must sum to 1")
        for t, nodes in enumerate(self.node_of):
            if len(nodes) != self.n_scenarios:
                raise ValueError(
                    f"node_of[{t}] lists {len(nodes)} scenarios, tree "
                    f"has {self.n_scenarios}")
        if N is not None and self.robust_horizon > N:
            raise ValueError(
                f"robust horizon {self.robust_horizon} exceeds the "
                f"{N}-interval control horizon")
        return self

    def subtree(self, keep) -> "ScenarioTree":
        """The tree restricted to the surviving scenario indices
        ``keep`` (ascending order enforced so sliced state arrays stay
        aligned), with the group probabilities RE-NORMALIZED to sum to
        one again. This is the scenario-axis degrade contract (ISSUE
        14): dropping branches without renormalizing leaves the
        expectation weighted by a sub-distribution — every surviving
        branch under-weighted against the consensus/NA penalties — and
        the actuated group mean permanently biased vs a robust problem
        honestly posed at the reduced branch count. Node groups shrink
        with their members (``groups_at`` derives from ``node_of``), so
        a lost branch leaves its non-anticipativity groups exactly.

        An all-zero surviving mass (every kept branch was probability-0
        padding) falls back to uniform — dead weight stays solvable."""
        keep = tuple(int(s) for s in keep)
        if not keep:
            raise ValueError("subtree needs >= 1 surviving scenario")
        if list(keep) != sorted(set(keep)):
            raise ValueError(
                f"surviving scenario indices must be strictly "
                f"ascending, got {keep}")
        bad = [s for s in keep if not 0 <= s < self.n_scenarios]
        if bad:
            raise ValueError(
                f"surviving indices {bad} outside the "
                f"{self.n_scenarios}-scenario tree")
        probs = tuple(self.probabilities[s] for s in keep)
        total = sum(probs)
        probs = (tuple(p / total for p in probs) if total > 0
                 else _uniform(len(keep)))
        node_of = tuple(tuple(nodes[s] for s in keep)
                        for nodes in self.node_of)
        return ScenarioTree(n_scenarios=len(keep), node_of=node_of,
                            probabilities=probs).validate()


def _uniform(n: int) -> tuple:
    return tuple(1.0 / n for _ in range(n))


def fan_tree(n_scenarios: int, robust_horizon: int = 1,
             probabilities=None) -> ScenarioTree:
    """All scenarios branch at the root: one non-anticipativity group
    per robust stage (the classic S-fan — ``u_0..u_{R-1}`` identical
    across every scenario, everything after free to recourse)."""
    probs = tuple(probabilities) if probabilities is not None \
        else _uniform(n_scenarios)
    node_of = tuple((0,) * n_scenarios for _ in range(max(robust_horizon,
                                                          0)))
    return ScenarioTree(n_scenarios=int(n_scenarios), node_of=node_of,
                        probabilities=probs).validate()


def branching_tree(factors, probabilities=None) -> ScenarioTree:
    """Multi-stage tree from per-stage branching factors: ``factors =
    (3, 2)`` is 6 scenarios — every scenario shares the root control
    ``u_0``, triples sharing the first branch share ``u_1``, and from
    stage 2 each leaf recourses freely. Scenario ``s`` enumerates
    branch choices lexicographically, so the stage-``t`` node id is the
    ancestor index ``s // prod(factors[t:])``."""
    factors = tuple(int(f) for f in factors)
    if not factors or any(f < 1 for f in factors):
        raise ValueError(f"branching factors must be >= 1, got {factors}")
    n = int(np.prod(factors))
    node_of = []
    for t in range(len(factors)):
        stride = int(np.prod(factors[t:], dtype=np.int64))
        node_of.append(tuple(s // stride for s in range(n)))
    probs = tuple(probabilities) if probabilities is not None \
        else _uniform(n)
    return ScenarioTree(n_scenarios=n, node_of=tuple(node_of),
                        probabilities=probs).validate()


def single_scenario() -> ScenarioTree:
    """The degenerate tree: one branch, no coupling — the bitwise
    flat-path routing case."""
    return ScenarioTree(n_scenarios=1, node_of=(), probabilities=(1.0,))


class TreePartition(NamedTuple):
    """Static tree metadata of a scenario-batched KKT system: the
    per-branch :class:`StagePartition` plus the tree and the primal
    indices each robust stage's non-anticipativity coupling pins.
    Hashable like its parts, so it rides static arguments unchanged.

    ``na_indices`` lists, per robust stage ``t``, the tuple of
    per-branch primal (w) indices holding ``u_t`` — the coordinates the
    coupling rows difference across scenarios of a node group."""

    base: StagePartition
    tree: ScenarioTree
    na_indices: tuple

    @property
    def n_scenarios(self) -> int:
        return self.tree.n_scenarios

    @property
    def n_coupling_rows(self) -> int:
        """Non-anticipativity equality rows of the coupled tree KKT:
        per robust stage and node group, ``|group|-1`` pairwise pins
        per coupled coordinate."""
        rows = 0
        for t in range(self.tree.robust_horizon):
            for grp in self.tree.groups_at(t):
                rows += (len(grp) - 1) * len(self.na_indices[t])
        return rows


def build_tree_partition(base: StagePartition, tree: ScenarioTree,
                         na_indices) -> TreePartition:
    """Validate + assemble a :class:`TreePartition`. ``na_indices``:
    one tuple of primal indices per robust stage (must lie below
    ``base.n_w``)."""
    tree.validate()
    na_indices = tuple(tuple(int(i) for i in idx) for idx in na_indices)
    if len(na_indices) != tree.robust_horizon:
        raise ValueError(
            f"na_indices covers {len(na_indices)} stages, tree couples "
            f"{tree.robust_horizon}")
    for t, idx in enumerate(na_indices):
        bad = [i for i in idx if not 0 <= i < base.n_w]
        if bad:
            raise ValueError(
                f"na_indices[{t}] contains non-primal indices {bad} "
                f"(n_w={base.n_w})")
    return TreePartition(base=base, tree=tree, na_indices=na_indices)


def tree_partition_for_ocp(ocp, tree: ScenarioTree) -> TreePartition:
    """Tree partition for a transcribed OCP: the OCP's stage partition
    per branch, with robust-stage controls located from the
    transcription's decision layout (u blocks lead the flattened
    pytree, ``ops/stagewise.build_stage_partition``)."""
    if ocp.stage_partition is None:
        raise ValueError(
            f"OCP {ocp.model.__class__.__name__} carries no stage "
            f"partition — transcribe() attaches one")
    tree.validate(ocp.N)
    n_u = len(ocp.control_names)
    na_indices = tuple(
        tuple(range(t * n_u, (t + 1) * n_u))
        for t in range(tree.robust_horizon))
    return build_tree_partition(ocp.stage_partition, tree, na_indices)


# --------------------------------------------------------------------------
# the non-anticipativity coupling layout (static numpy)
# --------------------------------------------------------------------------

def _coupling_layout(tp: TreePartition):
    """Static rows of the coupling matrix A (m, S·M-sparse): per row a
    (w-index, scenario, reference-scenario) pairwise pin. Returns
    ``(idx, s_pos, s_ref)`` int arrays of length ``m`` (empty for
    degenerate trees)."""
    idx, s_pos, s_ref = [], [], []
    for t in range(tp.tree.robust_horizon):
        for grp in tp.tree.groups_at(t):
            ref = grp[0]
            for s in grp[1:]:
                for i in tp.na_indices[t]:
                    idx.append(i)
                    s_pos.append(s)
                    s_ref.append(ref)
    return (np.asarray(idx, dtype=np.int64),
            np.asarray(s_pos, dtype=np.int64),
            np.asarray(s_ref, dtype=np.int64))


def _apply_A(x_batch: jnp.ndarray, layout) -> jnp.ndarray:
    """A @ x for the stacked per-scenario solution x (S, M): pairwise
    differences at the coupled coordinates."""
    idx, s_pos, s_ref = layout
    return x_batch[s_pos, idx] - x_batch[s_ref, idx]


def _apply_AT(nu: jnp.ndarray, layout, n_scenarios: int,
              n_total: int) -> jnp.ndarray:
    """Aᵀ @ ν scattered into a (S, M) right-hand-side stack."""
    idx, s_pos, s_ref = layout
    flat = jnp.zeros((n_scenarios * n_total,), nu.dtype)
    flat = flat.at[s_pos * n_total + idx].add(nu)
    flat = flat.at[s_ref * n_total + idx].add(-nu)
    return flat.reshape(n_scenarios, n_total)


# --------------------------------------------------------------------------
# tree factor / resolve (mirrors factor_kkt_stage / resolve_kkt_stage)
# --------------------------------------------------------------------------

def factor_kkt_tree(K_batch: jnp.ndarray, tp: TreePartition,
                    delta_c: float = 1e-8):
    """Factor the non-anticipativity-coupled tree KKT system

        [[blkdiag(K_s), Aᵀ], [A, -δ_c I]]

    given the per-scenario stacks ``K_batch`` (S, M, M): S independent
    stage sweeps (one vmap) plus the coupling Schur complement
    ``S_c = A K⁻¹ Aᵀ + δ_c I`` — SPD because A touches primal
    coordinates only and the primal block of a quasi-definite inverse
    is positive definite — factored dense once (``m`` is the thin
    coupling dimension, horizon- and scenario-local). Degenerate trees
    (1 scenario, or no coupled stages) skip the Schur complement
    entirely and the S=1 stack routes through the flat sweep bit for
    bit."""
    S = tp.n_scenarios
    if K_batch.shape[0] != S:
        raise ValueError(
            f"K_batch has {K_batch.shape[0]} scenarios, partition "
            f"describes {S}")
    F = factor_kkt_scenarios(K_batch, tp.base)
    layout = _coupling_layout(tp)
    m = layout[0].shape[0]
    if m == 0:
        return (F, None, None)
    # columns of K⁻¹ Aᵀ, via m coupled-unit-vector resolves against the
    # scenario-separable factors (each resolve is itself refined)
    def col(r):
        rhs = _apply_AT(jnp.zeros((m,), K_batch.dtype).at[r].set(1.0),
                        layout, S, tp.base.n_total)
        return resolve_kkt_scenarios(F, rhs, tp.base)

    KinvAT = jax.vmap(col)(jnp.arange(m))          # (m, S, M)
    Sc = jax.vmap(lambda X: _apply_A(X, layout))(KinvAT)   # (m, m)
    Sc = 0.5 * (Sc + Sc.T) + delta_c * jnp.eye(m, dtype=K_batch.dtype)
    Fc = kkt_ops.ldl_factor(Sc)
    return (F, Fc, KinvAT)


def resolve_kkt_tree(factor, rhs_batch: jnp.ndarray, tp: TreePartition,
                     refine_steps: int = 2) -> jnp.ndarray:
    """Solve the coupled tree system for a new right-hand-side stack
    (S, M) (coupling rows' rhs is 0 — the non-anticipativity target):
    block elimination through the stored factors,

        ν = S_c⁻¹ A K⁻¹ b,   x = K⁻¹ (b − Aᵀ ν).
    """
    F, Fc, _KinvAT = factor
    x = resolve_kkt_scenarios(F, rhs_batch, tp.base, refine_steps)
    if Fc is None:
        return x
    layout = _coupling_layout(tp)
    nu = kkt_ops.ldl_solve(Fc, _apply_A(x, layout))
    corr = _apply_AT(nu, layout, tp.n_scenarios, tp.base.n_total)
    return x - resolve_kkt_scenarios(F, corr, tp.base, refine_steps)


def solve_kkt_tree(K_batch: jnp.ndarray, rhs_batch: jnp.ndarray,
                   tp: TreePartition, refine_steps: int = 2,
                   delta_c: float = 1e-8) -> jnp.ndarray:
    """Factor + resolve in one call — the tree analogue of
    :func:`~agentlib_mpc_tpu.ops.stagewise.solve_kkt_stage`."""
    return resolve_kkt_tree(factor_kkt_tree(K_batch, tp, delta_c),
                            rhs_batch, tp, refine_steps)


def synthetic_tree_kkt(tp: TreePartition, seed: int = 0, dtype=None):
    """Per-scenario synthetic banded quasi-definite stacks (S, M, M) +
    right-hand sides (S, M) — the probe/benchmark workload; each branch
    draws its own seed so the batch is not a trivial broadcast."""
    Ks, rhs = [], []
    for s in range(tp.n_scenarios):
        K_s, r_s = synthetic_stage_kkt(tp.base, seed=seed + s,
                                       dtype=dtype)
        Ks.append(K_s)
        rhs.append(r_s)
    return np.stack(Ks), np.stack(rhs)


_TREE_PROBE: dict = {}


def tree_method_available(tp: TreePartition) -> bool:
    """Eager once-per-(backend, partition) probe of the coupled tree
    solve at the production shape — the safety net
    :func:`~agentlib_mpc_tpu.ops.stagewise.stage_method_available`
    provides for the flat sweep, extended to the coupling Schur path.
    Checks the residual of the FULL coupled system, non-anticipativity
    rows included."""
    key = (jax.default_backend(), tp)
    if key in _TREE_PROBE:
        return _TREE_PROBE[key]
    try:
        K, rhs = synthetic_tree_kkt(tp)
        layout = _coupling_layout(tp)
        # at the coupled coordinates the residual K x − b equals −Aᵀν
        # by construction (the coupling force) — check the K-residual
        # OFF them, and the constraint A x = 0 ON them
        coupled = np.zeros(rhs.shape, dtype=bool)
        if layout[0].shape[0]:
            idx, s_pos, s_ref = layout
            coupled[s_pos, idx] = True
            coupled[s_ref, idx] = True

        def _probe():
            Kj = jnp.asarray(K)
            rj = jnp.asarray(rhs)
            x = solve_kkt_tree(Kj, rj, tp)
            r = jnp.einsum("sij,sj->si", Kj, x, precision=_HI) - rj
            res = jnp.max(jnp.abs(
                jnp.where(jnp.asarray(coupled), 0.0, r)))
            if layout[0].shape[0]:
                res = jnp.maximum(res, jnp.max(jnp.abs(
                    _apply_A(x, layout))))
            return bool(jnp.isfinite(res) and res < 1e-3)  # lint: ignore[jit-host-sync]

        ok = kkt_ops.run_probe_outside_trace(_probe)
    except Exception:  # noqa: BLE001 — any compile/runtime failure
        ok = False
    _TREE_PROBE[key] = ok
    return ok


# --------------------------------------------------------------------------
# extended structure certification (the PR 5 authority pattern)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TreeStructureCertificate:
    """The stage-structure certificate extended to a scenario tree: the
    branches share ONE traced structure (branch data is theta, not
    structure), so one flat certification answers for every branch; the
    tree fields record what that proof now covers. ``ok`` gates the
    tree-banded derivative/KKT path exactly like the flat certificate
    gates the flat one — refuted or unknown structure routes every
    branch dense, loudly."""

    base: "object"                 # lint.jaxpr.structure.StructureCertificate
    n_scenarios: int
    robust_horizon: int
    n_coupling_rows: int

    @property
    def ok(self) -> bool:
        return bool(self.base.ok)

    def describe(self) -> str:
        return (f"{self.base.describe()} x {self.n_scenarios} "
                f"scenario branch(es), {self.n_coupling_rows} "
                f"non-anticipativity row(s) over "
                f"{self.robust_horizon} robust stage(s)")


def certify_tree_structure(nlp, theta, n_w: int,
                           tp: TreePartition) -> TreeStructureCertificate:
    """Prove the per-branch KKT structure once for the whole tree (the
    branches share the traced functions; scenario data rides theta).
    The coupling rows need no proof — their layout is constructed
    static selector rows, banded by inspection."""
    from agentlib_mpc_tpu.lint.jaxpr import certify_stage_structure

    base = certify_stage_structure(nlp, theta, n_w, tp.base)
    return TreeStructureCertificate(
        base=base, n_scenarios=tp.n_scenarios,
        robust_horizon=tp.tree.robust_horizon,
        n_coupling_rows=tp.n_coupling_rows)
