"""Tests for the declarative model layer (variables, equations, objective)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agentlib_mpc_tpu.models.model import Model, ModelEquations
from agentlib_mpc_tpu.models.objective import (
    ChangePenaltyObjective,
    CombinedObjective,
    ConditionalObjective,
    SubObjective,
)
from agentlib_mpc_tpu.models.variables import (
    control_input,
    output,
    parameter,
    state,
)


class OneRoom(Model):
    """Single-zone cooling model with the same physics as the reference
    example (examples/one_room_mpc/physical/simple_mpc.py:95-138)."""

    inputs = [
        control_input("mDot", 0.0225, lb=0.0, ub=0.05),
        control_input("load", 150.0),
        control_input("T_in", 290.15),
        control_input("T_upper", 294.15),
    ]
    states = [state("T", 293.15), state("T_slack", 0.0)]
    parameters = [
        parameter("cp", 1000.0),
        parameter("C", 100000.0),
        parameter("s_T", 1.0),
        parameter("r_mDot", 1.0),
    ]
    outputs = [output("T_out")]

    def setup(self, v):
        eq = ModelEquations()
        eq.ode("T", v.cp * v.mDot / v.C * (v.T_in - v.T) + v.load / v.C)
        eq.alg("T_out", v.T)
        eq.constraint(0.0, v.T + v.T_slack, v.T_upper)
        eq.objective = (
            SubObjective(v.mDot, weight=v.r_mDot, name="control_costs")
            + SubObjective(v.T_slack**2, weight=v.s_T, name="temp_slack")
        )
        return eq


@pytest.fixture(scope="module")
def model():
    return OneRoom(overrides={"s_T": 0.001, "r_mDot": 0.01})


def test_structure(model):
    assert model.diff_state_names == ["T"]
    assert model.free_state_names == ["T_slack"]
    assert model.n_constraints == 1
    assert model.objective_term_names == ["control_costs", "temp_slack"]


def test_overrides(model):
    assert model.get_var("s_T").value == 0.001
    # class defaults untouched
    assert OneRoom().get_var("s_T").value == 1.0


def test_ode_value(model):
    x = jnp.array([298.16])
    z = jnp.array([0.0])
    u = model.default_vector("inputs")
    p = model.default_vector("parameters")
    dT = model.ode(x, z, u, p)
    expected = 1000.0 * 0.0225 / 1e5 * (290.15 - 298.16) + 150.0 / 1e5
    np.testing.assert_allclose(dT, [expected], rtol=1e-6)


def test_constraint_residuals_two_sided(model):
    x = jnp.array([298.16])
    z = jnp.array([0.0])
    u = model.default_vector("inputs")
    p = model.default_vector("parameters")
    res = model.constraint_residuals(x, z, u, p)
    # (expr - lb, ub - expr) with expr = T + slack = 298.16, ub = 294.15
    np.testing.assert_allclose(res, [298.16, 294.15 - 298.16], rtol=1e-6)


def test_output_rebinding():
    """Constraints referencing an *output* must see its algebraic value,
    not the declared default (two-pass bind)."""

    class M(Model):
        inputs = [control_input("u", 1.0)]
        states = [state("x", 2.0)]
        outputs = [output("y", value=-99.0)]

        def setup(self, v):
            eq = ModelEquations()
            eq.ode("x", -v.x + v.u)
            eq.alg("y", 3.0 * v.x)
            eq.constraint(0.0, v.y, 10.0)  # references the output
            return eq

    m = M()
    res = m.constraint_residuals(jnp.array([2.0]), jnp.zeros(0),
                                 jnp.array([1.0]), jnp.zeros(0))
    np.testing.assert_allclose(res, [6.0, 4.0], rtol=1e-6)


def test_simulation_cools_with_flow(model):
    u = model.default_vector("inputs")
    u = u.at[model.input_index("mDot")].set(0.05)
    p = model.default_vector("parameters")
    x0 = jnp.array([300.0])
    x1, y = model.simulate_step(x0, u, p, dt=600.0)
    assert float(x1[0]) < 300.0  # inflow at 290 K cools the zone
    np.testing.assert_allclose(y, x1, rtol=1e-6)


def test_simulation_matches_analytic(model):
    """Linear single-state ODE has a closed form; RK4 must track it."""
    u = model.default_vector("inputs")
    p = model.default_vector("parameters")
    mdot, load, t_in = 0.0225, 150.0, 290.15
    k = 1000.0 * mdot / 1e5
    x0 = 298.16
    dt = 300.0
    x_inf = t_in + load / (1000.0 * mdot)
    expected = x_inf + (x0 - x_inf) * np.exp(-k * dt)
    x1, _ = model.simulate_step(jnp.array([x0]), u, p, dt=dt, substeps=20)
    np.testing.assert_allclose(x1[0], expected, rtol=1e-8)


def test_duplicate_names_rejected():
    class Bad(Model):
        inputs = [control_input("a")]
        states = [state("a")]

        def setup(self, v):
            return ModelEquations()

    with pytest.raises(ValueError, match="duplicate"):
        Bad()


def test_unknown_override_rejected(model):
    with pytest.raises(KeyError):
        OneRoom(overrides={"nope": 1.0})


def test_objective_algebra():
    a = SubObjective(2.0, weight=3.0, name="a")  # 6
    b = SubObjective([1.0, 2.0], weight=0.5, name="b")  # 1.5
    combined = a + b
    np.testing.assert_allclose(combined.value(), 7.5)
    np.testing.assert_allclose((combined * 2.0).value(), 15.0)
    terms = combined.term_values()
    np.testing.assert_allclose(terms["a"], 6.0)
    np.testing.assert_allclose(terms["b"], 1.5)
    norm = CombinedObjective(a, b, normalization=3.0)
    np.testing.assert_allclose(norm.value(), 2.5)


def test_change_penalty_and_conditional():
    cp = ChangePenaltyObjective(du=2.0, weight=0.5)
    np.testing.assert_allclose(cp.value(), 2.0)
    cond = ConditionalObjective(jnp.asarray(True), SubObjective(5.0),
                                SubObjective(1.0))
    np.testing.assert_allclose(cond.value(), 5.0)
    cond2 = ConditionalObjective(jnp.asarray(False), SubObjective(5.0),
                                 SubObjective(1.0))
    np.testing.assert_allclose(cond2.value(), 1.0)


def test_model_is_jit_and_grad_safe(model):
    x = jnp.array([298.16])
    z = jnp.array([0.0])
    u = model.default_vector("inputs")
    p = model.default_vector("parameters")
    jitted = jax.jit(lambda xx: model.ode(xx, z, u, p))
    np.testing.assert_allclose(jitted(x), model.ode(x, z, u, p))
    grad = jax.grad(lambda uu: model.stage_cost(x, z, uu, p))(u)
    # d(cost)/d(mDot) = r_mDot (fixture override 0.01)
    assert float(grad[model.input_index("mDot")]) == pytest.approx(0.01)


def test_chained_output_references_resolve():
    """Outputs referencing other outputs must see final values (review
    regression: one-pass rebinding truncated chains)."""

    class Chained(Model):
        inputs = [control_input("u", 1.0)]
        states = [state("x", 1.0)]
        outputs = [output("A"), output("B"), output("C")]

        def setup(self, v):
            eq = ModelEquations()
            eq.ode("x", -v.x)
            eq.alg("A", 2.0 * v.x)
            eq.alg("B", v.A + 1.0)
            eq.alg("C", v.B * 3.0)
            eq.constraint(0.0, v.B, 10.0)
            return eq

    m = Chained()
    y = m.output(jnp.array([1.0]), jnp.zeros(0), jnp.array([1.0]), jnp.zeros(0))
    np.testing.assert_allclose(y, [2.0, 3.0, 9.0])
    res = m.constraint_residuals(jnp.array([1.0]), jnp.zeros(0),
                                 jnp.array([1.0]), jnp.zeros(0))
    np.testing.assert_allclose(res, [3.0, 7.0])
