"""Unified telemetry: metrics registry + span tracing + JAX compile hooks.

The one import every instrumentation site uses::

    from agentlib_mpc_tpu import telemetry

    telemetry.counter("broker_messages_total").inc(agent="room_1")
    with telemetry.span("backend.solve", backend="JAXBackend"):
        ...jit dispatch...
    telemetry.metrics().prometheus_text()     # scrape payload
    telemetry.metrics().write_jsonl(path)     # artifact export

Layout:

- :mod:`.registry` — :class:`MetricsRegistry` (counters / gauges /
  fixed-bucket histograms, labels, Prometheus text + JSONL export) and the
  process-global :data:`~agentlib_mpc_tpu.telemetry.registry.DEFAULT`
- :mod:`.spans` — ``span(name, **labels)`` context manager + ring-buffer
  :class:`SpanRecorder`
- :mod:`.jax_events` — ``jax.monitoring`` listeners turning XLA
  compiles/retraces into metrics (installed via
  :func:`agentlib_mpc_tpu.utils.jax_setup.enable_compile_profiling`)
- :mod:`.profiler` / :mod:`.calibration` / :mod:`.regression` — the
  performance observatory (ISSUE 16): named-phase device profiles,
  certificate-calibrated cost ledgers, per-phase regression baselines
  (``bench.py --perf-gate``)

Enablement is process-global and ON by default (counters are ~100 ns;
spans a few µs). ``telemetry.configure(enabled=False)`` turns every write
into a near-zero no-op — the mode the latency-critical fleets run in, and
what the ``telemetry-overhead`` tier-1 test pins. See ``docs/telemetry.md``.
"""

from __future__ import annotations

from agentlib_mpc_tpu.telemetry.registry import (
    DEFAULT,
    ITERATION_BUCKETS,
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from agentlib_mpc_tpu.telemetry.spans import (
    NOOP_SPAN,
    RECORDER,
    SpanRecord,
    SpanRecorder,
    current_span,
    span,
)
from agentlib_mpc_tpu.telemetry import journal as _journal_mod

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "ITERATION_BUCKETS", "LATENCY_BUCKETS_S",
    "SpanRecord", "SpanRecorder", "NOOP_SPAN",
    "metrics", "recorder", "span", "current_span",
    "configure", "enabled", "counter", "gauge", "histogram",
    "solver_metrics", "serving_metrics", "install_jax_hooks",
    "record_device_memory", "reset",
    "enable_journal", "disable_journal", "journal_active",
    "journal_event", "journal_set_round", "serve_metrics",
    "PhaseProfile", "PeriodicCapture", "capture_phase_profile",
    "phase_scope",
]

from agentlib_mpc_tpu.telemetry.profiler import (  # noqa: E402
    PeriodicCapture,
    PhaseProfile,
    capture_phase_profile,
    phase_scope,
)


def metrics() -> MetricsRegistry:
    """The process-global registry."""
    return DEFAULT


def recorder() -> SpanRecorder:
    """The process-global span ring buffer."""
    return RECORDER


def enabled() -> bool:
    return DEFAULT.enabled


def configure(enabled: bool) -> None:
    """Turn all telemetry writes on/off process-wide (metrics AND spans)."""
    DEFAULT.configure(enabled)


def counter(name: str, help: str = "") -> Counter:
    return DEFAULT.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return DEFAULT.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets=LATENCY_BUCKETS_S) -> Histogram:
    return DEFAULT.histogram(name, help, buckets=buckets)


def solver_metrics(registry: "MetricsRegistry | None" = None) -> dict:
    """The shared solver metric families — ONE declaration site (names,
    help text, buckets) used by both the backend base class
    (``OptimizationBackend._record_solve``) and the host-side helper
    :func:`agentlib_mpc_tpu.ops.solver.record_solver_stats`, so the two
    writers can never drift apart. Keys: solves, failures, iterations,
    solve_seconds, kkt_error."""
    reg = registry or DEFAULT
    return {
        "solves": reg.counter(
            "solver_solves_total", "backend solve() calls"),
        "failures": reg.counter(
            "solver_failures_total",
            "backend solve() calls whose solver did not reach an "
            "acceptable point"),
        "iterations": reg.histogram(
            "solver_iterations", "interior-point iterations per solve",
            buckets=ITERATION_BUCKETS),
        "solve_seconds": reg.histogram(
            "solver_solve_seconds", "wall-clock seconds per backend solve"),
        "kkt_error": reg.gauge(
            "solver_kkt_error", "KKT error of the most recent solve"),
    }


def serving_metrics(registry: "MetricsRegistry | None" = None) -> dict:
    """The serving-plane metric families — one declaration site shared
    by the dispatch plane (``agentlib_mpc_tpu/serving/``) and the
    ``bench.py --serve`` artifact, like :func:`solver_metrics` for the
    solver. Keys: requests, rounds, solves, active, queue_depth,
    round_seconds. ``serving_solves_total`` is labelled by the guard
    ``action`` (actuate/replay/hold/fallback) so availability —
    actuated ÷ delivered — is computable from telemetry alone. (The
    cache, admission and survivability layers declare their own
    families at their write sites: ``serving_compile_cache_*``,
    ``serving_cache_evictions_total``, ``serving_shed_total``,
    ``serving_join_build_seconds``, ``serving_health_state``,
    ``serving_evictions_total``, ``serving_readmissions_total``,
    ``serving_watchdog_stalls_total``,
    ``serving_watchdog_probes_total``.)"""
    reg = registry or DEFAULT
    return {
        "requests": reg.counter(
            "serving_requests_total",
            "solve requests submitted to the serving plane"),
        "rounds": reg.counter(
            "serving_rounds_total", "fused rounds dispatched"),
        "solves": reg.counter(
            "serving_solves_total", "per-tenant solve results delivered"),
        "active": reg.gauge(
            "serving_active_tenants", "admitted tenants per bucket"),
        "queue_depth": reg.gauge(
            "serving_queue_depth",
            "pending solve requests at last drain"),
        "round_seconds": reg.histogram(
            "serving_round_seconds",
            "wall-clock seconds per serve_round call"),
    }


def record_device_memory(registry: "MetricsRegistry | None" = None
                         ) -> None:
    """Sample ``device.memory_stats()`` of every local device into the
    ``device_memory_bytes_in_use`` gauge (labelled ``device=<id>``).

    Guarded: backends that report no memory stats (CPU returns None)
    write nothing — the gauge simply stays absent there, which is how
    dashboards distinguish "no accelerator" from "0 bytes". Called at
    engine build and per recorded round next to the statically
    certified ``memory_certified_peak_bytes`` gauge, so the proved
    ceiling and the measured residency sit side by side."""
    reg = registry or DEFAULT
    if not reg.enabled:
        return
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend init races / no jax
        return
    samples = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device API variance
            stats = None
        if not stats:
            continue
        used = stats.get("bytes_in_use")
        if used is not None:
            samples.append((str(d.id), float(used)))
    if not samples:
        # declare nothing: the documented contract is that the FAMILY
        # is absent on backends that report no memory — dashboards key
        # "no accelerator" on absence, which an empty declared family
        # in the exports would break
        return
    gauge = reg.gauge(
        "device_memory_bytes_in_use",
        "bytes currently allocated on each local accelerator device "
        "(from device.memory_stats(); absent on backends that do not "
        "report memory, e.g. CPU)")
    for dev_id, used in samples:
        gauge.set(used, device=dev_id)


def install_jax_hooks(registry: "MetricsRegistry | None" = None
                      ) -> MetricsRegistry:
    """Install the compile/retrace listeners (idempotent; lazy jax import).
    Prefer :func:`agentlib_mpc_tpu.utils.jax_setup.enable_compile_profiling`
    which also documents the platform story."""
    from agentlib_mpc_tpu.telemetry import jax_events

    return jax_events.install(registry)


def reset() -> None:
    """Clear all recorded samples, spans and retrace scopes (declared
    metric families survive; the flight-recorder journal does too — a
    tape that ``reset()`` could wipe would not be a flight recorder).
    Test-isolation / between-runs helper."""
    DEFAULT.reset()
    RECORDER.clear()
    from agentlib_mpc_tpu.telemetry import jax_events

    jax_events.reset_scopes()


# -- flight recorder (ISSUE 15) ----------------------------------------------


def enable_journal(path: str, **kwargs):
    """Install the process-global flight-recorder journal at ``path``
    (:mod:`agentlib_mpc_tpu.telemetry.journal` for the durability
    contract). Every built-in fault/recovery seam starts recording."""
    return _journal_mod.enable(path, **kwargs)


def disable_journal() -> None:
    """Close and uninstall the global journal (the file survives)."""
    _journal_mod.disable()


def journal_active():
    """The global :class:`~agentlib_mpc_tpu.telemetry.journal.Journal`,
    or None when journaling is off."""
    return _journal_mod.active()


def journal_event(etype: str, **fields) -> "int | None":
    """Record one typed event into the global journal (no-op when
    journaling is off) — the one call every emit site uses."""
    if _journal_mod._GLOBAL is None:       # the disabled fast path
        return None
    return _journal_mod.record(etype, **fields)


def journal_set_round(round_: "int | None") -> None:
    """Stamp subsequent journal events with this control round."""
    _journal_mod.set_round(round_)


def serve_metrics(port: int = 0, registry: "MetricsRegistry | None" = None,
                  host: str = "127.0.0.1"):
    """Start the Prometheus scrape endpoint (``/metrics`` on a stdlib
    http.server thread); returns a
    :class:`~agentlib_mpc_tpu.telemetry.scrape.MetricsServer` —
    ``.port`` for the bound port, ``.close()`` for clean shutdown."""
    from agentlib_mpc_tpu.telemetry.scrape import (
        serve_metrics as _serve,
    )

    return _serve(port=port, registry=registry, host=host)
