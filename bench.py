"""Benchmark: consensus-ADMM MPC fleets, wall-clock per control step.

The BASELINE.json north-star metric: "ADMM-MPC wall-clock per control step;
agents/sec scaling 4->256 zones". One control step = `ADMM_ITERS` fused
consensus-ADMM iterations, each iteration = vmapped per-zone interior-point
NLP solves + consensus mean + scaled-dual update, all inside one jitted XLA
computation (the TPU-native replacement for the reference's coordinator
round driving one IPOPT process per zone, ``admm_coordinator.py:259-321``).
On TPU the per-iteration KKT systems factor in the lanes-batched Pallas
LDLᵀ kernel (``agentlib_mpc_tpu/ops/kkt.py``).

The reference itself cannot run here (CasADi/IPOPT not installed, zero
egress) and publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
measured speedup of the default platform (TPU under the driver) over the
same workload forced onto host CPU — a conservative stand-in: the CPU run
uses the same fused XLA path, which is already far faster than 256
sequential CasADi+IPOPT processes.

Modes:
    python bench.py             # the driver artifact: ONE final JSON line.
                                # On an accelerator it embeds the whole
                                # evidence matrix (headline, LDL-vs-LU
                                # micro at the production KKT tile, knob
                                # A/Bs, QP-fast-path A/B, scaling curve
                                # to 1024 zones) under "evidence"; on CPU
                                # fallback, headline only.
    python bench.py --evidence  # the matrix alone, one JSON per section
    python bench.py --scaling   # 4/16/64/256(/1024)-zone curve
    python bench.py --ab        # A/B the solver latency knobs on hardware
    python bench.py --qp-ab     # QP fast path vs IPM on the linear fleet
    python bench.py --ldl       # LDLᵀ-vs-LU micro at the 256-lane KKT tile
    python bench.py --horizon-shard  # single-agent horizon-sharding
                                # work-split experiment (SURVEY §5;
                                # provisions an 8-virtual-device mesh)
    python bench.py --ocp-ab [N]     # dense-vs-stage-structured KKT
                                # factorization A/B at horizons
                                # N=32/128/256 (the fatrop role,
                                # ops/stagewise.py); optional single N
    python bench.py --jac-ab [N]     # stage-sparse vs dense derivative
                                # pipeline A/B (eval+jac, Hessian, warm
                                # solve, per-agent working set) at the
                                # same horizons (ops/stagejac.py)
    python bench.py --mesh-ab [zones]   # sharded-vs-single-device A/B
                                # of the fused fleet: the shard_map
                                # agent-mesh engine (psum consensus) vs
                                # the single-device vmap at 256/1024
                                # zones (optional single size) on an
                                # 8-device mesh (virtual on CPU) —
                                # per-zone step cost + consensus
                                # identity; keys carry a d<n> qualifier
    python bench.py --scenario-ab [S] [n]   # batched-S vs serial-S
                                # scenario-tree robust A/B: one fused
                                # ScenarioFleet round (vmapped scenario
                                # axis, non-anticipativity on u0) vs S
                                # branch-at-a-time rounds (the reference
                                # pattern); identity-gated, keys carry
                                # platform + d<n> qualifiers
    python bench.py --fusion-ab [n] [r]  # fused-vs-staged IPM dispatch
                                # A/B (ISSUE 18): the same consensus
                                # fleet with SolverOptions.fusion
                                # "require" (one device program per
                                # round, certified) vs "off" (stage
                                # boundaries materialized) — warm round
                                # cost + the analytic FusionPlan;
                                # bitwise identity-gated, keys carry
                                # platform + d<n> qualifiers
    python bench.py --warmstart-ab [n]  # learned warm starts A/B
    python bench.py --precision-ab [n]  # certified mixed precision A/B
                                # (ISSUE 19): trains a fingerprint-
                                # stamped predictor from plain solves
                                # of an offset theta grid, then
                                # publishes cold-IP-iteration,
                                # equal-budget consensus-spread and
                                # warm-budget-1-vs-plain-budget-2 rows
                                # on the n-zone (default 256) workload;
                                # identity-gated, platform-independent
                                # *_iters keys (docs/ml.md)
    python bench.py --profile [dir] [n]   # XLA profiler trace of the
                                # warm n-zone step (default 256;
                                # --profile DIR 1024 = the sub-linearity
                                # attribution run)
    python bench.py --sequential [n]    # architecture baseline: SAME
                                # solver driven one-call-per-zone like the
                                # reference coordinator (BASELINE.md
                                # "Architecture decomposition")
    python bench.py --conventional [n]  # independent-solver baseline:
                                # sequential per-zone SciPy SLSQP
    python bench.py --emit-metrics PATH [n]   # telemetry-instrumented
                                # run: writes a phase-breakdown artifact
                                # (compile/trace/retrace counts + seconds
                                # per entry point, solver-iterations
                                # histogram, per-ADMM-iteration residual
                                # gauges, span aggregates, full metrics
                                # snapshot) — see docs/telemetry.md
    python bench.py --chaos SEED [n]    # resilience smoke: the n-zone
                                # (default 4) fused consensus fleet with
                                # one seeded agent's theta NaN-poisoned —
                                # asserts the quarantine keeps consensus
                                # state/warm starts finite end-to-end
                                # (docs/robustness.md); ONE JSON line
    python bench.py --serve SEED [n]    # serving-plane sustained-
                                # throughput benchmark: n (default 8)
                                # LinearRCZone tenants churn through the
                                # dispatch plane (seeded join/leave,
                                # per-round solve requests) — solves/sec,
                                # p50/p99 round latency, sync-vs-
                                # pipelined dispatch A/B, cold-vs-cached
                                # join latency (docs/serving.md)
    python bench.py --chaos-serve SEED [n]   # serving survivability:
                                # n (default 6) tenants across 2 buckets
                                # under a seeded fault schedule (tenant
                                # NaN storm, dispatcher stall, process
                                # crash + checkpoint restore) —
                                # availability %, shed rate, eviction/
                                # readmission counts, crash-restart MTTR
                                # (docs/serving.md "Surviving failures")
    python bench.py --chaos-autopilot SEED [n]  # SLO-autopilot A/B
                                # (ISSUE 17): n (default 8) tenants
                                # through the SAME seeded overload
                                # storm twice — uncontrolled vs
                                # autopilot-controlled; asserts the
                                # controlled plane holds the
                                # availability SLO the uncontrolled one
                                # breaches, every quality-ladder move
                                # is journaled, and the incident CLI
                                # joins storm -> down-move -> up-move;
                                # the controlled number publishes under
                                # _q<level> (docs/serving.md
                                # "SLO autopilot")
    python bench.py --chaos-mesh SEED [n]    # SHARDED-fleet
                                # survivability: n (default 8) trackers
                                # under a FleetSupervisor on the
                                # 8-virtual-device mesh with a seeded
                                # shard NaN storm, collective stall and
                                # device loss + revival — availability
                                # %, degraded-mode rounds, shard-loss
                                # MTTR, and a CHILD-process checkpoint
                                # restore against the engine store =
                                # real cross-process restart MTTR
                                # (docs/robustness.md "Surviving shard
                                # loss"); degraded rounds publish
                                # _d<k>_degraded keys, never the
                                # full-mesh headline
    python bench.py --chaos-scenario SEED [S] [n]  # 2-D robust-fleet
                                # survivability (ISSUE 14): n trackers
                                # x S disturbance branches under a
                                # ScenarioFleetSupervisor on the 4x2
                                # virtual grid, seeded branch NaN
                                # storm, stall, and device loss +
                                # revival on EACH axis — availability,
                                # per-axis shard-loss MTTR, degraded
                                # rounds; degraded rounds publish
                                # _d<A>x<S>_degraded at their reduced
                                # shape, never the full-grid key
                                # (docs/robustness.md "Surviving loss
                                # on either axis")

Headline JSON:
    {"metric": "admm256_step_ms", "value": <ms>, "unit": "ms",
     "vs_baseline": <cpu_ms / this_ms>}
(The unqualified metric name is reserved for TPU measurements; any
other platform publishes as ``admm256_step_ms_<platform>`` so the BENCH
trajectory never mixes platforms.)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_AGENTS = 256
HORIZON = 10
ADMM_ITERS = 10
DT = 300.0
SCALING_SIZES = (4, 16, 64, 256)

# ONE definition of the solver configuration and inner-budget schedule,
# shared by the fused program (build_step) and the sequential
# architecture baseline (run_sequential_native) — the A/B is only valid
# while both run the identical solver setup. Values from the round-3/4
# sweeps (PERF.md): Mehrotra corrector ON, cold 10 / warm 1, barrier
# 0.1 cold / 1e-2 warm.
SOLVER_BASE = {"tol": 1e-4, "max_iter": 10, "corrector": True}
COLD_BUDGET, WARM_BUDGET = 10, 1
COLD_MU, WARM_MU = 0.1, 1e-2
ZONE_X0_RANGE = (294.0, 300.0)
ZONE_LOAD_RANGE = (80.0, 250.0)


def fleet_inputs(n_agents: int):
    """Per-zone initial temperatures and loads (the heterogeneity axis)."""
    import numpy as np

    return (np.linspace(*ZONE_X0_RANGE, n_agents),
            np.linspace(*ZONE_LOAD_RANGE, n_agents))


def zone_ocp():
    """The per-zone OCP every bench mode solves (61-var collocation NLP)."""
    from agentlib_mpc_tpu.models.zoo import ZoneWithSupply
    from agentlib_mpc_tpu.ops.transcription import transcribe

    return transcribe(ZoneWithSupply(), ["mDot"], N=HORIZON, dt=DT,
                      method="collocation", collocation_degree=2)


def linear_zone_ocp():
    """LQ per-zone OCP (LinearRCZone: power-actuated 1R1C) — the linear-MPC
    workload the QP fast path serves (``ops/qp.py``)."""
    from agentlib_mpc_tpu.models.zoo import LinearRCZone
    from agentlib_mpc_tpu.ops.transcription import transcribe

    return transcribe(LinearRCZone(), ["Q"], N=HORIZON, dt=DT,
                      method="collocation", collocation_degree=2)


#: per-model fleet knobs: (ocp factory, disturbance row builder, initial
#: consensus value, penalty on the coupling's physical scale)
_MODELS = {
    "zone": (zone_ocp, lambda load: [load, 290.15, 294.15], 0.02, 20.0),
    "linear": (linear_zone_ocp, lambda load: [load, 303.15, 295.15],
               100.0, 5e-3),
}


def build_step(n_agents: int = N_AGENTS, solver_overrides: dict | None = None,
               warm_budget: int = WARM_BUDGET,
               cold_budget: int = COLD_BUDGET,
               model: str = "zone", inner: str = "nlp",
               record_stats: bool = False):
    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()

    from agentlib_mpc_tpu.ops.solver import (
        NLPFunctions,
        SolverOptions,
        solve_nlp,
    )

    ocp_fn, d_row, zbar0, rho0 = _MODELS[model]
    ocp = ocp_fn()
    if inner == "qp":
        from agentlib_mpc_tpu.ops.qp import solve_qp as inner_solve
    else:
        inner_solve = solve_nlp

    def f_aug(w, theta):
        ocp_theta, zbar, lam, rho = theta
        u = ocp.unflatten(w)["u"]
        return ocp.nlp.f(w, ocp_theta) + \
            0.5 * rho * jnp.sum((u - zbar + lam) ** 2)

    nlp = NLPFunctions(f=f_aug, g=lambda w, th: ocp.nlp.g(w, th[0]),
                       h=lambda w, th: ocp.nlp.h(w, th[0]))

    # two-phase inexact ADMM: the first (cold) iteration gets the full
    # interior-point budget; subsequent iterations are warm-started in
    # primal, duals AND barrier, so a short budget suffices — in a vmapped
    # while_loop wall time is the slowest lane's iteration count, so the
    # budget is the lever (measured 2.4x on this workload at equal final
    # consensus error). The budget is a TRACED scalar (solve_nlp max_iter
    # override), so the cold and warm phases share one solver trace — the
    # Python-tracing floor of this program was 2 solver traces ≈ 7 s.
    # The Mehrotra corrector is ON for this workload (round-4 A/B,
    # PERF.md "Corrector in the warm phase"): its second back-substitution
    # per iteration buys warm budget 1 at equal-or-better consensus
    # spread — a 32% cut in sequential inner iterations per control step.
    base_opts = dict(SOLVER_BASE)
    base_opts.update(solver_overrides or {})
    opts = SolverOptions(**base_opts)

    def local_solve(x0, load, w_guess, y_guess, z_guess, mu0, budget,
                    zbar, lam, rho):
        theta = ocp.default_params(
            x0=x0, d_traj=jnp.broadcast_to(
                jnp.stack([load, jnp.asarray(d_row(0.0)[1]),
                           jnp.asarray(d_row(0.0)[2])]), (HORIZON, 3)))
        lb, ub = ocp.bounds(theta)
        res = inner_solve(nlp, w_guess, (theta, zbar, lam, rho), lb, ub,
                          opts, y0=y_guess, z0=z_guess, mu0=mu0,
                          max_iter=budget)
        # solver stats ride along for --emit-metrics; XLA dead-code-
        # eliminates the outputs when the caller drops them
        return (res.w, res.y, res.z, ocp.unflatten(res.w)["u"],
                res.stats.iterations, res.stats.success,
                res.stats.kkt_error)

    vsolve = jax.vmap(local_solve,
                      in_axes=(0, 0, 0, 0, 0, None, None, None, 0, None))

    # budgets swept on this workload (warm steady state, final consensus
    # spread max|u - zbar| as the equal-quality gate). r3 (no corrector):
    #   10/3: 37 inner iters, spread 0.01147   10/2: 28, 0.01137
    #    8/2: 26, 0.01136                      12/1: 21, 0.01171
    # r4 (64 zones): corrector+10/1: 19 iters, spread 0.00873 beats
    # plain 10/2 (28 iters, 0.00902); plain 10/1 degrades (0.01059).
    # → cold=10 / warm=1 with the corrector (see PERF.md).
    # All ADMM_ITERS iterations run in ONE scan whose per-iteration
    # (budget, mu0) are scanned-over values — a single solver call site
    # means a single solver trace (the jit trace cache is trace-context-
    # sensitive, so a separate cold call outside the loop would trace the
    # whole interior-point method twice).
    budgets = jnp.full((ADMM_ITERS,), warm_budget).at[0].set(cold_budget)
    mu0s = jnp.full((ADMM_ITERS,), WARM_MU).at[0].set(COLD_MU)

    def control_step(x0s, loads, w_gs, y_gs, z_gs, zbar, lams, rho):
        def admm_iter(carry, x):
            budget, mu0 = x
            w_gs, y_gs, z_gs, zbar, lams = carry
            w_gs, y_gs, z_gs, u, iters, ok, kkt = vsolve(
                x0s, loads, w_gs, y_gs, z_gs, mu0, budget, zbar, lams, rho)
            zbar_new = jnp.mean(u, axis=0)
            lams_new = lams + (u - zbar_new)
            if record_stats:
                # Boyd residuals of this iteration (the same quantities
                # ops/admm.consensus_update reports in the fused engine)
                ys = (jnp.linalg.norm((u - zbar_new).reshape(-1)),
                      jnp.linalg.norm((rho * (zbar_new - zbar)).reshape(-1)),
                      iters, ok, kkt)
            else:
                ys = None
            return (w_gs, y_gs, z_gs, zbar_new, lams_new), ys

        carry, stats = jax.lax.scan(admm_iter,
                                    (w_gs, y_gs, z_gs, zbar, lams),
                                    (budgets, mu0s))
        # stats: (prim (I,), dual (I,), iters/ok/kkt (I, n_agents)) when
        # record_stats, else None — default callers get the carry alone so
        # measure()/warm_step() layouts are unchanged
        return (carry, stats) if record_stats else carry

    theta0 = ocp.default_params()
    x0s_np, loads_np = fleet_inputs(n_agents)
    x0s = jnp.asarray(x0s_np).reshape(n_agents, 1)
    loads = jnp.asarray(loads_np)
    w_gs = jnp.broadcast_to(ocp.initial_guess(theta0), (n_agents, ocp.n_w))
    y_gs = jnp.zeros((n_agents, ocp.n_g))
    z_gs = jnp.full((n_agents, ocp.n_h), 0.1)
    zbar = jnp.full((HORIZON, 1), zbar0)
    lams = jnp.zeros((n_agents, HORIZON, 1))
    rho = jnp.asarray(rho0)
    args = (x0s, loads, w_gs, y_gs, z_gs, zbar, lams, rho)
    return jax.jit(control_step), args


def warm_step(step, args, out):
    """Re-invoke the compiled control step warm-started from its own
    outputs (carry: w, y, z, zbar, lams) with the original problem data
    (x0s, loads, rho) — the closed-loop steady-state regime. The ONE
    place that knows build_step's positional layout."""
    return step(args[0], args[1], out[0], out[1], out[2], out[3],
                out[4], args[7])


def measure(n_agents: int = N_AGENTS,
            solver_overrides: dict | None = None,
            warm_budget: int = WARM_BUDGET,
            model: str = "zone", inner: str = "nlp") -> dict:
    import jax

    step, args = build_step(n_agents, solver_overrides, warm_budget,
                            model=model, inner=inner)
    t0 = time.perf_counter()
    out = step(*args)
    jax.block_until_ready(out)
    compile_ms = 1e3 * (time.perf_counter() - t0)
    # steady state: warm-started repeat (the closed-loop regime)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = warm_step(step, args, out)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    step_ms = 1e3 * min(times)
    return {
        "n_agents": n_agents,
        "step_ms": step_ms,
        "compile_ms": compile_ms,
        # agents served per second of wall clock (one control step serves
        # every agent once) — the north-star "agents/sec" definition
        "agents_per_sec": n_agents / (step_ms / 1e3),
        # per-zone ADMM iterations per second (each step runs ADMM_ITERS)
        "zone_iters_per_sec": n_agents * ADMM_ITERS / (step_ms / 1e3),
        "platform": jax.devices()[0].platform,
        # devices the compiled step actually spanned — the headline key
        # gains a _d<n> qualifier when >1 so mesh and single-device
        # numbers can never conflate in the trajectory (ISSUE 9; the
        # same honesty rule PR 6 applied to platforms)
        "n_devices": len(getattr(
            jax.tree_util.tree_leaves(out)[0].sharding, "device_set",
            (None,))),
    }


def run_scaling() -> list[dict]:
    """The 4→256-zone curve (BASELINE.md scaling rows); on an
    accelerator the 1024-zone point is added (VERDICT r4 #1 asks the
    curve to 1024 — skipped on CPU where that point alone takes
    tens of minutes)."""
    import jax

    sizes = SCALING_SIZES
    if jax.devices()[0].platform != "cpu":
        sizes = (*SCALING_SIZES, 1024)
    rows = []
    for n in sizes:
        res = measure(n)
        rows.append(res)
        print(f"[bench] n={n:4d}  step={res['step_ms']:8.1f}ms  "
              f"agents/s={res['agents_per_sec']:8.0f}  "
              f"compile={res['compile_ms']:.0f}ms", file=sys.stderr)
    for res in rows:
        print(json.dumps({
            "metric": f"admm{res['n_agents']}_step_ms",
            "value": round(res["step_ms"], 2),
            "unit": "ms",
            "agents_per_sec": round(res["agents_per_sec"], 1),
            "zone_iters_per_sec": round(res["zone_iters_per_sec"], 1),
            "platform": res["platform"],
        }))
    return rows


def run_conventional(n_agents: int = N_AGENTS,
                     admm_iters: int = ADMM_ITERS) -> dict:
    """Measured stand-in for the reference's solver architecture: ONE
    sequential compiled-solver NLP call per zone per ADMM iteration,
    coordinator updates on the host between calls — the structure of
    ``admm_coordinator.py:259-321`` driving per-agent CasADi/IPOPT
    solves (``casadi_backend.py:133-139``), on identical hardware and
    the identical 256-zone workload.

    The per-zone solver is SciPy SLSQP (compiled Fortran SQP, the same
    class of method IPOPT belongs to) with ONE fused XLA-jitted callback
    per solver iteration evaluating objective+gradient+constraints+
    Jacobians together, memoized by iterate — compiled derivatives with
    a single Python dispatch per iteration, the most charitable stand-in
    for CasADi's C boundary this environment allows. Zones are
    warm-started across iterations and steps like the reference's
    ``_determine_initial_guess``. What this measures is therefore the
    cost of the *architecture* (N sequential solver calls + host
    round-trips per iteration) vs the fused plane (one XLA computation);
    it is not an IPOPT binary benchmark."""
    import numpy as np
    from scipy.optimize import minimize

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()

    ocp = zone_ocp()

    def f_aug(w, theta, zbar, lam, rho):
        u = ocp.unflatten(w)["u"]
        return ocp.nlp.f(w, theta) + \
            0.5 * rho * jnp.sum((u - zbar + lam) ** 2)

    # two compiled callbacks: values (+objective gradient, which scipy's
    # MemoizeJac wants at every fun call) and, LAZILY, the constraint
    # Jacobians — SLSQP's line search evaluates values only at rejected
    # trial points, and charging full-Jacobian work there would inflate
    # the baseline with work a real CasADi stack would not do
    @jax.jit
    def eval_vals(w, theta, zbar, lam, rho):
        fv, gf = jax.value_and_grad(f_aug)(w, theta, zbar, lam, rho)
        return fv, gf, ocp.nlp.g(w, theta), ocp.nlp.h(w, theta)

    @jax.jit
    def eval_jacs(w, theta):
        return (jax.jacfwd(ocp.nlp.g)(w, theta),
                jax.jacfwd(ocp.nlp.h)(w, theta))

    u_of = jax.jit(lambda w: ocp.unflatten(w)["u"])

    # SLSQP issues several callbacks per iterate; memoize per iterate so
    # each costs ONE dispatch of the right kind — without this the
    # measurement is dominated by Python-boundary overhead the
    # reference does not pay
    val_memo: dict = {}
    jac_memo: dict = {}

    def _vals(x, th, zb, lm, rho):
        key = x.tobytes()
        if key not in val_memo:
            val_memo.clear()  # SLSQP only revisits the current iterate
            val_memo[key] = tuple(
                np.asarray(v, dtype=float)
                for v in eval_vals(jnp.asarray(x), th, zb, lm, rho))
        return val_memo[key]

    def _jacs(x, th):
        key = x.tobytes()
        if key not in jac_memo:
            jac_memo.clear()
            jac_memo[key] = tuple(
                np.asarray(v, dtype=float)
                for v in eval_jacs(jnp.asarray(x), th))
        return jac_memo[key]

    x0s, loads = fleet_inputs(n_agents)
    thetas, bnds = [], []
    for i in range(n_agents):
        th = ocp.default_params(
            x0=jnp.array([x0s[i]]),
            d_traj=jnp.broadcast_to(
                jnp.array([loads[i], 290.15, 294.15]), (HORIZON, 3)))
        thetas.append(th)
        lb, ub = ocp.bounds(th)
        bnds.append(list(zip(np.asarray(lb), np.asarray(ub))))
    w = [np.asarray(ocp.initial_guess(th)) for th in thetas]
    zbar = np.full((HORIZON, 1), 0.02)
    lams = np.zeros((n_agents, HORIZON, 1))
    rho = 20.0

    def control_step():
        nonlocal zbar, lams
        for _ in range(admm_iters):
            us = np.zeros((n_agents, HORIZON, 1))
            for i in range(n_agents):
                th, zb, lm = thetas[i], jnp.asarray(zbar), \
                    jnp.asarray(lams[i])
                val_memo.clear()
                jac_memo.clear()
                res = minimize(
                    lambda x: _vals(x, th, zb, lm, rho)[:2],
                    x0=w[i], jac=True, bounds=bnds[i], method="SLSQP",
                    constraints=[
                        {"type": "eq",
                         "fun": lambda x: _vals(x, th, zb, lm, rho)[2],
                         "jac": lambda x: _jacs(x, th)[0]},
                        {"type": "ineq",
                         "fun": lambda x: _vals(x, th, zb, lm, rho)[3],
                         "jac": lambda x: _jacs(x, th)[1]},
                    ],
                    options={"maxiter": 50, "ftol": 1e-6})
                w[i] = res.x
                us[i] = np.asarray(u_of(jnp.asarray(res.x)))
            zbar = us.mean(axis=0)
            lams = lams + (us - zbar)
        return us

    control_step()                       # warm-up (compiles + warm starts)
    times = []
    for _ in range(3):                   # min-of-3, like measure()
        t0 = time.perf_counter()
        us = control_step()
        times.append(time.perf_counter() - t0)
    step_ms = 1e3 * min(times)
    spread = float(np.max(np.abs(us - zbar)))
    out = {
        "metric": f"admm{n_agents}_step_ms[conventional_sequential]",
        "value": round(step_ms, 1),
        "unit": "ms",
        "agents_per_sec": round(n_agents / (step_ms / 1e3), 2),
        "nlp_calls_per_step": n_agents * admm_iters,
        "consensus_spread": round(spread, 6),
        "platform": "cpu-sequential-slsqp",
    }
    print(json.dumps(out))
    return out


def run_sequential_native(n_agents: int = N_AGENTS,
                          admm_iters: int = ADMM_ITERS) -> dict:
    """Architecture A/B with the confound removed: the SAME interior-point
    solver, SAME inner budgets and SAME compiled kernels as the fused
    plane, but driven the way the reference drives IPOPT — one solver
    call per zone per ADMM iteration, sequentially, with the coordinator
    update on the host between calls (``admm_coordinator.py:259-321``).
    The fused-plane speedup over THIS number is purely what batching the
    zones into one XLA computation buys (vmapped lanes + no per-call
    dispatch + no host round-trips); solver-quality questions cancel."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()

    from agentlib_mpc_tpu.ops.solver import (
        NLPFunctions,
        SolverOptions,
        solve_nlp,
    )

    ocp = zone_ocp()

    def f_aug(w, theta):
        ocp_theta, zbar, lam, rho = theta
        u = ocp.unflatten(w)["u"]
        return ocp.nlp.f(w, ocp_theta) + \
            0.5 * rho * jnp.sum((u - zbar + lam) ** 2)

    nlp = NLPFunctions(f=f_aug, g=lambda w, th: ocp.nlp.g(w, th[0]),
                       h=lambda w, th: ocp.nlp.h(w, th[0]))
    opts = SolverOptions(**SOLVER_BASE)

    @jax.jit
    def one_solve(w0, y0, z0, theta, zbar, lam, rho, mu0, budget):
        th = (theta, zbar, lam, rho)
        lb, ub = ocp.bounds(theta)
        res = solve_nlp(nlp, w0, th, lb, ub, opts, y0=y0, z0=z0,
                        mu0=mu0, max_iter=budget)
        return res.w, res.y, res.z, ocp.unflatten(res.w)["u"]

    x0s, loads = fleet_inputs(n_agents)
    thetas = [ocp.default_params(
        x0=jnp.array([x0s[i]]),
        d_traj=jnp.broadcast_to(
            jnp.array([loads[i], 290.15, 294.15]), (HORIZON, 3)))
        for i in range(n_agents)]
    w = [ocp.initial_guess(th) for th in thetas]
    y = [jnp.zeros((ocp.n_g,))] * n_agents
    z = [jnp.full((ocp.n_h,), 0.1)] * n_agents
    zbar = jnp.full((HORIZON, 1), 0.02)
    lams = [jnp.zeros((HORIZON, 1))] * n_agents
    rho = jnp.asarray(20.0)

    def control_step():
        nonlocal zbar, lams, w, y, z
        for it in range(admm_iters):
            budget = jnp.asarray(COLD_BUDGET if it == 0 else WARM_BUDGET)
            mu0 = jnp.asarray(COLD_MU if it == 0 else WARM_MU)
            us = []
            for i in range(n_agents):
                w[i], y[i], z[i], u = one_solve(
                    w[i], y[i], z[i], thetas[i], zbar, lams[i], rho,
                    mu0, budget)
                us.append(np.asarray(u))   # host round-trip per agent,
                #                            like the coordinator's reply
            us = np.stack(us)
            zbar = jnp.asarray(us.mean(axis=0))
            lams = [lams[i] + (jnp.asarray(us[i]) - zbar)
                    for i in range(n_agents)]
        return us

    control_step()                       # warm-up (compile + warm starts)
    times = []
    for _ in range(3):                   # min-of-3, like measure()
        t0 = time.perf_counter()
        us = control_step()
        times.append(time.perf_counter() - t0)
    step_ms = 1e3 * min(times)
    spread = float(np.max(np.abs(us - np.asarray(zbar))))
    out = {
        "metric": f"admm{n_agents}_step_ms[sequential_same_solver]",
        "value": round(step_ms, 1),
        "unit": "ms",
        "agents_per_sec": round(n_agents / (step_ms / 1e3), 2),
        "nlp_calls_per_step": n_agents * admm_iters,
        "consensus_spread": round(spread, 6),
        "platform": "cpu-sequential-native",
    }
    print(json.dumps(out))
    return out


def _mesh_section() -> dict:
    """Device inventory + a measured consensus-shaped ``pmean``
    round-trip when more than one device is visible — the same probe a
    mesh-built :class:`FusedADMM` records per round as
    ``admm_collective_seconds``. Embedded in ``--emit-metrics`` so every
    telemetry artifact states what mesh (if any) was available to the
    run it describes."""
    import jax

    devs = jax.devices()
    out = {
        "devices": len(devs),
        "platform": devs[0].platform,
        "fleet_mesh_axis": "agents",
    }
    if len(devs) > 1:
        from agentlib_mpc_tpu.parallel import fleet_mesh
        from agentlib_mpc_tpu.parallel.multihost import collective_probe

        # the SAME builder FusedADMM's per-round probe uses, so
        # collective_pmean_us and admm_collective_seconds measure one
        # structurally identical collective (compiled+warmed inside)
        probe, x = collective_probe(fleet_mesh(), HORIZON)
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(probe(x))
            times.append(time.perf_counter() - t0)
        out["collective_pmean_us"] = round(1e6 * min(times), 1)
    return out


def _emit_metrics_slo_report() -> dict:
    """A real (tiny) serving plane's SLO report for the --emit-metrics
    artifact: two tracker tenants, three served rounds — enough for the
    availability/error-budget/burn-rate columns to carry live numbers
    instead of a schema stub."""
    from agentlib_mpc_tpu.lint.retrace_budget import (
        serve_tenants,
        tracker_ocp,
        tracker_tenant_spec,
    )
    from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
    from agentlib_mpc_tpu.serving import ServingPlane

    ocp = tracker_ocp()
    plane = ServingPlane(FusedADMMOptions(max_iterations=5, rho=2.0),
                         slot_multiple=1, initial_capacity=2,
                         pipelined=False, donate=False)
    plane.join(tracker_tenant_spec(ocp, "slo-a", 1.0))
    plane.join(tracker_tenant_spec(ocp, "slo-b", 2.0))
    for _ in range(3):
        serve_tenants(plane, "slo-a", "slo-b")
    return plane.slo_report()


def run_emit_metrics(path: str, n_agents: int = N_AGENTS) -> dict:
    """``--emit-metrics PATH``: run the fused ADMM bench step with the
    full telemetry stack on (metrics registry + spans + JAX compile hooks)
    and write a phase-breakdown artifact to PATH — the file future
    ``BENCH_r*.json`` rounds embed so a regression can be attributed to
    compile vs. execute instead of staring at one wall-clock number.

    The artifact carries: compile counts/seconds and retraces per entry
    point, the solver-iterations histogram over every inner solve of the
    round, per-ADMM-iteration primal/dual residual gauges, the span
    breakdown (cold step = trace+compile+execute, warm steps = execute),
    and the broker counter families (zero-valued here — the fused plane
    does not route messages; their presence keys the dashboards).

    Runs on the current process's default platform — pin
    ``JAX_PLATFORMS=cpu`` for a host run.
    """
    import numpy as np

    import jax

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.ops.admm import record_residuals
    from agentlib_mpc_tpu.ops.solver import record_solver_stats, SolverStats
    from agentlib_mpc_tpu.utils.jax_setup import enable_compile_profiling
    import agentlib_mpc_tpu.runtime.broker  # noqa: F401 - declares the
    #                      broker_* metric families (exported even at zero)

    telemetry.configure(enabled=True)
    telemetry.reset()
    enable_compile_profiling()
    # flight recorder on for the run: the artifact embeds the journal's
    # own volume accounting, and the journal file rides NEXT TO the
    # metrics artifact (the incident CLI's input for this run)
    telemetry.enable_journal(path + ".journal.jsonl")

    # the build (transcription, structure probes) compiles its own small
    # programs — give it its own span so those do not pollute the
    # cold-step attribution below
    with telemetry.span("bench.build"):
        step, args = build_step(n_agents, record_stats=True)
    with telemetry.span("bench.cold_step") as cold_sp:
        carry, stats = step(*args)
        jax.block_until_ready(carry)
    warm_times = []
    for _ in range(3):
        with telemetry.span("bench.warm_step") as sp:
            # warm start: carry (w, y, z, zbar, lams) feeds back, problem
            # data (x0s, loads, rho) unchanged — warm_step()'s layout with
            # the record_stats carry
            carry, stats = step(args[0], args[1], *carry[:5], args[7])
            jax.block_until_ready(carry)
        warm_times.append(sp.duration)

    prim, dual, iters, ok, kkt = (np.asarray(s) for s in stats)
    for k in range(prim.shape[0]):
        record_residuals(prim[k], dual[k], iteration=k, fleet="bench")
    # real per-lane solver stats of the final warm step (note: warm
    # inexact iterations run a 1-iteration budget, so success=False lanes
    # are expected — that IS the inexact-ADMM operating point)
    record_solver_stats(
        SolverStats(iterations=iters.reshape(-1),
                    kkt_error=kkt.reshape(-1),
                    success=ok.reshape(-1),
                    objective=np.zeros(iters.size),
                    mu=np.zeros(iters.size),
                    constraint_violation=np.zeros(iters.size)),
        backend="bench")

    reg = telemetry.metrics()

    def scoped(name, entry_point):
        return reg.get(name, entry_point=entry_point) or 0.0

    cold_s = cold_sp.duration
    warm_s = min(warm_times)
    # decompose the cold step from ITS OWN entry-point-labeled events —
    # registry-wide totals also cover the build-phase compiles and would
    # overcount (the whole point of span-scoped attribution)
    cold_compile_s = scoped("jax_compile_seconds_total", "bench.cold_step")
    cold_trace_s = scoped("jax_trace_seconds_total", "bench.cold_step")
    cold_lower_s = scoped("jax_lower_seconds_total", "bench.cold_step")
    payload = {
        "metric": "telemetry_phase_breakdown",
        "n_agents": n_agents,
        "admm_iters": ADMM_ITERS,
        "platform": jax.devices()[0].platform,
        "phases": {
            # process-wide compile economics (build + cold step)
            "compile_count": reg.counter("jax_compiles_total").total(),
            "compile_seconds_total":
                reg.counter("jax_compile_seconds_total").total(),
            "trace_count": reg.counter("jax_traces_total").total(),
            "trace_seconds_total":
                reg.counter("jax_trace_seconds_total").total(),
            "retrace_count": reg.counter("jax_retraces_total").total(),
            # the cold step's own entry-point-attributed phase seconds.
            # Diagnostics, NOT an additive decomposition: trace events
            # nest (an outer jit's trace duration includes its inner
            # jits') and XLA compiles sub-modules concurrently, so these
            # can sum past the wall-clock.
            "cold_step_s": cold_s,
            "cold_step_trace_s": cold_trace_s,
            "cold_step_lower_s": cold_lower_s,
            "cold_step_compile_s": cold_compile_s,
            "warm_step_s": warm_s,
            # the warm step runs the SAME program with zero compile work,
            # so it is the measured execute time; the rest of the cold
            # step is trace+lower+compile overhead
            "cold_overhead_s": max(0.0, cold_s - warm_s),
            "execute_share_of_cold": (warm_s / cold_s if cold_s else None),
        },
        "spans": telemetry.recorder().aggregate(),
        "metrics": reg.snapshot(),
    }
    # lint debt rides along with the perf trajectory: findings per rule
    # per module (python -m agentlib_mpc_tpu.lint --stats), so a round
    # that got faster by cutting hygiene corners shows it in the same
    # artifact that celebrates the speedup
    try:
        from agentlib_mpc_tpu.lint import collect_stats

        payload["lint_stats"] = collect_stats()
    except Exception as exc:  # the bench must never die to the linter
        payload["lint_stats"] = {"error": repr(exc)}
    # jaxpr certificate outcomes (LQ status, stage-structure proof,
    # dtype advisories, FLOP/bytes cost attribution per example OCP):
    # the routing decisions a round ran under, recorded next to the
    # wall-clock they produced
    try:
        from agentlib_mpc_tpu.lint.jaxpr.examples import certificate_summary

        payload["jaxpr_certificates"] = certificate_summary()
    except Exception as exc:
        payload["jaxpr_certificates"] = {"error": repr(exc)}
    # collective-schedule certificates of the mesh fleets (ISSUE 11):
    # the proved psum schedule, its mesh-independent digest and the
    # modeled per-round collective_bytes (payload x axis size x ADMM
    # iteration budget) — the comms column fusion-target picking weighs
    # against eval_jac_cost's compute column
    try:
        from agentlib_mpc_tpu.lint.jaxpr.collectives import (
            collectives_gate_summary,
        )

        payload["collective_certificates"] = collectives_gate_summary()
    except Exception as exc:
        payload["collective_certificates"] = {"error": repr(exc)}
    # memory certificates (ISSUE 13): per-fleet certified peak, the XLA
    # memory_analysis cross-check ratio, and the capacity-planner table
    # — "how many agents fit one device" recorded next to how fast the
    # round ran. Planner HBM: the device's reported capacity, or a
    # nominal 16 GiB when the backend reports none (CPU), noted.
    try:
        from agentlib_mpc_tpu.lint.jaxpr.memory import (
            device_hbm_bytes,
            memory_gate_summary,
            plan_capacity,
        )
        from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
        from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions

        mem = memory_gate_summary()
        hbm = device_hbm_bytes()
        plan = plan_capacity(
            tracker_ocp(), FusedADMMOptions(max_iterations=8, rho=2.0),
            hbm_bytes=hbm if hbm else 16 * 2**30, refine=False)
        mem["capacity_plan"] = dict(
            plan.as_dict(),
            hbm_source="device" if hbm else "nominal-16GiB")
        payload["memory_certificates"] = mem
    except Exception as exc:
        payload["memory_certificates"] = {"error": repr(exc)}
    # dispatch certificates + the analytic fusion plan (ISSUE 18): the
    # proved host↔device schedule of the gate fleets (one device
    # program per warm round, zero host syncs, mesh-independent digest)
    # and the planner's ranked stage merges for THIS bench's warm step
    # — what fusing the IPM pipeline is modeled to save, recorded next
    # to the wall-clock it produced
    try:
        from agentlib_mpc_tpu.lint.jaxpr.dispatch import (
            dispatch_gate_summary,
        )
        from agentlib_mpc_tpu.lint.jaxpr.fusion import plan_fusion

        disp = dispatch_gate_summary()
        wargs = (args[0], args[1], *carry[:5], args[7])
        disp["fusion_plan"] = plan_fusion(
            step, *wargs, while_trips=ADMM_ITERS).as_dict()
        payload["dispatch_certificates"] = disp
    except Exception as exc:
        payload["dispatch_certificates"] = {"error": repr(exc)}
    # banded-vs-dense eval+jac cost comparison (lint/jaxpr cost model):
    # the analytical crossover evidence behind jacobian="auto", recorded
    # next to the measured phases (PERF.md round 8; the modeled dense
    # FLOPs grow O(N²), the sparse ones O(N))
    # mesh inventory + collective round-trip: which device fabric this
    # artifact's numbers ran on (single-device and mesh rounds must be
    # attributable without guessing)
    try:
        payload["mesh"] = _mesh_section()
    except Exception as exc:
        payload["mesh"] = {"error": repr(exc)}
    try:
        from agentlib_mpc_tpu.lint.jaxpr.cost import compare_eval_jac_cost
        from agentlib_mpc_tpu.ops.stagejac import plan_from_certificate

        ocp = zone_ocp()
        plan = plan_from_certificate(
            ocp.nlp, ocp.default_params(), ocp.n_w, ocp.stage_partition,
            label="the bench zone OCP")
        payload["eval_jac_cost"] = {"error": "stage structure not proved"} \
            if plan is None else compare_eval_jac_cost(
                ocp.nlp, ocp.default_params(), ocp.n_w, plan)
    except Exception as exc:
        payload["eval_jac_cost"] = {"error": repr(exc)}
    # SLO report (ISSUE 15): a tiny live serving plane's per-tenant
    # availability/error-budget/burn-rate columns beside the
    # certificate sections
    try:
        payload["slo_report"] = _emit_metrics_slo_report()
    except Exception as exc:
        payload["slo_report"] = {"error": repr(exc)}
    # per-phase device attribution + the certificate-calibrated
    # roofline (ISSUE 16): measured phase table and modeled FLOP/bytes
    # ledger joined over the SAME named scopes, beside the span/compile
    # sections — runs after the phase counters above were read, so its
    # one-time HLO-join retrace never pollutes the compile economics
    try:
        payload["phase_profile"], payload["calibration"] = \
            _emit_metrics_phase_section(step, args, carry)
    except Exception as exc:
        payload["phase_profile"] = {"error": repr(exc)}
        payload["calibration"] = {"error": repr(exc)}
    # ... and the flight recorder's own volume accounting (events by
    # type, bytes, rotations) — the observability layer reports itself
    try:
        j = telemetry.journal_active()
        payload["journal"] = None if j is None else j.stats()
    finally:
        telemetry.disable_journal()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1)
    summary = {
        "metric": "admm_emit_metrics",
        "n_agents": n_agents,
        "path": path,
        "warm_step_ms": round(1e3 * warm_s, 2),
        "compile_count": payload["phases"]["compile_count"],
        "compile_seconds_total": round(
            payload["phases"]["compile_seconds_total"], 2),
        "phase_coverage": payload["phase_profile"].get("coverage"),
        "platform": payload["platform"],
    }
    print(json.dumps(summary))
    return payload


def _emit_metrics_phase_section(step, args, carry):
    """The --emit-metrics observatory section: capture a 3-round phase
    profile of the warm (record_stats) step and join it against the
    certificate cost model. Returns ``(phase_profile, calibration)``
    dicts, each with its rendered markdown ``table``."""
    import jax

    from agentlib_mpc_tpu.telemetry import calibration
    from agentlib_mpc_tpu.telemetry.profiler import (
        capture_phase_profile,
        hlo_text_for,
    )

    wargs = (args[0], args[1], *carry[:5], args[7])
    hlo = hlo_text_for(step, *wargs)

    def run_round():
        jax.block_until_ready(step(*wargs))

    prof = capture_phase_profile(run_round, rounds=3, hlo_text=hlo)
    costs = calibration.phase_costs(step, *wargs)
    report = calibration.calibrate(prof, costs)
    return (dict(prof.as_dict(), table=prof.table()),
            dict(report.as_dict(), table=report.table()))


def _bench_phase_setup(n_agents: int, mutate: bool = False):
    """Warm fused step + per-round runner + compiled text for the phase
    profiler (ISSUE 16). ``mutate=True`` wraps the step with artificial
    extra work INSIDE the ``phase.factor`` scope — the perf-gate's
    self-test fault injection: the gate must fail this and pass A/A.
    The extra work is data-dependent on the step's output and folded
    back into it (×1e-30, numerically invisible) so XLA can neither
    constant-fold nor dead-code-eliminate it."""
    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.telemetry.profiler import (
        hlo_text_for,
        phase_scope,
    )

    step, args = build_step(n_agents)
    if mutate:
        inner = step

        @jax.jit
        def step(*a):  # noqa: F811 — deliberate mutated shadow
            out = inner(*a)
            with phase_scope("factor"):
                # sized to land decisively OUTSIDE the factor noise
                # band (25% of mean): ~8.6 GFLOP of serial dependent
                # matmuls ≈ tens of ms on CPU vs a ~7 ms band
                x = jnp.eye(512, dtype=jnp.float32) \
                    + 1e-30 * out[0][0, 0]
                for _ in range(32):
                    x = (x @ x) * (1.0 / 512.0)
                extra = jnp.sum(x) * 1e-30
            leaves, treedef = jax.tree_util.tree_flatten(out)
            leaves[0] = leaves[0] + extra.astype(leaves[0].dtype)
            return jax.tree_util.tree_unflatten(treedef, leaves)

    out = step(*args)
    jax.block_until_ready(out)
    hlo = hlo_text_for(step, *args)

    def run_round():
        jax.block_until_ready(warm_step(step, args, out))

    return run_round, hlo


def run_phase_profile(n_agents: int = 64, rounds: int = 3,
                      journal: bool = False) -> dict:
    """Named-phase device attribution of the warm fused bench step (the
    ``--evidence`` matrix's ``phase_profile`` section): where a warm
    round's device time goes, per ``phase.*`` scope, with the explicit
    ``unattributed`` residual and the coverage ratio."""
    from agentlib_mpc_tpu.telemetry.profiler import capture_phase_profile

    run_round, hlo = _bench_phase_setup(n_agents)
    prof = capture_phase_profile(run_round, rounds=rounds,
                                 hlo_text=hlo, journal=journal)
    return {"n_agents": n_agents, **prof.as_dict()}


def run_perf_gate(baseline_path: "str | None" = None, *,
                  update: bool = False, mutate: bool = False,
                  n_agents: int = 64, rounds: int = 3,
                  samples: int = 2,
                  journal_path: "str | None" = None) -> dict:
    """``--perf-gate``: the per-phase performance regression gate
    (ISSUE 16) — capture a phase profile of the warm fused step and
    check it against the committed, platform-qualified baselines
    (``perf_baselines.json``); out-of-band phases FAIL the gate (exit
    1), improvements are noted, a missing key under this platform is an
    explicit SKIP. ``--update`` records ``samples`` captures as the new
    baseline (noise band = observed spread with rel/abs floors);
    ``--mutate`` self-tests the gate by injecting extra ``factor``-phase
    work that MUST trip it. Verdicts are journaled (``perf.gate`` +
    per-phase ``perf.regression``) when ``--journal PATH`` is given or
    a journal is already active."""
    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import regression
    from agentlib_mpc_tpu.telemetry.profiler import capture_phase_profile

    if baseline_path is None:
        baseline_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "perf_baselines.json")
    own_journal = journal_path is not None \
        and telemetry.journal_active() is None
    if own_journal:
        telemetry.enable_journal(journal_path)
    try:
        run_round, hlo = _bench_phase_setup(n_agents, mutate=mutate)
        if update:
            profiles = [capture_phase_profile(run_round, rounds=rounds,
                                              hlo_text=hlo)
                        for _ in range(max(int(samples), 1))]
            entry = regression.update_baseline(baseline_path, profiles)
            row = {"metric": "perf_gate", "mode": "update",
                   "metric_key": profiles[0].metric_key,
                   "platform": profiles[0].platform,
                   "n_agents": n_agents, "path": baseline_path,
                   "coverage": entry["coverage"],
                   "phases": entry["phases"]}
            print(json.dumps(row))
            return row
        # check mode is min-of-`samples` captures per phase: a one-shot
        # OS/autotune spike (CPU eval_jac is bimodal across processes)
        # disappears under the min, while a persistent slowdown — the
        # mutation self-test, a real regression — survives every
        # capture and still trips the gate
        from agentlib_mpc_tpu.telemetry.profiler import min_profile
        profile = min_profile(
            [capture_phase_profile(run_round, rounds=rounds,
                                   hlo_text=hlo)
             for _ in range(max(int(samples), 1))])
        report = regression.check_regression(baseline_path, profile)
        row = {"metric": "perf_gate",
               "mode": "mutate" if mutate else "check",
               "n_agents": n_agents,
               "coverage": round(profile.coverage, 4),
               "measured_ms": {k: round(v, 4)
                               for k, v in profile.device_ms.items()},
               **report}
        print(json.dumps(row))
        return row
    finally:
        if own_journal:
            telemetry.disable_journal()


def run_mesh_ab(sizes=(256, 1024), device_counts=(1, 8)) -> list[dict]:
    """``--mesh-ab [zones]``: sharded-vs-single-device A/B of the fused
    ADMM fleet (ROADMAP item 1 / ISSUE 9 acceptance row).

    For each fleet size, the SAME zone workload runs as (a) the
    single-device vmapped engine and (b) the explicit ``shard_map``
    engine over a ``device_counts[i]``-device agent mesh (``psum``
    consensus). The per-zone warm-step cost is the headline column: the
    round-6 attribution (PERF.md) pinned the single-core ceiling on LLC
    pressure from the batched KKT factor working set, which splitting
    the agent axis across shards divides — the per-zone curve must
    flatten with devices at 1024+ zones. Also checks consensus identity
    (max |Δz̄| vs the single-device run) so the A/B can never publish a
    fast-but-wrong number.

    On CPU the mesh is 8 virtual host devices (the child requests them
    before backend init); metric keys carry platform AND device count
    (``mesh_ab[256,d8]``) per the PR 6 honesty rule — mesh and
    single-device numbers must never conflate in the trajectory.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import (
        AgentGroup,
        FusedADMM,
        FusedADMMOptions,
        pad_group_to_devices,
        stack_params,
    )
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    platform = jax.devices()[0].platform
    n_avail = len(jax.devices())
    ocp = zone_ocp()
    cold = SolverOptions(**SOLVER_BASE, mu_init=COLD_MU)
    warm = cold._replace(max_iter=WARM_BUDGET, mu_init=WARM_MU)
    admm_opts = FusedADMMOptions(max_iterations=ADMM_ITERS, rho=20.0)

    rows = []
    for n in sizes:
        x0s, loads = fleet_inputs(n)
        thetas = stack_params([
            ocp.default_params(
                x0=jnp.array([x0s[i]]),
                d_traj=jnp.broadcast_to(
                    jnp.array([loads[i], 290.15, 294.15]), (HORIZON, 3)))
            for i in range(n)])
        zbar_ref = None
        for d in device_counts:
            if d > n_avail:
                print(f"[bench] mesh-ab: skipping d={d} "
                      f"({n_avail} devices available)", file=sys.stderr)
                continue
            group = AgentGroup(
                name="zones", ocp=ocp, n_agents=n,
                couplings={"mDotCoolAir": "mDot"},
                solver_options=cold, warm_solver_options=warm)
            # any size works: pad to the shard multiple (masked dead
            # lanes) so e.g. --mesh-ab 100 runs on the 8-device mesh
            # instead of dying on the engine's divisibility check
            group, thetas_d, mask = pad_group_to_devices(group, thetas, d)
            mesh = None if d == 1 else Mesh(
                np.array(jax.devices()[:d]), ("agents",))
            t0 = time.perf_counter()
            engine = FusedADMM([group], admm_opts, active=[mask],
                               mesh=mesh)
            state = engine.init_state([thetas_d])
            if mesh is not None:
                state, (thetas_run,) = engine.shard_args(
                    mesh, state, [thetas_d])
            else:
                thetas_run = thetas_d
            state, _trajs, stats = engine.step(state, [thetas_run])
            jax.block_until_ready(state)
            compile_ms = 1e3 * (time.perf_counter() - t0)
            times = []
            for _ in range(2 if n >= 2048 else 3):
                t0 = time.perf_counter()
                state, _trajs, stats = engine.step(state, [thetas_run])
                jax.block_until_ready(state)
                times.append(time.perf_counter() - t0)
            step_ms = 1e3 * min(times)
            zbar = np.asarray(state.zbar["mDotCoolAir"])
            if d == min(device_counts):
                zbar_ref = zbar
            diff = None if zbar_ref is None \
                else float(np.max(np.abs(zbar - zbar_ref)))
            # the "never publish a fast-but-wrong number" gate: a
            # sharded run that disagrees with the single-device
            # consensus beyond f32 reduction-order noise is marked
            # broken IN the row (and loudly on stderr) so no consumer
            # can quote its speed without its wrongness
            identity_ok = diff is None or diff < 1e-3
            if not identity_ok:
                print(f"[bench] mesh-ab n={n} d={d}: consensus DIVERGES "
                      f"from the single-device run (max |dzbar| = "
                      f"{diff:.3e}) — row marked identity_ok=false",
                      file=sys.stderr)
            row = {
                "metric": f"mesh_ab[{n},d{d}]",
                "n_agents": n,
                "devices": d,
                "step_ms": round(step_ms, 2),
                "per_zone_us": round(1e3 * step_ms / n, 2),
                "compile_ms": round(compile_ms, 0),
                "iterations": int(stats.iterations),
                "converged": bool(stats.converged),
                "zbar_max_abs_diff": diff,
                "identity_ok": identity_ok,
                "platform": platform,
            }
            rows.append(row)
            print(json.dumps(row))
            sys.stdout.flush()
            print(f"[bench] mesh-ab n={n:5d} d={d}  "
                  f"step={step_ms:8.1f}ms  "
                  f"per-zone={row['per_zone_us']:7.1f}us  "
                  f"compile={compile_ms:.0f}ms", file=sys.stderr)
            del engine, state
    return rows


def run_scenario_ab(n_scenarios: int = 8, n_agents: int = 4,
                    seed: int = 0) -> list[dict]:
    """``--scenario-ab [S]``: batched-S-vs-serial-S robust scenario cost
    scaling (ISSUE 12 acceptance row).

    The SAME zone workload solves its S disturbance scenarios (seeded
    load perturbations from the chaos sampler — scenario 0 nominal) two
    ways: (a) **serial** — S single-scenario rounds back to back, the
    reference's branch-at-a-time scenario handling; (b) **batched** —
    one :class:`~agentlib_mpc_tpu.scenario.fleet.ScenarioFleet` round
    with the scenario axis vmapped. Per-scenario warm cost is the
    headline column. Identity gate: the UNCOUPLED batched run (fan tree
    with robust horizon 0 — independent branches) must reproduce the
    serial consensus trajectories to f32 reduction noise, so the A/B
    can never publish a fast-but-wrong number. Both identity legs run
    with the Boyd exit tolerances pinned to ZERO (fixed iteration
    count): the batched round's residual exit aggregates over all
    branches and would otherwise legitimately stop at a different
    iteration than a lone serial branch — a false identity failure on
    a correct run (the test-suite comparison pins the same way). The
    ROBUST batched row (non-anticipativity on u0) runs the live
    tolerances and additionally reports ``na_spread`` — the workload
    class the reference cannot batch at all.

    Metric keys carry platform and device count (``_d<n>``) per the
    PR 6/9 honesty rules; no CPU-fallback number can enter a TPU
    trajectory headline.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
    from agentlib_mpc_tpu.scenario import (
        ScenarioFleet,
        ScenarioFleetOptions,
        ensemble_thetas,
        fan_tree,
        single_scenario,
    )
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    qual = f"{platform},d{n_dev}"
    S = int(n_scenarios)
    ocp = zone_ocp()
    cold = SolverOptions(**SOLVER_BASE, mu_init=COLD_MU)
    group = AgentGroup(name="zones", ocp=ocp, n_agents=n_agents,
                       couplings={"mDotCoolAir": "mDot"},
                       solver_options=cold)
    fleet_opts = ScenarioFleetOptions(
        max_iterations=ADMM_ITERS, rho=20.0, rho_na=20.0,
        warm_budget=WARM_BUDGET, warm_mu=WARM_MU)
    x0s, loads = fleet_inputs(n_agents)

    def agent_thetas(tree):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *[
            ensemble_thetas(
                ocp.default_params(
                    x0=jnp.array([x0s[i]]),
                    d_traj=jnp.broadcast_to(
                        jnp.array([loads[i], 290.15, 294.15]),
                        (HORIZON, 3))),
                tree, seed=seed + i, scale=0.15 * loads[i],
                channels=(0,))
            for i in range(n_agents)])

    rows: list[dict] = []

    def warm_trace(fleet, thetas):
        st = fleet.init_state(thetas)
        st, _t, _s = fleet.step(st, thetas)
        jax.block_until_ready(st)

    def one_round(fleet, thetas):
        """One cold-state warm-trace round — the symmetric unit both
        legs measure (the serial leg sums S of them)."""
        st = fleet.init_state(thetas)
        t0 = time.perf_counter()
        st, _t, stats = fleet.step(st, thetas)
        jax.block_until_ready(st)
        return st, stats, 1e3 * (time.perf_counter() - t0)

    # -- serial leg: S single-scenario rounds (the reference pattern) --
    # fixed-iteration options for the two identity legs (docstring)
    ab_opts = fleet_opts._replace(abs_tol=0.0, rel_tol=0.0,
                                  primal_tol=0.0, dual_tol=0.0)
    fleet1 = ScenarioFleet(group, single_scenario(), ab_opts)
    fan = fan_tree(S, robust_horizon=1)
    thetas_all = agent_thetas(fan)          # (n_agents, S, ...) data
    slice_s = lambda s: jax.tree.map(lambda l: l[:, s:s + 1], thetas_all)
    warm_trace(fleet1, slice_s(0))
    serial_states = []
    serial_ms = 0.0
    for s in range(S):
        st, _stats, ms = one_round(fleet1, slice_s(s))
        serial_states.append(st)
        serial_ms += ms
    rows.append({
        "metric": f"scenario_ab[{S},serial,{qual}]",
        "n_scenarios": S, "n_agents": n_agents,
        "total_ms": round(serial_ms, 2),
        "per_scenario_ms": round(serial_ms / S, 3),
        "platform": platform, "devices": n_dev,
    })

    # -- batched legs: uncoupled identity gate + robust row ------------
    free = fan_tree(S, robust_horizon=0)    # independent branches
    fleetF = ScenarioFleet(group, free, ab_opts)
    warm_trace(fleetF, thetas_all)
    stF, _statsF, free_ms = one_round(fleetF, thetas_all)
    # identity: per-scenario consensus means of the uncoupled batch vs
    # the serial runs (same data, same iteration budget)
    diffs = [float(jnp.max(jnp.abs(
        stF.zbar["mDotCoolAir"][s] - serial_states[s].zbar[
            "mDotCoolAir"][0]))) for s in range(S)]
    identity_diff = max(diffs)
    identity_ok = identity_diff < 1e-3
    if not identity_ok:
        print(f"[bench] scenario-ab S={S}: batched consensus DIVERGES "
              f"from serial branches (max |dzbar| = {identity_diff:.3e})"
              f" — rows marked identity_ok=false", file=sys.stderr)
    rows.append({
        "metric": f"scenario_ab[{S},batched,{qual}]",
        "n_scenarios": S, "n_agents": n_agents,
        "total_ms": round(free_ms, 2),
        "per_scenario_ms": round(free_ms / S, 3),
        "serial_over_batched": round(serial_ms / max(free_ms, 1e-9), 2),
        "zbar_max_abs_diff": identity_diff,
        "identity_ok": identity_ok,
        "platform": platform, "devices": n_dev,
    })

    fleetR = ScenarioFleet(group, fan, fleet_opts)
    warm_trace(fleetR, thetas_all)
    stR, statsR, robust_ms = one_round(fleetR, thetas_all)
    u0 = np.asarray(fleetR.actuated_u0(stR))
    rows.append({
        "metric": f"scenario_ab[{S},robust,{qual}]",
        "n_scenarios": S, "n_agents": n_agents,
        "total_ms": round(robust_ms, 2),
        "per_scenario_ms": round(robust_ms / S, 3),
        "iterations": int(statsR.iterations),
        "converged": bool(statsR.converged),
        "na_spread": float(statsR.na_spread),
        "u0_group_identical": bool(
            np.all(u0 == u0[:, :1])),
        "platform": platform, "devices": n_dev,
    })
    for row in rows:
        print(json.dumps(row))
        sys.stdout.flush()
    print(f"[bench] scenario-ab S={S}: serial={serial_ms:.1f}ms "
          f"batched={free_ms:.1f}ms robust={robust_ms:.1f}ms "
          f"({qual})", file=sys.stderr)
    return rows


def run_fusion_ab(n_agents: int = 4, rounds: int = 5) -> list[dict]:
    """``--fusion-ab [n] [r]``: fused-vs-staged IPM dispatch A/B
    (ISSUE 18 acceptance row).

    The SAME zone consensus fleet runs its warm rounds two ways: (a)
    **fused** — ``SolverOptions.fusion="require"``: eval+jac → banded
    assemble → stage factor → line search live in ONE device program
    per round, and the build carries the proof (staged-twin collective
    digest identity, memory certificate within the analytic
    :class:`FusionPlan`'s projected peak — the plan rides the row); (b)
    **staged** — ``fusion="off"``: the reference-shaped program whose
    stage hand-offs go through ``stage_boundary`` materialization
    points. Warm per-round wall time is the headline column.

    Identity gate: both legs run the Boyd exits pinned to ZERO (fixed
    iteration count — the batched exit aggregation caveat from
    ``--scenario-ab`` applies here too) and the staged leg must
    reproduce the fused round's carried state and trajectories
    **bitwise** (optimization barriers are scheduling hints, not math),
    so the A/B can never publish a fast-but-wrong number. Metric keys
    carry platform and device count per the PR 6/9 honesty rules.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import (
        AgentGroup,
        FusedADMM,
        FusedADMMOptions,
        stack_params,
    )
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    qual = f"{platform},d{n_dev}"
    R = max(int(rounds), 1)
    ocp = zone_ocp()
    x0s, loads = fleet_inputs(n_agents)
    thetas = stack_params([
        ocp.default_params(
            x0=jnp.array([x0s[i]]),
            d_traj=jnp.broadcast_to(
                jnp.array([loads[i], 290.15, 294.15]), (HORIZON, 3)))
        for i in range(n_agents)])
    # fixed-iteration rounds: zero Boyd exits so both legs execute the
    # identical schedule and the identity gate compares like with like
    opts = FusedADMMOptions(
        max_iterations=ADMM_ITERS, rho=20.0, abs_tol=0.0, rel_tol=0.0,
        primal_tol=0.0, dual_tol=0.0)

    def build(fusion, **engine_kw):
        group = AgentGroup(
            name="zones", ocp=ocp, n_agents=n_agents,
            couplings={"mDotCoolAir": "mDot"},
            solver_options=SolverOptions(
                **SOLVER_BASE, mu_init=COLD_MU, fusion=fusion))
        return FusedADMM([group], opts, **engine_kw)

    legs = {}
    for fusion, label in (("require", "fused"), ("off", "staged")):
        # the fused leg also certifies its dispatch schedule — the row
        # carries digest + dispatches-per-round next to the wall-clock
        engine = build(fusion, dispatch_certify="require"
                       if label == "fused" else "auto")
        state = engine.init_state([thetas])
        state, _trajs, _stats = engine.step(state, [thetas])  # compile
        jax.block_until_ready(state)
        times, last = [], None
        for _ in range(R):
            t0 = time.perf_counter()
            state, trajs, stats = engine.step(state, [thetas])
            jax.block_until_ready(state)
            times.append(1e3 * (time.perf_counter() - t0))
            last = (state, trajs, stats)
        legs[label] = {"engine": engine, "times": times, "last": last}

    # -- identity gate: bitwise, every carried/returned leaf -----------
    fused_leaves = jax.tree.leaves(legs["fused"]["last"])
    staged_leaves = jax.tree.leaves(legs["staged"]["last"])
    identity_ok = len(fused_leaves) == len(staged_leaves) and all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(fused_leaves, staged_leaves))
    max_diff = max((float(np.max(np.abs(
        np.asarray(a, np.float64) - np.asarray(b, np.float64))))
        for a, b in zip(fused_leaves, staged_leaves)
        if np.issubdtype(np.asarray(a).dtype, np.floating)),
        default=0.0)
    if not identity_ok:
        print(f"[bench] fusion-ab: staged round DIVERGES from fused "
              f"(max |diff| = {max_diff:.3e}) — rows marked "
              f"identity_ok=false", file=sys.stderr)

    rows: list[dict] = []
    fused_engine = legs["fused"]["engine"]
    plan = fused_engine.fusion_plan
    cert = fused_engine.dispatch_certificate
    for label in ("fused", "staged"):
        times = legs[label]["times"]
        row = {
            "metric": f"fusion_ab[{label},{qual}]",
            "n_agents": n_agents, "rounds": R,
            "admm_iters": ADMM_ITERS,
            "warm_round_ms": round(min(times), 3),
            "mean_round_ms": round(sum(times) / len(times), 3),
            "identity_ok": identity_ok,
            "max_abs_diff": max_diff,
            "platform": platform, "devices": n_dev,
        }
        if label == "fused":
            row["fusion_plan"] = None if plan is None else plan.as_dict()
            row["dispatch_digest"] = fused_engine.dispatch_digest
            row["dispatches_per_round"] = (
                None if cert is None or not cert.proved
                else cert.dispatch_count())
        else:
            fused_best = min(legs["fused"]["times"])
            row["staged_over_fused"] = round(
                min(times) / max(fused_best, 1e-9), 3)
        rows.append(row)
    for row in rows:
        print(json.dumps(row))
        sys.stdout.flush()
    print(f"[bench] fusion-ab n={n_agents}: "
          f"fused={min(legs['fused']['times']):.1f}ms "
          f"staged={min(legs['staged']['times']):.1f}ms per warm round "
          f"({qual}, identity_ok={identity_ok})", file=sys.stderr)
    return rows


def run_warmstart_ab(n_agents: int = N_AGENTS) -> list[dict]:
    """``--warmstart-ab [n]``: learned warm starts A/B (ISSUE 19).

    Trains a fingerprint-stamped warm-start predictor from plain cold
    solves of an OFFSET theta grid (midpoints of the eval grid — never
    the eval points themselves), then publishes three identity-gated
    comparisons on the ``n``-zone tracker workload, all as
    platform-independent ``*_iters`` keys (iteration counts transfer
    across hosts; CPU milliseconds do not):

    1. **cold IP iterations** — the vmapped per-zone cold solve from
       the production plain start vs the gated predicted start, both
       run to convergence (tol ``SOLVER_BASE``). Identity gate: every
       converged predicted-start lane must land on the SAME solution
       as its plain-start twin — judged by equal objective value +
       feasibility of the *polished* endpoints (both continued to
       tol 1e-7, identity instrumentation only) — or the rows
       publish ``identity_ok=false``. Headline:
       ``cold_iters_reduction`` (the acceptance floor is 0.25).
    2. **fleet consensus spread, equal budgets** — one control step of
       the two-phase inexact-ADMM program (cold 10 / warm 2) from the
       plain vs the predicted initial point: the predicted start must
       hold ``consensus_spread`` no worse than plain.
    3. **warm budget 1 + predictor vs plain budget 2** — the round-4
       inner-budget ladder with the predictor paying for the dropped
       warm iteration: spread must again hold.

    The predicted legs run through the SAME in-graph quality gate that
    serves production traffic (``ml.warmstart.make_gated_init``) — a
    rejected prediction falls back to the plain point inside the jit,
    so the A/B measures the deployable path, not an unguarded oracle.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ml.training import fit_warmstart
    from agentlib_mpc_tpu.ml.warmstart import (
        build_warmstart,
        flatten_theta,
        make_gated_init,
        plain_init,
    )
    from agentlib_mpc_tpu.ops.solver import SolverOptions, solve_nlp
    from agentlib_mpc_tpu.parallel.fused_admm import stack_params
    from agentlib_mpc_tpu.serving.fingerprint import tenant_fingerprint
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    qual = f"{platform},d{n_dev}"
    ocp = zone_ocp()
    fingerprint = tenant_fingerprint(ocp).digest
    cold_opts = SolverOptions(**{**SOLVER_BASE, "max_iter": 50},
                              mu_init=COLD_MU)

    def zone_theta(x0, load):
        return ocp.default_params(
            x0=jnp.array([x0]),
            d_traj=jnp.broadcast_to(
                jnp.array([load, 290.15, 294.15]), (HORIZON, 3)))

    x0s, loads = fleet_inputs(n_agents)
    eval_thetas = stack_params(
        [zone_theta(x0s[i], loads[i]) for i in range(n_agents)])

    def cold_solve(w0, theta, y0, z0):
        lb, ub = ocp.bounds(theta)
        res = solve_nlp(ocp.nlp, w0, theta, lb, ub, cold_opts,
                        y0=y0, z0=z0)
        return (res.w, res.y, res.z,
                res.stats.iterations, res.stats.success)

    vcold = jax.jit(jax.vmap(cold_solve))
    # identity instrumentation (NOT part of any headline number): the
    # production tolerance leaves ~1% objective scatter in the
    # termination points themselves, so the limit point each start
    # converges to is estimated by continuing the solve to 1e-7
    pol_opts = SolverOptions(**{**SOLVER_BASE, "tol": 1e-7,
                                "max_iter": 60}, mu_init=1e-4)

    def polish_solve(w0, theta, y0, z0):
        lb, ub = ocp.bounds(theta)
        res = solve_nlp(ocp.nlp, w0, theta, lb, ub, pol_opts,
                        y0=y0, z0=z0)
        return (res.w, res.stats.success)

    vpolish = jax.jit(jax.vmap(polish_solve))

    # -- train on the OFFSET grid (midpoints — no eval-point leakage) --
    n_train = max(n_agents, 32)
    tx0 = np.linspace(*ZONE_X0_RANGE, n_train + 1)
    tld = np.linspace(*ZONE_LOAD_RANGE, n_train + 1)
    tx0, tld = (tx0[:-1] + tx0[1:]) / 2, (tld[:-1] + tld[1:]) / 2
    train_list = [zone_theta(tx0[i], tld[i]) for i in range(n_train)]
    train_thetas = stack_params(train_list)
    lb_t, ub_t = jax.vmap(ocp.bounds)(train_thetas)
    w0_t = jax.vmap(lambda th: ocp.initial_guess(th))(train_thetas)
    vtrain = jax.jit(jax.vmap(
        lambda w0, th, lb, ub: solve_nlp(ocp.nlp, w0, th, lb, ub,
                                         cold_opts)))
    sol_t = vtrain(w0_t, train_thetas, lb_t, ub_t)
    ok_t = np.asarray(sol_t.stats.success)
    if not ok_t.any():
        raise RuntimeError("warmstart-ab: no converged training solves")
    data = {
        "theta": np.stack([
            np.asarray(flatten_theta(th))
            for i, th in enumerate(train_list) if ok_t[i]]),
        "w": np.asarray(sol_t.w)[ok_t],
        "y": np.asarray(sol_t.y)[ok_t],
        "z": np.asarray(sol_t.z)[ok_t],
        "iterations": np.asarray(sol_t.stats.iterations)[ok_t],
    }
    # full-batch Adam to near-interpolation: the KKT merit gate needs
    # the predicted duals accurate to ~0.1% relative (the zone duals
    # are O(5e3) against constraint Jacobians in Watts), so a casually
    # trained net is rejected wholesale (measured: max |w| error 0.18
    # at 20k epochs vs 3.2 at 2k)
    model = fit_warmstart(
        data, fingerprint=fingerprint, dt=DT, val_share=0.0,
        trainer_config={"hidden": (64, 64), "epochs": 20000,
                        "learning_rate": 1e-2, "batch_size": 4096,
                        "seed": 0})
    bundle = build_warmstart(model, ocp=ocp)

    gated = jax.vmap(make_gated_init(ocp, bundle),
                     in_axes=(None, None, 0))
    plain = jax.vmap(plain_init(ocp), in_axes=(None, None, 0))
    enable = jnp.asarray(True)
    w0_p, y0_p, z0_p, _lam, _src = plain(bundle.params, enable,
                                         eval_thetas)
    w0_g, y0_g, z0_g, _lam, src = gated(bundle.params, enable,
                                        eval_thetas)
    src = np.asarray(src)
    accepted_frac = float((src == 1).mean())

    # -- leg 1: cold IP iterations to convergence ----------------------
    legs = {}
    for label, (w0, y0, z0) in (("plain", (w0_p, y0_p, z0_p)),
                                ("predicted", (w0_g, y0_g, z0_g))):
        w, y, z, iters, ok = vcold(w0, eval_thetas, y0, z0)
        wp, okp = vpolish(w, eval_thetas, y, z)
        legs[label] = {"w": np.asarray(w),
                       "w_pol": np.asarray(wp),
                       "ok_pol": np.asarray(okp),
                       "iters": np.asarray(iters),
                       "ok": np.asarray(ok)}
    both_ok = legs["plain"]["ok"] & legs["predicted"]["ok"]
    w_pl, w_pr = legs["plain"]["w"], legs["predicted"]["w"]
    max_w_diff = float(np.max(np.abs(w_pl - w_pr)[both_ok])) \
        if both_ok.any() else float("inf")
    # identity = both starts converge to the SAME solution: equal
    # objective value + equal feasibility of the LIMIT POINTS, judged
    # over lanes both legs converge (a lane the plain start cannot
    # converge either is the workload's, not the predictor's) — but
    # the predictor must never converge FEWER lanes than plain. Two
    # measurement traps, both hit while building this leg:
    #   * the zone optimum is non-unique (decision-variable scatter
    #     between two converged plain-start runs is ~0.25 and does NOT
    #     shrink when the tolerance is tightened: a flat valley), so
    #     raw |w_pred - w_plain| cannot distinguish "different
    #     solution" from "different point of the same valley";
    #   * the tol-1e-4 termination points themselves scatter up to
    #     ~1% in objective around the limit point (in BOTH
    #     directions — on some lanes the plain endpoint is the one
    #     far out), so comparing unpolished endpoints misreads loose
    #     termination as a basin flip. Polishing both endpoints to
    #     1e-7 collapses the worst lane's rel diff 0.113 -> 0.0023.
    # Hence the objective/feasibility comparison runs on the polished
    # endpoints; the unpolished scatter is published alongside.
    vobj = jax.jit(jax.vmap(lambda w, th: ocp.nlp.f(w, th)))
    vviol = jax.jit(jax.vmap(lambda w, th: jnp.maximum(
        jnp.max(jnp.abs(ocp.nlp.g(w, th))) if ocp.n_g else 0.0,
        jnp.max(jnp.maximum(-ocp.nlp.h(w, th), 0.0)) if ocp.n_h
        else 0.0)))
    both_pol = (both_ok & legs["plain"]["ok_pol"]
                & legs["predicted"]["ok_pol"])
    wp_pl = legs["plain"]["w_pol"]
    wp_pr = legs["predicted"]["w_pol"]
    f_pl = np.asarray(vobj(jnp.asarray(wp_pl), eval_thetas))
    f_pr = np.asarray(vobj(jnp.asarray(wp_pr), eval_thetas))
    v_pl = np.asarray(vviol(jnp.asarray(wp_pl), eval_thetas))
    v_pr = np.asarray(vviol(jnp.asarray(wp_pr), eval_thetas))
    f_pl_raw = np.asarray(vobj(jnp.asarray(w_pl), eval_thetas))
    f_pr_raw = np.asarray(vobj(jnp.asarray(w_pr), eval_thetas))

    def _rel(a, b, mask):
        return float(np.max(np.abs(a - b)[mask]
                            / np.maximum(1.0, np.abs(a)[mask]))) \
            if mask.any() else float("inf")

    obj_rel_diff = _rel(f_pl, f_pr, both_pol)
    obj_rel_diff_unpolished = _rel(f_pl_raw, f_pr_raw, both_ok)
    # ident_tol is calibrated against a measured A/A control: the SAME
    # polished comparison between two PLAIN-start runs (one start
    # perturbed by 1e-2) over the 256-lane workload scatters up to
    # 6.1e-3 rel (p99 2.9e-3, 236 lanes) — the flat valley plus the
    # dual-scaled termination test leave that much objective
    # indeterminacy even at polish tol 1e-7. 7.5e-3 is that A/A max
    # with ~20% headroom; a genuinely different valley shows as O(1).
    ident_tol = 7.5e-3
    identity_ok = bool(
        both_pol.any() and obj_rel_diff <= ident_tol
        and float(np.max(v_pr[both_pol]))
        <= max(float(np.max(v_pl[both_pol])), 1e-2)
        and legs["predicted"]["ok"].sum() >= legs["plain"]["ok"].sum())
    cold_plain = float(legs["plain"]["iters"].mean())
    cold_pred = float(legs["predicted"]["iters"].mean())
    reduction = 1.0 - cold_pred / max(cold_plain, 1e-9)

    # -- legs 2+3: fleet consensus spread (two-phase inexact ADMM) -----
    def fleet_leg(warm_budget, w_gs, y_gs, z_gs, zbar=None, lams=None):
        step, args = build_step(n_agents, warm_budget=warm_budget,
                                record_stats=True)
        zb = args[5] if zbar is None else zbar
        lm = args[6] if lams is None else lams
        carry, stats = step(args[0], args[1], w_gs, y_gs, z_gs,
                            zb, lm, args[7])
        jax.block_until_ready(carry)
        w_out, _y, _z, zbar_out, _lams = carry
        u = jax.vmap(lambda w: ocp.unflatten(w)["u"])(w_out)
        spread = float(jnp.max(jnp.abs(u - zbar_out)))
        inner = float(np.asarray(stats[2]).sum(axis=0).mean())
        return spread, inner

    _s, args0 = build_step(n_agents, record_stats=True)
    plain_gs = (args0[2], args0[3], args0[4])
    pred_gs = (w0_g, y0_g, z0_g)
    # consensus cold-phase seeding from the predictor: zbar starts at
    # the fleet-mean predicted control trajectory, and the consensus
    # duals get one ADMM dual update pre-applied (lam0 =
    # rho*(u_pred - zbar0) instead of zeros) — the predicted initial
    # point flowing through the FusedADMM cold phase, not just the
    # per-agent NLP starts
    u_pred = jax.vmap(lambda w: ocp.unflatten(w)["u"])(w0_g)
    zbar_pred = u_pred.mean(axis=0)
    lam_pred = args0[7] * (u_pred - zbar_pred[None])
    spread_plain2, inner_plain2 = fleet_leg(2, *plain_gs)
    spread_pred2, inner_pred2 = fleet_leg(2, *pred_gs,
                                          zbar=zbar_pred, lams=lam_pred)
    spread_pred1, inner_pred1 = fleet_leg(1, *pred_gs,
                                          zbar=zbar_pred, lams=lam_pred)
    # equality to the round-4 sweeps' resolution; the spread floor is
    # the solver tolerance, not zero
    spread_tol = 1e-4
    spread2_ok = spread_pred2 <= spread_plain2 + spread_tol
    budget1_ok = spread_pred1 <= spread_plain2 + spread_tol

    rows: list[dict] = [
        {"metric": f"warmstart_ab[cold_plain,{qual}]",
         "n_agents": n_agents,
         "cold_iters_mean": round(cold_plain, 3),
         "cold_iters_max": int(legs["plain"]["iters"].max()),
         "converged_frac": float(legs["plain"]["ok"].mean()),
         "identity_ok": identity_ok, "platform": platform,
         "devices": n_dev},
        {"metric": f"warmstart_ab[cold_predicted,{qual}]",
         "n_agents": n_agents,
         "cold_iters_mean": round(cold_pred, 3),
         "cold_iters_max": int(legs["predicted"]["iters"].max()),
         "converged_frac": float(legs["predicted"]["ok"].mean()),
         "cold_iters_reduction": round(reduction, 4),
         "gate_accepted_frac": accepted_frac,
         "identity_ok": identity_ok,
         "obj_rel_diff": obj_rel_diff, "identity_tol": ident_tol,
         "obj_rel_diff_unpolished": obj_rel_diff_unpolished,
         "identity_lanes": int(both_pol.sum()),
         "max_w_diff": max_w_diff,
         "train_rows": int(ok_t.sum()),
         "platform": platform, "devices": n_dev},
        {"metric": f"warmstart_ab[fleet_plain_b2,{qual}]",
         "n_agents": n_agents, "warm_budget": 2,
         "consensus_spread": round(spread_plain2, 6),
         "inner_iters_per_agent": round(inner_plain2, 3),
         "platform": platform, "devices": n_dev},
        {"metric": f"warmstart_ab[fleet_predicted_b2,{qual}]",
         "n_agents": n_agents, "warm_budget": 2,
         "consensus_spread": round(spread_pred2, 6),
         "inner_iters_per_agent": round(inner_pred2, 3),
         "spread_ok": bool(spread2_ok), "dual_seeded": True,
         "platform": platform, "devices": n_dev},
        {"metric": f"warmstart_ab[fleet_predicted_b1,{qual}]",
         "n_agents": n_agents, "warm_budget": 1,
         "consensus_spread": round(spread_pred1, 6),
         "inner_iters_per_agent": round(inner_pred1, 3),
         "spread_ok": bool(budget1_ok), "dual_seeded": True,
         "baseline": "fleet_plain_b2",
         "platform": platform, "devices": n_dev},
    ]
    for row in rows:
        print(json.dumps(row))
        sys.stdout.flush()
    print(f"[bench] warmstart-ab n={n_agents}: cold "
          f"{cold_plain:.1f} -> {cold_pred:.1f} iters "
          f"({100 * reduction:.0f}% cut, gate accepted "
          f"{100 * accepted_frac:.0f}%), spread plain-b2 "
          f"{spread_plain2:.5f} / pred-b2 {spread_pred2:.5f} / "
          f"pred-b1 {spread_pred1:.5f} ({qual}, "
          f"identity_ok={identity_ok})", file=sys.stderr)
    return rows


def run_precision_ab(n_agents: int = N_AGENTS) -> list[dict]:
    """``--precision-ab [n]``: certificate-gated mixed precision A/B
    (ISSUE 20).

    A = the full-precision IPM (``SolverOptions.precision="f64"``),
    B = the certified-mixed routing (``precision="mixed"``: eval_jac /
    assemble contractions at bf16-input/f32-accumulate, the Hessian
    rounded through bf16 storage, factor/resolve/line-search untouched)
    on the ``n``-zone cold-solve workload. Identity gate is the ISSUE
    19 methodology verbatim: both legs' endpoints are POLISHED to tol
    1e-7 at full precision (limit-point estimation — the production
    tolerance leaves ~1% objective scatter in the endpoints
    themselves), and the mixed leg must land within the noise floor an
    A/A control (two full-precision runs, one start perturbed 1e-2)
    measures ON THIS RUN — never a hardcoded constant.

    Honesty rows: every mixed number publishes under a
    ``_mixed``-qualified key (the :func:`_qualified_metric` rule — a
    mixed solve can never read as a full-precision headline); the
    build-time :class:`~agentlib_mpc_tpu.lint.jaxpr.precision.
    PrecisionCertificate` is published next to the measurements with
    its per-phase table + digest, plus the agreement check the
    acceptance demands: the runtime stats label says "mixed" iff the
    routing ran narrow, and every phase the routing narrows is a phase
    the certificate certifies bf16 (refuted/full phases provably stay
    at certified precision — they are never wrapped by the narrow
    context). The projected HBM/collective-bytes saving comes from the
    cost model's what-if width (:func:`op_cost` ``itemsize_override=2``
    — an upper bound: ALL float traffic recosted at bf16 width)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.lint.jaxpr.cost import op_cost
    from agentlib_mpc_tpu.lint.jaxpr.precision import (
        MIXED_NARROW_PHASES,
        certify_solver_precision,
    )
    from agentlib_mpc_tpu.ops.solver import (
        SolverOptions,
        precision_path_name,
        solve_nlp,
    )
    from agentlib_mpc_tpu.parallel.fused_admm import stack_params
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    qual = f"{platform},d{n_dev}"
    ocp = zone_ocp()
    base = {**SOLVER_BASE, "max_iter": 50}
    opts_full = SolverOptions(**base, mu_init=COLD_MU, precision="f64")
    opts_mixed = SolverOptions(**base, mu_init=COLD_MU,
                               precision="mixed")
    pol_opts = SolverOptions(**{**SOLVER_BASE, "tol": 1e-7,
                                "max_iter": 60}, mu_init=1e-4,
                             precision="f64")

    def zone_theta(x0, load):
        return ocp.default_params(
            x0=jnp.array([x0]),
            d_traj=jnp.broadcast_to(
                jnp.array([load, 290.15, 294.15]), (HORIZON, 3)))

    x0s, loads = fleet_inputs(n_agents)
    thetas = stack_params(
        [zone_theta(x0s[i], loads[i]) for i in range(n_agents)])
    w0 = jax.vmap(lambda th: ocp.initial_guess(th))(thetas)

    def solver(opts):
        def one(w0, theta):
            lb, ub = ocp.bounds(theta)
            res = solve_nlp(ocp.nlp, w0, theta, lb, ub, opts)
            return (res.w, res.y, res.z, res.stats.iterations,
                    res.stats.success, res.stats.precision_path)
        return jax.jit(jax.vmap(one))

    def polish(w, theta, y, z):
        lb, ub = ocp.bounds(theta)
        res = solve_nlp(ocp.nlp, w, theta, lb, ub, pol_opts,
                        y0=y, z0=z)
        return res.w, res.stats.success
    vpolish = jax.jit(jax.vmap(polish))

    legs = {}
    for label, opts, starts in (
            ("full", opts_full, w0),
            ("mixed", opts_mixed, w0),
            # the A/A control: full precision from a perturbed start —
            # the same-valley scatter the identity gate must tolerate
            ("aa", opts_full, w0 + 1e-2)):
        w, y, z, iters, ok, path = solver(opts)(starts, thetas)
        wp, okp = vpolish(w, thetas, y, z)
        legs[label] = {
            "w_pol": np.asarray(wp), "ok_pol": np.asarray(okp),
            "iters": np.asarray(iters), "ok": np.asarray(ok),
            "path": precision_path_name(path)}

    vobj = jax.jit(jax.vmap(lambda w, th: ocp.nlp.f(w, th)))
    vviol = jax.jit(jax.vmap(lambda w, th: jnp.maximum(
        jnp.max(jnp.abs(ocp.nlp.g(w, th))) if ocp.n_g else 0.0,
        jnp.max(jnp.maximum(-ocp.nlp.h(w, th), 0.0)) if ocp.n_h
        else 0.0)))

    def _rel(a, b, mask):
        return float(np.max(np.abs(a - b)[mask]
                            / np.maximum(1.0, np.abs(a)[mask]))) \
            if mask.any() else float("inf")

    f_legs = {k: np.asarray(vobj(jnp.asarray(v["w_pol"]), thetas))
              for k, v in legs.items()}
    v_legs = {k: np.asarray(vviol(jnp.asarray(v["w_pol"]), thetas))
              for k, v in legs.items()}
    ok_fm = (legs["full"]["ok"] & legs["mixed"]["ok"]
             & legs["full"]["ok_pol"] & legs["mixed"]["ok_pol"])
    ok_aa = (legs["full"]["ok"] & legs["aa"]["ok"]
             & legs["full"]["ok_pol"] & legs["aa"]["ok_pol"])
    obj_rel_mixed = _rel(f_legs["full"], f_legs["mixed"], ok_fm)
    obj_rel_aa = _rel(f_legs["full"], f_legs["aa"], ok_aa)
    # noise floor = this run's measured A/A max with 20% headroom,
    # floored at the ISSUE 19 calibration (7.5e-3) so a lucky A/A
    # cannot tighten the gate below the workload's known indeterminacy
    ident_tol = max(1.2 * obj_rel_aa, 7.5e-3)
    # feasibility ceiling, A/A-calibrated like the objective: the
    # polished-endpoint violation max is a heavy-tailed one-lane
    # statistic — the full and A/A legs span ~2.5x between their own
    # maxima on this workload (5.2e-3 / 6.9e-3 raw at n=256, medians
    # and p99 identical across legs) — so the mixed leg is held to 2x
    # the worst same-precision envelope, floored at 1e-2 raw (~2e-5
    # relative on the O(500 W) dynamics scale). A routing-induced
    # feasibility loss (a bf16-rounded Jacobian driving the active
    # set wrong) sits orders of magnitude above this band.
    viol_env = max(
        float(np.max(v_legs["full"][ok_fm])) if ok_fm.any() else 0.0,
        float(np.max(v_legs["aa"][ok_aa])) if ok_aa.any() else 0.0)
    viol_tol = max(2.0 * viol_env, 1e-2)
    viol_mixed = float(np.max(v_legs["mixed"][ok_fm])) \
        if ok_fm.any() else float("inf")
    identity_ok = bool(
        ok_fm.any() and obj_rel_mixed <= ident_tol
        and viol_mixed <= viol_tol
        and legs["mixed"]["ok"].sum() >= legs["full"]["ok"].sum())

    # -- certificate + stats-label agreement ---------------------------
    theta0 = zone_theta(float(x0s[0]), float(loads[0]))
    lb0, ub0 = ocp.bounds(theta0)
    cert = certify_solver_precision(
        ocp.nlp, theta0, ocp.n_w, w_lb=lb0, w_ub=ub0,
        options=opts_full)
    cert_table = {v.phase: v.certified_dtype for v in cert.phases}
    bf16_certified = {p for p, d in cert_table.items() if d == "bf16"}
    # the routing narrows exactly MIXED_NARROW_PHASES — agreement means
    # the stats label matches the leg's routing AND every narrowed
    # phase present in the program carries a bf16 proof (a refuted /
    # full-only phase is never wrapped by the narrow context, so it
    # provably ran at certified precision in BOTH legs)
    labels_ok = (legs["full"]["path"] == "full"
                 and legs["mixed"]["path"] == "mixed")
    routing_certified = all(p in bf16_certified
                            for p in MIXED_NARROW_PHASES
                            if p in cert_table)
    cert_agrees = bool(labels_ok and (cert.status != "proved"
                                      or routing_certified))

    # -- projected traffic saving (cost-model what-if width) -----------
    def one_full(w0_single):
        lb, ub = ocp.bounds(theta0)
        return solve_nlp(ocp.nlp, w0_single, theta0, lb, ub,
                         opts_full).w
    closed = jax.make_jaxpr(one_full)(np.asarray(w0)[0])
    cost_f = op_cost(closed, while_trips=base["max_iter"])
    cost_n = op_cost(closed, while_trips=base["max_iter"],
                     itemsize_override=2)
    hbm_ratio = cost_n.bytes_accessed / max(cost_f.bytes_accessed, 1)
    comm_ratio = (cost_n.collective_bytes
                  / max(cost_f.collective_bytes, 1)) \
        if cost_f.collective_bytes else None

    key_mixed = _qualified_metric("precision_ab_cold_iters", platform,
                                  n_dev, precision="mixed")
    rows: list[dict] = [
        {"metric": f"precision_ab[full,{qual}]",
         "n_agents": n_agents,
         "cold_iters_mean": round(float(legs["full"]["iters"].mean()),
                                  3),
         "converged_frac": float(legs["full"]["ok"].mean()),
         "precision_path": legs["full"]["path"],
         "identity_ok": identity_ok, "platform": platform,
         "devices": n_dev},
        {"metric": f"precision_ab[mixed,{qual}]",
         "qualified_key": key_mixed,
         "precision": "mixed",
         "n_agents": n_agents,
         "cold_iters_mean": round(float(legs["mixed"]["iters"].mean()),
                                  3),
         "converged_frac": float(legs["mixed"]["ok"].mean()),
         "precision_path": legs["mixed"]["path"],
         "identity_ok": identity_ok,
         "obj_rel_diff": obj_rel_mixed,
         "identity_tol": ident_tol,
         "aa_noise_floor": obj_rel_aa,
         "viol_max": viol_mixed,
         "viol_tol": viol_tol,
         "identity_lanes": int(ok_fm.sum()),
         "stats_label_agrees": cert_agrees,
         "platform": platform, "devices": n_dev},
        {"metric": f"precision_ab[certificate,{qual}]",
         "status": cert.status,
         "phases": cert_table,
         "precision_digest": cert.precision_digest,
         "refutations": list(cert.refutations),
         "routing_certified": routing_certified,
         "projected_hbm_bytes_ratio": round(hbm_ratio, 4),
         "projected_collective_bytes_ratio": comm_ratio,
         "hbm_bytes_full": int(cost_f.bytes_accessed),
         "hbm_bytes_bf16_bound": int(cost_n.bytes_accessed),
         "platform": platform, "devices": n_dev},
    ]
    for row in rows:
        print(json.dumps(row))
        sys.stdout.flush()
    print(f"[bench] precision-ab n={n_agents}: full "
          f"{legs['full']['iters'].mean():.1f} / mixed "
          f"{legs['mixed']['iters'].mean():.1f} iters, obj rel "
          f"{obj_rel_mixed:.2e} vs floor {ident_tol:.2e} "
          f"(identity_ok={identity_ok}), certificate {cert.status} "
          f"(digest {cert.precision_digest}), projected HBM x"
          f"{hbm_ratio:.2f} ({qual})", file=sys.stderr)
    return rows


def run_chaos(seed: int = 0, n_agents: int = 4) -> dict:
    """``--chaos SEED``: deterministic resilience smoke on the fused
    plane. Builds the ``n_agents``-zone consensus fleet as a
    :class:`FusedADMM` engine (quarantine ON — the production
    configuration), runs one healthy round, then NaN-poisons a
    seeded-random agent's parameters and runs another. The quarantine
    contract (``docs/robustness.md``): the poisoned agent's non-finite
    local solutions are substituted inside the jit, so consensus means,
    warm starts and every healthy agent's trajectories stay finite, and
    the poisoning causes zero additional retraces. Mirrors the tier-1
    chaos tests; here it runs on whatever the process's default platform
    is, so the driver can exercise the same path on real hardware."""
    import random

    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import (
        AgentGroup,
        FusedADMM,
        FusedADMMOptions,
        stack_params,
    )
    from agentlib_mpc_tpu.utils.jax_setup import (
        enable_compile_profiling,
        enable_persistent_cache,
    )
    from agentlib_mpc_tpu import telemetry

    enable_persistent_cache()
    telemetry.configure(enabled=True)
    telemetry.reset()
    enable_compile_profiling()

    rng = random.Random(f"bench-chaos:{seed}")
    ocp = zone_ocp()
    group = AgentGroup(
        name="zones", ocp=ocp, n_agents=n_agents,
        couplings={"mDotCoolAir": "mDot"},
        solver_options=SolverOptions(**SOLVER_BASE))
    engine = FusedADMM([group], FusedADMMOptions(
        max_iterations=ADMM_ITERS, rho=20.0))
    x0s, loads = fleet_inputs(n_agents)
    thetas = stack_params([
        ocp.default_params(
            x0=jnp.array([x0s[i]]),
            d_traj=jnp.broadcast_to(
                jnp.array([loads[i], 290.15, 294.15]), (HORIZON, 3)))
        for i in range(n_agents)])
    state = engine.init_state([thetas])
    state, _, _ = engine.step(state, [thetas])     # healthy warm round
    retraces_before = telemetry.metrics().counter(
        "jax_retraces_total").total()

    victim = rng.randrange(n_agents)
    poisoned = jax.tree.map(
        lambda leaf: leaf.at[victim].set(jnp.nan)
        if hasattr(leaf, "ndim") and leaf.ndim >= 1
        and leaf.shape[0] == n_agents else leaf, thetas)
    state, trajs, stats = engine.step(state, [poisoned])

    # EVERY carried leaf, multipliers included — lam is where an unmasked
    # NaN consensus mean would hide while zbar/w stay finite
    finite_state = all(
        bool(jnp.all(jnp.isfinite(leaf)))
        for leaf in jax.tree.leaves(state))
    healthy_u = np.asarray(trajs[0]["u"])[
        [i for i in range(n_agents) if i != victim]]
    out = {
        "metric": "chaos_smoke",
        "seed": seed,
        "n_agents": n_agents,
        "poisoned_agent": victim,
        "quarantined_agent_iters": int(
            np.asarray(stats.quarantined).sum()),
        "state_finite": bool(finite_state),
        "healthy_trajectories_finite": bool(np.isfinite(healthy_u).all()),
        "extra_retraces": int(telemetry.metrics().counter(
            "jax_retraces_total").total() - retraces_before),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    return out


def run_serve(seed: int = 0, n_tenants: int = 8, rounds: int = 40) -> dict:
    """``--serve SEED [n]``: sustained-throughput benchmark of the
    serving dispatch plane (``agentlib_mpc_tpu/serving/``) under seeded
    tenant churn from the chaos harness.

    ``n_tenants`` LinearRCZone tenants (the QP-fast-path workload)
    join/leave a :class:`ServingPlane` following the deterministic
    :func:`~agentlib_mpc_tpu.resilience.chaos.churn_schedule`; every
    active tenant submits one solve request per round with drifting
    initial state. The SAME schedule runs twice — once through the
    synchronous dispatch loop and once through the donated, depth-1
    pipelined one — so the per-round dispatch overhead the pipeline
    hides is measured in situ, not modeled. Reported: solves/sec and
    p50/p99 round latency (pipelined plane, the production
    configuration), the sync-vs-pipelined mean round-time A/B, cold vs
    cached join latency (the compile-cache story: a structurally
    identical rejoin must be orders of magnitude cheaper than the first
    build), compile-cache hit/miss counts, shed counts and warm-phase
    retraces (must be 0 — churn is data, not structure).

    The headline metric is platform-qualified off the accelerator
    (``serve_solves_per_sec_<platform>``) exactly like the ADMM
    trajectory row.
    """
    import random as _random

    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
    from agentlib_mpc_tpu.resilience.chaos import churn_schedule
    from agentlib_mpc_tpu.serving import ServingPlane, TenantSpec
    from agentlib_mpc_tpu.utils.jax_setup import (
        enable_compile_profiling,
        enable_persistent_cache,
    )

    enable_persistent_cache()
    telemetry.configure(enabled=True)
    telemetry.reset()
    enable_compile_profiling()

    ocp = linear_zone_ocp()
    schedule = churn_schedule(seed, n_tenants, rounds)
    rng = _random.Random(f"bench-serve:{seed}")
    x0_base = {f"t{i:03d}": 294.0 + 6.0 * i / max(n_tenants - 1, 1)
               for i in range(n_tenants)}

    def theta_for(tid, drift=0.0):
        return ocp.default_params(
            x0=jnp.array([x0_base[tid] + drift]),
            d_traj=jnp.broadcast_to(
                jnp.array([150.0, 303.15, 295.15]), (HORIZON, 3)))

    def make_spec(tid):
        return TenantSpec(
            tenant_id=tid, ocp=ocp, theta=theta_for(tid),
            couplings={"power": "Q"},
            solver_options=SolverOptions(**SOLVER_BASE),
            deadline_s=60.0)

    def run_plane(pipelined: bool) -> dict:
        plane = ServingPlane(
            FusedADMMOptions(max_iterations=5, rho=5e-3),
            initial_capacity=n_tenants, pipelined=pipelined,
            donate=pipelined, queue_limit=4 * n_tenants)
        joins = {"cold": [], "cached": []}
        walls, delivered = [], 0
        retrace_counter = telemetry.metrics().counter("jax_retraces_total")
        retr_mark = None
        for r, events in enumerate(schedule):
            for kind, tid in events:
                if kind == "join":
                    rec = plane.join(make_spec(tid))
                    joins["cached" if rec.engine_cached
                          else "cold"].append(rec.latency_s)
                elif tid in plane.tenants:
                    plane.leave(tid)
            for tid in plane.tenants:
                plane.submit(tid, theta=theta_for(
                    tid, drift=rng.uniform(-0.5, 0.5)))
            t0 = time.perf_counter()
            res = plane.serve_round()
            walls.append(time.perf_counter() - t0)
            delivered += len(res)
            if r == 0:
                # membership churn and request traffic beyond this
                # point are DATA; any retrace would be a regression
                retr_mark = retrace_counter.total()
        delivered += len(plane.flush())
        warm_retraces = retrace_counter.total() - (retr_mark or 0.0)
        serving_s = float(np.sum(walls))
        warm_walls = np.asarray(walls[1:] if len(walls) > 1 else walls)
        return {
            "plane": plane,
            "joins": joins,
            "delivered": delivered,
            "serving_s": serving_s,
            "solves_per_sec": delivered / serving_s if serving_s else 0.0,
            "round_ms_mean": float(1e3 * warm_walls.mean()),
            "round_ms_p50": float(1e3 * np.percentile(warm_walls, 50)),
            "round_ms_p99": float(1e3 * np.percentile(warm_walls, 99)),
            "warm_retraces": int(warm_retraces),
        }

    sync = run_plane(pipelined=False)
    piped = run_plane(pipelined=True)

    def join_ms(vals):
        return round(1e3 * float(np.mean(vals)), 2) if vals else None

    platform = jax.devices()[0].platform
    metric = "serve_solves_per_sec" if platform == "tpu" \
        else f"serve_solves_per_sec_{platform}"
    # the headline is the AUTO-resolved production configuration's
    # throughput (ServingPlane defaults: sync on CPU — where the
    # measured pipeline A/B is parity-to-negative — pipelined on
    # accelerators); both columns always ride along
    auto = sync if platform == "cpu" else piped
    stats = auto["plane"].stats()
    out = {
        "metric": metric,
        "value": round(auto["solves_per_sec"], 2),
        "config": "sync" if platform == "cpu" else "pipelined",
        "unit": "solves/s",
        "seed": seed,
        "n_tenants": n_tenants,
        "rounds": rounds,
        "round_ms_p50": round(auto["round_ms_p50"], 2),
        "round_ms_p99": round(auto["round_ms_p99"], 2),
        "sync_round_ms_mean": round(sync["round_ms_mean"], 2),
        "pipelined_round_ms_mean": round(piped["round_ms_mean"], 2),
        #: what the donated async pipeline saves per round vs the
        #: synchronous loop, same schedule, same hardware
        "dispatch_overhead_saved_ms": round(
            sync["round_ms_mean"] - piped["round_ms_mean"], 2),
        "sync_solves_per_sec": round(sync["solves_per_sec"], 2),
        "join_cold_ms": join_ms(auto["joins"]["cold"]),
        "join_cached_ms": join_ms(auto["joins"]["cached"]),
        "cache": stats["cache"],
        "queue": stats["queue"],
        "warm_retraces": sync["warm_retraces"] + piped["warm_retraces"],
        "platform": platform,
    }
    print(json.dumps(out))
    return out


def _bench_journal(tag: str):
    """Arm the flight recorder for a chaos bench. ``CHAOS_JOURNAL``
    names the file (kept afterwards — CI points the incident CLI at
    it); otherwise a temp file is used and removed after the closing
    assertion reads it back. Returns (path, tmp_dir_or_None)."""
    import tempfile

    from agentlib_mpc_tpu import telemetry

    path = os.environ.get("CHAOS_JOURNAL")
    tmp = None
    if not path:
        tmp = tempfile.mkdtemp(prefix=f"{tag}-journal-")
        path = os.path.join(tmp, "journal.jsonl")
    journal = telemetry.enable_journal(path)
    # a pre-existing CHAOS_JOURNAL (a re-run onto the same tape —
    # sequence numbers resume by design) must not leak the EARLIER
    # run's injections into this run's closing assertion: remember
    # where this run starts
    return path, tmp, journal.stats()["last_seq"]


def _bench_journal_close(path: str, tmp, chaos, base_seq: int = 0,
                         min_complete_chains: int = 1):
    """The chaos benches' CLOSING ASSERTION (ISSUE 15): chaos is a test
    of observability, not just of survival. Asserts (a) the FULL
    injected schedule is reconstructible from the journal alone —
    every (rule, target) the controller injected appears as a
    ``chaos.injected`` event with rule, target and round stamp — and
    (b) the incident builder joins at least ``min_complete_chains``
    injections to an observed symptom AND recovery. Returns
    (journal_stats, incident_summary, events)."""
    import shutil

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.telemetry import journal as journal_mod
    from agentlib_mpc_tpu.telemetry.incident import build_incident

    active = telemetry.journal_active()
    stats = active.stats() if active is not None else None
    telemetry.disable_journal()
    events = [e for e in journal_mod.read_events(path)
              if int(e.get("seq", 0)) > int(base_seq)]
    recorded = [e for e in events if e.get("etype") == "chaos.injected"]
    injected = sorted((str(e.get("rule")), str(e.get("target")))
                      for e in recorded)
    ground = sorted((str(k), str(w)) for k, w in chaos.events)
    assert injected == ground, (
        f"injected chaos schedule is NOT reconstructible from the "
        f"journal alone: journal={injected} controller={ground}")
    for e in recorded:
        assert e.get("rule") and e.get("target") is not None \
            and e.get("round") is not None, (
            f"chaos.injected event lacks rule/target/round: {e}")
    incident = build_incident(events)
    assert incident["complete_chains"] >= min_complete_chains, (
        f"incident reconstruction joined only "
        f"{incident['complete_chains']} injection→symptom→recovery "
        f"chain(s), need >= {min_complete_chains}: "
        f"{[(c['injection'].get('rule'), c['status']) for c in incident['chains']]}")
    summary = {
        "complete_chains": incident["complete_chains"],
        "chains": [{"rule": c["injection"].get("rule"),
                    "round": c["injection"].get("round"),
                    "status": c["status"],
                    "symptom": (c["symptom"] or {}).get("etype"),
                    "recovery": (c["recovery"] or {}).get("etype")}
                   for c in incident["chains"]],
        "events_total": incident["events_total"],
    }
    if tmp:
        shutil.rmtree(tmp, ignore_errors=True)
    return stats, summary, events


def run_chaos_serve(seed: int = 0, n_tenants: int = 6,
                    rounds: int = 24) -> dict:
    """``--chaos-serve SEED [n]``: survivability benchmark of the
    serving plane under a seeded fault schedule (the PR 2 chaos
    machinery cashed in at the serving layer).

    ``n_tenants`` tracker tenants split across TWO structure buckets
    (different warm budgets) join a plane armed with the health ladder
    and the dispatch watchdog, then serve ``rounds`` control rounds
    while the schedule injects, deterministically from ``seed``:

    1. a **NaN storm** on one victim tenant (every submission inside
       the window carries an all-NaN parameter tree — the bad-sensor
       feed): the door rejects each poisoned submission, the victim
       walks quarantine → eviction, its bucket's other tenants keep
       actuating, and it re-admits on probation after the window;
    2. a **dispatcher stall** (one round's readback hangs): the
       watchdog times the round out, sheds its tenants into their
       ladders, and the dispatcher continues synchronously;
    3. a **process crash** mid-run: the plane is checkpointed, dropped,
       and restored into a fresh plane against the warm compile cache —
       the restore wall-clock is the reported **MTTR** (cached-join
       splices; 0 cold builds is the contract).

    Reported: availability (actuated ÷ expected actuations — degraded
    replay/hold/fallback rounds count as unavailable), shed rate,
    eviction/readmission/stall counts, crash-restart MTTR (total and
    per tenant) and the restore's cold-build count. Platform-qualified
    like every serving metric.
    """
    import shutil
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
    from agentlib_mpc_tpu.resilience.chaos import (
        ServeChaosConfig,
        ServeNaNStormRule,
        ServeStallRule,
        install_serving_chaos,
    )
    from agentlib_mpc_tpu.serving import (
        HealthPolicy,
        ServingPlane,
        TenantSpec,
    )
    from agentlib_mpc_tpu.utils.jax_setup import (
        enable_compile_profiling,
        enable_persistent_cache,
    )

    enable_persistent_cache()
    telemetry.configure(enabled=True)
    telemetry.reset()
    enable_compile_profiling()
    journal_path, journal_tmp, journal_base = _bench_journal(
        "chaos-serve")

    import random as _random

    rng = _random.Random(f"bench-chaos-serve:{seed}")
    ocp = tracker_ocp()
    ids = [f"t{i:03d}" for i in range(n_tenants)]
    # two structure buckets: even tenants run the 30-iteration solver,
    # odd ones 31 — identical physics, distinct executables, so the
    # crash restore exercises the multi-bucket path
    specs = {
        tid: TenantSpec(
            tenant_id=tid, ocp=ocp,
            theta=ocp.default_params(
                p=jnp.array([float(i - n_tenants // 2)])),
            couplings={},
            solver_options=SolverOptions(max_iter=30 + (i % 2)))
        for i, tid in enumerate(ids)
    }
    victim = rng.choice(ids)
    storm_start = rng.randrange(2, 5)
    storm_len = rng.randrange(4, 7)
    stall_call = storm_start + storm_len + rng.randrange(1, 3)
    crash_round = min(rounds - 4, stall_call + rng.randrange(3, 5))
    health = HealthPolicy(quarantine_after=1, evict_after=2,
                          readmit_after=2, probation_rounds=2)

    def build_plane(cache=None):
        return ServingPlane(
            FusedADMMOptions(max_iterations=5, rho=2.0),
            slot_multiple=1, initial_capacity=n_tenants,
            pipelined=False, donate=False, queue_limit=4 * n_tenants,
            health_policy=health, watchdog_timeout_s=10.0, cache=cache)

    plane = build_plane()
    join_cold = []
    for tid in ids:
        rec = plane.join(specs[tid])
        if not rec.engine_cached:
            join_cold.append(rec.latency_s)
    chaos = install_serving_chaos(plane, ServeChaosConfig(
        nan_storm=(ServeNaNStormRule(tenant=victim,
                                     start_round=storm_start,
                                     n_rounds=storm_len),),
        stall=(ServeStallRule(call=stall_call, duration_s=30.0),),
    ), seed=seed)

    expected = actuated = shed = 0
    mttr = None
    restore_report = None
    ckpt_dir = tempfile.mkdtemp(prefix="chaos-serve-ckpt-")
    try:
        for r in range(rounds):
            if r == crash_round:
                # "crash": checkpoint, drop the plane, restore into a
                # fresh one against the warm compile cache (the
                # supervisor-restart model; cross-process the
                # persistent XLA cache plays the warm-cache role)
                chaos.uninstall()
                path = plane.save_checkpoint(
                    os.path.join(ckpt_dir, "plane"))
                cache = plane.cache
                del plane
                t0 = time.perf_counter()
                plane = build_plane(cache=cache)
                restore_report = plane.restore_checkpoint(path, specs)
                mttr = time.perf_counter() - t0
            for tid in ids:
                if tid not in plane.tenants:
                    continue
                drift = rng.uniform(-0.2, 0.2)
                theta = ocp.default_params(p=jnp.array([
                    float(ids.index(tid) - n_tenants // 2) + drift]))
                expected += 1
                decision = plane.submit(tid, theta=theta)
                if decision is not None:
                    shed += 1
            res = plane.serve_round()
            actuated += sum(1 for v in res.values()
                            if v.action == "actuate")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    # closing assertions (ISSUE 15): the injected schedule must be
    # reconstructible from the journal alone, the incident builder must
    # join injection → symptom → recovery, and the SLO plane's
    # availability must agree with the bench's own count — live AND
    # recomputed offline from the journal — to within one round
    journal_stats, incident, events = _bench_journal_close(
        journal_path, journal_tmp, chaos, journal_base)
    from agentlib_mpc_tpu.telemetry.slo import slo_from_events

    availability = 100.0 * actuated / max(expected, 1)
    slo_live = plane.slo_report()
    slo_offline = slo_from_events(events)
    live_avail = slo_live["fleet"]["availability_pct"]
    off_avail = slo_offline["fleet"]["availability_pct"]
    quantum = 100.0 * n_tenants / max(expected, 1)
    assert live_avail is not None and \
        abs(live_avail - availability) <= quantum + 1e-6, (
        f"slo_report availability {live_avail}% disagrees with the "
        f"bench's {availability:.3f}% beyond one round's quantization "
        f"({quantum:.3f}%)")
    assert off_avail is not None and \
        abs(off_avail - live_avail) <= quantum + 1e-6, (
        f"journal-recomputed availability {off_avail}% disagrees with "
        f"the live report {live_avail}%")

    stats = plane.stats()
    platform = jax.devices()[0].platform
    metric = "serve_availability_pct" if platform == "tpu" \
        else f"serve_availability_pct_{platform}"
    out = {
        "metric": metric,
        "value": round(100.0 * actuated / max(expected, 1), 2),
        "unit": "%",
        "seed": seed,
        "n_tenants": n_tenants,
        "rounds": rounds,
        "victim": victim,
        "storm_rounds": [storm_start, storm_start + storm_len],
        "stall_call": stall_call,
        "crash_round": crash_round,
        "shed_rate_pct": round(100.0 * shed / max(expected, 1), 2),
        "evictions": int(telemetry.metrics().counter(
            "serving_evictions_total").total()),
        "readmissions": int(telemetry.metrics().counter(
            "serving_readmissions_total").total()),
        "still_evicted": int(stats["evicted"]),
        # process-global counter, NOT plane.stats(): the pre-crash
        # plane's dispatcher (and its stall) died with the "crash"
        "watchdog_stalls": int(telemetry.metrics().counter(
            "serving_watchdog_stalls_total").total()),
        "sync_fallback": stats["watchdog"]["sync_fallback"],
        "mttr_ms": None if mttr is None else round(1e3 * mttr, 2),
        "restore_cold_builds": (None if restore_report is None
                                else restore_report.cold_builds),
        "restore_cache_hits": (None if restore_report is None
                               else restore_report.cache_hits),
        "restore_per_tenant_ms": (
            None if restore_report is None else
            {t: round(1e3 * s, 3)
             for t, s in sorted(restore_report.per_tenant_s.items())}),
        "join_cold_ms": (round(1e3 * float(np.mean(join_cold)), 2)
                         if join_cold else None),
        "cache": stats["cache"],
        "chaos_events": {k: chaos.count(k)
                         for k in ("serve_nan_theta", "serve_stall")},
        "slo": {
            "availability_pct": live_avail,
            "offline_availability_pct": off_avail,
            "tenants_in_violation":
                slo_live["fleet"]["tenants_in_violation"],
            "victim_budget_remaining": (
                slo_live["tenants"].get(victim) or
                {}).get("error_budget_remaining"),
        },
        "journal": journal_stats,
        "incident": incident,
        "platform": platform,
    }
    print(json.dumps(out))
    return out


def run_chaos_autopilot(seed: int = 0, n_tenants: int = 8,
                        rounds: int = 32) -> dict:
    """``--chaos-autopilot SEED [n]``: the SLO autopilot's acceptance
    bench (ISSUE 17) — a controlled-vs-uncontrolled A/B under ONE
    seeded overload storm schedule.

    Two sequential phases serve the same ``n_tenants`` tracker
    population for ``rounds`` rounds against the same storm schedule
    (two deadline-squeeze windows: a moderate SLA squeeze a full-
    quality round cannot meet, then a brutal one even a cheap round
    cannot meet without relaxed admission), on a shared compile cache:

    * **uncontrolled** — no autopilot: every storm round expires at the
      drain, tenants walk replay → hold → fallback, availability burns
      far through the SLO target;
    * **controlled** — ``ServingPlane(autopilot=AutopilotPolicy())``:
      the controller reads the fast-window burn, caps warm iteration
      budgets (L1, a re-bucket through the warm cache), relaxes
      admission deadlines (L2, host-side), and spends the budget back
      up the ladder when the burn recedes.

    Time is a virtual clock: each round costs its MODELED device time
    (base + per-tenant warm-iterations x scenario-branches), so the L1
    lever genuinely cuts the round cost under the storm deadline and
    the A/B is deterministic on any host.

    Closing assertions: the controlled phase holds availability at or
    above the SLO target while the uncontrolled phase breaches it
    (delta > 0); every ladder move the controller reports is on the
    journal as a typed ``autopilot.move``; the incident builder joins
    at least one complete storm -> down-move -> up-move chain FROM THE
    JOURNAL ALONE; and the controlled availability publishes under the
    ``_q<level>`` qualified key, never the full-quality headline.
    """
    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
    from agentlib_mpc_tpu.resilience.chaos import (
        ServeChaosConfig,
        ServeOverloadRule,
        install_serving_chaos,
    )
    from agentlib_mpc_tpu.serving import (
        AutopilotPolicy,
        CompileCache,
        ServingPlane,
        TenantSpec,
    )
    from agentlib_mpc_tpu.telemetry.slo import SLOPolicy
    from agentlib_mpc_tpu.utils.jax_setup import (
        enable_compile_profiling,
        enable_persistent_cache,
    )

    enable_persistent_cache()
    telemetry.configure(enabled=True)
    telemetry.reset()
    enable_compile_profiling()

    import random as _random

    rng = _random.Random(f"bench-chaos-autopilot:{seed}")
    ocp = tracker_ocp()
    # ONE storm schedule for both phases: a moderate SLA squeeze a
    # full-quality round (modeled 0.08 s) cannot meet but an L1-capped
    # one (0.04 s) can, then — after a recovery gap long enough for the
    # up-moves — a brutal squeeze only the L2 deadline relaxation
    # (x4 -> 0.12 s admission window) survives
    a_start = rng.randrange(3, 7)
    a_len = 8
    b_start = a_start + a_len + 6
    b_len = 8
    storm_a = ServeOverloadRule(start_round=a_start, n_rounds=a_len,
                                deadline_s=0.06)
    storm_b = ServeOverloadRule(start_round=b_start, n_rounds=b_len,
                                deadline_s=0.03)
    slo_policy = SLOPolicy(availability_target=0.8, windows=(4, 16))
    cache = CompileCache()

    def modeled_round_cost(plane, ids) -> float:
        """The virtual clock's round cost: base + k per warm interior-
        point iteration per scenario branch, from the LIVE effective
        specs — an L1/L3 move changes next round's cost, which is the
        entire point of the lever."""
        total = 0
        for tid in ids:
            spec = plane._specs[tid]
            warm = spec.warm_solver_options
            iters = warm.max_iter if warm is not None \
                else min(spec.solver_options.max_iter, 6)
            tree = spec.scenario_tree
            total += int(iters) * (tree.n_scenarios
                                   if tree is not None else 1)
        return 0.02 + 0.00125 * total

    def run_phase(tag: str, prefix: str, controlled: bool) -> dict:
        phase_rng = _random.Random(
            f"bench-chaos-autopilot:{seed}:{tag}")
        journal_path, journal_tmp, journal_base = _bench_journal(
            "chaos-autopilot")
        plane = ServingPlane(
            FusedADMMOptions(max_iterations=5, rho=2.0),
            slot_multiple=1, initial_capacity=n_tenants,
            pipelined=False, donate=False, queue_limit=4 * n_tenants,
            slo_policy=slo_policy, cache=cache,
            autopilot=AutopilotPolicy() if controlled else None)
        ids = [f"{prefix}{i:03d}" for i in range(n_tenants)]
        join_cold = 0
        for i, tid in enumerate(ids):
            rec = plane.join(TenantSpec(
                tenant_id=tid, ocp=ocp,
                theta=ocp.default_params(
                    p=jnp.array([float(i - n_tenants // 2)])),
                couplings={},
                solver_options=SolverOptions(max_iter=30)))
            if not rec.engine_cached:
                join_cold += 1
        chaos = install_serving_chaos(plane, ServeChaosConfig(
            overload=(storm_a, storm_b)), seed=seed)
        expected = actuated = 0
        vclock = 0.0
        for _ in range(rounds):
            for i, tid in enumerate(ids):
                drift = phase_rng.uniform(-0.2, 0.2)
                expected += 1
                plane.submit(tid, theta=ocp.default_params(
                    p=jnp.array([float(i - n_tenants // 2) + drift])),
                    now=vclock)
            vclock += modeled_round_cost(plane, ids)
            res = plane.serve_round(now=vclock)
            actuated += sum(1 for v in res.values()
                            if v.action == "actuate")
        chaos.uninstall()
        journal_stats, incident, events = _bench_journal_close(
            journal_path, journal_tmp, chaos, journal_base,
            min_complete_chains=1 if controlled else 0)
        moves = [e for e in events
                 if e.get("etype") == "autopilot.move"]
        out = {
            "availability_pct": round(
                100.0 * actuated / max(expected, 1), 2),
            "expected": expected,
            "join_cold_builds": join_cold,
            "moves": len(moves),
            "moves_down": sum(1 for e in moves
                              if e.get("direction") == "down"),
            "moves_up": sum(1 for e in moves
                            if e.get("direction") == "up"),
            "max_level": max((int(e.get("level_to", 0))
                              for e in moves), default=0),
            "incident": incident,
            "journal": journal_stats,
        }
        if controlled:
            ledger = plane.autopilot.report()
            out["ladder"] = ledger
            # EVERY move the controller counted is on the tape — the
            # "every move journaled" acceptance criterion, asserted
            # from the journal alone
            counted = sum(int(r["moves"]) for r in ledger.values())
            assert len(moves) == counted, (
                f"controller counted {counted} ladder moves but the "
                f"journal carries {len(moves)} autopilot.move events")
            assert out["moves_down"] and out["moves_up"], (
                f"expected moves in BOTH directions (spend and "
                f"restore), got {out['moves_down']} down / "
                f"{out['moves_up']} up")
        else:
            assert not moves, (
                f"uncontrolled phase journaled {len(moves)} "
                f"autopilot.move events — chaos leaked a controller")
        return out

    uncontrolled = run_phase("uncontrolled", "u", controlled=False)
    controlled = run_phase("controlled", "c", controlled=True)

    target_pct = 100.0 * slo_policy.availability_target
    assert controlled["availability_pct"] >= target_pct, (
        f"controlled plane breached the availability SLO through the "
        f"storm: {controlled['availability_pct']}% < {target_pct}%")
    assert uncontrolled["availability_pct"] < target_pct, (
        f"uncontrolled plane survived the storm "
        f"({uncontrolled['availability_pct']}% >= {target_pct}%) — "
        f"the schedule no longer stresses the SLO, re-tune the storm")
    delta = round(controlled["availability_pct"]
                  - uncontrolled["availability_pct"], 2)
    assert delta > 0, (
        f"autopilot delta must be positive, got {delta}")
    # the controlled phase re-joined the SAME structures through the
    # shared cache: its joins must all be warm hits
    assert controlled["join_cold_builds"] == 0, (
        f"controlled phase paid {controlled['join_cold_builds']} cold "
        f"builds joining structures the uncontrolled phase already "
        f"compiled — the quality ladder broke the bucket key")

    platform = jax.devices()[0].platform
    out = {
        # the headline is the CONTROLLED availability and it publishes
        # under the _q<level> key: a quality-reduced number must never
        # read as the full-quality headline
        "metric": _qualified_metric(
            "serve_availability_pct", platform,
            quality_level=controlled["max_level"]),
        "value": controlled["availability_pct"],
        "unit": "%",
        "seed": seed,
        "n_tenants": n_tenants,
        "rounds": rounds,
        "storm_rounds": [[a_start, a_start + a_len],
                         [b_start, b_start + b_len]],
        "storm_deadlines_s": [storm_a.deadline_s, storm_b.deadline_s],
        "slo_target_pct": target_pct,
        "uncontrolled_availability_pct":
            uncontrolled["availability_pct"],
        "controlled_availability_pct": controlled["availability_pct"],
        "autopilot_delta_pct": delta,
        "moves": {"total": controlled["moves"],
                  "down": controlled["moves_down"],
                  "up": controlled["moves_up"],
                  "max_level": controlled["max_level"]},
        "ladder": controlled["ladder"],
        "budget_spent_by_policy": int(telemetry.metrics().counter(
            "error_budget_spent_by_policy").total()),
        "incident": controlled["incident"],
        "journal": controlled["journal"],
        "platform": platform,
    }
    print(json.dumps(out))
    return out


def _restore_bench_specs(n_tenants: int):
    """ONE deterministic TenantSpec construction shared by the
    --chaos-mesh parent (checkpoint save) and the --restore-mttr child
    (fresh-process restore): the two processes must fingerprint into
    identical buckets or the restore drift-check rightly refuses."""
    import jax.numpy as jnp

    from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.serving import TenantSpec

    ocp = tracker_ocp()
    return {
        f"m{i:02d}": TenantSpec(
            tenant_id=f"m{i:02d}", ocp=ocp,
            theta=ocp.default_params(p=jnp.array([float(i + 1)])),
            couplings={},
            solver_options=SolverOptions(max_iter=30))
        for i in range(n_tenants)
    }


def _restore_bench_plane(n_tenants: int, store_dir: str, cache=None):
    from agentlib_mpc_tpu.parallel.fused_admm import FusedADMMOptions
    from agentlib_mpc_tpu.serving import ServingPlane

    return ServingPlane(
        FusedADMMOptions(max_iterations=5, rho=2.0),
        slot_multiple=1, initial_capacity=n_tenants,
        pipelined=False, donate=False, cache=cache,
        engine_store=store_dir)


def run_restore_mttr(ckpt_dir: str, store_dir: str,
                     n_tenants: int = 2) -> dict:
    """``--restore-mttr`` (worker): restore a serving-plane checkpoint
    in THIS (fresh) process against the on-disk engine store + the
    persistent XLA cache — the real cross-process crash-restart MTTR,
    process death included. Run by ``--chaos-mesh`` as a child; the
    parent embeds the JSON line."""
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    specs = _restore_bench_specs(n_tenants)
    t0 = time.perf_counter()
    plane = _restore_bench_plane(n_tenants, store_dir)
    report = plane.restore_checkpoint(ckpt_dir, specs)
    mttr_s = time.perf_counter() - t0
    res = {tid: r.action for tid, r in _serve_once(plane, specs).items()}
    out = {
        "metric": "restore_mttr_ms",
        "value": round(1e3 * mttr_s, 2),
        "unit": "ms",
        "restore_total_ms": round(1e3 * report.total_s, 2),
        "cold_builds": report.cold_builds,
        "persistent_restores": report.persistent_restores,
        "cache_hits": report.cache_hits,
        "tenants": len(report.tenants),
        "post_restore_actions": res,
    }
    print(json.dumps(out))
    return out


def _serve_once(plane, specs) -> dict:
    for tid in specs:
        if tid in plane.tenants:
            plane.submit(tid)
    results = plane.serve_round()
    results.update(plane.flush())
    return results


def run_chaos_mesh(seed: int = 0, n_agents: int = 8,
                   rounds: int = 12) -> dict:
    """``--chaos-mesh SEED [n]``: survivability benchmark of the
    SHARDED fused fleet (ISSUE 10 — the PR 8 chaos discipline applied
    to the newest layer). An ``n_agents`` tracker consensus fleet runs
    under a :class:`FleetSupervisor` on the 8-virtual-device mesh while
    the seeded schedule injects, deterministically:

    1. a **shard-local NaN storm** (one shard's theta rows poisoned for
       a window — the fused quarantine must contain it: every other
       shard's controls stay finite);
    2. a **collective stall** (one round's dispatch hangs — the
       collective watchdog condemns it; with every shard answering the
       probe, the round retries on the SAME mesh);
    3. a **device loss with revival** (rounds hang while the dead
       device is meshed and it stops answering probes — the fleet
       degrades onto the survivors, serves degraded rounds, and the
       hysteretic re-admission reshards back after revival).

    Reported: availability % (finite actuations ÷ expected, masked
    dead-shard lanes counted unavailable), degraded-mode round count,
    shard-loss MTTR (condemnation → first completed degraded round),
    per-round step cost split into full-mesh and degraded keys (the
    honesty satellite: degraded rounds publish ``_d<k>_degraded``,
    NEVER the headline ``_d<n>`` key), and the cross-process restart
    MTTR measured in a CHILD process restoring a plane checkpoint
    against the engine store + persistent XLA cache (real process
    death). Platform- and device-qualified like every mesh metric.
    """
    import random as _random
    import shutil
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel import fleet_mesh
    from agentlib_mpc_tpu.parallel.fused_admm import (
        AgentGroup,
        FusedADMMOptions,
        stack_params,
    )
    from agentlib_mpc_tpu.parallel.survival import FleetSupervisor
    from agentlib_mpc_tpu.resilience.chaos import (
        MeshChaosConfig,
        MeshDeviceLossRule,
        MeshNaNStormRule,
        MeshStallRule,
        install_mesh_chaos,
    )
    from agentlib_mpc_tpu.utils.jax_setup import (
        cpu_subprocess_env,
        enable_persistent_cache,
    )

    enable_persistent_cache()
    telemetry.configure(enabled=True)
    telemetry.reset()
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    if n_dev < 2:
        # nothing to degrade on a 1-device backend (the virtual-device
        # request is a no-op once the backend is up, and real
        # single-chip boxes have no shards to lose) — say so loudly
        # instead of dying in the schedule randomization
        out = {
            "metric": f"chaos_mesh_availability_pct_{platform}_d1",
            "value": None, "unit": "%", "platform": platform,
            "error": (f"chaos-mesh needs >= 2 devices, got {n_dev}; "
                      f"run in a fresh process (the 8-virtual-device "
                      f"request must precede backend init) or on a "
                      f"multi-chip mesh"),
        }
        print(json.dumps(out))
        return out
    rng = _random.Random(f"bench-chaos-mesh:{seed}")
    journal_path, journal_tmp, journal_base = _bench_journal(
        "chaos-mesh")

    ocp = tracker_ocp()
    group = AgentGroup(name="chaos-mesh", ocp=ocp, n_agents=n_agents,
                       couplings={"shared_u": "u"},
                       solver_options=SolverOptions(max_iter=30))
    thetas = [stack_params([
        ocp.default_params(p=jnp.array([float(i + 1)]))
        for i in range(n_agents)])]
    sup = FleetSupervisor(
        [group], FusedADMMOptions(max_iterations=8, rho=2.0),
        mesh=fleet_mesh(), watchdog_timeout_s=10.0,
        readmit_after=1, probation_rounds=1)

    storm_round = rng.randrange(1, 3)
    stall_round = storm_round + 1
    die_round = stall_round + rng.randrange(1, 3)
    revive_round = die_round + rng.randrange(2, 4)
    victim_dev = rng.randrange(1, n_dev)
    chaos = install_mesh_chaos(sup, MeshChaosConfig(
        nan_storm=(MeshNaNStormRule(device_index=victim_dev,
                                    start_round=storm_round,
                                    n_rounds=1),),
        stall=(MeshStallRule(round=stall_round, duration_s=30.0),),
        device_loss=(MeshDeviceLossRule(device_index=victim_dev,
                                        die_at_round=die_round,
                                        revive_at_round=revive_round),),
    ), seed=seed)

    expected = available = 0
    full_times, degraded_times = [], []
    shard_loss_mttr = None
    was_degraded = False
    state = sup.init_state(thetas)
    for r in range(rounds):
        t0 = time.perf_counter()
        state, trajs, _stats = sup.step(state, thetas)
        dt = time.perf_counter() - t0
        just_degraded = sup.degraded and not was_degraded
        if just_degraded and shard_loss_mttr is None:
            # condemnation -> first completed DEGRADED round (probe +
            # rebuild + compile + round); a transient-stall retry's
            # recovery is not a shard loss and must not claim this key
            shard_loss_mttr = sup.last_mttr_s
        was_degraded = sup.degraded
        u = np.asarray(trajs[0]["u"])
        alive = ~np.asarray(sup.dead_lanes[0])
        expected += n_agents
        available += int((np.isfinite(u).all(axis=tuple(
            range(1, u.ndim))) & alive).sum())
        # honesty satellite: a degraded-mode round must NEVER land in
        # the full-mesh key — the two are different experiments. The
        # round that absorbed the rebuild is the MTTR row, not a step
        # sample.
        if just_degraded:
            continue
        (degraded_times if sup.degraded else full_times).append(dt)
    chaos.uninstall()
    # closing assertion (ISSUE 15): schedule reconstructible from the
    # journal alone + at least one injection→symptom→recovery chain
    # (the device loss: hang → condemned/degrade → readmit)
    journal_stats, incident, _events = _bench_journal_close(
        journal_path, journal_tmp, chaos, journal_base)

    # cross-process restart MTTR: checkpoint a store-backed serving
    # plane here, restore it in a CHILD process (real process death —
    # only the on-disk engine store + persistent XLA cache survive)
    n_tenants = 2
    tmp = tempfile.mkdtemp(prefix="chaos-mesh-")
    restore = None
    try:
        store_dir = os.path.join(tmp, "engine_store")
        ckpt_dir = os.path.join(tmp, "plane")
        plane = _restore_bench_plane(n_tenants, store_dir)
        specs = _restore_bench_specs(n_tenants)
        for tid in specs:
            plane.join(specs[tid])
        _serve_once(plane, specs)
        plane.save_checkpoint(ckpt_dir)
        try:
            lines = _spawn(
                ["--worker", "--restore-mttr", ckpt_dir, store_dir,
                 str(n_tenants)],
                cpu_subprocess_env() if platform == "cpu"
                else dict(os.environ), WORKER_TIMEOUT_S)
            restore = lines[-1]
        except Exception as exc:  # noqa: BLE001 - report, don't die
            print(f"[bench] chaos-mesh: child restore failed: {exc}",
                  file=sys.stderr)
            restore = {"error": str(exc)[:300]}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    def q(base: str, devices: int, degraded: bool = False) -> str:
        # the ONE qualifier rule (platform / _d<n> / _degraded), shared
        # with the headline metric so the conventions cannot drift
        return _qualified_metric(base, platform, devices, degraded)

    stats = sup.stats()
    out = {
        "metric": q("chaos_mesh_availability_pct", n_dev),
        "value": round(100.0 * available / max(expected, 1), 2),
        "unit": "%",
        "seed": seed,
        "n_agents": n_agents,
        "rounds": rounds,
        "devices": n_dev,
        "schedule": {"storm_round": storm_round,
                     "stall_round": stall_round,
                     "die_round": die_round,
                     "revive_round": revive_round,
                     "victim_device": victim_dev},
        "degraded_rounds": stats["degraded_rounds"],
        "layouts_built": stats["layouts_built"],
        "shard_loss_mttr_ms": (None if shard_loss_mttr is None
                               else round(1e3 * shard_loss_mttr, 2)),
        q("chaos_mesh_step_ms", n_dev): (
            round(1e3 * float(np.median(full_times)), 2)
            if full_times else None),
        q("chaos_mesh_step_ms", n_dev - 1, degraded=True): (
            round(1e3 * float(np.median(degraded_times)), 2)
            if degraded_times else None),
        "restart": restore,
        "chaos_events": {k: chaos.count(k) for k in (
            "mesh_nan_theta", "mesh_stall", "mesh_device_hang",
            "mesh_probe_dead")},
        "journal": journal_stats,
        "incident": incident,
        "platform": platform,
    }
    print(json.dumps(out))
    return out


def run_chaos_scenario(seed: int = 0, n_scenarios: int = 4,
                       n_agents: int = 4,
                       rounds: "int | None" = None) -> dict:
    """``--chaos-scenario SEED [S] [n]``: survivability benchmark of
    the 2-D (agents × scenarios) robust fleet (ISSUE 14 — the
    ``--chaos-mesh`` discipline on both axes). An ``n``-agent tracker
    consensus fleet solving ``S`` disturbance branches per agent runs
    under a :class:`ScenarioFleetSupervisor` on the 4×2
    8-virtual-device grid while the seeded schedule injects,
    deterministically:

    1. a **scenario-shard NaN storm** (one column's branch data
       poisoned for a window — the branch quarantine/solver guards
       must contain it);
    2. a **collective stall** (transient: every shard answers the
       probe, the round retries on the same grid);
    3. a **scenarios-axis device loss with revival** — the fleet drops
       the dead column's branches, RE-NORMALIZES the surviving node-
       group probabilities, and serves every agent at reduced
       robustness breadth until re-admission;
    4. an **agents-axis device loss with revival** — the dead row's
       lanes mask out and the survivors re-pad (the supervisor's
       classification policy is scripted to the agents axis for this
       phase, so both axes' ladders land in one run).

    Reported: agent-actuation availability % (finite actuated u0 ÷
    expected, dead lanes unavailable — scenario-degraded rounds keep
    EVERY agent available, which is the point of preferring that
    axis), branch availability %, per-AXIS shard-loss MTTR, degraded-
    round counts, and per-round step cost under the ``_d<A>x<S>``
    qualifier rule: a degraded round publishes its reduced shape with
    ``_degraded`` (e.g. ``_d4x1_degraded``), NEVER the full-mesh key,
    and the rebuild-bearing round is the MTTR row, never a step
    sample."""
    import random as _random

    import numpy as np

    import jax
    import jax.numpy as jnp

    from agentlib_mpc_tpu import telemetry
    from agentlib_mpc_tpu.lint.retrace_budget import tracker_ocp
    from agentlib_mpc_tpu.ops.solver import SolverOptions
    from agentlib_mpc_tpu.parallel.fused_admm import AgentGroup
    from agentlib_mpc_tpu.parallel.multihost import scenario_mesh
    from agentlib_mpc_tpu.parallel.survival import (
        ScenarioFleetSupervisor,
    )
    from agentlib_mpc_tpu.resilience.chaos import (
        MeshChaosConfig,
        MeshDeviceLossRule,
        MeshNaNStormRule,
        MeshStallRule,
        install_mesh_chaos,
    )
    from agentlib_mpc_tpu.scenario import ScenarioFleetOptions, fan_tree
    from agentlib_mpc_tpu.utils.jax_setup import enable_persistent_cache

    enable_persistent_cache()
    telemetry.configure(enabled=True)
    telemetry.reset()
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    if n_dev < 4 or n_dev % 2:
        out = {
            "metric": f"chaos_scenario_availability_pct_{platform}_d1",
            "value": None, "unit": "%", "platform": platform,
            "error": (f"chaos-scenario needs an even device count "
                      f">= 4 for the 2-column scenario grid, got "
                      f"{n_dev}; run in a fresh process (the "
                      f"8-virtual-device request must precede backend "
                      f"init) or on a multi-chip mesh"),
        }
        print(json.dumps(out))
        return out
    rng = _random.Random(f"bench-chaos-scenario:{seed}")
    journal_path, journal_tmp, journal_base = _bench_journal(
        "chaos-scenario")

    S = max(2, n_scenarios + (n_scenarios % 2))   # 2 columns divide S
    mesh = scenario_mesh(2)
    a_sh, s_sh = (int(v) for v in mesh.devices.shape)
    ocp = tracker_ocp()
    group = AgentGroup(name="chaos-scenario", ocp=ocp,
                       n_agents=n_agents,
                       couplings={"shared_u": "u"},
                       solver_options=SolverOptions(max_iter=30))
    tree = fan_tree(S, robust_horizon=1)
    thetas = jax.tree.map(lambda *xs: jnp.stack(xs), *[
        jax.tree.map(lambda *ys: jnp.stack(ys), *[
            ocp.default_params(p=jnp.array([float(i + 1) + 0.3 * s]))
            for s in range(S)])
        for i in range(n_agents)])
    sup = ScenarioFleetSupervisor(
        group, tree, ScenarioFleetOptions(max_iterations=8, rho=2.0,
                                          rho_na=2.0),
        mesh=mesh, watchdog_timeout_s=10.0,
        readmit_after=1, probation_rounds=1)

    storm_round = rng.randrange(1, 3)
    stall_round = storm_round + 1
    die_scen = stall_round + rng.randrange(1, 3)
    revive_scen = die_scen + rng.randrange(2, 4)
    # the agents-axis phase starts after the scenario phase has fully
    # re-admitted (readmit_after=1 + probation 1)
    die_agents = revive_scen + 3
    revive_agents = die_agents + rng.randrange(2, 4)
    if rounds is None:
        rounds = revive_agents + 3
    scen_col = rng.randrange(0, s_sh)
    agents_row = rng.randrange(1, a_sh)
    # a scenarios-axis kill degrades scenarios only while MORE than
    # one branch would survive — spd 1 grids (the S=2 smoke) fall back
    # to the agents axis, honestly reported in the schedule below
    chaos = install_mesh_chaos(sup, MeshChaosConfig(
        nan_storm=(MeshNaNStormRule(device_index=scen_col,
                                    axis="scenarios",
                                    start_round=storm_round,
                                    n_rounds=1),),
        stall=(MeshStallRule(round=stall_round, duration_s=30.0,
                             axis="scenarios"),),
        device_loss=(
            MeshDeviceLossRule(device_index=scen_col,
                               axis="scenarios", cross_index=0,
                               die_at_round=die_scen,
                               revive_at_round=revive_scen),
            MeshDeviceLossRule(device_index=agents_row,
                               axis="agents", cross_index=0,
                               die_at_round=die_agents,
                               revive_at_round=revive_agents),
        ),
    ), seed=seed)

    expected = available = 0
    branch_expected = branch_available = 0
    full_times: list = []
    degraded_times: dict = {}          # mesh shape -> [dt]
    was_degraded = False
    state = sup.init_state(thetas)
    for r in range(rounds):
        if r == revive_scen + 1:
            # phase 2 is the AGENTS-axis drill: script the
            # classification so the second kill exercises the row
            # ladder (the auto policy would keep trading robustness
            # breadth instead — a deliberate choice, overridden here
            # to land both axes' evidence in one run)
            sup.degrade_axis = "agents"
        t0 = time.perf_counter()
        state, trajs, _stats = sup.step(state, thetas)
        dt = time.perf_counter() - t0
        just_degraded = sup.degraded and not was_degraded
        was_degraded = sup.degraded
        u0 = np.asarray(sup.actuated_u0(state))   # (n, S, n_u)
        alive_lane = ~np.asarray(sup.dead_lanes)
        expected += n_agents
        available += int((np.isfinite(u0).all(axis=(1, 2))
                          & alive_lane).sum())
        branch_expected += S
        branch_available += S - len(sup.dead_branches)
        # honesty: the rebuild-bearing round is the MTTR row, never a
        # step sample; degraded rounds land under their REDUCED shape
        if just_degraded:
            continue
        if sup.degraded:
            degraded_times.setdefault(sup.mesh_shape, []).append(dt)
        else:
            full_times.append(dt)
    chaos.uninstall()
    # closing assertion (ISSUE 15): schedule reconstructible from the
    # journal alone + the axis-classified loss chains joined
    journal_stats, incident, _events = _bench_journal_close(
        journal_path, journal_tmp, chaos, journal_base)

    def q(base: str, shape: tuple, degraded: bool = False) -> str:
        return _qualified_metric(base, platform, degraded=degraded,
                                 mesh_shape=shape)

    stats = sup.stats()
    out = {
        "metric": q("chaos_scenario_availability_pct", (a_sh, s_sh)),
        "value": round(100.0 * available / max(expected, 1), 2),
        "unit": "%",
        "branch_availability_pct": round(
            100.0 * branch_available / max(branch_expected, 1), 2),
        "seed": seed,
        "n_agents": n_agents,
        "n_scenarios": S,
        "rounds": rounds,
        "mesh_shape": [a_sh, s_sh],
        "schedule": {"storm_round": storm_round,
                     "stall_round": stall_round,
                     "die_scenarios": die_scen,
                     "revive_scenarios": revive_scen,
                     "die_agents": die_agents,
                     "revive_agents": revive_agents,
                     "victim_scenario_col": scen_col,
                     "victim_agents_row": agents_row},
        "degraded_rounds": stats["degraded_rounds"],
        "layouts_built": stats["layouts_built"],
        "shard_loss_mttr_ms_by_axis": {
            axis: (None if v is None else round(1e3 * v, 2))
            for axis, v in stats["mttr_by_axis"].items()},
        q("chaos_scenario_step_ms", (a_sh, s_sh)): (
            round(1e3 * float(np.median(full_times)), 2)
            if full_times else None),
        "chaos_events": {k: chaos.count(k) for k in (
            "mesh_nan_theta", "mesh_stall", "mesh_device_hang",
            "mesh_probe_dead")},
        "journal": journal_stats,
        "incident": incident,
        "platform": platform,
    }
    for shape, times in sorted(degraded_times.items()):
        out[q("chaos_scenario_step_ms", shape, degraded=True)] = \
            round(1e3 * float(np.median(times)), 2)
    print(json.dumps(out))
    return out


def run_profile(trace_dir: str = "bench_trace",
                n_agents: int = N_AGENTS) -> None:
    """Capture an XLA profiler trace of the warm ``n_agents``-zone step
    (for TensorBoard / xprof kernel-level analysis on TPU — the tool the
    PERF.md latency budget comes from; ``--profile DIR 1024`` is the
    VERDICT r5 #7 sub-linearity attribution run)."""
    import jax

    step, args = build_step(n_agents)
    out = step(*args)
    jax.block_until_ready(out)
    with jax.profiler.trace(trace_dir):
        out = warm_step(step, args, out)
        jax.block_until_ready(out)
    print(json.dumps({"metric": "profile_trace", "dir": trace_dir,
                      "n_agents": n_agents,
                      "platform": jax.devices()[0].platform}))


def run_ab() -> list[dict]:
    """A/B the per-iteration latency knobs on the current backend
    (used to validate SolverOptions defaults on real TPU hardware)."""
    rows = []
    for label, ov, wb in (
            ("fused_ls=off", {"fused_ls_jacobian": "off"}, 1),
            ("fused_ls=on", {"fused_ls_jacobian": "on"}, 1),
            ("corrector=off,warm=2", {"corrector": False}, 2),
            ("corrector=on,warm=1", {}, 1)):
        res = measure(N_AGENTS, ov, warm_budget=wb)
        rows.append({
            "metric": f"admm256_step_ms[{label}]",
            "value": round(res["step_ms"], 2), "unit": "ms",
            "compile_ms": round(res["compile_ms"]),
            "platform": res["platform"]})
        print(json.dumps(rows[-1]))
    return rows


def run_qp_ab(n_agents: int = N_AGENTS) -> list[dict]:
    """QP-fast-path A/B inside the fused ADMM inner loop (VERDICT r4 #3):
    the SAME linear 256-zone fleet once through the general interior-point
    solver and once through the Mehrotra QP path — the reference's
    qpoases/osqp role (``casadi_utils.py:52-61``) measured in situ."""
    rows = []
    for label, inner in (("qp=off", "nlp"), ("qp=on", "qp")):
        res = measure(n_agents, model="linear", inner=inner)
        rows.append({
            "metric": f"linear{n_agents}_step_ms[{label}]",
            "value": round(res["step_ms"], 2), "unit": "ms",
            "compile_ms": round(res["compile_ms"]),
            "platform": res["platform"]})
        print(json.dumps(rows[-1]))
    return rows


def timed_best_ms(fn, *args, reps: int = 3):
    """Warm-up call, then best-of-``reps`` wall time: ``(ms, last_out)``.

    The shared timing harness for every micro/A-B section — one place to
    change methodology so the columns stay comparable across modes.
    """
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return 1e3 * min(ts), out


def run_ldl_micro() -> dict:
    """LDLᵀ-vs-LU at the bench solver's exact reduced-KKT tile,
    lanes-batched over the 256-zone fleet — on real hardware when run
    under the driver (VERDICT r4 #1/weak #2: the kernel behind the
    <300 ms projection had only ever run in interpret mode)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentlib_mpc_tpu.ops import kkt as kkt_ops
    from agentlib_mpc_tpu.ops.solver import _factor_kkt_lu, _resolve_kkt_lu

    ocp = zone_ocp()
    n, m_e = ocp.n_w, ocp.n_g
    size = n + m_e                    # the production reduced-KKT dim
    rng = np.random.default_rng(0)
    M = rng.normal(size=(N_AGENTS, n, n)).astype(np.float32)
    W = M @ M.transpose(0, 2, 1) / n + 2.0 * np.eye(n, dtype=np.float32)
    A = rng.normal(size=(N_AGENTS, m_e, n)).astype(np.float32)
    K = np.zeros((N_AGENTS, size, size), np.float32)
    K[:, :n, :n] = W
    K[:, :n, n:] = A.transpose(0, 2, 1)
    K[:, n:, :n] = A
    K[:, n:, n:] = -1e-8 * np.eye(m_e, dtype=np.float32)
    rhs = rng.normal(size=(N_AGENTS, size)).astype(np.float32)
    Kj, rj = jnp.asarray(K), jnp.asarray(rhs)

    out = {"size": size, "batch": N_AGENTS,
           "platform": jax.devices()[0].platform,
           "ldl_available": bool(kkt_ops.kkt_method_available(size))}
    lu = jax.jit(jax.vmap(
        lambda Ki, ri: _resolve_kkt_lu(_factor_kkt_lu(Ki), ri)))
    out["lu_ms"], sol_lu = timed_best_ms(lu, Kj, rj, reps=5)
    if out["ldl_available"]:
        ldl = jax.jit(jax.vmap(
            lambda Ki, ri: kkt_ops.resolve_kkt_ldl(
                kkt_ops.factor_kkt_ldl(Ki), ri)))
        out["ldl_ms"], sol_ldl = timed_best_ms(ldl, Kj, rj, reps=5)
        out["speedup_vs_lu"] = round(out["lu_ms"] / out["ldl_ms"], 2)
        out["max_sol_diff"] = float(jnp.max(jnp.abs(sol_ldl - sol_lu)))
    print(json.dumps({"metric": "kkt_factor_solve_ms", **{
        k: v for k, v in out.items()}}), file=sys.stderr)
    return out


def run_horizon_shard() -> list[dict]:
    """SURVEY §5 experiment (VERDICT r4 #9): does sharding the HORIZON
    axis pay for a single agent whose problem outgrows one core?

    The per-iteration work of an interior-point solve splits into (a) the
    stage-parallel stacked value+Jacobian evaluation — shardable along
    the horizon/constraint-row axis — and (b) the KKT factorization,
    which couples every stage (dense LDLᵀ/LU here; a Riccati
    restructuring would still be an O(N)-depth sequential recursion).
    Amdahl therefore bounds any horizon-sharding win by the evaluation
    share, which this mode measures at growing horizons, alongside a
    compile+execute check of the row-sharded evaluation on the virtual
    device mesh. (On this VM the virtual CPU devices timeshare ONE core,
    so sharded wall times are validity checks, not speedups — the
    decision number is the work breakdown.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops.solver import (
        SolverOptions,
        _factor_kkt_lu,
        _resolve_kkt_lu,
        solve_nlp,
    )
    from agentlib_mpc_tpu.ops.transcription import transcribe

    rows = []
    for N in (32, 128, 256):
        ocp = transcribe(OneRoom(), ["mDot"], N=N, dt=60.0,
                         method="collocation", collocation_degree=2)
        theta = ocp.default_params()
        w0 = ocp.initial_guess(theta)
        lb, ub = ocp.bounds(theta)
        n, m_e, m_h = ocp.n_w, ocp.n_g, ocp.n_h

        # (a) the stage-parallel stacked value+Jacobian pass (what the
        # solver evaluates once per accepted point)
        def fgh(w):
            return jnp.concatenate([ocp.nlp.f(w, theta)[None],
                                    ocp.nlp.g(w, theta),
                                    ocp.nlp.h(w, theta)])

        eye = jnp.eye(1 + m_e + m_h)

        @jax.jit
        def eval_and_jac(w):
            vals, pullback = jax.vjp(fgh, w)
            return vals, jax.vmap(lambda ct: pullback(ct)[0])(eye)

        eval_ms = timed_best_ms(eval_and_jac, w0)[0]

        # (b) the horizon-coupled KKT factor+solve at this problem's
        # reduced dimension
        size = n + m_e
        rng = np.random.default_rng(0)
        M = rng.normal(size=(size, size))
        K = jnp.asarray(M @ M.T + size * np.eye(size))
        rhs = jnp.asarray(rng.normal(size=size))
        kkt_ms = timed_best_ms(jax.jit(
            lambda K, r: _resolve_kkt_lu(_factor_kkt_lu(K), r)), K, rhs)[0]

        # (c) whole warm solve for scale
        opts = SolverOptions(tol=1e-4, max_iter=15)
        solve_ms = timed_best_ms(
            lambda w: solve_nlp(ocp.nlp, w, theta, lb, ub, opts), w0)[0]

        # (d) row-sharded evaluation across the virtual mesh: must
        # compile + run + agree; its wall time is reported but on shared
        # physical hardware it measures partition overhead, not speedup
        shard_ok, shard_ms = False, None
        devices = jax.devices()
        if len(devices) >= 2:
            try:
                from jax.sharding import (
                    Mesh,
                    NamedSharding,
                    PartitionSpec,
                )

                n_dev = max(d for d in range(1, len(devices) + 1)
                            if (1 + m_e + m_h) % d == 0)
                if n_dev > 1:
                    mesh = Mesh(np.array(devices[:n_dev]), ("rows",))
                    sharding = NamedSharding(mesh, PartitionSpec("rows"))

                    @jax.jit
                    def eval_sharded(w):
                        vals, pullback = jax.vjp(fgh, w)
                        rows_sh = jax.lax.with_sharding_constraint(
                            eye, sharding)
                        return vals, jax.vmap(
                            lambda ct: pullback(ct)[0])(rows_sh)

                    v1, j1 = eval_and_jac(w0)
                    v2, j2 = eval_sharded(w0)
                    shard_ok = bool(jnp.allclose(j1, j2, atol=1e-6))
                    shard_ms = timed_best_ms(eval_sharded, w0)[0]
            except Exception as exc:  # noqa: BLE001 - record, not die
                print(f"[bench] horizon-shard N={N}: sharded eval "
                      f"failed: {exc}", file=sys.stderr)
        row = {
            "metric": f"horizon_shard[N={N}]",
            "n_w": n, "kkt_size": size,
            "eval_jac_ms": round(eval_ms, 3),
            "kkt_factor_solve_ms": round(kkt_ms, 3),
            "warm_solve_ms": round(solve_ms, 2),
            #: Amdahl ceiling: fraction of (eval + factor) that sharding
            #: the stage-parallel part could ever remove
            "shardable_share": round(eval_ms / (eval_ms + kkt_ms), 3),
            "sharded_eval_ok": shard_ok,
            "sharded_eval_ms": (round(shard_ms, 3)
                                if shard_ms is not None else None),
            "platform": jax.devices()[0].platform,
        }
        rows.append(row)
        print(json.dumps(row))
    return rows


def run_ocp_ab(sizes=(32, 128, 256)) -> list[dict]:
    """Dense-vs-structured KKT factorization A/B over growing horizons
    (the fatrop role, VERDICT r5 task #2): the stage-structured
    block-tridiagonal sweep (``ops/stagewise.py``) against the dense
    pivoted-LU path, on (a) a synthetic quasi-definite system carrying
    the transcribed OCP's EXACT stage partition and sparsity — isolating
    the factor+resolve cost the round-5 components table showed
    exploding 2.0 → 33.4 → 236 ms — and (b) a warm whole-solve through
    ``solve_nlp`` with each ``kkt_method``. The two solutions must
    agree; ``speedup`` is dense/stage on (a)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops import stagewise
    from agentlib_mpc_tpu.ops.solver import (
        SolverOptions,
        _factor_kkt,
        _resolve_kkt,
        solve_nlp,
    )
    from agentlib_mpc_tpu.ops.transcription import transcribe

    rows = []
    for N in sizes:
        ocp = transcribe(OneRoom(), ["mDot"], N=N, dt=60.0,
                         method="collocation", collocation_degree=2)
        part = ocp.stage_partition
        K, rhs = stagewise.synthetic_stage_kkt(part, seed=0,
                                               dtype=np.float32)
        Kj, rj = jnp.asarray(K), jnp.asarray(rhs)
        dense = jax.jit(lambda K, r: _resolve_kkt(_factor_kkt(K, "lu"), r))
        stage = jax.jit(
            lambda K, r, p=part: _resolve_kkt(_factor_kkt(K, "stage", p), r))
        dense_ms, sol_dense = timed_best_ms(dense, Kj, rj)
        stage_ms, sol_stage = timed_best_ms(stage, Kj, rj)
        diff = float(jnp.max(jnp.abs(sol_dense - sol_stage)))

        theta = ocp.default_params()
        w0 = ocp.initial_guess(theta)
        lb, ub = ocp.bounds(theta)
        solve_rows = {}
        for label, method in (("dense", "lu"), ("stage", "stage")):
            opts = SolverOptions(tol=1e-4, max_iter=15, kkt_method=method,
                                 stage_partition=part)
            solve_rows[label] = timed_best_ms(
                lambda w, o=opts: solve_nlp(ocp.nlp, w, theta, lb, ub, o),
                w0)[0]
        row = {
            "metric": f"ocp_ab[N={N}]",
            "kkt_size": part.n_total,
            "n_stages": part.n_stages,
            "stage_block": part.block,
            "dense_factor_solve_ms": round(dense_ms, 3),
            "stage_factor_solve_ms": round(stage_ms, 3),
            "speedup": round(dense_ms / stage_ms, 2),
            "max_abs_diff": diff,
            "warm_solve_dense_ms": round(solve_rows["dense"], 2),
            "warm_solve_stage_ms": round(solve_rows["stage"], 2),
            "platform": jax.devices()[0].platform,
        }
        rows.append(row)
        print(json.dumps(row))
    return rows


def run_jac_ab(sizes=(32, 128, 256)) -> list[dict]:
    """Stage-sparse vs dense derivative pipeline A/B over growing
    horizons (``ops/stagejac.py``; PERF.md round 8): on the same OneRoom
    collocation OCPs as ``--ocp-ab``, measure

    (a) eval+jac — the stacked value+Jacobian pass the solver makes once
        per accepted point: dense ``1+m_e+m_h`` unit-cotangent pullbacks
        vs the plan's compressed ``1+3e_s+3h_s`` pullbacks, results
        asserted IDENTICAL (the compression is loss-free);
    (b) the Lagrangian-Hessian pass: ``n_w`` vs ``3·v_s`` forward seeds;
    (c) a warm whole-solve through ``solve_nlp`` with each
        ``jacobian`` setting (both on the stage KKT path, isolating the
        derivative side), solutions compared; and
    (d) the per-agent KKT working set: dense (n+m_e)² floats vs the
        banded S·n_s² blocks — the LLC-pressure lever of the round-6
        1024-zone attribution.

    The cost-model ratio (``lint.jaxpr.compare_eval_jac_cost``) rides
    along so the measured and modeled crossovers can be compared."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agentlib_mpc_tpu.lint.jaxpr.cost import compare_eval_jac_cost
    from agentlib_mpc_tpu.models.zoo import OneRoom
    from agentlib_mpc_tpu.ops import stagejac
    from agentlib_mpc_tpu.ops.solver import (
        SolverOptions,
        attach_jacobian_plan,
        attach_stage_partition,
        solve_nlp,
    )
    from agentlib_mpc_tpu.ops.transcription import transcribe

    rows = []
    for N in sizes:
        ocp = transcribe(OneRoom(), ["mDot"], N=N, dt=60.0,
                         method="collocation", collocation_degree=2)
        part = ocp.stage_partition
        theta = ocp.default_params()
        plan = stagejac.plan_from_certificate(ocp.nlp, theta, ocp.n_w,
                                              part, label=f"OneRoom N={N}")
        if plan is None:
            rows.append({"metric": f"jac_ab[N={N}]",
                         "error": "stage structure not proved"})
            print(json.dumps(rows[-1]))
            continue
        n, m_e, m_h = ocp.n_w, ocp.n_g, ocp.n_h
        w0 = ocp.initial_guess(theta)
        lb, ub = ocp.bounds(theta)
        fgh = stagejac.stacked_fgh(ocp.nlp, theta)
        eye = jnp.eye(1 + m_e + m_h)

        @jax.jit
        def eval_dense(w):
            vals, pullback = jax.vjp(fgh, w)
            return vals, jax.vmap(lambda ct: pullback(ct)[0])(eye)

        @jax.jit
        def eval_sparse(w):
            return stagejac.banded_fgh_jac(plan, fgh, w)

        dense_ms, (vals_d, J_d) = timed_best_ms(eval_dense, w0)
        sparse_ms, (vals_s, _gf, Jg_rows, Jh_rows) = \
            timed_best_ms(eval_sparse, w0)

        # loss-free compression check: expand the banded rows and compare
        def expand(rows_b, cols, m):
            out = jnp.zeros((m, n))
            if m == 0:
                return out
            r_idx = jnp.broadcast_to(jnp.arange(m)[:, None], cols.shape)
            return out.at[r_idx.reshape(-1),
                          jnp.asarray(np.maximum(cols, 0)).reshape(-1)
                          ].add(rows_b.reshape(-1))

        jac_diff = max(
            float(jnp.max(jnp.abs(expand(Jg_rows, plan.g_cols, m_e)
                                  - J_d[1:1 + m_e]))) if m_e else 0.0,
            float(jnp.max(jnp.abs(expand(Jh_rows, plan.h_cols, m_h)
                                  - J_d[1 + m_e:]))) if m_h else 0.0)

        def grad_f(w):
            return jax.grad(lambda ww: ocp.nlp.f(ww, theta))(w)

        @jax.jit
        def hess_dense(w):
            _, jvp_fn = jax.linearize(grad_f, w)
            return jax.vmap(jvp_fn)(jnp.eye(n))

        @jax.jit
        def hess_sparse(w):
            return stagejac.banded_lagrangian_hessian(plan, grad_f, w)

        hdense_ms, _ = timed_best_ms(hess_dense, w0)
        hsparse_ms, _ = timed_best_ms(hess_sparse, w0)

        # warm whole-solve: both on the stage factor path so the A/B
        # isolates the derivative pipeline
        solve_ms, sols = {}, {}
        for label, jac in (("dense", "dense"), ("sparse", "sparse")):
            opts = attach_jacobian_plan(attach_stage_partition(
                SolverOptions(tol=1e-4, max_iter=15, kkt_method="stage",
                              jacobian=jac), part), plan)
            solve_ms[label], res = timed_best_ms(
                lambda w, o=opts: solve_nlp(ocp.nlp, w, theta, lb, ub, o),
                w0)
            sols[label] = res.w
        sol_diff = float(jnp.max(jnp.abs(sols["dense"] - sols["sparse"])))

        cost = compare_eval_jac_cost(ocp.nlp, theta, n, plan)
        dense_kkt_bytes = 4 * part.n_total ** 2
        banded_kkt_bytes = 4 * plan.kkt_band_entries
        dense_jac_bytes = 4 * (m_e + m_h) * n
        banded_jac_bytes = 4 * (m_e * plan.W_g + m_h * plan.W_h)
        row = {
            "metric": f"jac_ab[N={N}]",
            "kkt_size": part.n_total,
            "rows_dense": 1 + m_e + m_h,
            "rows_compressed": plan.n_ct,
            "eval_jac_dense_ms": round(dense_ms, 3),
            "eval_jac_sparse_ms": round(sparse_ms, 3),
            "eval_jac_speedup": round(dense_ms / sparse_ms, 2),
            "max_jac_diff": jac_diff,
            "hessian_dense_ms": round(hdense_ms, 3),
            "hessian_sparse_ms": round(hsparse_ms, 3),
            "hessian_speedup": round(hdense_ms / hsparse_ms, 2),
            "warm_solve_dense_jac_ms": round(solve_ms["dense"], 2),
            "warm_solve_sparse_jac_ms": round(solve_ms["sparse"], 2),
            "warm_solve_speedup": round(
                solve_ms["dense"] / solve_ms["sparse"], 2),
            "max_sol_diff": sol_diff,
            "kkt_bytes_dense": dense_kkt_bytes,
            "kkt_bytes_banded": banded_kkt_bytes,
            "jac_carry_bytes_dense": dense_jac_bytes,
            "jac_carry_bytes_banded": banded_jac_bytes,
            "cost_model_flops_ratio": cost["flops_ratio"],
            "platform": jax.devices()[0].platform,
        }
        rows.append(row)
        print(json.dumps(row))
    return rows


def run_evidence() -> None:
    """The whole evidence matrix in ONE child process (VERDICT r4 #1):
    headline, LDL micro, knob A/Bs, QP A/B, scaling curve, the
    dense-vs-structured OCP factorization A/B — each section fail-soft,
    each row platform-tagged, one ``{"section": ...}`` JSON line per
    section (HEADLINE FIRST, so a short-lived tunnel window still
    captures the key row) so the parent can assemble the final artifact
    even if a late section dies."""
    def section(name, fn):
        try:
            payload = fn()
        except Exception as exc:  # noqa: BLE001 - record, keep going
            print(f"[bench] evidence section {name!r} failed: {exc}",
                  file=sys.stderr)
            payload = {"error": str(exc)[:300]}
        print(json.dumps({"section": name,
                          **(payload if isinstance(payload, dict)
                             else {"rows": payload})}))
        sys.stdout.flush()

    section("headline", measure)
    section("ldl_micro", run_ldl_micro)
    section("ab", run_ab)
    section("qp_ab", run_qp_ab)
    section("scaling", run_scaling)
    section("horizon_shard", run_horizon_shard)
    section("ocp_ab", run_ocp_ab)
    section("jac_ab", run_jac_ab)
    section("serve", run_serve)
    # one size keeps the matrix inside the worker watchdog; the full
    # 256-4096 table is the on-demand `--mesh-ab` run (PERF.md round 10)
    section("mesh_ab", lambda: run_mesh_ab(sizes=(256,)))
    # where the warm round's device time goes, by named phase (ISSUE 16)
    section("phase_profile", run_phase_profile)


# --- fail-soft orchestration (round-3 lesson: a wedged TPU tunnel hangs
# jax backend init *forever* inside the axon sitecustomize, before any of
# our code runs, and the round's BENCH came back `rc=1, parsed=null`).
# The parent process below never initializes JAX itself: every measurement
# runs in a watchdogged child, and a dead/wedged tunnel degrades to a CPU
# measurement with the platform recorded in the JSON — a JSON line is
# emitted on EVERY path.

_HERE = os.path.dirname(os.path.abspath(__file__))
PROBE_TIMEOUT_S = 240.0    # tunnel init is ~30 s when healthy
WORKER_TIMEOUT_S = 2400.0  # compile (~40 s/size on TPU) + measurement


def _child_main() -> None:
    """Measurement child. ``--probe`` pins to host CPU (the launcher also
    hands us a scrubbed env so the axon sitecustomize never dials the
    tunnel; the in-process override is belt-and-braces for direct
    invocations from an unscrubbed shell); ``--worker`` runs on whatever
    the default platform is (TPU under the driver)."""
    if "--horizon-shard" in sys.argv or "--evidence" in sys.argv \
            or "--mesh-ab" in sys.argv:
        # the sharded-eval validity check and the mesh A/B need a
        # multi-device mesh; on CPU that means virtual host devices,
        # which must be requested BEFORE backend init (no-op on real
        # multi-chip)
        from agentlib_mpc_tpu.utils.jax_setup import (
            request_virtual_devices,
        )

        request_virtual_devices(8)
    if "--probe" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if "--scaling" in sys.argv:
        run_scaling()
    elif "--ab" in sys.argv:
        run_ab()
    elif "--qp-ab" in sys.argv:
        run_qp_ab()
    elif "--ldl" in sys.argv:
        print(json.dumps(run_ldl_micro()))
    elif "--horizon-shard" in sys.argv:
        run_horizon_shard()
    elif "--ocp-ab" in sys.argv:
        idx = sys.argv.index("--ocp-ab")
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            run_ocp_ab(sizes=(int(sys.argv[idx + 1]),))
        else:
            run_ocp_ab()
    elif "--jac-ab" in sys.argv:
        idx = sys.argv.index("--jac-ab")
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            run_jac_ab(sizes=(int(sys.argv[idx + 1]),))
        else:
            run_jac_ab()
    elif "--mesh-ab" in sys.argv:
        idx = sys.argv.index("--mesh-ab")
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            run_mesh_ab(sizes=(int(sys.argv[idx + 1]),))
        else:
            run_mesh_ab()
    elif "--restore-mttr" in sys.argv:
        idx = sys.argv.index("--restore-mttr")
        n = int(sys.argv[idx + 3]) if len(sys.argv) > idx + 3 else 2
        run_restore_mttr(sys.argv[idx + 1], sys.argv[idx + 2], n)
    elif "--evidence" in sys.argv:
        run_evidence()
    else:
        print(json.dumps(measure()))


def _filter_xla_noise(text: str) -> str:
    """Drop known-noise XLA machine-feature warning lines before
    forwarding child stderr (what the driver's ``tail`` capture stores).
    The marker set and filtering live in
    :func:`agentlib_mpc_tpu.utils.jax_setup.filter_xla_noise` — ONE
    definition shared with ``__graft_entry__``'s multichip-dryrun child,
    whose MULTICHIP_r0x output tails the same blob used to dominate."""
    from agentlib_mpc_tpu.utils.jax_setup import filter_xla_noise

    return filter_xla_noise(text)


def _spawn(args: list, env: dict, timeout: float) -> list:
    """Run this script as a child, forward its stderr (known-noise XLA
    machine-feature warnings filtered, see :func:`_filter_xla_noise`),
    return its parsed JSON stdout lines. Raises on rc != 0 or no JSON
    output. A TIMEOUT salvages whatever JSON the child already flushed
    (the evidence worker prints+flushes per section, so a late heavy
    section dying must not discard the completed ones) and raises only
    when nothing was produced."""
    def parse(out: str) -> list:
        lines = []
        for line in (out or "").strip().splitlines():
            if not line.strip().startswith("{"):
                continue
            try:
                lines.append(json.loads(line))
            except json.JSONDecodeError:
                # a kill can land mid-write of a multi-KB section line;
                # the truncated tail must not discard the complete ones
                print("[bench] dropping truncated JSON line",
                      file=sys.stderr)
        return lines

    def as_text(stream) -> str:
        return stream if isinstance(stream, str) else \
            (stream or b"").decode(errors="replace")

    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=_HERE)
    except subprocess.TimeoutExpired as exc:
        sys.stderr.write(_filter_xla_noise(as_text(exc.stderr)))
        lines = parse(as_text(exc.stdout))
        if lines:
            print(f"[bench] child timed out after {timeout:.0f}s; "
                  f"salvaged {len(lines)} completed JSON line(s)",
                  file=sys.stderr)
            return lines
        raise
    sys.stderr.write(_filter_xla_noise(proc.stderr))
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench child rc={proc.returncode}: "
            f"{_filter_xla_noise(proc.stderr)[-500:]}")
    lines = parse(proc.stdout)
    if not lines:
        raise RuntimeError("bench child emitted no JSON")
    return lines


def _default_platform() -> "str | None":
    """Initialize JAX in a tiny watchdogged child; return its default
    platform name, or None if init fails/hangs (wedged tunnel)."""
    code = ("import jax, json; "
            "print(json.dumps({'p': jax.devices()[0].platform}))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT_S, env=dict(os.environ), cwd=_HERE)
        if proc.returncode != 0:
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])["p"]
    except Exception:  # noqa: BLE001 - any failure means "unavailable"
        return None


# bounded tunnel re-probe (VERDICT r5 weak #2 / task #1): a wedged TPU
# tunnel shows up as a FAILED platform probe (backend init hangs into the
# watchdog). The driver invocation retries the probe on that signature —
# an intermittently-revived tunnel minutes later still yields a silicon
# number that round — before degrading to CPU. A clean "cpu" answer is a
# real answer (no accelerator registered) and is never retried: tests and
# CPU-only boxes must not pay a 15-minute wait.
PROBE_RETRY_INTERVAL_S = float(os.environ.get("BENCH_PROBE_RETRY_S", 120.0))
PROBE_RETRY_WINDOW_S = float(os.environ.get("BENCH_PROBE_WINDOW_S", 900.0))


def _probe_platform_bounded(retry: bool,
                            interval_s: float = None,
                            window_s: float = None):
    """(platform | None, probe_attempts). Each attempt is logged as
    ``{"t_s": <seconds since first probe>, "platform": <result>}`` so the
    final JSON line can prove how many real re-probes the window ran."""
    interval_s = PROBE_RETRY_INTERVAL_S if interval_s is None else interval_s
    window_s = PROBE_RETRY_WINDOW_S if window_s is None else window_s
    attempts = []
    t0 = time.monotonic()
    while True:
        platform = _default_platform()
        attempts.append({"t_s": round(time.monotonic() - t0, 1),
                         "platform": platform})
        if platform is not None or not retry:
            return platform, attempts
        elapsed = time.monotonic() - t0
        if elapsed + interval_s > window_s:
            print(f"[bench] platform probe failed {len(attempts)}x over "
                  f"{elapsed:.0f}s; re-probe window exhausted",
                  file=sys.stderr)
            return None, attempts
        print(f"[bench] platform probe failed (attempt {len(attempts)}, "
              f"wedged tunnel?); re-probing in {interval_s:.0f}s "
              f"(window {window_s:.0f}s)", file=sys.stderr)
        time.sleep(interval_s)


def _measure_failsoft(mode_args: list, cpu_mode_args: "list | None" = None,
                      validate=None, probe_retry: bool = False
                      ) -> "tuple[list, str, bool, list]":
    """(json_lines, platform, fell_back, probe_attempts). Tries the
    default platform first; degrades to a tunnel-free CPU child on any
    failure (including a ``validate(lines)`` callback raising on
    semantically-broken worker output). ``cpu_mode_args`` lets the CPU
    fallback run a lighter mode than the accelerator worker (the evidence
    matrix costs ~an hour on this 1-core VM). ``fell_back`` is True only
    when an accelerator was expected but the measurement degraded to CPU
    — a machine whose default platform IS the CPU is a normal run, not a
    fallback. ``probe_retry`` turns on the bounded tunnel re-probe (the
    driver invocation); ``probe_attempts`` records every probe either
    way."""
    platform, attempts = _probe_platform_bounded(probe_retry)
    if platform is not None and platform != "cpu":
        try:
            lines = _spawn(["--worker"] + mode_args, dict(os.environ),
                           WORKER_TIMEOUT_S)
            if validate is not None:
                validate(lines)
            return lines, platform, False, attempts
        except Exception as exc:  # noqa: BLE001 - degrade, never die
            print(f"[bench] {platform} worker failed ({exc}); "
                  f"falling back to CPU", file=sys.stderr)
        fell_back = True
    elif platform is None:
        print("[bench] default platform unavailable (backend init failed "
              "or timed out — wedged TPU tunnel?); measuring on CPU",
              file=sys.stderr)
        fell_back = True
    else:
        print("[bench] default platform is CPU (no accelerator "
              "registered); measuring on CPU", file=sys.stderr)
        fell_back = False
    from agentlib_mpc_tpu.utils.jax_setup import cpu_subprocess_env

    lines = _spawn(
        ["--probe"] + (mode_args if cpu_mode_args is None
                       else cpu_mode_args),
        cpu_subprocess_env(), WORKER_TIMEOUT_S)
    return lines, "cpu", fell_back, attempts


def _qualified_metric(base: str, platform: str, n_devices: int = 1,
                      degraded: bool = False,
                      mesh_shape: "tuple | None" = None,
                      quality_level: int = 0,
                      precision: str = "full") -> str:
    """The ONE metric-qualification rule (used by the headline and by
    ``--chaos-mesh``/``--chaos-scenario``): unqualified names are
    reserved for TPU; any other platform gets a ``_<platform>`` suffix
    (ROADMAP item 2 — BENCH_r04/r05 read as a 3.6× regression when
    they were a platform change); a measurement that spanned a device
    mesh gains ``_d<n>`` (ISSUE 9 — mesh and single-device numbers are
    different experiments) — or, for a 2-D (agents × scenarios) grid,
    the FULL shape ``_d<A>x<S>`` (ISSUE 14: a 4x2 grid and an
    8-device line are different experiments too); a round served on a
    DEGRADED mesh (shard loss absorbed by a supervisor) gains
    ``_degraded`` (ISSUE 10/14 — a fallback round must never read as
    the full-mesh steady state's regression, or its improvement; a
    degraded 2-D round publishes ``_d<A>x<S>_degraded`` at its reduced
    shape, never the full-mesh key); a run the SLO autopilot held at
    reduced quality gains ``_q<level>`` — the deepest ladder level
    reached (ISSUE 17: a quality-reduced availability number must never
    read as a full-quality headline); a run on a non-full precision
    path gains ``_<precision>`` — ``_mixed``/``_bf16`` (ISSUE 20: a
    mixed-precision solve must never publish under a full-precision
    headline key).

    The rule itself lives in
    :func:`agentlib_mpc_tpu.telemetry.regression.qualified_metric`
    (ISSUE 16: the perf-gate baselines key on the same rule — a gate
    keyed differently from the bench would compare different
    experiments); this wrapper keeps the local name bench callers use."""
    from agentlib_mpc_tpu.telemetry.regression import qualified_metric

    return qualified_metric(base, platform, n_devices, degraded,
                            mesh_shape, quality_level, precision)


def _headline_metric(platform: str, n_devices: int = 1,
                     degraded: bool = False) -> str:
    """Headline metric name under the shared qualification rule
    (:func:`_qualified_metric`)."""
    return _qualified_metric("admm256_step_ms", platform, n_devices,
                             degraded)


def main() -> None:
    if "--probe" in sys.argv or "--worker" in sys.argv:
        _child_main()
        return

    # architecture baselines: sequential per-zone solver calls on the
    # host CPU — run in-process (no TPU involvement possible). The SLSQP
    # variant costs ~200 ms per zone-solve, so it defaults to 16 zones
    # (the BASELINE.md table point); pass an explicit n to change.
    for flag, runner, default_n in (
            ("--conventional", run_conventional, 16),
            ("--sequential", run_sequential_native, N_AGENTS)):
        if flag in sys.argv:
            idx = sys.argv.index(flag)
            n = default_n
            if len(sys.argv) > idx + 1 and not \
                    sys.argv[idx + 1].startswith("-"):
                n = int(sys.argv[idx + 1])   # typos fail loudly
            import jax

            jax.config.update("jax_platforms", "cpu")
            runner(n)
            return

    if "--serve" in sys.argv:
        # serving-plane churn benchmark, in-process like --chaos (pin
        # JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --serve SEED [n_tenants]
        idx = sys.argv.index("--serve")
        seed, n = 0, 8
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            seed = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_serve(seed, n)
        return

    if "--scenario-ab" in sys.argv:
        # scenario-tree robust A/B, in-process like --chaos (pin
        # JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --scenario-ab [n_scenarios] [n_agents]
        idx = sys.argv.index("--scenario-ab")
        S, n = 8, 4
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            S = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_scenario_ab(S, n)
        return

    if "--fusion-ab" in sys.argv:
        # fused-vs-staged IPM dispatch A/B, in-process like --chaos
        # (pin JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --fusion-ab [n_agents] [rounds]
        idx = sys.argv.index("--fusion-ab")
        n, r = 4, 5
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            n = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            r = int(sys.argv[idx + 2])
        run_fusion_ab(n, r)
        return

    if "--warmstart-ab" in sys.argv:
        # learned warm starts A/B, in-process like --fusion-ab (pin
        # JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --warmstart-ab [n_agents]
        idx = sys.argv.index("--warmstart-ab")
        n = N_AGENTS
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            n = int(sys.argv[idx + 1])
        run_warmstart_ab(n)
        return

    if "--precision-ab" in sys.argv:
        # certificate-gated mixed precision A/B, in-process like
        # --warmstart-ab (pin JAX_PLATFORMS=cpu for a tunnel-free
        # host run):
        #   python bench.py --precision-ab [n_agents]
        idx = sys.argv.index("--precision-ab")
        n = N_AGENTS
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            n = int(sys.argv[idx + 1])
        run_precision_ab(n)
        return

    if "--chaos-scenario" in sys.argv:
        # 2-D (agents x scenarios) survivability benchmark (ISSUE 14),
        # in-process like --chaos-mesh; the 8-virtual-device grid must
        # be requested BEFORE backend init (no-op on real multi-chip):
        #   python bench.py --chaos-scenario SEED [n_scenarios] [n_agents]
        from agentlib_mpc_tpu.utils.jax_setup import (
            request_virtual_devices,
        )

        request_virtual_devices(8)
        idx = sys.argv.index("--chaos-scenario")
        seed, S, n = 0, 4, 4
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            seed = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            S = int(sys.argv[idx + 2])
        if len(sys.argv) > idx + 3 and not sys.argv[idx + 3].startswith("-"):
            n = int(sys.argv[idx + 3])
        run_chaos_scenario(seed, S, n)
        return

    if "--chaos-mesh" in sys.argv:
        # mesh survivability benchmark, in-process like --chaos-serve;
        # the 8-virtual-device mesh must be requested BEFORE backend
        # init (no-op on real multi-chip):
        #   python bench.py --chaos-mesh SEED [n_agents]
        from agentlib_mpc_tpu.utils.jax_setup import (
            request_virtual_devices,
        )

        request_virtual_devices(8)
        idx = sys.argv.index("--chaos-mesh")
        seed, n = 0, 8
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            seed = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_chaos_mesh(seed, n)
        return

    if "--chaos-autopilot" in sys.argv:
        # SLO-autopilot A/B under a seeded overload storm, in-process
        # like --chaos-serve (pin JAX_PLATFORMS=cpu for a tunnel-free
        # host run):
        #   python bench.py --chaos-autopilot SEED [n_tenants]
        idx = sys.argv.index("--chaos-autopilot")
        seed, n = 0, 8
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            seed = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_chaos_autopilot(seed, n)
        return

    if "--chaos-serve" in sys.argv:
        # serving survivability benchmark, in-process like --serve (pin
        # JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --chaos-serve SEED [n_tenants]
        idx = sys.argv.index("--chaos-serve")
        seed, n = 0, 6
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            seed = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_chaos_serve(seed, n)
        return

    if "--chaos" in sys.argv:
        # resilience smoke, in-process like --emit-metrics (pin
        # JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --chaos SEED [n_agents]
        idx = sys.argv.index("--chaos")
        seed, n = 0, 4
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            seed = int(sys.argv[idx + 1])
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_chaos(seed, n)
        return

    if "--perf-gate" in sys.argv:
        # per-phase regression gate, in-process (pin JAX_PLATFORMS=cpu
        # for a tunnel-free host run — baselines are platform-qualified
        # so a CPU run gates only against CPU baselines):
        #   python bench.py --perf-gate [BASELINE_PATH] [n_agents]
        #       [--update] [--mutate] [--journal PATH]
        idx = sys.argv.index("--perf-gate")
        path, n = None, 64
        if len(sys.argv) > idx + 1 and not sys.argv[idx + 1].startswith("-"):
            path = sys.argv[idx + 1]
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        jpath = None
        if "--journal" in sys.argv:
            j = sys.argv.index("--journal")
            if len(sys.argv) > j + 1:
                jpath = sys.argv[j + 1]
        row = run_perf_gate(path, update="--update" in sys.argv,
                            mutate="--mutate" in sys.argv,
                            n_agents=n, journal_path=jpath)
        sys.exit(1 if row.get("status") == "fail" else 0)

    if "--emit-metrics" in sys.argv:
        # telemetry-instrumented run, in-process (initializes JAX here;
        # pin JAX_PLATFORMS=cpu for a tunnel-free host run):
        #   python bench.py --emit-metrics out.json [n_agents]
        idx = sys.argv.index("--emit-metrics")
        if len(sys.argv) <= idx + 1 or sys.argv[idx + 1].startswith("-"):
            print("usage: bench.py --emit-metrics PATH [n_agents]",
                  file=sys.stderr)
            sys.exit(2)
        path = sys.argv[idx + 1]
        n = N_AGENTS
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        run_emit_metrics(path, n)
        return

    if "--profile" in sys.argv:
        idx = sys.argv.index("--profile")
        trace_dir = (sys.argv[idx + 1]
                     if len(sys.argv) > idx + 1
                     and not sys.argv[idx + 1].startswith("-")
                     else "bench_trace")
        n = N_AGENTS
        if len(sys.argv) > idx + 2 and not sys.argv[idx + 2].startswith("-"):
            n = int(sys.argv[idx + 2])
        # same fail-soft rule as the measurements: never hang on a
        # wedged tunnel — probe first, degrade to a host trace
        if _default_platform() is None:
            print("[bench] default platform unavailable; tracing on CPU",
                  file=sys.stderr)
            import jax

            jax.config.update("jax_platforms", "cpu")
        run_profile(trace_dir, n)
        return

    for mode in ("--scaling", "--ab", "--qp-ab", "--ldl",
                 "--horizon-shard", "--ocp-ab", "--jac-ab", "--mesh-ab",
                 "--evidence"):
        if mode in sys.argv:
            idx = sys.argv.index(mode)
            mode_args = [mode]
            if len(sys.argv) > idx + 1 and not \
                    sys.argv[idx + 1].startswith("-"):
                # only --ocp-ab/--jac-ab/--mesh-ab take a positional
                # (size N); a value after any other mode would be
                # silently ignored by the child, reporting numbers for a
                # different config
                if mode in ("--ocp-ab", "--jac-ab", "--mesh-ab"):
                    mode_args.append(sys.argv[idx + 1])
                else:
                    print(f"[bench] {mode} takes no value; ignoring "
                          f"{sys.argv[idx + 1]!r}", file=sys.stderr)
            try:
                lines, _, _, _ = _measure_failsoft(mode_args)
                for line in lines:
                    print(json.dumps(line))
            except Exception as exc:  # noqa: BLE001 - always emit a line
                print(f"[bench] catastrophic failure: {exc}",
                      file=sys.stderr)
                print(json.dumps({
                    "metric": f"bench[{mode.lstrip('-')}]",
                    "value": None, "unit": "ms",
                    "platform": "unavailable", "error": str(exc)[:300]}))
            return

    # default (driver) invocation. On an accelerator, ONE worker child
    # runs the full evidence matrix (VERDICT r4 #1) and the final JSON
    # line embeds every section; on CPU (no accelerator / wedged tunnel)
    # only the headline runs — the heavy evidence rows would take the
    # better part of an hour on this 1-core VM and prove nothing new.
    def _validate_evidence(lines):
        head = next((ln for ln in lines
                     if ln.get("section") == "headline"), {})
        if "step_ms" not in head:
            raise RuntimeError(
                f"headline section failed: {head.get('error')}")

    probe_attempts: list = []
    try:
        lines, platform, fell_back, probe_attempts = _measure_failsoft(
            ["--evidence"], cpu_mode_args=[], validate=_validate_evidence,
            probe_retry=True)
        if platform == "cpu":
            res = lines[-1]
            evidence = None
        else:
            sections = {ln.pop("section"): ln for ln in lines
                        if "section" in ln}
            res = sections.pop("headline")
            evidence = sections
        print(f"[bench] platform={platform} "
              f"step={res['step_ms']:.1f}ms "
              f"compile={res['compile_ms']:.0f}ms "
              f"agents/s={res['agents_per_sec']:.0f}", file=sys.stderr)

        if fell_back or platform == "cpu":
            # the headline IS the CPU number; the ratio vs itself is 1
            vs_baseline = 1.0
        else:
            vs_baseline = 0.0
            try:
                from agentlib_mpc_tpu.utils.jax_setup import (
                    cpu_subprocess_env,
                )

                cpu = _spawn(["--probe"], cpu_subprocess_env(),
                             WORKER_TIMEOUT_S)[-1]
                print(f"[bench] cpu baseline step={cpu['step_ms']:.1f}ms",
                      file=sys.stderr)
                vs_baseline = cpu["step_ms"] / res["step_ms"]
            except Exception as exc:  # noqa: BLE001 - best-effort
                print(f"[bench] cpu baseline unavailable: {exc}",
                      file=sys.stderr)

        line = {
            "metric": _headline_metric(platform,
                                       int(res.get("n_devices", 1))),
            "value": round(res["step_ms"], 2),
            "unit": "ms",
            "vs_baseline": round(vs_baseline, 2),
            "platform": platform,
            "tpu_fallback_to_cpu": fell_back,
            # every watchdogged platform probe the bounded re-probe
            # window ran (one entry on a healthy first answer)
            "probe_attempts": probe_attempts,
        }
        if evidence is not None:
            line["evidence"] = evidence
        else:
            line["evidence_skipped"] = (
                "cpu fallback — heavy evidence rows only run on an "
                "accelerator")
        print(json.dumps(line))
    except Exception as exc:  # noqa: BLE001 - the line must always emit
        print(f"[bench] catastrophic failure: {exc}", file=sys.stderr)
        print(json.dumps({
            # platform-qualified like every non-TPU emission: a null
            # datapoint must not land in the TPU trajectory either
            "metric": _headline_metric("unavailable"),
            "value": None,
            "unit": "ms",
            "vs_baseline": 0.0,
            "platform": "unavailable",
            "probe_attempts": probe_attempts,
            "error": str(exc)[:300],
        }))


if __name__ == "__main__":
    main()
