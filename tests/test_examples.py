"""Examples as integration tests — the reference's test backbone
(``tests/test_examples.py:74-243``): run each example's ``run_example``
for a bounded sim time with ``testing=True`` so the example's own
closed-loop assertions execute.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def test_admm_cooled_room_example():
    from examples.admm_cooled_room import run_example

    results = run_example(until=1800, testing=True, verbose=False)
    assert "CooledRoom" in results and "Cooler" in results


@pytest.mark.slow
def test_admm_4rooms_coordinator_example():
    from examples.admm_4rooms_coordinator import run_example

    results = run_example(until=1800, testing=True, verbose=False)
    assert "Coordinator" in results and "AHU" in results


@pytest.mark.slow
def test_exchange_admm_4rooms_example():
    from examples.exchange_admm_4rooms import run_example

    results = run_example(until=1800, testing=True, verbose=False)
    assert "Supplier" in results


@pytest.mark.slow
def test_three_zone_datadriven_admm_example():
    from examples.three_zone_datadriven_admm import run_example

    results = run_example(until=1800, testing=True, verbose=False,
                          epochs=200)
    assert "AHU" in results and "Zone_1" in results


def test_output_ann_example():
    from examples.output_ann import run_example

    out = run_example(testing=True, verbose=False, epochs=300)
    assert out["rmse"].shape == (2,)


def test_mhe_one_room_example():
    from examples.mhe_one_room import run_example

    results = run_example(until=3600, testing=True, verbose=False)
    assert "Plant" in results


def test_linear_qp_mpc_example():
    from examples.linear_qp_mpc import run_example

    results = run_example(until=3600, testing=True, verbose=False)
    assert "LinearZone" in results


def test_minlp_switched_room_example():
    from examples.minlp_switched_room import run_example

    results = run_example(until=4500, testing=True, verbose=False)
    assert "Plant" in results


def test_ml_mpc_example():
    from examples.ml_mpc_one_room import run_example

    out = run_example(until=4500, testing=True, verbose=False, epochs=200)
    assert len(out["temps"]) == 15


def test_fused_fleet_rooms_example():
    from examples.fused_fleet_rooms import run_example

    out = run_example(until=1800, n_rooms=8, testing=True, verbose=False)
    assert len(out["iterations"]) == 6
