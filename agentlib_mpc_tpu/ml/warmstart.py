"""Learned solver warm starts: fingerprint-keyed initial-point prediction.

PERF.md rounds 3-4 established that iteration count is the second big
lever besides per-iteration cost. This module turns the ``ml/`` surrogate
stack inward: instead of predicting the plant, a small jax-native MLP
predicts the *solver's own* primal/dual initial point
``theta -> (w0, y0, z0[, lam0])``, evaluated **inside** the jit graph, so
cold starts (tenant joins, fleet boots, probation readmissions) begin
near the solution instead of at the generic transcription guess.

Three invariants make this safe enough for the serving plane:

* **Fingerprint stamping** — a trained artifact records the structural
  fingerprint digest (PR 7 ``lint.jaxpr.structural_fingerprint``) of the
  problem class it was trained for. :func:`build_warmstart` REFUSES a
  drifted digest (:class:`WarmstartDriftError`); the caller falls back
  to the plain start. One artifact serves every tenant in a bucket —
  the bucket key *is* the fingerprint.
* **In-graph quality gate** — the predicted point's KKT-style residual
  is compared against the plain cold start's at trace level; a worse
  (or non-finite) prediction is ``jnp.where``-rejected in favor of the
  plain start and counted
  (``SolverStats.init_point_source = predicted_rejected``,
  ``solver_warmstart_rejections_total``). A poisoned or stale model can
  therefore degrade latency, never actuation.
* **Data from the tape only** — training rows are a replay of the
  flight-recorder journal (``warmstart.tape`` events extracted by
  ``python -m agentlib_mpc_tpu.telemetry --dataset``), never a live
  hook into the serving loop (``ml/training.fit_warmstart``).

The predictor weights ride the traced argument list of whatever splice
uses them (slot resets, fleet cold starts), so installing, poisoning or
disabling a predictor is DATA — zero retraces, pinned by the
``[serving.warmstart]`` budget.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import numpy as np

from agentlib_mpc_tpu.ml.serialized import (
    WARMSTART_HEADS,
    SerializedWarmstart,
)

#: default acceptance factor of the in-graph quality gate: the predicted
#: point must not be worse than ``gate_factor`` x the plain start's
#: KKT-style residual (1.0 = "at least as good as what we had")
DEFAULT_GATE_FACTOR = 1.0

#: the generic inequality-dual cold start the gate falls back to —
#: matches the fleet/slot plain resets (``FusedADMM.init_state``)
Z_COLD = 0.1


class WarmstartDriftError(ValueError):
    """A warm-start artifact was offered to a problem class whose
    structural fingerprint differs from the one it was trained for.
    Matching array dimensions do not make two problems interchangeable —
    the caller must fall back to the plain start."""


def flatten_theta(theta) -> "Any":
    """One flat feature vector from an (unbatched) OCP parameter pytree.

    Leaf order is ``jax.tree.leaves`` order — deterministic for a fixed
    pytree structure, which the structural fingerprint already pins.
    The same layout is used by the journal tape rows, the dataset CLI
    and the predictor input, so the three can never disagree.

    Non-finite entries are zeroed: parameter trees carry ±inf
    unbounded-bound sentinels that are structural (identical for every
    tenant of the class, so zero information) and would poison both
    the trainer's standardization and the in-graph matmul
    (``inf * 0 = nan`` would NaN the prediction and force the gate to
    reject every point).
    """
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(theta)
    flat = jnp.concatenate(
        [jnp.ravel(jnp.asarray(leaf, dtype=float)) for leaf in leaves])
    return jnp.where(jnp.isfinite(flat), flat, 0.0)


def theta_flat_size(ocp) -> int:
    """Flattened parameter-vector length of one agent of ``ocp``."""
    import jax

    theta = ocp.default_params()
    # np.prod(()) == 1.0, so scalars count 1 and zero-size leaves 0
    return sum(int(np.prod(np.shape(leaf)))
               for leaf in jax.tree.leaves(theta))


class WarmstartBundle(NamedTuple):
    """A revived warm-start predictor, ready to sit inside a trace.

    ``apply(params, theta_flat) -> (n_out,)`` is the pure MLP forward;
    ``params`` is the swappable weight pytree (same shapes = no
    recompile, the hot-swap/poison/restore seam); ``heads`` maps head
    name -> (offset, length) into the output vector.
    """

    apply: Callable[[Any, Any], Any]
    params: Any
    heads: "dict[str, tuple]"
    n_theta: int
    fingerprint: str
    aliases: tuple
    model: SerializedWarmstart


def build_warmstart(model: SerializedWarmstart,
                    ocp=None,
                    fingerprint: "str | None" = None) -> WarmstartBundle:
    """Build the traced evaluator for a serialized warm-start document.

    ``ocp`` (or an explicit ``fingerprint`` digest) identifies the
    problem class the caller wants to warm-start; a mismatch against
    the document's training stamp raises :class:`WarmstartDriftError`
    (drift = refuse, fall back to plain). With ``ocp`` given the head
    lengths are cross-checked against the transcription too.
    """
    import jax.numpy as jnp

    from agentlib_mpc_tpu.ml.predictors import _ACT

    if not isinstance(model, SerializedWarmstart):
        raise TypeError(f"expected SerializedWarmstart, got "
                        f"{type(model).__name__}")
    if not model.fingerprint:
        raise WarmstartDriftError(
            "warm-start document carries no fingerprint stamp — refusing "
            "to serve an unstamped predictor")
    want = fingerprint
    if want is None and ocp is not None:
        from agentlib_mpc_tpu.serving.fingerprint import tenant_fingerprint

        want = tenant_fingerprint(ocp).digest
    if want is not None and str(want) != str(model.fingerprint):
        raise WarmstartDriftError(
            f"warm-start artifact was trained for fingerprint "
            f"{model.fingerprint} but the problem class here is {want} "
            f"— structural drift, falling back to plain starts")
    if ocp is not None:
        expect = {"w": int(ocp.n_w), "y": int(ocp.n_g), "z": int(ocp.n_h)}
        for head, (_off, n) in model.head_slices().items():
            if head in expect and n != expect[head]:
                raise WarmstartDriftError(
                    f"warm-start head {head!r} has length {n}, problem "
                    f"class needs {expect[head]}")
        n_theta = theta_flat_size(ocp)
        if int(model.n_theta) != n_theta:
            raise WarmstartDriftError(
                f"warm-start input length {model.n_theta} != flattened "
                f"theta length {n_theta}")

    params = {
        "W": [jnp.asarray(np.asarray(w, dtype=float))
              for w in model.weights],
        "b": [jnp.asarray(np.asarray(b, dtype=float))
              for b in model.biases],
    }
    acts = tuple(model.activations)

    def apply(p, x):
        h = x
        for W, b, a in zip(p["W"], p["b"], acts):
            h = _ACT[a](h @ W + b)
        return jnp.atleast_1d(h)

    return WarmstartBundle(
        apply=apply, params=params, heads=model.head_slices(),
        n_theta=int(model.n_theta), fingerprint=str(model.fingerprint),
        aliases=tuple(model.aliases), model=model)


def _kkt_merit(nlp, w, theta, y, z):
    """Scalar KKT-style residual at an arbitrary point: relative
    stationarity + primal infeasibility — cheap (one gradient, two
    vjps) and monotone in 'how far from a KKT point is this'.

    The stationarity norm is divided by the magnitude of the largest
    term composing the Lagrangian gradient at that point,
    ``max(1, |∇f|, |J_gᵀy|, |J_hᵀz|)``. The raw norm is useless for
    comparing two arbitrary points: when the true multipliers are
    large (badly scaled constraints — e.g. dynamics in Watts against
    states in Kelvin), a start within 0.1%% of the exact duals still
    carries a raw residual of thousands, while a primal point in a
    flat region of the cost with zero duals scores near zero despite
    being far from optimal. Normalizing by the constituent terms makes
    the test invariant to constraint/dual scaling (the same reason
    SNOPT tests relative KKT error); it also subsumes IPOPT's s_d
    dual-magnitude scaling, so large predicted multipliers cannot win
    the comparison by deflating their own stationarity norm."""
    import jax
    import jax.numpy as jnp

    def _mx(a):
        return jnp.max(jnp.abs(a)) if a.size else jnp.zeros(())

    gf = jax.grad(nlp.f)(w, theta)
    gv = nlp.g(w, theta)
    hv = nlp.h(w, theta)
    grad_l = gf
    denom = jnp.maximum(1.0, _mx(gf))
    if y.size:
        _, vjp_g = jax.vjp(lambda ww: nlp.g(ww, theta), w)
        jty = vjp_g(y)[0]
        grad_l = grad_l + jty
        denom = jnp.maximum(denom, _mx(jty))
    if z.size:
        _, vjp_h = jax.vjp(lambda ww: nlp.h(ww, theta), w)
        jtz = vjp_h(z)[0]
        grad_l = grad_l - jtz
        denom = jnp.maximum(denom, _mx(jtz))
    viol = jnp.zeros(())
    if gv.size:
        viol = jnp.maximum(viol, jnp.max(jnp.abs(gv)))
    if hv.size:
        viol = jnp.maximum(viol, jnp.max(jnp.maximum(-hv, 0.0)))
    return _mx(grad_l) / denom + viol


def make_gated_init(ocp, bundle: WarmstartBundle,
                    gate_factor: float = DEFAULT_GATE_FACTOR):
    """The in-graph gated initial point for one agent of ``ocp``.

    Returns ``gated_init(params, enable, theta_row) ->
    (w0, y0, z0, lam0, src)`` — a pure traced function:

    * ``params`` — the bundle's weight pytree (traced, hot-swappable),
    * ``enable`` — traced scalar bool; False = plain start, src=0
      (flipping the predictor on/off is DATA, zero retraces),
    * ``src`` — int32 :data:`~agentlib_mpc_tpu.ops.solver.
      INIT_POINT_SOURCES` code (0 plain / 1 predicted / 2 rejected).

    The quality gate compares the predicted point's KKT residual
    against the plain start's (``initial_guess``, zero duals); worse or
    non-finite => every output ``jnp.where``-falls back to the plain
    start. ``lam0`` is the raw (n_aliases*T,) ADMM multiplier head
    (zeros when absent or rejected) for fleet cold starts.
    """
    import jax.numpy as jnp

    heads = bundle.heads
    n_w, n_g, n_h = int(ocp.n_w), int(ocp.n_g), int(ocp.n_h)
    n_lam = heads.get("lam", (0, 0))[1]
    factor = float(gate_factor)

    def _head(out, name, n):
        if name in heads:
            off, ln = heads[name]
            return out[off:off + ln]
        return jnp.zeros((n,))

    def gated_init(params, enable, theta_row):
        w_plain = ocp.initial_guess(theta_row)
        out = bundle.apply(params, flatten_theta(theta_row))
        w_pred = _head(out, "w", n_w)
        y_pred = _head(out, "y", n_g)
        z_pred = jnp.clip(_head(out, "z", n_h), 1e-6, 1e4) \
            if ("z" in heads and n_h) else jnp.full((n_h,), Z_COLD)
        lam_pred = _head(out, "lam", n_lam)
        err_pred = _kkt_merit(ocp.nlp, w_pred, theta_row, y_pred, z_pred)
        # score the fallback at the point it actually starts from:
        # zero equality duals, Z_COLD bound duals (same as plain_init)
        err_plain = _kkt_merit(ocp.nlp, w_plain, theta_row,
                               jnp.zeros((n_g,)), jnp.full((n_h,), Z_COLD))
        enabled = jnp.asarray(enable, bool)
        # NaN err_pred compares False -> rejected; the <= keeps an
        # equally-good prediction (its duals still help)
        accept = enabled & (err_pred <= factor * err_plain)
        w0 = jnp.where(accept, w_pred, w_plain)
        y0 = jnp.where(accept, y_pred, jnp.zeros((n_g,)))
        z0 = jnp.where(accept, z_pred, jnp.full((n_h,), Z_COLD))
        lam0 = jnp.where(accept, lam_pred, jnp.zeros((n_lam,)))
        src = jnp.where(enabled, jnp.where(accept, 1, 2), 0)
        return w0, y0, z0, lam0, src.astype(jnp.int32)

    return gated_init


def plain_init(ocp):
    """The generic fresh start as an ``initial_point_fn`` — the same
    signature :func:`make_gated_init` produces, so predicted and plain
    starts share ONE splice executable (``params`` is an empty pytree,
    ``enable`` is ignored, src is always 0)."""
    import jax.numpy as jnp

    n_g, n_h = int(ocp.n_g), int(ocp.n_h)

    def init(params, enable, theta_row):
        del params, enable
        w0 = ocp.initial_guess(theta_row)
        return (w0, jnp.zeros((n_g,)), jnp.full((n_h,), Z_COLD),
                jnp.zeros((0,)), jnp.zeros((), jnp.int32))

    return init


# -- artifact persistence beside the engine blob ------------------------------

def warmstart_artifact_path(store, fingerprint: str) -> str:
    """Path of the warm-start document for a problem-class fingerprint
    under an :class:`~agentlib_mpc_tpu.serving.store.EngineStore` root.
    Keyed by the FINGERPRINT digest (not the full engine key): one
    trained artifact serves every capacity/options variant of the same
    structure."""
    import os

    return os.path.join(store.root, f"{fingerprint}.warmstart.json")


def save_warmstart(store, model: SerializedWarmstart) -> str:
    """Persist a warm-start document beside the engine blobs (atomic
    tmp+rename, like the store's own writes). Returns the path."""
    import os

    if not model.fingerprint:
        raise WarmstartDriftError(
            "refusing to store an unstamped warm-start document")
    path = warmstart_artifact_path(store, model.fingerprint)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(model.to_json())
    os.replace(tmp, path)
    return path


def load_warmstart(store, fingerprint: str) -> "SerializedWarmstart | None":
    """Revive the warm-start document stamped for ``fingerprint``;
    None when absent or unreadable (both mean 'plain starts')."""
    import os

    from agentlib_mpc_tpu.ml.serialized import SerializedMLModel

    path = warmstart_artifact_path(store, fingerprint)
    if not os.path.isfile(path):
        return None
    try:
        model = SerializedMLModel.load(path)
    except (OSError, ValueError, KeyError):
        return None
    if not isinstance(model, SerializedWarmstart):
        return None
    return model


# -- provenance accounting ----------------------------------------------------

def summarize_init_sources(sources) -> "dict[str, int]":
    """Tally per-lane ``init_point_source`` codes (arrays / None mix) into
    ``{"plain": n, "predicted": n, "predicted_rejected": n}``. None
    entries (groups without a predictor) are not counted — the caller
    knows those lanes are plain by construction."""
    from agentlib_mpc_tpu.ops.solver import INIT_POINT_SOURCES

    counts = {name: 0 for name in INIT_POINT_SOURCES}
    for src in sources:
        if src is None:
            continue
        flat = np.asarray(src).reshape(-1)
        for code in flat:
            code = int(code)
            if 0 <= code < len(INIT_POINT_SOURCES):
                counts[INIT_POINT_SOURCES[code]] += 1
    return counts


def record_init_sources(sources, scope: str, names=None) -> "dict[str, int]":
    """Host-side bookkeeping for a cold-start prediction pass: increments
    the warm-start counters and journals a ``warmstart.init`` event.
    Never called from inside a jit trace."""
    from agentlib_mpc_tpu import telemetry

    counts = summarize_init_sources(sources)
    src_counter = telemetry.counter(
        "solver_warmstart_init_total",
        "Cold-start initial points by provenance")
    for name, n in counts.items():
        if n:
            src_counter.inc(n, scope=scope, init_point_source=name)
    if counts["predicted_rejected"]:
        telemetry.counter(
            "solver_warmstart_rejections_total",
            "Predicted initial points rejected by the in-graph "
            "quality gate").inc(counts["predicted_rejected"], scope=scope)
    telemetry.journal_event(
        "warmstart.init", scope=scope,
        groups=list(names) if names is not None else None, **counts)
    return counts


__all__ = [
    "DEFAULT_GATE_FACTOR",
    "WARMSTART_HEADS",
    "WarmstartBundle",
    "WarmstartDriftError",
    "Z_COLD",
    "build_warmstart",
    "flatten_theta",
    "load_warmstart",
    "make_gated_init",
    "plain_init",
    "record_init_sources",
    "save_warmstart",
    "summarize_init_sources",
    "theta_flat_size",
    "warmstart_artifact_path",
]
