"""Typed model variables.

Mirrors the declarative variable groups of the reference
(``agentlib_mpc/models/casadi_model.py:36-274``: CasadiInput, CasadiState,
CasadiParameter, CasadiOutput) but carries no symbolic payload — in the
TPU-native design a variable is pure metadata (name, default, bounds, unit);
its *value* only exists inside traced JAX functions.

Semantics kept from the reference:
- "inputs" are every exogenous signal of a model — controls, disturbances and
  settings alike; which input is a control is decided by the *controller
  config*, not the model (reference: modules/mpc/mpc.py:31-107 splits the
  module's variables into controls/inputs groups against the model).
- a state with no ODE assigned is a stage-wise free (algebraic / slack)
  variable in the OCP (reference: CasadiState.ode unset →
  differentials/algebraics split in casadi_model.py:469-500).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Literal

Role = Literal["state", "input", "parameter", "output"]


@dataclasses.dataclass(frozen=True)
class Var:
    """Metadata for one scalar model quantity."""

    name: str
    value: float = 0.0
    lb: float = -math.inf
    ub: float = math.inf
    unit: str = "-"
    description: str = ""
    role: Role = "input"
    #: variable type, for interop with reference-style JSON configs
    type: str = "float"

    def replace(self, **kw) -> "Var":
        return dataclasses.replace(self, **kw)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for key in ("lb", "ub"):
            if math.isinf(d[key]):
                d[key] = None
        return d

    @classmethod
    def from_dict(cls, d: dict, role: Role | None = None) -> "Var":
        d = dict(d)
        d.pop("alias", None)
        d.pop("source", None)
        d.pop("shared", None)
        if d.get("lb") is None:
            d["lb"] = -math.inf
        if d.get("ub") is None:
            d["ub"] = math.inf
        if role is not None:
            d["role"] = role
        known = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in known}
        return cls(**d)


def state(name: str, value: float = 0.0, *, lb: float = -math.inf,
          ub: float = math.inf, unit: str = "-", description: str = "") -> Var:
    """A (differential or algebraic/slack) state."""
    return Var(name=name, value=value, lb=lb, ub=ub, unit=unit,
               description=description, role="state")


def control_input(name: str, value: float = 0.0, *, lb: float = -math.inf,
                  ub: float = math.inf, unit: str = "-",
                  description: str = "") -> Var:
    """An exogenous input (control, disturbance or setting — the controller
    config decides)."""
    return Var(name=name, value=value, lb=lb, ub=ub, unit=unit,
               description=description, role="input")


def parameter(name: str, value: float = 0.0, *, unit: str = "-",
              description: str = "") -> Var:
    return Var(name=name, value=value, unit=unit, description=description,
               role="parameter")


def output(name: str, value: float = 0.0, *, unit: str = "-",
           description: str = "") -> Var:
    return Var(name=name, value=value, unit=unit, description=description,
               role="output")
