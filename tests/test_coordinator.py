"""Coordinated ADMM: coordinator + two employees + plant simulator.

Mirrors the reference's coordinator example family
(``examples/admm/admm_example_coordinator.py``): an `admm_coordinator`
module drives `admm_coordinated` participants through the registration /
start-iteration / optimization wire protocol; convergence by Boyd residuals.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from agentlib_mpc_tpu.models.zoo import CooledRoom, Cooler
from agentlib_mpc_tpu.modules.coordinator import AgentStatus
from agentlib_mpc_tpu.runtime.mas import LocalMAS
import agentlib_mpc_tpu.modules  # noqa: F401

TIME_STEP = 300.0
HORIZON = 8

COORDINATOR = {
    "id": "Coordinator",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "coordinator",
            "type": "admm_coordinator",
            "time_step": TIME_STEP,
            "prediction_horizon": HORIZON,
            "admm_iter_max": 12,
            "penalty_factor": 10.0,
            "abs_tol": 1e-4,
            "rel_tol": 1e-3,
            "penalty_change_threshold": 10.0,
        },
    ],
}


def _employee(aid, model_cls, couplings, controls, extra):
    return {
        "id": aid,
        "modules": [
            {"module_id": "com", "type": "local_broadcast"},
            {
                "module_id": "admm",
                "type": "admm_coordinated",
                "coordinator": "Coordinator",
                "registration_interval": 30.0,
                "optimization_backend": {
                    "type": "jax_admm",
                    "model": {"class": model_cls},
                    "discretization_options": {
                        "collocation_order": 2,
                        "collocation_method": "legendre",
                    },
                    "solver": {"max_iter": 40},
                },
                "time_step": TIME_STEP,
                "prediction_horizon": HORIZON,
                "couplings": couplings,
                "controls": controls,
                **extra,
            },
        ],
    }


ROOM = _employee(
    "CooledRoom", CooledRoom,
    couplings=[{"name": "mDot", "alias": "mDotCoolAir", "value": 0.02,
                "ub": 0.05, "lb": 0.0}],
    controls=[],
    extra={
        "inputs": [
            {"name": "load", "value": 150},
            {"name": "T_in", "value": 290.15},
            {"name": "T_upper", "value": 295.15},
        ],
        "states": [
            {"name": "T", "value": 298.16, "ub": 303.15, "lb": 288.15,
             "alias": "T", "source": "Simulation"},
        ],
        "parameters": [{"name": "s_T", "value": 1.0}],
    },
)

COOLER = _employee(
    "Cooler", Cooler,
    couplings=[{"name": "mDot_out", "alias": "mDotCoolAir", "value": 0.02}],
    controls=[{"name": "mDot", "value": 0.02, "ub": 0.05, "lb": 0.0}],
    extra={"parameters": [{"name": "r_mDot", "value": 1.0}]},
)

SIM = {
    "id": "Simulation",
    "modules": [
        {"module_id": "com", "type": "local_broadcast"},
        {
            "module_id": "simulator",
            "type": "simulator",
            "model": {"class": CooledRoom,
                      "states": [{"name": "T", "value": 298.16}]},
            "t_sample": 60,
            "outputs": [{"name": "T_out", "value": 298.16, "alias": "T"}],
            "inputs": [{"name": "mDot", "value": 0.02, "alias": "mDot"}],
        },
    ],
}


@pytest.fixture(scope="module")
def mas():
    mas = LocalMAS([COORDINATOR, ROOM, COOLER, SIM], env={"rt": False})
    mas.run(until=1500)
    return mas


def test_registration(mas):
    coord = mas.agents["Coordinator"].get_module("coordinator")
    assert len(coord.agent_dict) == 2
    assert all(e.status in (AgentStatus.standby, AgentStatus.ready)
               for e in coord.agent_dict.values())
    assert "mDotCoolAir" in coord._coupling_variables


def test_residuals_decrease(mas):
    coord = mas.agents["Coordinator"].get_module("coordinator")
    stats = coord.results()
    assert stats is not None and len(stats) >= 3
    first_round = stats.loc[stats.index.get_level_values("time")[0]]
    prim = first_round["primal_residual"].to_numpy()
    assert prim[-1] < prim[0], "primal residual should decrease"


def test_room_cools(mas):
    sim = mas.get_results()["Simulation"]["simulator"]
    temps = np.asarray(
        sim[("variable", "T")] if ("variable", "T") in sim else sim["T"],
        dtype=float)
    assert temps[0] > temps[-1]


def test_couplings_agree(mas):
    coord = mas.agents["Coordinator"].get_module("coordinator")
    var = coord._coupling_variables["mDotCoolAir"]
    trajs = list(var.local_trajectories.values())
    assert len(trajs) == 2
    assert np.max(np.abs(trajs[0] - trajs[1])) < 5e-3
