"""Certificate-calibrated cost ledger: predicted vs measured, per phase.

The middle layer of the performance observatory: join the *analytical*
certificates (the PR 5 per-primitive FLOP/bytes model of
``lint/jaxpr/cost.py``) against the *measured* per-phase device time
(:mod:`.profiler`) — per-phase achieved FLOP/s and bytes/s, a
predicted-vs-measured ratio, and a roofline placement that names the
top fusion candidates analytically. This is exactly the input ROADMAP
item 2 ("pick fusion targets analytically") was blocked behind: a
memory-bound phase running far under the roofline is fusion fuel; a
compute-bound phase at the roofline is done.

:func:`phase_costs` is the certificate side: the same charging rules as
:func:`~agentlib_mpc_tpu.lint.jaxpr.cost.op_cost` (dot = 2·M·N·K,
transcendentals weighted, data movement 0 FLOPs/full bytes, scan bodies
× trip count, while bodies × the caller's trip budget), but accumulated
per ``phase.*`` component of each equation's ``name_stack`` instead of
per primitive — the SAME ``jax.named_scope`` annotations drive both the
measured and the modeled column, so they can never label different
code. Equations outside every phase scope accumulate under
``unattributed``, mirroring the profiler's residual row.

The roofline peaks are a per-platform MODEL (``PLATFORM_PEAKS``,
overridable per call) — their value is placement and ranking, not
absolute truth; the report says which peaks it assumed.
"""

from __future__ import annotations

import dataclasses

from agentlib_mpc_tpu.telemetry.profiler import (
    UNATTRIBUTED,
    deepest_phase,
)

__all__ = ["CalibrationReport", "PLATFORM_PEAKS", "calibrate",
           "phase_costs"]

#: platform -> (peak FLOP/s, peak bytes/s): the roofline model.
#: Deliberately round numbers — the report's value is *placement*
#: (which side of the ridge, how far under the roof) and *ranking*
#: (which phase to fuse first), not absolute efficiency claims. TPU
#: row: f32 VPU+MXU order of magnitude per chip; CPU row: a few cores
#: of AVX + dual-channel DRAM, the shared-CI-runner reality.
PLATFORM_PEAKS = {
    "cpu": (5.0e10, 2.0e10),
    "tpu": (1.0e14, 1.2e12),
    "gpu": (2.0e13, 1.0e12),
}


def phase_costs(fn_or_jaxpr, *args,
                while_trips: "int | None" = None) -> dict:
    """Modeled ``{phase: {"flops", "bytes"}}`` of ``fn(*args)`` (or an
    already-closed jaxpr), keyed by the deepest ``phase.*`` name-stack
    component of each equation — plus the ``unattributed`` row for
    equations outside every phase scope and a ``"_notes"`` list
    (while-trip budgets, exactly like ``op_cost``)."""
    from agentlib_mpc_tpu.lint.jaxpr.cost import (
        _FREE,
        _TRANSCENDENTAL,
        TRANSCENDENTAL_FLOPS,
        WHILE_TRIP_GUESS,
        _dot_flops,
        _nbytes,
        _out_size,
    )

    if hasattr(fn_or_jaxpr, "jaxpr") and not args:
        closed = fn_or_jaxpr
    else:
        import jax

        closed = jax.make_jaxpr(fn_or_jaxpr)(*args)

    acc: dict = {}
    notes: "set[str]" = set()

    def charge(phase, flops, bytes_):
        row = acc.setdefault(phase, {"flops": 0, "bytes": 0})
        row["flops"] += int(flops)
        row["bytes"] += int(bytes_)

    def walk(jaxpr, mult, inherited):
        jaxpr = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
        for eqn in jaxpr.eqns:
            phase = deepest_phase(
                str(eqn.source_info.name_stack)) or inherited
            name = eqn.primitive.name
            if name == "pjit":
                walk(eqn.params["jaxpr"], mult, phase)
                continue
            if name == "shard_map":
                walk(eqn.params["jaxpr"], mult, phase)
                continue
            if name == "scan":
                walk(eqn.params["jaxpr"],
                     mult * int(eqn.params["length"]), phase)
                continue
            if name == "while":
                if while_trips is not None:
                    trips = int(while_trips)
                    notes.add(f"while charged the caller's {trips}-trip "
                              f"budget")
                else:
                    trips = WHILE_TRIP_GUESS
                    notes.add(f'while trips="unbounded" — charged the '
                              f"{WHILE_TRIP_GUESS}-trip guess; pass "
                              f"while_trips=<budget> for a bounded "
                              f"ledger")
                walk(eqn.params["body_jaxpr"], mult * trips, phase)
                walk(eqn.params["cond_jaxpr"], mult * trips, phase)
                continue
            if name == "cond":
                for br in eqn.params["branches"]:
                    walk(br, mult, phase)
                continue
            key = phase or UNATTRIBUTED
            io_bytes = mult * (
                sum(_nbytes(v) for v in eqn.invars
                    if hasattr(v, "aval"))
                + sum(_nbytes(v) for v in eqn.outvars))
            if name in _FREE:
                charge(key, 0, io_bytes)
                continue
            if name == "dot_general":
                charge(key, mult * _dot_flops(eqn), io_bytes)
            elif name in _TRANSCENDENTAL:
                charge(key,
                       mult * TRANSCENDENTAL_FLOPS * _out_size(eqn),
                       io_bytes)
            else:
                charge(key, mult * _out_size(eqn), io_bytes)

    walk(closed, 1, None)
    out = {ph: dict(row) for ph, row in acc.items()}
    out["_notes"] = sorted(notes)
    return out


@dataclasses.dataclass(frozen=True)
class CalibrationReport:
    """The joined ledger: per phase, measured device ms next to modeled
    FLOPs/bytes, achieved rates, roofline placement and the
    predicted-vs-measured ratio; ``fusion_candidates`` ranks the
    memory-bound under-roofline phases — the analytical fusion-target
    list ROADMAP item 2 consumes."""

    platform: str
    metric_key: str
    peak_flops_per_s: float
    peak_bytes_per_s: float
    phases: dict          # phase -> joined row (see calibrate())
    fusion_candidates: tuple
    coverage: float
    notes: tuple = ()

    def as_dict(self) -> dict:
        return {
            "metric_key": self.metric_key,
            "platform": self.platform,
            "peaks": {"flops_per_s": self.peak_flops_per_s,
                      "bytes_per_s": self.peak_bytes_per_s},
            "coverage": round(self.coverage, 4),
            "phases": self.phases,
            "fusion_candidates": list(self.fusion_candidates),
            "notes": list(self.notes),
        }

    def table(self) -> str:
        """Markdown calibration table (the --emit-metrics artifact)."""
        lines = [
            "| phase | ms | GFLOP/s | GB/s | intensity | bound | "
            "measured/roofline |",
            "|---|---|---|---|---|---|---|"]
        for ph, row in sorted(self.phases.items(),
                              key=lambda kv: -kv[1]["device_ms"]):
            lines.append(
                f"| {ph} | {row['device_ms']:.3f} | "
                f"{row['achieved_gflops_per_s']:.2f} | "
                f"{row['achieved_gbytes_per_s']:.2f} | "
                f"{row['intensity']:.2f} | {row['bound']} | "
                f"{row['measured_vs_roofline']:.1f}x |")
        return "\n".join(lines)


def calibrate(profile, costs: dict, *,
              peaks: "tuple | None" = None) -> CalibrationReport:
    """Join a measured :class:`~.profiler.PhaseProfile` against the
    modeled :func:`phase_costs` ledger.

    Per phase present in either side: measured device ms, modeled
    FLOPs/bytes, achieved GFLOP/s and GB/s, arithmetic intensity,
    roofline ``bound`` (compute vs memory vs the ridge point of the
    platform peaks), the roofline-predicted ms and the
    measured-vs-roofline ratio (>1 = slower than the model says this
    phase could run). Fusion candidates: memory-bound phases ranked by
    potential saving ``measured_ms − roofline_ms`` — the time fusing
    away their memory traffic could reclaim."""
    platform = profile.platform
    peak_f, peak_b = peaks or PLATFORM_PEAKS.get(
        platform, PLATFORM_PEAKS["cpu"])
    ridge = peak_f / peak_b
    notes = list(costs.get("_notes", ()))
    if peaks is None and platform not in PLATFORM_PEAKS:
        notes.append(f"no peak model for platform {platform!r} — "
                     f"used the cpu row")
    phases: dict = {}
    for ph in sorted(set(profile.device_ms) | set(costs) - {"_notes"}):
        ms = float(profile.device_ms.get(ph, 0.0))
        row = costs.get(ph, {"flops": 0, "bytes": 0})
        flops, bytes_ = int(row["flops"]), int(row["bytes"])
        secs = ms / 1e3
        intensity = flops / bytes_ if bytes_ else 0.0
        roofline_s = max(flops / peak_f, bytes_ / peak_b)
        phases[ph] = {
            "device_ms": round(ms, 4),
            "model_flops": flops,
            "model_bytes": bytes_,
            "achieved_gflops_per_s": round(
                flops / secs / 1e9 if secs else 0.0, 3),
            "achieved_gbytes_per_s": round(
                bytes_ / secs / 1e9 if secs else 0.0, 3),
            "intensity": round(intensity, 3),
            "bound": ("compute" if intensity >= ridge else "memory")
            if (flops or bytes_) else "unmodeled",
            "roofline_ms": round(1e3 * roofline_s, 4),
            "measured_vs_roofline": round(
                secs / roofline_s if roofline_s > 0 else 0.0, 2),
        }
    candidates = []
    for ph, row in phases.items():
        if ph == UNATTRIBUTED or row["bound"] != "memory":
            continue
        saving = row["device_ms"] - row["roofline_ms"]
        if saving <= 0:
            continue
        candidates.append((saving, ph, row))
    candidates.sort(reverse=True)
    fusion = tuple(
        {"phase": ph,
         "potential_saving_ms": round(saving, 4),
         "reason": (f"memory-bound (intensity {row['intensity']:.2f} "
                    f"< ridge {ridge:.1f} FLOP/B) at "
                    f"{row['measured_vs_roofline']:.1f}x the roofline "
                    f"— fusing its producers/consumers removes "
                    f"round-trip traffic")}
        for saving, ph, row in candidates[:3])
    return CalibrationReport(
        platform=platform, metric_key=profile.metric_key,
        peak_flops_per_s=peak_f, peak_bytes_per_s=peak_b,
        phases=phases, fusion_candidates=fusion,
        coverage=profile.coverage, notes=tuple(notes))
