"""Fingerprint-keyed compile cache for fused serving engines.

The most expensive event in the serving plane is building a fused
engine: jaxpr certification, solver tracing and XLA compilation of the
whole ADMM round (seconds to tens of seconds — the "compile latency /
persistent cache" table in PERF.md). The cache makes that a
once-per-structure cost: a tenant whose problem is structurally
identical to one already compiled — including a tenant REJOINING after
an eviction — reuses the warm executable, and the join is a dictionary
lookup plus a slot splice.

Counters: ``serving_compile_cache_hits_total`` /
``serving_compile_cache_misses_total`` (labelled by bucket digest), and
a ``serving_join_build_seconds`` histogram labelled ``cached="yes"/"no"``
so the cached-vs-cold join-latency A/B is always measured in
production, not just in the bench.
"""

from __future__ import annotations

import time

from agentlib_mpc_tpu import telemetry


class CompileCache:
    """Maps hashable engine keys to built (and warmed) engine objects.

    The cache never evicts: an engine is a compiled executable plus
    static metadata, exactly the artifact worth keeping for the life of
    the process (the persistent XLA cache plays the cross-process
    role). ``get_or_build(key, builder)`` returns
    ``(engine, hit, latency_s)``.
    """

    def __init__(self):
        self._entries: dict = {}
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    def note_hit(self, label: str = "") -> None:
        """Count an executable reuse that never had to consult the
        entry dict — a tenant joining a LIVE bucket whose engine is
        already serving. Same counter family as lookup hits: the metric
        is "compiled engines reused", however shallow the path."""
        self.hits += 1
        if telemetry.enabled():
            telemetry.counter(
                "serving_compile_cache_hits_total",
                "serving engine cache lookups that reused a compiled "
                "engine").inc(bucket=label or "?")

    def get_or_build(self, key, builder, label: str = ""):
        t0 = time.perf_counter()
        engine = self._entries.get(key)
        hit = engine is not None
        if not hit:
            engine = builder()
            self._entries[key] = engine
            self.misses += 1
        else:
            self.hits += 1
        latency = time.perf_counter() - t0
        if telemetry.enabled():
            name = ("serving_compile_cache_hits_total" if hit
                    else "serving_compile_cache_misses_total")
            telemetry.counter(
                name, "serving engine cache lookups that "
                + ("reused a compiled engine" if hit
                   else "had to build (certify + trace + compile)")
                ).inc(bucket=label or "?")
            telemetry.histogram(
                "serving_join_build_seconds",
                "engine acquisition latency at tenant join, by cache "
                "outcome").observe(latency, cached="yes" if hit else "no")
        return engine, hit, latency
